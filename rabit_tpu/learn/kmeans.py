"""Distributed k-means (cosine distance) — the reference's flagship app.

Equivalent of reference: rabit-learn/kmeans/kmeans.cc, re-designed for TPU:
the per-iteration cluster-statistics pass is a single jitted XLA program —
``lax.scan`` over fixed-size row blocks, each block scatter-densified and
pushed through two MXU matmuls (similarity, then stats accumulation) —
instead of the reference's per-row sparse loop (kmeans.cc:126-140).
Cross-rank aggregation is one framework allreduce of the (k, d+1) stats
matrix (counts in the last column), and progress is committed with an
in-memory checkpoint every iteration, exactly the reference's structure
(kmeans.cc:141-156).
"""
from __future__ import annotations

import sys
from dataclasses import dataclass, field

import numpy as np

import rabit_tpu
from rabit_tpu.learn.data import SparseMat, load_libsvm, save_matrix_txt
from rabit_tpu.ops import MAX, SUM
from rabit_tpu.utils.checks import check

DEFAULT_ROW_BLOCK = 1024


@dataclass
class KMeansModel:
    """Centroid matrix; checkpointed by value (reference: kmeans.cc:11-46).

    ``hash_dim`` records the signed-hash width the centroids live in
    (None = original feature space).  It rides the checkpoint and the
    saved-model header so a resume or scoring run in a different space
    fails loudly instead of silently clamping features away.
    """

    centroids: np.ndarray = field(
        default_factory=lambda: np.zeros((0, 0), np.float32))
    hash_dim: int | None = None

    def normalize(self) -> None:
        """L2-normalize centroid rows (reference: Model::Normalize,
        kmeans.cc:31-45; rows with ~zero norm are left unscaled)."""
        norm = np.linalg.norm(self.centroids, axis=1, keepdims=True)
        scale = np.where(norm < 1e-6, 1.0, 1.0 / np.maximum(norm, 1e-30))
        self.centroids = (self.centroids * scale).astype(np.float32)


def save_model(model: KMeansModel, fname: str) -> None:
    """Write the centroid matrix; hashed-space models get a ``#``-comment
    header (skipped by ``np.loadtxt``) naming the hash width, so a scorer
    can't silently apply them in the wrong feature space."""
    header = (None if model.hash_dim is None
              else "rabit-kmeans hash_dim=%d" % model.hash_dim)
    save_matrix_txt(model.centroids, fname, header=header)


def init_centroids(data: SparseMat, num_cluster: int, feat_dim: int,
                   seed: int = 0) -> KMeansModel:
    """Seed centroids from random data rows, each broadcast from a random
    rank (reference: InitCentroids, kmeans.cc:47-60)."""
    rng = np.random.default_rng(seed)
    cent = np.zeros((num_cluster, feat_dim), np.float32)
    for i in range(num_cluster):
        fi, fv = data.row(int(rng.integers(data.num_row)))
        # add, not assign: hashed rows (hash_features) carry duplicate
        # indices whose values must sum
        np.add.at(cent, (i, fi), fv)
    for i in range(num_cluster):
        root = int(rng.integers(rabit_tpu.get_world_size()))
        cent[i] = rabit_tpu.broadcast(
            cent[i] if rabit_tpu.get_rank() == root else None, root)
    model = KMeansModel(cent)
    model.normalize()
    return model


_STEP_CACHE: dict = {}

# Pre-densify the shard when the dense copy fits this budget: the scatter
# (data-dependent, VPU-bound) then runs ONCE at load, and each iteration
# is pure MXU matmuls over dense blocks.
DENSIFY_BUDGET_BYTES = 2 << 30
# Half-width dense staging (compute_dtype="bfloat16"): x stored (n, d)
# bf16 plus an f32 validity vector.  This is the biggest-that-fits tier
# — the bound leaves headroom for centroids/stats/scratch on a ~16 GB
# chip — and each iteration then rides the HBM-roofline fused kernel
# (the bench.py path) instead of the ELL one.
DENSE16_BUDGET_BYTES = 14 << 30


def _dense16_budget() -> int:
    """HBM budget for the dense16 tier: 7/8 of the local device's memory
    when the backend reports it (smaller-HBM chips would otherwise OOM
    where the ELL tier fits), else the 14 GiB ~16 GB-chip constant."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        limit = int(stats.get("bytes_limit", 0)) if stats else 0
        if limit > 0:
            return limit - (limit >> 3)
    except Exception:
        pass
    return DENSE16_BUDGET_BYTES
_DENSE16_ROW_TILE = 16384   # fused-kernel row block: stage an exact
#                             multiple so its padding never copies
_STAGE_CHUNK_ROWS = 1 << 20


def _densify_fn(block: int, d: int, nnz: int):
    key = ("densify", block, d, nnz)
    fn = _STEP_CACHE.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def run(idx, val, valid):
            def body(_, blk):
                i, v, vld = blk
                dense = _ell_densify(i, v, d)
                # pad column d becomes the validity column
                dense = dense.at[:, d].set(vld)
                return None, dense

            _, out = jax.lax.scan(body, None, (idx, val, valid))
            return out                     # (nb, block, d+1)

        _STEP_CACHE[key] = run
        fn = run
    return fn


def _stage_dense16(idx, val, valid, feat_dim: int, row_block: int,
                   compute_dtype: str):
    """Densify the whole shard into a device-resident (n16, d) array of
    ``compute_dtype`` + an f32 validity vector, chunk by chunk.

    The f32 blocks tier ships everything at once; at biggest-that-fits
    scale that would hold idx+val AND the output on device together, so
    this stager streams host chunks through a donating
    ``dynamic_update_slice`` writer — peak device memory is the output
    plus one chunk.  Rows pad to the fused kernel's 16384 block so its
    padding never copies the array.
    """
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    import math

    n, nnz = idx.shape
    # rows pad to lcm(row_block, fused-kernel tile) so chunking stays
    # row_block-aligned AND the kernel's row padding is a no-op; the
    # feature dim pads to the 128-lane tile at STAGING time — otherwise
    # every stats call would re-pad the whole multi-GB array
    row_lcm = math.lcm(row_block, _DENSE16_ROW_TILE)
    n16 = -(-n // row_lcm) * row_lcm
    dp = -(-feat_dim // 128) * 128
    cdt = jnp.dtype(compute_dtype)
    chunk = min(n16, max(row_block,
                         (_STAGE_CHUNK_ROWS // row_block) * row_block))

    def writer_fn(rows: int):
        key = ("stage16", feat_dim, dp, nnz, row_block, rows, str(cdt))
        fn = _STEP_CACHE.get(key)
        if fn is None:
            @functools.partial(jax.jit, donate_argnums=(0,))
            def fn(x, ci, cv, start):
                def body(_, blk):
                    bi, bv = blk
                    dense = _ell_densify(bi, bv, feat_dim)[:, :feat_dim]
                    return None, jnp.pad(
                        dense, ((0, 0), (0, dp - feat_dim))).astype(cdt)

                _, dense = jax.lax.scan(
                    body, None, (ci.reshape(-1, row_block, nnz),
                                 cv.reshape(-1, row_block, nnz)))
                return lax.dynamic_update_slice(
                    x, dense.reshape(rows, dp), (start, 0))

            _STEP_CACHE[key] = fn
        return fn

    x = jnp.zeros((n16, dp), cdt)
    for start in range(0, n16, chunk):
        rows = min(chunk, n16 - start)
        # start/chunk/n16 are all row_block multiples, so rows is too
        check(rows % row_block == 0,
              "dense16 staging: chunk misalignment (%d %% %d)",
              rows, row_block)
        stop = min(start + rows, n)
        real = max(0, stop - start)       # rows pad to lcm(row_block,
        if real == 0:                     # tile), so a whole chunk can
            continue                      # land past n: x is already 0
        ci = idx[start:stop]
        cv = val[start:stop]
        if real < rows:                   # tail: pad with inert rows
            pad = rows - real             # (index feat_dim is sliced
            ci = np.pad(ci, ((0, pad), (0, 0)),   # away; validity 0)
                        constant_values=feat_dim)
            cv = np.pad(cv, ((0, pad), (0, 0)))
        x = writer_fn(rows)(x, jnp.asarray(ci), jnp.asarray(cv),
                            jnp.int32(start))
    v16 = np.zeros(n16, np.float32)
    v16[:n] = valid
    return x, jax.device_put(jnp.asarray(v16))


def _ell_densify(idx, val, d: int):
    """Densify a padded-ELL block to (rows, d+1).

    Expressed as a one-hot contraction rather than ``.at[].add`` — the
    TPU scatter lowering serialises updates (~15 ns each, measured),
    while the compare + einsum stays on the vector/matrix units and runs
    ~2.3x faster at d=256, nnz=32.  Pad entries (index d) land in the
    extra column, which callers overwrite or slice away.
    """
    import jax.numpy as jnp

    iota = jnp.arange(d + 1, dtype=idx.dtype)
    onehot = (idx[:, :, None] == iota).astype(jnp.float32)
    return jnp.einsum("rj,rjd->rd", val, onehot)


def _normalize_rows(m, eps: float = 1e-12):
    """L2-normalise rows on device (cosine distance prep)."""
    import jax.numpy as jnp

    return m / (jnp.linalg.norm(m, axis=1, keepdims=True) + eps)


def _dense_assign(cnorm, x, valid):
    """Shared dense stats core: similarity (MXU) → argmax → masked
    one-hot.  Returns the (rows, k) one-hot assignment matrix."""
    import jax
    import jax.numpy as jnp

    sim = x @ cnorm.T                                 # (rows, k) MXU
    assign = jnp.argmax(sim, axis=1)
    return (jax.nn.one_hot(assign, cnorm.shape[0], dtype=jnp.float32)
            * valid[:, None])


def _dense_stats_fn(k: int, d: int, block: int):
    """Stats pass over pre-densified blocks: two MXU matmuls per block."""
    key = ("dense", k, d, block)
    fn = _STEP_CACHE.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        def body(stats, dense):
            onehot = _dense_assign(stats["cnorm"], dense[:, :d],
                                   dense[:, d])
            new = stats["acc"] + onehot.T @ dense          # (k, d+1) MXU
            return {"cnorm": stats["cnorm"], "acc": new}, None

        @jax.jit
        def run(centroids, dense_blocks):
            init = {"cnorm": _normalize_rows(centroids),
                    "acc": jnp.zeros((k, d + 1), jnp.float32)}
            out, _ = jax.lax.scan(body, init, dense_blocks)
            return out["acc"]

        _STEP_CACHE[key] = run
        fn = run
    return fn


def _stats_fn(k: int, d: int, block: int, nnz: int):
    """Jitted pass: blocks of padded-ELL rows → (k, d+1) stats matrix."""
    key = (k, d, block, nnz)
    fn = _STEP_CACHE.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    def body(stats, blk):
        idx, val, valid = blk
        # densify via one-hot contraction; pad column d sliced away
        dense = _ell_densify(idx, val, d)[:, :d]
        onehot = _dense_assign(stats["cnorm"], dense, valid)
        ext = jnp.concatenate([dense * valid[:, None], valid[:, None]], axis=1)
        new = stats["acc"] + onehot.T @ ext               # (k, d+1) MXU
        return {"cnorm": stats["cnorm"], "acc": new}, None

    @jax.jit
    def run(centroids, idx_blocks, val_blocks, valid_blocks):
        init = {"cnorm": _normalize_rows(centroids),
                "acc": jnp.zeros((k, d + 1), jnp.float32)}
        out, _ = jax.lax.scan(
            body, init, (idx_blocks, val_blocks, valid_blocks))
        return out["acc"]

    _STEP_CACHE[key] = run
    return run


def centroid_update(cent, stats):
    """New centroids from an (allreduced) (k, d+1) stats matrix: divide
    by counts (empty clusters keep their previous centroid), then
    renormalise (cosine k-means, reference: kmeans.cc:141-157).
    Jax-traceable — usable inside jit/shard_map programs."""
    import jax.numpy as jnp

    counts = stats[:, -1:]
    new = jnp.where(counts > 0,
                    stats[:, :-1] / jnp.maximum(counts, 1.0), cent)
    norm = jnp.linalg.norm(new, axis=1, keepdims=True)
    return jnp.where(norm < 1e-6, new, new / jnp.maximum(norm, 1e-30))


def _device_loop_fn(iters: int, use_pallas: bool, block: int | None,
                    compute_dtype: str):
    """Jitted: run ``iters`` full k-means iterations on device.

    The single-program analogue of the reference's host loop
    (kmeans.cc:121-157): stats pass → divide → renormalise, chained
    without leaving the accelerator.  With the XLA engine the cross-rank
    allreduce also stays in-program (psum); here world-local stats.
    Clusters that receive no points keep their previous centroid.

    ``compute_dtype="bfloat16"`` stores the data and runs the similarity
    pass in bf16 (half the HBM traffic — the TPU idiom); statistics
    still accumulate in float32.  Assignments may differ near decision
    boundaries.
    """
    key = ("loop", iters, use_pallas, block, compute_dtype)
    fn = _STEP_CACHE.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        cdt = jnp.dtype(compute_dtype)

        def one_iter(cent, xv):
            x, valid = xv
            if use_pallas:
                from rabit_tpu.ops.kmeans_kernel import kmeans_stats_fused
                stats = kmeans_stats_fused(cent, x, valid, block=block)
            else:
                onehot = _dense_assign(
                    _normalize_rows(cent).astype(cdt), x, valid)
                sums = jax.lax.dot_general(
                    onehot.astype(cdt), x, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                counts = jnp.sum(onehot, axis=0)
                stats = jnp.concatenate([sums, counts[:, None]], axis=1)
            return centroid_update(cent, stats)

        @jax.jit
        def run(cent, x, valid):
            x = x.astype(cdt)  # one cast, reused across the chain
            return jax.lax.fori_loop(
                0, iters, lambda _, c: one_iter(c, (x, valid)), cent)

        _STEP_CACHE[key] = run
        fn = run
    return fn


def device_iterations(centroids, x, valid, iters: int,
                      use_pallas: bool | None = None,
                      block: int | None = None,
                      compute_dtype: str = "float32"):
    """Run ``iters`` k-means iterations device-resident; returns the final
    centroid array (a ``jax.Array`` — not fetched)."""
    import jax

    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    fn = _device_loop_fn(iters, use_pallas, block, compute_dtype)
    return fn(centroids, x, valid)


_ELL_FUSED_BLOCK = 2048
_ELL_FUSED_HI = 128
_ELL_FUSED_GROUP = 4


def _next_pow2(v: int) -> int:
    p = 1
    while p < v:
        p <<= 1
    return p


def prepare_shard(idx, val, valid, feat_dim: int,
                  row_block: int = DEFAULT_ROW_BLOCK,
                  budget: int = DENSIFY_BUDGET_BYTES,
                  compute_dtype: str = "float32"):
    """Stage this rank's shard on device for repeated stats passes.

    Small-enough shards are densified once (the scatter is
    centroid-independent), making each iteration pure MXU matmuls.
    Larger shards stay in ELL form: on TPU the fused two-level Pallas
    kernel (:func:`rabit_tpu.ops.kmeans_kernel.kmeans_ell_stats_fused`)
    runs the whole stats pass without ever materialising dense rows in
    HBM — measured 4x the scan path's throughput at the 50M-point shape
    (doc/benchmarks.md "ELL densify bound", superseded in round 4);
    elsewhere the block-scan densify pass is used.
    """
    import jax

    import jax.numpy as jnp

    n = idx.shape[0]
    nb = n // row_block
    if n * (feat_dim + 1) * 4 <= budget:
        fn = _densify_fn(row_block, feat_dim, idx.shape[1])
        blocks = fn(idx.reshape(nb, row_block, -1),
                    val.reshape(nb, row_block, -1),
                    valid.reshape(nb, row_block))
        return ("dense", feat_dim, blocks)
    if compute_dtype != "float32":
        itemsize = jnp.dtype(compute_dtype).itemsize
        dp = -(-feat_dim // 128) * 128   # staged at lane-padded width
        if n * dp * itemsize + n * 4 <= _dense16_budget():
            x, v16 = _stage_dense16(idx, val, valid, feat_dim,
                                    row_block, compute_dtype)
            return ("dense16", feat_dim, (x, v16))
    if jax.default_backend() == "tpu":
        # pad slots to a power of two (index shifts), rows to the kernel
        # block; pad slots carry (index=feat_dim, value=0) so they land
        # in the sliced-away validity column with zero weight
        nnz = idx.shape[1]
        nnz_p = _next_pow2(nnz)
        n_p = -(-n // _ELL_FUSED_BLOCK) * _ELL_FUSED_BLOCK
        if nnz_p != nnz or n_p != n:
            idx = np.pad(idx, ((0, n_p - n), (0, nnz_p - nnz)),
                         constant_values=feat_dim)
            val = np.pad(val, ((0, n_p - n), (0, nnz_p - nnz)))
            valid = np.pad(valid, (0, n_p - n))
        # Exact-d padding when possible: slots at index feat_dim with a
        # ZERO value (ELL pads) vanish through the val-weighted one-hot,
        # so only clamped out-of-range features carrying real values
        # force an extra sliced-away feature block (+hi columns = +20%
        # MACs at d=512) to absorb them.
        contaminated = bool(np.any(val[idx >= feat_dim]))
        d_base = feat_dim + 1 if contaminated else feat_dim
        d_pad = -(-d_base // _ELL_FUSED_HI) * _ELL_FUSED_HI
        # Stage GROUPED (n/G, G*nnz): a device array with a 32-wide
        # minor dim is lane-padded to 128 (4x HBM — OOM at 50M rows);
        # the grouped layout is what the kernel consumes anyway.
        g = _ELL_FUSED_GROUP
        idx_g = np.ascontiguousarray(idx.reshape(n_p // g, g * nnz_p))
        val_g = np.ascontiguousarray(
            val.reshape(n_p // g, g * nnz_p).astype(np.float32))
        return ("ell_fused", feat_dim,
                (jax.device_put(idx_g), jax.device_put(val_g),
                 jax.device_put(valid), d_pad, nnz_p))
    return ("ell", feat_dim, device_ell(idx, val, valid, row_block))


def shard_stats_device(model: KMeansModel, shard):
    """Per-iteration (k, d+1) stats for a staged shard, left on device
    (a ``jax.Array`` — feed it straight to the XLA engine's allreduce so
    the reduction rides ICI)."""
    kind, feat_dim, payload = shard
    k, d = model.centroids.shape
    if kind == "dense":
        fn = _dense_stats_fn(k, d, payload.shape[1])
        return fn(model.centroids, payload)
    if kind == "dense16":
        x, v16 = payload
        return _dense16_stats_fn(k, d, x.shape[1])(model.centroids, x, v16)
    if kind == "ell_fused":
        return _ell_fused_stats(model.centroids, payload, d)
    idx, val, valid = payload  # pre-blocked by device_ell: (nb, block, nnz)
    fn = _stats_fn(k, d, idx.shape[1], idx.shape[2])
    return fn(model.centroids, idx, val, valid)


def _dense16_stats_fn(k: int, d: int, dp: int):
    """Single fused-kernel stats pass over a half-width staged shard.

    ``x`` is staged at the lane-padded width ``dp``; centroids pad up
    (zero columns change neither norms nor similarities) and the stats
    slice back, so the multi-GB array is never re-padded per call."""
    key = ("dense16stats", k, d, dp)
    fn = _STEP_CACHE.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        from rabit_tpu.ops.kmeans_kernel import kmeans_stats_fused

        @jax.jit
        def fn(centroids, x, valid):
            cent_p = jnp.pad(centroids, ((0, 0), (0, dp - d)))
            stats = kmeans_stats_fused(cent_p, x, valid)   # (k, dp+1)
            return jnp.concatenate([stats[:, :d], stats[:, -1:]], axis=1)

        _STEP_CACHE[key] = fn
    return fn


def _ell_chain_fn(iters: int, k: int, d: int, d_pad: int, nnz: int):
    """Jitted: ``iters`` fused-ELL k-means iterations device-resident
    (the sparse twin of :func:`_device_loop_fn` — same checkpoint-
    granularity tradeoff, same recurrence)."""
    key = ("ellchain", iters, k, d, d_pad, nnz)
    fn = _STEP_CACHE.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        from rabit_tpu.ops.kmeans_kernel import kmeans_ell_stats_fused

        def one_iter(cent, idx_g, val_g, valid):
            cent_p = jnp.pad(cent, ((0, 0), (0, d_pad - d)))
            stats = kmeans_ell_stats_fused(
                cent_p, idx_g, val_g, valid, d_pad, nnz=nnz,
                group=_ELL_FUSED_GROUP, hi=_ELL_FUSED_HI,
                block=_ELL_FUSED_BLOCK)
            stats = jnp.concatenate([stats[:, :d], stats[:, -1:]], axis=1)
            return centroid_update(cent, stats)

        @jax.jit
        def run(cent, idx_g, val_g, valid):
            return jax.lax.fori_loop(
                0, iters, lambda _, c: one_iter(c, idx_g, val_g, valid),
                cent)

        _STEP_CACHE[key] = run
        fn = run
    return fn


def _ell_fused_stats(centroids, payload, d: int):
    """Fused-kernel stats with feature padding folded in: centroids are
    zero-padded to the kernel's d (multiple of hi), the sliced-away
    columns absorb pad slots (index ``feat_dim`` -> column d, value 0)."""
    import jax.numpy as jnp

    from rabit_tpu.ops.kmeans_kernel import kmeans_ell_stats_fused

    idx_g, val_g, valid, d_pad, nnz = payload
    cent_p = jnp.pad(jnp.asarray(centroids), ((0, 0), (0, d_pad - d)))
    stats = kmeans_ell_stats_fused(
        cent_p, idx_g, val_g, valid, d_pad, nnz=nnz,
        group=_ELL_FUSED_GROUP, hi=_ELL_FUSED_HI, block=_ELL_FUSED_BLOCK)
    # (k, d_pad+1) -> (k, d+1): keep real features + the counts column
    return jnp.concatenate([stats[:, :d], stats[:, -1:]], axis=1)


def shard_stats(model: KMeansModel, shard) -> np.ndarray:
    """Per-iteration (k, d+1) stats for a staged shard."""
    return np.asarray(shard_stats_device(model, shard))


def device_ell(idx, val, valid, row_block: int = DEFAULT_ROW_BLOCK):
    """Move ELL arrays to the accelerator once, pre-blocked.

    Feeding the returned triple to :func:`compute_stats` avoids a
    host→device copy of the whole dataset every iteration.
    """
    import jax

    nb = idx.shape[0] // row_block
    return (
        jax.device_put(idx.reshape(nb, row_block, -1)),
        jax.device_put(val.reshape(nb, row_block, -1)),
        jax.device_put(valid.reshape(nb, row_block)),
    )


def compute_stats(model: KMeansModel, idx, val, valid,
                  row_block: int = DEFAULT_ROW_BLOCK) -> np.ndarray:
    """Local (k, d+1) cluster stats for this rank's shard.

    Accepts flat (nrow, nnz) arrays or pre-blocked device arrays from
    :func:`device_ell`.
    """
    k, d = model.centroids.shape
    if idx.ndim == 2:
        nb = idx.shape[0] // row_block
        idx = idx.reshape(nb, row_block, -1)
        val = val.reshape(nb, row_block, -1)
        valid = valid.reshape(nb, row_block)
    fn = _stats_fn(k, d, idx.shape[1], idx.shape[2])
    out = fn(model.centroids, idx, val, valid)
    return np.asarray(out)


def run(data: SparseMat, num_cluster: int, max_iter: int,
        out_model: str | None = None, seed: int = 0,
        row_block: int = DEFAULT_ROW_BLOCK,
        device_chain: int = 0,
        hash_dim: int | None = None,
        compute_dtype: str = "float32") -> KMeansModel:
    """Train; mirrors the reference main loop (kmeans.cc:104-161).

    ``device_chain > 1`` enables the single-worker device-resident fast
    path: that many iterations run as one XLA program between
    checkpoints (resume granularity coarsens to the chain length).

    ``hash_dim`` (power of two) clusters in SIGNED-HASHED feature space
    instead of the original one: every downstream stage — init,
    staging, stats, checkpoints, the saved model — then lives at that
    width, which typically routes staging onto the pre-densified
    HBM-roofline path (13.6x the exact ELL kernel at d=512→128,
    doc/benchmarks.md "Feature-hashed sparse k-means").  Approximate:
    collisions add (zero-mean under the signed hash); quality is
    data-dependent.  The saved centroids are hashed-space vectors —
    score new rows by hashing them the same way.

    ``compute_dtype="bfloat16"`` additionally unlocks the HALF-WIDTH
    dense staging tier: shards too big for the exact float32 blocks but
    within DENSE16_BUDGET_BYTES stage as a (n, d) bf16 array and every
    iteration rides the HBM-roofline fused kernel (similarity in bf16,
    accumulation in float32 — the bench.py numerics).
    """
    if hash_dim is not None:
        from rabit_tpu.learn.data import hash_features

        hidx, hval = hash_features(data.findex, data.fvalue, hash_dim)
        data = SparseMat(indptr=data.indptr, findex=hidx, fvalue=hval,
                         labels=data.labels, feat_dim=hash_dim)
    model = KMeansModel()
    version, restored = rabit_tpu.load_checkpoint()
    if version == 0:
        feat_dim = int(rabit_tpu.allreduce(
            np.array([data.feat_dim], np.int64), MAX)[0])
        model = init_centroids(data, num_cluster, feat_dim, seed)
        model.hash_dim = hash_dim
        rabit_tpu.tracker_print(
            "[%d] start at %s" % (
                rabit_tpu.get_rank(), rabit_tpu.get_processor_name()))
    else:
        model = restored
        check(getattr(model, "hash_dim", None) == hash_dim,
              "kmeans resume: checkpoint was trained with hash_dim=%s "
              "but run() got hash_dim=%s — centroids live in a different "
              "feature space; pass the original value",
              getattr(model, "hash_dim", None), hash_dim)
        rabit_tpu.tracker_print(
            "[%d] restart iter=%d" % (rabit_tpu.get_rank(), version))
    k, feat_dim = model.centroids.shape
    idx, val, _labels, valid = data.to_ell(
        pad_index=feat_dim, row_block=row_block)
    # clamp out-of-range features (another shard defined feat_dim)
    idx = np.minimum(idx, feat_dim).astype(np.int32)
    # dataset lives on device across iterations; only the (k, d+1) stats
    # matrix crosses the host boundary for the fault-tolerant allreduce
    shard = prepare_shard(idx, val, valid, feat_dim, row_block,
                          compute_dtype=compute_dtype)

    if (device_chain > 1 and not rabit_tpu.is_distributed()
            and shard[0] in ("dense", "dense16", "ell_fused")):
        # Single-worker fast path: chain iterations device-resident
        # (lax.fori_loop in one XLA program), syncing to the host only to
        # commit a checkpoint every `device_chain` iterations.  There is
        # no cross-rank allreduce at world=1, so the chain is exact.
        # Works for both staging layouts: pre-densified blocks and the
        # fused-ELL kernel (the sparse path's per-iteration host fetch —
        # ~100 ms through a tunneled chip — amortizes over the chain).
        import jax.numpy as jnp

        if shard[0] == "dense":
            blocks = shard[2]
            n_total = blocks.shape[0] * blocks.shape[1]
            x = blocks[:, :, :feat_dim].reshape(n_total, feat_dim)
            vcol = blocks[:, :, feat_dim].reshape(n_total)
        elif shard[0] == "dense16":
            x, vcol = shard[2]
        else:
            idx_g, val_g, dvalid, d_pad, nnz_p = shard[2]
        it = version
        cent = jnp.asarray(model.centroids)
        if shard[0] == "dense16" and x.shape[1] != feat_dim:
            # the shard is staged at the lane-padded width; iterate in
            # that space (zero columns are inert) and slice on fetch
            cent = jnp.pad(cent, ((0, 0), (0, x.shape[1] - feat_dim)))
        while it < max_iter:
            chain = min(device_chain, max_iter - it)
            if shard[0] == "dense":
                cent = device_iterations(cent, x, vcol, chain)
            elif shard[0] == "dense16":
                cent = device_iterations(cent, x, vcol, chain,
                                         compute_dtype=compute_dtype,
                                         block=_DENSE16_ROW_TILE)
            else:
                fn = _ell_chain_fn(chain, k, feat_dim, d_pad, nnz_p)
                cent = fn(cent, idx_g, val_g, dvalid)
            it += chain
            model.centroids = np.asarray(cent)[:, :feat_dim]
            rabit_tpu.checkpoint(model)
        if out_model and rabit_tpu.get_rank() == 0:
            save_model(model, out_model)
        return model

    # With the XLA engine the stats matrix can stay device-resident and
    # reduce over ICI; other engines take the fault-tolerant host path
    # with lazy preparation (replay skips the compute on recovery).
    from rabit_tpu import engine as _engine_mod

    device_plane = _engine_mod.is_device_plane()

    epoch = rabit_tpu.device_epoch()
    for _ in range(version, max_iter):
        if rabit_tpu.device_epoch() != epoch:
            # the device plane was re-formed at a checkpoint boundary
            # (failure recovery): arrays of the old epoch died with the
            # backends — re-upload the shard, then continue at full speed
            epoch = rabit_tpu.device_epoch()
            shard = prepare_shard(idx, val, valid, feat_dim, row_block,
                                  compute_dtype=compute_dtype)
        if device_plane:
            local = shard_stats_device(model, shard)
            stats = np.asarray(rabit_tpu.allreduce(local, SUM))
        else:
            stats = np.zeros((k, feat_dim + 1), np.float32)

            def lazy_stats(stats=stats, model=model):
                stats[...] = shard_stats(model, shard)

            stats = rabit_tpu.allreduce(stats, SUM, prepare_fun=lazy_stats)
        counts = stats[:, -1:]
        check(bool((counts != 0).all()), "get zero sized cluster")
        model.centroids = (stats[:, :-1] / counts).astype(np.float32)
        model.normalize()
        rabit_tpu.checkpoint(model)

    if out_model and rabit_tpu.get_rank() == 0:
        save_model(model, out_model)
    return model


def main(argv: list[str]) -> int:
    """CLI mirroring the reference binary:
    ``kmeans <data> num_cluster max_iter <out_model> [name=value ...]``
    (reference: kmeans.cc:84-165)."""
    if len(argv) < 5:
        rabit_tpu.init(argv[1:])
        if rabit_tpu.get_rank() == 0:
            rabit_tpu.tracker_print(
                "Usage: <data_dir> num_cluster max_iter <out_model>")
        rabit_tpu.finalize()
        return 0
    import time

    t0 = time.perf_counter()
    # app-level name=value args (everything else goes to the engine)
    app = {}
    engine_args = []
    for a in argv[5:]:
        key, _, v = a.partition("=")
        if key in ("kmeans_hash_dim", "kmeans_device_chain"):
            check(v.isdigit(), "%s needs an integer value, got %r "
                  "(usage: %s=<int>)", key, v, key)
            app[key] = int(v)
        elif key == "kmeans_compute_dtype":
            check(v in ("float32", "bfloat16"),
                  "kmeans_compute_dtype must be float32|bfloat16, got %r",
                  v)
            app[key] = v
        else:
            engine_args.append(a)
    rabit_tpu.init(engine_args)
    data = load_libsvm(argv[1])
    run(data, int(argv[2]), int(argv[3]), argv[4],
        device_chain=app.get("kmeans_device_chain", 0),
        hash_dim=app.get("kmeans_hash_dim"),
        compute_dtype=app.get("kmeans_compute_dtype", "float32"))
    rabit_tpu.tracker_print(
        "[%d] Time taken: %f seconds" % (
            rabit_tpu.get_rank(), time.perf_counter() - t0))
    rabit_tpu.finalize()
    return 0


def cli() -> int:
    """Console-script entry point."""
    import sys

    return main(sys.argv)


if __name__ == "__main__":
    sys.exit(main(sys.argv))
