"""Vector-free distributed L-BFGS (+ OWL-QN for L1) solver.

Equivalent of reference: rabit-learn/solver/lbfgs.h:55-650, keeping its
parallel decomposition — each rank owns one contiguous, 8-aligned
**parameter-range shard** (lbfgs.h:125-135); the (s, y) history lives only
as shards; the two-loop recursion runs on *dot products* (computed on
shards, summed with one allreduce, lbfgs.h:244-249) so no rank ever
materializes another rank's history; the final direction is assembled
shard-locally and completed with an allreduce (lbfgs.h:283-296).

TPU re-design: shard linear algebra (the Gram products and the direction
assembly) is batched into single jitted matmuls over the (2m+1, nsub)
history matrix instead of per-pair host loops — MXU work rather than
pointer walks.  Cross-rank sums go through the framework allreduce; solver
state is committed with the (global, local) checkpoint pair exactly like
the reference (gstate global / history shard local, lbfgs.h:119,192).
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional

import numpy as np

import rabit_tpu
from rabit_tpu.ops import SUM
from rabit_tpu.utils.checks import check


class ObjFunction(ABC):
    """Objective contract (reference: IObjFunction, lbfgs.h:21-51).

    Eval/CalcGrad see only this rank's data shard; the solver allreduces.
    ``save``/``load`` let the objective persist extra state inside the
    solver checkpoint.
    """

    @abstractmethod
    def eval(self, weight: np.ndarray) -> float: ...

    @abstractmethod
    def calc_grad(self, weight: np.ndarray) -> np.ndarray: ...

    @abstractmethod
    def init_num_dim(self) -> int: ...

    @abstractmethod
    def init_model(self, weight: np.ndarray) -> None: ...

    def save_state(self) -> object:
        return None

    def load_state(self, state: object) -> None:
        pass


def _gram(hist: np.ndarray) -> np.ndarray:
    """Gram matrix of the history rows, in float64.

    The two-loop recursion's curvature ratios need the full float64 the
    solver state carries; a device matmul would silently downcast to f32
    without x64 mode, so this small (2m+1)² product stays on host.  The
    FLOP-heavy work (the objective's eval/grad) is on device.
    """
    return hist @ hist.T


class LBFGSSolver:
    """Reference: LBFGSSolver, lbfgs.h:55-650.

    History layout matches the reference rolling array: rows [0, m) are
    s-vectors (weight deltas), rows [m, 2m) are y-vectors (gradient
    deltas), row 2m is the current steepest-descent proposal
    (lbfgs.h:229-309).  ``dot_buf`` caches the Gram matrix of those rows
    across shifts (lbfgs.h:499-503).
    """

    def __init__(self, obj: Optional[ObjFunction] = None):
        self.obj = obj
        # hyper-parameters (defaults per reference ctor, lbfgs.h:57-67)
        self.reg_L1 = 0.0
        self.max_linesearch_iter = 100
        self.linesearch_backoff = 0.5
        self.linesearch_c1 = 1e-4
        self.min_lbfgs_iter = 5
        self.max_lbfgs_iter = 500
        self.lbfgs_stop_tol = 1e-5
        self.silent = 0
        self.size_memory = 10
        # global state (reference: GlobalState, lbfgs.h:459-545)
        self.num_dim = 0
        self.num_iteration = 0
        self.init_objval = 0.0
        self.old_objval = 0.0
        self.new_objval = 0.0
        self.weight: np.ndarray | None = None
        # rolling history (reference: HistoryArray, lbfgs.h:547-632)
        self.hist: np.ndarray | None = None     # (2m+1, nsub) float64
        self.num_useful = 0
        self.offset = 0
        self.dot_buf: np.ndarray | None = None  # (2m+1, 2m+1) float64
        self.range_begin = 0
        self.range_end = 0

    # ------------------------------------------------------------------
    def set_param(self, name: str, val: str) -> None:
        """Untyped name=value config (reference: lbfgs.h:74-102)."""
        if name == "num_dim":
            self.num_dim = int(val)
        elif name == "size_memory":
            self.size_memory = int(val)
        elif name == "reg_L1":
            self.reg_L1 = float(val)
        elif name == "lbfgs_stop_tol":
            self.lbfgs_stop_tol = float(val)
        elif name == "linesearch_backoff":
            self.linesearch_backoff = float(val)
        elif name == "max_linesearch_iter":
            self.max_linesearch_iter = int(val)
        elif name == "max_lbfgs_iter":
            self.max_lbfgs_iter = int(val)
        elif name == "min_lbfgs_iter":
            self.min_lbfgs_iter = int(val)
        elif name == "linesearch_c1":
            self.linesearch_c1 = float(val)
        elif name == "silent":
            self.silent = int(val)

    # ------------------------------------------------------------------
    # rolling-array indexing (reference: MapIndex, lbfgs.h:447-457)
    def _map(self, i: int) -> int:
        m = self.size_memory
        if i == 2 * m:
            return i
        if i < m:
            return (i + self.offset) % m
        return (i + self.offset) % m + m

    def _row(self, i: int) -> np.ndarray:
        return self.hist[self._map(i)]

    def _dot(self, i: int, j: int) -> float:
        return self.dot_buf[self._map(i), self._map(j)]

    def _set_dot(self, i: int, j: int, v: float) -> None:
        a, b = self._map(i), self._map(j)
        self.dot_buf[a, b] = v
        self.dot_buf[b, a] = v

    def _shift(self) -> None:
        self.offset = (self.offset + 1) % self.size_memory

    # ------------------------------------------------------------------
    def init(self) -> None:
        """Restore-or-initialize (reference: lbfgs.h:116-152)."""
        check(self.obj is not None, "LBFGSSolver.init: set an objective first")
        version, gstate, hist = rabit_tpu.load_checkpoint(with_local=True)
        if version == 0:
            self.num_dim = self.obj.init_num_dim()
        else:
            self._restore_global(gstate)
        # parameter partition: contiguous, 8-aligned upper split
        # (reference: lbfgs.h:125-135)
        nproc = rabit_tpu.get_world_size()
        rank = rabit_tpu.get_rank()
        step = (self.num_dim + nproc - 1) // nproc
        step = (step + 7) // 8 * 8
        self.range_begin = min(rank * step, self.num_dim)
        self.range_end = min((rank + 1) * step, self.num_dim)
        nsub = self.range_end - self.range_begin
        if version == 0:
            m = self.size_memory
            self.dot_buf = np.zeros((2 * m + 1, 2 * m + 1), np.float64)
            self.hist = np.zeros((2 * m + 1, nsub), np.float64)
            self.weight = np.zeros(self.num_dim, np.float64)
            self.obj.init_model(self.weight)
            # all ranks adopt rank 0's initialization
            self.weight = rabit_tpu.broadcast(
                self.weight if rank == 0 else None, 0)
            self.old_objval = self._eval(self.weight)
            self.init_objval = self.old_objval
            if self.silent == 0 and rank == 0:
                rabit_tpu.tracker_print(
                    "L-BFGS solver starts, num_dim=%d, init_objval=%g, "
                    "size_memory=%d"
                    % (self.num_dim, self.init_objval, self.size_memory))
        else:
            self._restore_local(hist)
            if self.silent == 0 and rank == 0:
                rabit_tpu.tracker_print("restart from version=%d" % version)

    # -- checkpoint payloads (reference: GlobalState/HistoryArray
    #    Load/Save, lbfgs.h:505-528,596-617) --------------------------------
    def _global_payload(self) -> dict:
        return {
            "size_memory": self.size_memory,
            "num_iteration": self.num_iteration,
            "num_dim": self.num_dim,
            "init_objval": self.init_objval,
            "old_objval": self.old_objval,
            "offset": self.offset,
            "dot_buf": self.dot_buf,
            "weight": self.weight,
            "obj_state": self.obj.save_state(),
        }

    def _restore_global(self, payload: dict) -> None:
        self.size_memory = payload["size_memory"]
        self.num_iteration = payload["num_iteration"]
        self.num_dim = payload["num_dim"]
        self.init_objval = payload["init_objval"]
        self.old_objval = payload["old_objval"]
        self.offset = payload["offset"]
        self.dot_buf = payload["dot_buf"]
        self.weight = payload["weight"]
        self.obj.load_state(payload["obj_state"])

    def _local_payload(self) -> dict:
        return {"hist": self.hist, "num_useful": self.num_useful}

    def _restore_local(self, payload: Optional[dict]) -> None:
        nsub = self.range_end - self.range_begin
        if payload is None:
            # local state lost beyond replication reach: restart history
            # (the reference would abort; we degrade to a cold history)
            self.hist = np.zeros(
                (2 * self.size_memory + 1, nsub), np.float64)
            self.num_useful = 0
            return
        self.hist = payload["hist"]
        self.num_useful = payload["num_useful"]

    # ------------------------------------------------------------------
    def update_one_iter(self) -> bool:
        """One outer iteration (reference: UpdateOneIter, lbfgs.h:166-194)."""
        grad = self.obj.calc_grad(self.weight).astype(np.float64)
        # codec=False on every solver collective: the L-BFGS direction
        # math is precision-critical (curvature ratios of near-equal
        # dots), so these ops keep exact full-width bytes even when the
        # job arms a lossy wire codec for its bulk traffic
        # (doc/performance.md "Quantized wire codecs").
        grad = rabit_tpu.allreduce(grad, SUM, codec=False)
        dir_, vdot = self._find_change_direction(grad)
        if vdot >= -1e-15:
            # the (sub)gradient direction vanished: already at the optimum
            # (the reference asserts dotv<0, lbfgs.h:318; converging to an
            # exact stationary point is a stop, not an error, here)
            self.new_objval = self.old_objval
            return True
        iters, new_weight = self._backtrack_line_search(dir_, vdot)
        check(iters < self.max_linesearch_iter, "line search failed")
        self.weight = new_weight
        if self.num_iteration > self.min_lbfgs_iter:
            if (self.old_objval - self.new_objval
                    < self.lbfgs_stop_tol * self.init_objval):
                return True
        if self.silent == 0 and rabit_tpu.get_rank() == 0:
            rabit_tpu.tracker_print(
                "[%d] L-BFGS: linesearch finishes in %d rounds, "
                "new_objval=%g, improvement=%g"
                % (self.num_iteration, iters, self.new_objval,
                   self.old_objval - self.new_objval))
        self.old_objval = self.new_objval
        rabit_tpu.checkpoint(self._global_payload(), self._local_payload())
        return False

    def run(self) -> None:
        """Optimize to convergence (reference: Run, lbfgs.h:196-210)."""
        self.init()
        while self.num_iteration < self.max_lbfgs_iter:
            if self.update_one_iter():
                break
        if self.silent == 0 and rabit_tpu.get_rank() == 0:
            nonzero = int(np.count_nonzero(self.weight))
            rabit_tpu.tracker_print(
                "L-BFGS: finishes at iteration %d, %d/%d active weights"
                % (self.num_iteration, nonzero, self.num_dim))

    def get_weight(self) -> np.ndarray:
        return self.weight

    # ------------------------------------------------------------------
    def _find_change_direction(self, grad: np.ndarray):
        """Vector-free two-loop recursion on shard dot products
        (reference: FindChangeDirection, lbfgs.h:214-311)."""
        m = self.size_memory
        n = self.num_useful
        lo, hi = self.range_begin, self.range_end
        nsub = hi - lo
        gsub = grad[lo:hi]
        dir_ = np.zeros(self.num_dim, np.float64)
        if n != 0:
            # hist[m+n-1] holds the previous gradient shard → turn it into
            # the newest y-vector (lbfgs.h:231)
            self.hist[self._map(m + n - 1)] = gsub - self._row(m + n - 1)
            self.hist[self._map(2 * m)] = self._l1_dir(
                gsub, self.weight[lo:hi])
            # Gram products of all history rows in one matmul, then a
            # single allreduce of the needed entries
            # (reference computes 5n dots pairwise, lbfgs.h:233-249)
            gram = _gram(self.hist)
            idxset = ([(j, 2 * m) for j in range(n)]
                      + [(j, n - 1) for j in range(n)]
                      + [(j, m + n - 1) for j in range(n)]
                      + [(m + j, 2 * m) for j in range(n)]
                      + [(m + j, m + n - 1) for j in range(n)])
            vals = np.array(
                [gram[self._map(i), self._map(j)] for i, j in idxset])
            vals = rabit_tpu.allreduce(vals, SUM, codec=False)
            for (i, j), v in zip(idxset, vals):
                self._set_dot(i, j, v)
            # two-loop recursion in dot space (lbfgs.h:253-281)
            alpha = np.zeros(n)
            delta = np.zeros(2 * m + 1)
            delta[2 * m] = 1.0
            for j in range(n - 1, -1, -1):
                vsum = sum(delta[k] * self._dot(k, j)
                           for k in range(2 * m + 1))
                alpha[j] = vsum / self._dot(j, m + j)
                delta[m + j] -= alpha[j]
            scale = (self._dot(n - 1, m + n - 1)
                     / self._dot(m + n - 1, m + n - 1))
            delta *= scale
            for j in range(n):
                vsum = sum(delta[k] * self._dot(k, m + j)
                           for k in range(2 * m + 1))
                beta = vsum / self._dot(j, m + j)
                delta[j] += alpha[j] - beta
            # assemble shard direction: one (2m+1)-row matvec
            # (reference: AddScale loop, lbfgs.h:283-291)
            delta_phys = np.zeros(2 * m + 1)
            for i in range(2 * m + 1):
                delta_phys[self._map(i)] = delta[i]
            dirsub = delta_phys @ self.hist
            steep = self._row(2 * m)
            if self.reg_L1 != 0.0:
                dirsub = np.where(dirsub * steep <= 0.0, 0.0, dirsub)
            vdot = -float(dirsub @ steep)
            dir_[lo:hi] = dirsub
            # The direction assembly is the big wire op of the
            # iteration (num_dim + 1 doubles): issue it async with
            # fuse=False (eager dispatch — a lone bucketed op would sit
            # unsent until wait()) and run the history-shift bookkeeping
            # below — pure local state — while it is in flight.
            both_handle = rabit_tpu.allreduce_async(
                np.concatenate([dir_, [vdot]]), SUM, fuse=False,
                codec=False)
        else:
            dir_ = self._l1_dir(grad, self.weight)
            vdot = -float(dir_ @ dir_)
            both_handle = None
        # shift history (lbfgs.h:302-309)
        if n < m:
            n += 1
        else:
            # rolling shift discards the oldest (s, y) pair and rotates
            # dot_buf with it (reference: GlobalState::Shift + hist.Shift)
            self._shift()
        self.num_useful = n
        self.hist[self._map(m + n - 1)] = gsub
        if both_handle is not None:
            both = both_handle.wait()
            dir_, vdot = both[:-1], float(both[-1])
        return dir_, vdot

    def _backtrack_line_search(self, dir_: np.ndarray, vdot: float):
        """Armijo backtracking (reference: BacktrackLineSearch,
        lbfgs.h:314-350); first iteration uses a unit-norm step."""
        check(vdot < 0.0, "gradient error, dotv=%g", vdot)
        alpha = 1.0
        backoff = self.linesearch_backoff
        if self.num_iteration == 0:
            alpha = 1.0 / np.sqrt(-vdot)
            backoff = 0.1
        iters = 0
        c1 = self.linesearch_c1
        new_weight = self.weight
        while True:
            iters += 1
            if iters >= self.max_linesearch_iter:
                break
            new_weight = self.weight + dir_ * alpha
            if self.reg_L1 != 0.0:
                # OWL-QN: clamp sign flips (lbfgs.h:391-401)
                new_weight = np.where(
                    new_weight * self.weight < 0.0, 0.0, new_weight)
            new_val = self._eval(new_weight)
            if new_val - self.old_objval <= c1 * vdot * alpha:
                self.new_objval = new_val
                break
            alpha *= backoff
        lo, hi = self.range_begin, self.range_end
        self.hist[self._map(self.num_useful - 1)] = (
            new_weight[lo:hi] - self.weight[lo:hi])
        self.num_iteration += 1
        return iters, new_weight

    def _l1_dir(self, grad: np.ndarray, weight: np.ndarray) -> np.ndarray:
        """Steepest descent with L1 subgradient (reference: SetL1Dir,
        lbfgs.h:352-377)."""
        if self.reg_L1 == 0.0:
            return -grad
        r = self.reg_L1
        pos = -grad - r
        neg = -grad + r
        at_zero = np.where(grad < -r, pos, np.where(grad > r, neg, 0.0))
        return np.where(weight > 0.0, pos,
                        np.where(weight < 0.0, neg, at_zero))

    def _eval(self, weight: np.ndarray) -> float:
        """Global objective = allreduced data term + L1 (reference: Eval,
        lbfgs.h:402-413)."""
        val = float(self.obj.eval(weight))
        val = float(rabit_tpu.allreduce(np.array([val]), SUM,
                                        codec=False)[0])
        if self.reg_L1 != 0.0:
            val += self.reg_L1 * float(np.abs(weight).sum())
        check(not np.isnan(val), "nan occurs")
        return val
