"""rabit_tpu.sched — topology-aware collective schedules + auto-tuner.

The collective hot path runs behind a **schedule object**
(:class:`Schedule`): each allreduce algorithm — the PR-3 tree and ring
pumps, recursive halving/doubling, the Swing-style short-cut ring, the
hierarchical two-level pod schedule — is a pluggable singleton selected
per ``(op, dtype, payload_bytes, world, topology)`` dispatch point, so
new algorithms are data, not code forks (doc/performance.md "Schedule
selection").

Selection modes (``rabit_sched``):

* ``static`` (default) — the classic tree/ring byte crossover, now
  configurable via ``rabit_ring_threshold_bytes``;
* ``auto`` — consult the measured :class:`TuningCache` persisted by
  ``bench.py --suite collectives --tune-dir``, falling back to static
  on any miss;
* a schedule name — force it wherever it applies (bench/tests).

The peer-pattern math lives in :mod:`rabit_tpu.sched.topo`, shared
with the tracker so every schedule's links are wired at rendezvous.
"""
from __future__ import annotations

from rabit_tpu.sched.base import Schedule
from rabit_tpu.sched.halving import HalvingDoublingSchedule
from rabit_tpu.sched.hier import HierarchicalSchedule
from rabit_tpu.sched.ring import (RingSchedule, ring_allreduce,
                                  ring_segmented)
from rabit_tpu.sched.swing import SwingSchedule
from rabit_tpu.sched.synth import SynthSchedule, load_plan, synthesize
from rabit_tpu.sched.tree import TreeSchedule
from rabit_tpu.sched.tuner import (CACHE_FILENAME, SCHEMA_VERSION,
                                   TuningCache, decode_directive,
                                   directive_codec, directive_entry,
                                   directive_pick, encode_directive)

TREE = TreeSchedule()
RING = RingSchedule()
HALVING = HalvingDoublingSchedule()
SWING = SwingSchedule()
HIER = HierarchicalSchedule()
SYNTH = SynthSchedule()

#: every registered schedule, by name
SCHEDULES: dict[str, Schedule] = {
    s.name: s for s in (TREE, RING, HALVING, SWING, HIER, SYNTH)}

#: legal rabit_sched values
MODES = ("static", "auto") + tuple(SCHEDULES)

__all__ = [
    "Schedule", "TreeSchedule", "RingSchedule", "HalvingDoublingSchedule",
    "SwingSchedule", "HierarchicalSchedule", "SynthSchedule",
    "TuningCache", "load_plan", "synthesize",
    "ring_allreduce", "ring_segmented", "SCHEDULES", "MODES",
    "TREE", "RING", "HALVING", "SWING", "HIER", "SYNTH",
    "CACHE_FILENAME", "SCHEMA_VERSION",
    "encode_directive", "decode_directive", "directive_pick",
    "directive_entry", "directive_codec",
]
