"""Sketch-driven schedule synthesis (TACCL-style, offline-capable).

Instead of hand-writing one more peer pattern, ``synth`` SEARCHES for
one: it takes the topology handout (world size + host groups) and a
**communication sketch** — a link-cost table plus a chunk count — and
synthesizes a permuted ring: the min-cost Hamiltonian cycle over the
edges the tracker always wires (ring ∪ halving ∪ swing,
:mod:`rabit_tpu.sched.topo`).  The synthesized cycle then runs through
the shared :func:`~rabit_tpu.sched.ring.ring_allreduce` sub-ring walk,
so correctness, chunking, hop pipelining, codec composition and
pyrobust replay are all inherited from the ring — the search owns only
the VISITING ORDER.

Why a permuted ring is worth searching for: a synchronous ring step is
gated by its slowest link, so the cycle's bottleneck edge sets the
steady-state rate and the number of expensive (cross-host) edges sets
the fill/drain skew.  The identity ring visits ranks in rank order,
which on an interleaved placement (groups ``0,1,0,1``) crosses hosts on
EVERY hop; the synthesized cycle visits each host's ranks consecutively
and crosses only ``#groups`` times — the hierarchical schedule's
intuition, discovered instead of hard-coded.

Cost model (the sketch)::

    cost(cycle) = 2*(world-1)*max_edge + sum_edges/chunks

— steady state (every reduce-scatter/all-gather step waits on the
bottleneck link) plus pipeline fill/drain skew amortized over the chunk
count.  Link costs default to the host-group sketch (same-host
``local=1``, cross-host ``cross=4``) and can be overridden per link.

The optional plan JSON (``rabit_synth_plan=<path>`` — collective:
identical content on every rank) carries the sketch and, optionally, a
precomputed cycle::

    {"chunks": 4, "local": 1.0, "cross": 4.0,
     "links": {"0-3": 0.5}, "perm": [0, 2, 1, 3]}

``perm`` short-circuits the runtime search — the offline CLI's output
fed straight back in (TACCL's compile-once-run-many shape)::

    python -m rabit_tpu.sched.synth --world 4 --groups 0,1,0,1 --out plan.json

Everything here is deterministic from replicated inputs (world, groups,
plan bytes): every rank synthesizes the SAME cycle, so the peer pattern
is a collective decision exactly like the hand-written schedules, and
replay stays bit-exact.
"""
from __future__ import annotations

import argparse
import json
import sys
import threading

import numpy as np

from rabit_tpu.ops import ReduceOp
from rabit_tpu.sched import topo
from rabit_tpu.sched.base import Schedule
from rabit_tpu.sched.ring import ring_allreduce
from rabit_tpu.utils.checks import check

#: host-group sketch defaults: same-host hop vs cross-host hop cost
DEFAULT_LOCAL_COST = 1.0
DEFAULT_CROSS_COST = 4.0
#: pipeline chunk count the fill/drain term is amortized over
DEFAULT_CHUNKS = 4
#: 2-opt improvement passes cap — the search must stay cheap enough
#: for a (cached) applies() path; small worlds converge in 1-2 passes
MAX_2OPT_PASSES = 8


# ---------------------------------------------------------------------
# sketch: wired edges + link costs
# ---------------------------------------------------------------------
def wired_edges(world: int) -> set[tuple[int, int]]:
    """The undirected always-wired edge set the search may use: ring
    neighbors plus every halving/swing partner — exactly what the
    tracker hands out at rendezvous for ANY world (topo.py), so a
    synthesized cycle never needs a link that does not exist.  The
    hierarchical leader links are deliberately excluded: they depend on
    the demotion set, which changes between epochs."""
    edges: set[tuple[int, int]] = set()

    def add(u: int, v: int) -> None:
        if u != v:
            edges.add((min(u, v), max(u, v)))

    for r in range(world):
        add(r, (r + 1) % world)
        for p in topo.halving_peers(r, world):
            add(r, p)
        for p in topo.swing_peers(r, world):
            add(r, p)
    return edges


def _norm_sketch(plan: dict | None, world: int) -> dict:
    """Fold a plan JSON (or None) into the normalized sketch the search
    consumes: numeric local/cross/chunks plus an edge->cost override
    map keyed by the canonical ``(min, max)`` tuple."""
    plan = plan or {}
    check(isinstance(plan, dict),
          "rabit_synth_plan must decode to a JSON object, got %s",
          type(plan).__name__)
    local = float(plan.get("local", DEFAULT_LOCAL_COST))
    cross = float(plan.get("cross", DEFAULT_CROSS_COST))
    chunks = int(plan.get("chunks", DEFAULT_CHUNKS))
    check(local > 0 and cross > 0, "synth link costs must be > 0")
    check(chunks >= 1, "synth chunks must be >= 1, got %r", chunks)
    links: dict[tuple[int, int], float] = {}
    for key, cost in (plan.get("links") or {}).items():
        parts = str(key).split("-")
        check(len(parts) == 2, "synth link key must be 'u-v', got %r",
              key)
        u, v = int(parts[0]), int(parts[1])
        check(0 <= u < world and 0 <= v < world and u != v,
              "synth link %r out of range for world %d", key, world)
        links[(min(u, v), max(u, v))] = float(cost)
    return {"local": local, "cross": cross, "chunks": chunks,
            "links": links}


def _cost_fn(sketch: dict, groups: list[int] | None):
    local, cross = sketch["local"], sketch["cross"]
    links = sketch["links"]

    def cost(u: int, v: int) -> float:
        e = (min(u, v), max(u, v))
        if e in links:
            return links[e]
        if groups and groups[u] != groups[v]:
            return cross
        return local

    return cost


def cycle_cost(perm: list[int], cost, chunks: int) -> float:
    """The sketch objective for one Hamiltonian cycle (see module
    docstring): bottleneck-gated steady state + amortized skew."""
    n = len(perm)
    edges = [cost(perm[i], perm[(i + 1) % n]) for i in range(n)]
    return 2.0 * (n - 1) * max(edges) + sum(edges) / chunks


# ---------------------------------------------------------------------
# the search: greedy construction + edge-constrained 2-opt
# ---------------------------------------------------------------------
def _greedy_cycle(world: int, edges: set, cost) -> list[int] | None:
    """Nearest-neighbor construction over the wired graph; None when
    greedy paints itself into a corner (no wired unvisited neighbor, or
    the closing edge is missing) — the identity ring then seeds the
    2-opt instead, so a feasible cycle always exists."""
    perm, seen = [0], {0}
    while len(perm) < world:
        here = perm[-1]
        best = None
        for nxt in range(world):
            if nxt in seen or (min(here, nxt), max(here, nxt)) not in edges:
                continue
            key = (cost(here, nxt), nxt)  # cost, then rank: deterministic
            if best is None or key < best:
                best = key
        if best is None:
            return None
        perm.append(best[1])
        seen.add(best[1])
    if (min(perm[-1], 0), max(perm[-1], 0)) not in edges:
        return None
    return perm


def _two_opt(perm: list[int], edges: set, cost, chunks: int) -> list[int]:
    """First-improvement 2-opt restricted to wired edges: reversing
    ``perm[i+1..j]`` replaces edges ``(p[i],p[i+1])`` and
    ``(p[j],p[j+1])`` with ``(p[i],p[j])`` and ``(p[i+1],p[j+1])`` —
    accepted only when both replacements are wired and the sketch
    objective strictly improves.  Fixed scan order + first-improvement
    makes the result a pure function of the inputs."""
    n = len(perm)
    best = cycle_cost(perm, cost, chunks)
    for _ in range(MAX_2OPT_PASSES):
        improved = False
        for i in range(n - 1):
            for j in range(i + 1, n):
                a, b = perm[i], perm[(i + 1) % n]
                c, d = perm[j], perm[(j + 1) % n]
                if a == c or b == d:
                    continue
                if ((min(a, c), max(a, c)) not in edges
                        or (min(b, d), max(b, d)) not in edges):
                    continue
                cand = (perm[:i + 1] + perm[i + 1:j + 1][::-1]
                        + perm[j + 1:])
                cc = cycle_cost(cand, cost, chunks)
                if cc < best - 1e-12:
                    perm, best, improved = cand, cc, True
        if not improved:
            break
    return perm


def _canonical(perm: list[int]) -> list[int]:
    """Rotate to start at rank 0 and pick the lexicographically smaller
    direction (a cycle and its reverse cost the same) — one canonical
    spelling per cycle, so caching and cross-rank comparison are
    stable."""
    i = perm.index(0)
    fwd = perm[i:] + perm[:i]
    rev = [fwd[0]] + fwd[1:][::-1]
    return fwd if fwd <= rev else rev


def synthesize(world: int, groups: list[int] | None = None,
               plan: dict | None = None) -> dict:
    """Synthesize the cycle for one topology+sketch.  Returns the full
    result document (what the offline CLI emits)::

        {"world": N, "perm": [...], "cost": float,
         "ring_cost": float, "cross_edges": int}

    ``ring_cost`` is the identity ring under the same sketch — the
    honest baseline a plan's predicted win is measured against."""
    check(world >= 2, "synth needs world >= 2, got %r", world)
    if groups is not None:
        check(len(groups) == world,
              "synth groups must have one entry per rank "
              "(world=%d, got %d)", world, len(groups))
    sketch = _norm_sketch(plan, world)
    cost = _cost_fn(sketch, groups)
    chunks = sketch["chunks"]
    identity = list(range(world))
    pinned = (plan or {}).get("perm")
    if pinned is not None:
        pinned = [int(r) for r in pinned]
        check(sorted(pinned) == identity,
              "synth plan 'perm' must be a permutation of 0..%d",
              world - 1)
        perm = _canonical(pinned)
    else:
        edges = wired_edges(world)
        cands = [identity]
        greedy = _greedy_cycle(world, edges, cost)
        if greedy is not None:
            cands.append(greedy)
        cands = [_two_opt(p, edges, cost, chunks) for p in cands]
        perm = _canonical(min(
            cands, key=lambda p: (cycle_cost(p, cost, chunks), p)))
    cross = sum(1 for i in range(world)
                if groups and groups[perm[i]]
                != groups[perm[(i + 1) % world]])
    return {"world": world,
            "perm": perm,
            "cost": round(cycle_cost(perm, cost, chunks), 6),
            "ring_cost": round(cycle_cost(identity, cost, chunks), 6),
            "cross_edges": cross}


def load_plan(path: str) -> dict:
    """Load + sanity-check a plan JSON for the engine (loud on a bad
    explicit path — a silently dropped plan is a misconfiguration the
    operator can never see)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            plan = json.load(fh)
    except (OSError, ValueError) as e:
        check(False, "rabit_synth_plan=%s unreadable: %s", path, e)
    check(isinstance(plan, dict),
          "rabit_synth_plan=%s must hold a JSON object", path)
    return plan


# ---------------------------------------------------------------------
# the schedule
# ---------------------------------------------------------------------
class SynthSchedule(Schedule):
    """Run the synthesized cycle as a permuted ring.  The cycle is a
    pure function of (world, groups, plan) — all replicated — computed
    once per topology and cached; epoch changes (new world/groups after
    a failover) naturally key a fresh synthesis."""

    name = "synth"

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cache: dict[tuple, list[int]] = {}

    def _cycle(self, eng) -> list[int]:
        groups = list(getattr(eng, "_groups", None) or [])
        if len(groups) != eng._world:
            groups = []
        plan = getattr(eng, "_synth_plan", None)
        if plan:
            # A failover-shrunk world outlives the plan it launched
            # with: drop the parts pinned to the old world (the stale
            # perm, out-of-range link rows) and re-synthesize from the
            # surviving sketch instead of dying in validation.
            n = eng._world
            perm = plan.get("perm")
            links = {k: v for k, v in (plan.get("links") or {}).items()
                     if all(p.isdigit() and int(p) < n
                            for p in str(k).split("-"))}
            plan = {k: v for k, v in plan.items()
                    if k not in ("perm", "links")}
            if links:
                plan["links"] = links
            if perm is not None and len(perm) == n:
                plan["perm"] = perm
        key = (eng._world, tuple(groups),
               json.dumps(plan, sort_keys=True) if plan else None)
        with self._lock:
            perm = self._cache.get(key)
            if perm is None:
                perm = synthesize(eng._world, groups or None,
                                  plan)["perm"]
                self._cache[key] = perm
        return perm

    def applies(self, eng, nbytes: int) -> bool:
        if eng._world < 2:
            return False
        perm = self._cycle(eng)
        p = perm.index(eng._rank)
        n = len(perm)
        # Honest link check, like every schedule: a plan-pinned cycle
        # may name edges outside the always-wired set, and the dispatch
        # must fall back instead of dying mid-collective.
        return self._links_ok(
            eng, {perm[(p - 1) % n], perm[(p + 1) % n]} - {eng._rank})

    def run(self, eng, buf: np.ndarray, op: ReduceOp,
            red_dtype=None) -> None:
        perm = self._cycle(eng)
        n = len(perm)
        p = perm.index(eng._rank)
        ring_allreduce(eng, buf, op, red_dtype,
                       ring_rank=p, ring_world=n,
                       prev=perm[(p - 1) % n], nxt=perm[(p + 1) % n])


# ---------------------------------------------------------------------
# offline CLI: python -m rabit_tpu.sched.synth
# ---------------------------------------------------------------------
def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="offline schedule synthesis: topology + sketch -> "
                    "plan JSON for rabit_synth_plan")
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--groups", default=None,
                    help="comma-separated host-group id per rank, "
                         "e.g. 0,1,0,1 (default: one flat group)")
    ap.add_argument("--plan", default=None,
                    help="input sketch JSON (link costs / chunks); "
                         "its 'perm', if any, is re-synthesized")
    ap.add_argument("--out", default=None,
                    help="write the plan JSON here (default: stdout)")
    args = ap.parse_args(argv)
    groups = ([int(g) for g in args.groups.split(",")]
              if args.groups else None)
    sketch = dict(load_plan(args.plan)) if args.plan else {}
    sketch.pop("perm", None)  # --plan is a sketch, not an answer
    result = synthesize(args.world, groups, sketch or None)
    # The emitted document doubles as a runtime plan: the sketch rides
    # along so the runtime cost/validation sees what the search saw.
    doc = {**sketch, **result}
    text = json.dumps(doc, indent=1, sort_keys=True) + "\n"
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(text)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
