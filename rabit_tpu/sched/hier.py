"""Hierarchical two-level allreduce for ``launch_pod`` shapes.

Keyed off the tracker's topology handout (the ``groups`` field — one
host-group id per rank, derived from registrant hosts or the
``RABIT_TRACKER_GROUPS`` override): each group's members reduce into
their leader (minimum rank — the chunked concurrent drain the tree
pump uses), the leaders run a bandwidth-optimal ring among themselves
over the cross-host leader links, and each leader broadcasts the
finished vector back to its members.  Cross-host traffic thus shrinks
from every-rank-crosses to one-rank-per-host-crosses — the win on pods
where intra-host loopback is an order of magnitude faster than DCN.

Merge order is deterministic (member-rank order inside the group,
leader-ring block order across), so pyrobust replay stays bit-exact.
The cross-host leader ring is the shared :func:`ring_allreduce` walk,
so its hop loops ride the engine's pipelined exchange+merge window
(``rabit_pipeline_depth`` — doc/performance.md "Hop pipelining")
exactly like the whole-world ring: leader merge compute hides behind
the (slow, cross-host) leader-link wire.
"""
from __future__ import annotations

import numpy as np

from rabit_tpu.ops import ReduceOp
from rabit_tpu.sched import topo
from rabit_tpu.sched.base import Schedule
from rabit_tpu.sched.ring import ring_allreduce


class HierarchicalSchedule(Schedule):
    name = "hier"

    def applies(self, eng, nbytes: int) -> bool:
        n = eng._world
        groups = getattr(eng, "_groups", None) or []
        if n < 2 or len(groups) != n or len(set(groups)) < 2:
            return False
        demoted = getattr(eng, "_demoted", ()) or ()
        return self._links_ok(
            eng, topo.hier_peers(eng._rank, n, groups, demoted))

    def run(self, eng, buf: np.ndarray, op: ReduceOp,
            red_dtype=None) -> None:
        n, r = eng._world, eng._rank
        groups = eng._groups
        # Straggler-demoted ranks (the adaptive controller's verdicts,
        # handed out with the topology) are excluded from leadership:
        # every rank received the same set at rendezvous, so the
        # election is uniform.
        demoted = getattr(eng, "_demoted", ()) or ()
        flat = buf.reshape(-1)
        if flat.nbytes == 0:
            return
        red = red_dtype if red_dtype is not None else flat.dtype
        rflat = flat.view(red)
        view = memoryview(flat).cast("B")
        item = flat.itemsize
        nelems = len(flat)
        members = topo.group_members(groups, r)
        leader = topo.group_leader(groups, groups[r], demoted)
        if r != leader:
            # Contribute, then park for the finished vector — the
            # intra-host legs ride the (fast, usually loopback) local
            # links only.
            eng._send(leader, view)
            eng._recv(leader, len(view), view)
            return
        # Drain order stays ascending member rank (minus the leader):
        # deterministic given the demotion set, so pyrobust replay and
        # cross-rank parity hold within an epoch.
        others = [m for m in members if m != leader]
        if others:
            # The engine's shared chunked concurrent drain: every
            # member streams at once, merges stay in member-rank order
            # so the reduction order is deterministic.
            def merge(off: int, ne: int, src) -> None:
                eng._wire_merge(op, rflat, off, ne,
                                np.frombuffer(src, dtype=red, count=ne))

            eng._drain_merge(others, nelems, item, merge)
        leaders = topo.group_leaders(groups, demoted)
        if len(leaders) > 1:
            li = leaders.index(r)
            nl = len(leaders)
            ring_allreduce(eng, buf, op, red_dtype,
                           ring_rank=li, ring_world=nl,
                           prev=leaders[(li - 1) % nl],
                           nxt=leaders[(li + 1) % nl])
        for mr in others:
            eng._send(mr, view)
