"""Ring schedules: bandwidth-optimal reduce-scatter + all-gather.

Extracted from the engine (the PR-3 pumps) and generalized: the ring
walk now runs over ANY ordered member list — the global world by
default, or a sub-ring such as the hierarchical schedule's cross-host
leader ring.  The fused segmented variant (one vectored exchange moves
every bucket member's block per step) lives here too.
"""
from __future__ import annotations

import numpy as np

from rabit_tpu.ops import ReduceOp
from rabit_tpu.ops.reduce_ops import apply_op_numpy
from rabit_tpu.sched.base import Schedule


def ring_allreduce(eng, buf: np.ndarray, op: ReduceOp, red_dtype=None, *,
                   ring_rank: int | None = None,
                   ring_world: int | None = None,
                   prev: int | None = None, nxt: int | None = None) -> None:
    """Bandwidth-optimal ring: reduce-scatter then all-gather.

    With the keyword arguments the walk runs over a sub-ring:
    ``ring_rank``/``ring_world`` index this member within it and
    ``prev``/``nxt`` are the GLOBAL ranks of its ring neighbors.
    Defaults reproduce the classic whole-world ring.
    """
    n = eng._world if ring_world is None else ring_world
    me = eng._rank if ring_rank is None else ring_rank
    nxt = eng._ring_next if nxt is None else nxt
    prev = eng._ring_prev if prev is None else prev
    flat = buf.reshape(-1)
    view = memoryview(flat).cast("B")
    # Block b covers bytes [off[b], off[b+1]); blocks itemsize-aligned.
    item = flat.itemsize
    per = (len(flat) + n - 1) // n
    bounds = [min(i * per, len(flat)) for i in range(n + 1)]
    red = red_dtype if red_dtype is not None else flat.dtype
    rflat = flat.view(red)

    def block(i: int) -> memoryview:
        b = i % n
        return view[bounds[b] * item: bounds[b + 1] * item]

    # Reduce-scatter scratch is one ring block, capped at the
    # rabit_reduce_buffer budget: oversized blocks stream through the
    # exchange in budget-sized sub-chunks (TCP framing is
    # size-agnostic, so peers with different budgets interoperate).
    # The chunked exchange+merge itself is the engine's pipelined hop
    # primitive: with rabit_pipeline_depth > 1 the next sub-chunk's
    # exchange is in flight while this one merges.  Ragged worlds
    # (len % world != 0) produce zero-length edge blocks, which take
    # zero sub-steps by construction — symmetric on both sides of
    # every link, since block b has one global length.
    chunk_elems = min(max(eng._reduce_buffer // item, 1), max(per, 1))
    cbytes = chunk_elems * item
    # Phase 1: reduce-scatter.  After step s, block (me-s) has been
    # combined at this member with s+1 contributions.
    for s in range(n - 1):
        send_b = me - s
        recv_b = me - s - 1
        sblk, rblk = block(send_b), block(recv_b)
        relem0 = bounds[recv_b % n]

        def merge(coff: int, rl: int, src) -> None:
            nelem = rl // item
            eng._wire_merge(op, rflat, relem0 + coff // item, nelem,
                            np.frombuffer(src, dtype=red, count=nelem))

        eng._hop_exchange_merge(nxt, sblk, prev, len(rblk), cbytes,
                                item, merge, what="ring hop")
    # Phase 2: all-gather the fully reduced blocks around the ring.
    for s in range(n - 1):
        send_b = me + 1 - s
        recv_b = me - s
        eng._exchange(nxt, block(send_b), prev, block(recv_b))


def ring_segmented(eng, tflats: list[np.ndarray], op: ReduceOp,
                   red) -> None:
    """Fused multi-member ring: every exchange step moves the
    corresponding block of EVERY member in one vectored write/read
    (scatter-gather ``sendmsg``, receives landing straight in the
    member arrays on the all-gather phase — no staging copies), so
    a bucket of K ring-sized ops costs one ring walk instead of K.
    Each member keeps its OWN block partition, hence its solo
    reduction order, bit for bit.  Merges stay raw ``apply_op_numpy``:
    a block-scaled codec never rides the segmented ring (its fused
    buckets concatenate into ONE codec op — pysocket._fused_wire), and
    the bf16 codec's members arrive here already cast per member."""
    n = eng._world
    item = tflats[0].itemsize
    views = [memoryview(f).cast("B") for f in tflats]
    rflats = [f.view(red) for f in tflats]
    bounds = []
    for f in tflats:
        per = (len(f) + n - 1) // n
        bounds.append([min(i * per, len(f)) for i in range(n + 1)])
    nmem = len(tflats)

    def blk(i: int, b: int) -> memoryview:
        b %= n
        return views[i][bounds[i][b] * item: bounds[i][b + 1] * item]

    max_recv = sum((bd[1] - bd[0]) * item for bd in bounds)
    scratch = eng._arena.take(max_recv)
    eng._note_scratch(max_recv)

    def _merge_member(i: int, recv_b: int, rpart, rl: int) -> None:
        nelem = rl // item
        e0 = bounds[i][recv_b % n]
        apply_op_numpy(op, rflats[i][e0:e0 + nelem],
                       np.frombuffer(rpart, dtype=red, count=nelem))

    try:
        # Phase 1: reduce-scatter, all members per step.
        for s in range(n - 1):
            send_b = eng._rank - s
            recv_b = eng._rank - s - 1
            sparts = [blk(i, send_b) for i in range(nmem)]
            rlens = [len(blk(i, recv_b)) for i in range(nmem)]
            rparts, off = [], 0
            for rl in rlens:
                rparts.append(scratch[off:off + rl])
                off += rl
            if eng._pipe_depth > 1 and nmem > 1:
                # Pipelined fused hop: member i's block merges while
                # member i+1's exchange is in flight — same bytes in
                # the same order as the vectored exchange below, so
                # mixed-depth peers interoperate.  Each member's recv
                # slice is distinct, so the window needs no slot
                # leases; the step boundary drains (a merged block is
                # the NEXT step's send).
                _seg_hop_pipelined(eng, sparts, rparts, rlens, recv_b,
                                   _merge_member)
            else:
                eng._exchange_v(eng._ring_next, sparts,
                                eng._ring_prev, rparts)
                for i, rl in enumerate(rlens):
                    if rl:
                        _merge_member(i, recv_b, rparts[i], rl)
        # Phase 2: all-gather the fully reduced blocks.
        for s in range(n - 1):
            send_b = eng._rank + 1 - s
            recv_b = eng._rank - s
            eng._exchange_v(
                eng._ring_next, [blk(i, send_b) for i in range(nmem)],
                eng._ring_prev, [blk(i, recv_b) for i in range(nmem)])
    finally:
        eng._arena.give(scratch)


def _seg_hop_pipelined(eng, sparts: list, rparts: list, rlens: list,
                       recv_b: int, merge_member) -> None:
    """One pipelined step of the fused segmented ring: per-member
    chunk pushes through a :class:`~rabit_tpu.transport.pump.
    HopPipeline`, popped and merged in member order with at most
    ``rabit_pipeline_depth`` exchanges in flight.  The engine's
    ``_pipe_run`` owns the open/close/abort + failover-attribution
    choreography (one copy of the discipline)."""
    def body(pipe) -> None:
        def pop_merge() -> None:
            i, rl = pipe.pop()
            if rl:
                merge_member(i, recv_b, rparts[i], rl)

        for i, sp in enumerate(sparts):
            if pipe.inflight >= eng._pipe_depth:
                pop_merge()
            rl = rlens[i]
            pipe.push([sp] if len(sp) else [],
                      [rparts[i]] if rl else [], (i, rl))
        while pipe.inflight:
            pop_merge()

    eng._pipe_run(eng._ring_next, eng._ring_prev, "fused ring hop",
                  body)


class RingSchedule(Schedule):
    name = "ring"

    def applies(self, eng, nbytes: int) -> bool:
        return eng._world >= 2  # ring links are always wired

    def run(self, eng, buf: np.ndarray, op: ReduceOp,
            red_dtype=None) -> None:
        ring_allreduce(eng, buf, op, red_dtype)
