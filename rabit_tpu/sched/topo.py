"""Pure peer-pattern math for the collective schedules.

Shared by BOTH sides of the wire: the tracker calls
:func:`extra_link_peers` when computing each rank's linkset (so every
schedule's peers are wired at rendezvous, exactly like the tree/ring
links), and the engine-side ``Schedule.applies`` checks call the same
functions to decide whether the links a schedule needs actually exist.
Keeping one source of truth here is what makes "new algorithms are
data, not code forks" safe: a schedule that needs a peer the tracker
did not hand out simply reports ``applies() == False`` and the dispatch
falls back, instead of dying on a missing link.

No engine/tracker imports — this module must stay import-cycle-free
(tracker → sched.topo, engine → sched → sched.topo).
"""
from __future__ import annotations


def pow2_floor(n: int) -> int:
    """Largest power of two <= n (n >= 1)."""
    return 1 << (n.bit_length() - 1)


def is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


# ---------------------------------------------------------------------
# recursive halving/doubling (Rabenseifner-style)
# ---------------------------------------------------------------------
def halving_peers(rank: int, world: int) -> set[int]:
    """Peers rank talks to under recursive halving/doubling allreduce.

    Non-power-of-two worlds fold the ``world - m`` extra ranks into a
    pre/post step: extra rank ``r >= m`` talks only to its fold partner
    ``r - m``; core ranks exchange with every XOR partner ``r ^ d`` for
    ``d`` in the power-of-two ladder, plus their fold extra if any.
    """
    if world < 2:
        return set()
    m = pow2_floor(world)
    if rank >= m:
        return {rank - m}
    peers = set()
    d = m >> 1
    while d:
        peers.add(rank ^ d)
        d >>= 1
    if rank + m < world:
        peers.add(rank + m)
    return peers


# ---------------------------------------------------------------------
# Swing-style short-cut ring (distance-doubling over the ring order)
# ---------------------------------------------------------------------
def rho(h: int) -> int:
    """Swing step distance: 1, -1, 3, -5, 11, -21, ... — the partial
    sums of (-2)**i, so consecutive steps jump in alternating
    directions with doubling reach (Swing, PAPERS.md)."""
    return (1 - (-2) ** (h + 1)) // 3


def swing_peer(rank: int, world: int, step: int) -> int:
    """Peer of ``rank`` at Swing step ``step``: even ranks move
    ``+rho``, odd ranks ``-rho`` around the ring, which pairs every
    rank with exactly one partner per step (an involution for even
    worlds)."""
    d = rho(step)
    return (rank + d) % world if rank % 2 == 0 else (rank - d) % world


def swing_steps(world: int) -> int:
    """log2(world) for the power-of-two worlds Swing runs on."""
    return max(world.bit_length() - 1, 0)


def swing_peers(rank: int, world: int) -> set[int]:
    if not is_pow2(world) or world < 2:
        return set()
    return {swing_peer(rank, world, h) for h in range(swing_steps(world))}


# ---------------------------------------------------------------------
# hierarchical two-level (intra-host leader + cross-host leader ring)
# ---------------------------------------------------------------------
def group_leader(groups: list[int], gid: int,
                 demoted=()) -> int:
    """Leader of one group: the minimum rank NOT in ``demoted`` (the
    adaptive controller's straggler-demotion set — a persistently late
    rank must not anchor the cross-host leader ring).  Falls back to
    the plain minimum rank when the whole group is demoted: a degraded
    leader still beats no schedule at all, and every rank computes the
    same fallback."""
    demoted = frozenset(demoted)
    best: tuple[int, int] | None = None
    for rank, g in enumerate(groups):
        if g != gid:
            continue
        pref = (1 if rank in demoted else 0, rank)
        if best is None or pref < best:
            best = pref
    assert best is not None, f"group {gid} has no members"
    return best[1]


def group_leaders(groups: list[int], demoted=()) -> list[int]:
    """Leader of each group (see :func:`group_leader`), in ascending
    rank order.  One O(world) pass — this sits on the hierarchical
    schedule's per-dispatch ``applies()`` path: per group, keep the
    (not-demoted, rank)-minimal member, which IS "min non-demoted rank,
    else min rank"."""
    demoted = frozenset(demoted)
    best: dict[int, tuple[int, int]] = {}
    for rank, gid in enumerate(groups):
        pref = (1 if rank in demoted else 0, rank)
        cur = best.get(gid)
        if cur is None or pref < cur:
            best[gid] = pref
    return sorted(r for _d, r in best.values())


def group_members(groups: list[int], rank: int) -> list[int]:
    """Ranks sharing ``rank``'s group, ascending."""
    gid = groups[rank]
    return [r for r, g in enumerate(groups) if g == gid]


def hier_peers(rank: int, world: int, groups: list[int],
               demoted=()) -> set[int]:
    """Peers for the two-level schedule: members link to their group
    leader; leaders additionally link to their neighbors on the
    cross-host leader ring.  Only handed out for true multi-group
    topologies — with one group the schedule would degenerate to a
    star on rank 0, which scales worse than the tree it would replace.
    ``demoted`` excludes straggler-demoted ranks from leadership (the
    tracker passes the job's demotion set at rendezvous; the engine's
    ``applies()`` check passes the same set from its topology reply,
    so both sides agree on the links)."""
    if world < 2 or len(groups) != world or len(set(groups)) < 2:
        return set()
    members = group_members(groups, rank)
    leader = group_leader(groups, groups[rank], demoted)
    if rank != leader:
        return {leader}
    peers = {r for r in members if r != rank}
    leaders = group_leaders(groups, demoted)
    if len(leaders) > 1:
        li = leaders.index(rank)
        peers.add(leaders[(li - 1) % len(leaders)])
        peers.add(leaders[(li + 1) % len(leaders)])
    return peers


# ---------------------------------------------------------------------
# tracker-side union
# ---------------------------------------------------------------------
def extra_link_peers(rank: int, world: int,
                     groups: list[int] | None = None,
                     demoted=()) -> set[int]:
    """Union of every schedule's extra peers for one rank — what the
    tracker adds to the tree/ring linkset at rendezvous.  O(log world)
    extra links per rank (plus group-local links on leaders), so the
    handout stays sparse at scale.  ``demoted`` shifts the hierarchical
    leader links away from straggler-demoted ranks; the union ALSO
    keeps the undemoted leader links wired, so a later reinstatement
    epoch never meets a missing link."""
    peers = halving_peers(rank, world) | swing_peers(rank, world)
    if groups:
        peers |= hier_peers(rank, world, groups)
        if demoted:
            peers |= hier_peers(rank, world, groups, demoted)
    peers.discard(rank)
    return peers
