"""Swing-style short-cut ring allreduce (latency-optimal variant).

The Swing peer pattern (PAPERS.md): at step ``h`` even ranks jump
``+rho(h)`` and odd ranks ``-rho(h)`` around the ring, where ``rho`` is
the partial sum of ``(-2)**i`` — 1, -1, 3, -5, 11, ... — so reach
doubles per step while hops alternate direction, halving the distance
travelled on a physical ring versus recursive doubling.  Each step is a
full-vector exchange-and-reduce with the paired peer; the pairing is an
involution whose reachability sets are disjoint and double per step, so
after log2(n) rounds every rank holds each contribution exactly once
(power-of-two worlds only — others fall back at ``applies()``).

log2(n) rounds of N bytes beats the tree's 2*log2(n) sequential
full-payload hops on latency-bound and mid-size payloads, and beats
the ring's 2(n-1) rounds whenever per-hop latency dominates the
per-byte cost — exactly the regime the auto-tuner hands it.
"""
from __future__ import annotations

import numpy as np

from rabit_tpu.ops import ReduceOp
from rabit_tpu.sched import topo
from rabit_tpu.sched.base import Schedule


class SwingSchedule(Schedule):
    name = "swing"

    def applies(self, eng, nbytes: int) -> bool:
        n = eng._world
        if n < 2 or not topo.is_pow2(n):
            return False
        return self._links_ok(eng, topo.swing_peers(eng._rank, n))

    def run(self, eng, buf: np.ndarray, op: ReduceOp,
            red_dtype=None) -> None:
        n, r = eng._world, eng._rank
        flat = buf.reshape(-1)
        if flat.nbytes == 0:
            return
        red = red_dtype if red_dtype is not None else flat.dtype
        rflat = flat.view(red)
        view = memoryview(flat).cast("B")
        item = flat.itemsize
        chunk_elems = min(max(eng._reduce_buffer // item, 1), len(flat))
        cbytes = chunk_elems * item
        for h in range(topo.swing_steps(n)):
            p = topo.swing_peer(r, n, h)
            # Full-vector exchange+reduce, sub-chunked through the
            # engine's pipelined hop window.  A chunk is merged only
            # AFTER its own exchange fully completes (the pipeline's
            # pop contract — for framed links that includes the tx
            # backlog, since the merge mutates the region just sent),
            # and later chunks' regions are untouched until their own
            # turn — so both sides always ship this step's pre-merge
            # bytes, symmetrically, at any depth.
            # record=(r < p): both pairing members run the IDENTICAL
            # requantizing merge over the same range (that symmetry is
            # what keeps the bits equal), so under a block-scaled wire
            # codec one quantization event would land on TWO ranks'
            # error-feedback ledgers and the dual-sided compensation
            # would overcorrect 2x.  Exactly one side of each pairing
            # records the hop residual; the merged bytes are unchanged.

            def merge(coff: int, rl: int, src) -> None:
                ne = rl // item
                eng._wire_merge(op, rflat, coff // item, ne,
                                np.frombuffer(src, dtype=red, count=ne),
                                r < p)

            eng._hop_exchange_merge(p, view, p, len(view), cbytes,
                                    item, merge, what="swing hop")
