"""The schedule auto-tuner's persisted measurement cache.

``bench.py --suite collectives`` measures per-(payload, world) MB/s for
every applicable schedule and — given ``--tune-dir`` — persists the
winners here, the way obs reports are persisted: a versioned JSON file
under a caller-chosen directory (an ``--obs-dir`` sibling), written
atomically (tmp + rename).  At runtime ``rabit_sched=auto`` loads the
cache once at ``init()`` and picks the measured winner for each
dispatch point (nearest benchmarked size in log space, exact world
match); any miss — no cache, schema drift, unknown schedule, world
never benchmarked — falls back to the static tree/ring crossover.

The cache MUST be identical on every rank (schedule choice is a
collective decision, like ``rabit_bucket_bytes``): point every rank at
the same file, e.g. a shared filesystem path or a per-host copy of the
same tuning run (doc/performance.md "Schedule selection").
"""
from __future__ import annotations

import json
import math
import os
import tempfile
from typing import Optional

from rabit_tpu.utils.checks import log

#: bump when the on-disk layout changes; readers reject other versions
SCHEMA_VERSION = 1
CACHE_FILENAME = "sched_cache.json"


class TuningCache:
    """In-memory form of the persisted tuning table.

    ``table`` maps op kind -> world (str) -> payload bytes (str) ->
    winning schedule name; ``meta`` carries provenance (schema, host,
    world, bench row) so a recorded cache explains itself.
    """

    def __init__(self, table: dict, meta: dict | None = None) -> None:
        self.table = table
        self.meta = dict(meta or {})
        # Nearest-world fallback memo: pick() sits on the per-collective
        # dispatch hot path, so the full-table scan runs once per
        # (kind, world) — every later miss is a dict hit — and the
        # structured-log note fires once with it.
        self._world_fallback: dict = {}

    # ------------------------------------------------------------- build
    @staticmethod
    def table_kind(kind: str, transport: str = "tcp",
                   codec: str = "none") -> str:
        """Cache-table key for an op kind on a transport and wire
        codec.  ``tcp``/``none`` keep the bare kind (every pre-existing
        cache keeps working); any other transport gets its own
        ``kind@transport`` rows and any other codec its own
        ``kind+codec`` rows, so a winner measured over shm rings never
        answers a TCP world and a winner measured over a quantized wire
        (whose per-payload wire bytes — hence crossovers — genuinely
        differ) never answers a full-width job, or vice versa."""
        if transport not in ("", "tcp", None):
            kind = f"{kind}@{transport}"
        if codec not in ("", "none", None):
            kind = f"{kind}+{codec}"
        return kind

    @classmethod
    def from_bench(cls, per_size_mbps: dict, world: int, *,
                   host: str = "", candidates=None,
                   extra_meta: dict | None = None,
                   transport: str = "tcp",
                   codec: str = "none") -> "TuningCache":
        """Build from the per-size MB/s table the collectives bench
        emits (``{"<bytes>": {"tree": MBps, "ring": ..., ...}}``).
        ``candidates`` restricts which columns may win (the bench also
        measures non-schedule paths like ``bucketed``); ``transport``
        and ``codec`` key the rows to the wire they were measured on."""
        best: dict[str, str] = {}
        for size, row in per_size_mbps.items():
            cand = {k: float(v) for k, v in row.items()
                    if candidates is None or k in candidates}
            if cand:
                best[str(int(size))] = max(cand, key=cand.get)
        meta = {"host": host, "world": int(world),
                "transport": transport, "codec": codec}
        meta.update(extra_meta or {})
        return cls({cls.table_kind("allreduce", transport, codec):
                    {str(int(world)): best}}, meta)

    # --------------------------------------------------------------- io
    def save(self, dir_path: str) -> str:
        """Atomic persist under ``dir_path`` (created if missing);
        returns the cache file path."""
        os.makedirs(dir_path, exist_ok=True)
        path = os.path.join(dir_path, CACHE_FILENAME)
        payload = {"schema": SCHEMA_VERSION, "meta": self.meta,
                   "table": self.table}
        fd, tmp = tempfile.mkstemp(dir=dir_path, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f, indent=2, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path

    @classmethod
    def load(cls, path: str) -> Optional["TuningCache"]:
        """Load from a cache file or a directory holding one.  Returns
        None (never raises) on anything unusable — a missing file,
        corrupt JSON, or a schema version this reader does not speak —
        so ``auto`` degrades to the static crossover instead of
        refusing to start."""
        if os.path.isdir(path):
            path = os.path.join(path, CACHE_FILENAME)
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(payload, dict):
            return None
        if payload.get("schema") != SCHEMA_VERSION:
            return None
        table = payload.get("table")
        if not isinstance(table, dict):
            return None
        return cls(table, payload.get("meta") or {})

    # ---------------------------------------------------------- online
    def merge_online(self, kind: str, world: int, nbytes: int,
                     name: str, transport: str = "tcp",
                     codec: str = "none") -> None:
        """Fold one LIVE measurement verdict into the table: the
        adaptive controller decided ``name`` wins ``(kind, world,
        payload bucket)`` from rolling span data (doc/performance.md
        "Online adaptation").  Widens the cache's world coverage — a
        bench'd cache learns worlds the bench never ran — and the next
        ``rabit_sched=auto`` job at this world starts on the learned
        schedule instead of re-discovering it.  ``transport`` and
        ``codec`` key the rows (:meth:`table_kind`): verdicts measured
        over shm rings must never answer a tcp world, nor quantized-
        wire verdicts a full-width job, or vice versa."""
        rows = self.table.setdefault(
            self.table_kind(kind, transport, codec), {}).setdefault(
            str(int(world)), {})
        rows[str(int(nbytes))] = str(name)
        self._world_fallback.clear()  # coverage changed: re-derive
        self.meta["online_merges"] = int(
            self.meta.get("online_merges", 0)) + 1

    # ------------------------------------------------------------- query
    def pick(self, kind: str, nbytes: int, world: int,
             transport: str = "tcp", codec: str = "none"
             ) -> Optional[str]:
        """Winning schedule name for the nearest benchmarked payload
        size (log-space distance), or None.  An exact world match wins;
        a world the cache never saw falls back to the NEAREST bench'd
        world in log space (noted once per world in the structured log)
        instead of silently dropping to static — peer patterns scale
        smoothly enough in log(world) that a neighboring world's winner
        beats no information at all.  ``transport`` and ``codec`` scope
        the lookup to rows measured on the same wire format
        (:meth:`table_kind`) — a shm or int8 world with no matching
        rows misses to static rather than borrowing full-width TCP
        numbers whose crossovers don't apply."""
        kind = self.table_kind(kind, transport, codec)
        table = self.table.get(kind)
        if not table:
            return None
        key = str(int(world))
        rows = table.get(key)
        if not rows:
            # Miss: resolve (and memoize) the nearest bench'd world —
            # the scan runs once per (kind, world), not once per op.
            near = self._world_fallback.get((kind, key), "")
            if near == "":
                worlds = [w for w, r in table.items()
                          if r and str(w).isdigit()]
                if worlds:
                    wt = math.log(max(int(world), 1))
                    near = min(sorted(worlds),
                               key=lambda w: (abs(math.log(int(w)) - wt),
                                              int(w)))
                    log("tuner: no %s rows for world %d; falling back "
                        "to the nearest bench'd world %s", kind, world,
                        near)
                else:
                    near = None
                self._world_fallback[(kind, key)] = near
            if near is None:
                return None
            rows = table[near]
            # A neighbor world's coverage may be SPARSE (a single
            # online-merged bucket): compounding the world fallback
            # with unbounded size extrapolation would let that one row
            # answer every payload — e.g. a 64-byte op picking a
            # bandwidth schedule learned at 512KB.  On the fallback
            # path only, sizes further than two octaves from any
            # covered row miss to static (the exact-world pick keeps
            # its original unbounded nearest-size semantics).
            target = math.log(max(int(nbytes), 1))
            size = min(rows, key=lambda s: abs(
                math.log(max(int(s), 1)) - target))
            if abs(math.log(max(int(size), 1)) - target) > math.log(4.0):
                return None
            name = rows[size]
            return str(name) if name else None
        target = math.log(max(int(nbytes), 1))
        size = min(rows, key=lambda s: abs(
            math.log(max(int(s), 1)) - target))
        name = rows[size]
        return str(name) if name else None


# ---------------------------------------------------------------------
# live schedule directives (the adaptive controller's wire format)
# ---------------------------------------------------------------------
# A directive is a tiny per-payload-bucket override table the tracker's
# AdaptiveController pushes with the topology at a schedule-switch
# epoch (rabit_tpu/obs/adapt.py): "``bytes:name``" entries joined by
# commas, e.g. "524288:swing" or "262144:halving,4194304:hier".  The
# engine consults it like a one-job tuning cache (nearest bucket in
# log space) before the static/auto pick.  Encoded as a plain string
# so it rides the topology reply as one trailing field and tolerates
# version skew (an unknown entry is simply skipped).
#
# A directive entry may additionally carry a PER-OP CODEC OVERRIDE:
# "``bytes:name/codec``" (e.g. "4194304:ring/int8") asks the engine to
# run the dominant bucket's eligible ops on that wire codec regardless
# of the job's ``rabit_wire_codec`` — the schedule verdict and the wire
# format it was measured on travel together.  The old plain-name form
# parses unchanged in both directions, and on a pre-codec-directive
# engine the slashed name simply misses the schedule registry and falls
# through to the static/auto pick (the entry degrades, never deadlocks
# — which is also why a controller should only emit the slashed form to
# a world it knows speaks it).

def encode_directive(table: dict[int, str]) -> str:
    return ",".join(f"{int(b)}:{n}" for b, n in sorted(table.items()))


def decode_directive(raw: str) -> dict[int, str]:
    """Parse a directive string; malformed entries are skipped, never
    raised — the string arrives from the network."""
    out: dict[int, str] = {}
    for part in str(raw or "").split(","):
        if ":" not in part:
            continue
        b, name = part.split(":", 1)
        name = name.strip()
        try:
            bucket = int(b)
        except ValueError:
            continue
        if bucket > 0 and name:
            out[bucket] = name
    return out


def _directive_value(table: dict[int, str],
                     nbytes: int) -> Optional[str]:
    """Raw directive entry for one payload: nearest bucket in log
    space — capped at two octaves, like the cache's nearest-world
    fallback.  The controller only writes the DOMINANT bucket, so an
    uncapped nearest pick would steer every stray small op onto the
    dominant bucket's bandwidth schedule (a 4KB op has no business
    riding a directive learned at 512KB); out-of-range sizes fall
    through to the engine's static/auto pick instead."""
    if not table:
        return None
    target = math.log(max(int(nbytes), 1))
    bucket = min(table, key=lambda b: abs(math.log(max(b, 1)) - target))
    if abs(math.log(max(bucket, 1)) - target) > math.log(4.0):
        return None
    return table[bucket]


def directive_entry(table: dict[int, str],
                    nbytes: int) -> tuple[Optional[str], Optional[str]]:
    """``(schedule, codec)`` for one payload — the codec is None for
    the classic plain-name entry form ("use the job's codec") and a
    codec name for the slashed ``name/codec`` per-op override form."""
    raw = _directive_value(table, nbytes)
    if raw is None:
        return None, None
    if "/" in raw:
        name, codec = raw.split("/", 1)
        return (name.strip() or None), (codec.strip() or None)
    return raw, None


def directive_pick(table: dict[int, str], nbytes: int) -> Optional[str]:
    """The directive's SCHEDULE verdict for one payload (codec
    stripped; see :func:`directive_entry` for both halves)."""
    return directive_entry(table, nbytes)[0]


def directive_codec(table: dict[int, str],
                    nbytes: int) -> Optional[str]:
    """The directive's per-op CODEC override for one payload, or None
    when the entry keeps the job default."""
    return directive_entry(table, nbytes)[1]
