"""The schedule auto-tuner's persisted measurement cache.

``bench.py --suite collectives`` measures per-(payload, world) MB/s for
every applicable schedule and — given ``--tune-dir`` — persists the
winners here, the way obs reports are persisted: a versioned JSON file
under a caller-chosen directory (an ``--obs-dir`` sibling), written
atomically (tmp + rename).  At runtime ``rabit_sched=auto`` loads the
cache once at ``init()`` and picks the measured winner for each
dispatch point (nearest benchmarked size in log space, exact world
match); any miss — no cache, schema drift, unknown schedule, world
never benchmarked — falls back to the static tree/ring crossover.

The cache MUST be identical on every rank (schedule choice is a
collective decision, like ``rabit_bucket_bytes``): point every rank at
the same file, e.g. a shared filesystem path or a per-host copy of the
same tuning run (doc/performance.md "Schedule selection").
"""
from __future__ import annotations

import json
import math
import os
import tempfile
from typing import Optional

#: bump when the on-disk layout changes; readers reject other versions
SCHEMA_VERSION = 1
CACHE_FILENAME = "sched_cache.json"


class TuningCache:
    """In-memory form of the persisted tuning table.

    ``table`` maps op kind -> world (str) -> payload bytes (str) ->
    winning schedule name; ``meta`` carries provenance (schema, host,
    world, bench row) so a recorded cache explains itself.
    """

    def __init__(self, table: dict, meta: dict | None = None) -> None:
        self.table = table
        self.meta = dict(meta or {})

    # ------------------------------------------------------------- build
    @classmethod
    def from_bench(cls, per_size_mbps: dict, world: int, *,
                   host: str = "", candidates=None,
                   extra_meta: dict | None = None) -> "TuningCache":
        """Build from the per-size MB/s table the collectives bench
        emits (``{"<bytes>": {"tree": MBps, "ring": ..., ...}}``).
        ``candidates`` restricts which columns may win (the bench also
        measures non-schedule paths like ``bucketed``)."""
        best: dict[str, str] = {}
        for size, row in per_size_mbps.items():
            cand = {k: float(v) for k, v in row.items()
                    if candidates is None or k in candidates}
            if cand:
                best[str(int(size))] = max(cand, key=cand.get)
        meta = {"host": host, "world": int(world)}
        meta.update(extra_meta or {})
        return cls({"allreduce": {str(int(world)): best}}, meta)

    # --------------------------------------------------------------- io
    def save(self, dir_path: str) -> str:
        """Atomic persist under ``dir_path`` (created if missing);
        returns the cache file path."""
        os.makedirs(dir_path, exist_ok=True)
        path = os.path.join(dir_path, CACHE_FILENAME)
        payload = {"schema": SCHEMA_VERSION, "meta": self.meta,
                   "table": self.table}
        fd, tmp = tempfile.mkstemp(dir=dir_path, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f, indent=2, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path

    @classmethod
    def load(cls, path: str) -> Optional["TuningCache"]:
        """Load from a cache file or a directory holding one.  Returns
        None (never raises) on anything unusable — a missing file,
        corrupt JSON, or a schema version this reader does not speak —
        so ``auto`` degrades to the static crossover instead of
        refusing to start."""
        if os.path.isdir(path):
            path = os.path.join(path, CACHE_FILENAME)
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(payload, dict):
            return None
        if payload.get("schema") != SCHEMA_VERSION:
            return None
        table = payload.get("table")
        if not isinstance(table, dict):
            return None
        return cls(table, payload.get("meta") or {})

    # ------------------------------------------------------------- query
    def pick(self, kind: str, nbytes: int, world: int) -> Optional[str]:
        """Winning schedule name for the nearest benchmarked payload
        size (log-space distance, exact world match), or None."""
        rows = self.table.get(kind, {}).get(str(int(world)))
        if not rows:
            return None
        target = math.log(max(int(nbytes), 1))
        size = min(rows, key=lambda s: abs(
            math.log(max(int(s), 1)) - target))
        name = rows[size]
        return str(name) if name else None
