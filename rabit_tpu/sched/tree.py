"""Tree schedule: reduce up the binary tree, broadcast down.

The latency king for small payloads (log2(n) hops) and the baseline
every other schedule is bit-compared against.  The two-phase chunked
pump itself lives on the engine (``_tree_chunked``) because it is also
the transport for custom-reducer allreduces; this schedule is the thin
allreduce face over it.
"""
from __future__ import annotations

import numpy as np

from rabit_tpu.ops import ReduceOp
from rabit_tpu.sched.base import Schedule


class TreeSchedule(Schedule):
    name = "tree"

    def applies(self, eng, nbytes: int) -> bool:
        return eng._world >= 2  # tree links are always wired

    def run(self, eng, buf: np.ndarray, op: ReduceOp,
            red_dtype=None) -> None:
        eng._tree_allreduce(buf, op, red_dtype)
