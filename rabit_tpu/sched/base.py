"""The Schedule interface — collective algorithms as pluggable data.

A :class:`Schedule` is a stateless singleton describing ONE allreduce
algorithm over the engine's wired links.  The engine's dispatch
(``PySocketEngine._allreduce_dispatch``) selects a schedule per
``(op, dtype, payload_bytes, world, topology)`` point — statically via
the tree/ring crossover, by force (``rabit_sched=<name>``), or from the
auto-tuner's measured table (``rabit_sched=auto``) — and every layer
above (bucket fusion, async pump, pyrobust seqno/replay, chaos
injection) composes unchanged because a schedule is deterministic given
the topology: the same op stream on the same world produces the same
wire traffic, so replay stays bit-exact.

Schedules run INSIDE the engine's op body with the engine's own IO
helpers (``_exchange``/``_send``/``_recv``/``_recv_all``), scratch
arena and reduce-buffer chunk budget; they own only the peer pattern
and block math.  Reductions go through the engine's ``_wire_merge``
seam (absolute element offset + count): classic and bf16 ops reduce
elementwise in the handed ``red_dtype``, while an armed block-scaled
wire codec (rabit_tpu/codec/) dequantizes→accumulates→requantizes the
encoded blocks — one wire element per quantization block, so the
schedules' item-aligned chunking composes with quantization for free.  ``applies()`` must be cheap, deterministic across
ranks (it sees only replicated state: world, topology handout, payload
size) and honest about link availability — a schedule whose links the
tracker did not wire reports False and the dispatch falls back to the
static crossover instead of dying on a KeyError mid-collective.
"""
from __future__ import annotations

import numpy as np

from rabit_tpu.ops import ReduceOp


class Schedule:
    """One allreduce algorithm; subclasses override ``name``/``run``."""

    #: registry key, obs counter suffix (``sched.pick.<name>``) and the
    #: ``rabit_sched`` value that forces this schedule
    name = "?"

    def applies(self, eng, nbytes: int) -> bool:
        """Can this schedule run the given payload on ``eng``'s current
        topology?  Checked on EVERY rank with replicated inputs, so all
        ranks agree; False sends the op to the static fallback."""
        return eng._world >= 2

    def run(self, eng, buf: np.ndarray, op: ReduceOp,
            red_dtype=None) -> None:
        """Reduce ``buf`` in place across the world.  ``red_dtype``
        decouples the merge element type from the transport dtype (the
        bf16 wire path moves uint16 bytes but reduces in bf16); None
        means they coincide."""
        raise NotImplementedError

    def _links_ok(self, eng, peers) -> bool:
        links = eng._links
        return all(p in links for p in peers)
