"""Recursive halving/doubling allreduce (Rabenseifner-style).

Reduce-scatter by recursive vector halving (log2(m) exchange steps,
each moving half the remaining region to the XOR partner), then
all-gather by recursive doubling — 2*log2(m) total rounds moving
2*(1-1/m)*N bytes per rank, the same volume as the ring in log(n)
rather than 2(n-1) rounds.  That makes it the mid-size sweet spot:
latency-bound enough that the ring's 2(n-1) serial hops hurt, large
enough that the tree's full-payload store-and-forward per level hurts.

Non-power-of-two worlds fold the ``world - m`` extra ranks in a
pre/post step: extra rank ``r >= m`` ships its vector to fold partner
``r - m`` (merged before the power-of-two phase) and receives the
finished result after it — the classic 3-phase fallback.

Block bounds are the same global, itemsize-aligned partition the ring
uses, so ragged payloads (``len % m != 0``, including ``len < m`` with
zero-length edge blocks) take zero-byte exchanges symmetrically on
both sides of every link.
"""
from __future__ import annotations

import numpy as np

from rabit_tpu.ops import ReduceOp
from rabit_tpu.sched import topo
from rabit_tpu.sched.base import Schedule


class HalvingDoublingSchedule(Schedule):
    name = "halving"

    def applies(self, eng, nbytes: int) -> bool:
        if eng._world < 2:
            return False
        return self._links_ok(
            eng, topo.halving_peers(eng._rank, eng._world))

    def run(self, eng, buf: np.ndarray, op: ReduceOp,
            red_dtype=None) -> None:
        n, r = eng._world, eng._rank
        flat = buf.reshape(-1)
        if flat.nbytes == 0:
            return
        red = red_dtype if red_dtype is not None else flat.dtype
        rflat = flat.view(red)
        view = memoryview(flat).cast("B")
        item = flat.itemsize
        nelems = len(flat)
        m = topo.pow2_floor(n)
        chunk_elems = min(max(eng._reduce_buffer // item, 1), nelems)
        cbytes = chunk_elems * item

        # Fold pre-step: extra ranks ship their whole vector to the
        # partner (chunk-drained there) and park until the post-step.
        if r >= m:
            p = r - m
            eng._send(p, view)
            eng._recv(p, len(view), view)
            return
        scratch = np.empty(chunk_elems, dtype=flat.dtype)
        rscratch = scratch.view(red)
        sview = memoryview(scratch).cast("B")
        eng._note_scratch(scratch.nbytes)
        if r + m < n:
            for off in range(0, len(view), cbytes):
                nb = min(cbytes, len(view) - off)
                eng._recv(r + m, nb, sview[:nb])
                ne = nb // item
                e0 = off // item
                eng._wire_merge(op, rflat, e0, ne, rscratch)

        per = -(-nelems // m)
        bounds = [min(i * per, nelems) for i in range(m + 1)]
        # Phase 1: reduce-scatter by halving.  At distance d my live
        # region [nb, nb+d) blocks halves; I ship the partner's half
        # and fold its contribution for mine.  After the walk, block r
        # is fully reduced here.
        d = m >> 1
        while d:
            p = r ^ d
            nb = r & ~(d - 1)
            pnb = p & ~(d - 1)
            sblk = view[bounds[pnb] * item: bounds[pnb + d] * item]
            r_lo = bounds[nb]
            rbytes = (bounds[nb + d] - r_lo) * item
            nsteps = max(-(-len(sblk) // cbytes), -(-rbytes // cbytes))
            for ci in range(nsteps):
                coff = ci * cbytes
                sl = min(cbytes, max(len(sblk) - coff, 0))
                rl = min(cbytes, max(rbytes - coff, 0))
                eng._exchange(p, sblk[coff:coff + sl], p, sview[:rl])
                ne = rl // item
                e0 = r_lo + coff // item
                eng._wire_merge(op, rflat, e0, ne, rscratch)
            d >>= 1
        # Phase 2: all-gather by doubling — the reverse walk, receives
        # landing straight in the payload (no scratch, like the ring's
        # gather phase).
        d = 1
        while d < m:
            p = r ^ d
            base = r & ~(d - 1)
            pbase = base ^ d
            eng._exchange(
                p, view[bounds[base] * item: bounds[base + d] * item],
                p, view[bounds[pbase] * item: bounds[pbase + d] * item])
            d <<= 1
        # Fold post-step: hand the finished vector back to the extra.
        if r + m < n:
            eng._send(r + m, view)
