"""Recursive halving/doubling allreduce (Rabenseifner-style).

Reduce-scatter by recursive vector halving (log2(m) exchange steps,
each moving half the remaining region to the XOR partner), then
all-gather by recursive doubling — 2*log2(m) total rounds moving
2*(1-1/m)*N bytes per rank, the same volume as the ring in log(n)
rather than 2(n-1) rounds.  That makes it the mid-size sweet spot:
latency-bound enough that the ring's 2(n-1) serial hops hurt, large
enough that the tree's full-payload store-and-forward per level hurts.

Non-power-of-two worlds fold the ``world - m`` extra ranks in a
pre/post step: extra rank ``r >= m`` ships its vector to fold partner
``r - m`` (merged before the power-of-two phase) and receives the
finished result after it — the classic 3-phase fallback.

Block bounds are the same global, itemsize-aligned partition the ring
uses, so ragged payloads (``len % m != 0``, including ``len < m`` with
zero-length edge blocks) take zero-byte exchanges symmetrically on
both sides of every link.
"""
from __future__ import annotations

import numpy as np

from rabit_tpu.ops import ReduceOp
from rabit_tpu.sched import topo
from rabit_tpu.sched.base import Schedule


class HalvingDoublingSchedule(Schedule):
    name = "halving"

    def applies(self, eng, nbytes: int) -> bool:
        if eng._world < 2:
            return False
        return self._links_ok(
            eng, topo.halving_peers(eng._rank, eng._world))

    def run(self, eng, buf: np.ndarray, op: ReduceOp,
            red_dtype=None) -> None:
        n, r = eng._world, eng._rank
        flat = buf.reshape(-1)
        if flat.nbytes == 0:
            return
        red = red_dtype if red_dtype is not None else flat.dtype
        rflat = flat.view(red)
        view = memoryview(flat).cast("B")
        item = flat.itemsize
        nelems = len(flat)
        m = topo.pow2_floor(n)
        chunk_elems = min(max(eng._reduce_buffer // item, 1), nelems)
        cbytes = chunk_elems * item

        # Fold pre-step: extra ranks ship their whole vector to the
        # partner (chunk-drained there) and park until the post-step.
        if r >= m:
            p = r - m
            eng._send(p, view)
            eng._recv(p, len(view), view)
            return

        def merge_at(e_base: int):
            def merge(coff: int, rl: int, src) -> None:
                ne = rl // item
                eng._wire_merge(op, rflat, e_base + coff // item, ne,
                                np.frombuffer(src, dtype=red, count=ne))
            return merge

        if r + m < n:
            # Recv-only pipelined drain of the extra rank's vector:
            # chunk k merges while chunk k+1 is in flight.
            eng._hop_exchange_merge(r + m, view[:0], r + m, len(view),
                                    cbytes, item, merge_at(0),
                                    what="halving fold")

        per = -(-nelems // m)
        bounds = [min(i * per, nelems) for i in range(m + 1)]
        # Phase 1: reduce-scatter by halving.  At distance d my live
        # region [nb, nb+d) blocks halves; I ship the partner's half
        # and fold its contribution for mine.  After the walk, block r
        # is fully reduced here.  Each halving exchange is one
        # pipelined hop: sub-chunks stream through the engine's depth
        # window so the fold compute hides behind the wire.
        d = m >> 1
        while d:
            p = r ^ d
            nb = r & ~(d - 1)
            pnb = p & ~(d - 1)
            sblk = view[bounds[pnb] * item: bounds[pnb + d] * item]
            r_lo = bounds[nb]
            rbytes = (bounds[nb + d] - r_lo) * item
            eng._hop_exchange_merge(p, sblk, p, rbytes, cbytes, item,
                                    merge_at(r_lo), what="halving hop")
            d >>= 1
        # Phase 2: all-gather by doubling — the reverse walk, receives
        # landing straight in the payload (no scratch, like the ring's
        # gather phase).
        d = 1
        while d < m:
            p = r ^ d
            base = r & ~(d - 1)
            pbase = base ^ d
            eng._exchange(
                p, view[bounds[base] * item: bounds[base + d] * item],
                p, view[bounds[pbase] * item: bounds[pbase + d] * item])
            d <<= 1
        # Fold post-step: hand the finished vector back to the extra.
        if r + m < n:
            eng._send(r + m, view)
