"""rabit_tpu.obs — the telemetry subsystem.

Three pieces (doc/observability.md):

* :mod:`rabit_tpu.obs.metrics` — counters, gauges and log2-bucket
  latency histograms behind a thread-safe :class:`Metrics` registry;
* :mod:`rabit_tpu.obs.trace` — a bounded ring-buffer
  :class:`EventTrace` of structured events (op spans, link errors,
  recovery phases, checkpoint commits) dumpable as JSON lines and
  Chrome-trace format;
* :mod:`rabit_tpu.obs.log` — the rank/role/seqno-prefixed structured
  logger (``rabit_debug``-gated);
* :mod:`rabit_tpu.obs.export` — the **live telemetry plane**: delta
  frame export over the heartbeat channel, the tracker's per-job fold,
  and the Prometheus text exposition for ``GET /metrics``;
* :mod:`rabit_tpu.obs.span` — cross-rank collective spans, per-op skew
  merging and rolling straggler scores (doc/observability.md "Live
  telemetry");
* :mod:`rabit_tpu.obs.adapt` — the **adaptive controller** closing the
  loop: live span folds re-score the schedule choice online, push
  schedule-switch epochs, demote persistent stragglers out of
  hierarchical leadership and warm the TuningCache
  (doc/performance.md "Online adaptation").

Engines expose their instruments through ``Engine.stats()`` /
``Engine.events()``; at shutdown each worker ships its rank-local
summary over the tracker's print channel (:data:`OBS_SUMMARY_PREFIX`)
and the tracker aggregates min/mean/max across ranks into a per-job
report under ``--obs-dir`` (rendered by ``tools/obs_report.py``).

Telemetry is **off by default**: :func:`configure` enables it when
``rabit_obs`` is truthy or ``rabit_obs_dir`` is set, and the engines
gate every call site on that single bool, so the disabled cost is one
attribute check per collective.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass

from rabit_tpu.obs import log
from rabit_tpu.obs.adapt import (AdaptiveController, Decision,
                                 ScheduleScorer, candidate_schedules)
from rabit_tpu.obs.export import (DeltaExporter, LiveTable, prom_name,
                                  prometheus_text, serve_slo,
                                  serve_straggler_scores)
from rabit_tpu.obs.log import _truthy
from rabit_tpu.obs.metrics import (Counter, Gauge, Histogram, Metrics,
                                   aggregate_snapshots, flatten_snapshot)
from rabit_tpu.obs.span import (SpanBuffer, SpanMerger, merge_group,
                                payload_bucket)
from rabit_tpu.obs.trace import (DEFAULT_FLIGHT_EVENTS,
                                 DEFAULT_TRACE_SAMPLE, HOP_FIELDS,
                                 EventTrace, FlightRecorder, HopBuffer,
                                 TraceAssembler, chrome_trace,
                                 load_flight_records, trace_sampled)

# Print-channel extension marker: a tracker print message starting with
# this is a rank-local telemetry summary (JSON), ingested by the tracker
# instead of echoed (tracker/tracker.py).
OBS_SUMMARY_PREFIX = "\x01rabit-obs1\x01"

DEFAULT_TRACE_CAPACITY = 4096
# Streaming export cadence (rabit_obs_flush_sec): how often a worker
# ships one delta frame + its buffered spans over the heartbeat channel
# while telemetry is on.  0 disables streaming (shutdown-only shipping,
# the PR-2 behaviour).
DEFAULT_FLUSH_SEC = 2.0


@dataclass
class ObsConfig:
    """Resolved telemetry settings for one engine instance."""

    enabled: bool = False
    obs_dir: str | None = None
    trace_capacity: int = DEFAULT_TRACE_CAPACITY
    flush_sec: float = DEFAULT_FLUSH_SEC
    # Causal hop tracing (rabit_trace_sample): trace every Nth op; 0 =
    # off — and the engines keep the entire arm/emit path behind one
    # attribute check, so the disabled cost is zero on the hot path.
    trace_sample: int = 0
    # Flight recorder: ring capacity and the persistence directory
    # (records only land on disk when rabit_trace_dir is set).
    flight_events: int = DEFAULT_FLIGHT_EVENTS
    trace_dir: str | None = None


def configure(params: dict | None = None) -> ObsConfig:
    """Resolve telemetry settings from engine params / environment and
    apply the log level (``rabit_debug``).  Called from every engine's
    ``init()``; see doc/parameters.md "Observability"."""
    params = params or {}
    log.configure(params)
    obs_dir = params.get("rabit_obs_dir") or os.environ.get("RABIT_OBS_DIR")
    obs_dir = str(obs_dir) if obs_dir else None
    raw = params.get("rabit_obs")
    if raw is None:
        raw = os.environ.get("RABIT_OBS", "")
    enabled = _truthy(raw) or obs_dir is not None
    cap = params.get("rabit_obs_events")
    if cap is None:
        cap = os.environ.get("RABIT_OBS_EVENTS", DEFAULT_TRACE_CAPACITY)
    try:
        cap = int(cap)
    except (TypeError, ValueError):
        cap = DEFAULT_TRACE_CAPACITY
    flush = params.get("rabit_obs_flush_sec")
    if flush is None:
        flush = os.environ.get("RABIT_OBS_FLUSH_SEC", DEFAULT_FLUSH_SEC)
    try:
        flush = max(float(flush), 0.0)
    except (TypeError, ValueError):
        flush = DEFAULT_FLUSH_SEC
    sample = params.get("rabit_trace_sample")
    if sample is None:
        sample = os.environ.get("RABIT_TRACE_SAMPLE", 0)
    try:
        sample = max(int(sample), 0)
    except (TypeError, ValueError):
        sample = 0
    flight = params.get("rabit_flight_events")
    if flight is None:
        flight = os.environ.get("RABIT_FLIGHT_EVENTS",
                                DEFAULT_FLIGHT_EVENTS)
    try:
        flight = max(int(flight), 8)
    except (TypeError, ValueError):
        flight = DEFAULT_FLIGHT_EVENTS
    trace_dir = (params.get("rabit_trace_dir")
                 or os.environ.get("RABIT_TRACE_DIR"))
    trace_dir = str(trace_dir) if trace_dir else None
    return ObsConfig(enabled=enabled, obs_dir=obs_dir, trace_capacity=cap,
                     flush_sec=flush, trace_sample=sample,
                     flight_events=flight, trace_dir=trace_dir)


def record_op(metrics: Metrics, trace: EventTrace, kind: str, nbytes: int,
              dt: float, rank: int, seqno: int | None = None,
              version: int | None = None, replayed: bool = False) -> None:
    """Record one completed collective — the per-op metric/event scheme
    shared by every instrumented engine (doc/observability.md), so the
    emitted names can never drift between backends."""
    metrics.counter(f"op.{kind}.count").inc()
    metrics.counter(f"op.{kind}.bytes").inc(nbytes)
    metrics.histogram(f"op.{kind}.seconds").observe(dt)
    if replayed:
        metrics.counter(f"op.{kind}.replayed").inc()
    trace.emit("op", kind=kind, nbytes=nbytes, dur=dt, seqno=seqno,
               version=version, rank=rank, replayed=replayed or None)


def ship_summary(print_fn, logger, engine_name: str, rank: int, world: int,
                 metrics_snapshot: dict, recovery_events: list[dict],
                 job: str | None = None) -> None:
    """Ship one rank-local summary over the tracker print channel
    (``print_fn`` is the engine's ``tracker_print``).  Shared by every
    instrumented engine; the tracker merges multiple summaries for the
    same rank section-wise, so a layered engine (XLA over a host inner)
    ships its own instruments without clobbering the inner's.  ``job``
    names the tenant on a multi-tenant tracker so merged reports stay
    attributable (None/"default" = the implicit single job)."""
    payload = {"rank": rank, "world": world, "engine": engine_name,
               "metrics": metrics_snapshot, "recovery": recovery_events}
    if job and job != "default":
        payload["job"] = job
    try:
        print_fn(OBS_SUMMARY_PREFIX + json.dumps(payload))
    except Exception as e:  # noqa: BLE001 — teardown path, best effort
        logger.debug("obs summary ship failed: %s", e)


def note_drops(metrics: Metrics, trace: EventTrace) -> None:
    """Sync the ``obs.events_dropped`` counter to the trace's eviction
    count — called at every streaming flush and at shutdown shipping,
    so silent ring-buffer eviction always surfaces in the shipped
    summaries (and the obs_report warning that renders it)."""
    dropped = trace.dropped
    c = metrics.counter("obs.events_dropped")
    behind = dropped - c.value
    if behind > 0:
        c.inc(behind)


def dump_events(logger, obs_dir: str, rank: int, events: list[dict]) -> None:
    """Write one rank's event trace to ``<obs_dir>/events.rank<N>.jsonl``
    (the format tools/obs_report.py consumes)."""
    try:
        os.makedirs(obs_dir, exist_ok=True)
        path = os.path.join(obs_dir, f"events.rank{rank}.jsonl")
        with open(path, "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")
    except OSError as e:
        logger.warn("obs event dump failed: %s", e)


__all__ = [
    "Counter", "Gauge", "Histogram", "Metrics", "EventTrace",
    "aggregate_snapshots", "flatten_snapshot", "chrome_trace",
    "ObsConfig", "configure", "log", "OBS_SUMMARY_PREFIX",
    "DEFAULT_TRACE_CAPACITY", "DEFAULT_FLUSH_SEC", "record_op",
    "ship_summary", "dump_events", "note_drops",
    "DeltaExporter", "LiveTable", "prom_name", "prometheus_text",
    "serve_slo",
    "serve_straggler_scores",
    "SpanBuffer", "SpanMerger", "merge_group", "payload_bucket",
    "AdaptiveController", "ScheduleScorer", "Decision",
    "candidate_schedules",
    "HOP_FIELDS", "DEFAULT_TRACE_SAMPLE", "DEFAULT_FLIGHT_EVENTS",
    "HopBuffer", "TraceAssembler", "FlightRecorder", "trace_sampled",
    "load_flight_records",
]
