"""Bounded structured event trace, causal hop tracing and the flight
recorder.

Four pieces (doc/observability.md "Causal tracing & postmortem"):

* :class:`EventTrace` — a fixed-capacity ring buffer of structured
  events — op begin/end (one complete event carrying ``ts``+``dur``),
  link errors, recovery phases, checkpoint commits — dumpable as JSON
  lines and as the Chrome trace format (`chrome://tracing` / Perfetto
  "Trace Event Format").  Bounded so a long job's trace memory is
  configuration (`rabit_obs_events`), not runtime; eviction drops the
  oldest events.
* :class:`HopBuffer` (worker side) — compact per-hop/per-chunk records
  from the sampled ops (``rabit_trace_sample``), drained into the
  streaming obs frames like spans;
* :class:`TraceAssembler` (tracker side) — folds every rank's hop
  records into one skew-corrected causal timeline per op (clock offsets
  calibrated from the heartbeat frame timestamps + the hb-RTT echo
  samples), names the binding (rank, link, hop) per collective, folds
  per-link cost tables and exports Chrome-trace/Perfetto JSON;
* :class:`FlightRecorder` — the always-on bounded crash ring: recent
  wire/engine events plus the op in flight, persisted atomically on
  every fault path (LinkError escalation, recovery budget exhaustion,
  SIGTERM, serve drain) for ``tools/postmortem.py`` to reconstruct a
  dead job's last seconds.

Timestamps are ``time.time()`` epoch seconds so traces from different
ranks merge on one timeline; durations are measured by the caller with
``perf_counter`` and events with a duration are stamped at their START
(``ts = now - dur``), which is what the Chrome ``"X"`` phase expects.
"""
from __future__ import annotations

import collections
import json
import os
import statistics
import threading
import time


class EventTrace:
    """Thread-safe ring buffer of event dicts."""

    def __init__(self, capacity: int = 4096) -> None:
        self._buf: collections.deque = collections.deque(maxlen=max(capacity, 1))
        self._lock = threading.Lock()
        # Eviction is silent by deque design; this counter is the
        # signal (shipped as ``obs.events_dropped``, rendered by
        # obs_report) that a trace window was too small for the job.
        self.dropped = 0

    @property
    def capacity(self) -> int:
        return self._buf.maxlen

    def emit(self, name: str, *, ts: float | None = None,
             dur: float | None = None, **fields) -> None:
        """Append one event.  ``name`` is the event family ("op",
        "recovery", "checkpoint", ...); ``fields`` carry the structured
        payload (kind/bytes/seqno/version/phase/...).  None-valued
        fields are dropped."""
        if ts is None:
            ts = time.time() - (dur or 0.0)
        ev = {"ts": ts, "name": name}
        if dur is not None:
            ev["dur"] = dur
        for k, v in fields.items():
            if v is not None:
                ev[k] = v
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(ev)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def __len__(self) -> int:
        return len(self._buf)

    def to_jsonl(self) -> str:
        """One JSON object per line (the on-disk ``events.rank*.jsonl``
        format the report tool consumes)."""
        return "".join(json.dumps(e) + "\n" for e in self.events())


def chrome_trace(events: list[dict], default_pid: int = 0) -> list[dict]:
    """Convert event dicts to Chrome "Trace Event Format" entries.

    Events with a duration become complete ("X") slices; the rest become
    instants ("i").  ``rank`` maps to the Chrome pid lane so a merged
    multi-rank dump renders one row per rank; times are microseconds
    relative to the earliest event.
    """
    if not events:
        return []
    t0 = min(e["ts"] for e in events)
    out = []
    for e in events:
        entry = {
            "name": str(e.get("phase") or e.get("kind") or e.get("name")),
            "cat": str(e.get("name", "event")),
            "pid": int(e.get("rank", default_pid)),
            "tid": 0,
            "ts": (e["ts"] - t0) * 1e6,
            "args": {k: v for k, v in e.items()
                     if k not in ("ts", "dur", "name")},
        }
        if e.get("dur") is not None:
            entry["ph"] = "X"
            entry["dur"] = e["dur"] * 1e6
        else:
            entry["ph"] = "i"
            entry["s"] = "p"  # process-scoped instant
        out.append(entry)
    return out


# ----------------------------------------------------------------------
# causal hop tracing (doc/observability.md "Causal tracing & postmortem")
# ----------------------------------------------------------------------

# One hop/chunk/codec-window record, shipped as a positional list like
# spans (span.py SPAN_FIELDS) so a frame full of them stays small.
# ``phase`` is "hop" (one _hop_exchange_merge call, or one tree phase
# on the tree schedule small worlds default to), "chunk" (one
# pipelined merge window inside a hop), "encode"/"decode" (the codec
# windows); ``hop`` is the op-local hop index and ``peer`` the send-side
# neighbour (the egress link the hop loaded; -1 for the codec windows,
# which touch no wire).  (t0, t1) are the emitting RANK's epoch-seconds
# clock — the assembler corrects them onto the tracker's timeline.
HOP_FIELDS = ("seq", "epoch", "version", "kind", "hop", "peer", "phase",
              "nbytes", "t0", "t1")

# "Default sampling" when tracing is armed without an explicit rate:
# trace every 64th op.  Coarse enough that the bench gate's <3%
# obs-overhead budget holds, fine enough that a minute of training
# yields dozens of assembled timelines.
DEFAULT_TRACE_SAMPLE = 64
# Flight-recorder ring capacity (rabit_flight_events).
DEFAULT_FLIGHT_EVENTS = 512


def trace_sampled(seq: int, sample: int) -> bool:
    """The per-op trace decision: deterministic in the op seqno, so all
    ranks trace the SAME ops and the tracker can assemble complete
    cross-rank timelines.  ``sample`` <= 0 never samples (tracing off —
    the engines additionally keep the entire arm/emit path behind one
    attribute check)."""
    return sample > 0 and seq % sample == 0


class HopBuffer:
    """Worker-side bounded buffer of hop records awaiting the next
    streaming flush (the hop analogue of span.SpanBuffer): ``add`` from
    the collective hot path, ``drain`` from the heartbeat thread.  Full
    buffer drops (and counts) rather than blocking or growing."""

    CAPACITY = 4096

    def __init__(self, capacity: int = CAPACITY) -> None:
        self._buf: list[list] = []
        self._cap = max(int(capacity), 1)
        self._lock = threading.Lock()
        self.dropped = 0

    def add(self, seq: int, epoch: int, version: int, kind: str, hop: int,
            peer: int, phase: str, nbytes: int, t0: float, t1: float) -> None:
        rec = [seq, epoch, version, kind, hop, peer, phase, nbytes,
               round(t0, 6), round(t1, 6)]
        with self._lock:
            if len(self._buf) >= self._cap:
                self.dropped += 1
                return
            self._buf.append(rec)

    def drain(self) -> list[list]:
        with self._lock:
            out, self._buf = self._buf, []
            return out

    def __len__(self) -> int:
        return len(self._buf)


def _hop_dict(rec) -> dict | None:
    """One wire hop record (positional list) → field dict; None for
    records that don't parse (garbage tolerated like span rows)."""
    try:
        d = dict(zip(HOP_FIELDS, rec))
        return {"seq": int(d["seq"]), "epoch": int(d["epoch"]),
                "version": int(d["version"]), "kind": str(d["kind"]),
                "hop": int(d["hop"]), "peer": int(d["peer"]),
                "phase": str(d["phase"]), "nbytes": int(d["nbytes"]),
                "t0": float(d["t0"]), "t1": float(d["t1"])}
    except (TypeError, ValueError, KeyError):
        return None


class TraceAssembler:
    """Tracker-side causal timeline assembly over streamed hop records.

    Records group by the span key (epoch, version, seq, kind); because
    sampling is deterministic in the seqno every rank contributes to the
    same groups, and a group holding hops from every live rank is one
    complete cross-rank causal timeline for that collective.  A bounded
    window of assembled ops is retained for exposition (``/trace``,
    ``/status``); per-link costs fold over everything ever assembled.

    Clock-skew calibration: each streamed frame carries the sender's
    ``time.time()`` and its hb-RTT estimate; ``note_offset`` folds
    ``recv_time - frame_ts - rtt/2`` samples into a rolling median
    offset per rank, and every exposed timestamp is corrected by it —
    cross-rank orderings survive multi-second clock skew."""

    MAX_OPS = 64
    OFFSET_WINDOW = 32

    def __init__(self, max_ops: int = MAX_OPS) -> None:
        self._lock = threading.Lock()
        self._ops: collections.OrderedDict = collections.OrderedDict()
        self._offsets: dict[int, collections.deque] = {}
        self._links: dict[str, dict] = {}
        self.assembled = 0   # op groups ever finalized into the window
        self.records = 0     # hop records ever ingested
        self._max_ops = max(int(max_ops), 1)

    # -- clock calibration -------------------------------------------
    def note_offset(self, rank: int, sample: float) -> None:
        """One ``tracker_clock - rank_clock`` estimate (from a frame's
        send timestamp and half its heartbeat RTT)."""
        with self._lock:
            dq = self._offsets.get(rank)
            if dq is None:
                dq = self._offsets[rank] = collections.deque(
                    maxlen=self.OFFSET_WINDOW)
            dq.append(float(sample))

    def offset(self, rank: int) -> float:
        """Current offset estimate for ``rank`` (median of the rolling
        window; 0 with no samples — uncorrected)."""
        with self._lock:
            dq = self._offsets.get(rank)
            return statistics.median(dq) if dq else 0.0

    # -- ingest --------------------------------------------------------
    def add(self, rank: int, hops: list, world: int = 0) -> None:
        """Fold one rank's drained hop records in.  ``world`` is advisory
        (groups are exposed as soon as they exist; completeness is a
        property of sampling determinism, not a gate — a dead rank must
        not hide the timeline that explains its death)."""
        if not isinstance(hops, list):
            return
        with self._lock:
            for rec in hops:
                d = _hop_dict(rec)
                if d is None:
                    continue
                d["rank"] = int(rank)
                self.records += 1
                key = (d["epoch"], d["version"], d["seq"], d["kind"])
                grp = self._ops.get(key)
                if grp is None:
                    grp = self._ops[key] = {"records": [], "ranks": set()}
                    self.assembled += 1
                    while len(self._ops) > self._max_ops:
                        self._ops.popitem(last=False)
                grp["records"].append(d)
                grp["ranks"].add(int(rank))
                if d["phase"] == "hop" and d["peer"] >= 0:
                    link = f"{d['rank']}->{d['peer']}"
                    row = self._links.get(link)
                    if row is None:
                        row = self._links[link] = {
                            "n": 0, "sec": 0.0, "bytes": 0}
                    row["n"] += 1
                    row["sec"] += max(d["t1"] - d["t0"], 0.0)
                    row["bytes"] += d["nbytes"]

    # -- analysis ------------------------------------------------------
    def ops(self) -> list[tuple]:
        with self._lock:
            return list(self._ops.keys())

    def timeline(self, key: tuple | None = None) -> list[dict]:
        """The skew-corrected records of one op (default: the newest),
        sorted by corrected start time."""
        with self._lock:
            if not self._ops:
                return []
            if key is None:
                key = next(reversed(self._ops))
            grp = self._ops.get(tuple(key))
            if grp is None:
                return []
            out = []
            for d in grp["records"]:
                dq = self._offsets.get(d["rank"])
                off = statistics.median(dq) if dq else 0.0
                c = dict(d)
                c["t0"] = round(d["t0"] + off, 6)
                c["t1"] = round(d["t1"] + off, 6)
                out.append(c)
        out.sort(key=lambda d: (d["t0"], d["rank"], d["hop"]))
        return out

    @staticmethod
    def _binding(records: list[dict]) -> dict | None:
        """The critical-path verdict for one assembled op: the single
        longest wire hop is what the collective's completion waited on
        — it names the binding (rank, link, hop)."""
        hops = [d for d in records if d["phase"] == "hop"] or records
        if not hops:
            return None
        worst = max(hops, key=lambda d: d["t1"] - d["t0"])
        return {"rank": worst["rank"], "peer": worst["peer"],
                "hop": worst["hop"],
                "link": f"{worst['rank']}->{worst['peer']}",
                "sec": round(max(worst["t1"] - worst["t0"], 0.0), 6),
                "nbytes": worst["nbytes"], "kind": worst["kind"],
                "seq": worst["seq"], "epoch": worst["epoch"],
                "version": worst["version"]}

    def critical_path(self, key: tuple | None = None) -> dict | None:
        return self._binding(self.timeline(key))

    def link_costs(self) -> dict:
        """Per-link cost fold over every hop ever ingested: the
        evidence table the adaptive controller / TuningCache side can
        consume (``tools/trace_report.py`` renders and exports it)."""
        with self._lock:
            return {link: {"n": row["n"],
                           "mean_sec": round(row["sec"] / row["n"], 6)
                           if row["n"] else 0.0,
                           "bytes": row["bytes"]}
                    for link, row in sorted(self._links.items())}

    def bound_by(self) -> str | None:
        """Modal binding link across the retained window — the one-line
        per-job verdict ``rabit_top`` renders."""
        votes: collections.Counter = collections.Counter()
        for key in self.ops():
            b = self.critical_path(key)
            if b is not None:
                votes[b["link"]] += 1
        if not votes:
            return None
        link, n = votes.most_common(1)[0]
        return f"link {link} ({n}/{sum(votes.values())} ops)"

    # -- exposition ------------------------------------------------------
    def chrome(self, key: tuple | None = None) -> dict:
        """Perfetto-loadable Chrome-trace JSON object for one op's
        timeline (default: the newest), one pid lane per rank."""
        recs = self.timeline(key)
        events: list[dict] = []
        for r in sorted({d["rank"] for d in recs}):
            events.append({"ph": "M", "pid": r, "tid": 0,
                           "name": "process_name",
                           "args": {"name": f"rank {r}"}})
        t0 = min((d["t0"] for d in recs), default=0.0)
        for d in recs:
            name = (f"{d['kind']} hop{d['hop']}" if d["phase"] == "hop"
                    else d["phase"])
            events.append({
                "name": name, "cat": d["phase"], "ph": "X",
                "pid": d["rank"], "tid": 0,
                "ts": round((d["t0"] - t0) * 1e6, 3),
                "dur": round(max(d["t1"] - d["t0"], 0.0) * 1e6, 3),
                "args": {k: d[k] for k in ("seq", "epoch", "version",
                                           "peer", "nbytes")}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def report(self) -> dict:
        """Compact JSON-safe summary for the ``/status`` per-job
        ``trace`` section (and hence for shard-level folding: the whole
        section rides the job row, and jobs are disjoint across
        shards)."""
        keys = self.ops()
        last = self.timeline(keys[-1]) if keys else []
        rep = {"ops_assembled": self.assembled,
               "records": self.records,
               "ops_held": len(keys),
               "links": self.link_costs()}
        bb = self.bound_by()
        if bb is not None:
            rep["bound_by"] = bb
        if last:
            rep["last_op"] = {"key": list(keys[-1]),
                              "critical": self._binding(last),
                              "records": last[-64:]}
        return rep


# ----------------------------------------------------------------------
# flight recorder (crash forensics)
# ----------------------------------------------------------------------

class FlightRecorder:
    """Always-on bounded crash ring for one rank.

    A small :class:`EventTrace` of recent wire/engine events (op
    markers, link errors, recovery phases) plus the op currently in
    flight, persisted ATOMICALLY (tmp + rename) on every fault path —
    LinkError escalation, recovery budget exhaustion, the SIGTERM
    handler, serve drain — so a dead job leaves
    ``<trace_dir>/flight.rank<N>.json`` files that
    ``tools/postmortem.py`` can reconstruct the last seconds from.
    Recording is independent of ``rabit_obs`` (the ring is a few dict
    appends per collective); persistence needs ``rabit_trace_dir``."""

    def __init__(self, capacity: int = DEFAULT_FLIGHT_EVENTS) -> None:
        self.ring = EventTrace(capacity=max(int(capacity), 8))
        self.inflight: dict | None = None
        self.persists = 0

    def op_begin(self, kind: str, seq: int, epoch: int, version: int,
                 nbytes: int) -> None:
        """Mark one collective entering the wire (cleared by
        :meth:`op_end` ONLY on success, so a fault-path persist always
        names the op that was in flight)."""
        self.inflight = {"kind": kind, "seq": seq, "epoch": epoch,
                         "version": version, "nbytes": nbytes}
        self.ring.emit("op_begin", kind=kind, seq=seq, epoch=epoch,
                       version=version, nbytes=nbytes)

    def op_end(self) -> None:
        self.inflight = None

    def note(self, name: str, **fields) -> None:
        self.ring.emit(name, **fields)

    def persist(self, trace_dir: str, rank: int, reason: str,
                **meta) -> str | None:
        """Atomically write this rank's flight record (last writer wins
        — the record closest to death is the interesting one).  Best
        effort: a fault path must never die in its own forensics."""
        doc = {"rank": int(rank), "reason": str(reason),
               "ts": round(time.time(), 6), "pid": os.getpid(),
               "inflight": self.inflight,
               "events": self.ring.events()}
        for k, v in meta.items():
            if v is not None:
                doc[k] = v
        path = os.path.join(trace_dir, f"flight.rank{int(rank)}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(trace_dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        self.persists += 1
        return path


def load_flight_records(trace_dir: str) -> list[dict]:
    """Read every ``flight.rank*.json`` under ``trace_dir`` (malformed
    or half-written files skipped — postmortems run over whatever the
    crash left behind)."""
    out = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("flight.rank") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(trace_dir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict):
            out.append(doc)
    return out
