"""Bounded structured event trace.

A fixed-capacity ring buffer of structured events — op begin/end (one
complete event carrying ``ts``+``dur``), link errors, recovery phases,
checkpoint commits — dumpable as JSON lines and as the Chrome trace
format (`chrome://tracing` / Perfetto "Trace Event Format").  Bounded so
a long job's trace memory is configuration (`rabit_obs_events`), not
runtime; eviction drops the oldest events.

Timestamps are ``time.time()`` epoch seconds so traces from different
ranks merge on one timeline; durations are measured by the caller with
``perf_counter`` and events with a duration are stamped at their START
(``ts = now - dur``), which is what the Chrome ``"X"`` phase expects.
"""
from __future__ import annotations

import collections
import json
import threading
import time


class EventTrace:
    """Thread-safe ring buffer of event dicts."""

    def __init__(self, capacity: int = 4096) -> None:
        self._buf: collections.deque = collections.deque(maxlen=max(capacity, 1))
        self._lock = threading.Lock()
        # Eviction is silent by deque design; this counter is the
        # signal (shipped as ``obs.events_dropped``, rendered by
        # obs_report) that a trace window was too small for the job.
        self.dropped = 0

    @property
    def capacity(self) -> int:
        return self._buf.maxlen

    def emit(self, name: str, *, ts: float | None = None,
             dur: float | None = None, **fields) -> None:
        """Append one event.  ``name`` is the event family ("op",
        "recovery", "checkpoint", ...); ``fields`` carry the structured
        payload (kind/bytes/seqno/version/phase/...).  None-valued
        fields are dropped."""
        if ts is None:
            ts = time.time() - (dur or 0.0)
        ev = {"ts": ts, "name": name}
        if dur is not None:
            ev["dur"] = dur
        for k, v in fields.items():
            if v is not None:
                ev[k] = v
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(ev)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def __len__(self) -> int:
        return len(self._buf)

    def to_jsonl(self) -> str:
        """One JSON object per line (the on-disk ``events.rank*.jsonl``
        format the report tool consumes)."""
        return "".join(json.dumps(e) + "\n" for e in self.events())


def chrome_trace(events: list[dict], default_pid: int = 0) -> list[dict]:
    """Convert event dicts to Chrome "Trace Event Format" entries.

    Events with a duration become complete ("X") slices; the rest become
    instants ("i").  ``rank`` maps to the Chrome pid lane so a merged
    multi-rank dump renders one row per rank; times are microseconds
    relative to the earliest event.
    """
    if not events:
        return []
    t0 = min(e["ts"] for e in events)
    out = []
    for e in events:
        entry = {
            "name": str(e.get("phase") or e.get("kind") or e.get("name")),
            "cat": str(e.get("name", "event")),
            "pid": int(e.get("rank", default_pid)),
            "tid": 0,
            "ts": (e["ts"] - t0) * 1e6,
            "args": {k: v for k, v in e.items()
                     if k not in ("ts", "dur", "name")},
        }
        if e.get("dur") is not None:
            entry["ph"] = "X"
            entry["dur"] = e["dur"] * 1e6
        else:
            entry["ph"] = "i"
            entry["s"] = "p"  # process-scoped instant
        out.append(entry)
    return out
