"""Streaming metric export and Prometheus text exposition.

The live half of the telemetry plane (doc/observability.md "Live
telemetry"): PR 2's instruments ship once, at shutdown — useless for a
long-lived multi-tenant tracker.  Here:

* :class:`DeltaExporter` (worker side) turns successive
  ``Metrics.snapshot()`` calls into **delta** frames — counters ship as
  increments since the last flush (a lost frame under-counts briefly
  and the authoritative shutdown summary still closes the books),
  gauges and histogram summary stats ship as current values;
* :class:`LiveTable` (tracker side) folds those frames back into a
  per-rank cumulative view plus a bounded rolling window of samples —
  journal-free by design, this is operational visibility, not durable
  state;
* :func:`prometheus_text` renders labeled samples in the Prometheus
  text exposition format (version 0.0.4) for the tracker's
  ``GET /metrics`` endpoint.
"""
from __future__ import annotations

import collections
import re
import threading

# Histogram summary stats shipped live as gauges (the full bucket map
# stays in the shutdown summary; frames must stay small).
_HIST_LIVE_KEYS = ("count", "mean", "p50", "p99", "max")

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_]")
_LABEL_ESC = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}


class DeltaExporter:
    """Worker-side frame builder over one :class:`Metrics` registry."""

    def __init__(self, metrics) -> None:
        self._metrics = metrics
        self._last: dict[str, float] = {}

    def frame(self) -> dict:
        """One delta frame: ``{"counters": {name: delta},
        "gauges": {name: value}}`` (histogram summaries ride the gauge
        section).  Zero deltas are omitted so an idle worker's frame is
        near-empty."""
        snap = self._metrics.snapshot()
        counters: dict[str, float] = {}
        for name, v in snap.get("counters", {}).items():
            delta = v - self._last.get(name, 0)
            if delta:
                counters[name] = delta
            self._last[name] = v
        gauges = dict(snap.get("gauges", {}))
        for name, h in snap.get("histograms", {}).items():
            for k in _HIST_LIVE_KEYS:
                gauges[f"{name}.{k}"] = h.get(k, 0.0)
        return {"counters": counters, "gauges": gauges}


class LiveTable:
    """Tracker-side fold of one job's streamed frames.

    Per rank: cumulative counters (deltas summed), last-wins gauges,
    frame bookkeeping, and a bounded deque of ``(ts, ops, bytes)``
    samples (total collective op count/bytes at that instant) — the
    rolling time-series ``rabit_top`` turns into rates."""

    def __init__(self, window: int = 120) -> None:
        self._lock = threading.Lock()
        self._ranks: dict[int, dict] = {}
        self._window = max(int(window), 2)

    def ingest(self, rank: int, ts: float, frame: dict) -> None:
        counters = frame.get("counters") or {}
        gauges = frame.get("gauges") or {}
        with self._lock:
            row = self._ranks.get(rank)
            if row is None:
                row = self._ranks[rank] = {
                    "counters": {}, "gauges": {}, "frames": 0,
                    "ts": 0.0, "engine": None, "codec_impl": None,
                    "series": collections.deque(maxlen=self._window),
                }
            for name, delta in counters.items():
                try:
                    row["counters"][name] = (
                        row["counters"].get(name, 0) + delta)
                except TypeError:
                    continue  # non-numeric garbage from the wire
            for name, v in gauges.items():
                if isinstance(v, (int, float)):
                    row["gauges"][name] = v
            row["frames"] += 1
            row["ts"] = ts
            if frame.get("engine"):
                row["engine"] = frame["engine"]
            # Active codec backend (native / numpy / numpy-fallback):
            # per-rank, because the impl is a per-rank perf knob — one
            # rank silently degraded to numpy is exactly the situation
            # /status and rabit_top must make visible at a glance.
            impl = frame.get("codec_impl")
            if isinstance(impl, str) and impl:
                row["codec_impl"] = impl
            ops = sum(v for n, v in row["counters"].items()
                      if n.startswith("op.") and n.endswith(".count"))
            nbytes = sum(v for n, v in row["counters"].items()
                         if n.startswith("op.") and n.endswith(".bytes"))
            row["series"].append((round(ts, 3), ops, nbytes))

    def rows(self) -> list[tuple[int, dict]]:
        """Snapshot of ``(rank, row)`` pairs (counters/gauges copied —
        the scrape thread must not race the ingest fold)."""
        with self._lock:
            return [(r, {"counters": dict(row["counters"]),
                         "gauges": dict(row["gauges"]),
                         "frames": row["frames"], "ts": row["ts"],
                         "engine": row["engine"],
                         "codec_impl": row["codec_impl"]})
                    for r, row in sorted(self._ranks.items())]

    def report(self) -> dict:
        """Compact per-rank summary for ``/status`` and the obs report:
        frames seen, last flush timestamp, headline op totals and the
        rolling sample window.  Ranks running the serving plane
        additionally get a ``serve`` section (per-status request
        totals, queue depth, model version, latency p50/p99) — the row
        ``rabit_top`` renders and the soak gate reads."""
        out = {}
        with self._lock:
            for r, row in sorted(self._ranks.items()):
                series = list(row["series"])
                ops, nbytes = (series[-1][1], series[-1][2]) \
                    if series else (0, 0)
                out[str(r)] = {"frames": row["frames"],
                               "last_ts": round(row["ts"], 3),
                               "engine": row["engine"],
                               "ops": ops, "bytes": nbytes,
                               "window": series}
                if row["codec_impl"] is not None:
                    out[str(r)]["codec_impl"] = row["codec_impl"]
                    ck = row["gauges"].get("codec.kernel.seconds.mean")
                    if isinstance(ck, (int, float)):
                        out[str(r)]["codec_kernel_ms"] = round(
                            ck * 1e3, 4)
                serve = self._serve_section(row)
                if serve is not None:
                    out[str(r)]["serve"] = serve
        return out

    @staticmethod
    def _serve_section(row: dict) -> dict | None:
        """Fold one rank's ``serve.*`` instruments into the compact
        serving view (None for ranks that never filed any)."""
        counters, gauges = row["counters"], row["gauges"]
        requests = {n[len("serve.requests."):]: v
                    for n, v in counters.items()
                    if n.startswith("serve.requests.")}
        if not requests and "serve.queue_depth" not in gauges:
            return None
        section = {
            "requests": requests,
            "batches": counters.get("serve.batches", 0),
            "queue_depth": gauges.get("serve.queue_depth", 0),
            "model_version": gauges.get("serve.model_version", 0),
            "latency_p50_sec": gauges.get("serve.latency.seconds.p50",
                                          0.0),
            "latency_p99_sec": gauges.get("serve.latency.seconds.p99",
                                          0.0),
        }
        # Per-QoS-class sub-books ("serve.qos.<class>.<status>"): the
        # hierarchical fold the per-class accounting identity is
        # checked through (doc/serving.md "QoS classes").
        qos: dict = {}
        prefix = "serve.qos."
        for name, v in counters.items():
            if not name.startswith(prefix):
                continue
            cls, _, status = name[len(prefix):].partition(".")
            if not cls or not status:
                continue
            qos.setdefault(cls, {})[status] = v
        if qos:
            section["qos"] = qos
        return section


# Default serving SLO: 99% of requests answered (non-shed, non-timeout,
# non-error).  RABIT_SERVE_SLO_TARGET overrides it tracker-side.
DEFAULT_SLO_TARGET = 0.99
# Request outcomes that don't burn error budget: answered requests and
# the deliberate drain refusals of a shutting-down replica.
_SLO_GOOD = ("ok", "draining")


def serve_slo(rows: list, target: float = DEFAULT_SLO_TARGET) -> dict | None:
    """Fold one job's live rows into SLO burn math (doc/observability.md
    "Serving SLO"): ``bad`` is every shed/timeout/error outcome,
    ``burn_rate`` is the observed bad fraction over the allowed bad
    fraction (1.0 = burning exactly the budget, >1 = on course to miss
    the SLO), ``budget_remaining`` the unburnt fraction (clamped at 0).
    None for jobs that serve nothing — no serve series, no SLO rows.
    Sums of per-rank counters, so the fold is associative and the
    sharded exposition merge (``merge_prometheus_pages``) stays exact.
    ``rows`` is whatever holds the per-rank row dicts: ``LiveTable
    .rows()`` pairs, a ``{rank: row}`` mapping, or bare row dicts —
    the rank is irrelevant to the fold."""
    target = min(max(float(target), 0.0), 0.999999)
    good = bad = 0
    if hasattr(rows, "values"):
        rows = list(rows.values())
    for row in rows:
        if isinstance(row, tuple):  # LiveTable.rows() (rank, row) pairs
            row = row[1]
        for name, v in (row.get("counters") or {}).items():
            if not name.startswith("serve.requests."):
                continue
            status = name[len("serve.requests."):]
            try:
                v = float(v)
            except (TypeError, ValueError):
                continue
            if status in _SLO_GOOD:
                good += v
            else:
                bad += v
    total = good + bad
    if not total:
        return None
    burn = (bad / total) / (1.0 - target)
    return {"target": target, "requests": total, "bad": bad,
            "burn_rate": round(burn, 6),
            "budget_remaining": round(max(1.0 - burn, 0.0), 6)}


def serve_straggler_scores(rows: list) -> dict[int, float]:
    """Serving-plane straggler scores: each rank's batch-service EWMA
    (the ``serve.svc_ewma_ms`` gauge the server files) over the fleet
    median.  Same score semantics as the training-plane span fold in
    :mod:`rabit_tpu.obs.adapt` — 1.0 is fleet-typical, ``factor``x is
    conviction territory — so the tracker can max-merge the two into
    one ``rabit_straggler_score`` series and the serving router's
    hysteresis reads them interchangeably.  Empty when fewer than two
    ranks file the gauge (a singleton is its own median: no verdict)."""
    ewma: dict[int, float] = {}
    if hasattr(rows, "values"):
        rows = list(rows.values())
    for entry in rows:
        if isinstance(entry, tuple):
            rank, row = entry
        else:
            rank, row = entry.get("rank", len(ewma)), entry
        v = (row.get("gauges") or {}).get("serve.svc_ewma_ms")
        try:
            v = float(v)
        except (TypeError, ValueError):
            continue
        if v > 0.0:
            ewma[int(rank)] = v
    if len(ewma) < 2:
        return {}
    med = sorted(ewma.values())[len(ewma) // 2]
    if med <= 0.0:
        return {}
    return {r: round(v / med, 4) for r, v in ewma.items()}


def merge_status_docs(docs: list) -> dict:
    """Hierarchical ``/status`` fold across tracker shards
    (doc/fault_tolerance.md "Sharded tracker").

    Jobs are DISJOINT across shards — a job lives on exactly its ring
    owner — so the global fold is the union of the per-shard job
    tables: bit-for-bit the table one flat tracker holding every job
    would render.  Service counters sum, ``jobs_active`` unions
    (sorted, like the flat render), ``ts`` is the newest shard's.  A
    shard doc carrying a ``shard`` index stamps it onto each of its
    jobs, so the merged view stays shard-attributable.  Non-dict
    entries (a failed scrape) are skipped — the fold degrades to the
    shards that answered."""
    out: dict = {"ts": 0.0, "elastic": False,
                 "service": {"jobs_active": [], "counters": {}},
                 "jobs": {}}
    counters = out["service"]["counters"]
    for doc in docs:
        if not isinstance(doc, dict):
            continue
        try:
            out["ts"] = max(out["ts"], float(doc.get("ts") or 0.0))
        except (TypeError, ValueError):
            pass
        out["elastic"] = out["elastic"] or bool(doc.get("elastic"))
        svc = doc.get("service") or {}
        out["service"]["jobs_active"].extend(svc.get("jobs_active") or [])
        for name, v in (svc.get("counters") or {}).items():
            try:
                counters[name] = counters.get(name, 0) + v
            except TypeError:
                continue
        shard = doc.get("shard")
        for name, row in (doc.get("jobs") or {}).items():
            if not isinstance(row, dict):
                continue
            row = dict(row)
            if shard is not None:
                row.setdefault("shard", shard)
            out["jobs"][name] = row
    out["service"]["jobs_active"] = sorted(set(
        out["service"]["jobs_active"]))
    return out


def merge_prometheus_pages(pages: list[str]) -> str:
    """Merge per-shard Prometheus exposition pages into one page: one
    ``# TYPE`` header per series name (first shard's verdict wins),
    series sorted by name like :func:`prometheus_text`, samples kept in
    shard-major order within each series.  Per-job series are disjoint
    across shards (labels carry the job), so for them this is
    bit-for-bit the flat exposition; samples whose (name, labels) pair
    COLLIDES across shards — the service-level fleet counters — are
    summed into one sample, which is exactly the fleet-wide value."""
    types: dict[str, str] = {}
    rows: dict[str, dict] = {}   # name -> {labelstr: value}
    for page in pages:
        for line in (page or "").splitlines():
            line = line.rstrip()
            if not line:
                continue
            if line.startswith("# TYPE "):
                parts = line.split()
                if len(parts) >= 4:
                    types.setdefault(parts[2], parts[3])
                continue
            if line.startswith("#"):
                continue
            series, _, sval = line.rpartition(" ")
            if not series:
                continue
            brace = series.find("{")
            name = series if brace < 0 else series[:brace]
            try:
                value = float(sval)
            except ValueError:
                continue
            per = rows.setdefault(name, {})
            per[series] = per.get(series, 0.0) + value \
                if series in per else value
    lines = []
    for name in sorted(rows):
        lines.append(f"# TYPE {name} {types.get(name, 'gauge')}")
        for series, value in rows[name].items():
            if value == int(value) and abs(value) < 1e15:
                sval = str(int(value))
            else:
                sval = repr(value)
            lines.append(f"{series} {sval}")
    return "\n".join(lines) + "\n"


def prom_name(name: str) -> str:
    """Metric name → Prometheus-safe series name (``op.allreduce.count``
    → ``rabit_op_allreduce_count``)."""
    safe = _NAME_BAD.sub("_", name)
    if not safe or not (safe[0].isalpha() or safe[0] == "_"):
        safe = "_" + safe
    return safe if safe.startswith("rabit_") else "rabit_" + safe


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k])
        for bad, esc in _LABEL_ESC.items():
            v = v.replace(bad, esc)
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def prometheus_text(samples: list[tuple[str, dict, float]],
                    types: dict[str, str] | None = None) -> str:
    """Render ``(name, labels, value)`` samples as Prometheus text
    (one ``# TYPE`` header per series name, samples grouped under it).
    ``types`` maps series names to ``counter``/``gauge`` (default
    gauge).  Non-finite values are skipped — the format has no NaN
    story worth exporting."""
    types = types or {}
    by_name: dict[str, list] = {}
    for name, labels, value in samples:
        try:
            value = float(value)
        except (TypeError, ValueError):
            continue
        if value != value or value in (float("inf"), float("-inf")):
            continue
        by_name.setdefault(name, []).append((labels, value))
    lines = []
    for name in sorted(by_name):
        lines.append(f"# TYPE {name} {types.get(name, 'gauge')}")
        for labels, value in by_name[name]:
            if value == int(value) and abs(value) < 1e15:
                sval = str(int(value))
            else:
                sval = repr(value)
            lines.append(f"{name}{_label_str(labels)} {sval}")
    return "\n".join(lines) + "\n"
