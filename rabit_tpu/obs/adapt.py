"""The telemetry-driven adaptive controller — closing the loop.

PR 7's auto-tuner is offline (bench → cache → one init-time pick); the
live telemetry plane (obs/span.py) measures exactly what it cannot
see: per-op span latency PER SCHEDULE at the actual payload mix, and
per-rank straggler scores.  This module feeds the live fold back
(doc/performance.md "Online adaptation"):

* :class:`ScheduleScorer` — the pure decision core.  Given the
  rolling per-(schedule, payload-bucket) cost estimates the
  :class:`~rabit_tpu.obs.span.SpanMerger` folds from merged spans, it
  decides per bucket: **probe** a candidate that has no fresh
  measurement yet, **switch** when a measured challenger beats the
  incumbent by the hysteresis margin with enough samples, or **hold**.
  Pure and deterministic given the fold — the ``adapt`` unit tests
  drive it directly on synthetic folds.
* :class:`AdaptiveController` — one per job on the tracker.  Ticks on
  the tracker's adapt sweep, walks the scorer through an exploration
  pass over the applicable schedules for the job's dominant payload
  bucket (each probe/switch is pushed to the workers as a
  **schedule-switch epoch** — the rescale choreography at an unchanged
  world, so the whole world switches together at a commit boundary),
  and turns persistent straggler verdicts into **leader demotions**
  for the hierarchical schedule (sched/topo.py leader election
  excludes demoted ranks).  Every decision is recorded with its
  evidence (incumbent vs challenger cost, sample counts) for the
  ``/status`` decisions section, the ``controller.*`` counters and the
  job timeline.

Knobs (doc/parameters.md): ``RABIT_ADAPT_MIN_SAMPLES`` (default 12)
gates every decision on a minimum merged-span count per (schedule,
bucket); ``RABIT_ADAPT_MARGIN`` (default 0.15) is the relative cost a
challenger must beat the incumbent by — the hysteresis that keeps a
noisy fold from flapping the schedule; ``RABIT_DEMOTE_CHECKS``
(default 3) is how many consecutive over-threshold ticks demote a
straggler (the threshold itself REUSES ``RABIT_STRAGGLER_FACTOR``, and
reinstatement uses the same factor/2 hysteresis as the straggler
timeline).

The module is tracker-side only (no engine imports); it consults
:mod:`rabit_tpu.sched.topo` for schedule applicability so the
candidate set matches what the engines' ``applies()`` checks accept.
"""
from __future__ import annotations

import collections
import os
import time
from dataclasses import dataclass, field

from rabit_tpu.sched import topo as sched_topo

DEFAULT_MIN_SAMPLES = 12
DEFAULT_MARGIN = 0.15
DEFAULT_DEMOTE_CHECKS = 3
#: a probe that accumulated no samples after this many further merged
#: ops (or PROBE_TIMEOUT_SEC of wall clock) is abandoned and its
#: schedule banned for the bucket — the engines' applies() gate fell
#: back (or the workers never armed rabit_adapt), so waiting is futile.
PROBE_TIMEOUT_SEC = 60.0


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def candidate_schedules(world: int, groups: list[int] | None) -> list[str]:
    """The schedules the controller may probe/switch for one job, in
    the deterministic order probes run: exactly the set whose
    engine-side ``applies()`` can accept this (world, topology) — a
    candidate that cannot run would probe forever and get banned, so
    the applicability rules are mirrored here via sched.topo."""
    if world < 2:
        return []
    out = ["tree", "ring", "halving"]
    if sched_topo.is_pow2(world):
        out.append("swing")
    groups = groups or []
    if len(groups) == world and len(set(groups)) >= 2:
        out.append("hier")
    return out


@dataclass
class Decision:
    """One controller decision, with the evidence it was made on."""

    ts: float
    kind: str                  # probe | switch | settle | demote | reinstate
    bucket: int | None = None
    sched: str | None = None
    rank: int | None = None
    evidence: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        out = {"ts": round(self.ts, 3), "kind": self.kind}
        if self.bucket is not None:
            out["bucket"] = self.bucket
        if self.sched is not None:
            out["sched"] = self.sched
        if self.rank is not None:
            out["rank"] = self.rank
        if self.evidence:
            out["evidence"] = self.evidence
        return out


class ScheduleScorer:
    """Pure per-bucket decision core over a SpanMerger cost fold.

    ``decide`` never mutates state: given the same fold, incumbent and
    ban set it returns the same verdict — decision determinism is a
    test invariant (a replayed fold must replay the decision)."""

    def __init__(self, candidates: list[str], min_samples: int,
                 margin: float) -> None:
        self.candidates = list(candidates)
        self.min_samples = max(int(min_samples), 1)
        self.margin = max(float(margin), 0.0)

    def decide(self, costs: dict[tuple[str, int], dict], bucket: int,
               incumbent: str | None,
               banned=frozenset()) -> tuple[str, str | None, dict]:
        """One verdict for ``bucket``: ``("hold"|"probe"|"switch",
        schedule_or_None, evidence)``.

        * hold — not enough incumbent samples yet, or no measured
          challenger beats the incumbent by the margin;
        * probe — a candidate has fewer than ``min_samples`` fresh
          measurements: measure it before judging (first unmeasured
          candidate in the fixed order, so exploration is
          deterministic);
        * switch — a fully-measured challenger's mean cost beats the
          incumbent's by more than ``margin`` (relative).  The margin
          is the hysteresis: after a switch the roles flip, so
          flapping needs the costs to keep leap-frogging each other by
          the margin in both directions — noise inside the margin
          cannot flap.
        """
        rows = {s: costs.get((s, bucket)) for s in self.candidates}
        inc = rows.get(incumbent) if incumbent else None
        if incumbent is None or incumbent not in self.candidates:
            return ("hold", None, {"why": "no-incumbent"})
        if inc is None or inc["n"] < self.min_samples:
            # The incumbent is what the job is (mostly) running: let
            # its own window fill before exploring challengers.
            return ("hold", None,
                    {"why": "incumbent-samples",
                     "n": int(inc["n"]) if inc else 0,
                     "need": self.min_samples})
        for s in self.candidates:
            if s == incumbent or s in banned:
                continue
            row = rows.get(s)
            if row is None or row["n"] < self.min_samples:
                return ("probe", s,
                        {"why": "unmeasured", "sched": s,
                         "n": int(row["n"]) if row else 0,
                         "need": self.min_samples})
        measured = {s: rows[s] for s in self.candidates
                    if s not in banned and rows.get(s) is not None
                    and rows[s]["n"] >= self.min_samples}
        best = min(measured, key=lambda s: (measured[s]["mean_sec"],
                                            self.candidates.index(s)))
        evidence = {
            "incumbent": incumbent,
            "incumbent_sec": round(inc["mean_sec"], 6),
            "challenger": best,
            "challenger_sec": round(measured[best]["mean_sec"], 6),
            "samples": {s: int(r["n"]) for s, r in measured.items()},
            "margin": self.margin,
        }
        if (best != incumbent
                and measured[best]["mean_sec"] * (1.0 + self.margin)
                < inc["mean_sec"]):
            return ("switch", best, evidence)
        return ("hold", None, evidence)

    def codec_override(self, wire_costs: dict[tuple[str, int, str], dict],
                       bucket: int, sched: str,
                       base_wire: str = "none",
                       incumbent_codec: str | None = None
                       ) -> tuple[str | None, dict]:
        """Per-op codec override verdict for one settled (bucket,
        schedule): ``(codec_or_None, evidence)``.

        Pure like :meth:`decide`.  ``wire_costs`` is the UNSCOPED
        per-(schedule, bucket, wire) fold
        (span.py ``sched_costs_wires``): if spans of the same schedule
        and bucket measured on a quantized wire beat the ``base_wire``
        cost by the margin — both sides with ``min_samples`` — the
        winning wire's name is the override the controller emits as a
        ``bytes:sched/codec`` directive entry (sched/tuner.py
        directive_codec; the engine arming landed in PR 14).

        Hysteresis is ASYMMETRIC like straggler demotion (factor vs
        factor/2): EMITTING needs a beat-by-``margin``, but an
        ``incumbent_codec`` already on the directive is only REVERTED
        once it stops beating the base wire at all — a codec cost
        hovering right at the margin boundary cannot flap the
        directive (each flap costs the whole world an epoch)."""
        base = wire_costs.get((sched, bucket, base_wire))
        if base is None or base["n"] < self.min_samples:
            return None, {"why": "base-samples",
                          "n": int(base["n"]) if base else 0}
        challengers = {
            w: row for (s, b, w), row in wire_costs.items()
            if s == sched and b == bucket and w != base_wire
            and row["n"] >= self.min_samples}
        if not challengers:
            return None, {"why": "no-codec-evidence"}
        best = min(challengers,
                   key=lambda w: (challengers[w]["mean_sec"], w))
        evidence = {
            "base_wire": base_wire,
            "base_sec": round(base["mean_sec"], 6),
            "codec": best,
            "codec_sec": round(challengers[best]["mean_sec"], 6),
            "samples": {w: int(r["n"])
                        for w, r in challengers.items()},
            "margin": self.margin,
        }
        if challengers[best]["mean_sec"] * (1.0 + self.margin) \
                < base["mean_sec"]:
            return best, evidence
        inc = challengers.get(incumbent_codec)
        if inc is not None and inc["mean_sec"] < base["mean_sec"]:
            # Inside the margin but still ahead of full width: HOLD
            # the already-emitted override rather than flapping.
            evidence["held"] = incumbent_codec
            return incumbent_codec, evidence
        return None, evidence


class AdaptiveController:
    """Per-job controller state machine over the live span fold.

    ``tick()`` consumes the job's :class:`SpanMerger` and straggler
    scores and returns the ACTIONS the tracker must apply — directive
    pushes (schedule-switch epochs) and demotions/reinstatements.  The
    controller itself holds no sockets and journals nothing: a tracker
    restart rebuilds it empty and it re-learns from the live stream
    (the durable knowledge lives in the TuningCache it persists
    through)."""

    def __init__(self, world: int, groups: list[int] | None, *,
                 min_samples: int | None = None,
                 margin: float | None = None,
                 straggler_factor: float = 3.0,
                 demote_checks: int | None = None,
                 adapt_codec: bool | None = None) -> None:
        self.world = int(world)
        self.groups = list(groups or [])
        if min_samples is None:
            min_samples = _env_int("RABIT_ADAPT_MIN_SAMPLES",
                                   DEFAULT_MIN_SAMPLES)
        if margin is None:
            margin = _env_float("RABIT_ADAPT_MARGIN", DEFAULT_MARGIN)
        if demote_checks is None:
            demote_checks = _env_int("RABIT_DEMOTE_CHECKS",
                                     DEFAULT_DEMOTE_CHECKS)
        self.min_samples = max(int(min_samples), 1)
        self.margin = max(float(margin), 0.0)
        self.straggler_factor = max(float(straggler_factor), 1.0)
        self.demote_checks = max(int(demote_checks), 1)
        #: RABIT_ADAPT_CODEC=1: the controller may extend a settled
        #: bucket's directive entry to the slashed ``sched/codec`` form
        #: when codec-scoped span evidence shows the quantized wire
        #: beating full width by the margin (PR 13/14 follow-on: the
        #: wire format and the engine-side arming already exist — this
        #: closes the emission half).  Off by default: emitting a
        #: per-op codec override changes numerics for the affected ops,
        #: so it is an operator opt-in, not a silent default.
        if adapt_codec is None:
            adapt_codec = os.environ.get(
                "RABIT_ADAPT_CODEC", "0").lower() in ("1", "true", "yes")
        self.adapt_codec = bool(adapt_codec)
        self.candidates = candidate_schedules(self.world, self.groups)
        self.scorer = ScheduleScorer(self.candidates, self.min_samples,
                                     self.margin)
        #: the directive currently pushed to the workers (bucket->sched)
        self.active: dict[int, str] = {}
        #: the settled (post-exploration) choice per bucket
        self.settled: dict[int, str] = {}
        self.demoted: set[int] = set()
        self.decisions: collections.deque = collections.deque(maxlen=64)
        self.counters: collections.Counter = collections.Counter()
        # in-flight probe: (bucket, sched, merged_ops_at_start, t_start)
        self._probe: tuple[int, str, int, float] | None = None
        self._banned: dict[int, set] = {}
        # straggler demotion streaks (consecutive over/under ticks)
        self._over: collections.Counter = collections.Counter()
        self._under: collections.Counter = collections.Counter()

    # -- helpers -------------------------------------------------------
    def note_epoch_landed(self, merged_ops: int,
                          now: float | None = None) -> None:
        """The schedule-switch epoch carrying the current probe's
        directive just completed: re-baseline the probe's abandonment
        budget HERE.  The original baseline was captured at decision
        time, but workers only adopt a directive at their next commit
        boundary — in a long-commit-interval job the incumbent merges
        far more than the budget's worth of ops before the probe
        schedule can run a single one, and the stale baseline would
        spuriously ban every candidate as 'cannot run here'."""
        if self._probe is not None:
            bucket, sched, _ops0, _t0 = self._probe
            self._probe = (bucket, sched, int(merged_ops),
                           time.monotonic() if now is None else now)

    def _record(self, kind: str, **kw) -> Decision:
        d = Decision(ts=time.time(), kind=kind, **kw)
        self.decisions.append(d)
        self.counters[kind] += 1
        return d

    @staticmethod
    def _dominant_bucket(costs: dict[tuple[str, int], dict]) -> int | None:
        """The payload bucket carrying the most merged samples — where
        adaptation pays.  Other buckets ride the directive's nearest-
        bucket pick and the persisted TuningCache."""
        per: collections.Counter = collections.Counter()
        for (_s, bucket), row in costs.items():
            per[bucket] += row["n"]
        if not per:
            return None
        # ties break toward the LARGER bucket (more bytes at stake)
        return max(per, key=lambda b: (per[b], b))

    def _observed_incumbent(self, costs, bucket) -> str | None:
        """The schedule actually carrying this bucket's ops (most
        samples) — the static/auto pick the controller starts from."""
        rows = {s: r for (s, b), r in costs.items() if b == bucket}
        if not rows:
            return None
        return max(rows, key=lambda s: (rows[s]["n"], s))

    # -- the tick ------------------------------------------------------
    def tick(self, merger, scores: dict[int, float],
             now: float | None = None,
             wire: str = "none") -> list[Decision]:
        """One controller pass: returns the decisions the tracker must
        act on (probe/switch/settle → push a schedule-switch epoch with
        the updated directive; demote/reinstate → update the demotion
        set and push).  ``scores`` are the merger's rolling straggler
        scores per rank; ``wire`` is the job's wire-codec label — the
        schedule evidence is scoped to spans measured on that wire
        format (span.py ``sched_costs``), so full-width opt-out ops in
        a codec-armed job never steer codec-keyed verdicts."""
        if now is None:
            now = time.monotonic()
        actions: list[Decision] = []
        actions += self._tick_demotion(scores)
        actions += self._tick_schedule(merger, now, wire)
        return actions

    def _tick_demotion(self, scores: dict[int, float]) -> list[Decision]:
        """Persistent-straggler demotion: the SAME threshold as the
        straggler timeline (RABIT_STRAGGLER_FACTOR), held for
        ``demote_checks`` consecutive ticks — one noisy window must not
        cost a rank its leadership; recovery below factor/2 (the
        timeline's hysteresis) for as many ticks reinstates."""
        actions: list[Decision] = []
        if "hier" not in self.candidates:
            return actions  # leadership only exists hierarchically
        for rank, score in sorted(scores.items()):
            if rank not in self.demoted and score > self.straggler_factor:
                self._under[rank] = 0
                self._over[rank] += 1
                if self._over[rank] >= self.demote_checks:
                    self.demoted.add(rank)
                    actions.append(self._record(
                        "demote", rank=rank,
                        evidence={"score": round(score, 3),
                                  "factor": self.straggler_factor,
                                  "checks": self.demote_checks}))
            elif rank in self.demoted \
                    and score < self.straggler_factor / 2:
                self._over[rank] = 0
                self._under[rank] += 1
                if self._under[rank] >= self.demote_checks:
                    self.demoted.discard(rank)
                    actions.append(self._record(
                        "reinstate", rank=rank,
                        evidence={"score": round(score, 3),
                                  "factor": self.straggler_factor}))
            else:
                self._over[rank] = 0
                self._under[rank] = 0
        # A demoted rank with NO rolling score (its spans vanished —
        # tracker restart rebuilt the merger, or the rank died and the
        # slot was refilled) must not stay demoted forever on absent
        # evidence: no-signal ticks count toward reinstatement, so a
        # fresh, healthy worker inheriting the rank re-earns
        # leadership within demote_checks ticks.
        for rank in sorted(self.demoted):
            if rank in scores:
                continue
            self._over[rank] = 0
            self._under[rank] += 1
            if self._under[rank] >= self.demote_checks:
                self.demoted.discard(rank)
                actions.append(self._record(
                    "reinstate", rank=rank,
                    evidence={"why": "no-signal",
                              "checks": self.demote_checks}))
        return actions

    def _tick_schedule(self, merger, now: float,
                       wire: str = "none") -> list[Decision]:
        costs = merger.sched_costs(wire)
        if not costs:
            return []
        bucket = self._dominant_bucket(costs)
        if bucket is None:
            return []
        pre: list[Decision] = []    # probe_failed surfaced with the
        # follow-up decision, so the tracker logs/counts/timelines it
        # like every other decision kind
        if self._probe is not None:
            pbucket, sched, ops0, t0 = self._probe
            row = costs.get((sched, pbucket))
            got = row["n"] if row is not None else 0
            if got >= self.min_samples:
                self._probe = None  # measured: fall through and decide
            elif (got == 0 and merger.merged_ops - ops0
                    > 8 * self.min_samples) \
                    or now - t0 > PROBE_TIMEOUT_SEC:
                # Zero samples while other ops kept merging: the
                # schedule cannot run here (engine applies() fallback,
                # or the workers never armed rabit_adapt).  The
                # wall-clock bound also catches a probe stuck with a
                # PARTIAL window (the workload drifted out of the
                # bucket) — either way, ban it for this bucket and
                # move on rather than wedging exploration forever.
                self._banned.setdefault(pbucket, set()).add(sched)
                self._probe = None
                pre.append(self._record(
                    "probe_failed", bucket=pbucket, sched=sched,
                    evidence={"samples": got,
                              "merged_ops": merger.merged_ops - ops0}))
            else:
                return []  # probe still filling its window
        # Read the ban set AFTER the probe block: a probe abandoned
        # just above must be out of the running for THIS decision.
        banned = self._banned.get(bucket, set())
        incumbent = self.settled.get(bucket)
        if incumbent not in self.scorer.candidates:
            # No settled choice yet — or a seeded/settled schedule that
            # left the candidate set (topology changed, e.g. the host
            # groups collapsed and hier no longer exists): fall back to
            # what the job is observably running instead of holding on
            # a ghost incumbent forever.
            incumbent = self._observed_incumbent(costs, bucket)
        kind, sched, evidence = self.scorer.decide(
            costs, bucket, incumbent, banned)
        if kind == "probe":
            self._probe = (bucket, sched, merger.merged_ops, now)
            self.active[bucket] = sched
            return pre + [self._record("probe", bucket=bucket,
                                       sched=sched, evidence=evidence)]
        if kind == "switch":
            self.settled[bucket] = sched
            self.active[bucket] = sched
            return pre + [self._record("switch", bucket=bucket,
                                       sched=sched, evidence=evidence)]
        # hold — but if the last probe left the directive pointing at a
        # loser, settle back on the incumbent (still an epoch push: the
        # workers are running the probe's schedule right now).  NOT
        # gated on settled: a rebuilt controller (tracker restart,
        # membership change) re-probes with its seeded directive and
        # must still return to the incumbent when every challenger
        # loses — otherwise the workers stay pinned on the last, worst
        # probe forever.  The comparison is on the PLAIN schedule half:
        # an active ``sched/codec`` override of the incumbent is the
        # incumbent, not a probe leftover.
        active_plain = (self.active.get(bucket) or "").split("/", 1)[0]
        if incumbent is not None and active_plain \
                and active_plain != incumbent:
            self.settled[bucket] = incumbent
            self.active[bucket] = incumbent
            return pre + [self._record("settle", bucket=bucket,
                                       sched=incumbent,
                                       evidence=evidence)]
        # Stable state (holding on the incumbent): the codec-override
        # emission pass, gated on the opt-in flag and on a full-width
        # job (a job whose own wire is already quantized has nothing
        # to gain from a per-op override of the same codec).
        if self.adapt_codec and incumbent is not None \
                and wire == "none":
            return pre + self._codec_tick(merger, bucket, incumbent)
        return pre

    def _codec_tick(self, merger, bucket: int,
                    sched: str) -> list[Decision]:
        """Re-derive the bucket's directive VALUE (plain or slashed)
        from the wire-scoped fold; a change is a ``codec`` decision the
        tracker pushes like any other directive move."""
        current = self.active.get(bucket)
        current_codec = None
        if current and "/" in current \
                and current.split("/", 1)[0] == sched:
            current_codec = current.split("/", 1)[1]
        codec, evidence = self.scorer.codec_override(
            merger.sched_costs_wires(), bucket, sched,
            incumbent_codec=current_codec)
        desired = f"{sched}/{codec}" if codec else sched
        if current == desired:
            return []
        if codec is None and current is None:
            # No override to emit and no directive to revert: pinning
            # the incumbent into a directive would push an epoch for
            # nothing.
            return []
        self.active[bucket] = desired
        return [self._record("codec", bucket=bucket, sched=desired,
                             evidence=evidence)]


__all__ = [
    "AdaptiveController", "ScheduleScorer", "Decision",
    "candidate_schedules", "DEFAULT_MIN_SAMPLES", "DEFAULT_MARGIN",
    "DEFAULT_DEMOTE_CHECKS",
]
