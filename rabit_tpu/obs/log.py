"""Structured logger for the engines and tracker.

Replaces the ad-hoc ``print()`` / silent exception swallowing in the
host engines with one rank/role/seqno-prefixed stderr stream:

    [rabit][pyrobust][rank=2 v=1 seq=3][WARN] killed at kill-point ...

``debug`` lines are gated by the ``rabit_debug`` parameter (or
``RABIT_DEBUG`` env); info/warn/error always print.  Engines construct a
:class:`Logger` with a *context callable* so the prefix always reflects
the live rank/version/seqno without the call sites threading them
through (the reference's analogue is the ``utils::Printf`` handlers,
include/rabit/utils.h:66-84, which had no structure at all).
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Callable, Optional

DEBUG, INFO, WARN, ERROR = 10, 20, 30, 40
_LEVEL_NAMES = {DEBUG: "DEBUG", INFO: "INFO", WARN: "WARN", ERROR: "ERROR"}

_level = INFO
_level_lock = threading.Lock()


def set_debug(on: bool) -> None:
    """Gate ``Logger.debug`` output (the ``rabit_debug`` param)."""
    global _level
    with _level_lock:
        _level = DEBUG if on else INFO


def debug_enabled() -> bool:
    return _level <= DEBUG


def _truthy(v) -> bool:
    return str(v).lower() not in ("", "0", "false", "no", "none", "off")


def configure(params: dict | None = None) -> None:
    """Set the level from ``rabit_debug`` / ``RABIT_DEBUG``."""
    params = params or {}
    raw = params.get("rabit_debug")
    if raw is None:
        raw = os.environ.get("RABIT_DEBUG", "")
    set_debug(_truthy(raw))


class Logger:
    """Role-tagged printf-style logger with a live context prefix."""

    def __init__(self, role: str,
                 context: Optional[Callable[[], dict]] = None) -> None:
        self.role = role
        self._context = context

    def _emit(self, level: int, fmt: str, *args) -> None:
        if level < _level:
            return
        msg = (fmt % args) if args else fmt
        parts = [f"[rabit][{self.role}]"]
        if self._context is not None:
            try:
                ctx = self._context()
            except Exception:  # noqa: BLE001 — the prefix must never raise
                ctx = {}
            if ctx:
                parts.append("[" + " ".join(f"{k}={v}"
                                            for k, v in ctx.items()) + "]")
        parts.append(f"[{_LEVEL_NAMES[level]}]")
        print(" ".join(parts) + " " + msg, file=sys.stderr, flush=True)

    def debug(self, fmt: str, *args) -> None:
        self._emit(DEBUG, fmt, *args)

    def info(self, fmt: str, *args) -> None:
        self._emit(INFO, fmt, *args)

    def warn(self, fmt: str, *args) -> None:
        self._emit(WARN, fmt, *args)

    def error(self, fmt: str, *args) -> None:
        self._emit(ERROR, fmt, *args)
