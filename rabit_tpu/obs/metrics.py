"""Metrics registry: counters, gauges and log2-bucket latency histograms.

The measurement substrate for every engine (tentpole of the telemetry
subsystem): zero dependencies beyond the stdlib, thread-safe, and cheap
enough that the engines leave the *call sites* compiled in and gate them
with a single bool (`rabit_obs` / `rabit_obs_dir`, doc/observability.md)
— when telemetry is off no instrument is ever touched.

Histograms use **fixed log2 buckets**: a value lands in the bucket of
its binary exponent (`math.frexp`), so bucket boundaries are powers of
two and a percentile estimate is accurate within one octave.  On top of
the buckets a Welford accumulator tracks exact count/sum/mean/std and
min/max — the same implementation `utils.profiler.Timer` now wraps
(reference's only aggregation was the speed test's hand-rolled
sum/sum² allreduce, test/speed_test.cc:53-70).
"""
from __future__ import annotations

import math
import threading

# Bucket i spans [2**(i + _EXP0), 2**(i + _EXP0 + 1)); _EXP0 puts the
# bottom bucket at ~1 ns so latencies and byte sizes both fit.
_EXP0 = -40
_NBUCKET = 64


class Counter:
    """Monotonic counter (op counts, byte totals)."""

    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Log2-bucketed distribution with exact Welford mean/std and min/max."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._buckets = [0] * _NBUCKET
        self.count = 0
        self.sum = 0.0
        self._mean = 0.0
        self._m2 = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            delta = v - self._mean
            self._mean += delta / self.count
            self._m2 += delta * (v - self._mean)
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            self._buckets[self._index(v)] += 1

    @staticmethod
    def _index(v: float) -> int:
        if v <= 0.0:
            return 0
        e = math.frexp(v)[1] - 1  # v in [2**e, 2**(e+1))
        return min(max(e - _EXP0, 0), _NBUCKET - 1)

    @staticmethod
    def bucket_bound(i: int) -> float:
        """Lower bound of bucket ``i``."""
        return 2.0 ** (i + _EXP0)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    @property
    def std(self) -> float:
        if self.count < 2:
            return 0.0
        return math.sqrt(self._m2 / self.count)

    def _percentile_locked(self, q: float) -> float:
        if not self.count:
            return 0.0
        target = self.count * q / 100.0
        acc = 0
        for i, n in enumerate(self._buckets):
            acc += n
            if acc >= target:
                hi = self.bucket_bound(i + 1)
                return min(max(hi, self.min), self.max)
        return self.max

    def percentile(self, q: float) -> float:
        """Estimate the q-th percentile from the log2 buckets (upper
        bucket bound, clamped to the exact observed min/max — accurate
        within one octave)."""
        with self._lock:
            return self._percentile_locked(q)

    def snapshot(self) -> dict:
        # One locked section so count/min/max/percentiles are mutually
        # consistent even against concurrent observe().
        with self._lock:
            return {
                "count": self.count, "sum": self.sum,
                "mean": self.mean, "std": self.std,
                "min": self.min if self.count else 0.0,
                "max": self.max if self.count else 0.0,
                "p50": self._percentile_locked(50),
                "p90": self._percentile_locked(90),
                "p99": self._percentile_locked(99),
                "buckets": {str(i + _EXP0): n
                            for i, n in enumerate(self._buckets) if n},
            }


class Metrics:
    """Named-instrument registry; instruments are created on first use."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def _get(self, store: dict, name: str, cls):
        inst = store.get(name)
        if inst is None:
            with self._lock:
                inst = store.setdefault(name, cls())
        return inst

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(self._histograms, name, Histogram)

    def snapshot(self) -> dict:
        """JSON-able dump: {"counters": {}, "gauges": {}, "histograms": {}}."""
        # Copy the registries under the lock (a concurrent first-use
        # registration mutates the dicts); instrument reads take each
        # instrument's own lock.
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            histograms = sorted(self._histograms.items())
        return {
            "counters": {n: c.value for n, c in counters},
            "gauges": {n: g.value for n, g in gauges},
            "histograms": {n: h.snapshot() for n, h in histograms},
        }


def flatten_snapshot(snap: dict) -> dict[str, float]:
    """Flatten a ``Metrics.snapshot()`` into scalar series for
    cross-rank aggregation (histograms contribute their summary stats)."""
    out: dict[str, float] = {}
    for name, v in snap.get("counters", {}).items():
        out[name] = float(v)
    for name, v in snap.get("gauges", {}).items():
        out[name] = float(v)
    for name, h in snap.get("histograms", {}).items():
        for k in ("count", "sum", "mean", "std", "max", "p50", "p90", "p99"):
            out[f"{name}.{k}"] = float(h.get(k, 0.0))
    return out


def aggregate_snapshots(snaps: list[dict]) -> dict[str, dict[str, float]]:
    """min/mean/max across ranks for every flattened metric (the shape
    the tracker writes into its per-job obs report)."""
    flats = [flatten_snapshot(s) for s in snaps]
    keys = sorted({k for f in flats for k in f})
    out: dict[str, dict[str, float]] = {}
    for k in keys:
        vals = [f[k] for f in flats if k in f]
        out[k] = {"min": min(vals), "mean": sum(vals) / len(vals),
                  "max": max(vals)}
    return out
