"""Cross-rank collective spans and straggler attribution.

Every instrumented collective records a **span** — begin/end epoch
timestamps keyed by ``(epoch, seqno, kind)`` plus the schedule that
carried it and the payload size.  Workers buffer spans locally
(:class:`SpanBuffer`) and ship them to the tracker inside the periodic
``cmd=obs`` frames on the heartbeat channel; the tracker merges the
spans of all ranks per op (:class:`SpanMerger`), computes per-op skew,
and maintains a rolling **straggler score** per rank.

Attribution model: in a blocking collective the ranks that arrived
early *wait* for the late one, so the straggler is the rank whose span
**begins latest** (its own span is also the shortest — everything is
already in flight when it shows up).  Per merged op we take

* ``lateness(rank) = begin(rank) - min(begin)`` — how long the rest of
  the world waited on this rank, and
* ``op_sec = min(duration)`` — the *true* wire cost of the op (the last
  arriver's own duration, unpolluted by waiting).

A rank's score is ``mean(lateness window) / mean(op_sec window)``: "how
many op-times late is this rank, on average".  A rank is flagged when
its score exceeds ``rabit_straggler_factor`` AND its mean lateness
clears an absolute floor (``RABIT_STRAGGLER_MIN_SEC``) — the floor
keeps scheduler jitter on microsecond-scale ops from producing verdicts
(doc/observability.md "Live telemetry").
"""
from __future__ import annotations

import collections
import threading

# Wire layout of one span inside an obs frame: a compact positional
# list, not a dict — frames ship every flush period.  ``version`` is
# part of the key on purpose: the robust protocol's seqno RESETS to 0
# at every checkpoint commit, so (epoch, seq) alone would merge spans
# of different versions' ops into one bogus group.  ``wire`` (trailing,
# optional — pre-codec emitters ship 8-field spans and the merger
# tolerates both) is the op's EFFECTIVE wire format: in a codec-armed
# job, opted-out and ineligible ops ride full-width bytes, and their
# measurements must never answer codec-keyed tuner rows.
SPAN_FIELDS = ("seq", "epoch", "version", "kind", "sched", "nbytes",
               "t0", "t1", "wire")


def payload_bucket(nbytes: int) -> int:
    """Power-of-two payload bucket (floor) — the size coordinate the
    adaptive controller's per-(bucket, schedule) cost estimates fold
    on (rabit_tpu/obs/adapt.py).  Matches the log-space granularity of
    the tuning cache's nearest-size pick."""
    n = max(int(nbytes), 1)
    return 1 << (n.bit_length() - 1)


class SpanBuffer:
    """Worker-side bounded span staging area, drained per obs flush."""

    def __init__(self, capacity: int = 2048) -> None:
        self._buf: list[list] = []
        self._cap = max(int(capacity), 1)
        self._lock = threading.Lock()
        self.dropped = 0

    def add(self, seq: int, epoch: int, version: int, kind: str,
            sched: str | None, nbytes: int, t0: float,
            t1: float, wire: str = "none") -> None:
        with self._lock:
            if len(self._buf) >= self._cap:
                self.dropped += 1
                return
            self._buf.append([int(seq), int(epoch), int(version), kind,
                              sched, int(nbytes), round(t0, 6),
                              round(t1, 6), str(wire)])

    def drain(self) -> list[list]:
        with self._lock:
            out, self._buf = self._buf, []
        return out

    def __len__(self) -> int:
        return len(self._buf)


def merge_group(spans: dict[int, tuple[float, float]]) -> dict:
    """Merge ONE op's spans across ranks: ``{rank: (t0, t1)}`` →
    per-rank lateness, the op's skew, and its true wire cost.  Pure —
    the synthetic-timeline unit tests drive it directly."""
    begins = {r: t0 for r, (t0, _t1) in spans.items()}
    first = min(begins.values())
    lateness = {r: b - first for r, b in begins.items()}
    durs = {r: t1 - t0 for r, (t0, t1) in spans.items()}
    latest = max(begins, key=lambda r: (begins[r], r))
    return {
        "skew": max(lateness.values()),
        "op_sec": max(min(durs.values()), 0.0),
        "lateness": lateness,
        "durations": durs,
        "latest_rank": latest,
    }


class _SchedStats:
    """Per-schedule latency/skew aggregation for merged spans."""

    __slots__ = ("count", "dur_sum", "dur_max", "skew_sum", "skew_max")

    def __init__(self) -> None:
        self.count = 0
        self.dur_sum = 0.0
        self.dur_max = 0.0
        self.skew_sum = 0.0
        self.skew_max = 0.0

    def fold(self, dur: float, skew: float) -> None:
        self.count += 1
        self.dur_sum += dur
        self.dur_max = max(self.dur_max, dur)
        self.skew_sum += skew
        self.skew_max = max(self.skew_max, skew)


class SpanMerger:
    """Tracker-side per-job span merge + rolling straggler scores.

    ``add()`` groups incoming spans by ``(epoch, seq, kind)``; a group
    finalizes as soon as every member reported (``world`` spans) or
    when it is evicted as the oldest of ``max_pending`` — ranks whose
    span buffer overflowed may never report, and a bounded pending set
    must not leak.  Finalized groups with at least two ranks feed the
    rolling windows; single-rank groups carry no cross-rank signal.
    """

    def __init__(self, window: int = 64, max_pending: int = 512,
                 min_ops: int = 6) -> None:
        self._lock = threading.Lock()
        self._pending: collections.OrderedDict = collections.OrderedDict()
        self._window = max(int(window), 2)
        self._max_pending = max(int(max_pending), 8)
        self.min_ops = max(int(min_ops), 1)
        # rank -> rolling lateness samples; one shared op-cost window.
        self._lateness: dict[int, collections.deque] = {}
        self._op_sec: collections.deque = collections.deque(
            maxlen=self._window)
        self._ops_per_rank: collections.Counter = collections.Counter()
        self._sched: dict[str, _SchedStats] = {}
        self._rank_sched_late: dict[tuple[int, str], float] = {}
        # (sched, payload bucket) -> rolling true-wire-cost window: the
        # fold the adaptive controller re-scores schedule choice from.
        self._cost: dict[tuple[str, int], collections.deque] = {}
        self.merged_ops = 0

    # -- ingest --------------------------------------------------------
    def add(self, rank: int, spans: list, world: int) -> None:
        """Fold one rank's shipped spans (wire layout ``SPAN_FIELDS``);
        malformed entries are skipped, never raised — frames arrive
        from the network."""
        with self._lock:
            for s in spans:
                try:
                    (seq, epoch, version, kind, sched, nbytes, t0, t1,
                     *rest) = s
                    key = (int(epoch), int(version), int(seq), str(kind))
                    t0, t1 = float(t0), float(t1)
                except (TypeError, ValueError):
                    continue
                # Trailing wire-format label (9th field); 8-field spans
                # from pre-codec emitters read as the full-width wire.
                wire = str(rest[0]) if rest and rest[0] else "none"
                grp = self._pending.get(key)
                if grp is None:
                    grp = self._pending[key] = {}
                grp[int(rank)] = (t0, max(t1, t0),
                                  str(sched) if sched else None,
                                  int(nbytes) if isinstance(
                                      nbytes, (int, float)) else 0,
                                  wire)
                self._ops_per_rank[int(rank)] += 1
                if len(grp) >= max(world, 2):
                    self._pending.pop(key, None)
                    self._finalize(grp)
            while len(self._pending) > self._max_pending:
                _key, grp = self._pending.popitem(last=False)
                self._finalize(grp)

    def _finalize(self, grp: dict) -> None:
        if len(grp) < 2:
            return
        res = merge_group({r: (t0, t1)
                           for r, (t0, t1, _s, _n, _w) in grp.items()})
        self.merged_ops += 1
        self._op_sec.append(res["op_sec"])
        scheds = {s for _t0, _t1, s, _n, _w in grp.values() if s}
        sched = scheds.pop() if len(scheds) == 1 else None
        if sched is not None:
            st = self._sched.get(sched)
            if st is None:
                st = self._sched[sched] = _SchedStats()
            # Fold the TRUE wire cost (the last arriver's own
            # duration): folding the earliest arriver's wait-inflated
            # duration would let a host-level straggler pollute every
            # schedule's latency — exactly the schedule-vs-host
            # attribution this table exists to separate.  Host-level
            # lateness lives in the skew column instead.
            st.fold(res["op_sec"], res["skew"])
            # Per-(sched, payload bucket, wire) cost window — the
            # adaptive controller's evidence (sched labels only ride
            # allreduce spans, so the fold is allreduce cost by
            # construction).  The wire label is replicated per op
            # (codec eligibility is a collective decision), so the
            # group agrees; a mixed group is malformed input and folds
            # as full-width.
            nbytes = max((n for _t0, _t1, _s, n, _w in grp.values()),
                         default=0)
            wires = {w for _t0, _t1, _s, _n, w in grp.values()}
            wire = wires.pop() if len(wires) == 1 else "none"
            if nbytes > 0:
                ck = (sched, payload_bucket(nbytes), wire)
                dq = self._cost.get(ck)
                if dq is None:
                    dq = self._cost[ck] = collections.deque(
                        maxlen=self._window)
                dq.append(res["op_sec"])
        for r, late in res["lateness"].items():
            dq = self._lateness.get(r)
            if dq is None:
                dq = self._lateness[r] = collections.deque(
                    maxlen=self._window)
            dq.append(late)
            if sched is not None:
                k = (r, sched)
                self._rank_sched_late[k] = (
                    self._rank_sched_late.get(k, 0.0) + late)

    # -- scoring -------------------------------------------------------
    def _score_locked(self, rank: int) -> tuple[float, float, int]:
        """(score, mean lateness, samples) for one rank."""
        dq = self._lateness.get(rank)
        if not dq:
            return 0.0, 0.0, 0
        late = sum(dq) / len(dq)
        op = (sum(self._op_sec) / len(self._op_sec)
              if self._op_sec else 0.0)
        return late / max(op, 1e-6), late, len(dq)

    def score(self, rank: int) -> float:
        with self._lock:
            return self._score_locked(rank)[0]

    def scores(self) -> dict[int, float]:
        with self._lock:
            return {r: self._score_locked(r)[0]
                    for r in sorted(self._lateness)}

    def sched_costs(self, wire: str = "none"
                    ) -> dict[tuple[str, int], dict]:
        """Rolling per-(schedule, payload bucket) cost estimates from
        the merged spans: ``{(sched, bucket): {"mean_sec", "n"}}`` —
        the fold the adaptive controller re-scores schedule choice
        from (rabit_tpu/obs/adapt.py).  Scoped to ops measured on the
        requested ``wire`` format: in a codec-armed job, full-width
        spans (per-op opt-outs, ineligible dtypes) must never become
        evidence for codec-keyed tuner rows, or vice versa."""
        with self._lock:
            return {(s, b): {"mean_sec": sum(dq) / len(dq),
                             "n": len(dq)}
                    for (s, b, w), dq in self._cost.items()
                    if dq and w == wire}

    def sched_costs_wires(self) -> dict[tuple[str, int, str], dict]:
        """Like :meth:`sched_costs` but UNSCOPED: every (schedule,
        bucket, wire) row — the fold the controller's codec-override
        emission compares wire formats over (obs/adapt.py
        ``ScheduleScorer.codec_override``, RABIT_ADAPT_CODEC)."""
        with self._lock:
            return {(s, b, w): {"mean_sec": sum(dq) / len(dq),
                                "n": len(dq)}
                    for (s, b, w), dq in self._cost.items() if dq}

    def reset_windows(self) -> None:
        """Drop every rolling window (costs, lateness, per-sched
        stats) while keeping the cumulative counters.  Called on a
        membership change: timings and lateness measured at the OLD
        world — under the old rank numbering — must not feed schedule
        decisions, TuningCache merges or straggler verdicts for the
        new one."""
        with self._lock:
            self._pending.clear()
            self._lateness.clear()
            self._op_sec.clear()
            self._sched.clear()
            self._rank_sched_late.clear()
            self._cost.clear()

    def straggler_verdicts(self, factor: float,
                           min_sec: float) -> list[tuple[int, float, float]]:
        """Ranks currently over the line: ``(rank, score, mean
        lateness)`` where score > factor, lateness > min_sec, and the
        window holds at least ``min_ops`` merged samples."""
        out = []
        with self._lock:
            for r in sorted(self._lateness):
                score, late, n = self._score_locked(r)
                if n >= self.min_ops and score > factor and late > min_sec:
                    out.append((r, score, late))
        return out

    # -- reporting -----------------------------------------------------
    def report(self) -> dict:
        """The obs_report sections: per-rank straggler rows (score,
        mean lateness, per-schedule lateness split) + the per-schedule
        latency/skew breakdown."""
        with self._lock:
            ranks = {}
            for r in sorted(self._lateness):
                score, late, n = self._score_locked(r)
                per_sched = {s: round(v, 6)
                             for (rr, s), v
                             in sorted(self._rank_sched_late.items())
                             if rr == r}
                ranks[str(r)] = {"score": round(score, 3),
                                 "mean_lateness_sec": round(late, 6),
                                 "ops": int(self._ops_per_rank[r]),
                                 "window": n,
                                 "sched_lateness_sec": per_sched}
            sched = {}
            for name, st in sorted(self._sched.items()):
                sched[name] = {
                    "count": st.count,
                    "mean_sec": round(st.dur_sum / max(st.count, 1), 6),
                    "max_sec": round(st.dur_max, 6),
                    "mean_skew_sec": round(
                        st.skew_sum / max(st.count, 1), 6),
                    "max_skew_sec": round(st.skew_max, 6),
                }
            return {"merged_ops": self.merged_ops, "ranks": ranks,
                    "sched": sched}
