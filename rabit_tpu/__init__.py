"""rabit_tpu — a TPU-native fault-tolerant collective-communication framework.

A ground-up rebuild of the capabilities of rabit (Reliable Allreduce and
Broadcast Interface) designed for TPUs: the steady-state data plane runs as
XLA collectives over ICI across the device mesh (``rabit_engine=xla``),
while a native C++ engine provides the host/DCN transport, tracker
rendezvous, fault-tolerant recovery and in-memory checkpoint replication
(``rabit_engine=native``).  See SURVEY.md for the full design map.
"""
from rabit_tpu.api import (
    init,
    finalize,
    initialized,
    get_rank,
    get_world_size,
    get_processor_name,
    is_distributed,
    tracker_print,
    allreduce,
    allreduce_async,
    allreduce_custom,
    allreduce_many,
    allgather,
    allgather_async,
    broadcast,
    load_checkpoint,
    checkpoint,
    lazy_checkpoint,
    version_number,
    device_epoch,
)
from rabit_tpu.ckpt import CheckpointSkewError
from rabit_tpu.engine.interface import AsyncOrderError, CollectiveHandle
from rabit_tpu.engine.pysocket import (AdmissionError, AsyncPumpError,
                                       ShardMovedError, TrackerLostError,
                                       WorldChangedError)
from rabit_tpu.engine.robust import RecoveryError
from rabit_tpu.ops import MAX, MIN, SUM, PROD, BITOR, BITAND, BITXOR, ReduceOp
from rabit_tpu.utils import Serializable, RabitError

__version__ = "0.1.0"

__all__ = [
    "init",
    "finalize",
    "initialized",
    "get_rank",
    "get_world_size",
    "get_processor_name",
    "is_distributed",
    "tracker_print",
    "allreduce",
    "allreduce_async",
    "allreduce_custom",
    "allreduce_many",
    "allgather",
    "allgather_async",
    "broadcast",
    "load_checkpoint",
    "checkpoint",
    "lazy_checkpoint",
    "version_number",
    "device_epoch",
    "MAX",
    "MIN",
    "SUM",
    "PROD",
    "BITOR",
    "BITAND",
    "BITXOR",
    "ReduceOp",
    "CollectiveHandle",
    "AsyncOrderError",
    "AsyncPumpError",
    "RecoveryError",
    "CheckpointSkewError",
    "WorldChangedError",
    "TrackerLostError",
    "AdmissionError",
    "ShardMovedError",
    "Serializable",
    "RabitError",
    "__version__",
]
