"""Model families shipped with the framework.

The reference's model layer is the rabit-learn toolkit
(reference: rabit-learn/ — kmeans, linear/logistic via L-BFGS); the
implementations live in :mod:`rabit_tpu.learn` and are re-exported here
so the package layout mirrors the framework map (models / ops /
parallel / utils).
"""
from rabit_tpu.learn.boosting import BoostedModel
from rabit_tpu.learn.kmeans import KMeansModel
from rabit_tpu.learn.lbfgs import LBFGSSolver, ObjFunction
from rabit_tpu.learn.linear import LinearModel

__all__ = ["BoostedModel", "KMeansModel", "LBFGSSolver", "ObjFunction",
           "LinearModel"]
