// C ABI implementation: engine singleton + error translation.
// Reference analogue: wrapper/rabit_wrapper.cc plus the engine selector
// src/engine.cc:20-48 — but variant selection happens at *runtime* via the
// rabit_engine parameter instead of compile-time macros producing five
// library flavours.
#include "rabit_tpu/c_api.h"

#include <cstring>
#include <memory>
#include <string>
#include <vector>

#include "rabit_tpu/base_engine.h"
#include "rabit_tpu/empty_engine.h"
#include "rabit_tpu/engine.h"
#include "rabit_tpu/rabit_tpu.h"
#include "rabit_tpu/robust_engine.h"
#include "rabit_tpu/utils.h"

namespace {

std::unique_ptr<rabit_tpu::IEngine> g_engine;
thread_local std::string g_last_error;
thread_local std::string g_blob;         // BroadcastBlob result
thread_local std::string g_ckpt_global;  // LoadCheckPoint results
thread_local std::string g_ckpt_local;

rabit_tpu::IEngine* Engine() {
  rabit_tpu::Check(g_engine != nullptr,
                   "rabit_tpu native engine not initialised");
  return g_engine.get();
}

std::unique_ptr<rabit_tpu::IEngine> MakeEngine(const std::string& name);

}  // namespace

namespace rabit_tpu {

// Singleton accessors shared by the public C++ API (rabit_tpu.h) and the
// C ABI below — both surfaces drive the same engine.
IEngine* GetEngine() { return Engine(); }

void InitEngine(const std::vector<std::string>& args) {
  Check(g_engine == nullptr, "already initialised");
  std::vector<std::pair<std::string, std::string>> params;
  std::string variant = "base";
  for (const auto& arg : args) {
    auto eq = arg.find('=');
    if (eq == std::string::npos) continue;
    std::string key = arg.substr(0, eq), val = arg.substr(eq + 1);
    if (key == "rabit_engine") {
      variant = val;
    } else {
      params.emplace_back(key, val);
    }
  }
  auto eng = MakeEngine(variant);
  eng->Init(params);
  g_engine = std::move(eng);
}

void FinalizeEngine() {
  if (g_engine) {
    g_engine->Shutdown();
    g_engine.reset();
  }
}

}  // namespace rabit_tpu

namespace {

template <typename Fn>
int Guard(Fn&& fn) {
  try {
    fn();
    return 0;
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return -1;
  }
}

std::unique_ptr<rabit_tpu::IEngine> MakeEngine(const std::string& name);

}  // namespace

extern "C" {

int RbtTpuInit(int argc, const char** argv) {
  return Guard([&] {
    std::vector<std::string> args;
    for (int i = 0; i < argc; ++i) args.emplace_back(argv[i]);
    rabit_tpu::InitEngine(args);
  });
}

int RbtTpuFinalize(void) {
  return Guard([&] { rabit_tpu::FinalizeEngine(); });
}

int RbtTpuGetRank(void) {
  int out = -1;
  Guard([&] { out = Engine()->rank(); });
  return out;
}

int RbtTpuGetWorldSize(void) {
  int out = -1;
  Guard([&] { out = Engine()->world_size(); });
  return out;
}

int RbtTpuIsDistributed(void) {
  int out = 0;
  Guard([&] { out = Engine()->world_size() > 1 ? 1 : 0; });
  return out;
}

int RbtTpuGetProcessorName(char* out, size_t max_len) {
  return Guard([&] {
    std::string h = Engine()->host();
    size_t n = std::min(max_len - 1, h.size());
    memcpy(out, h.data(), n);
    out[n] = '\0';
  });
}

const char* RbtTpuGetLastError(void) { return g_last_error.c_str(); }

int RbtTpuTrackerPrint(const char* msg) {
  return Guard([&] { Engine()->TrackerPrint(msg); });
}

int RbtTpuAllreduce(void* buf, size_t count, int dtype, int op,
                    void (*prepare)(void*), void* prepare_arg) {
  return Guard([&] {
    rabit_tpu::PrepareFn fn;
    if (prepare != nullptr) {
      fn = [prepare, prepare_arg] { prepare(prepare_arg); };
    }
    Engine()->Allreduce(buf, count, static_cast<rabit_tpu::DataType>(dtype),
                        static_cast<rabit_tpu::ReduceOp>(op), fn);
  });
}

int RbtTpuAllreduceCustom(void* buf, size_t count, size_t item_size,
                          void (*reducer)(void* dst, const void* src,
                                          size_t count, void* arg),
                          void* reducer_arg,
                          void (*prepare)(void*), void* prepare_arg) {
  return Guard([&] {
    rabit_tpu::Check(reducer != nullptr, "AllreduceCustom: null reducer");
    rabit_tpu::PrepareFn pfn;
    if (prepare != nullptr) {
      pfn = [prepare, prepare_arg] { prepare(prepare_arg); };
    }
    Engine()->AllreduceCustom(
        buf, count, item_size,
        [reducer, reducer_arg](void* dst, const void* src, size_t n) {
          reducer(dst, src, n, reducer_arg);
        },
        pfn);
  });
}

int RbtTpuBroadcast(void* buf, size_t size, int root) {
  return Guard([&] {
    std::string payload;
    if (Engine()->rank() == root) {
      payload.assign(static_cast<char*>(buf), size);
    }
    Engine()->Broadcast(&payload, root);
    rabit_tpu::Check(payload.size() == size,
                     "broadcast: size mismatch (%zu != %zu)", payload.size(),
                     size);
    if (Engine()->rank() != root) memcpy(buf, payload.data(), size);
  });
}

int RbtTpuBroadcastBlob(const char* in, size_t in_len, int root,
                        const char** out, size_t* out_len) {
  return Guard([&] {
    if (Engine()->rank() == root) {
      g_blob.assign(in, in_len);
    } else {
      g_blob.clear();
    }
    Engine()->Broadcast(&g_blob, root);
    *out = g_blob.data();
    *out_len = g_blob.size();
  });
}

int RbtTpuAllgather(const void* mine, size_t nbytes, void* out) {
  return Guard([&] { Engine()->Allgather(mine, nbytes, out); });
}

int RbtTpuLoadCheckPoint(const char** global_ptr, size_t* global_len,
                         const char** local_ptr, size_t* local_len) {
  int version = -1;
  Guard([&] {
    g_ckpt_global.clear();
    g_ckpt_local.clear();
    version = Engine()->LoadCheckPoint(&g_ckpt_global, &g_ckpt_local);
    *global_ptr = g_ckpt_global.data();
    *global_len = g_ckpt_global.size();
    *local_ptr = g_ckpt_local.data();
    *local_len = g_ckpt_local.size();
  });
  return version;
}

int RbtTpuCheckPoint(const char* global, size_t global_len, const char* local,
                     size_t local_len) {
  return Guard([&] {
    std::string g(global ? global : "", global ? global_len : 0);
    if (local != nullptr) {
      std::string l(local, local_len);
      Engine()->CheckPoint(&g, &l);
    } else {
      Engine()->CheckPoint(&g, nullptr);
    }
  });
}

int RbtTpuLazyCheckPoint(const char* (*serialize)(size_t* len, void* arg),
                         void* arg, const char* local, size_t local_len) {
  return Guard([&] {
    rabit_tpu::Check(serialize != nullptr, "LazyCheckPoint: null serializer");
    auto get_global = [serialize, arg]() -> std::string {
      size_t len = 0;
      const char* p = serialize(&len, arg);
      return std::string(p != nullptr ? p : "", p != nullptr ? len : 0);
    };
    if (local != nullptr) {
      std::string l(local, local_len);
      Engine()->LazyCheckPoint(get_global, &l);
    } else {
      Engine()->LazyCheckPoint(get_global, nullptr);
    }
  });
}

int RbtTpuVersionNumber(void) {
  int out = -1;
  Guard([&] { out = Engine()->version_number(); });
  return out;
}

unsigned long long RbtTpuDebugRoutedBytes(void) {
  unsigned long long out = 0;
  Guard([&] {
    auto* base = dynamic_cast<rabit_tpu::BaseEngine*>(Engine());
    if (base != nullptr) out = base->routed_payload_bytes();
  });
  return out;
}

unsigned long long RbtTpuDebugScratchPeakBytes(void) {
  unsigned long long out = 0;
  Guard([&] {
    auto* base = dynamic_cast<rabit_tpu::BaseEngine*>(Engine());
    if (base != nullptr) out = base->scratch_peak_bytes();
  });
  return out;
}

int RbtTpuLastReplayed(void) {
  int out = 0;
  Guard([&] {
    auto* robust = dynamic_cast<rabit_tpu::RobustEngine*>(Engine());
    if (robust != nullptr) out = robust->last_op_replayed() ? 1 : 0;
  });
  return out;
}

int RbtTpuWasRelaunched(void) {
  int out = 0;
  Guard([&] {
    auto* base = dynamic_cast<rabit_tpu::BaseEngine*>(Engine());
    if (base != nullptr) out = base->was_relaunched() ? 1 : 0;
  });
  return out;
}

}  // extern "C"

namespace {

std::unique_ptr<rabit_tpu::IEngine> MakeEngine(const std::string& name) {
  if (name == "empty") {
    return std::make_unique<rabit_tpu::EmptyEngine>();
  }
  if (name == "base") {
    return std::make_unique<rabit_tpu::BaseEngine>();
  }
  if (name == "robust" || name == "native") {
    return std::make_unique<rabit_tpu::RobustEngine>();
  }
  if (name == "mock") {
    return std::make_unique<rabit_tpu::MockEngine>();
  }
  rabit_tpu::Fail("unknown native engine variant: %s", name.c_str());
}

}  // namespace
