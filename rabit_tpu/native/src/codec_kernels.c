/* Compiled codec kernels for the quantized wire path (librabit_codec.so).
 *
 * One tight C translation of rabit_tpu/codec/blockscale.py's hop math:
 * the fused dequantize -> accumulate -> requantize merge, the encode
 * (requantize + residual) and the decode, for the block-scaled formats
 * (int8 / int4 / fp8 e4m3fn / fp8 e5m2) plus the bf16 elementwise
 * merge.  Loaded through the ctypes seam in rabit_tpu/codec/kernel.py
 * (rabit_codec_impl=native|auto); the numpy path stays the reference.
 *
 * BIT-IDENTITY CONTRACT: every arithmetic step reproduces the numpy
 * reference EXACTLY, bit for bit, so replay/retry and the sched_parity
 * guarantees carry over when ranks mix implementations:
 *
 *  - all intermediates are f32 (numpy's ufunc loops never widen);
 *  - comparisons are written as the ternaries numpy's maximum /
 *    minimum / clip inner loops use ((a > b || isnan(a)) ? a : b,
 *    (x < lo) ? lo : ...), NOT fmaxf/fminf, whose NaN and +-0
 *    semantics differ;
 *  - rounding is rintf under the default round-to-nearest-even mode,
 *    which is what np.rint does;
 *  - the fp8 casts implement IEEE RNE with subnormal support, matching
 *    ml_dtypes' float8_e4m3fn / float8_e5m2 astype (verified
 *    exhaustively over all 256 codes and by randomized property tests
 *    in tests/test_native_codec.py);
 *  - the bf16 cast is the Eigen/ml_dtypes round-to-nearest-even
 *    (bias 0x7FFF + lsb) with NaN quieting.
 *
 * Wire layout (numpy structured dtype, packed, little-endian):
 *   int8:  [ f32 scale | block   x i8 ]   stride 4 + block
 *   int4:  [ f32 scale | block/2 x u8 ]   stride 4 + block/2
 *   fp8:   [ f32 scale | block   x u8 ]   stride 4 + block
 * The scale sits at byte offset 0 of each block element and is NOT
 * 4-byte aligned in general (stride 4+block/2 can be odd only if block
 * is even, which the factory enforces — but int4 stride 4+block/2 may
 * still be non-multiple-of-4), so scales move through memcpy.
 */
#include <math.h>
#include <stdint.h>
#include <string.h>

/* keep in sync with rabit_tpu/codec/kernel.py (ABI gate) */
#define RABIT_CODEC_ABI 1

/* factory enforces rabit_codec_block <= 4096 */
#define RABIT_MAX_BLOCK 4096

enum {
    FMT_INT8 = 0,
    FMT_INT4 = 1,
    FMT_E4M3 = 2,
    FMT_E5M2 = 3,
};

int rabit_codec_abi(void) { return RABIT_CODEC_ABI; }

/* ------------------------------------------------------------------ */
/* numpy-semantics helpers                                             */
/* ------------------------------------------------------------------ */

/* np.maximum inner loop: (in1 > in2 || isnan(in1)) ? in1 : in2 */
static inline float np_max(float a, float b)
{
    return (a > b || isnan(a)) ? a : b;
}

/* np.minimum inner loop */
static inline float np_min(float a, float b)
{
    return (a < b || isnan(a)) ? a : b;
}

/* np.clip: below -> lo, above -> hi, NaN passes through */
static inline float np_clip(float x, float lo, float hi)
{
    if (x < lo)
        return lo;
    if (x > hi)
        return hi;
    return x;
}

static inline float load_f32(const uint8_t *p)
{
    float f;
    memcpy(&f, p, 4);
    return f;
}

static inline void store_f32(uint8_t *p, float f)
{
    memcpy(p, &f, 4);
}

/* ------------------------------------------------------------------ */
/* fp8 casts (ml_dtypes-compatible)                                    */
/* ------------------------------------------------------------------ */

/* f32 -> fp8, round to nearest even, subnormal-correct.  man = stored
 * mantissa bits, bias = exponent bias.  Callers clip to +-qmax first,
 * so overflow never occurs; NaN input yields the format's NaN code. */
static inline uint8_t f32_to_fp8(float v, int man, int bias, uint8_t nan_code)
{
    uint32_t u;
    memcpy(&u, &v, 4);
    uint8_t sign = (uint8_t)((u >> 31) << 7);
    int e32 = (int)((u >> 23) & 0xFFu);
    uint32_t m = u & 0x7FFFFFu;
    if (e32 == 0xFF)
        return (uint8_t)(sign | nan_code);
    if (e32 == 0 && m == 0)
        return sign; /* signed zero (f32 subnormals land below via e) */
    int e = e32 - 127 + bias;
    if (e >= 1) {
        /* normal target: RNE the 23-bit mantissa down to man bits */
        int shift = 23 - man;
        uint32_t lsb = (m >> shift) & 1u;
        m += (1u << (shift - 1)) - 1u + lsb;
        if (m >> 23) {
            m &= 0x7FFFFFu;
            e += 1;
        }
        return (uint8_t)(sign | (uint32_t)(e << man) | (m >> shift));
    }
    /* subnormal target: the effective shift grows as e drops below 1;
     * the implicit bit becomes explicit.  A carry out of the mantissa
     * lands on exponent code 1, which is exactly the right encoding. */
    int shift = 23 - man + (1 - e);
    if (shift > 24)
        return sign; /* below half the smallest subnormal: RNE -> 0 */
    m |= 0x800000u;
    uint32_t lsb = (m >> shift) & 1u;
    m += (1u << (shift - 1)) - 1u + lsb;
    return (uint8_t)(sign | (m >> shift));
}

/* fp8 -> f32 (exact).  fn = 1 for e4m3fn (max exponent is a normal
 * value except mantissa-all-ones = NaN, no inf); fn = 0 for the
 * IEEE-style e5m2 (max exponent = inf/NaN). */
static inline float fp8_to_f32(uint8_t b, int man, int bias, int fn)
{
    uint32_t sign = (uint32_t)(b >> 7) << 31;
    int emax = (1 << (7 - man)) - 1;
    int e = (b >> man) & emax;
    uint32_t m = b & ((1u << man) - 1u);
    uint32_t u;
    float f;
    if (e == 0) {
        if (m == 0) {
            u = sign;
        } else {
            /* subnormal: m * 2^(1 - bias - man), exact in f32 */
            f = ldexpf((float)m, 1 - bias - man);
            memcpy(&u, &f, 4);
            u |= sign;
        }
    } else if (e == emax && (!fn || m == (1u << man) - 1u)) {
        /* e5m2 inf/NaN; e4m3fn NaN only at mantissa all-ones */
        u = sign | 0x7F800000u | (m << (23 - man));
        if (m && !fn)
            u = sign | 0x7FC00000u | (m << (23 - man));
        if (fn)
            u = sign | 0x7FC00000u; /* e4m3fn NaN -> quiet f32 NaN */
    } else {
        u = sign | (uint32_t)(e - bias + 127) << 23 | (m << (23 - man));
    }
    memcpy(&f, &u, 4);
    return f;
}

/* ------------------------------------------------------------------ */
/* bf16 (Eigen/ml_dtypes round-to-nearest-even)                        */
/* ------------------------------------------------------------------ */

static inline uint16_t f32_to_bf16(float f)
{
    uint32_t u;
    memcpy(&u, &f, 4);
    if ((u & 0x7FFFFFFFu) > 0x7F800000u)
        return (uint16_t)((u >> 16) | 0x0040u); /* quiet the NaN */
    uint32_t lsb = (u >> 16) & 1u;
    u += 0x7FFFu + lsb;
    return (uint16_t)(u >> 16);
}

static inline float bf16_to_f32(uint16_t h)
{
    uint32_t u = (uint32_t)h << 16;
    float f;
    memcpy(&f, &u, 4);
    return f;
}

/* dst[i] = bf16(f32(dst[i]) + f32(src[i])) — the ml_dtypes bf16 sum
 * apply_op_numpy runs on the elementwise (bf16 codec) wire. */
void rabit_bf16_merge(uint16_t *dst, const uint16_t *src, int64_t n)
{
    for (int64_t i = 0; i < n; i++)
        dst[i] = f32_to_bf16(bf16_to_f32(dst[i]) + bf16_to_f32(src[i]));
}

/* ------------------------------------------------------------------ */
/* block-scaled formats                                                */
/* ------------------------------------------------------------------ */

static inline int64_t fmt_stride(int32_t fmt, int64_t block)
{
    return 4 + (fmt == FMT_INT4 ? block / 2 : block);
}

static inline float fmt_qmax(int32_t fmt)
{
    switch (fmt) {
    case FMT_INT8:
        return 127.0f;
    case FMT_INT4:
        return 7.0f;
    case FMT_E4M3:
        return 448.0f;
    default:
        return 57344.0f; /* FMT_E5M2 */
    }
}

/* dequantize one encoded block into acc[block] (f32), the same f32
 * products the numpy _deq_into produces */
static inline void deq_block(const uint8_t *p, float *acc, int64_t block,
                             int32_t fmt)
{
    float s = load_f32(p);
    const uint8_t *q = p + 4;
    int64_t i;
    switch (fmt) {
    case FMT_INT8:
        for (i = 0; i < block; i++)
            acc[i] = (float)(int8_t)q[i] * s;
        break;
    case FMT_INT4:
        for (i = 0; i < block / 2; i++) {
            acc[2 * i] = (float)((int)(q[i] & 0x0F) - 8) * s;
            acc[2 * i + 1] = (float)((int)(q[i] >> 4) - 8) * s;
        }
        break;
    case FMT_E4M3:
        for (i = 0; i < block; i++)
            acc[i] = fp8_to_f32(q[i], 3, 7, 1) * s;
        break;
    default: /* FMT_E5M2 */
        for (i = 0; i < block; i++)
            acc[i] = fp8_to_f32(q[i], 2, 15, 0) * s;
        break;
    }
}

/* requantize acc[block] into the encoded block at p; when residual is
 * nonzero, acc is rewritten in place into acc - deq(p) using the exact
 * f32 products the next dequantize will produce (deq + residual == acc
 * bitwise — the error-feedback contract). */
static inline void requant_block(uint8_t *p, float *acc, int64_t block,
                                 int32_t fmt, int residual)
{
    float qmax = fmt_qmax(fmt);
    /* np.maximum(acc.max(-1), -acc.min(-1)) with numpy reduce order */
    float maxv = acc[0], minv = acc[0];
    int64_t i;
    for (i = 1; i < block; i++) {
        maxv = np_max(maxv, acc[i]);
        minv = np_min(minv, acc[i]);
    }
    float absmax = np_max(maxv, -minv);
    float scale = absmax / qmax;
    float inv = (absmax > 0.0f) ? qmax / absmax : 0.0f;
    store_f32(p, scale);
    uint8_t *q = p + 4;
    if (fmt == FMT_INT8 || fmt == FMT_INT4) {
        for (i = 0; i < block; i++) {
            float w = np_clip(rintf(acc[i] * inv), -qmax, qmax);
            int8_t q8 = (int8_t)w;
            if (fmt == FMT_INT8)
                q[i] = (uint8_t)q8;
            else if (i & 1)
                q[i / 2] = (uint8_t)(q[i / 2] | ((q8 + 8) << 4));
            else
                q[i / 2] = (uint8_t)(q8 + 8);
            if (residual)
                acc[i] = acc[i] - w * scale;
        }
    } else {
        int man = (fmt == FMT_E4M3) ? 3 : 2;
        int bias = (fmt == FMT_E4M3) ? 7 : 15;
        uint8_t nan_code = (fmt == FMT_E4M3) ? 0x7F : 0x7E;
        for (i = 0; i < block; i++) {
            float w = np_clip(acc[i] * inv, -qmax, qmax);
            uint8_t c = f32_to_fp8(w, man, bias, nan_code);
            q[i] = c;
            if (residual)
                acc[i] = acc[i] - fp8_to_f32(c, man, bias, fmt == FMT_E4M3) * scale;
        }
    }
}

/* Fused hop merge: for each of nblocks encoded blocks, dequantize both
 * sides, accumulate in f32, requantize into dst; with record nonzero
 * the requantization residual is added into hop (f32, nblocks*block,
 * already offset to the merge window).  Mirrors
 * BlockScaleCodec.merge -> _deq_into + add + _requant_into. */
void rabit_bs_merge(uint8_t *dst, const uint8_t *src, int64_t nblocks,
                    int64_t block, int32_t fmt, int32_t record, float *hop)
{
    float acc[RABIT_MAX_BLOCK], work[RABIT_MAX_BLOCK];
    int64_t stride = fmt_stride(fmt, block);
    for (int64_t b = 0; b < nblocks; b++) {
        uint8_t *dp = dst + b * stride;
        deq_block(dp, acc, block, fmt);
        deq_block(src + b * stride, work, block, fmt);
        for (int64_t i = 0; i < block; i++)
            acc[i] += work[i];
        requant_block(dp, acc, block, fmt, record);
        if (record) {
            float *h = hop + b * block;
            for (int64_t i = 0; i < block; i++)
                h[i] += acc[i];
        }
    }
}

/* Encode: requantize acc (nblocks*block f32, already padded and
 * residual-fed by the caller) into the wire blocks; acc is rewritten
 * in place into the encode residual (BlockScaleCodec._enc_into). */
void rabit_bs_encode(uint8_t *blocks, float *acc, int64_t nblocks,
                     int64_t block, int32_t fmt)
{
    int64_t stride = fmt_stride(fmt, block);
    for (int64_t b = 0; b < nblocks; b++)
        requant_block(blocks + b * stride, acc + b * block, block, fmt, 1);
}

/* Decode: out[nblocks*block] = dequantized f32 (BlockScaleCodec._deq). */
void rabit_bs_decode(const uint8_t *blocks, float *out, int64_t nblocks,
                     int64_t block, int32_t fmt)
{
    int64_t stride = fmt_stride(fmt, block);
    for (int64_t b = 0; b < nblocks; b++)
        deq_block(blocks + b * stride, out + b * block, block, fmt);
}
