#include "rabit_tpu/socket.h"

#include <fcntl.h>
#include <netdb.h>

#include <algorithm>
#include <chrono>
#include <thread>

namespace rabit_tpu {

static double g_link_timeout_sec = 600.0;

void SetLinkTimeoutSec(double sec) {
  g_link_timeout_sec = sec;  // <= 0 disables (infinite waits)
}

double GetLinkTimeoutSec() { return g_link_timeout_sec; }

void TcpSocket::SetNonBlocking(bool on) {
  int flags = fcntl(fd_, F_GETFL, 0);
  if (on) {
    fcntl(fd_, F_SETFL, flags | O_NONBLOCK);
  } else {
    fcntl(fd_, F_SETFL, flags & ~O_NONBLOCK);
  }
}

int TcpSocket::BindListen(int port, int backlog) {
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  Check(::bind(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0,
        "bind(%d) failed: %s", port, strerror(errno));
  Check(::listen(fd_, backlog) == 0, "listen failed: %s", strerror(errno));
  socklen_t len = sizeof(addr);
  Check(getsockname(fd_, reinterpret_cast<sockaddr*>(&addr), &len) == 0,
        "getsockname failed: %s", strerror(errno));
  return ntohs(addr.sin_port);
}

static void ResolveHost(const std::string& host, int port, sockaddr_in* out) {
  memset(out, 0, sizeof(*out));
  out->sin_family = AF_INET;
  out->sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host.c_str(), &out->sin_addr) == 1) return;
  addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  Check(getaddrinfo(host.c_str(), nullptr, &hints, &res) == 0 && res != nullptr,
        "cannot resolve host %s", host.c_str());
  out->sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
  freeaddrinfo(res);
}

void TcpSocket::Connect(const std::string& host, int port, int retries,
                        int retry_ms) {
  sockaddr_in addr;
  ResolveHost(host, port, &addr);
  for (int attempt = 0;; ++attempt) {
    if (!valid()) Create();
    if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      return;
    }
    Close();
    if (attempt >= retries) {
      Fail("connect to %s:%d failed after %d attempts: %s", host.c_str(), port,
           attempt + 1, strerror(errno));
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(retry_ms));
  }
}

void TcpSocket::SendAll(const void* data, size_t nbytes) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  size_t sent = 0;
  while (sent < nbytes) {
    ssize_t n = ::send(fd_, p + sent, nbytes - sent, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR)) continue;
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        throw LinkError("send timed out (peer hung?)");
      }
      throw LinkError(std::string("send failed: ") + strerror(errno));
    }
    sent += static_cast<size_t>(n);
  }
}

void TcpSocket::RecvAll(void* data, size_t nbytes) {
  uint8_t* p = static_cast<uint8_t*>(data);
  size_t got = 0;
  while (got < nbytes) {
    ssize_t n = ::recv(fd_, p + got, nbytes - got, 0);
    if (n == 0) throw LinkError("peer closed the link");
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        throw LinkError("recv timed out (peer hung?)");
      }
      throw LinkError(std::string("recv failed: ") + strerror(errno));
    }
    got += static_cast<size_t>(n);
  }
}

void Exchange(TcpSocket& send_sock, const uint8_t* send_data, size_t nsend,
              TcpSocket& recv_sock, uint8_t* recv_buf, size_t nrecv) {
  constexpr size_t kChunk = 256 << 10;
  send_sock.SetNonBlocking(true);
  recv_sock.SetNonBlocking(true);
  size_t sent = 0, got = 0;
  try {
    while (sent < nsend || got < nrecv) {
      pollfd fds[2];
      nfds_t nfds = 0;
      int send_idx = -1, recv_idx = -1;
      if (sent < nsend) {
        send_idx = nfds;
        fds[nfds++] = {send_sock.fd(), POLLOUT, 0};
      }
      if (got < nrecv) {
        if (sent < nsend && recv_sock.fd() == send_sock.fd()) {
          fds[send_idx].events |= POLLIN;
          recv_idx = send_idx;
        } else {
          recv_idx = nfds;
          fds[nfds++] = {recv_sock.fd(), POLLIN, 0};
        }
      }
      int rc = ::poll(fds, nfds,
                      g_link_timeout_sec <= 0
                          ? -1  // timeout disabled
                          : static_cast<int>(g_link_timeout_sec * 1000));
      if (rc == 0) throw LinkError("exchange: poll timed out");
      if (rc < 0) {
        if (errno == EINTR) continue;
        throw LinkError(std::string("poll failed: ") + strerror(errno));
      }
      if (recv_idx >= 0 && (fds[recv_idx].revents & (POLLIN | POLLHUP))) {
        ssize_t n = ::recv(recv_sock.fd(), recv_buf + got, nrecv - got, 0);
        if (n == 0) throw LinkError("exchange: peer closed the link");
        if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
          throw LinkError(std::string("exchange recv failed: ") +
                          strerror(errno));
        }
        if (n > 0) got += static_cast<size_t>(n);
      }
      if (send_idx >= 0 && (fds[send_idx].revents & POLLOUT) && sent < nsend) {
        size_t chunk = std::min(kChunk, nsend - sent);
        ssize_t n =
            ::send(send_sock.fd(), send_data + sent, chunk, MSG_NOSIGNAL);
        if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
          throw LinkError(std::string("exchange send failed: ") +
                          strerror(errno));
        }
        if (n > 0) sent += static_cast<size_t>(n);
      }
      if (recv_idx >= 0 && (fds[recv_idx].revents & POLLERR)) {
        throw LinkError("exchange: socket error");
      }
    }
  } catch (...) {
    send_sock.SetNonBlocking(false);
    recv_sock.SetNonBlocking(false);
    throw;
  }
  send_sock.SetNonBlocking(false);
  recv_sock.SetNonBlocking(false);
}

}  // namespace rabit_tpu
