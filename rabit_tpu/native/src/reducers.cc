// Reducer dispatch: (dtype, op) -> elementwise combine function.
// TPU-native equivalent of the reference's template reducers
// (reference: include/rabit/rabit-inl.h:55-92 op::Max/Min/Sum/BitOR and the
// dtype switch in wrapper/rabit_wrapper.cc:33-118), generated from a
// dtype x op table instead of nested switches at every call site.
#include "rabit_tpu/engine.h"
#include "rabit_tpu/utils.h"

#include <cstring>

namespace rabit_tpu {

size_t ItemSize(DataType dtype) {
  switch (dtype) {
    case DataType::kInt8:
    case DataType::kUInt8:
      return 1;
    case DataType::kFloat16:
    case DataType::kBFloat16:
      return 2;
    case DataType::kInt32:
    case DataType::kUInt32:
    case DataType::kFloat32:
      return 4;
    case DataType::kInt64:
    case DataType::kUInt64:
    case DataType::kFloat64:
      return 8;
  }
  Fail("bad dtype %d", static_cast<int>(dtype));
}

namespace {

template <typename T, typename Op>
void Reduce(void* dst, const void* src, size_t count) {
  T* d = static_cast<T*>(dst);
  const T* s = static_cast<const T*>(src);
  Op op;
  for (size_t i = 0; i < count; ++i) d[i] = op(d[i], s[i]);
}

struct OpMax {
  template <typename T>
  T operator()(T a, T b) const { return a > b ? a : b; }
};
struct OpMin {
  template <typename T>
  T operator()(T a, T b) const { return a < b ? a : b; }
};
struct OpSum {
  template <typename T>
  T operator()(T a, T b) const { return a + b; }
};
struct OpProd {
  template <typename T>
  T operator()(T a, T b) const { return a * b; }
};
struct OpBitOr {
  template <typename T>
  T operator()(T a, T b) const { return a | b; }
};
struct OpBitAnd {
  template <typename T>
  T operator()(T a, T b) const { return a & b; }
};
struct OpBitXor {
  template <typename T>
  T operator()(T a, T b) const { return a ^ b; }
};

template <typename T>
ReduceFn ArithmeticReducer(ReduceOp op) {
  switch (op) {
    case ReduceOp::kMax: return Reduce<T, OpMax>;
    case ReduceOp::kMin: return Reduce<T, OpMin>;
    case ReduceOp::kSum: return Reduce<T, OpSum>;
    case ReduceOp::kProd: return Reduce<T, OpProd>;
    default: return nullptr;
  }
}

template <typename T>
ReduceFn IntegerReducer(ReduceOp op) {
  switch (op) {
    case ReduceOp::kBitOr: return Reduce<T, OpBitOr>;
    case ReduceOp::kBitAnd: return Reduce<T, OpBitAnd>;
    case ReduceOp::kBitXor: return Reduce<T, OpBitXor>;
    default: return ArithmeticReducer<T>(op);
  }
}

}  // namespace

ReduceFn GetReducer(DataType dtype, ReduceOp op) {
  ReduceFn fn = nullptr;
  switch (dtype) {
    case DataType::kInt8: fn = IntegerReducer<int8_t>(op); break;
    case DataType::kUInt8: fn = IntegerReducer<uint8_t>(op); break;
    case DataType::kInt32: fn = IntegerReducer<int32_t>(op); break;
    case DataType::kUInt32: fn = IntegerReducer<uint32_t>(op); break;
    case DataType::kInt64: fn = IntegerReducer<int64_t>(op); break;
    case DataType::kUInt64: fn = IntegerReducer<uint64_t>(op); break;
    case DataType::kFloat32: fn = ArithmeticReducer<float>(op); break;
    case DataType::kFloat64: fn = ArithmeticReducer<double>(op); break;
    // 16-bit float payloads are reduced by the XLA/device path; the host
    // engine treats them as opaque (no arithmetic) — only bit ops allowed.
    case DataType::kFloat16:
    case DataType::kBFloat16:
      fn = nullptr;
      break;
  }
  Check(fn != nullptr, "unsupported (dtype=%d, op=%d) host reduction",
        static_cast<int>(dtype), static_cast<int>(op));
  return fn;
}

}  // namespace rabit_tpu
