#include "rabit_tpu/robust_engine.h"

#include <unistd.h>

#include "rabit_tpu/timer.h"

#include <algorithm>
#include <cstring>

namespace rabit_tpu {

// ---------------------------------------------------------------------------
// consensus machinery
// ---------------------------------------------------------------------------

void RobustEngine::ReduceWord(void* dst, const void* src, size_t count) {
  Word* d = static_cast<Word*>(dst);
  const Word* s = static_cast<const Word*>(src);
  for (size_t i = 0; i < count; ++i) {
    d[i].flags |= s[i].flags;
    if (d[i].seq != s[i].seq) d[i].flags |= kDiffSeq;
    d[i].seq = std::min(d[i].seq, s[i].seq);
    if (d[i].version != s[i].version) d[i].flags |= kDiffVersion;
    d[i].version = std::max(d[i].version, s[i].version);
  }
}

static void ReduceMaxU64(void* dst, const void* src, size_t count) {
  uint64_t* d = static_cast<uint64_t*>(dst);
  const uint64_t* s = static_cast<const uint64_t*>(src);
  for (size_t i = 0; i < count; ++i) d[i] = std::max(d[i], s[i]);
}

RobustEngine::Word RobustEngine::Consensus(uint32_t my_flag) {
  for (;;) {
    Word w{my_flag, seq_, static_cast<uint32_t>(version_)};
    try {
      TreeAllreduceFn(reinterpret_cast<uint8_t*>(&w), 1, sizeof(Word),
                      ReduceWord);
      return w;
    } catch (const LinkError&) {
      Rendezvous("recover");
    }
  }
}

int RobustEngine::AgreeRoot(bool i_have, uint64_t key) {
  // max over (key, lowest-rank tiebreak); 0 == nobody has it.
  uint64_t word = 0;
  if (i_have) {
    word = ((key + 1) << 20) | static_cast<uint64_t>(0xFFFFF - topo_.rank);
  }
  TreeAllreduceFn(reinterpret_cast<uint8_t*>(&word), 1, sizeof(word),
                  ReduceMaxU64);
  if (word == kNoRoot) return -1;
  return static_cast<int>(0xFFFFF - (word & 0xFFFFF));
}

// ---------------------------------------------------------------------------
// the recovery state machine
// ---------------------------------------------------------------------------

bool RobustEngine::RecoverExec(uint32_t my_flag, std::string* recovered) {
  const bool loader = (my_flag & kLoadCheck) != 0;
  for (;;) {
    try {
      Word w = Consensus(my_flag);
      if (w.flags & kLoadCheck) {
        if (my_flag & kCheckPoint) {
          // A relaunched peer is loading while we sit at the checkpoint
          // barrier: commit the pending model FIRST so the loader is
          // served the NEW version.  Serving the stale one would resume
          // it into the just-finished iteration, whose collective
          // results may exist nowhere (device-plane ops are not in the
          // replay cache) — the load must land on the version the
          // barrier is about to commit.  Replication of a local model
          // is skipped on this rare path, like the catch-up commit.
          CommitCheckPoint();
          ServeCheckpointLoad(loader);
          return false;  // barrier complete via the early commit
        }
        bool served = ServeCheckpointLoad(loader);
        if (loader && served) return true;
        continue;
      }
      if (w.flags & kDiffVersion) {
        if (static_cast<uint32_t>(version_) < w.version) {
          if (my_flag & kCheckPoint) {
            // The epoch advanced while we were at the barrier: the commit
            // already happened globally; commit ours now (replication is
            // skipped on this rare recovery path — see header).
            CommitCheckPoint();
            return false;
          }
          Fail("robust: version fell behind (%d < %u) outside a checkpoint "
               "barrier — collective call sequences diverged across ranks",
               version_, w.version);
        }
        continue;  // someone else is catching up
      }
      if (w.flags & kDiffSeq) {
        bool filled = false;
        ServeResult(w.seq, (my_flag == 0) ? recovered : nullptr, &filled);
        if (filled) return true;
        continue;
      }
      // Versions and seqnos are uniform across the world.
      uint32_t agreed = w.flags;
      if (my_flag == 0) {
        if (agreed == 0) return false;  // everyone ready: run the real op
        continue;  // checkpoint/shutdown stragglers still draining
      }
      if (my_flag & kCheckPoint) {
        if (agreed == my_flag) return false;  // barrier complete
        uint32_t mine_wo_local = my_flag & ~kLocalChk;
        if ((agreed & ~kLocalChk) == mine_wo_local &&
            (agreed & kLocalChk) != (my_flag & kLocalChk)) {
          Fail("robust: local checkpoint model must be passed on every rank "
               "or none (reference: LocalModelCheck)");
        }
        continue;
      }
      if (my_flag & kCheckAck) {
        // Commit phase done once nobody is still at the barrier.
        if (!(agreed & kCheckPoint)) return false;
        continue;
      }
      if (my_flag & kShutdown) {
        if (agreed == kShutdown) return false;
        continue;
      }
      continue;
    } catch (const LinkError&) {
      Rendezvous("recover");
    }
  }
}

void RobustEngine::ServeResult(uint32_t seq, std::string* recovered,
                               bool* filled) {
  auto it = cache_.find(seq);
  int root = AgreeRoot(it != cache_.end(), 1);
  Check(root >= 0,
        "robust: result seq %u is cached nowhere — unrecoverable (raise "
        "rabit_global_replica)", seq);
  // Requester-aware routing: only ranks actually replaying seq pull the
  // payload; everyone else exchanges single-byte control messages (the
  // old path tree-broadcast the full result to every rank, O(world x
  // payload) per recovered item).
  const bool i_need = (recovered != nullptr && seq_ == seq);
  std::string blob;
  if (topo_.rank == root) blob = it->second;
  TreeRoutedBroadcast(&blob, root, i_need);
  if (i_need) {
    *recovered = std::move(blob);
    *filled = true;
  }
}

bool RobustEngine::ServeCheckpointLoad(bool i_am_loader) {
  int root = AgreeRoot(has_checkpoint_, static_cast<uint64_t>(version_));
  if (root < 0) {
    // Fresh start everywhere: loaders are satisfied with version 0.
    return true;
  }
  std::string blob;
  // Requester-aware routing: the checkpoint payload streams only along
  // root->loader paths, and the root serializes (MaterializeGlobal)
  // only when a loader actually exists somewhere.
  TreeRoutedBroadcast(
      &blob, root, i_am_loader && topo_.rank != root,
      [this](std::string* out) {
        MaterializeGlobal();  // a peer actually needs the payload now
        out->resize(4);
        uint32_t v = static_cast<uint32_t>(version_);
        memcpy(out->data(), &v, 4);
        *out += global_model_;
      });
  if (i_am_loader && topo_.rank != root) {
    uint32_t bver = 0;
    memcpy(&bver, blob.data(), 4);
    version_ = static_cast<int>(bver);
    global_model_ = blob.substr(4);
    lazy_global_ = nullptr;  // received bytes supersede any stale lazy fn
    has_checkpoint_ = true;
    seq_ = 0;
    HarvestCache();
    cache_.clear();
  }
  // Local-model ring recovery: run whenever anyone anywhere holds local
  // state (all ranks must participate in the ring passes together).
  int lroot = AgreeRoot(!local_store_.empty(), 1);
  if (lroot >= 0) RecoverLocal();
  return i_am_loader;
}

// ---------------------------------------------------------------------------
// collectives with replay
// ---------------------------------------------------------------------------

bool RobustEngine::Striped(uint32_t seq) const {
  int round = std::max(topo_.world / num_global_replica_, 1);
  return static_cast<int>(seq) % round == topo_.rank % round;
}

void RobustEngine::PushResultOwned(std::string&& blob) {
  cache_[seq_] = std::move(blob);
}

void RobustEngine::StashRetired(std::string&& blob) {
  // Keep the biggest kPoolSize retired payload buffers for reuse.
  for (auto& slot : pool_) {
    if (blob.capacity() > slot.capacity()) std::swap(slot, blob);
  }
}

void RobustEngine::RefillAttempt() {
  // attempt_ was typically moved into the cache by the previous op,
  // leaving it with the 15-byte SSO capacity of a moved-from libstdc++
  // string — NOT zero, so no capacity()==0 test can detect that state.
  // Swap in the biggest pooled buffer whenever it beats what attempt_
  // holds, so the upcoming assign reuses warm pages instead of
  // fresh-allocating (fresh 4 MB costs ~2 ms of kernel page zeroing +
  // faults per op on the benchmark box).  swap, not move-assign: when
  // attempt_ does hold real capacity it parks in the pool instead of
  // being freed.
  auto* best = &pool_[0];
  for (auto& slot : pool_) {
    if (slot.capacity() > best->capacity()) best = &slot;
  }
  if (best->capacity() > attempt_.capacity()) {
    std::swap(attempt_, *best);
    pool_hits_ += 1;  // observable: tests pin the recycle behavior
  }
}

void RobustEngine::HarvestCache() {
  // Move the biggest retiring buffers into the pool so the next version
  // span runs warm even in the retention regime (apps that checkpoint
  // every iteration — the reference's usage pattern — then never
  // fresh-allocate payload memory after the first span).
  for (auto& [seq, blob] : cache_) {
    (void) seq;
    StashRetired(std::move(blob));
  }
}

void RobustEngine::PruneStale() {
  // Striped replication bounds memory: drop everything outside this
  // rank's stripe (reference: src/allreduce_robust.cc:86-89).  Runs at
  // the TOP of each collective, after the consensus round — never at
  // push time: between the push and the next consensus the newest
  // result must stay on every rank that completed the op, because a
  // peer that died mid-op recovers it from *any* completer (the stripe
  // keepers for that seq may be exactly the ranks that errored).  The
  // reference's DropLast sits at the same post-consensus boundary.
  for (auto it = cache_.begin(); it != cache_.end();) {
    if (!Striped(it->first)) {
      // Recycle the pruned entry's allocation into the buffer pool
      // (attempt_ was usually just moved into the cache, leaving it
      // empty): in the striped steady state the hot path then needs no
      // fresh payload allocations at all (the raised M_TRIM_THRESHOLD
      // already keeps freed chunks mapped; this removes the free/malloc
      // round trip on top).  The pool holds several buffers so the ops
      // whose result the stripe KEEPS — which recycle nothing — still
      // find a warm buffer for their next attempt.
      StashRetired(std::move(it->second));
      it = cache_.erase(it);
    } else {
      ++it;
    }
  }
}

void RobustEngine::PushResult(const uint8_t* buf, size_t nbytes) {
  PushResultOwned(std::string(reinterpret_cast<const char*>(buf), nbytes));
}

bool RobustEngine::RunCollective(uint8_t* buf, size_t nbytes,
                                 const std::function<void()>& real_op,
                                 bool initial_recover) {
  std::string recovered;
  if (initial_recover && RecoverExec(0, &recovered)) {
    last_replayed_ = true;
    Check(recovered.size() == nbytes,
          "robust: recovered result size %zu != expected %zu — collective "
          "call sequences diverged across ranks", recovered.size(), nbytes);
    memcpy(buf, recovered.data(), nbytes);
    return true;
  }
  for (;;) {
    try {
      real_op();
      return false;
    } catch (const LinkError&) {
      Rendezvous("recover");
      recovered.clear();
      if (RecoverExec(0, &recovered)) {
        Check(recovered.size() == nbytes,
              "robust: recovered result size %zu != expected %zu",
              recovered.size(), nbytes);
        memcpy(buf, recovered.data(), nbytes);
        return true;
      }
    }
  }
}

// Measurement-only switch behind doc/benchmarks.md "round-5 tax
// decomposition": RABIT_DIAG_STEADYSTATE=no_consensus|no_cache|
// base_path disables ONE stage of the robust Allreduce so its cost can
// be isolated on a live harness.  Every mode breaks the fault-tolerance
// contract (skipped consensus/cache means replay cannot serve peers) —
// never set it outside a benchmark.
static int DiagMode() {
  static int mode = [] {
    const char* d = std::getenv("RABIT_DIAG_STEADYSTATE");
    if (d == nullptr) return 0;
    if (strcmp(d, "no_consensus") == 0) return 1;
    if (strcmp(d, "no_cache") == 0) return 2;
    if (strcmp(d, "base_path") == 0) return 3;
    return 0;
  }();
  return mode;
}

void RobustEngine::Allreduce(void* buf, size_t count, DataType dtype,
                             ReduceOp op, const PrepareFn& prepare) {
  Verify(seq_);
  last_replayed_ = false;
  if (topo_.world == 1) {
    if (prepare) prepare();
    seq_ += 1;
    return;
  }
  size_t nbytes = count * ItemSize(dtype);
  uint8_t* p = static_cast<uint8_t*>(buf);
  if (DiagMode() == 3) {  // pure base path: no consensus/copies/cache
    if (prepare) prepare();
    if (nbytes <= kTreeRingCrossoverBytes || topo_.world == 2) {
      TreeAllreduce(p, count, dtype, op);
    } else {
      RingAllreduce(p, count, dtype, op);
    }
    seq_ += 1;
    return;
  }
  std::string recovered;
  if (DiagMode() != 1 && RecoverExec(0, &recovered)) {
    last_replayed_ = true;
    Check(recovered.size() == nbytes, "robust: recovered allreduce size "
          "%zu != %zu", recovered.size(), nbytes);
    memcpy(p, recovered.data(), nbytes);
    PruneStale();
    PushResultOwned(std::move(recovered));
    seq_ += 1;
    return;
  }
  PruneStale();
  if (prepare) prepare();
  // Run the op on attempt_ — a copy of the prepared input that doubles
  // as the future cache entry, so the user buffer stays pristine for
  // retry after a failed attempt and peak memory per op is user buffer
  // + one payload copy, not two (the reference folds its retry temp
  // into the result cache the same way, src/allreduce_robust.cc:91-97).
  // attempt_ draws retired buffers from the pool (PruneStale /
  // HarvestCache), so the striped steady state and checkpointing apps
  // run with zero fresh payload allocations.  (An in-place-on-the-user
  // -buffer variant with chunk-level result mirroring inside the ring
  // exchange was measured SLOWER on the 1-core harness: per-chunk copy
  // work inside the duplex streaming loop lengthens the synchronous
  // ring pipeline, where a straight-line memcpy outside it does not.)
  RefillAttempt();
  auto real_op = [&] {
    attempt_.assign(reinterpret_cast<char*>(p), nbytes);  // pristine input
    uint8_t* t = reinterpret_cast<uint8_t*>(attempt_.data());
    if (nbytes <= kTreeRingCrossoverBytes || topo_.world == 2) {
      TreeAllreduce(t, count, dtype, op);
    } else {
      RingAllreduce(t, count, dtype, op);
    }
  };
  // The RecoverExec above already aligned the world; skip the
  // duplicate initial consensus round inside RunCollective.
  if (!RunCollective(p, nbytes, real_op, /*initial_recover=*/false)) {
    memcpy(p, attempt_.data(), nbytes);
    if (DiagMode() != 2) PushResultOwned(std::move(attempt_));
  } else {
    if (DiagMode() != 2) PushResult(p, nbytes);
  }
  seq_ += 1;
}

void RobustEngine::AllreduceCustom(void* buf, size_t count, size_t item_size,
                                   const CustomReducer& reducer,
                                   const PrepareFn& prepare) {
  Verify(seq_);
  last_replayed_ = false;
  if (topo_.world == 1) {
    if (prepare) prepare();
    seq_ += 1;
    return;
  }
  size_t nbytes = count * item_size;
  uint8_t* p = static_cast<uint8_t*>(buf);
  std::string recovered;
  if (RecoverExec(0, &recovered)) {
    last_replayed_ = true;
    Check(recovered.size() == nbytes, "robust: recovered custom allreduce "
          "size %zu != %zu", recovered.size(), nbytes);
    memcpy(p, recovered.data(), nbytes);
    PruneStale();
    PushResultOwned(std::move(recovered));
    seq_ += 1;
    return;
  }
  PruneStale();
  if (prepare) prepare();
  RefillAttempt();
  auto real_op = [&] {
    attempt_.assign(reinterpret_cast<char*>(p), nbytes);  // pristine input
    TreeAllreduceFn(reinterpret_cast<uint8_t*>(attempt_.data()), count,
                    item_size, reducer);
  };
  if (!RunCollective(p, nbytes, real_op, /*initial_recover=*/false)) {
    memcpy(p, attempt_.data(), nbytes);
    PushResultOwned(std::move(attempt_));
  } else {
    PushResult(p, nbytes);
  }
  seq_ += 1;
}

void RobustEngine::Broadcast(std::string* data, int root) {
  Verify(seq_);
  last_replayed_ = false;
  if (topo_.world == 1) {
    seq_ += 1;
    return;
  }
  std::string recovered;
  if (RecoverExec(0, &recovered)) {
    last_replayed_ = true;
    *data = recovered;
    PruneStale();
    PushResultOwned(std::move(recovered));
    seq_ += 1;
    return;
  }
  PruneStale();
  // The broadcast streams into attempt_, which then MOVES into the
  // result cache: one payload copy per op (root: into attempt_;
  // non-root: attempt_ -> *data) instead of the former two (payload +
  // cache snapshot).  Root's *data is never touched, so a retry after
  // a mid-op failure just re-copies it.
  RefillAttempt();
  for (;;) {
    try {
      if (topo_.rank == root) {
        attempt_.assign(data->data(), data->size());
      } else {
        attempt_.clear();
      }
      TreeBroadcast(&attempt_, root);
      break;
    } catch (const LinkError&) {
      Rendezvous("recover");
      recovered.clear();
      if (RecoverExec(0, &recovered)) {
        attempt_ = std::move(recovered);
        break;
      }
    }
  }
  if (topo_.rank != root) *data = attempt_;
  PushResultOwned(std::move(attempt_));
  seq_ += 1;
}

void RobustEngine::Allgather(const void* mine, size_t nbytes, void* out) {
  Verify(seq_);
  last_replayed_ = false;
  uint8_t* p = static_cast<uint8_t*>(out);
  if (topo_.world == 1) {
    memcpy(p, mine, nbytes);
    seq_ += 1;
    return;
  }
  size_t total = nbytes * static_cast<size_t>(topo_.world);
  std::string recovered;
  if (RecoverExec(0, &recovered)) {
    last_replayed_ = true;
    Check(recovered.size() == total, "robust: recovered allgather size "
          "%zu != %zu", recovered.size(), total);
    memcpy(p, recovered.data(), total);
    PruneStale();
    PushResultOwned(std::move(recovered));
    seq_ += 1;
    return;
  }
  PruneStale();
  // Gather into attempt_ (input `mine` stays pristine by construction,
  // so retries need no snapshot), copy out once, move into the cache.
  RefillAttempt();
  auto real_op = [&] {
    attempt_.resize(total);
    BaseEngine::Allgather(mine, nbytes, attempt_.data());
  };
  if (!RunCollective(p, total, real_op, /*initial_recover=*/false)) {
    memcpy(p, attempt_.data(), total);
    PushResultOwned(std::move(attempt_));
  } else {
    PushResult(p, total);
  }
  seq_ += 1;
}

// ---------------------------------------------------------------------------
// checkpointing
// ---------------------------------------------------------------------------

void RobustEngine::MaterializeGlobal() {
  if (lazy_global_) {
    global_model_ = lazy_global_();
    lazy_global_ = nullptr;
  }
}

void RobustEngine::CommitCheckPoint() {
  if (pending_lazy_) {
    lazy_global_ = std::move(pending_lazy_);
    pending_lazy_ = nullptr;
    global_model_.clear();
  } else {
    global_model_ = pending_global_;
    lazy_global_ = nullptr;
  }
  has_checkpoint_ = true;
  version_ += 1;
  if (has_pending_local_) {
    local_store_[topo_.rank] = {version_, pending_local_};
    local_model_ = pending_local_;  // world-of-1 load path reads this
    has_local_ = true;
  }
  HarvestCache();
  cache_.clear();
  seq_ = 0;
}

void RobustEngine::CheckPoint(const std::string* global_model,
                              const std::string* local_model) {
  pending_global_ = global_model ? *global_model : std::string();
  pending_lazy_ = nullptr;
  CheckPointImpl(local_model);
}

void RobustEngine::LazyCheckPoint(
    const std::function<std::string()>& get_global,
    const std::string* local_model) {
  pending_global_.clear();
  pending_lazy_ = get_global;
  CheckPointImpl(local_model);
}

void RobustEngine::CheckPointImpl(const std::string* local_model) {
  Verify(kSeqCheckPoint);
  has_pending_local_ = local_model != nullptr;
  pending_local_ = local_model ? *local_model : std::string();
  if (topo_.world == 1) {
    CommitCheckPoint();
    return;
  }
  uint32_t flag = kCheckPoint | (has_pending_local_ ? uint32_t{kLocalChk} : 0u);
  int version_before = version_;
  RecoverExec(flag, nullptr);
  if (version_ == version_before) {  // not committed via catch-up
    if (has_pending_local_) {
      // Every rank exits the barrier on the same consensus round, so the
      // ring replication passes are globally aligned.
      local_store_[topo_.rank] = {version_ + 1, pending_local_};
      try {
        ReplicateLocal();
      } catch (const LinkError&) {
        // Degraded: this checkpoint's local blobs are under-replicated
        // until the next one; global safety is unaffected.
        Rendezvous("recover");
      }
    }
    CommitCheckPoint();
  }
  RecoverExec(kCheckAck, nullptr);
}

int RobustEngine::LoadCheckPoint(std::string* global_model,
                                 std::string* local_model) {
  Verify(kSeqLoadCheck);
  if (topo_.world == 1) {
    return BaseEngine::LoadCheckPoint(global_model, local_model);
  }
  RecoverExec(kLoadCheck, nullptr);
  if (!has_checkpoint_) return 0;
  MaterializeGlobal();
  if (global_model) *global_model = global_model_;
  if (local_model) {
    auto it = local_store_.find(topo_.rank);
    if (it != local_store_.end() && it->second.first == version_) {
      *local_model = it->second.second;
    }
  }
  seq_ = 0;
  return version_;
}

// ---------------------------------------------------------------------------
// local-model ring replication
// ---------------------------------------------------------------------------

void RobustEngine::RingPassBlobs(bool backward) {
  // Serialize the whole local store; exchange with ring neighbours
  // (send backward = toward ring_prev, recv from ring_next; or the
  // reverse), then merge keeping the highest version per origin.
  std::string out;
  uint32_t n = static_cast<uint32_t>(local_store_.size());
  out.append(reinterpret_cast<char*>(&n), 4);
  for (const auto& [origin, entry] : local_store_) {
    uint32_t o = static_cast<uint32_t>(origin);
    uint32_t v = static_cast<uint32_t>(entry.first);
    uint64_t len = entry.second.size();
    out.append(reinterpret_cast<char*>(&o), 4);
    out.append(reinterpret_cast<char*>(&v), 4);
    out.append(reinterpret_cast<char*>(&len), 8);
    out += entry.second;
  }
  TcpSocket& send_sock =
      links_.at(backward ? topo_.ring_prev : topo_.ring_next);
  TcpSocket& recv_sock =
      links_.at(backward ? topo_.ring_next : topo_.ring_prev);
  uint64_t out_size = out.size(), in_size = 0;
  Exchange(send_sock, reinterpret_cast<uint8_t*>(&out_size), 8, recv_sock,
           reinterpret_cast<uint8_t*>(&in_size), 8);
  std::string in(in_size, '\0');
  Exchange(send_sock, reinterpret_cast<const uint8_t*>(out.data()),
           out.size(), recv_sock, reinterpret_cast<uint8_t*>(in.data()),
           in_size);
  size_t pos = 0;
  uint32_t cnt = 0;
  memcpy(&cnt, in.data(), 4);
  pos = 4;
  for (uint32_t i = 0; i < cnt; ++i) {
    uint32_t o = 0, v = 0;
    uint64_t len = 0;
    memcpy(&o, in.data() + pos, 4);
    memcpy(&v, in.data() + pos + 4, 4);
    memcpy(&len, in.data() + pos + 8, 8);
    pos += 16;
    auto it = local_store_.find(static_cast<int>(o));
    if (it == local_store_.end() ||
        it->second.first < static_cast<int>(v)) {
      local_store_[static_cast<int>(o)] = {static_cast<int>(v),
                                           in.substr(pos, len)};
    }
    pos += len;
  }
}

void RobustEngine::ReplicateLocal() {
  // Push blobs forward so ranks r+1..r+K hold origin r's state.
  for (int p = 0; p < num_local_replica_; ++p) RingPassBlobs(false);
  // Prune to the origins this rank is responsible for.
  for (auto it = local_store_.begin(); it != local_store_.end();) {
    int dist = ((topo_.rank - it->first) % topo_.world + topo_.world) %
               topo_.world;
    if (dist > num_local_replica_) {
      it = local_store_.erase(it);
    } else {
      ++it;
    }
  }
}

void RobustEngine::RecoverLocal() {
  // Backward floods bring each origin's blob back to the origin (any
  // survivor within K successors holds it), then forward floods restore
  // the replication invariant.
  for (int p = 0; p < num_local_replica_; ++p) RingPassBlobs(true);
  ReplicateLocal();
  has_local_ = local_store_.count(topo_.rank) != 0;
}

// ---------------------------------------------------------------------------
// lifecycle
// ---------------------------------------------------------------------------

void RobustEngine::Init(
    const std::vector<std::pair<std::string, std::string>>& params) {
  for (const auto& [key, val] : params) {
    if (key == "rabit_global_replica") num_global_replica_ = std::stoi(val);
    if (key == "rabit_local_replica") num_local_replica_ = std::stoi(val);
  }
  Check(num_global_replica_ > 0, "rabit_global_replica must be >= 1");
  Check(num_local_replica_ > 0, "rabit_local_replica must be >= 1");
  BaseEngine::Init(params);
}

void RobustEngine::Shutdown() {
  if (topo_.world > 1 && !links_.empty()) {
    try {
      RecoverExec(kShutdown, nullptr);
    } catch (const Error&) {
      // best effort: peers may already be gone
    }
  }
  BaseEngine::Shutdown();
}

// ---------------------------------------------------------------------------
// mock engine (deterministic fault injection)
// ---------------------------------------------------------------------------

void MockEngine::Init(
    const std::vector<std::pair<std::string, std::string>>& params) {
  const char* trial = std::getenv("RABIT_NUM_TRIAL");
  if (trial != nullptr) num_trial_ = std::atoi(trial);
  RobustEngine::Init(params);
  for (const auto& [key, val] : params) {
    if (key == "report_stats" || key == "rabit_report_stats") {
      report_stats_ = std::stoi(val) != 0;
      continue;
    }
    if (key != "mock" && key != "rabit_mock" && key != "rabit_num_trial") {
      continue;
    }
    if (key == "rabit_num_trial") {
      num_trial_ = std::stoi(val);
      continue;
    }
    // mock=rank,version,seqno,ndeath — ';'-separated list accepted.
    std::string rest = val;
    while (!rest.empty()) {
      auto semi = rest.find(';');
      std::string one = rest.substr(0, semi);
      rest = (semi == std::string::npos) ? "" : rest.substr(semi + 1);
      int f[4] = {0, 0, 0, 0};
      if (sscanf(one.c_str(), "%d,%d,%d,%d", &f[0], &f[1], &f[2], &f[3]) ==
          4 && f[0] == rank()) {
        kill_points_.insert({f[1], static_cast<uint32_t>(f[2]), f[3]});
      }
    }
  }
}

void MockEngine::Allreduce(void* buf, size_t count, DataType dtype,
                           ReduceOp op, const PrepareFn& prepare) {
  double t0 = GetTime();
  RobustEngine::Allreduce(buf, count, dtype, op, prepare);
  tsum_allreduce_ += GetTime() - t0;
}

void MockEngine::AllreduceCustom(void* buf, size_t count, size_t item_size,
                                 const CustomReducer& reducer,
                                 const PrepareFn& prepare) {
  double t0 = GetTime();
  RobustEngine::AllreduceCustom(buf, count, item_size, reducer, prepare);
  tsum_allreduce_ += GetTime() - t0;
}

void MockEngine::Allgather(const void* mine, size_t nbytes, void* out) {
  double t0 = GetTime();
  RobustEngine::Allgather(mine, nbytes, out);
  tsum_allreduce_ += GetTime() - t0;
}

void MockEngine::Broadcast(std::string* data, int root) {
  double t0 = GetTime();
  RobustEngine::Broadcast(data, root);
  tsum_allreduce_ += GetTime() - t0;
}

void MockEngine::ReportVersionStats(double t0, double t1,
                                    size_t chkpt_bytes) {
  if (report_stats_) {
    char line[256];
    std::snprintf(line, sizeof(line),
                  "[mock] rank %d version %d: allreduce_tcost=%.6f "
                  "check_tcost=%.6f between_chpt=%.6f chkpt_bytes=%zu "
                  "pool_hits_total=%zu",
                  rank(), version_number(), tsum_allreduce_,
                  t1 - t0, time_checkpoint_ == 0.0 ? 0.0
                                                   : t0 - time_checkpoint_,
                  chkpt_bytes, pool_hits());
    TrackerPrint(line);
    tsum_allreduce_ = 0.0;
  }
  time_checkpoint_ = t1;
}

void MockEngine::CheckPoint(const std::string* global_model,
                            const std::string* local_model) {
  double t0 = GetTime();
  RobustEngine::CheckPoint(global_model, local_model);
  size_t bytes = (global_model != nullptr ? global_model->size() : 0) +
                 (local_model != nullptr ? local_model->size() : 0);
  ReportVersionStats(t0, GetTime(), bytes);
}

void MockEngine::LazyCheckPoint(
    const std::function<std::string()>& get_global,
    const std::string* local_model) {
  double t0 = GetTime();
  RobustEngine::LazyCheckPoint(get_global, local_model);
  // payload is not serialized on this path (that is the point of lazy);
  // report only the local part's size
  ReportVersionStats(t0, GetTime(),
                     local_model != nullptr ? local_model->size() : 0);
}

void MockEngine::Verify(uint32_t seqno) {
  auto it = kill_points_.find({version_, seqno, num_trial_});
  if (it == kill_points_.end()) return;
  fprintf(stderr, "[mock] rank %d killed at version=%d seq=%u trial=%d\n",
          rank(), version_, seqno, num_trial_);
  fflush(stderr);
  _exit(254);  // the keepalive launcher's restart code
}

}  // namespace rabit_tpu
