#include "rabit_tpu/base_engine.h"

#include <malloc.h>
#include <unistd.h>

#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdlib>
#include <cstring>

namespace rabit_tpu {

static std::string EnvOr(const char* key, const std::string& fallback) {
  const char* v = std::getenv(key);
  return v ? std::string(v) : fallback;
}

size_t BaseEngine::ParseByteSize(const std::string& s) {
  Check(!s.empty(), "empty byte-size value");
  size_t pos = 0;
  double v = 0;
  try {
    v = std::stod(s, &pos);
  } catch (const std::exception&) {
    Fail("bad byte-size value %s (want e.g. 256MB, 64KB, 1048576)",
         s.c_str());
  }
  std::string suffix = s.substr(pos);
  while (!suffix.empty() && suffix.front() == ' ') suffix.erase(0, 1);
  for (char& c : suffix) c = static_cast<char>(toupper(c));
  double mult = 1.0;
  if (suffix.empty() || suffix == "B") mult = 1.0;
  else if (suffix == "K" || suffix == "KB") mult = 1024.0;
  else if (suffix == "M" || suffix == "MB") mult = 1024.0 * 1024.0;
  else if (suffix == "G" || suffix == "GB") mult = 1024.0 * 1024.0 * 1024.0;
  else Fail("bad byte-size suffix in %s (want B/KB/MB/GB)", s.c_str());
  double bytes = v * mult;
  // stod accepts "inf"/"nan", and e.g. "1e30GB" overflows: converting an
  // out-of-range double to size_t is undefined behavior — reject first
  Check(std::isfinite(bytes), "byte size must be finite: %s", s.c_str());
  Check(bytes >= 1.0, "byte size must be >= 1 byte: %s", s.c_str());
  Check(bytes <= 9.0e15,  // < 2^53: exactly representable, < SIZE_MAX
        "byte size out of range: %s", s.c_str());
  return static_cast<size_t>(bytes);
}

void BaseEngine::SetParam(const std::string& name, const std::string& value) {
  if (name == "rabit_tracker_uri") tracker_uri_ = value;
  if (name == "rabit_tracker_port") tracker_port_ = std::stoi(value);
  if (name == "rabit_task_id") task_id_ = value;
  if (name == "rabit_world_size") world_hint_ = std::stoi(value);
  if (name == "rabit_timeout_sec") link_timeout_sec_ = std::stod(value);
  if (name == "rabit_reduce_buffer") reduce_buffer_bytes_ = ParseByteSize(value);
}

void BaseEngine::Init(
    const std::vector<std::pair<std::string, std::string>>& params) {
#ifdef __GLIBC__
  // Keep multi-MB collective buffers on the heap instead of per-call
  // mmap/munmap: fresh mappings cost ~ms of page faults per op at the
  // payload sizes the robust cache and ring scratch churn through.
  mallopt(M_MMAP_THRESHOLD, 64 << 20);
  mallopt(M_TRIM_THRESHOLD, 64 << 20);
#endif
  tracker_uri_ = EnvOr("RABIT_TRACKER_URI", "");
  std::string port = EnvOr("RABIT_TRACKER_PORT", "0");
  tracker_port_ = std::stoi(port);
  task_id_ = EnvOr("RABIT_TASK_ID", "0");
  world_hint_ = std::stoi(EnvOr("RABIT_WORLD_SIZE", "0"));
  link_timeout_sec_ = std::stod(EnvOr("RABIT_TIMEOUT_SEC", "600"));
  reduce_buffer_bytes_ = ParseByteSize(EnvOr("RABIT_REDUCE_BUFFER", "256MB"));
  for (const auto& kv : params) SetParam(kv.first, kv.second);
  Check(!tracker_uri_.empty(), "native engine needs rabit_tracker_uri");
  SetLinkTimeoutSec(link_timeout_sec_);  // poll-based Exchange path
  Rendezvous(InitCmd());
}

std::string BaseEngine::host() const {
  char buf[256];
  gethostname(buf, sizeof(buf));
  return std::string(buf);
}

TcpSocket BaseEngine::TrackerConnect(const std::string& cmd) {
  TcpSocket sock;
  sock.Connect(tracker_uri_, tracker_port_);
  sock.SendU32(kMagic);
  sock.SendStr(cmd);
  sock.SendStr(task_id_);
  sock.SendU32(static_cast<uint32_t>(world_hint_));
  return sock;
}

void BaseEngine::Rendezvous(const std::string& cmd) {
  CloseLinks();
  TcpSocket listener;
  listener.Create();
  listener.SetReuseAddr();
  int my_port = listener.BindListen();

  // Advertised address: loopback for local jobs, else the interface that
  // routes to the tracker (UDP-connect trick; see pysocket.py).
  std::string my_host = "127.0.0.1";
  if (tracker_uri_ != "127.0.0.1" && tracker_uri_ != "localhost") {
    int ufd = ::socket(AF_INET, SOCK_DGRAM, 0);
    sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(tracker_port_));
    if (inet_pton(AF_INET, tracker_uri_.c_str(), &addr.sin_addr) == 1 &&
        ::connect(ufd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      socklen_t len = sizeof(addr);
      getsockname(ufd, reinterpret_cast<sockaddr*>(&addr), &len);
      char buf[INET_ADDRSTRLEN];
      inet_ntop(AF_INET, &addr.sin_addr, buf, sizeof(buf));
      my_host = buf;
    }
    ::close(ufd);
  }

  TcpSocket tracker = TrackerConnect(cmd);
  tracker.SendStr(my_host);
  tracker.SendU32(static_cast<uint32_t>(my_port));

  topo_.rank = static_cast<int>(tracker.RecvU32());
  topo_.world = static_cast<int>(tracker.RecvU32());
  topo_.parent = static_cast<int>(tracker.RecvU32());
  uint32_t nneighbor = tracker.RecvU32();
  topo_.tree_links.clear();
  for (uint32_t i = 0; i < nneighbor; ++i) {
    topo_.tree_links.push_back(static_cast<int>(tracker.RecvU32()));
  }
  topo_.ring_prev = static_cast<int>(tracker.RecvU32());
  topo_.ring_next = static_cast<int>(tracker.RecvU32());
  uint32_t nconnect = tracker.RecvU32();
  struct Peer {
    int rank;
    std::string host;
    int port;
  };
  std::vector<Peer> peers;
  for (uint32_t i = 0; i < nconnect; ++i) {
    Peer p;
    p.rank = static_cast<int>(tracker.RecvU32());
    p.host = tracker.RecvStr();
    p.port = static_cast<int>(tracker.RecvU32());
    peers.push_back(std::move(p));
  }
  uint32_t naccept = tracker.RecvU32();
  relaunched_ = relaunched_ || tracker.RecvU32() != 0;
  tracker.Close();

  // Outgoing links (to lower ranks, already listening).
  for (const Peer& p : peers) {
    TcpSocket s;
    s.Connect(p.host, p.port);
    s.SetNoDelay();
    s.SetKeepAlive();
    s.SetIOTimeout(link_timeout_sec_);
    s.SendU32(kMagic);
    s.SendU32(static_cast<uint32_t>(topo_.rank));
    Check(s.RecvU32() == kMagic, "link handshake: bad magic");
    uint32_t got = s.RecvU32();
    Check(static_cast<int>(got) == p.rank, "link handshake: rank mismatch");
    links_.emplace(p.rank, std::move(s));
  }
  // Incoming links (from higher ranks).
  for (uint32_t i = 0; i < naccept; ++i) {
    TcpSocket s = listener.Accept();
    s.SetNoDelay();
    s.SetKeepAlive();
    s.SetIOTimeout(link_timeout_sec_);
    Check(s.RecvU32() == kMagic, "link handshake: bad magic");
    int peer_rank = static_cast<int>(s.RecvU32());
    s.SendU32(kMagic);
    s.SendU32(static_cast<uint32_t>(topo_.rank));
    links_.emplace(peer_rank, std::move(s));
  }
  listener.Close();
}

void BaseEngine::CloseLinks() {
  links_.clear();  // TcpSocket dtor closes
}

void BaseEngine::Shutdown() {
  if (!tracker_uri_.empty()) {
    try {
      TcpSocket sock = TrackerConnect("shutdown");
      sock.Close();
    } catch (const Error&) {
      // tracker already gone — nothing to report to
    }
  }
  CloseLinks();
}

void BaseEngine::TrackerPrint(const std::string& msg) {
  TcpSocket sock = TrackerConnect("print");
  sock.SendStr(msg);
  sock.Close();
}

std::vector<int> BaseEngine::Children() const {
  std::vector<int> out;
  for (int r : topo_.tree_links) {
    if (r != topo_.parent) out.push_back(r);
  }
  return out;
}

int BaseEngine::TowardRoot(int root) const {
  // First hop on the binary-heap-tree path toward `root`; see
  // pysocket.py _toward for the derivation.
  int r = root, prev = static_cast<int>(kNone);
  while (r > topo_.rank) {
    prev = r;
    r = (r - 1) / 2;
  }
  return (r == topo_.rank) ? prev : topo_.parent;
}

void BaseEngine::Allreduce(void* buf, size_t count, DataType dtype,
                           ReduceOp op, const PrepareFn& prepare) {
  if (prepare) prepare();
  if (topo_.world == 1) return;
  size_t nbytes = count * ItemSize(dtype);
  uint8_t* p = static_cast<uint8_t*>(buf);
  if (nbytes <= kTreeRingCrossoverBytes || topo_.world == 2) {
    TreeAllreduce(p, count, dtype, op);
  } else {
    RingAllreduce(p, count, dtype, op);
  }
}

void BaseEngine::AllreduceCustom(void* buf, size_t count, size_t item_size,
                                 const CustomReducer& reducer,
                                 const PrepareFn& prepare) {
  if (prepare) prepare();
  if (topo_.world == 1) return;
  // Custom payloads take the tree path: the reducer need not be
  // element-aligned-commutative across ring chunk boundaries in the
  // SerializeReducer case, and they are typically small.
  TreeAllreduceFn(static_cast<uint8_t*>(buf), count, item_size, reducer);
}

void BaseEngine::TreeAllreduce(uint8_t* buf, size_t count, DataType dtype,
                               ReduceOp op) {
  TreeAllreduceFn(buf, count, ItemSize(dtype), GetReducer(dtype, op));
}

void BaseEngine::TreeAllreduceFn(uint8_t* buf, size_t count, size_t item_size,
                                 const CustomReducer& reduce) {
  // Zero-size payloads move no wire bytes on any rank (also guards the
  // chunk_items division below).
  if (count == 0 || item_size == 0) return;
  // Chunked so per-op scratch never exceeds the rabit_reduce_buffer
  // budget (reference: reduce_buffer chunking, src/allreduce_base.cc:
  // 31,117-132,326-491).  Two strictly one-directional phases — every
  // chunk reduces up the tree, then every chunk broadcasts down — so
  // blocking sockets cannot deadlock, and chunks stream across tree
  // levels (a node forwards chunk k upward before receiving chunk k+1
  // from its children).  Per-link byte streams are identical to the
  // unchunked protocol, so peers with different budgets interoperate.
  size_t chunk_items =
      std::min(std::max<size_t>(reduce_buffer_bytes_ / item_size, 1), count);
  size_t chunk_bytes = chunk_items * item_size;
  // Small payloads (the per-collective consensus words) reuse the
  // member scratch to avoid a hot-path allocation; large payloads use
  // a local buffer so one big tree allreduce doesn't pin its size in
  // the engine for the rest of the job.
  std::vector<uint8_t> big;
  uint8_t* tmp;
  if (chunk_bytes <= kTreeRingCrossoverBytes) {
    if (tree_scratch_.size() < chunk_bytes) tree_scratch_.resize(chunk_bytes);
    tmp = tree_scratch_.data();
  } else {
    big.resize(chunk_bytes);
    tmp = big.data();
  }
  NoteScratch(chunk_bytes);
  const std::vector<int> children = Children();
  const int parent = topo_.parent;
  // Phase 1: reduce up.
  for (size_t off = 0; off < count; off += chunk_items) {
    size_t n = std::min(chunk_items, count - off);
    uint8_t* p = buf + off * item_size;
    for (int child : children) {
      links_.at(child).RecvAll(tmp, n * item_size);
      reduce(p, tmp, n);
    }
    if (parent != static_cast<int>(kNone)) {
      links_.at(parent).SendAll(p, n * item_size);
    }
  }
  // Phase 2: broadcast down.
  for (size_t off = 0; off < count; off += chunk_items) {
    size_t n = std::min(chunk_items, count - off);
    uint8_t* p = buf + off * item_size;
    if (parent != static_cast<int>(kNone)) {
      links_.at(parent).RecvAll(p, n * item_size);
    }
    for (int child : children) {
      links_.at(child).SendAll(p, n * item_size);
    }
  }
}

void BaseEngine::RingAllreduce(uint8_t* buf, size_t count, DataType dtype,
                               ReduceOp op) {
  const int n = topo_.world;
  const size_t item = ItemSize(dtype);
  ReduceFn reduce = GetReducer(dtype, op);
  // Element-aligned block bounds, identical to pysocket.py.
  const size_t per = (count + n - 1) / n;
  std::vector<size_t> bounds(n + 1);
  for (int i = 0; i <= n; ++i) bounds[i] = std::min<size_t>(i * per, count);
  auto block_off = [&](int i) {
    int b = ((i % n) + n) % n;
    return std::make_pair(bounds[b] * item, (bounds[b + 1] - bounds[b]) * item);
  };
  TcpSocket& next = links_.at(topo_.ring_next);
  TcpSocket& prev = links_.at(topo_.ring_prev);
  // Reduce-scatter scratch is one ring block, capped at the
  // rabit_reduce_buffer budget: oversized blocks stream through the
  // exchange in budget-sized sub-chunks (the per-link byte stream is
  // unchanged — TCP framing is size-agnostic, so peers with different
  // budgets interoperate).
  size_t chunk_bytes =
      std::min(std::max<size_t>(reduce_buffer_bytes_ / item, 1) * item,
               per * item);
  // member scratch, not a per-op vector: a fresh multi-hundred-KB
  // allocation is zero-initialised and page-faulted on every op (the
  // same 1-core pathology the robust cache hit; see Init's
  // M_TRIM_THRESHOLD note)
  if (tree_scratch_.size() < chunk_bytes) tree_scratch_.resize(chunk_bytes);
  uint8_t* scratch = tree_scratch_.data();
  NoteScratch(chunk_bytes);
  // Phase 1: reduce-scatter.
  for (int s = 0; s < n - 1; ++s) {
    auto [soff, slen] = block_off(topo_.rank - s);
    auto [roff, rlen] = block_off(topo_.rank - s - 1);
    size_t maxlen = std::max(slen, rlen);
    for (size_t coff = 0; coff == 0 || coff < maxlen; coff += chunk_bytes) {
      size_t sl = coff < slen ? std::min(chunk_bytes, slen - coff) : 0;
      size_t rl = coff < rlen ? std::min(chunk_bytes, rlen - coff) : 0;
      // clamp the zero-length side's offset: when slen != rlen, the
      // exhausted block's `buf + off + coff` would point past
      // one-past-the-end — UB even though the count is 0
      Exchange(next, buf + soff + std::min(coff, slen), sl,
               prev, scratch, rl);
      reduce(buf + roff + std::min(coff, rlen), scratch, rl / item);
    }
  }
  // Phase 2: all-gather.
  for (int s = 0; s < n - 1; ++s) {
    auto [soff, slen] = block_off(topo_.rank + 1 - s);
    auto [roff, rlen] = block_off(topo_.rank - s);
    Exchange(next, buf + soff, slen, prev, buf + roff, rlen);
  }
}

void BaseEngine::TreeBroadcast(std::string* data, int root) {
  // Chunked pipeline: forward each chunk downstream as soon as it
  // arrives, so the payload streams through the tree instead of paying
  // full-payload latency per level (the reference pipelines through
  // per-link ring buffers the same way, src/allreduce_base.cc:500-588).
  // The byte stream is unchanged (u64 size, then payload), so this
  // stays wire-compatible with the Python engine.
  constexpr size_t kChunk = 256 << 10;
  int src = -1;
  uint64_t size;
  if (topo_.rank == root) {
    size = data->size();
    for (int r : topo_.tree_links) links_.at(r).SendU64(size);
  } else {
    src = TowardRoot(root);
    size = links_.at(src).RecvU64();
    data->resize(size);
    for (int r : topo_.tree_links) {
      if (r != src) links_.at(r).SendU64(size);
    }
  }
  char* p = data->empty() ? nullptr : &(*data)[0];
  for (uint64_t off = 0; off < size; off += kChunk) {
    size_t len = std::min<uint64_t>(kChunk, size - off);
    if (src >= 0) links_.at(src).RecvAll(p + off, len);
    for (int r : topo_.tree_links) {
      if (r != src) links_.at(r).SendAll(p + off, len);
    }
  }
}

void BaseEngine::Broadcast(std::string* data, int root) {
  if (topo_.world == 1) return;
  TreeBroadcast(data, root);
}

bool BaseEngine::TreeRoutedBroadcast(
    std::string* data, int root, bool i_need,
    const std::function<void(std::string*)>& materialize) {
  // See header: requester-aware recovery broadcast.  Two phases on the
  // tree oriented at `root`:
  //   1. need up-pass — every rank receives one byte per downstream
  //      link ("does that subtree contain a requester?"), ORs in its
  //      own need, and forwards one byte upstream.  O(world) single
  //      bytes, independent of payload.
  //   2. payload down-pass — the payload streams (chunk-pipelined)
  //      only across edges whose far side reported need.
  if (topo_.world == 1) return i_need;
  const int up = (topo_.rank == root) ? -1 : TowardRoot(root);
  std::vector<int> down;
  for (int r : topo_.tree_links) {
    if (r != up) down.push_back(r);
  }

  std::vector<uint8_t> child_need(down.size(), 0);
  uint8_t subtree_need = i_need ? 1 : 0;
  for (size_t i = 0; i < down.size(); ++i) {
    links_.at(down[i]).RecvAll(&child_need[i], 1);
    subtree_need |= child_need[i];
  }
  if (up >= 0) links_.at(up).SendAll(&subtree_need, 1);

  // The serving phase runs with a generous timeout: waits here are
  // legitimately long (lazy serialization on the root, bulk streaming
  // through sibling subtrees), and a genuinely dead peer still cascades
  // fast — the rank adjacent to the failure closes its links, which
  // RSTs every blocked neighbor.  The fast rabit_timeout_sec is
  // restored on exit; on LinkError the rendezvous rebuilds links with
  // fresh timeouts anyway.
  const double bulk_sec =
      link_timeout_sec_ <= 0 ? 0 : std::max(link_timeout_sec_, 600.0);
  auto set_timeouts = [&](double sec) {
    if (up >= 0) links_.at(up).SetIOTimeout(sec);
    for (int r : down) links_.at(r).SetIOTimeout(sec);
  };
  set_timeouts(bulk_sec);

  constexpr size_t kChunk = 256 << 10;
  auto send_down = [&](const char* p, size_t len) {
    for (size_t i = 0; i < down.size(); ++i) {
      if (child_need[i]) {
        links_.at(down[i]).SendAll(p, len);
        routed_payload_bytes_ += len;
      }
    }
  };

  bool received = false;
  if (topo_.rank == root) {
    bool any_child = false;
    for (uint8_t n : child_need) any_child |= (n != 0);
    if ((any_child || i_need) && materialize) materialize(data);
    uint64_t size = data->size();
    for (size_t i = 0; i < down.size(); ++i) {
      if (child_need[i]) links_.at(down[i]).SendU64(size);
    }
    for (uint64_t off = 0; off < size; off += kChunk) {
      size_t len = static_cast<size_t>(
          std::min<uint64_t>(kChunk, size - off));
      send_down(data->data() + off, len);
    }
    received = i_need;
  } else if (subtree_need) {
    uint64_t size = links_.at(up).RecvU64();
    for (size_t i = 0; i < down.size(); ++i) {
      if (child_need[i]) links_.at(down[i]).SendU64(size);
    }
    std::string relay;  // pure relays hold one chunk, not the payload
    char* dst = nullptr;
    if (i_need) {
      data->resize(size);
      dst = size != 0 ? &(*data)[0] : nullptr;
    } else {
      relay.resize(static_cast<size_t>(std::min<uint64_t>(kChunk, size)));
    }
    for (uint64_t off = 0; off < size; off += kChunk) {
      size_t len = static_cast<size_t>(std::min<uint64_t>(kChunk, size - off));
      char* p = i_need ? dst + off : &relay[0];
      links_.at(up).RecvAll(p, len);
      send_down(p, len);
    }
    received = i_need;
  }
  // Completion barrier (done-wave up, release-wave down, single bytes):
  // WITHOUT this, pruned ranks would run ahead into the next consensus
  // collective and their per-link IO timeout could fire while the
  // payload is still streaming through a sibling subtree — aborting a
  // perfectly healthy recovery.  The waits here are bounded by the
  // pipeline drain (~depth x chunk), not the full payload time, because
  // every rank reaches this point one chunk-flush after its upstream.
  uint8_t token = 1;
  for (int r : down) links_.at(r).RecvAll(&token, 1);
  if (up >= 0) {
    links_.at(up).SendAll(&token, 1);
    links_.at(up).RecvAll(&token, 1);
  }
  for (int r : down) links_.at(r).SendAll(&token, 1);
  set_timeouts(link_timeout_sec_);
  return received;
}

void BaseEngine::RingAllgather(uint8_t* buf, size_t nbytes_per_rank) {
  const int n = topo_.world;
  TcpSocket& next = links_.at(topo_.ring_next);
  TcpSocket& prev = links_.at(topo_.ring_prev);
  auto off = [&](int i) {
    return static_cast<size_t>(((i % n) + n) % n) * nbytes_per_rank;
  };
  for (int s = 0; s < n - 1; ++s) {
    Exchange(next, buf + off(topo_.rank - s), nbytes_per_rank, prev,
             buf + off(topo_.rank - s - 1), nbytes_per_rank);
  }
}

void BaseEngine::Allgather(const void* mine, size_t nbytes, void* out) {
  uint8_t* p = static_cast<uint8_t*>(out);
  memcpy(p + static_cast<size_t>(topo_.rank) * nbytes, mine, nbytes);
  if (topo_.world == 1) return;
  RingAllgather(p, nbytes);
}

int BaseEngine::LoadCheckPoint(std::string* global_model,
                               std::string* local_model) {
  if (!has_checkpoint_) return 0;
  if (global_model) *global_model = global_model_;
  if (local_model && has_local_) *local_model = local_model_;
  return version_;
}

void BaseEngine::CheckPoint(const std::string* global_model,
                            const std::string* local_model) {
  global_model_ = global_model ? *global_model : std::string();
  has_local_ = local_model != nullptr;
  local_model_ = local_model ? *local_model : std::string();
  has_checkpoint_ = true;
  version_ += 1;
}

}  // namespace rabit_tpu
