/* Minimal OpenMPI 4.x ABI declarations.
 *
 * This image ships the OpenMPI *runtime* (libmpi.so.40 + the full MCA
 * plugin tree) but not the -dev package, so there is no <mpi.h>.  These
 * declarations reproduce the small, stable slice of the public OpenMPI
 * ABI we need: predefined handles are addresses of exported
 * ompi_predefined_* globals, MPI_Comm/Datatype/Op are opaque pointers,
 * and MPI_IN_PLACE is the documented ((void*)1) sentinel.  Everything
 * here is the MPI standard surface; nothing engine-specific.
 *
 * Used by the real-MPI leg of the framework (reference analogue:
 * /root/reference/src/engine_mpi.cc, which includes the vendor mpi.h).
 */
#ifndef RABIT_TPU_OMPI_ABI_H_
#define RABIT_TPU_OMPI_ABI_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef struct ompi_communicator_t *MPI_Comm;
typedef struct ompi_datatype_t *MPI_Datatype;
typedef struct ompi_op_t *MPI_Op;

/* Predefined-handle storage objects exported by libmpi.so.40.  Their
 * size is irrelevant here: only their addresses are used as handles. */
extern struct ompi_predefined_communicator_t ompi_mpi_comm_world;
extern struct ompi_predefined_communicator_t ompi_mpi_comm_self;
extern struct ompi_predefined_datatype_t ompi_mpi_float;
extern struct ompi_predefined_datatype_t ompi_mpi_double;
extern struct ompi_predefined_datatype_t ompi_mpi_int;
extern struct ompi_predefined_datatype_t ompi_mpi_long;
extern struct ompi_predefined_datatype_t ompi_mpi_unsigned_char;
extern struct ompi_predefined_op_t ompi_mpi_op_sum;
extern struct ompi_predefined_op_t ompi_mpi_op_max;
extern struct ompi_predefined_op_t ompi_mpi_op_min;
extern struct ompi_predefined_op_t ompi_mpi_op_bor;

#define MPI_COMM_WORLD ((MPI_Comm) &ompi_mpi_comm_world)
#define MPI_COMM_SELF ((MPI_Comm) &ompi_mpi_comm_self)
#define MPI_FLOAT ((MPI_Datatype) &ompi_mpi_float)
#define MPI_DOUBLE ((MPI_Datatype) &ompi_mpi_double)
#define MPI_INT ((MPI_Datatype) &ompi_mpi_int)
#define MPI_LONG ((MPI_Datatype) &ompi_mpi_long)
#define MPI_UNSIGNED_CHAR ((MPI_Datatype) &ompi_mpi_unsigned_char)
#define MPI_SUM ((MPI_Op) &ompi_mpi_op_sum)
#define MPI_MAX ((MPI_Op) &ompi_mpi_op_max)
#define MPI_MIN ((MPI_Op) &ompi_mpi_op_min)
#define MPI_BOR ((MPI_Op) &ompi_mpi_op_bor)

#define MPI_IN_PLACE ((void *) 1)
#define MPI_SUCCESS 0

int MPI_Init(int *argc, char ***argv);
int MPI_Finalize(void);
int MPI_Comm_rank(MPI_Comm comm, int *rank);
int MPI_Comm_size(MPI_Comm comm, int *size);
int MPI_Barrier(MPI_Comm comm);
int MPI_Allreduce(const void *sendbuf, void *recvbuf, int count,
                  MPI_Datatype datatype, MPI_Op op, MPI_Comm comm);
int MPI_Bcast(void *buffer, int count, MPI_Datatype datatype, int root,
              MPI_Comm comm);
int MPI_Allgather(const void *sendbuf, int sendcount, MPI_Datatype sendtype,
                  void *recvbuf, int recvcount, MPI_Datatype recvtype,
                  MPI_Comm comm);
int MPI_Abort(MPI_Comm comm, int errorcode);
double MPI_Wtime(void);
int MPI_Get_processor_name(char *name, int *resultlen);
#define MPI_MAX_PROCESSOR_NAME 256

#ifdef __cplusplus
}
#endif

#endif /* RABIT_TPU_OMPI_ABI_H_ */
