/* orted — ORTE daemon front-end.
 *
 * Debian's libopenmpi3 ships the complete ORTE runtime as a shared
 * library (libopen-rte.so.40, which exports orte_daemon()) but not the
 * openmpi-bin package that holds the two tiny executables driving it.
 * The real orted is a one-line main over orte_daemon; this rebuilds it
 * so the launcher-less image can run real multi-process MPI jobs for
 * the benchmark baseline (reference analogue: the mpirun leg of
 * /root/reference/test/speed_runner.py:13-18).
 */
int orte_daemon(int argc, char *argv[]);

int main(int argc, char *argv[]) { return orte_daemon(argc, argv); }
