/* mpirun — ORTE job-submission front-end.
 *
 * The image has the full OpenMPI runtime (libmpi + libopen-rte + every
 * MCA plugin) but no mpirun binary.  mpirun is a thin event-loop shell
 * over the exported orte_submit_* API; this rebuilds that shell so the
 * framework's MPI engine and the MPI_Allreduce bus-bandwidth baseline
 * (reference: /root/reference/test/speed_runner.py:13-18,
 * /root/reference/src/engine_mpi.cc) can run for real.
 *
 * Flow: orte_submit_init parses the mpirun command line (-n, --host,
 * MCA params...) and boots the HNP in-process; orte_submit_job launches
 * the app (local ranks are forked directly by the HNP's odls; remote
 * ranks would go through plm_rsh + our rebuilt orted).  We then spin
 * the ORTE event base until the launch and completion callbacks fire,
 * and exit with the job's aggregated exit status.
 */
#include <limits.h>
#include <stdbool.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

/* liborte exports (orte/orted/orted_submit.h API, stable across 4.x) */
typedef void (*orte_submit_cbfunc_t)(int index, void *jdata, int ret,
                                     void *cbdata);
int orte_submit_init(int argc, char *argv[], void *opts);
int orte_submit_job(char *cmd[], int *index,
                    orte_submit_cbfunc_t launch_cb, void *launch_cbdata,
                    orte_submit_cbfunc_t complete_cb, void *complete_cbdata);
void orte_submit_finalize(void);
int orte_finalize(void);
extern struct event_base *orte_event_base;
extern bool orte_event_base_active;
extern int orte_exit_status;

/* mpirun is itself a participating daemon: launch commands are xcast to
 * ALL daemons on ORTE_RML_TAG_DAEMON(1), including the HNP, so it must
 * post the daemon-command receive or the launch message sits unmatched
 * forever.  liborte exports the handler (orte_daemon_recv) and the RML
 * dispatch struct (orte_rml); recv_buffer_nb is the slot at byte offset
 * 0x30 — recovered from how orte_daemon itself registers this exact
 * receive (objdump: `call *0x30(%rax)` with rax=&orte_rml, esi=tag 1,
 * edx=persistent, rcx=orte_daemon_recv), so it is ABI-exact for the
 * installed libopen-rte.so.40. */
extern char orte_rml[];
extern char orte_name_wildcard[];
void orte_daemon_recv(int status, void *sender, void *buffer, int tag,
                      void *cbdata);
typedef void (*rml_recv_buffer_nb_fn)(void *peer, int tag, int persistent,
                                      void *cbfunc, void *cbdata);
#define RML_RECV_BUFFER_NB_SLOT 0x30
#define RML_TAG_DAEMON 1

static int post_daemon_recv(void) {
    rml_recv_buffer_nb_fn fn =
        *(rml_recv_buffer_nb_fn *) (orte_rml + RML_RECV_BUFFER_NB_SLOT);
    if (!fn) return -1;
    fn(orte_name_wildcard, RML_TAG_DAEMON, 1, (void *) orte_daemon_recv,
       NULL);
    return 0;
}

/* system libevent, which Debian's OPAL is built against */
int event_base_loop(struct event_base *base, int flags);
#define EVLOOP_ONCE 0x01

static struct {
    volatile bool active;
    int status;
} launchst, completest;

static void on_launch(int index, void *jdata, int ret, void *cbdata) {
    (void) index; (void) jdata; (void) cbdata;
    launchst.status = ret;
    __atomic_thread_fence(__ATOMIC_RELEASE);
    launchst.active = false;
}

static void on_complete(int index, void *jdata, int ret, void *cbdata) {
    (void) index; (void) jdata; (void) cbdata;
    completest.status = ret;
    __atomic_thread_fence(__ATOMIC_RELEASE);
    completest.active = false;
}

/* Put this binary's directory first on PATH so ORTE's launch plumbing
 * (ess_singleton, plm_rsh) finds the sibling rebuilt `orted`. */
static void prepend_self_to_path(const char *argv0) {
    char self[PATH_MAX];
    ssize_t n = readlink("/proc/self/exe", self, sizeof(self) - 1);
    if (n <= 0) {
        if (!strchr(argv0, '/')) return;
        snprintf(self, sizeof(self), "%s", argv0);
        n = (ssize_t) strlen(self);
    }
    self[n] = '\0';
    char *slash = strrchr(self, '/');
    if (!slash) return;
    *slash = '\0';
    const char *old = getenv("PATH");
    char merged[PATH_MAX * 4];
    snprintf(merged, sizeof(merged), "%s:%s", self, old ? old : "");
    setenv("PATH", merged, 1);
}

int main(int argc, char *argv[]) {
    int rc, index = 0;

    prepend_self_to_path(argv[0]);
    /* CI containers run as root; mpirun's refusal is interactive-user
     * protection that does not apply here. */
    setenv("OMPI_ALLOW_RUN_AS_ROOT", "1", 0);
    setenv("OMPI_ALLOW_RUN_AS_ROOT_CONFIRM", "1", 0);
    /* Single-host images often have no ssh: the default plm (rsh) then
     * fails component selection before the local-only isolated plm can
     * win.  Local ranks never use the agent either way, so default to
     * isolated when no agent is available (explicit env still wins). */
    if (!getenv("OMPI_MCA_plm") && !getenv("OMPI_MCA_plm_rsh_agent")
            && system("command -v ssh >/dev/null 2>&1") != 0)
        setenv("OMPI_MCA_plm", "isolated", 1);

    rc = orte_submit_init(argc, argv, NULL);
    if (rc != 0) {
        fprintf(stderr, "mini-mpirun: orte_submit_init failed (%d)\n", rc);
        exit(1);
    }

    if (post_daemon_recv() != 0) {
        fprintf(stderr,
                "mini-mpirun: rml recv_buffer_nb slot is empty — "
                "libopen-rte ABI mismatch\n");
        exit(1);
    }

    launchst.active = true;
    completest.active = true;
    rc = orte_submit_job(argv, &index, on_launch, NULL, on_complete, NULL);
    if (rc != 0) {
        fprintf(stderr, "mini-mpirun: orte_submit_job failed (%d)\n", rc);
        orte_exit_status = rc;
        goto done;
    }

    while (orte_event_base_active && launchst.active)
        event_base_loop(orte_event_base, EVLOOP_ONCE);
    __atomic_thread_fence(__ATOMIC_ACQUIRE);
    if (launchst.status != 0) {
        fprintf(stderr, "mini-mpirun: launch failed (%d)\n",
                launchst.status);
        goto done;
    }
    while (orte_event_base_active && completest.active)
        event_base_loop(orte_event_base, EVLOOP_ONCE);
    __atomic_thread_fence(__ATOMIC_ACQUIRE);

done:
    orte_submit_finalize();
    orte_finalize();
    return orte_exit_status;
}
