/* MPI_Allreduce speed benchmark — the comparison baseline the host
 * engines are measured against.
 *
 * Mirrors the reference's speed harness semantics
 * (/root/reference/test/speed_test.cc:53-97: per-op wall time averaged
 * across ranks;  /root/reference/test/speed_runner.py:13-18: float32
 * payload sweep, rabit vs MPI binaries) for raw MPI_Allreduce(SUM,
 * float32).  Per payload size it prints one line:
 *
 *   bytes=<payload> reps=<n> avg_s=<mean per-op> algbw_MBps=<payload/t>
 *   busbw_MBps=<algbw * 2(w-1)/w>
 *
 * busbw is the standard bus-bandwidth normalization (each rank must
 * move 2(w-1)/w of the payload in an optimal allreduce), making numbers
 * comparable across world sizes.  tools/speed_runner.py parses this
 * output to report each host engine at a % of the MPI baseline.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "ompi_abi.h"

int main(int argc, char **argv) {
    int rank = -1, world = 0;
    if (MPI_Init(&argc, &argv) != MPI_SUCCESS) {
        fprintf(stderr, "MPI_Init failed\n");
        return 1;
    }
    MPI_Comm_rank(MPI_COMM_WORLD, &rank);
    MPI_Comm_size(MPI_COMM_WORLD, &world);

    /* sizes in float32 counts; override with argv: n1 n2 ... */
    long sizes_default[] = {256, 4096, 65536, 1048576, 16777216};
    long *sizes = sizes_default;
    int nsizes = (int) (sizeof(sizes_default) / sizeof(sizes_default[0]));
    if (argc > 1) {
        nsizes = argc - 1;
        sizes = malloc(sizeof(long) * (size_t) nsizes);
        for (int i = 0; i < nsizes; i++) sizes[i] = atol(argv[i + 1]);
    }

    long maxn = 0;
    for (int i = 0; i < nsizes; i++)
        if (sizes[i] > maxn) maxn = sizes[i];
    float *buf = malloc(sizeof(float) * (size_t) maxn);

    for (int i = 0; i < nsizes; i++) {
        long n = sizes[i];
        /* scale repetitions so each size runs ~comparable wall time
         * (reference sweep: repeats 1e4 down to 10 as payload grows) */
        int reps = (int) (1 << 26) / (int) (n > 1024 ? n : 1024);
        if (reps < 5) reps = 5;
        if (reps > 2000) reps = 2000;
        for (long j = 0; j < n; j++) buf[j] = (float) (j % 97) + rank;
        /* warmup: let the tuned collective pick + prime its plan */
        for (int w = 0; w < 3; w++)
            MPI_Allreduce(MPI_IN_PLACE, buf, (int) n, MPI_FLOAT, MPI_SUM,
                          MPI_COMM_WORLD);
        MPI_Barrier(MPI_COMM_WORLD);
        double t0 = MPI_Wtime();
        for (int r = 0; r < reps; r++)
            MPI_Allreduce(MPI_IN_PLACE, buf, (int) n, MPI_FLOAT, MPI_SUM,
                          MPI_COMM_WORLD);
        double dt = MPI_Wtime() - t0;
        /* average the per-rank timing like the reference harness */
        double sum_dt = dt;
        MPI_Allreduce(MPI_IN_PLACE, &sum_dt, 1, MPI_DOUBLE, MPI_SUM,
                      MPI_COMM_WORLD);
        double avg = sum_dt / world / reps;
        double bytes = (double) n * 4.0;
        double algbw = bytes / avg / 1e6;
        double busbw = algbw * 2.0 * (world - 1) / world;
        if (rank == 0) {
            printf("bytes=%ld reps=%d avg_s=%.6e algbw_MBps=%.2f "
                   "busbw_MBps=%.2f\n",
                   n * 4, reps, avg, algbw, busbw);
            fflush(stdout);
        }
    }
    MPI_Finalize();
    return 0;
}
