// Single-process no-op engine: world = 1, collectives are identities.
// TPU-native rebuild of the reference empty engine
// (reference: src/engine_empty.cc:19-83) — lets programs link and run
// without any communication stack (bring-up, unit tests, single-chip).
#pragma once

#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "rabit_tpu/engine.h"
#include "rabit_tpu/utils.h"

namespace rabit_tpu {

class EmptyEngine : public IEngine {
 public:
  void Init(const std::vector<std::pair<std::string, std::string>>&) override {
  }
  void Shutdown() override {}

  int rank() const override { return 0; }
  int world_size() const override { return 1; }
  std::string host() const override {
    char buf[256];
    gethostname(buf, sizeof(buf));
    return std::string(buf);
  }

  void Allreduce(void* /*buf*/, size_t /*count*/, DataType /*dtype*/,
                 ReduceOp /*op*/, const PrepareFn& prepare) override {
    if (prepare) prepare();
  }
  void AllreduceCustom(void* /*buf*/, size_t /*count*/, size_t /*item_size*/,
                       const CustomReducer& /*reducer*/,
                       const PrepareFn& prepare) override {
    if (prepare) prepare();
  }
  void Broadcast(std::string* /*data*/, int /*root*/) override {}
  void Allgather(const void* mine, size_t nbytes, void* out) override {
    if (nbytes != 0) std::memcpy(out, mine, nbytes);
  }

  int LoadCheckPoint(std::string* global_model,
                     std::string* local_model) override {
    if (version_ != 0) {
      *global_model = global_;
      if (local_model != nullptr) *local_model = local_;
    }
    return version_;
  }
  void CheckPoint(const std::string* global_model,
                  const std::string* local_model) override {
    global_ = global_model != nullptr ? *global_model : std::string();
    local_ = local_model != nullptr ? *local_model : std::string();
    ++version_;
  }
  int version_number() const override { return version_; }

  void TrackerPrint(const std::string& msg) override {
    std::fprintf(stderr, "%s", msg.c_str());
  }

 private:
  int version_ = 0;
  std::string global_, local_;
};

}  // namespace rabit_tpu
