// Serialization contract for checkpoint payloads.
// TPU-native rebuild of the reference's stream/serializable interfaces
// (reference: include/rabit_serializable.h:17-106 IStream/ISerializable;
// include/rabit/io.h:29-117 MemoryFixSizeBuffer/MemoryBufferStream).
// Models marshal themselves into in-memory byte streams; the robust
// engine replicates those bytes — it never interprets them.
#pragma once

#include <cstdio>
#include <cstring>
#include <string>
#include <type_traits>
#include <vector>

#include "rabit_tpu/utils.h"

namespace rabit_tpu {

// Byte-stream interface used by checkpoint marshalling.
class IStream {
 public:
  virtual ~IStream() = default;
  // Reads up to size bytes; returns bytes actually read (0 at EOF).
  virtual size_t Read(void* ptr, size_t size) = 0;
  virtual void Write(const void* ptr, size_t size) = 0;

  template <typename T>
  void WritePod(const T& v) {
    static_assert(std::is_trivially_copyable<T>::value,
                  "WritePod needs a trivially copyable type");
    Write(&v, sizeof(T));
  }

  template <typename T>
  bool ReadPod(T* v) {
    return Read(v, sizeof(T)) == sizeof(T);
  }

  template <typename T>
  void WriteVector(const std::vector<T>& vec) {
    uint64_t n = vec.size();
    WritePod(n);
    if (n != 0) Write(vec.data(), n * sizeof(T));
  }

  template <typename T>
  bool ReadVector(std::vector<T>* vec) {
    uint64_t n = 0;
    if (!ReadPod(&n)) return false;
    vec->resize(n);
    return n == 0 || Read(vec->data(), n * sizeof(T)) == n * sizeof(T);
  }

  void WriteString(const std::string& s) {
    uint64_t n = s.size();
    WritePod(n);
    if (n != 0) Write(s.data(), n);
  }

  bool ReadString(std::string* s) {
    uint64_t n = 0;
    if (!ReadPod(&n)) return false;
    s->resize(n);
    return n == 0 || Read(&(*s)[0], n) == n;
  }
};

// Anything checkpointable: models load/save themselves through IStream
// (the contract rabit::CheckPoint templates over,
// reference: include/rabit_serializable.h:95-106).
class ISerializable {
 public:
  virtual ~ISerializable() = default;
  virtual void Load(IStream& fi) = 0;
  virtual void Save(IStream& fo) const = 0;
};

// Fixed-size in-memory window (read and write bounded by the buffer;
// reference: include/rabit/io.h:29-74).
class MemoryFixSizeBuffer : public IStream {
 public:
  MemoryFixSizeBuffer(void* data, size_t size)
      : data_(static_cast<char*>(data)), size_(size) {}

  size_t Read(void* ptr, size_t size) override {
    size_t avail = pos_ < size_ ? size_ - pos_ : 0;
    size_t n = size < avail ? size : avail;
    if (n != 0) std::memcpy(ptr, data_ + pos_, n);
    pos_ += n;
    return n;
  }

  void Write(const void* ptr, size_t size) override {
    if (size == 0) return;
    Check(pos_ + size <= size_, "MemoryFixSizeBuffer overflow");
    std::memcpy(data_ + pos_, ptr, size);
    pos_ += size;
  }

  void Seek(size_t pos) {
    Check(pos <= size_, "MemoryFixSizeBuffer::Seek out of range");
    pos_ = pos;
  }

 private:
  char* data_;
  size_t size_;
  size_t pos_ = 0;
};

// Stdio-backed stream for persistent model IO — the app-side
// complement of the in-memory checkpoint streams (reference:
// rabit-learn/utils/io.h FileStream; final-model persistence is the
// app's job, reference: rabit-learn/linear/linear.cc:98-122).
class FileStream : public IStream {
 public:
  FileStream(const char* fname, const char* mode) {
    fp_ = std::fopen(fname, mode);
    Check(fp_ != nullptr, "FileStream: cannot open %s", fname);
  }
  ~FileStream() override {
    if (fp_ != nullptr) std::fclose(fp_);
  }
  FileStream(const FileStream&) = delete;
  FileStream& operator=(const FileStream&) = delete;

  size_t Read(void* ptr, size_t size) override {
    return std::fread(ptr, 1, size, fp_);
  }
  void Write(const void* ptr, size_t size) override {
    Check(std::fwrite(ptr, 1, size, fp_) == size, "FileStream: short write");
  }

 private:
  std::FILE* fp_ = nullptr;
};

// Growable in-memory stream over std::string (checkpoint marshalling;
// reference: include/rabit/io.h:77-117).
class MemoryBufferStream : public IStream {
 public:
  explicit MemoryBufferStream(std::string* buffer) : buffer_(buffer) {}

  size_t Read(void* ptr, size_t size) override {
    size_t avail = pos_ < buffer_->size() ? buffer_->size() - pos_ : 0;
    size_t n = size < avail ? size : avail;
    if (n != 0) std::memcpy(ptr, buffer_->data() + pos_, n);
    pos_ += n;
    return n;
  }

  void Write(const void* ptr, size_t size) override {
    if (size == 0) return;
    if (pos_ + size > buffer_->size()) buffer_->resize(pos_ + size);
    std::memcpy(&(*buffer_)[pos_], ptr, size);
    pos_ += size;
  }

  void Seek(size_t pos) {
    Check(pos <= buffer_->size(), "MemoryBufferStream::Seek out of range");
    pos_ = pos;
  }

 private:
  std::string* buffer_;
  size_t pos_ = 0;
};

}  // namespace rabit_tpu
