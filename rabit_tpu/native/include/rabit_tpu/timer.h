// Wall-clock helper (reference: include/rabit/timer.h:48-56).
#pragma once

#include <chrono>

namespace rabit_tpu {

// Seconds since an arbitrary steady epoch.
inline double GetTime() {
  using clock = std::chrono::steady_clock;
  return std::chrono::duration<double>(clock::now().time_since_epoch())
      .count();
}

}  // namespace rabit_tpu
