// Non-fault-tolerant TCP collective engine (tracker rendezvous + links +
// ring/tree collectives).
// TPU-native rebuild of the reference base engine (reference:
// src/allreduce_base.h:33-433), sharing the exact wire behaviour of the
// Python engine (rabit_tpu/engine/pysocket.py) so C++ (variant=base) and
// Python workers interoperate in one job.  The robust variant adds
// consensus traffic, so all workers in a job must run the same protocol
// level (as in the reference, where all workers link one engine flavour).  Algorithmic notes live in pysocket.py — ring
// reduce-scatter/all-gather for large payloads (bandwidth-optimal, unlike
// the reference's pipelined binary tree), tree for small, deterministic
// any-root tree-flood broadcast.
#pragma once

#include <map>
#include <string>
#include <vector>

#include "rabit_tpu/engine.h"
#include "rabit_tpu/socket.h"

namespace rabit_tpu {

constexpr uint32_t kMagic = 0x7AB17901;  // tracker/protocol.py MAGIC
constexpr uint32_t kNone = 0xFFFFFFFF;
constexpr size_t kTreeRingCrossoverBytes = 64 << 10;

struct Topology {
  int rank = 0;
  int world = 1;
  int parent = static_cast<int>(kNone);
  std::vector<int> tree_links;
  int ring_prev = static_cast<int>(kNone);
  int ring_next = static_cast<int>(kNone);
};

class BaseEngine : public IEngine {
 public:
  void Init(const std::vector<std::pair<std::string, std::string>>& params)
      override;
  void Shutdown() override;

  int rank() const override { return topo_.rank; }
  int world_size() const override { return topo_.world; }
  std::string host() const override;

  void Allreduce(void* buf, size_t count, DataType dtype, ReduceOp op,
                 const PrepareFn& prepare = nullptr) override;
  void AllreduceCustom(void* buf, size_t count, size_t item_size,
                       const CustomReducer& reducer,
                       const PrepareFn& prepare = nullptr) override;
  void Broadcast(std::string* data, int root) override;
  void Allgather(const void* mine, size_t nbytes, void* out) override;

  int LoadCheckPoint(std::string* global_model,
                     std::string* local_model) override;
  void CheckPoint(const std::string* global_model,
                  const std::string* local_model) override;
  int version_number() const override { return version_; }

  void TrackerPrint(const std::string& msg) override;

 protected:
  virtual const char* InitCmd() const { return "start"; }
  void SetParam(const std::string& name, const std::string& value);

  // Tracker rendezvous: register, receive topology, wire links.
  void Rendezvous(const std::string& cmd);
  TcpSocket TrackerConnect(const std::string& cmd);
  void CloseLinks();

  // Collective building blocks (throw LinkError on peer failure).
  // The Fn variant takes an arbitrary reducer — the robust layer's
  // consensus words reduce with custom combine functions
  // (reference analogue: ReduceHandle, include/rabit/engine.h:215-253).
  void TreeAllreduceFn(uint8_t* buf, size_t count, size_t item_size,
                       const CustomReducer& reduce);
  void TreeAllreduce(uint8_t* buf, size_t count, DataType dtype, ReduceOp op);
  void RingAllreduce(uint8_t* buf, size_t count, DataType dtype, ReduceOp op);
  void TreeBroadcast(std::string* data, int root);
  void RingAllgather(uint8_t* buf, size_t nbytes_per_rank);
  int TowardRoot(int root) const;
  std::vector<int> Children() const;

  std::string tracker_uri_;
  int tracker_port_ = 0;
  std::string task_id_ = "0";
  int world_hint_ = 0;
  Topology topo_;
  std::map<int, TcpSocket> links_;
  int version_ = 0;
  std::string global_model_;
  std::string local_model_;
  bool has_checkpoint_ = false;
  bool has_local_ = false;
};

}  // namespace rabit_tpu
