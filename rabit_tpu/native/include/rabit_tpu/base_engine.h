// Non-fault-tolerant TCP collective engine (tracker rendezvous + links +
// ring/tree collectives).
// TPU-native rebuild of the reference base engine (reference:
// src/allreduce_base.h:33-433), sharing the exact wire behaviour of the
// Python engine (rabit_tpu/engine/pysocket.py) so C++ (variant=base) and
// Python workers interoperate in one job.  The robust variant adds
// consensus traffic, so all workers in a job must run the same protocol
// level (as in the reference, where all workers link one engine flavour).  Algorithmic notes live in pysocket.py — ring
// reduce-scatter/all-gather for large payloads (bandwidth-optimal, unlike
// the reference's pipelined binary tree), tree for small, deterministic
// any-root tree-flood broadcast.
#pragma once

#include <cstdint>
#include <functional>
#include <map>
#include <string>
#include <vector>

#include "rabit_tpu/engine.h"
#include "rabit_tpu/socket.h"

namespace rabit_tpu {

constexpr uint32_t kMagic = 0x7AB17901;  // tracker/protocol.py MAGIC
constexpr uint32_t kNone = 0xFFFFFFFF;
constexpr size_t kTreeRingCrossoverBytes = 64 << 10;

struct Topology {
  int rank = 0;
  int world = 1;
  int parent = static_cast<int>(kNone);
  std::vector<int> tree_links;
  int ring_prev = static_cast<int>(kNone);
  int ring_next = static_cast<int>(kNone);
};

class BaseEngine : public IEngine {
 public:
  void Init(const std::vector<std::pair<std::string, std::string>>& params)
      override;
  void Shutdown() override;

  int rank() const override { return topo_.rank; }
  int world_size() const override { return topo_.world; }
  std::string host() const override;

  void Allreduce(void* buf, size_t count, DataType dtype, ReduceOp op,
                 const PrepareFn& prepare = nullptr) override;
  void AllreduceCustom(void* buf, size_t count, size_t item_size,
                       const CustomReducer& reducer,
                       const PrepareFn& prepare = nullptr) override;
  void Broadcast(std::string* data, int root) override;
  void Allgather(const void* mine, size_t nbytes, void* out) override;

  int LoadCheckPoint(std::string* global_model,
                     std::string* local_model) override;
  void CheckPoint(const std::string* global_model,
                  const std::string* local_model) override;
  int version_number() const override { return version_; }

  void TrackerPrint(const std::string& msg) override;

 protected:
  virtual const char* InitCmd() const { return "start"; }
  void SetParam(const std::string& name, const std::string& value);

  // Tracker rendezvous: register, receive topology, wire links.
  void Rendezvous(const std::string& cmd);
  TcpSocket TrackerConnect(const std::string& cmd);
  void CloseLinks();

  // Collective building blocks (throw LinkError on peer failure).
  // The Fn variant takes an arbitrary reducer — the robust layer's
  // consensus words reduce with custom combine functions
  // (reference analogue: ReduceHandle, include/rabit/engine.h:215-253).
  void TreeAllreduceFn(uint8_t* buf, size_t count, size_t item_size,
                       const CustomReducer& reduce);
  void TreeAllreduce(uint8_t* buf, size_t count, DataType dtype, ReduceOp op);
  void RingAllreduce(uint8_t* buf, size_t count, DataType dtype, ReduceOp op);
  void TreeBroadcast(std::string* data, int root);
  // Requester-aware tree broadcast for recovery serving: a 1-byte
  // "subtree needs it" up-pass prunes payload edges, then the payload
  // streams only along root->requester paths (pure relays forward
  // chunk-by-chunk with O(chunk) memory; subtrees without requesters
  // move no payload bytes).  All ranks must call with the same root.
  // Returns true iff this rank received the payload into *data.
  // (Reference analogue: shortest-path recovery routing,
  // src/allreduce_robust.cc:526-700 + MsgPassing
  // src/allreduce_robust-inl.h:33-158, re-designed for the fixed tree.)
  // On the root, `materialize` (optional) is invoked to fill *data only
  // when at least one requester exists — lazy checkpoints stay
  // unserialized when nobody is recovering.
  bool TreeRoutedBroadcast(std::string* data, int root, bool i_need,
                           const std::function<void(std::string*)>&
                               materialize = nullptr);
  void RingAllgather(uint8_t* buf, size_t nbytes_per_rank);
  int TowardRoot(int root) const;
  std::vector<int> Children() const;

 public:
  // Payload bytes this rank SENT through TreeRoutedBroadcast (recovery
  // serving traffic); exposed through the C ABI for tests asserting
  // that recovery cost scales with requesters, not world size.
  uint64_t routed_payload_bytes() const { return routed_payload_bytes_; }
  // Largest per-op collective scratch allocation so far; tests assert it
  // stays within the rabit_reduce_buffer budget.
  uint64_t scratch_peak_bytes() const { return scratch_peak_bytes_; }
  // True iff the tracker flagged this process as a mid-job relaunch (a
  // cmd=start re-registration of a task_id that already completed a
  // round) — platform-restart detection without environment variables.
  bool was_relaunched() const { return relaunched_; }
  // "256MB" / "64KB" / "1073741824" -> bytes (reference: the
  // rabit_reduce_buffer suffix parse, src/allreduce_base.cc:117-132).
  static size_t ParseByteSize(const std::string& s);

  std::string tracker_uri_;
  int tracker_port_ = 0;
  std::string task_id_ = "0";
  int world_hint_ = 0;
  Topology topo_;
  std::map<int, TcpSocket> links_;
  // Reused tree-allreduce scratch: the consensus path runs one small
  // TreeAllreduceFn per collective, and a fresh vector each time was
  // the one allocation the hot path still paid.
  std::vector<uint8_t> tree_scratch_;
  uint64_t routed_payload_bytes_ = 0;
  // Collective scratch budget (rabit_reduce_buffer): payloads larger than
  // this stream through the tree/ring in budget-sized chunks so per-op
  // scratch memory is bounded by configuration, not payload size
  // (reference: reduce_buffer chunking, src/allreduce_base.cc:31,117-132).
  size_t reduce_buffer_bytes_ = size_t{256} << 20;
  uint64_t scratch_peak_bytes_ = 0;
  void NoteScratch(size_t nbytes) {
    if (nbytes > scratch_peak_bytes_) scratch_peak_bytes_ = nbytes;
  }
  // Peer-link IO timeout (rabit_timeout_sec / RABIT_TIMEOUT_SEC): a
  // hung-but-alive peer surfaces as LinkError after this many seconds
  // instead of wedging the job; tracker waits are not bounded by it
  // (barrier waits are legitimately long during recovery).
  double link_timeout_sec_ = 600.0;
  bool relaunched_ = false;
  int version_ = 0;
  std::string global_model_;
  std::string local_model_;
  bool has_checkpoint_ = false;
  bool has_local_ = false;
};

}  // namespace rabit_tpu
