// Error checking and logging for the native engine.
// TPU-native rebuild of the reference utility layer
// (reference: include/rabit/utils.h:100-154) in C++17: failures throw
// rabit_tpu::Error so the C ABI layer can translate them into error codes
// instead of exiting the process from a library.
#pragma once

#include <cstdarg>
#include <cstdio>
#include <stdexcept>
#include <string>

namespace rabit_tpu {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& msg) : std::runtime_error(msg) {}
};

// A link-level failure (peer death / connection reset): the robust engine
// catches these and runs recovery; anything else is fatal.
class LinkError : public Error {
 public:
  explicit LinkError(const std::string& msg) : Error(msg) {}
};

inline std::string Format(const char* fmt, va_list ap) {
  char buf[1024];
  vsnprintf(buf, sizeof(buf), fmt, ap);
  return std::string(buf);
}

[[noreturn]] inline void Fail(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  std::string msg = Format(fmt, ap);
  va_end(ap);
  throw Error(msg);
}

inline void Check(bool cond, const char* fmt, ...) {
  if (cond) return;
  va_list ap;
  va_start(ap, fmt);
  std::string msg = Format(fmt, ap);
  va_end(ap);
  throw Error(msg);
}

inline void Log(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  vfprintf(stderr, fmt, ap);
  fputc('\n', stderr);
  va_end(ap);
}

}  // namespace rabit_tpu
