// Thin POSIX TCP socket wrapper for the native engine.
// TPU-native rebuild of the reference socket layer (reference: src/socket.h:
// 89-391 TCPSocket, :394-496 SelectHelper) — POSIX-only (the TPU fleet is
// Linux), RAII, poll(2) instead of select so large fd sets are no issue.
#pragma once

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>
#include <utility>

#include "rabit_tpu/utils.h"

namespace rabit_tpu {

class TcpSocket {
 public:
  TcpSocket() = default;
  explicit TcpSocket(int fd) : fd_(fd) {}
  TcpSocket(const TcpSocket&) = delete;
  TcpSocket& operator=(const TcpSocket&) = delete;
  TcpSocket(TcpSocket&& other) noexcept : fd_(other.fd_) { other.fd_ = -1; }
  TcpSocket& operator=(TcpSocket&& other) noexcept {
    if (this != &other) {
      Close();
      fd_ = other.fd_;
      other.fd_ = -1;
    }
    return *this;
  }
  ~TcpSocket() { Close(); }

  int fd() const { return fd_; }
  bool valid() const { return fd_ >= 0; }

  void Create() {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    Check(fd_ >= 0, "socket() failed: %s", strerror(errno));
  }

  void Close() {
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
  }

  void SetNoDelay() {
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }

  void SetReuseAddr() {
    int one = 1;
    setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  }

  void SetKeepAlive() {
    int one = 1;
    setsockopt(fd_, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
  }

  // Bound blocking IO on this socket: recv/send that stall longer than
  // `sec` fail with EAGAIN, which RecvAll/SendAll surface as LinkError —
  // a hung (but alive) peer is then detected in seconds instead of
  // wedging the collective (reference analogue: errno classification +
  // select exception sets, src/allreduce_base.cc:392-397).
  // sec <= 0 clears the timeout (blocking IO waits forever), honoring
  // the documented rabit_timeout_sec<=0 disable contract.
  void SetIOTimeout(double sec) {
    timeval tv{0, 0};  // zero = no timeout
    if (sec > 0) {
      tv.tv_sec = static_cast<time_t>(sec);
      tv.tv_usec = static_cast<suseconds_t>((sec - tv.tv_sec) * 1e6);
    }
    setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  }

  void SetNonBlocking(bool on);

  // Bind to an ephemeral (or given) port; returns the bound port.
  int BindListen(int port = 0, int backlog = 64);

  TcpSocket Accept() {
    int cfd = ::accept(fd_, nullptr, nullptr);
    if (cfd < 0) throw LinkError(std::string("accept failed: ") + strerror(errno));
    return TcpSocket(cfd);
  }

  // Connect with retry (peers may not be listening yet during rendezvous).
  void Connect(const std::string& host, int port, int retries = 30,
               int retry_ms = 200);

  // Blocking exact-size IO.  Throws LinkError on reset/close.
  void SendAll(const void* data, size_t nbytes);
  void RecvAll(void* data, size_t nbytes);

  // Protocol primitives (little-endian u32 + length-prefixed strings,
  // mirroring rabit_tpu/tracker/protocol.py).
  void SendU32(uint32_t v) { SendAll(&v, 4); }
  uint32_t RecvU32() {
    uint32_t v;
    RecvAll(&v, 4);
    return v;
  }
  void SendU64(uint64_t v) { SendAll(&v, 8); }
  uint64_t RecvU64() {
    uint64_t v;
    RecvAll(&v, 8);
    return v;
  }
  void SendStr(const std::string& s) {
    SendU32(static_cast<uint32_t>(s.size()));
    SendAll(s.data(), s.size());
  }
  std::string RecvStr() {
    uint32_t n = RecvU32();
    std::string s(n, '\0');
    RecvAll(s.data(), n);
    return s;
  }

 private:
  int fd_ = -1;
};

// Process-wide link IO timeout (seconds) for the poll-based Exchange
// path; engines set it from rabit_timeout_sec / RABIT_TIMEOUT_SEC.
void SetLinkTimeoutSec(double sec);
double GetLinkTimeoutSec();

// Full-duplex streaming: send `send_data` to one socket while filling
// `recv_buf` from another (they may be the same socket in a world of two).
// The ring primitives rely on this to avoid deadlock without threads.
void Exchange(TcpSocket& send_sock, const uint8_t* send_data, size_t nsend,
              TcpSocket& recv_sock, uint8_t* recv_buf, size_t nrecv);

}  // namespace rabit_tpu
