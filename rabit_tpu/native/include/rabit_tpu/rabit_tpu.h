// Public user-facing C++ API.
// TPU-native rebuild of the reference's template API surface
// (reference: include/rabit.h:58-326 — Init/Finalize/GetRank/
// GetWorldSize/Allreduce<OP>/Broadcast/LoadCheckPoint/CheckPoint/
// VersionNumber/TrackerPrint; template plumbing include/rabit/rabit-inl.h).
// One header: templates dispatch onto the process-wide engine singleton
// (runtime variant selection via rabit_engine=empty|base|robust|mock,
// unlike the reference's five compile-time library flavours).
#pragma once

#include <cstdint>
#include <cstring>
#include <string>
#include <type_traits>
#include <vector>

#include "rabit_tpu/engine.h"
#include "rabit_tpu/serializable.h"

namespace rabit_tpu {

// Engine singleton management (implemented in c_api.cc; shared with the
// C ABI so C++ and ctypes callers see the same engine).
IEngine* GetEngine();
void InitEngine(const std::vector<std::string>& args);
void FinalizeEngine();

// ---- reduction op tags (reference: include/rabit/rabit-inl.h:55-92) ----
namespace op {
struct Max {
  static constexpr ReduceOp kOp = ReduceOp::kMax;
};
struct Min {
  static constexpr ReduceOp kOp = ReduceOp::kMin;
};
struct Sum {
  static constexpr ReduceOp kOp = ReduceOp::kSum;
};
struct Prod {
  static constexpr ReduceOp kOp = ReduceOp::kProd;
};
struct BitOR {
  static constexpr ReduceOp kOp = ReduceOp::kBitOr;
};
struct BitAND {
  static constexpr ReduceOp kOp = ReduceOp::kBitAnd;
};
struct BitXOR {
  static constexpr ReduceOp kOp = ReduceOp::kBitXor;
};
}  // namespace op

// ---- C++ type -> wire dtype (reference: include/rabit/rabit-inl.h:17-52)
template <typename T>
struct DataTypeOf;
template <>
struct DataTypeOf<int8_t> {
  static constexpr DataType kType = DataType::kInt8;
};
template <>
struct DataTypeOf<uint8_t> {
  static constexpr DataType kType = DataType::kUInt8;
};
template <>
struct DataTypeOf<int32_t> {
  static constexpr DataType kType = DataType::kInt32;
};
template <>
struct DataTypeOf<uint32_t> {
  static constexpr DataType kType = DataType::kUInt32;
};
template <>
struct DataTypeOf<int64_t> {
  static constexpr DataType kType = DataType::kInt64;
};
template <>
struct DataTypeOf<uint64_t> {
  static constexpr DataType kType = DataType::kUInt64;
};
template <>
struct DataTypeOf<float> {
  static constexpr DataType kType = DataType::kFloat32;
};
template <>
struct DataTypeOf<double> {
  static constexpr DataType kType = DataType::kFloat64;
};

// ---- lifecycle (reference: include/rabit.h:58-78) ----
inline void Init(int argc, char* argv[]) {
  std::vector<std::string> args;
  for (int i = 0; i < argc; ++i) args.emplace_back(argv[i]);
  InitEngine(args);
}

inline void Finalize() { FinalizeEngine(); }

inline int GetRank() { return GetEngine()->rank(); }
inline int GetWorldSize() { return GetEngine()->world_size(); }
inline bool IsDistributed() { return GetWorldSize() != 1; }
inline std::string GetProcessorName() { return GetEngine()->host(); }
inline void TrackerPrint(const std::string& msg) {
  GetEngine()->TrackerPrint(msg);
}

// ---- collectives (reference: include/rabit.h:110-163) ----
// In-place allreduce: sendrecvbuf holds the local input and receives the
// global result.  `prepare` (optional) lazily fills the buffer and is
// skipped when a cached result is replayed during recovery.
template <typename OP, typename T>
void Allreduce(T* sendrecvbuf, size_t count,
               const PrepareFn& prepare = nullptr) {
  GetEngine()->Allreduce(sendrecvbuf, count, DataTypeOf<T>::kType, OP::kOp,
                         prepare);
}

// Any-root broadcast of a fixed-size buffer (reference: include/rabit.h:80-108).
inline void Broadcast(void* sendrecvbuf, size_t size, int root) {
  std::string tmp;
  if (GetEngine()->rank() == root) {
    tmp.assign(static_cast<const char*>(sendrecvbuf), size);
  }
  GetEngine()->Broadcast(&tmp, root);
  if (GetEngine()->rank() != root) {
    Check(tmp.size() == size, "Broadcast: payload size mismatch");
    std::memcpy(sendrecvbuf, tmp.data(), size);
  }
}

inline void Broadcast(std::string* sendrecv_data, int root) {
  GetEngine()->Broadcast(sendrecv_data, root);
}

template <typename T>
void Broadcast(std::vector<T>* sendrecv_data, int root) {
  std::string tmp;
  if (GetEngine()->rank() == root) {
    tmp.assign(reinterpret_cast<const char*>(sendrecv_data->data()),
               sendrecv_data->size() * sizeof(T));
  }
  GetEngine()->Broadcast(&tmp, root);
  sendrecv_data->resize(tmp.size() / sizeof(T));
  if (!tmp.empty()) {
    std::memcpy(sendrecv_data->data(), tmp.data(), tmp.size());
  }
}

// Allgather: every rank's fixed-size block, rank order (an extension
// over the reference API — first-class on TPU and used by rabit-learn).
template <typename T>
void Allgather(const T* mine, size_t count, std::vector<T>* out) {
  out->resize(count * GetWorldSize());
  GetEngine()->Allgather(mine, count * sizeof(T), out->data());
}

// ---- checkpointing (reference: include/rabit.h:165-234) ----
// Returns the version to resume from (0 = fresh start); fills the models
// from the replicated in-memory checkpoint otherwise.
inline int LoadCheckPoint(ISerializable* global_model,
                          ISerializable* local_model = nullptr) {
  std::string global_bytes, local_bytes;
  int version = GetEngine()->LoadCheckPoint(
      &global_bytes, local_model != nullptr ? &local_bytes : nullptr);
  if (version != 0) {
    MemoryBufferStream gs(&global_bytes);
    global_model->Load(gs);
    if (local_model != nullptr && !local_bytes.empty()) {
      MemoryBufferStream ls(&local_bytes);
      local_model->Load(ls);
    }
  }
  return version;
}

inline void CheckPoint(const ISerializable* global_model,
                       const ISerializable* local_model = nullptr) {
  std::string global_bytes, local_bytes;
  MemoryBufferStream gs(&global_bytes);
  global_model->Save(gs);
  if (local_model != nullptr) {
    MemoryBufferStream ls(&local_bytes);
    local_model->Save(ls);
  }
  GetEngine()->CheckPoint(&global_bytes,
                          local_model != nullptr ? &local_bytes : nullptr);
}

// LazyCheckPoint: stores the model pointer; serialization happens only
// if a recovering peer (or a local load) actually needs the payload.
// The model must stay alive and unmodified-between-checkpoints, exactly
// the reference's contract (reference: include/rabit.h:211-234).
inline void LazyCheckPoint(const ISerializable* global_model,
                           const ISerializable* local_model = nullptr) {
  std::string local_bytes;
  if (local_model != nullptr) {
    MemoryBufferStream ls(&local_bytes);
    local_model->Save(ls);
  }
  GetEngine()->LazyCheckPoint(
      [global_model] {
        std::string bytes;
        MemoryBufferStream ms(&bytes);
        global_model->Save(ms);
        return bytes;
      },
      local_model != nullptr ? &local_bytes : nullptr);
}

inline int VersionNumber() { return GetEngine()->version_number(); }

// ---- custom reducers (reference: include/rabit.h:236-326,
//      include/rabit/rabit-inl.h:198-308) ----

// Element-wise custom reduction over a trivially copyable struct:
//   rabit_tpu::Reducer<MyPair, MyPairReduce> red;
//   red.Allreduce(buf, n);
template <typename DType, void (*freduce)(DType& dst, const DType& src)>
class Reducer {
  static_assert(std::is_trivially_copyable<DType>::value,
                "Reducer needs a flat struct with no pointers");

 public:
  void Allreduce(DType* sendrecvbuf, size_t count,
                 const PrepareFn& prepare = nullptr) {
    GetEngine()->AllreduceCustom(
        sendrecvbuf, count, sizeof(DType),
        [](void* dst, const void* src, size_t n) {
          DType* d = static_cast<DType*>(dst);
          const DType* s = static_cast<const DType*>(src);
          for (size_t i = 0; i < n; ++i) freduce(d[i], s[i]);
        },
        prepare);
  }
};

// Custom reduction over serializable objects: each object marshals into
// a fixed max_nbyte slot; the wire reducer deserializes the incoming
// slot and calls DType::Reduce(src, max_nbyte).  DType must provide
// Load(IStream&), Save(IStream&) const, Reduce(const DType&, size_t).
template <typename DType>
class SerializeReducer {
 public:
  void Allreduce(DType* sendrecvobj, size_t max_nbyte, size_t count,
                 const PrepareFn& prepare = nullptr) {
    buffer_.resize(max_nbyte * count);
    // marshal (after the lazy prepare, which fills the objects)
    auto marshal = [&] {
      if (prepare) prepare();
      for (size_t i = 0; i < count; ++i) {
        MemoryFixSizeBuffer fs(&buffer_[i * max_nbyte], max_nbyte);
        sendrecvobj[i].Save(fs);
      }
    };
    GetEngine()->AllreduceCustom(
        buffer_.data(), count, max_nbyte,
        [max_nbyte](void* dst, const void* src, size_t n) {
          for (size_t i = 0; i < n; ++i) {
            DType dobj, sobj;
            MemoryFixSizeBuffer ds(static_cast<char*>(dst) + i * max_nbyte,
                                   max_nbyte);
            dobj.Load(ds);
            MemoryFixSizeBuffer ss(
                const_cast<char*>(static_cast<const char*>(src)) +
                    i * max_nbyte,
                max_nbyte);
            sobj.Load(ss);
            dobj.Reduce(sobj, max_nbyte);
            MemoryFixSizeBuffer out(static_cast<char*>(dst) + i * max_nbyte,
                                    max_nbyte);
            dobj.Save(out);
          }
        },
        marshal);
    for (size_t i = 0; i < count; ++i) {
      MemoryFixSizeBuffer fs(&buffer_[i * max_nbyte], max_nbyte);
      sendrecvobj[i].Load(fs);
    }
  }

 private:
  std::string buffer_;
};

}  // namespace rabit_tpu
