// Fault-tolerant engine: cached-result replay + in-memory checkpoint
// recovery over the base engine's collectives.
//
// TPU-native rebuild of the reference robust engine (reference:
// src/allreduce_robust.{h,cc}).  The shape is the same — every collective
// first runs a tiny consensus allreduce deciding "execute for real" vs
// "serve/receive recovery data" (reference: RecoverExec,
// src/allreduce_robust.cc:832-902); results are cached with striped
// replication (:21-35,86-89); failures tear links down and re-rendezvous
// with the tracker (:426-453) — but the mechanics are redesigned:
//
// * The consensus word carries {flags, min seqno, max version} (12 bytes)
//   instead of packing flags+seqno into one u32 (reference:
//   src/allreduce_robust.h:163-235).  Carrying the version makes the
//   checkpoint commit window race-free without the reference's special
//   seqno encodings: a node that missed the commit round learns the epoch
//   advanced (kDiffVersion) and commits immediately.
// * Recovery data routing is a consensus-selected root + the base tree
//   flood, replacing the reference's two-round shortest-path message
//   passing (reference: TryDecideRouting/TryRecoverData,
//   src/allreduce_robust.cc:526-700).  Every serving round is derived
//   from the (identical) consensus word, so all nodes take the same
//   action each round and link traffic never interleaves mismatched
//   message types.
// * Local checkpoints replicate to ring successors and recover via
//   backward/forward ring floods (reference: ring CSR double-buffer,
//   src/allreduce_robust.h:536-547, :919-1102), implemented as tagged
//   blob maps instead of CSR offsets.
#pragma once

#include <array>
#include <cstdint>
#include <map>
#include <set>
#include <string>

#include "rabit_tpu/base_engine.h"

namespace rabit_tpu {

class RobustEngine : public BaseEngine {
 public:
  void Allreduce(void* buf, size_t count, DataType dtype, ReduceOp op,
                 const PrepareFn& prepare = nullptr) override;
  void AllreduceCustom(void* buf, size_t count, size_t item_size,
                       const CustomReducer& reducer,
                       const PrepareFn& prepare = nullptr) override;
  void Broadcast(std::string* data, int root) override;
  void Allgather(const void* mine, size_t nbytes, void* out) override;
  int LoadCheckPoint(std::string* global_model,
                     std::string* local_model) override;
  void CheckPoint(const std::string* global_model,
                  const std::string* local_model) override;
  void LazyCheckPoint(const std::function<std::string()>& get_global,
                      const std::string* local_model) override;
  void Shutdown() override;
  void Init(const std::vector<std::pair<std::string, std::string>>& params)
      override;

  // True iff the LAST collective's result was served from the replay
  // cache because the op had already completed before this rank joined
  // (a relaunched rank catching up).  Mid-op recovery — this rank
  // participated, a peer died, the result was recovered — counts as
  // fresh: the value belongs to the current round.  The XLA engine uses
  // this to avoid ACTING on a replayed device-plane re-formation (the
  // group described by a stale coordinator payload predates this
  // incarnation).
  bool last_op_replayed() const { return last_replayed_; }

  // Lifetime-cumulative count of retired cache buffers swapped back
  // into service.  An OBSERVABLE for tests: the recycle path once
  // regressed invisibly (a capacity()==0 gate never matched moved-from
  // strings' 15-byte SSO capacity) because nothing asserted it fires.
  size_t pool_hits() const { return pool_hits_; }

 protected:
  // Consensus flags (reference analogue: src/allreduce_robust.h:163-235).
  enum : uint32_t {
    kLoadCheck = 1,   // a (re)started node wants the latest checkpoint
    kCheckPoint = 2,  // at the checkpoint barrier
    kCheckAck = 4,    // committed, waiting for everyone to commit
    kShutdown = 8,    // finished the program, serving stragglers
    kDiffSeq = 16,    // derived: seqnos differ -> serve min
    kDiffVersion = 32,  // derived: versions differ -> commit catch-up
    kLocalChk = 64,   // this checkpoint carries a local model
  };

  struct Word {
    uint32_t flags;
    uint32_t seq;
    uint32_t version;
  };
  static void ReduceWord(void* dst, const void* src, size_t count);

  // Fault-injection hook (overridden by MockEngine).
  virtual void Verify(uint32_t seqno) { (void)seqno; }
  // Sentinel seqnos for Verify at non-collective calls.
  static constexpr uint32_t kSeqCheckPoint = 1u << 20;
  static constexpr uint32_t kSeqLoadCheck = (1u << 20) + 1;

  // The recovery state machine.  Loops consensus rounds, serving recovery
  // data, until the whole world is aligned at (my_flag, seq_, version_).
  // Returns true if the caller's own operation was satisfied from a cached
  // result (filled into *recovered) — the caller must NOT execute it.
  bool RecoverExec(uint32_t my_flag, std::string* recovered);

  // One consensus allreduce with failure recovery built in.
  Word Consensus(uint32_t my_flag);
  // Agree on a serving root: max (key, then lowest rank); kNoRoot if none.
  static constexpr uint64_t kNoRoot = 0;
  int AgreeRoot(bool i_have, uint64_t key);

  // Serving rounds (all ranks participate; idempotent under retry).
  void ServeResult(uint32_t seq, std::string* recovered, bool* filled);
  bool ServeCheckpointLoad(bool i_am_loader);  // true once loader satisfied
  void CommitCheckPoint();
  void CheckPointImpl(const std::string* local_model);
  void ReplicateLocal();
  void RecoverLocal();
  void RingPassBlobs(bool backward);

  // Run a collective with recovery: returns true if result came from
  // cache (buf filled), false if executed for real.  When the caller
  // already ran RecoverExec for this seq, pass initial_recover=false to
  // skip the duplicate consensus round.
  bool RunCollective(uint8_t* buf, size_t nbytes,
                     const std::function<void()>& real_op,
                     bool initial_recover = true);
  void PushResult(const uint8_t* buf, size_t nbytes);
  void PushResultOwned(std::string&& blob);
  // Drop cache entries outside this rank's stripe.  Called at the top of
  // every collective AFTER the consensus round (the reference's DropLast
  // boundary) — never at push time, so a mid-op death can recover the
  // newest result from any completer.
  void PruneStale();
  bool Striped(uint32_t seq) const;

  uint32_t seq_ = 0;
  std::map<uint32_t, std::string> cache_;  // seq -> result bytes (this epoch)
  int num_global_replica_ = 5;  // reference default, doc/README.md "Parameters"
  int num_local_replica_ = 2;
  // Per-attempt working copy of the collective input: the op runs on
  // this buffer (user buffer stays pristine for retry after a failure),
  // and on success it is moved into the result cache — one payload copy
  // total, mirroring the reference's temp-inside-ResultBuffer trick
  // (reference: src/allreduce_robust.cc:91-97).  Its backing store
  // rotates through pool_: striped pruning and checkpoint clears stash
  // retired cache buffers, RefillAttempt draws them back, so the steady
  // state fresh-allocates no payload memory (fresh pages cost ~2 ms of
  // kernel zeroing + faults per 4 MB op — the dominant term of the
  // former robust steady-state tax; doc/benchmarks.md round 5).
  std::string attempt_;
  static constexpr int kPoolSize = 3;
  std::array<std::string, kPoolSize> pool_;
  size_t pool_hits_ = 0;
  void StashRetired(std::string&& blob);
  void RefillAttempt();
  // Recycle all retiring cache buffers into pool_ (called before
  // cache_.clear() at checkpoint commits and checkpoint loads).
  void HarvestCache();
  bool last_replayed_ = false;
  // Pending checkpoint state between barrier and commit.
  std::string pending_global_;
  bool has_pending_local_ = false;
  std::string pending_local_;
  // Lazy checkpoint: committed serializer invoked on demand
  // (MaterializeGlobal) when a peer or a local load needs the bytes.
  std::function<std::string()> pending_lazy_;
  std::function<std::string()> lazy_global_;
  void MaterializeGlobal();
  // origin rank -> (version, blob) for ring-replicated local models.
  std::map<int, std::pair<int, std::string>> local_store_;
};

class MockEngine : public RobustEngine {
 public:
  void Init(const std::vector<std::pair<std::string, std::string>>& params)
      override;

  // With report_stats=1, per-version timing (time inside collectives,
  // inside CheckPoint, and between checkpoints) plus the checkpoint
  // payload size are shipped to the tracker on every CheckPoint —
  // including custom-reduce/allgather time and lazy checkpoints
  // (reference: src/allreduce_mock.h:44-96 report_stats).
  void Allreduce(void* buf, size_t count, DataType dtype, ReduceOp op,
                 const PrepareFn& prepare = nullptr) override;
  void AllreduceCustom(void* buf, size_t count, size_t item_size,
                       const CustomReducer& reducer,
                       const PrepareFn& prepare = nullptr) override;
  void Allgather(const void* mine, size_t nbytes, void* out) override;
  void Broadcast(std::string* data, int root) override;
  void CheckPoint(const std::string* global_model,
                  const std::string* local_model) override;
  void LazyCheckPoint(const std::function<std::string()>& get_global,
                      const std::string* local_model) override;

 protected:
  // Kill-point: exit(254) when this rank reaches (version, seqno) on its
  // ndeath-th life (reference: src/allreduce_mock.h:139-171; the launcher
  // restarts on 254 and bumps RABIT_NUM_TRIAL).
  void Verify(uint32_t seqno) override;

 private:
  struct Key {
    int version;
    uint32_t seqno;
    int ndeath;
    bool operator<(const Key& o) const {
      if (version != o.version) return version < o.version;
      if (seqno != o.seqno) return seqno < o.seqno;
      return ndeath < o.ndeath;
    }
  };
  std::set<Key> kill_points_;
  int num_trial_ = 0;
  // report_stats accounting (all in seconds of wall clock)
  bool report_stats_ = false;
  double tsum_allreduce_ = 0.0;
  double time_checkpoint_ = 0.0;  // when the last CheckPoint finished
  // Shared stats emission for CheckPoint and LazyCheckPoint.
  void ReportVersionStats(double t0, double t1, size_t chkpt_bytes);
};

}  // namespace rabit_tpu
