// Abstract engine interface + op/dtype enums for the native library.
// TPU-native rebuild of the reference engine contract
// (reference: include/rabit/engine.h:22-157 IEngine, :169-186 enums).
// Payloads are raw byte buffers; reduction semantics come from the
// (dtype, op) pair — enum values are ABI-stable and shared with the
// Python layer (rabit_tpu/ops/reduce_ops.py).
#pragma once

#include <cstddef>
#include <cstdint>
#include <functional>
#include <string>
#include <vector>

namespace rabit_tpu {

enum class ReduceOp : int {
  kMax = 0,
  kMin = 1,
  kSum = 2,
  kProd = 3,
  kBitOr = 4,
  kBitAnd = 5,
  kBitXor = 6,
};

enum class DataType : int {
  kInt8 = 0,
  kUInt8 = 1,
  kInt32 = 2,
  kUInt32 = 3,
  kInt64 = 4,
  kUInt64 = 5,
  kFloat32 = 6,
  kFloat64 = 7,
  kBFloat16 = 8,
  kFloat16 = 9,
};

size_t ItemSize(DataType dtype);

// dst[i] = dst[i] OP src[i] for count elements.
using ReduceFn = void (*)(void* dst, const void* src, size_t count);
ReduceFn GetReducer(DataType dtype, ReduceOp op);

// User-defined reduction (custom ops beyond the enum set; reference:
// ReduceHandle, include/rabit/engine.h:215-253).  Same element-wise
// contract as ReduceFn, but may capture state.
using CustomReducer = std::function<void(void* dst, const void* src,
                                         size_t count)>;

// Lazy-preparation hook: fills the send buffer; skipped when a cached
// result is replayed during recovery (reference: include/rabit/engine.h:58-76).
using PrepareFn = std::function<void()>;

class IEngine {
 public:
  virtual ~IEngine() = default;

  virtual void Init(const std::vector<std::pair<std::string, std::string>>&
                        params) = 0;
  virtual void Shutdown() = 0;

  virtual int rank() const = 0;
  virtual int world_size() const = 0;
  virtual std::string host() const = 0;

  // In-place allreduce of count elements of dtype.
  virtual void Allreduce(void* buf, size_t count, DataType dtype, ReduceOp op,
                         const PrepareFn& prepare = nullptr) = 0;
  // In-place allreduce with a user-defined element reducer (count
  // elements of item_size bytes each; same order/recovery semantics as
  // Allreduce).
  virtual void AllreduceCustom(void* buf, size_t count, size_t item_size,
                               const CustomReducer& reducer,
                               const PrepareFn& prepare = nullptr) = 0;
  // Any-root broadcast; on non-roots `*data` is resized and filled.
  virtual void Broadcast(std::string* data, int root) = 0;
  // Gather every rank's nbytes block into out (world * nbytes).
  virtual void Allgather(const void* mine, size_t nbytes, void* out) = 0;

  // Checkpointing (the base engine keeps these process-local; the robust
  // engine replicates and recovers them).
  virtual int LoadCheckPoint(std::string* global_model,
                             std::string* local_model) = 0;
  virtual void CheckPoint(const std::string* global_model,
                          const std::string* local_model) = 0;
  // LazyCheckPoint: commit the version without serializing; the engine
  // invokes `get_global` only when the payload is actually needed (a
  // recovering peer requests it, or a local load) — zero serialization
  // cost in the steady state (reference: LazyCheckPoint,
  // src/allreduce_robust.h:125-127, allreduce_robust.cc:744-751).
  // Default: eager.
  virtual void LazyCheckPoint(const std::function<std::string()>& get_global,
                              const std::string* local_model) {
    std::string global = get_global();
    CheckPoint(&global, local_model);
  }
  virtual int version_number() const = 0;

  virtual void TrackerPrint(const std::string& msg) = 0;
};

}  // namespace rabit_tpu
