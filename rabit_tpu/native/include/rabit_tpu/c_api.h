// C ABI for FFI (ctypes) access to the native engine.
// TPU-native rebuild of the reference wrapper ABI
// (reference: wrapper/rabit_wrapper.h:25-121).  Differences: every call
// returns 0/-1 (or a value) instead of exiting on error — the message is
// retrievable via RbtTpuGetLastError — and blob transfers use
// library-owned buffers valid until the next call on the same thread.
#pragma once

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

// All functions returning int use 0 = success, -1 = failure (see
// RbtTpuGetLastError), unless documented otherwise.

int RbtTpuInit(int argc, const char** argv);  // argv: "name=value" params
int RbtTpuFinalize(void);

int RbtTpuGetRank(void);        // -1 on error
int RbtTpuGetWorldSize(void);   // -1 on error
int RbtTpuIsDistributed(void);
int RbtTpuGetProcessorName(char* out, size_t max_len);
const char* RbtTpuGetLastError(void);

int RbtTpuTrackerPrint(const char* msg);

// In-place allreduce of `count` items of `dtype` (enum values shared with
// rabit_tpu/ops/reduce_ops.py).  `prepare` may be NULL; when given it is
// invoked with `prepare_arg` before communication (and skipped if a cached
// result is replayed during recovery).
int RbtTpuAllreduce(void* buf, size_t count, int dtype, int op,
                    void (*prepare)(void*), void* prepare_arg);

// In-place allreduce with a user-defined element reducer: `reducer` is
// called as reducer(dst, src, count, arg) and must fold src into dst
// element-wise (`count` elements of `item_size` bytes).  Same ordering
// and recovery semantics as RbtTpuAllreduce.
int RbtTpuAllreduceCustom(void* buf, size_t count, size_t item_size,
                          void (*reducer)(void* dst, const void* src,
                                          size_t count, void* arg),
                          void* reducer_arg,
                          void (*prepare)(void*), void* prepare_arg);

// Fixed-size broadcast: every rank passes a `size`-byte buffer; the root's
// contents end up everywhere.
int RbtTpuBroadcast(void* buf, size_t size, int root);

// Variable-size broadcast: root passes (in, in_len); all ranks receive the
// payload via (*out, *out_len), a library-owned buffer valid until the
// next RbtTpu* call on this thread.
int RbtTpuBroadcastBlob(const char* in, size_t in_len, int root,
                        const char** out, size_t* out_len);

// Gather each rank's nbytes into out (world_size * nbytes, rank order).
int RbtTpuAllgather(const void* mine, size_t nbytes, void* out);

// Checkpointing.  LoadCheckPoint returns the version (0 = fresh start);
// pointers are library-owned, valid until the next RbtTpu* call.
int RbtTpuLoadCheckPoint(const char** global_ptr, size_t* global_len,
                         const char** local_ptr, size_t* local_len);
int RbtTpuCheckPoint(const char* global, size_t global_len,
                     const char* local, size_t local_len);  // local may be NULL

// Lazy checkpoint: `serialize` is invoked only when the payload is
// actually needed (a recovering peer, or a local load); it must return a
// pointer valid until it is called again or the next checkpoint, and set
// *len.  The callback must stay callable until the next RbtTpu*CheckPoint.
int RbtTpuLazyCheckPoint(const char* (*serialize)(size_t* len, void* arg),
                         void* arg,
                         const char* local, size_t local_len);
int RbtTpuVersionNumber(void);

// Debug/observability: payload bytes this rank has SENT through the
// requester-routed recovery broadcast (TreeRoutedBroadcast).  Used by
// tests to assert recovery traffic scales with requesters, not world
// size.  Returns 0 for engines without a link layer.
unsigned long long RbtTpuDebugRoutedBytes(void);

// Debug/observability: largest per-op collective scratch allocation so
// far.  Tests assert it stays within the rabit_reduce_buffer budget.
// Returns 0 for engines without a link layer.
unsigned long long RbtTpuDebugScratchPeakBytes(void);

// 1 iff the tracker flagged this process as a mid-job relaunch (a
// start re-registration of a task_id that already completed a round).
// 0 for engines without a tracker.
int RbtTpuWasRelaunched(void);

// 1 iff the last collective's result was served from the replay cache
// (the op completed before this relaunched rank joined).  0 for
// non-robust engines and for current-round results, including mid-op
// recovery.
int RbtTpuLastReplayed(void);

#ifdef __cplusplus
}
#endif
