"""Public user-facing API.

TPU-native equivalent of the reference's user API surface
(reference: include/rabit.h:58-326 — Init/Finalize/GetRank/GetWorldSize/
Allreduce/Broadcast/LoadCheckPoint/CheckPoint/LazyCheckPoint/VersionNumber/
TrackerPrint; Python mirror wrapper/rabit.py:54-306).

Arrays: numpy arrays are reduced in place (like the reference's ``void*``
buffers); ``jax.Array`` inputs are routed through the engine's
device-resident path and a new array is returned (JAX arrays are
immutable).  Python objects use pickle for broadcast/checkpoint, matching
the reference wrapper.
"""
from __future__ import annotations

import pickle
from typing import Any, Callable, Optional

import numpy as np

from rabit_tpu import engine as _engine_mod
from rabit_tpu.ops import ReduceOp, SUM
from rabit_tpu.utils.checks import check
from rabit_tpu.utils.serial import deserialize_model, serialize_model


def init(args: Optional[list[str]] = None, **params: Any) -> None:
    """Initialise the framework.

    ``args`` accepts reference-style ``name=value`` strings
    (reference: src/engine.cc:31-39); keyword params win on conflict.
    Recognised keys include ``rabit_engine``
    (empty|pysocket|pyrobust|native|mock|xla),
    ``rabit_tracker_uri``, ``rabit_tracker_port``, ``rabit_task_id``,
    ``rabit_reduce_buffer``, ``rabit_global_replica``,
    ``rabit_local_replica``, ``rabit_ckpt_dir`` (durable checkpoint
    tier) and ``rabit_heartbeat_sec`` (proactive liveness) — the full
    catalogue is doc/parameters.md.
    Environment variables prefixed ``RABIT_`` are read as defaults.
    """
    import os

    merged: dict[str, Any] = {}
    for key, val in os.environ.items():
        if key.startswith("RABIT_"):
            merged[key.lower()] = val
    for a in args or []:
        if "=" in a:
            k, v = a.split("=", 1)
            merged[k] = v
    merged.update(params)
    _engine_mod.init(merged)


def finalize() -> None:
    """Shut down the engine (reference: rabit::Finalize)."""
    _engine_mod.finalize()


def initialized() -> bool:
    return _engine_mod.initialized()


def get_rank() -> int:
    return _engine_mod.get_engine().rank


def get_world_size() -> int:
    # Note: the reference Python wrapper's get_world_size was broken by a
    # typo'd symbol name (reference: wrapper/rabit.py:90) — parity not kept.
    return _engine_mod.get_engine().world_size


def get_processor_name() -> str:
    return _engine_mod.get_engine().host


def is_distributed() -> bool:
    return _engine_mod.get_engine().is_distributed()


def tracker_print(msg: str) -> None:
    _engine_mod.get_engine().tracker_print(str(msg))


def allreduce(
    data,
    op: ReduceOp = SUM,
    prepare_fun: Optional[Callable[[], None]] = None,
    codec: bool = True,
):
    """Allreduce an array across all ranks.

    numpy input: reduced **in place** and returned (matching the reference's
    in-place Allreduce, include/rabit.h:134-137).  jax input: returns a new
    device-resident array.  ``prepare_fun`` is the lazy-preparation hook,
    skipped when a cached result is replayed during recovery.

    ``codec=False`` opts this op out of an armed lossy wire codec
    (``rabit_wire_codec=bf16|int8|int4`` — doc/performance.md
    "Quantized wire codecs"): a precision-critical op (an optimizer
    direction, a convergence test) keeps exact full-width bytes while
    the bulk traffic stays quantized.  Program order, hence
    deterministic across ranks — like ``fuse`` on the async face.
    """
    eng = _engine_mod.get_engine()
    if isinstance(data, np.ndarray):
        check(data.flags.c_contiguous, "allreduce: array must be C-contiguous")
        return eng.allreduce(data, op, prepare_fun, codec)
    try:
        import jax
    except ImportError:  # pragma: no cover
        jax = None
    if jax is not None and isinstance(data, jax.Array):
        return eng.allreduce(data, op, prepare_fun, codec)
    # scalars / lists: round-trip through numpy
    arr = np.asarray(data)
    scalar = arr.ndim == 0
    arr = np.atleast_1d(arr).copy()
    out = eng.allreduce(arr, op, prepare_fun, codec)
    return out[0] if scalar else out


def allreduce_async(
    data: np.ndarray,
    op: ReduceOp = SUM,
    prepare_fun: Optional[Callable[[], None]] = None,
    fuse: bool = True,
    codec: bool = True,
):
    """Issue an allreduce without blocking; returns a
    :class:`~rabit_tpu.engine.interface.CollectiveHandle` whose
    ``wait()`` yields the reduced array (the same in-place semantics as
    :func:`allreduce`).

    On the socket engines the op is driven by a background progress
    thread, so host compute overlaps the wire; small same-op/same-dtype
    payloads issued back to back coalesce into one fused wire op
    (``rabit_bucket_bytes`` — doc/performance.md).  A bucketed op only
    reaches the wire when its bucket flushes, so pass ``fuse=False``
    for a lone latency-sensitive op with no stream behind it — it
    dispatches eagerly and genuinely overlaps the caller's compute.
    Handles must be waited in issue order; the array must not be read
    or written between issue and ``wait()``.  Engines without an async
    path run the op synchronously and return a resolved handle, so
    callers never need a capability check.  ``codec=False`` opts the
    op out of an armed lossy wire codec (see :func:`allreduce`).
    """
    eng = _engine_mod.get_engine()
    check(isinstance(data, np.ndarray) and data.flags.c_contiguous,
          "allreduce_async: need a C-contiguous numpy array")
    return eng.allreduce_async(data, op, prepare_fun, fuse=fuse,
                               codec=codec)


def allgather_async(data: np.ndarray):
    """Issue an allgather without blocking; ``wait()`` returns the
    (world, *shape) stacked array (see :func:`allreduce_async` for the
    ordering and aliasing rules)."""
    eng = _engine_mod.get_engine()
    check(isinstance(data, np.ndarray) and data.flags.c_contiguous,
          "allgather_async: need a C-contiguous numpy array")
    return eng.allgather_async(data)


def allreduce_many(arrays, op: ReduceOp = SUM) -> list:
    """Allreduce a batch of independent arrays as one fused operation.

    Blocking-API face of the bucket coalescer: every array is issued
    async, the engine fuses eligible ones into shared wire ops, and the
    results come back in order — bit-identical to reducing each array
    with :func:`allreduce` separately, but with one wire op per
    ``rabit_bucket_bytes`` of payload instead of one per array.
    """
    eng = _engine_mod.get_engine()
    check(len(arrays) > 0, "allreduce_many: need at least one array")
    for a in arrays:
        check(isinstance(a, np.ndarray) and a.flags.c_contiguous,
              "allreduce_many: need C-contiguous numpy arrays")
    handles = [eng.allreduce_async(a, op) for a in arrays]
    return [h.wait() for h in handles]


def allreduce_custom(
    data: np.ndarray,
    reducer: Callable[[np.ndarray, np.ndarray], None],
    prepare_fun: Optional[Callable[[], None]] = None,
) -> np.ndarray:
    """Allreduce with a user-defined reduction function.

    ``reducer(dst, src)`` folds ``src`` into ``dst`` in place, row-wise
    over axis 0, and must be associative.  The Python face of the
    reference's C++-only custom-reducer surface
    (reference: rabit::Reducer, include/rabit.h:236-276); on the native
    engine the C++ robust protocol runs the tree and calls back per
    merge, with full cache/replay recovery semantics.
    """
    eng = _engine_mod.get_engine()
    check(isinstance(data, np.ndarray) and data.flags.c_contiguous,
          "allreduce_custom: need a C-contiguous numpy array")
    return eng.allreduce_custom(data, reducer, prepare_fun)


def broadcast(data: Any, root: int) -> Any:
    """Broadcast an arbitrary picklable object from ``root`` to all ranks.

    Two-phase (length, then payload), matching the reference wrapper
    (reference: wrapper/rabit.py:117-168).  At this layer both phases fold
    into one length-prefixed engine broadcast.
    """
    eng = _engine_mod.get_engine()
    check(0 <= root < eng.world_size, "broadcast: invalid root %d", root)
    payload = pickle.dumps(data) if eng.rank == root else None
    raw = eng.broadcast(payload, root)
    return pickle.loads(raw)


def allgather(data) -> np.ndarray:
    """Gather each rank's array; returns shape (world, *data.shape).

    jax inputs keep the device-resident path (engines with a device data
    plane gather over ICI); everything else goes through numpy.
    """
    eng = _engine_mod.get_engine()
    try:
        import jax
    except ImportError:  # pragma: no cover
        jax = None
    if jax is not None and isinstance(data, jax.Array):
        return eng.allgather(data)
    return eng.allgather(np.ascontiguousarray(data))


def load_checkpoint(with_local: bool = False, into_global: Any = None,
                    into_local: Any = None):
    """Load the latest in-memory checkpoint.

    Returns ``(version, global_model)`` or ``(version, global_model,
    local_model)`` when ``with_local``; version 0 means fresh start
    (reference: wrapper/rabit.py:232-266, src/allreduce_robust.cc:159-196).

    Models checkpointed through a custom :class:`Serializable` must be
    restored into an instance: pass it as ``into_global``/``into_local``
    (mirroring the reference's LoadCheckPoint(ISerializable*) contract).
    """
    eng = _engine_mod.get_engine()
    version, g, l = eng.load_checkpoint()
    gobj = (deserialize_model(g, into_global)
            if (g is not None and version > 0) else None)
    if with_local:
        lobj = (deserialize_model(l, into_local)
                if (l is not None and version > 0) else None)
        return version, gobj, lobj
    return version, gobj


def checkpoint(global_model: Any, local_model: Any = None) -> None:
    """Commit a checkpoint of the model(s); bumps the version
    (reference: rabit::CheckPoint, src/allreduce_robust.cc:242-295)."""
    eng = _engine_mod.get_engine()
    eng.checkpoint(
        serialize_model(global_model),
        serialize_model(local_model) if local_model is not None else None,
    )


def lazy_checkpoint(global_model: Any) -> None:
    """Checkpoint that defers serialization until a peer needs the payload
    (reference: rabit::LazyCheckPoint, src/allreduce_robust.h:125-127)."""
    eng = _engine_mod.get_engine()
    eng.checkpoint(None, None, lazy_global=lambda: serialize_model(global_model))


def version_number() -> int:
    return _engine_mod.get_engine().version_number


def device_epoch() -> int:
    """Device-plane epoch: bumped when the XLA engine re-forms the
    device mesh after a failure (engines without a device plane always
    report 0).  Device arrays created under an older epoch are dead —
    apps that keep shards resident re-upload when this moves, then
    continue from their last checkpoint state."""
    return getattr(_engine_mod.get_engine(), "device_epoch", 0)
