"""Host-collective microbenchmark worker (``bench.py --suite collectives``).

Run under the local launcher (one process per rank, loopback TCP):

    python -m rabit_tpu.tracker.launch_local -n 4 -- \
        python -m rabit_tpu.tools.collectives_bench OUT.json

Measures, per payload size, the MB/s of four host paths — ``tree``
(crossover pinned high), ``ring`` (crossover pinned low), ``async``
(handle stream, fusion off) and ``bucketed`` (handle stream, fusion on)
— plus the headline stream benchmark: 64 x 256 KB sum-allreduces,
sequential blocking vs bucketed/async (doc/performance.md).  Every
timed pass is verified against the exact expected sum, so a wire bug
can never masquerade as a fast run.  Rank 0 writes the JSON.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

import rabit_tpu
from rabit_tpu.engine import pysocket
from rabit_tpu.ops import SUM

STREAM_OPS = 64
STREAM_BYTES = 256 << 10
SIZES_BYTES = [4 << 10, 64 << 10, 256 << 10, 1 << 20, 4 << 20]
REPEAT = 3


def barrier() -> None:
    rabit_tpu.allreduce(np.zeros(1, np.float32), SUM)


def make_stream(nops: int, nelem: int, rank: int) -> list[np.ndarray]:
    return [np.full(nelem, float(rank + 1 + (i % 7)), np.float32)
            for i in range(nops)]


def check_stream(arrays: list[np.ndarray], world: int) -> None:
    for i, a in enumerate(arrays):
        expect = world * (world + 1) / 2.0 + world * (i % 7)
        if a[0] != expect or a[-1] != expect:
            raise AssertionError(
                f"stream op {i}: got {a[0]}/{a[-1]}, want {expect}")


def run_blocking(arrays: list[np.ndarray]) -> None:
    for a in arrays:
        rabit_tpu.allreduce(a, SUM)


def run_handles(arrays: list[np.ndarray]) -> None:
    handles = [rabit_tpu.allreduce_async(a, SUM) for a in arrays]
    for h in handles:
        h.wait()


def time_path(fn, nops: int, nelem: int, rank: int, world: int) -> float:
    """Best-of-REPEAT wall seconds for one pass of ``nops`` ops
    (barrier-bracketed so every rank times the same window)."""
    best = float("inf")
    for _ in range(REPEAT):
        arrays = make_stream(nops, nelem, rank)
        barrier()
        t0 = time.perf_counter()
        fn(arrays)
        dt = time.perf_counter() - t0
        barrier()
        check_stream(arrays, world)
        best = min(best, dt)
    return best


def main() -> None:
    out_path = sys.argv[1] if len(sys.argv) > 1 else None
    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    from rabit_tpu import engine as engine_mod

    eng = engine_mod.get_engine()
    crossover = pysocket.TREE_RING_CROSSOVER_BYTES
    bucket = eng._bucket_bytes

    # ---- headline stream: 64 x 256KB, blocking vs bucketed/async ----
    nelem = STREAM_BYTES // 4
    t_block = time_path(run_blocking, STREAM_OPS, nelem, rank, world)
    t_fused = time_path(run_handles, STREAM_OPS, nelem, rank, world)
    mbs = STREAM_OPS * STREAM_BYTES / 1e6
    stream = {
        "ops": STREAM_OPS, "payload_bytes": STREAM_BYTES,
        "blocking_MBps": round(mbs / t_block, 1),
        "fused_MBps": round(mbs / t_fused, 1),
        "speedup": round(t_block / t_fused, 3),
    }

    # ---- per-size path table ----------------------------------------
    sizes: dict[str, dict[str, float]] = {}
    for size in SIZES_BYTES:
        nelem = size // 4
        nops = max(8, min(64, (8 << 20) // size))
        row: dict[str, float] = {}
        try:
            pysocket.TREE_RING_CROSSOVER_BYTES = 1 << 62
            row["tree"] = nops * size / 1e6 / time_path(
                run_blocking, nops, nelem, rank, world)
            pysocket.TREE_RING_CROSSOVER_BYTES = 0
            row["ring"] = nops * size / 1e6 / time_path(
                run_blocking, nops, nelem, rank, world)
        finally:
            pysocket.TREE_RING_CROSSOVER_BYTES = crossover
        try:
            eng._bucket_bytes = 0  # async overlap only, no fusion
            row["async"] = nops * size / 1e6 / time_path(
                run_handles, nops, nelem, rank, world)
        finally:
            eng._bucket_bytes = bucket
        row["bucketed"] = nops * size / 1e6 / time_path(
            run_handles, nops, nelem, rank, world)
        sizes[str(size)] = {k: round(v, 1) for k, v in row.items()}

    if rank == 0 and out_path:
        with open(out_path, "w") as f:
            json.dump({"world": world, "stream": stream, "sizes": sizes,
                       "engine_stats": eng.stats()}, f, indent=2)
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
