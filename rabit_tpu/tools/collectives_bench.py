"""Host-collective microbenchmark worker (``bench.py --suite collectives``).

Run under the local launcher (one process per rank, loopback TCP):

    python -m rabit_tpu.tracker.launch_local -n 4 -- \
        python -m rabit_tpu.tools.collectives_bench OUT.json \
            [--sizes 4KB,64KB,1MB] [--tune-dir DIR]

Measures, per payload size, the MB/s of every applicable collective
schedule (``tree``/``ring``/``halving``/``swing``/``hier`` — forced via
the engine's schedule hook) plus the non-schedule paths ``static`` (the
tree/ring crossover dispatch), ``async`` (handle stream, fusion off)
and ``bucketed`` (handle stream, fusion on), and the headline stream
benchmark: 64 x 256 KB sum-allreduces, sequential blocking vs
bucketed/async (doc/performance.md).  Every timed pass is verified
against the exact expected sum, so a wire bug can never masquerade as a
fast run.

Rank 0 writes the JSON — stamped with a schema version and host/world
metadata, because the auto-tuner's cache format depends on it — and,
given ``--tune-dir``, persists the measured winners as a
:class:`rabit_tpu.sched.TuningCache` for ``rabit_sched=auto``.
"""
from __future__ import annotations

import argparse
import json
import socket as socket_mod
import sys
import time

import numpy as np

import rabit_tpu
from rabit_tpu import sched as sched_mod
from rabit_tpu.ops import SUM
from rabit_tpu.utils.units import parse_byte_size

#: bump when the JSON layout changes (the tuner reads the sizes table)
SCHEMA_VERSION = 2

STREAM_OPS = 64
STREAM_BYTES = 256 << 10
DEFAULT_SIZES = [4 << 10, 64 << 10, 256 << 10, 1 << 20, 4 << 20]
REPEAT = 3


def barrier() -> None:
    rabit_tpu.allreduce(np.zeros(1, np.float32), SUM)


def make_stream(nops: int, nelem: int, rank: int) -> list[np.ndarray]:
    return [np.full(nelem, float(rank + 1 + (i % 7)), np.float32)
            for i in range(nops)]


#: verification tolerance per wire codec: the classic wire must be
#: bit-exact; a lossy codec run is checked against its documented
#: accuracy envelope instead (doc/performance.md "Quantized wire
#: codecs") — a wire bug still cannot masquerade as a fast run, it
#: would blow far past one quantization step.
CODEC_TOL = {"none": 0.0, "bf16": 0.02, "int8": 0.05, "int4": 0.3,
             # fp8's error is relative to the VALUE (float format), not
             # the block absmax, but the stream payloads are constant
             # blocks whose normalized value 1.0 encodes exactly — the
             # envelope only has to absorb merge-order rounding.
             "fp8e4m3": 0.1, "fp8e5m2": 0.15}


def check_stream(arrays: list[np.ndarray], world: int,
                 tol: float = 0.0) -> None:
    for i, a in enumerate(arrays):
        expect = world * (world + 1) / 2.0 + world * (i % 7)
        if not len(a):
            continue
        err = max(abs(float(a[0]) - expect), abs(float(a[-1]) - expect))
        # `not (err <= bound)`, NEVER `err > bound`: a NaN result (an
        # overflowed scale, torn bytes decoded as NaN) compares False
        # both ways, and the inverted form keeps it a hard failure.
        if not (err <= tol * abs(expect)):
            raise AssertionError(
                f"stream op {i}: got {a[0]}/{a[-1]}, want {expect} "
                f"(tol {tol})")


def run_blocking(arrays: list[np.ndarray]) -> None:
    for a in arrays:
        rabit_tpu.allreduce(a, SUM)


def run_handles(arrays: list[np.ndarray]) -> None:
    handles = [rabit_tpu.allreduce_async(a, SUM) for a in arrays]
    for h in handles:
        h.wait()


def time_once(fn, nops: int, nelem: int, rank: int, world: int,
              tol: float = 0.0) -> float:
    """Wall seconds for ONE pass of ``nops`` ops (barrier-bracketed so
    every rank times the same window), result-verified."""
    arrays = make_stream(nops, nelem, rank)
    barrier()
    t0 = time.perf_counter()
    fn(arrays)
    dt = time.perf_counter() - t0
    barrier()
    check_stream(arrays, world, tol)
    return dt


def time_path(fn, nops: int, nelem: int, rank: int, world: int,
              tol: float = 0.0, repeat: int = REPEAT) -> float:
    """Best-of-``repeat`` wall seconds for one pass of ``nops`` ops."""
    return min(time_once(fn, nops, nelem, rank, world, tol)
               for _ in range(repeat))


def time_paths(paths, nops: int, nelem: int, rank: int,
               world: int, tol: float = 0.0,
               repeat: int = REPEAT) -> dict[str, float]:
    """Best-of-``repeat`` seconds per labeled path, with the candidates
    INTERLEAVED across trials (one full pass over all of them per
    trial) so a transient load burst perturbs every candidate instead
    of sinking whichever one it happened to land on — the same
    measurement discipline as the kmeans suite."""
    best = {label: float("inf") for label, _setup, _fn in paths}
    for _ in range(repeat):
        for label, setup, fn in paths:
            cleanup = setup() if setup is not None else None
            try:
                dt = time_once(fn, nops, nelem, rank, world, tol)
            finally:
                if cleanup is not None:
                    cleanup()
            best[label] = min(best[label], dt)
    return best


def parse_sizes(raw: str | None) -> list[int]:
    if not raw:
        return list(DEFAULT_SIZES)
    return [parse_byte_size(tok) for tok in raw.split(",") if tok.strip()]


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("out", nargs="?", default=None,
                    help="JSON output path (rank 0 writes it)")
    ap.add_argument("--sizes", default=None,
                    help="comma-separated payload sizes (byte suffixes "
                         "OK, e.g. 4KB,64KB,1MB) overriding the default "
                         "ladder — tuning sweeps need not hard-code it")
    ap.add_argument("--tune-dir", default=None,
                    help="persist the measured per-size winners as a "
                         "sched tuning cache here (rabit_sched=auto "
                         "reads it via rabit_tune_dir)")
    ap.add_argument("--repeat", type=int, default=REPEAT,
                    help="interleaved best-of trials per path (default "
                         f"{REPEAT}; raise it for noisy-box A/Bs like "
                         "the paced pipeline passes)")
    ap.add_argument("--trace-ab", action="store_true",
                    help="measure the blocking stream twice, "
                         "interleaved inside ONE run: causal hop "
                         "tracing armed (the launch's "
                         "RABIT_TRACE_SAMPLE) vs disarmed — the paired "
                         "A/B the trace-overhead budget is verified "
                         "on, immune to the cross-launch baseline "
                         "jitter that dominates oversubscribed boxes "
                         "(sampling is a per-rank perf knob, "
                         "byte-stream invariant, so toggling it "
                         "mid-run is safe; same discipline as "
                         "--pipe-depths)")
    ap.add_argument("--kernel-ab", action="store_true",
                    help="measure the blocking stream twice, "
                         "interleaved inside ONE run: compiled codec "
                         "kernel bound (native) vs unbound (the numpy "
                         "reference) — the paired A/B the native-"
                         "kernel speedup is recorded from.  The impl "
                         "is a per-rank perf knob, bit-identical by "
                         "contract (codec/kernel.py), so rebinding it "
                         "mid-run is safe; same discipline as "
                         "--trace-ab.  Requires an armed block-scale "
                         "codec; degrades to a recorded skip when the "
                         "library is not built")
    ap.add_argument("--pipe-depths", default=None,
                    help="comma list of rabit_pipeline_depth values: "
                         "adds ring_dN/halving_dN/bucketed_dN per-size "
                         "paths with the hop-pipeline depth forced to "
                         "N — depth A/B stays interleaved inside ONE "
                         "run, immune to cross-launch box noise (depth "
                         "is a per-rank perf knob, byte-stream "
                         "invariant, so forcing it mid-run is safe)")
    args = ap.parse_args()

    rabit_tpu.init()
    rank = rabit_tpu.get_rank()
    world = rabit_tpu.get_world_size()
    from rabit_tpu import engine as engine_mod

    eng = engine_mod.get_engine()
    mode = eng._sched_name
    bucket = eng._bucket_bytes
    sizes_bytes = parse_sizes(args.sizes)
    tol = CODEC_TOL.get(getattr(eng, "_codec_label", "none"), 0.0)

    # ---- headline stream: 64 x 256KB, blocking vs bucketed/async ----
    nelem = STREAM_BYTES // 4
    t_block = time_path(run_blocking, STREAM_OPS, nelem, rank, world,
                        tol, args.repeat)
    t_fused = time_path(run_handles, STREAM_OPS, nelem, rank, world,
                        tol, args.repeat)
    mbs = STREAM_OPS * STREAM_BYTES / 1e6
    stream = {
        "ops": STREAM_OPS, "payload_bytes": STREAM_BYTES,
        "blocking_MBps": round(mbs / t_block, 1),
        "fused_MBps": round(mbs / t_fused, 1),
        "speedup": round(t_block / t_fused, 3),
    }
    if args.trace_ab:
        # Paired tracing A/B (doc/observability.md "Causal tracing &
        # postmortem"): the same process, sockets and stream, with the
        # per-op sampling rate toggled between trials.  trace_sampled()
        # is deterministic in the replicated op seqno, so every rank
        # flips identically and the wire stays lockstep.
        sample0 = getattr(eng, "_trace_sample", 0)

        def force_sample(v):
            eng._trace_sample = v
            return lambda: setattr(eng, "_trace_sample", sample0)

        ab = time_paths(
            [("traced", (lambda: force_sample(sample0)), run_blocking),
             ("untraced", (lambda: force_sample(0)), run_blocking)],
            STREAM_OPS, nelem, rank, world, tol, args.repeat)
        stream["blocking_MBps_traced"] = round(mbs / ab["traced"], 1)
        stream["blocking_MBps_untraced"] = round(mbs / ab["untraced"], 1)
        stream["trace_sample"] = sample0
    if args.kernel_ab:
        # Paired native-kernel A/B (doc/benchmarks.md "Codec kernel
        # A/B"): the same process, sockets and stream, with the
        # compiled hop kernel bound vs unbound between interleaved
        # trials.  Both sides are bit-identical by contract, so the
        # check_stream verification doubles as the honesty guard.
        from rabit_tpu import codec as codec_mod

        codec = getattr(eng, "_codec", None)
        kern = codec_mod.load() if hasattr(codec, "_bind_kernel") else None
        if kern is None:
            # A skip is RECORDED, never silent: a bench row that quietly
            # measured numpy-vs-numpy would report speedup 1.0 as if the
            # kernel had been tried and found worthless.
            stream["kernel_ab_skipped"] = (
                "no block-scale codec armed" if not hasattr(
                    codec, "_bind_kernel")
                else f"kernel unavailable: {codec_mod.load_error()}")
        else:
            k0 = codec._k

            def force_kernel(k):
                codec._bind_kernel(k)
                return lambda: codec._bind_kernel(k0)

            ab = time_paths(
                [("native", (lambda: force_kernel(kern)), run_blocking),
                 ("numpy", (lambda: force_kernel(None)), run_blocking)],
                STREAM_OPS, nelem, rank, world, tol, args.repeat)
            stream["blocking_MBps_native"] = round(mbs / ab["native"], 1)
            stream["blocking_MBps_numpy"] = round(mbs / ab["numpy"], 1)
            stream["kernel_speedup"] = round(
                ab["numpy"] / ab["native"], 3)

    # ---- per-size path table: every applicable schedule + the ------
    # ---- static dispatch + async/bucketed handle streams -----------
    sizes: dict[str, dict[str, float]] = {}
    sched_names = [n for n, s in sched_mod.SCHEDULES.items()
                   if s.applies(eng, 1)]
    for size in sizes_bytes:
        nelem = max(size // 4, 1)
        nops = max(8, min(64, (8 << 20) // max(size, 1)))

        def force(name):
            eng.set_schedule(name)
            return lambda: eng.set_schedule(mode)

        def nofuse():
            eng._bucket_bytes = 0  # async overlap only, no fusion

            def restore():
                eng._bucket_bytes = bucket
            return restore

        paths = ([(name, (lambda n=name: force(n)), run_blocking)
                  for name in sched_names]
                 + [("static", lambda: force("static"), run_blocking),
                    ("async", nofuse, run_handles),
                    ("bucketed", None, run_handles)])
        if args.pipe_depths:
            depth0 = eng._pipe_depth

            def force_depth(name, dd):
                eng._pipe_depth = dd
                restore_sched = force(name) if name else None

                def restore():
                    eng._pipe_depth = depth0
                    if restore_sched is not None:
                        restore_sched()
                return restore

            for dstr in args.pipe_depths.split(","):
                dd = int(dstr)
                for name in ("ring", "halving"):
                    if name in sched_names:
                        paths.append(
                            (f"{name}_d{dd}",
                             (lambda n=name, d=dd: force_depth(n, d)),
                             run_blocking))
                paths.append((f"bucketed_d{dd}",
                              (lambda d=dd: force_depth(None, d)),
                              run_handles))
        timed = time_paths(paths, nops, nelem, rank, world, tol,
                           args.repeat)
        sizes[str(size)] = {label: round(nops * size / 1e6 / dt, 1)
                            for label, dt in timed.items()}

    host = socket_mod.gethostname()
    if rank == 0:
        data = {
            "schema": SCHEMA_VERSION,
            "host": host,
            "world": world,
            "groups": list(eng._groups),
            "transport": getattr(eng, "_transport_label", "tcp"),
            "codec": getattr(eng, "_codec_label", "none"),
            "pipeline_depth": getattr(eng, "_pipe_depth", 1),
            "engine": type(eng).__name__,
            "schedules": sched_names,
            "stream": stream,
            "sizes": sizes,
            "engine_stats": eng.stats(),
        }
        if args.out:
            with open(args.out, "w") as f:
                json.dump(data, f, indent=2)
        if args.tune_dir:
            # The transport AND wire codec this world measured on key
            # the cache rows (allreduce vs allreduce@shm vs
            # allreduce+int8 — sched/tuner.py table_kind): schedule
            # crossovers genuinely differ between loopback TCP and shm
            # rings, and between full-width and quantized wires whose
            # per-payload bytes differ 2-4x — auto picks must never
            # bleed across either dimension.
            transport = getattr(eng, "_transport_label", "tcp")
            codec = getattr(eng, "_codec_label", "none")
            cache = sched_mod.TuningCache.from_bench(
                sizes, world, host=host,
                candidates=set(sched_names), transport=transport,
                codec=codec,
                extra_meta={"bench": "collectives",
                            "sizes": sorted(int(s) for s in sizes),
                            "pipeline_depth": getattr(eng, "_pipe_depth",
                                                      1)})
            prior = sched_mod.TuningCache.load(args.tune_dir)
            if prior is not None:
                # Merge-don't-clobber, per (kind, world): a tcp pass, a
                # shm pass and runs at other world sizes all land in
                # ONE cache file — this run's rows win only for the
                # exact (kind, world) cells it actually measured, so a
                # world-2 transport pass can never erase the flagship
                # world-4 rows the nearest-world fallback serves.
                merged = {k: dict(w) for k, w in prior.table.items()}
                for kind, worlds in cache.table.items():
                    merged.setdefault(kind, {}).update(worlds)
                cache.table = merged
            path = cache.save(args.tune_dir)
            print(f"collectives_bench: wrote tuning cache to {path} "
                  f"(transport={transport}, codec={codec})",
                  file=sys.stderr, flush=True)
    rabit_tpu.finalize()


if __name__ == "__main__":
    main()
