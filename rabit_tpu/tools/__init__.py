"""Operational tools: benchmarks and sweep drivers."""
