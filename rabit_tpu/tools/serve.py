"""Serving-fleet supervisor: spawn, watch, autoscale, drain.

``python -m rabit_tpu.tools.serve`` runs the operator-side half of the
serving plane (doc/serving.md): it owns a tracker (or attaches to an
existing multi-tenant one), spawns ``--workers`` serving-rank
processes (rabit_tpu/serve/server.py) registered as one tenant job,
and closes the loop on fleet size and health:

* **Queue-depth-driven elastic autoscaling**: every ``--tick-sec`` the
  supervisor polls each rank's ctrl ``stats``; a mean queue depth over
  ``--scale-high`` for ``--scale-checks`` consecutive ticks spawns a
  joiner (admitted by the tracker's elastic machinery at the serve
  world's next commit boundary — PR 6's rescale choreography), and a
  fleet idle under ``--scale-low`` for as long drains the newest rank
  — never outside ``[--min-workers, --max-workers]``.
* **Health gating**: a rank whose stats poll keeps failing, whose own
  health verdict says failing, or whose heartbeat the tracker declared
  dead is killed and (budget permitting) replaced by a fresh joiner;
  a rank that exits with the deliberate EXIT_DRAINED code chose to
  leave (self health-gate or scale-down) and costs no restart.
* Every scale/health decision is appended to ``--state-json`` (one
  rolling JSON document) so drivers (tools/soak.py --serve) can assert
  the choreography from outside.

The tracker half of autoscaling is ordinary elastic membership: the
supervisor only decides *how many* ranks should exist; epochs, rank
reassignment and the workers' WorldChangedError adoption are exactly
the machinery training jobs already use.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time

from rabit_tpu.serve import protocol as SP
from rabit_tpu.serve.server import EXIT_DRAINED
from rabit_tpu.tracker import protocol as P


def _ctrl(host: str, port: int, cmd: str, timeout: float = 2.0) -> str:
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.settimeout(timeout)
        return SP.send_ctrl(s, cmd)


def parse_slow_task_ms(spec: str) -> dict[str, float]:
    """Parse a ``task:ms[,task:ms...]`` per-task slowdown spec (e.g.
    ``"s001:100"``) into ``{task_id: slow_ms}``.  Tasks not named fall
    back to the fleet-wide ``--slow-ms``.  The QoS soak uses this to
    manufacture exactly one straggler rank and assert the router
    shifts traffic off it."""
    out: dict[str, float] = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        task, _, ms = part.partition(":")
        task, ms = task.strip(), ms.strip()
        if not task or not ms:
            raise ValueError(
                f"bad --slow-task-ms entry {part!r} (want task:ms)")
        out[task] = float(ms)
    return out


class _Rank:
    """One spawned serving-rank process + its endpoint bookkeeping."""

    def __init__(self, task_id: str, proc: subprocess.Popen,
                 endpoints_dir: str) -> None:
        self.task_id = task_id
        self.proc = proc
        self.endpoints_dir = endpoints_dir
        self.stat_failures = 0
        self.draining = False
        self.published = False   # has it ever published its endpoint?
        self.spawned_at = time.monotonic()

    def endpoint(self) -> tuple[str, int] | None:
        path = os.path.join(self.endpoints_dir, f"{self.task_id}.json")
        try:
            with open(path) as f:
                doc = json.load(f)
            return str(doc["host"]), int(doc["port"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def stats(self) -> dict | None:
        ep = self.endpoint()
        if ep is None:
            return None
        try:
            return json.loads(_ctrl(ep[0], ep[1], SP.CTRL_STATS))
        except (OSError, ValueError):
            return None

    def drain(self) -> bool:
        ep = self.endpoint()
        if ep is None:
            return False
        try:
            _ctrl(ep[0], ep[1], SP.CTRL_DRAIN)
            self.draining = True
            return True
        except OSError:
            return False


class ServeSupervisor:
    def __init__(self, args) -> None:
        self.args = args
        self.ranks: list[_Rank] = []
        self.events: list[dict] = []
        self._seq = 0
        self._restarts_left = args.max_restarts
        self._high_ticks = 0
        self._low_ticks = 0
        self.tracker = None          # in-process tracker when owned
        self._stop = False
        self._slow_tasks = parse_slow_task_ms(args.slow_task_ms)

    # -- bookkeeping ---------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        ev = {"ts": time.time(), "kind": kind, **fields}
        self.events.append(ev)
        print(f"[serve] {kind}: "
              + " ".join(f"{k}={v}" for k, v in fields.items()),
              flush=True)
        self._write_state()

    def _write_state(self) -> None:
        if not self.args.state_json:
            return
        doc = {
            "ts": time.time(),
            "fleet": [{"task_id": r.task_id, "pid": r.proc.pid,
                       "alive": r.proc.poll() is None,
                       "draining": r.draining}
                      for r in self.ranks],
            "alive": sum(1 for r in self.ranks
                         if r.proc.poll() is None),
            "restarts_left": self._restarts_left,
            "events": self.events[-256:],
        }
        tmp = f"{self.args.state_json}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, self.args.state_json)
        except OSError as e:
            print(f"[serve] state-json write failed: {e}",
                  file=sys.stderr, flush=True)

    # -- tracker -------------------------------------------------------
    def _tracker_addr(self) -> tuple[str, int]:
        if self.args.directory:
            from rabit_tpu.tracker.directory import DirectoryClient

            client = DirectoryClient(self.args.directory)
            owner = client.owner(self.args.job or P.DEFAULT_JOB)
            if owner is None:
                raise SystemExit(
                    f"[serve] directory {self.args.directory} has no "
                    "registered shards")
            idx, host, port = owner
            self._event("directory", shard=idx, host=host, port=port,
                        generation=client.generation)
            return host, port
        if self.args.tracker:
            host, port = self.args.tracker.rsplit(":", 1)
            return host, int(port)
        from rabit_tpu.tracker.tracker import Tracker

        self.tracker = Tracker(
            self.args.workers, host="127.0.0.1",
            min_workers=self.args.min_workers,
            max_workers=self.args.max_workers,
            max_jobs=self.args.max_jobs,
            obs_port=self.args.obs_port)
        self.tracker.start()
        self._event("tracker", host=self.tracker.host,
                    port=self.tracker.port,
                    obs_port=self.tracker.obs_port)
        return self.tracker.host, self.tracker.port

    # -- rank lifecycle ------------------------------------------------
    def _spawn(self, reason: str) -> _Rank:
        args = self.args
        self._seq += 1
        task_id = f"s{self._seq:03d}"
        env = dict(os.environ)
        env.update({
            "RABIT_TRACKER_URI": self._addr[0],
            "RABIT_TRACKER_PORT": str(self._addr[1]),
            "RABIT_TASK_ID": task_id,
            "RABIT_WORLD_SIZE": str(args.workers),
            "RABIT_ENGINE": args.engine,
            "RABIT_ELASTIC": "1",
            "RABIT_HEARTBEAT_SEC": str(args.heartbeat_sec),
            "RABIT_OBS": "1",
            "RABIT_OBS_FLUSH_SEC": str(args.obs_flush_sec),
        })
        if args.job and args.job != P.DEFAULT_JOB:
            env["RABIT_JOB_ID"] = args.job
        if args.directory:
            env["RABIT_DIRECTORY"] = args.directory
        slow_ms = self._slow_tasks.get(task_id, args.slow_ms)
        cmd = [sys.executable, "-m", "rabit_tpu.serve.run",
               "--model-dir", args.model_dir,
               "--endpoints-dir", args.endpoints_dir,
               "--batch-max", str(args.batch_max),
               "--batch-wait-ms", str(args.batch_wait_ms),
               "--queue-max", str(args.queue_max),
               "--sync-sec", str(args.sync_sec),
               "--slow-ms", str(slow_ms)]
        if args.qos_budgets:
            cmd += ["--qos-budgets", args.qos_budgets]
        if args.dedup_window is not None:
            cmd += ["--dedup-window", str(args.dedup_window)]
        proc = subprocess.Popen(cmd, env=env)
        rank = _Rank(task_id, proc, args.endpoints_dir)
        self.ranks.append(rank)
        self._event("spawn", task=task_id, pid=proc.pid, why=reason)
        return rank

    def _alive(self) -> list[_Rank]:
        return [r for r in self.ranks if r.proc.poll() is None]

    def _reap(self) -> None:
        """Notice exits: a drained exit is a deliberate leave; a
        signal death spends a restart (fresh joiner) while the elastic
        epoch absorbs the old rank."""
        for rank in list(self.ranks):
            code = rank.proc.poll()
            if code is None:
                continue
            self.ranks.remove(rank)
            # A SIGKILLed rank cannot unpublish itself: reap its stale
            # endpoint file so the load balancers rotate it out now
            # instead of burning requests on a corpse.
            try:
                os.remove(os.path.join(self.args.endpoints_dir,
                                       f"{rank.task_id}.json"))
            except OSError:
                pass
            if code == EXIT_DRAINED and rank.draining:
                # A drain the SUPERVISOR ordered (scale-down): the
                # shrink is the point — no replacement owed.
                self._event("left", task=rank.task_id, code=code)
                continue
            if code == EXIT_DRAINED:
                # The rank's own health gate drained it (batcher died,
                # self-detected failure).  Clean exit or not, it is a
                # LOSS the fleet floor must recover from — fall
                # through to the below-min replacement check (budget-
                # bounded like any death).
                self._event("left", task=rank.task_id, code=code,
                            why="self health gate")
            else:
                self._event("died", task=rank.task_id, code=code)
            if len(self._alive()) < self.args.min_workers:
                if self._restarts_left > 0:
                    self._restarts_left -= 1
                    self._spawn(f"replace {rank.task_id} "
                                f"(exit {code})")
                else:
                    self._event("restart_budget_exhausted",
                                task=rank.task_id)

    # -- autoscale + health --------------------------------------------
    def _tick(self) -> None:
        self._reap()
        alive = self._alive()
        depths = []
        for rank in alive:
            if rank.draining:
                continue
            if not rank.published:
                # A joiner is still starting (interpreter + jax import
                # + parking at the tracker until the serve world's
                # next commit boundary admits it): no endpoint is not
                # a health verdict yet.  Only a rank that blows the
                # whole startup budget without ever publishing is
                # killed.
                if rank.endpoint() is None:
                    if (time.monotonic() - rank.spawned_at
                            > self.args.startup_timeout):
                        self._event("health_kill", task=rank.task_id,
                                    why="never published an endpoint "
                                        "within the startup budget")
                        try:
                            rank.proc.kill()
                        except OSError:
                            pass
                    continue
                rank.published = True
                self._event("published", task=rank.task_id)
            st = rank.stats()
            if st is None:
                rank.stat_failures += 1
                if rank.stat_failures >= self.args.health_fails:
                    self._event("health_kill", task=rank.task_id,
                                why="stats poll kept failing")
                    try:
                        rank.proc.kill()
                    except OSError:
                        pass  # already gone; _reap accounts it
                continue
            rank.stat_failures = 0
            if str(st.get("health", "ok")) != "ok":
                # The rank's own gate will drain it; make sure.
                if rank.drain():
                    self._event("health_drain", task=rank.task_id,
                                why=st.get("health"))
                continue
            depths.append(float(st.get("queue_depth", 0)))
        if not depths:
            return
        mean_depth = sum(depths) / len(depths)
        serving = len(depths)
        # The --max-workers cap counts every alive non-draining rank —
        # including published ranks whose stats poll just timed out
        # (likely during the very overload that triggers scaling) and
        # joiners still starting — so a flaky poll can never push the
        # fleet past the bound the elastic world assumes is hard.
        fleet_now = sum(1 for r in alive if not r.draining)
        if mean_depth >= self.args.scale_high \
                and fleet_now < self.args.max_workers:
            self._high_ticks += 1
            self._low_ticks = 0
            if self._high_ticks >= self.args.scale_checks:
                self._high_ticks = 0
                self._event("scale_up", mean_depth=round(mean_depth, 1),
                            serving=serving)
                self._spawn(f"queue depth {mean_depth:.1f} >= "
                            f"{self.args.scale_high}")
        elif mean_depth <= self.args.scale_low \
                and serving > self.args.min_workers:
            self._low_ticks += 1
            self._high_ticks = 0
            if self._low_ticks >= self.args.scale_checks:
                self._low_ticks = 0
                victim = next((r for r in reversed(self._alive())
                               if not r.draining), None)
                if victim is not None and victim.drain():
                    self._event("scale_down", task=victim.task_id,
                                mean_depth=round(mean_depth, 1))
        else:
            self._high_ticks = 0
            self._low_ticks = 0


    # -- run -----------------------------------------------------------
    def run(self) -> int:
        args = self.args
        os.makedirs(args.endpoints_dir, exist_ok=True)
        self._addr = self._tracker_addr()
        for _ in range(args.workers):
            self._spawn("initial fleet")
        # Wait for the initial fleet to publish endpoints.
        deadline = time.monotonic() + args.startup_timeout
        while time.monotonic() < deadline:
            if sum(1 for r in self.ranks
                   if r.endpoint() is not None) >= args.workers:
                break
            self._reap()
            time.sleep(0.2)
        else:
            self._event("startup_timeout",
                        published=sum(1 for r in self.ranks
                                      if r.endpoint() is not None))
            self.shutdown()
            return 1
        self._event("ready", workers=args.workers)

        def _on_term(_sig, _frm):
            self._stop = True
        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)

        t_end = (time.monotonic() + args.duration if args.duration
                 else None)
        try:
            while not self._stop:
                time.sleep(args.tick_sec)
                if t_end is not None and time.monotonic() > t_end:
                    break
                if args.stop_file and os.path.exists(args.stop_file):
                    self._event("stop_file")
                    break
                self._tick()
                self._write_state()
                if not self._alive() and self._restarts_left <= 0:
                    self._event("fleet_gone")
                    return 1
        finally:
            self.shutdown()
        return 0

    def shutdown(self) -> None:
        self._event("shutdown", alive=len(self._alive()))
        for rank in self._alive():
            try:
                rank.proc.terminate()
            except OSError:
                pass  # already exited; wait() below reaps it
        deadline = time.monotonic() + 10
        for rank in self.ranks:
            left = max(deadline - time.monotonic(), 0.1)
            try:
                rank.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                rank.proc.kill()
        if self.tracker is not None:
            self.tracker.stop()
        self._write_state()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="rabit_tpu serving-fleet supervisor "
                    "(doc/serving.md)")
    ap.add_argument("--model-dir", required=True)
    ap.add_argument("--endpoints-dir", required=True)
    ap.add_argument("--workers", type=int, default=2,
                    help="initial serving world size")
    ap.add_argument("--min-workers", type=int, default=None)
    ap.add_argument("--max-workers", type=int, default=None)
    ap.add_argument("--tracker", default=None, metavar="HOST:PORT",
                    help="attach to an existing multi-tenant tracker "
                         "instead of owning one (the tracker must run "
                         "elastic for autoscaling to move the world)")
    ap.add_argument("--directory", default=None, metavar="URL",
                    help="attach through a sharded-tracker directory: "
                         "the serving job lands on its hash-owned shard "
                         "and ranks carry RABIT_DIRECTORY so they "
                         "re-resolve the owner across shard failover")
    ap.add_argument("--job", default="serve",
                    help="tenant job name on the tracker")
    ap.add_argument("--engine", default="pyrobust")
    ap.add_argument("--max-jobs", type=int, default=None,
                    help="owned-tracker admission bound (co-tenant "
                         "training next to serving)")
    ap.add_argument("--obs-port", type=int, default=None,
                    help="owned tracker: serve /metrics + /status here")
    ap.add_argument("--batch-max", type=int, default=16)
    ap.add_argument("--batch-wait-ms", type=float, default=5.0)
    ap.add_argument("--queue-max", type=int, default=256)
    ap.add_argument("--sync-sec", type=float, default=0.5)
    ap.add_argument("--slow-ms", type=float, default=0.0)
    ap.add_argument("--slow-task-ms", default="",
                    metavar="TASK:MS[,TASK:MS...]",
                    help="per-task --slow-ms overrides keyed by task id "
                         "(e.g. 's001:100' makes the first spawned rank "
                         "a deliberate straggler; others keep --slow-ms)")
    ap.add_argument("--qos-budgets", default="",
                    help="per-class admission budgets passed through to "
                         "every rank (see rabit_tpu/serve/server.py)")
    ap.add_argument("--dedup-window", type=int, default=None,
                    help="idempotency-cache capacity passed through to "
                         "every rank (default: the rank's own default)")
    ap.add_argument("--heartbeat-sec", type=float, default=0.3)
    ap.add_argument("--obs-flush-sec", type=float, default=0.5)
    ap.add_argument("--scale-high", type=float, default=None,
                    help="mean queue depth per rank that triggers "
                         "scale-up (default 2*batch_max)")
    ap.add_argument("--scale-low", type=float, default=-1.0,
                    help="mean queue depth under which an idle fleet "
                         "scales down (default -1 = never shrink; "
                         "pass 0 to drain idle ranks)")
    ap.add_argument("--scale-checks", type=int, default=3,
                    help="consecutive ticks over/under the watermark "
                         "before acting (hysteresis)")
    ap.add_argument("--tick-sec", type=float, default=1.0)
    ap.add_argument("--health-fails", type=int, default=3,
                    help="consecutive failed stats polls before the "
                         "supervisor kills a rank")
    ap.add_argument("--max-restarts", type=int, default=4)
    ap.add_argument("--duration", type=float, default=0.0,
                    help="exit after this many seconds (0 = run until "
                         "SIGTERM / --stop-file)")
    ap.add_argument("--stop-file", default=None)
    ap.add_argument("--state-json", default=None,
                    help="rolling supervisor state document (fleet, "
                         "scale/health events) for external drivers")
    ap.add_argument("--startup-timeout", type=float, default=60.0)
    args = ap.parse_args(argv)
    if args.min_workers is None:
        args.min_workers = args.workers
    if args.max_workers is None:
        args.max_workers = max(args.workers, args.min_workers)
    if args.scale_high is None:
        args.scale_high = 2.0 * args.batch_max
    P.require_valid_job_id(args.job)
    try:
        parse_slow_task_ms(args.slow_task_ms)
    except ValueError as e:
        ap.error(str(e))
    return ServeSupervisor(args).run()


def cli() -> int:
    return main()


if __name__ == "__main__":
    sys.exit(main())
