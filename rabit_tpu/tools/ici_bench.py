"""In-program collective bandwidth sweep — the device data plane.

The reference measures its TCP allreduce with test/speed_test.cc; the
TPU build's hot path is an XLA collective inside one compiled program,
so this harness times exactly that: ``reps`` chained allreduces inside a
single ``jit``ed ``shard_map`` program (no per-op dispatch, the compiler
schedules the ICI ring), over a payload sweep mirroring the reference
grid (reference: test/speed_runner.py:13-18).  Reports bus bandwidth
with the standard 2(n-1)/n normalisation — the figure BASELINE.md's
v5p-64 target is quoted in.

Implementations: ``psum`` (XLA's native ring), ``ring`` (explicit
ppermute reduce-scatter/all-gather from rabit_tpu.parallel), ``pallas``
(remote-DMA ring kernel from rabit_tpu.ops.ring_allreduce).

Usage:
    python -m rabit_tpu.tools.ici_bench [--ndev N] [--reps R]
        [--impls psum,ring,ringunroll,pallas] [--sizes 4096,1048576]
Uses all visible devices by default; for a virtual CPU mesh export
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` before launch.
"""
from __future__ import annotations

import argparse
import time

import numpy as np


def bench_impl(impl: str, ndev: int, size: int, reps: int) -> float:
    """Seconds per allreduce of `size` float32s, chained in-program."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from rabit_tpu.ops import ReduceOp

    avail = len(jax.devices())
    if ndev > avail:
        raise ValueError(
            f"ici_bench: --ndev {ndev} but only {avail} devices are "
            "visible (on CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={ndev})")
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("x",))
    interpret = jax.default_backend() != "tpu"

    def one(x):
        if impl == "psum":
            return jax.lax.psum(x, "x")
        if impl == "ring":
            from rabit_tpu.parallel.collectives import ring_allreduce

            return ring_allreduce(x, "x")
        if impl == "ringunroll":
            from rabit_tpu.parallel.collectives import ring_allreduce

            return ring_allreduce(x, "x", unroll=True)
        if impl == "pallas":
            from rabit_tpu.ops.ring_allreduce import ring_allreduce_pallas

            return ring_allreduce_pallas(x, "x", op=ReduceOp.SUM,
                                         interpret=interpret)
        raise ValueError(impl)

    if impl == "pallas" and interpret:
        # The distributed interpreter is a correctness tool, not a fast
        # path — run one op (wiring check) instead of a timed chain.
        reps = 1

    def chained(x):
        def body(_, acc):
            return one(acc) * (1.0 / ndev)  # keep magnitude stable
        return jax.lax.fori_loop(0, reps, body, x)

    fn = jax.jit(jax.shard_map(chained, mesh=mesh, in_specs=P(),
                               out_specs=P(), check_vma=False))
    x = jnp.ones((size,), jnp.float32)
    np.asarray(fn(x))  # compile + warm
    t0 = time.perf_counter()
    np.asarray(fn(x))
    return (time.perf_counter() - t0) / reps


def main(argv: list[str] | None = None) -> int:
    import jax

    ap = argparse.ArgumentParser()
    ap.add_argument("--ndev", type=int, default=0,
                    help="mesh size (default: all devices)")
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--impls", default="psum,ring")
    ap.add_argument("--sizes", default="4096,65536,1048576")
    args = ap.parse_args(argv)

    ndev = args.ndev or len(jax.devices())
    for impl in args.impls.split(","):
        for size in map(int, args.sizes.split(",")):
            nbytes = size * 4
            try:
                dt = bench_impl(impl, ndev, size, args.reps)
            except Exception as e:  # noqa: BLE001 — report and continue sweep
                print(f"{impl:7s} n={size:>9d}: FAILED {str(e)[:80]}")
                continue
            bus = ((2.0 * (ndev - 1) / ndev) * nbytes / dt if ndev > 1
                   else nbytes / dt)
            print(f"{impl:7s} n={size:>9d} ({nbytes/1e6:8.2f} MB): "
                  f"{dt*1e6:10.1f} us/op, bus {bus/1e9:8.3f} GB/s",
                  flush=True)
    return 0


def cli() -> int:
    """Console-script entry point."""
    return main()


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))
