"""Collective micro-benchmark worker.

Equivalent of reference: test/speed_test.cc:1-107 — times Allreduce(max),
Allreduce(sum) and Broadcast over a payload of n floats for nrep
repetitions, allreduces the per-rank timing mean/std, and prints MB/s.
Works against whichever engine RABIT_ENGINE selects (native / pysocket /
mock / xla), so it doubles as the rabit-vs-MPI comparison harness the
reference drives via test/speed_runner.py — here the comparison axis is
host-TCP engine vs XLA/ICI device path.

Usage (as a launched worker):
    python -m rabit_tpu.tracker.launch_local -n 4 -- \
        python -m rabit_tpu.tools.speed_test <ndata> <nrepeat> [device]

With ``device`` the buffers are jax Arrays riding the device data plane;
otherwise numpy host buffers.
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

import rabit_tpu
from rabit_tpu.ops import MAX, SUM


def _stats(dt: float):
    """Allreduce (sum, sum^2) of the per-rank time, like the reference's
    mean/std aggregation (reference: test/speed_test.cc:53-70)."""
    world = rabit_tpu.get_world_size()
    agg = rabit_tpu.allreduce(np.array([dt, dt * dt], np.float64), SUM)
    mean = agg[0] / world
    var = max(agg[1] / world - mean * mean, 0.0)
    return mean, float(np.sqrt(var))


def run(ndata: int, nrep: int, device: bool = False,
        checkpoint_every: int = 0) -> dict:
    """``checkpoint_every > 0`` commits an in-memory checkpoint every
    that many ops — the reference apps' usage pattern (kmeans checkpoints
    per iteration).  Each commit clears the robust result cache and
    recycles its buffers (HarvestCache), so this mode measures the
    steady state a real application sees, where even the retention
    regime fresh-allocates no payload memory."""
    rank = rabit_tpu.get_rank()
    if device:
        import jax.numpy as jnp

        make = lambda: jnp.full((ndata,), float(rank + 1), jnp.float32)  # noqa: E731
    else:
        make = lambda: np.full(ndata, float(rank + 1), np.float32)  # noqa: E731

    nbytes = ndata * 4
    results = {}
    for name, op in (("allreduce_max", MAX), ("allreduce_sum", SUM)):
        buf = make()
        rabit_tpu.allreduce(buf, op)  # warmup (and XLA compile)
        t0 = time.perf_counter()
        for i in range(nrep):
            buf = make()
            out = rabit_tpu.allreduce(buf, op)
            if checkpoint_every and (i + 1) % checkpoint_every == 0:
                rabit_tpu.checkpoint({"op": name, "i": i})
        if device:
            import jax

            jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / nrep
        mean, std = _stats(dt)
        world = rabit_tpu.get_world_size()
        # bus bandwidth: the standard 2(n-1)/n normalisation that makes
        # allreduce numbers comparable across world sizes (each byte must
        # cross the bus twice, minus the local share) — the figure the
        # v5p-64 ≥90%-of-MPI target in BASELINE.md is quoted in.
        bus = (2.0 * (world - 1) / world) * nbytes / mean if world > 1 \
            else nbytes / mean
        results[name] = {"sec_mean": mean, "sec_std": std,
                         "mbps": nbytes / mean / 1e6,
                         "bus_gbps": bus / 1e9}

    payload = np.full(ndata, 7.0, np.float32).tobytes()
    rabit_tpu.broadcast(payload if rank == 0 else None, 0)
    t0 = time.perf_counter()
    for _ in range(nrep):
        rabit_tpu.broadcast(payload if rank == 0 else None, 0)
    dt = (time.perf_counter() - t0) / nrep
    mean, std = _stats(dt)
    results["broadcast"] = {"sec_mean": mean, "sec_std": std,
                            "mbps": nbytes / mean / 1e6}
    return results


def main(argv: list[str]) -> int:
    ndata = int(argv[1]) if len(argv) > 1 else 100000
    nrep = int(argv[2]) if len(argv) > 2 else 100
    device = len(argv) > 3 and argv[3] == "device"
    checkpoint_every = int(os.environ.get("RABIT_SPEED_CHECKPOINT", "0"))
    if device and os.environ.get("RABIT_JAX_CPU"):
        # Multi-process device runs on a machine whose accelerator can't
        # host several JAX processes (e.g. one shared chip): pin the CPU
        # backend BEFORE any jax use — env alone is not honoured when a
        # platform plugin pins the default (see tests/conftest.py).
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 1)
    rabit_tpu.init()
    results = run(ndata, nrep, device, checkpoint_every)
    if rabit_tpu.get_rank() == 0:
        for name, r in results.items():
            line = ("%s: %.6f +/- %.6f sec, %.2f MB/s"
                    % (name, r["sec_mean"], r["sec_std"], r["mbps"]))
            if "bus_gbps" in r:
                line += ", bus %.3f GB/s" % r["bus_gbps"]
            rabit_tpu.tracker_print(line)
    # Telemetry: with RABIT_OBS_DIR set, rank 0 drops the benchmark
    # results next to the per-rank metric summaries the engines ship at
    # finalize (the tracker then writes the aggregated obs_report.json).
    obs_dir = os.environ.get("RABIT_OBS_DIR")
    if obs_dir and rabit_tpu.get_rank() == 0:
        import json

        os.makedirs(obs_dir, exist_ok=True)
        with open(os.path.join(obs_dir, "speed_results.json"), "w") as f:
            json.dump({"ndata": ndata, "nrep": nrep, "device": device,
                       "world": rabit_tpu.get_world_size(),
                       "results": results}, f, indent=2, sort_keys=True)
    rabit_tpu.finalize()
    return 0


def cli() -> int:
    """Console-script entry point."""
    return main(sys.argv)


if __name__ == "__main__":
    sys.exit(main(sys.argv))
