"""Critical-path analyzer over the tracker's causal-trace plane.

Points at a tracker started with ``--obs-port`` (or at a saved
``/trace`` / ``/status`` JSON document) and answers the operator's
question per assembled collective: *what was this op bound by?*  The
tracker's :class:`~rabit_tpu.obs.trace.TraceAssembler` has already
merged the per-rank hop records into skew-corrected cross-rank
timelines; this tool renders the verdicts:

- one line per retained op naming the binding ``(rank, link, hop)`` —
  the single longest wire hop the collective's completion waited on;
- the per-link cost fold (hop count, mean seconds, bytes) — the
  evidence table the adaptive controller / TuningCache side consumes;
- the modal ``bound by`` verdict across the window.

``--export FILE`` additionally saves the newest op's Perfetto-loadable
Chrome-trace JSON (``GET /trace?job=J``) so the timeline can be eyed in
a trace viewer.  ``--out FILE`` writes the analysis itself as JSON for
scripting (doc/observability.md "Causal tracing & postmortem").

Usage:
    python -m rabit_tpu.tools.trace_report --port 9100 [--job J]
        [--out report.json] [--export chrome.json]
    python -m rabit_tpu.tools.trace_report --status-file status.json
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def _fetch(url: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _job_traces(status: dict) -> dict:
    """{job name: trace report} from a ``/status`` document, or from a
    single tracker teardown journal (``tracker.<job>.json`` as dumped
    by ``--trace-dir``) — the journal is flat, with the job name under
    ``job`` and the trace report at top level."""
    if "jobs" not in status and isinstance(status.get("trace"), dict):
        return {str(status.get("job", "default")): status["trace"]}
    return {name: (job or {}).get("trace")
            for name, job in (status.get("jobs") or {}).items()
            if isinstance(job, dict) and job.get("trace")}


def analyze(trace: dict) -> dict:
    """Fold one job's ``/status`` trace report into the analysis
    document: binding verdict per retained op plus the link cost
    table.  Pure — unit-testable on synthetic reports."""
    out: dict = {"ops_assembled": trace.get("ops_assembled", 0),
                 "records": trace.get("records", 0),
                 "links": trace.get("links") or {}}
    if trace.get("bound_by"):
        out["bound_by"] = trace["bound_by"]
    last = trace.get("last_op") or {}
    if last.get("critical"):
        out["last_op"] = {"key": last.get("key"),
                          "critical": last["critical"]}
    # Rank the link table by total cost so the controller-facing
    # export leads with the most expensive wire.
    ranked = sorted(((link, row) for link, row in out["links"].items()),
                    key=lambda kv: -(kv[1].get("n", 0)
                                     * kv[1].get("mean_sec", 0.0)))
    out["costliest_links"] = [link for link, _ in ranked[:8]]
    return out


def render(name: str, analysis: dict, out=sys.stdout) -> None:
    print(f"job {name}: ops_assembled={analysis['ops_assembled']} "
          f"records={analysis['records']}", file=out)
    if analysis.get("bound_by"):
        print(f"  bound by: {analysis['bound_by']}", file=out)
    last = analysis.get("last_op") or {}
    crit = last.get("critical") or {}
    if crit:
        print(f"  last op {last.get('key')}: binding {crit.get('kind')} "
              f"hop{crit.get('hop')} link {crit.get('link')} "
              f"({crit.get('sec', 0.0) * 1e3:.3f}ms, "
              f"{crit.get('nbytes', 0)}B)", file=out)
    links = analysis.get("links") or {}
    if links:
        print(f"  {'link':<12}{'hops':>8}{'mean ms':>10}{'MB':>10}",
              file=out)
        for link in analysis.get("costliest_links") or sorted(links):
            row = links[link]
            print(f"  {link:<12}{row.get('n', 0):>8}"
                  f"{row.get('mean_sec', 0.0) * 1e3:>10.3f}"
                  f"{row.get('bytes', 0) / 1e6:>10.2f}", file=out)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="critical-path analysis over the causal-trace plane")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None,
                    help="the tracker's --obs-port")
    ap.add_argument("--status-file", default=None,
                    help="analyze a saved /status JSON document instead "
                         "of polling a live tracker")
    ap.add_argument("--job", default=None,
                    help="restrict to one job (default: every job with "
                         "assembled traces)")
    ap.add_argument("--out", default=None,
                    help="write the analysis as JSON here")
    ap.add_argument("--export", default=None,
                    help="save the newest op's Chrome-trace JSON here "
                         "(live tracker only; loads in Perfetto)")
    args = ap.parse_args(argv)
    if (args.port is None) == (args.status_file is None):
        ap.error("exactly one of --port / --status-file is required")

    url = f"http://{args.host}:{args.port}" if args.port is not None else None
    try:
        if args.status_file:
            with open(args.status_file, encoding="utf-8") as fh:
                status = json.load(fh)
        else:
            status = _fetch(url + "/status")
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"trace_report: cannot load status: {e}", file=sys.stderr)
        return 1

    traces = _job_traces(status)
    if args.job is not None:
        traces = {k: v for k, v in traces.items() if k == args.job}
    if not traces:
        print("trace_report: no assembled traces (workers need "
              "rabit_obs=1 and rabit_trace_sample > 0)", file=sys.stderr)
        return 1

    report = {name: analyze(tr) for name, tr in sorted(traces.items())}
    for name, analysis in report.items():
        render(name, analysis)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(report, fh, sort_keys=True, indent=1)
        print(f"trace_report: analysis -> {args.out}", file=sys.stderr)
    if args.export:
        if url is None:
            print("trace_report: --export needs a live tracker (--port)",
                  file=sys.stderr)
            return 1
        job = args.job or next(iter(sorted(report)))
        try:
            doc = _fetch(url + f"/trace?job={job}")
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"trace_report: /trace fetch failed: {e}",
                  file=sys.stderr)
            return 1
        with open(args.export, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, sort_keys=True)
        print(f"trace_report: chrome trace -> {args.export}",
              file=sys.stderr)
    return 0


def cli() -> int:
    """Console-script entry point."""
    return main()


if __name__ == "__main__":
    sys.exit(main())
