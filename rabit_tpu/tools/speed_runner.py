"""Sweep driver for the collective micro-benchmark.

Equivalent of reference: test/speed_runner.py:1-30 — runs speed_test over
a payload×repeat grid for each engine variant and prints a table.  The
reference compares rabit vs MPI binaries across machine counts; the TPU
build's axes are engine (native C++ TCP vs pure-python socket vs
device-path XLA) × world size on one host (multi-host sweeps use the same
worker under the pod launcher).

Usage:  python -m rabit_tpu.tools.speed_runner [--workers 4]
"""
from __future__ import annotations

import argparse
import subprocess
import sys

# (ndata floats, nrepeat) pairs, scaled down from the reference grid
# (reference: test/speed_runner.py:13-18 uses 10^4..10^7 × 10^4..10)
GRID = [(10_000, 100), (100_000, 30), (1_000_000, 10)]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--engines", default="native,pysocket")
    ap.add_argument("--replica", default=None,
                    help="rabit_global_replica override (1 = striped "
                         "regime: results recycled, steady-state memory)")
    args = ap.parse_args(argv)
    if args.replica is not None:
        import os

        os.environ["RABIT_GLOBAL_REPLICA"] = str(args.replica)

    for engine in args.engines.split(","):
        for ndata, nrep in GRID:
            print(f"=== engine={engine} n={ndata} rep={nrep} ===",
                  flush=True)
            cmd = [sys.executable, "-m",
                   "rabit_tpu.tracker.launch_local",
                   "-n", str(args.workers), "--",
                   sys.executable, "-m", "rabit_tpu.tools.speed_test",
                   str(ndata), str(nrep)]
            import os

            proc = subprocess.run(
                cmd, env={**os.environ, "RABIT_ENGINE": engine})
            if proc.returncode != 0:
                print(f"engine={engine} failed ({proc.returncode})")
                return proc.returncode
    return 0


if __name__ == "__main__":
    sys.exit(main())
