"""Sweep driver for the collective micro-benchmark.

Equivalent of reference: test/speed_runner.py:1-30 — runs speed_test over
a payload×repeat grid for each engine variant and prints a table.  The
reference compares rabit vs MPI binaries across machine counts; here the
axes are engine (native C++ TCP / pure-python socket / device-path XLA /
the MPI engine under a real mpirun) × world size on one host, plus the
raw ``MPI_Allreduce`` baseline the reference races against
(reference: test/speed_runner.py:13-18) — BASELINE.md's host-path target
is quoted as a % of that number.

The MPI legs use the rebuilt launcher in ``rabit_tpu/native/mpi`` (the
image ships OpenMPI's libraries but no mpirun); ``make`` there builds
mpirun/orted/mpi_speed on first use.

Usage:  python -m rabit_tpu.tools.speed_runner [--workers 4]
        python -m rabit_tpu.tools.speed_runner \
            --engines native,pysocket,mpi,mpi_allreduce
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys

# (ndata floats, nrepeat) pairs, scaled down from the reference grid
# (reference: test/speed_runner.py:13-18 uses 10^4..10^7 × 10^4..10)
GRID = [(10_000, 100), (100_000, 30), (1_000_000, 10)]

MPI_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native", "mpi")


def _find_openmpi_libs() -> dict[str, str] | None:
    """Locate the OpenMPI runtime libraries wherever the distro put
    them (ldconfig cache first, then common lib dirs); returns the
    paths the native/mpi Makefile links against, or None."""
    import glob

    dirs: list[str] = []
    try:
        out = subprocess.run(["/sbin/ldconfig", "-p"],
                             capture_output=True, text=True).stdout
        for line in out.splitlines():
            if "libopen-rte.so" in line and "=>" in line:
                dirs.append(os.path.dirname(line.split("=>")[1].strip()))
    except (FileNotFoundError, OSError):
        pass
    dirs += ["/usr/lib/x86_64-linux-gnu", "/usr/lib64", "/usr/lib",
             "/usr/lib/aarch64-linux-gnu"]
    for d in dirs:
        orte = sorted(glob.glob(os.path.join(d, "libopen-rte.so.*")))
        mpi = sorted(glob.glob(os.path.join(d, "libmpi.so.*")))
        event = sorted(glob.glob(os.path.join(d, "libevent_core-*.so*"))
                       or glob.glob(os.path.join(d, "libevent_core.so*")))
        if orte and mpi and event:
            return {"ORTE": orte[0], "MPI": mpi[0], "EVENT": event[0]}
    return None


def ensure_mpi_tools() -> str | None:
    """Build mpirun/orted/mpi_speed if the OpenMPI runtime is present;
    returns the mpirun path or None when the image has no libmpi."""
    libs = _find_openmpi_libs()
    if libs is None:
        return None
    rc = subprocess.run(
        ["make", "-C", MPI_DIR, "-s",
         f"ORTE={libs['ORTE']}", f"MPI={libs['MPI']}",
         f"EVENT={libs['EVENT']}"],
        capture_output=True, text=True)
    if rc.returncode != 0:
        print(f"mpi tools build failed:\n{rc.stderr}", file=sys.stderr)
        return None
    return os.path.join(MPI_DIR, "mpirun")


def _run_mpi_leg(engine: str, workers: int, ndata: int, nrep: int) -> int:
    mpirun = ensure_mpi_tools()
    if mpirun is None:
        print(f"engine={engine}: no OpenMPI runtime on this image — "
              "skipping", flush=True)
        return 0
    if engine == "mpi_allreduce":
        cmd = [mpirun, "-n", str(workers), "--oversubscribe",
               os.path.join(MPI_DIR, "mpi_speed"), str(ndata)]
    else:  # the framework's MPI engine under a real mpirun
        cmd = [mpirun, "-n", str(workers), "--oversubscribe",
               sys.executable, "-m", "rabit_tpu.tools.speed_test",
               str(ndata), str(nrep)]
    env = {**os.environ, "RABIT_ENGINE": "mpi"}
    env.pop("RABIT_TRACKER_URI", None)
    env.pop("RABIT_TRACKER_PORT", None)
    return subprocess.run(cmd, env=env).returncode


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--engines", default="native,pysocket")
    ap.add_argument("--replica", default=None,
                    help="rabit_global_replica override (1 = striped "
                         "regime: results recycled, steady-state memory)")
    args = ap.parse_args(argv)
    if args.replica is not None:
        os.environ["RABIT_GLOBAL_REPLICA"] = str(args.replica)

    for engine in args.engines.split(","):
        for ndata, nrep in GRID:
            print(f"=== engine={engine} n={ndata} rep={nrep} ===",
                  flush=True)
            if engine in ("mpi", "mpi_allreduce"):
                rc = _run_mpi_leg(engine, args.workers, ndata, nrep)
                if rc != 0:
                    print(f"engine={engine} failed ({rc})")
                    return rc
                continue
            cmd = [sys.executable, "-m",
                   "rabit_tpu.tracker.launch_local",
                   "-n", str(args.workers), "--",
                   sys.executable, "-m", "rabit_tpu.tools.speed_test",
                   str(ndata), str(nrep)]
            proc = subprocess.run(
                cmd, env={**os.environ, "RABIT_ENGINE": engine})
            if proc.returncode != 0:
                print(f"engine={engine} failed ({proc.returncode})")
                return proc.returncode
    return 0


if __name__ == "__main__":
    sys.exit(main())
