"""Render a telemetry dump into a human table and a Chrome trace.

Consumes what a telemetry-enabled job leaves under its obs dir
(doc/observability.md):

* ``obs_report.json`` — the tracker-aggregated per-job report
  (min/mean/max across ranks + the merged recovery timeline);
* ``events.rank<N>.jsonl`` — each rank's structured event trace.

Usage:
    python -m rabit_tpu.tools.obs_report <obs-dir | obs_report.json>
        [--chrome trace.json]   # also write a Chrome/Perfetto trace
        [--events N]            # timeline rows to print (default 40)

Open the Chrome trace at chrome://tracing or https://ui.perfetto.dev
(each rank renders as one process lane; op spans are complete events,
recovery phases are instants).
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys

from rabit_tpu.obs.trace import chrome_trace


def _read_events(f: pathlib.Path) -> list[dict]:
    """One rank's event dump, tolerant of torn shutdowns: a truncated
    or corrupt JSONL line (the rank died mid-write) is skipped with a
    note, never a traceback."""
    events: list[dict] = []
    bad = 0
    try:
        lines = f.read_text().splitlines()
    except OSError as e:
        print(f"obs_report: cannot read {f}: {e}", file=sys.stderr)
        return events
    for line in lines:
        if not line.strip():
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            bad += 1
            continue
        if isinstance(ev, dict) and "ts" in ev:
            events.append(ev)
        else:
            bad += 1
    if bad:
        print(f"obs_report: {f.name}: skipped {bad} torn/corrupt "
              "line(s)", file=sys.stderr)
    return events


def _load(path: pathlib.Path) -> tuple[dict | None, list[dict]]:
    """Resolve (report, events) from a report file or an obs dir.
    Degrades instead of raising: a corrupt report renders as absent
    (the event dumps may still tell the story), torn event lines are
    skipped per file."""
    if path.is_dir():
        report = None
        rp = path / "obs_report.json"
        if rp.exists():
            try:
                report = json.loads(rp.read_text())
            except (ValueError, OSError) as e:
                print(f"obs_report: {rp} unreadable ({e}); rendering "
                      "the event dumps only", file=sys.stderr)
        events: list[dict] = []
        for f in sorted(path.glob("events.rank*.jsonl")):
            events.extend(_read_events(f))
        return report, events
    try:
        report = json.loads(path.read_text())
    except (ValueError, OSError) as e:
        print(f"obs_report: {path} unreadable: {e}", file=sys.stderr)
        return None, []
    if not isinstance(report, dict):
        print(f"obs_report: {path} is not a report object",
              file=sys.stderr)
        return None, []
    timeline = report.get("recovery_timeline", [])
    return report, [e for e in timeline if isinstance(e, dict)]


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.6g}"


def render_report(report: dict, out=sys.stdout) -> None:
    ranks = report.get("ranks_reported", sorted(report.get("ranks", {})))
    # Multi-tenant reports name their job; the tracker also stamps a
    # service section (active co-tenants + job.* lifecycle/admission
    # counters) into every per-job report.
    name = report.get("job")
    # A report written by a tracker shard carries its shard index
    # (sharded control plane) — keep the attribution in the header.
    shard = report.get("shard")
    print(f"job: {name + ' ' if name and name != 'default' else ''}"
          + (f"shard={shard} " if shard is not None else "")
          + f"world={report.get('world')} "
          f"ranks_reported={ranks}", file=out)
    # Torn shutdowns: a rank that died before shipping its summary is
    # an "(absent)" row, not a hole the reader has to infer.
    try:
        world = int(report.get("world") or 0)
    except (TypeError, ValueError):
        world = 0
    absent = [r for r in range(world) if r not in set(ranks)]
    if absent:
        for r in absent:
            print(f"  rank {r}: (absent) — no summary shipped "
                  "(torn shutdown?)", file=out)
    svc = report.get("service") or {}
    counters = svc.get("counters") or {}
    if svc.get("jobs_active") or counters:
        row = " ".join(f"{k}={v}" for k, v in sorted(counters.items()))
        print(f"service: jobs_active={svc.get('jobs_active', [])}"
              + (f" {row}" if row else ""), file=out)
    agg = report.get("aggregate", {})
    if agg:
        name_w = max(len(n) for n in agg) + 2
        print(f"\n{'metric':<{name_w}}{'min':>14}{'mean':>14}{'max':>14}",
              file=out)
        print("-" * (name_w + 42), file=out)
        for name in sorted(agg):
            row = agg[name]
            print(f"{name:<{name_w}}{_fmt(row['min']):>14}"
                  f"{_fmt(row['mean']):>14}{_fmt(row['max']):>14}",
                  file=out)
    dropped = agg.get("obs.events_dropped", {})
    if dropped.get("max", 0) > 0:
        print(f"\nWARNING: event-trace eviction dropped up to "
              f"{_fmt(dropped['max'])} events per rank "
              "(raise rabit_obs_events)", file=out)
    render_sched_breakdown(report.get("aggregate", {}), out)
    render_codec(report.get("aggregate", {}), out)
    render_straggler(report, out)
    render_sched_latency(report.get("sched_latency", {}), out)
    render_controller(report.get("controller", {}), out)
    timeline = [e for e in report.get("recovery_timeline", [])
                if isinstance(e, dict)]
    if timeline:
        liveness = sum(1 for e in timeline if e.get("name") == "liveness")
        # Elastic membership: completed rescale epochs chain into the
        # job's world history (4->6->3); tracker restarts are the HA
        # events (journal replayed, same port).
        rescales = [e for e in timeline
                    if e.get("name") == "epoch"
                    and e.get("phase") == "rescale"]
        # The tracker's own rescale events carry from_world/to_world
        # and chain into the authoritative history; the per-rank echo
        # (one "epoch" trace event per member) only counts epochs.
        chain = [e for e in rescales if "to_world" in e]
        restarts = sum(1 for e in timeline
                       if e.get("name") == "tracker"
                       and e.get("phase") == "restart")
        summary = ""
        if liveness:
            summary += f", {liveness} liveness transitions"
        if chain:
            worlds = [chain[0].get("from_world")] + [
                e.get("to_world") for e in chain]
            summary += (f", {len(chain)} rescale epoch(s) (world "
                        + "->".join(str(w) for w in worlds) + ")")
        elif rescales:
            epochs = sorted({e.get("epoch") for e in rescales})
            summary += f", {len(epochs)} rescale epoch(s)"
        if restarts:
            summary += f", {restarts} tracker restart(s)"
        print(f"\nrecovery timeline ({len(timeline)} events"
              + summary + "):", file=out)
        t0 = timeline[0].get("ts", 0.0)
        for ev in timeline:
            # Worker recovery phases carry a rank; tracker-side
            # liveness/restart transitions may only know the task id (a
            # rank is attached once assigned); epoch/restart events are
            # the control plane's own — no rank, no task.
            if "rank" in ev:
                who = f"rank={ev['rank']}"
            elif "task" in ev:
                who = f"task={ev['task']}"
            else:
                who = "tracker"
            # "task" never repeats in the fields: rank-less events carry
            # it in the who-prefix, ranked ones are identified by rank.
            extra = " ".join(
                f"{k}={ev[k]}" for k in ("kind", "seqno", "version",
                                         "disk_version", "nbytes",
                                         "epoch", "from_world",
                                         "to_world", "world", "barrier",
                                         "relaunched", "resumed", "job",
                                         "supervisor", "why", "score",
                                         "lateness_sec", "factor",
                                         "sched", "bucket", "incumbent",
                                         "incumbent_sec",
                                         "challenger_sec")
                if k in ev)
            print(f"  +{ev.get('ts', 0.0) - t0:9.3f}s {who}"
                  f" {ev.get('phase', ev.get('name')):<18} {extra}",
                  file=out)


def render_sched_breakdown(agg: dict, out=sys.stdout) -> None:
    """Schedule-choice breakdown from the ``sched.pick.*`` counters the
    dispatch emits (doc/performance.md "Schedule selection"): how many
    allreduces — and how many payload bytes — each collective schedule
    carried.  Counts are per rank; choices are collective decisions, so
    min == max unless telemetry windows differed across ranks."""
    picks = {}
    for name, row in agg.items():
        if not name.startswith("sched.pick."):
            continue
        rest = name[len("sched.pick."):]
        if rest.endswith(".bytes"):
            picks.setdefault(rest[:-len(".bytes")], {})["bytes"] = row
        else:
            picks.setdefault(rest, {})["ops"] = row
    if not picks:
        return
    total_ops = sum(p.get("ops", {}).get("max", 0) for p in picks.values())
    print("\nschedule choice breakdown (per rank):", file=out)
    print(f"{'schedule':<12}{'ops':>10}{'share':>9}{'bytes':>16}",
          file=out)
    print("-" * 47, file=out)
    for sched in sorted(picks, key=lambda s: -picks[s].get(
            "ops", {}).get("max", 0)):
        ops = picks[sched].get("ops", {}).get("max", 0)
        nbytes = picks[sched].get("bytes", {}).get("max", 0)
        share = 100.0 * ops / total_ops if total_ops else 0.0
        print(f"{sched:<12}{_fmt(ops):>10}{share:>8.1f}%"
              f"{_fmt(nbytes):>16}", file=out)


def render_codec(agg: dict, out=sys.stdout) -> None:
    """Wire-codec table from the ``codec.*`` counters the engine emits
    (doc/performance.md "Quantized wire codecs"): per codec the op
    count, then bytes on the wire vs logical bytes, the mean
    compression ratio and the error-feedback residual norm.  Counts
    are per rank; codec choice is replicated config, so min == max
    unless telemetry windows differed across ranks."""
    total = agg.get("codec.ops", {}).get("max", 0)
    if not total:
        return
    names = {n[len("codec.ops."):]: agg[n].get("max", 0)
             for n in agg if n.startswith("codec.ops.")}
    logical = agg.get("codec.bytes.logical", {}).get("max", 0)
    wire = agg.get("codec.bytes.wire", {}).get("max", 0)
    saved = agg.get("codec.bytes_saved", {}).get("max", 0)
    print("\nwire codec (per rank):", file=out)
    print(f"{'codec':<8}{'ops':>10}{'logical B':>14}{'wire B':>14}"
          f"{'ratio':>8}{'saved B':>14}", file=out)
    print("-" * 68, file=out)
    ratio = wire / logical if logical else 0.0
    for name in sorted(names, key=lambda n: -names[n]):
        print(f"{name:<8}{_fmt(names[name]):>10}{_fmt(logical):>14}"
              f"{_fmt(wire):>14}{ratio:>8.3f}{_fmt(saved):>14}",
              file=out)
    fb = agg.get("codec.feedback.norm.mean")
    if fb:
        print(f"error-feedback |residual| mean/rank: "
              f"{_fmt(fb.get('mean', 0.0))} "
              f"(max {_fmt(fb.get('max', 0.0))})", file=out)


def render_straggler(report: dict, out=sys.stdout) -> None:
    """The straggler table from the tracker's merged collective spans
    (doc/observability.md "Live telemetry"): per rank, the rolling
    straggler score (mean lateness in op-times), mean lateness, span
    count and the per-schedule lateness split — a rank that was only
    slow under one schedule points at the schedule, not the host."""
    stragg = report.get("straggler") or {}
    ranks = stragg.get("ranks") or {}
    if not ranks:
        return
    flagged = {str(r) for r in stragg.get("straggling", [])}
    print(f"\nstraggler attribution (factor "
          f"{stragg.get('factor', '?')}, merged spans):", file=out)
    print(f"{'rank':<6}{'spans':>7}{'score':>9}{'lateness':>12}"
          f"  per-schedule lateness", file=out)
    print("-" * 60, file=out)
    for rank in sorted(ranks, key=lambda r: -ranks[r].get("score", 0)):
        row = ranks[rank] or {}
        per = row.get("sched_lateness_sec") or {}
        split = " ".join(f"{s}={v * 1e3:.1f}ms"
                         for s, v in sorted(per.items()))
        mark = " <-- STRAGGLER" if rank in flagged else ""
        print(f"{rank:<6}{row.get('ops', 0):>7}"
              f"{row.get('score', 0.0):>9.2f}"
              f"{row.get('mean_lateness_sec', 0.0) * 1e3:>10.1f}ms"
              f"  {split}{mark}", file=out)


def render_sched_latency(sched: dict, out=sys.stdout) -> None:
    """Per-schedule latency/skew breakdown from the merged spans: how
    each collective schedule actually performed op-for-op, with the
    cross-rank skew it exhibited (TACCL's signal: slowness attributable
    to the schedule choice, not the host)."""
    if not sched:
        return
    print("\nper-schedule span latency (merged across ranks):", file=out)
    print(f"{'schedule':<12}{'ops':>8}{'mean':>11}{'max':>11}"
          f"{'mean skew':>12}{'max skew':>11}", file=out)
    print("-" * 65, file=out)
    for name in sorted(sched, key=lambda s: -sched[s].get("count", 0)):
        row = sched[name] or {}
        print(f"{name:<12}{row.get('count', 0):>8}"
              f"{row.get('mean_sec', 0.0) * 1e3:>9.2f}ms"
              f"{row.get('max_sec', 0.0) * 1e3:>9.2f}ms"
              f"{row.get('mean_skew_sec', 0.0) * 1e3:>10.2f}ms"
              f"{row.get('max_skew_sec', 0.0) * 1e3:>9.2f}ms", file=out)


def render_controller(ctl: dict, out=sys.stdout) -> None:
    """The adaptive controller's decision table (doc/performance.md
    "Online adaptation"): what the job converged on (active directive,
    demoted ranks) and every recorded decision with its evidence —
    incumbent vs challenger cost and the sample counts it was judged
    on, so a switch explains itself in the report."""
    if not ctl:
        return
    active = ctl.get("active_sched") or {}
    sched_s = " ".join(
        f"{b}B->{s}" for b, s in sorted(
            active.items(),
            key=lambda kv: int(kv[0]) if str(kv[0]).isdigit() else 0)) \
        or "(engine default)"
    print(f"\nadaptive controller: active sched {sched_s}"
          + (f"  demoted={ctl.get('demoted')}"
             if ctl.get("demoted") else ""), file=out)
    decisions = [d for d in ctl.get("decisions") or []
                 if isinstance(d, dict)]
    if not decisions:
        return
    print(f"{'decision':<12}{'bucket':>10}{'sched/rank':>12}"
          f"  evidence", file=out)
    print("-" * 64, file=out)
    for d in decisions:
        evd = d.get("evidence") or {}
        who = d.get("sched") or (f"rank {d['rank']}"
                                 if d.get("rank") is not None else "")
        bits = []
        if "incumbent_sec" in evd and "challenger_sec" in evd:
            bits.append(f"{evd.get('incumbent')} "
                        f"{evd['incumbent_sec'] * 1e3:.2f}ms vs "
                        f"{evd.get('challenger')} "
                        f"{evd['challenger_sec'] * 1e3:.2f}ms")
        if "samples" in evd:
            bits.append(f"n={evd['samples']}")
        if "score" in evd:
            bits.append(f"score={evd['score']}"
                        + (f" factor={evd['factor']}"
                           if "factor" in evd else ""))
        if "why" in evd:
            bits.append(f"why={evd['why']}")
        print(f"{d.get('kind', '?'):<12}"
              f"{d.get('bucket', ''):>10}{who:>12}"
              f"  {'; '.join(bits)}", file=out)


def render_events(events: list[dict], limit: int, out=sys.stdout) -> None:
    print(f"\nevent trace ({len(events)} events"
          + (f", showing first {limit}" if len(events) > limit else "")
          + "):", file=out)
    t0 = min(e["ts"] for e in events)
    for ev in events[:limit]:
        extra = " ".join(f"{k}={ev[k]}" for k in
                         ("kind", "phase", "sched", "mode", "nbytes",
                          "seqno", "version", "epoch", "from_world",
                          "world")
                         if k in ev)
        dur = f" dur={ev['dur'] * 1e3:.3f}ms" if "dur" in ev else ""
        print(f"  +{ev['ts'] - t0:9.3f}s rank={ev.get('rank', '?')} "
              f"{ev.get('name'):<10} {extra}{dur}", file=out)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="render a rabit_tpu telemetry dump")
    ap.add_argument("path", help="obs dir or obs_report.json")
    ap.add_argument("--chrome", default=None, metavar="OUT.json",
                    help="write a Chrome trace (chrome://tracing)")
    ap.add_argument("--events", type=int, default=40,
                    help="max event-trace rows to print")
    args = ap.parse_args(argv)

    path = pathlib.Path(args.path)
    if not path.exists():
        print(f"obs_report: {path} does not exist", file=sys.stderr)
        return 1
    report, events = _load(path)
    if report is None and not events:
        print(f"obs_report: nothing to render under {path} "
              "(no obs_report.json, no events.rank*.jsonl)",
              file=sys.stderr)
        return 1
    if report is not None:
        render_report(report)
    events = sorted(events, key=lambda e: e.get("ts", 0.0))
    if events:
        render_events(events, args.events)
    if args.chrome:
        trace = {"traceEvents": chrome_trace(events),
                 "displayTimeUnit": "ms"}
        with open(args.chrome, "w") as f:
            json.dump(trace, f)
        print(f"\nwrote Chrome trace ({len(trace['traceEvents'])} events) "
              f"to {args.chrome}")
    return 0


def cli() -> int:
    """Console-script entry point."""
    return main()


if __name__ == "__main__":
    sys.exit(main())
