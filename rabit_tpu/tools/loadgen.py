"""Open-loop load generator for the serving plane (doc/serving.md).

**Open loop means arrival-rate, not closed-loop**: requests arrive on a
fixed schedule (``--rate`` req/s, optionally Poisson gaps) regardless
of how fast the service answers — the generator never waits for a
reply before issuing the next request, so an overloaded service sees
the true offered load instead of a politely self-throttling client.
Latency is measured from each request's *scheduled arrival* (client-
side sender delay counts against the service, coordinated-omission
style), and every reply is accounted into exactly one outcome bucket:

    offered == ok + shed + timeout + error + duplicate   (books close)

— and the same identity is kept **per QoS class** (``--qos-mix``
spreads traffic across gold/silver/bronze; each class's sub-book must
close on its own, not just in aggregate) and **per wire status** (a
DRAINING shed and an Overloaded shed are separate rows in the
``statuses`` table even though both land in the ``shed`` bucket).

With ``--verify-dir`` pointed at the model's durable checkpoint store,
every OK reply is recomputed client-side from the committed blob of
the version the reply names and compared **bitwise**
(serve/model.py ``predict_row`` is the oracle) — a single wrong bit is
a counted ``wrong`` answer and a non-zero exit.

Endpoints come from ``--endpoint host:port`` (repeatable) or
``--endpoints-dir`` (the serve ranks' published files, re-scanned live
so a draining rank rotates out and a fresh joiner rotates in).

**Straggler-aware routing** (``--route``): the :class:`Router` replaces
plain round-robin with smooth weighted round-robin, scoring each
endpoint by max(the tracker's ``rabit_straggler_score`` for its rank
scraped from ``--metrics-url``, this client's own ok-latency EWMA over
the fleet median) and applying the SAME hysteresis ``obs/adapt.py``
uses for leadership demotion: convict above ``RABIT_STRAGGLER_FACTOR``
(default 3.0) held for ``RABIT_DEMOTE_CHECKS`` consecutive updates,
reinstate below factor/2 held just as long.  A convicted endpoint
keeps a small non-zero weight so fresh samples keep flowing and
reinstatement stays reachable.

**Hedged retries** (``--hedge-after-pct P``): a request whose primary
reply has not landed by the rolling ok-latency P-percentile is hedged
to a second endpoint carrying the SAME idempotency key; whichever
reply settles first wins the books, the loser's late reply is consumed
off its connection and counted (``hedges.stray_replies``), and the
server's dedup window guarantees the storm never double-serves — a
second STATUS_OK for one key anywhere in the run is counted in
``double_served`` and fails the gate.

Chaos composes here too: ``serve_req``/``serve_reply`` link sites
(reset/stall) are consulted client-side around each send/receive, so
every injection lands in this process's reconnect-retry or deadline
path and pairs with a counted detection.

Usage:
    python -m rabit_tpu.tools.loadgen --endpoints-dir D --rate 200
        --duration 10 [--deadline-ms 250] [--verify-dir CKPT]
        [--json OUT.json] [--poisson] [--seed 0] [--dim 16]
        [--qos-mix gold:0.2,silver:0.5,bronze:0.3]
        [--hedge-after-pct 95] [--route --metrics-url URL]
    python -m rabit_tpu.tools.loadgen --endpoints-dir D --once
        [--verify-dir CKPT]       # one request, verified: smoke test
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import queue
import re
import select
import socket
import statistics
import sys
import threading
import time
import urllib.request

import numpy as np

from rabit_tpu import chaos as chaos_mod
from rabit_tpu import ckpt as ckpt_mod
from rabit_tpu.obs.adapt import DEFAULT_DEMOTE_CHECKS
from rabit_tpu.serve import model as serve_model
from rabit_tpu.serve import protocol as SP

#: outcome buckets the accounting identity closes over.  ``duplicate``
#: is a first-class bucket: a hedge copy suppressed by the server's
#: dedup window was answered (typed), not dropped — folding it into any
#: other bucket would unbalance the fleet-wide books.
OUTCOMES = ("ok", "shed", "timeout", "error", "duplicate")


def _status_outcome(status: int) -> str:
    """Collapse wire statuses into the accounting buckets: DRAINING is
    a shed (typed not-served-retry-elsewhere, like Overloaded).  The
    per-status split lives in the ``statuses`` tables — the buckets
    summarize, the tables itemize."""
    return {SP.STATUS_OK: "ok", SP.STATUS_SHED: "shed",
            SP.STATUS_DRAINING: "shed",
            SP.STATUS_TIMEOUT: "timeout",
            SP.STATUS_DUPLICATE: "duplicate"}.get(status, "error")


def parse_qos_mix(spec: str) -> list[tuple[float, int]]:
    """Parse ``"gold:0.2,silver:0.5,bronze:0.3"`` into cumulative
    ``(threshold, qos)`` bins for a deterministic per-seq draw.
    Weights are normalized; order follows the spec."""
    pairs: list[tuple[str, float]] = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        name, _, raw = part.partition(":")
        if name.strip() not in SP.QOS_BY_NAME or not raw.strip():
            raise ValueError(
                f"bad qos mix {part!r} (want e.g. 'gold:0.2')")
        pairs.append((name.strip(), float(raw)))
    total = sum(w for _, w in pairs)
    if total <= 0:
        raise ValueError(f"qos mix {spec!r} has no positive weight")
    bins: list[tuple[float, int]] = []
    acc = 0.0
    for name, w in pairs:
        acc += w / total
        bins.append((acc, SP.QOS_BY_NAME[name]))
    return bins


class EndpointSet:
    """Round-robin endpoint picker over static addrs and/or a live
    re-scanned endpoints directory."""

    def __init__(self, static: list[tuple[str, int]],
                 endpoints_dir: str | None) -> None:
        self._static = list(static)
        self._dir = endpoints_dir
        self._lock = threading.Lock()
        self._dynamic: list[tuple[str, int]] = []
        self._i = 0
        self.rescan()

    def rescan(self) -> None:
        if not self._dir:
            return
        found = []
        for path in sorted(glob.glob(os.path.join(self._dir, "*.json"))):
            try:
                with open(path) as f:
                    doc = json.load(f)
                found.append((str(doc["host"]), int(doc["port"])))
            except (OSError, ValueError, KeyError, TypeError):
                continue  # torn write / vanished file: next scan
        with self._lock:
            self._dynamic = found

    def all(self) -> list[tuple[str, int]]:
        with self._lock:
            return self._static + self._dynamic

    def pick(self) -> tuple[str, int] | None:
        with self._lock:
            eps = self._static + self._dynamic
            if not eps:
                return None
            ep = eps[self._i % len(eps)]
            self._i += 1
            return ep


_SCORE_RE = re.compile(
    r'^rabit_straggler_score\{[^}]*?rank="(\d+)"[^}]*\}'
    r'\s+([0-9.eE+-]+)\s*$',
    re.MULTILINE)


class Router:
    """Straggler-aware smooth weighted round-robin over an
    :class:`EndpointSet`.

    Scores each endpoint as max(tracker straggler score for its rank,
    client ok-latency EWMA / fleet median) and runs the verdicts
    through the obs-plane hysteresis (module docstring).  A convicted
    endpoint's weight drops to :data:`CONVICTED_WEIGHT` — small but
    non-zero, so latency samples keep flowing and a recovered rank can
    earn its share back."""

    CONVICTED_WEIGHT = 0.25

    def __init__(self, endpoints: EndpointSet,
                 metrics_url: str | None = None,
                 factor: float | None = None,
                 checks: int | None = None) -> None:
        self.endpoints = endpoints
        self.metrics_url = metrics_url
        # The SAME knobs adapt.py reads for leadership demotion: one
        # conviction vocabulary across the whole system.
        self.factor = (float(factor) if factor is not None
                       else float(os.environ.get(
                           "RABIT_STRAGGLER_FACTOR", 3.0)))
        self.checks = (int(checks) if checks is not None
                       else int(os.environ.get(
                           "RABIT_DEMOTE_CHECKS",
                           DEFAULT_DEMOTE_CHECKS)))
        self._lock = threading.Lock()
        self._current: dict[tuple[str, int], float] = {}  # smooth WRR
        self._high: dict[tuple[str, int], int] = {}
        self._low: dict[tuple[str, int], int] = {}
        self._lat_ewma: dict[tuple[str, int], float] = {}
        self._rank_of: dict[tuple[str, int], int] = {}
        self.convicted: set[tuple[str, int]] = set()
        self.convictions = 0
        self.reinstatements = 0
        self.last_scores: dict[tuple[str, int], float] = {}

    # -- signals -------------------------------------------------------
    def note_latency(self, ep: tuple[str, int], service: float) -> None:
        with self._lock:
            prev = self._lat_ewma.get(ep)
            self._lat_ewma[ep] = (service if prev is None
                                  else prev + 0.2 * (service - prev))

    def _scrape_scores(self) -> dict[int, float]:
        try:
            with urllib.request.urlopen(self.metrics_url,
                                        timeout=1.0) as resp:
                page = resp.read().decode("utf-8", "replace")
        except (OSError, ValueError):
            return {}
        out: dict[int, float] = {}
        for m in _SCORE_RE.finditer(page):
            rank, v = int(m.group(1)), float(m.group(2))
            # Max-merge across jobs: a multi-tenant tracker renders one
            # series per (job, rank) and the router wants the rank's
            # worst verdict.
            out[rank] = max(out.get(rank, 0.0), v)
        return out

    def _refresh_ranks(self) -> None:
        """Map endpoints to their collective ranks (ctrl stats), so
        the tracker's per-rank scores can be joined to addresses.
        Cached; only unmapped endpoints pay a probe."""
        for ep in self.endpoints.all():
            if ep in self._rank_of:
                continue
            try:
                with socket.create_connection(ep, timeout=1.0) as s:
                    doc = json.loads(SP.send_ctrl(s, SP.CTRL_STATS))
                self._rank_of[ep] = int(doc["rank"])
            except (OSError, ValueError, KeyError, TypeError):
                continue  # next update retries

    def update(self) -> None:
        """One scoring round: gather both signals, max-merge, run the
        hysteresis.  Called on the load generator's rescan cadence."""
        scores: dict[tuple[str, int], float] = {}
        if self.metrics_url:
            by_rank = self._scrape_scores()
            if by_rank:
                self._refresh_ranks()
                for ep, rank in self._rank_of.items():
                    if rank in by_rank:
                        scores[ep] = by_rank[rank]
        with self._lock:
            ewma = dict(self._lat_ewma)
        if len(ewma) >= 2:
            med = statistics.median(ewma.values())
            if med > 0:
                for ep, v in ewma.items():
                    scores[ep] = max(scores.get(ep, 0.0), v / med)
        self.observe(scores)

    def observe(self, scores: dict[tuple[str, int], float]) -> None:
        """Apply one round of scores through the conviction hysteresis
        (unit-testable seam; :meth:`update` gathers the real ones)."""
        with self._lock:
            self.last_scores = dict(scores)
            for ep in self.endpoints.all():
                s = scores.get(ep, 1.0)
                if ep in self.convicted:
                    if s < self.factor / 2:
                        self._low[ep] = self._low.get(ep, 0) + 1
                        if self._low[ep] >= self.checks:
                            self.convicted.discard(ep)
                            self._low[ep] = 0
                            self.reinstatements += 1
                    else:
                        self._low[ep] = 0
                else:
                    if s > self.factor:
                        self._high[ep] = self._high.get(ep, 0) + 1
                        if self._high[ep] >= self.checks:
                            self.convicted.add(ep)
                            self._high[ep] = 0
                            self.convictions += 1
                    else:
                        self._high[ep] = 0

    # -- routing -------------------------------------------------------
    def _weight(self, ep: tuple[str, int]) -> float:
        return self.CONVICTED_WEIGHT if ep in self.convicted else 1.0

    def pick(self, exclude: tuple[str, int] | None = None
             ) -> tuple[str, int] | None:
        """Smooth weighted round-robin (the nginx algorithm): add each
        weight to its running current, pick the max, subtract the
        total from the winner — proportional share with no bursts."""
        with self._lock:
            eps = [e for e in self.endpoints.all() if e != exclude]
            if not eps:
                eps = self.endpoints.all()
            if not eps:
                return None
            total = 0.0
            best = None
            for ep in eps:
                w = self._weight(ep)
                total += w
                self._current[ep] = self._current.get(ep, 0.0) + w
                if best is None or self._current[ep] > self._current[best]:
                    best = ep
            self._current[best] -= total
            return best

    def rescan(self) -> None:
        self.endpoints.rescan()

    def all(self) -> list[tuple[str, int]]:
        return self.endpoints.all()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "factor": self.factor, "checks": self.checks,
                "convicted": sorted(f"{h}:{p}"
                                    for h, p in self.convicted),
                "convictions": self.convictions,
                "reinstatements": self.reinstatements,
                "scores": {f"{h}:{p}": round(s, 3)
                           for (h, p), s in self.last_scores.items()},
                "lat_ewma_ms": {f"{h}:{p}": round(v * 1e3, 3)
                                for (h, p), v in self._lat_ewma.items()},
            }


class Verifier:
    """Bitwise reply verification against the committed blobs."""

    def __init__(self, ckpt_dir: str) -> None:
        self._store = ckpt_mod.CheckpointStore(ckpt_dir, rank=0)
        self._lock = threading.Lock()
        self._weights: dict[int, np.ndarray | None] = {}

    def weights_for(self, version: int) -> np.ndarray | None:
        with self._lock:
            if version in self._weights:
                return self._weights[version]
        dc = self._store.load_version(version)
        w = None
        if dc is not None:
            try:
                w = serve_model.ServedModel.from_disk_checkpoint(
                    dc).weights
            except serve_model.ModelError:
                w = None
        with self._lock:
            if w is not None:
                # Only POSITIVE results are cached: a version whose
                # blob is currently unreadable (pruned by retention, a
                # transient CRC failure) may become readable — a
                # negative cache would turn every later reply naming
                # it into a permanent verdict.
                self._weights[version] = w
        return w

    def check(self, reply: SP.PredictReply,
              features: np.ndarray) -> bool | None:
        """True/False: the reply's prediction is/is not BITWISE what
        the named committed version produces for these features.
        ``None``: UNVERIFIABLE — the version's blob is not readable
        from the store right now (pruned, torn) — which is not
        evidence of a wrong answer and is counted separately."""
        if reply.predictions is None or len(reply.predictions) != 1:
            return False
        w = self.weights_for(reply.model_version)
        if w is None:
            return None
        if w.shape[0] != features.shape[0]:
            return False
        want = serve_model.predict_row(w, features)
        got = float(reply.predictions[0])
        return got == want


class _ChaosReplyLost(Exception):
    """An injected serve_reply reset ate the reply: retry the request
    on a fresh connection (safe — the idempotency key dedups)."""


class _Sender(threading.Thread):
    """One sender: a persistent connection per endpoint, re-dialed on
    failure.  Pulls (seq, scheduled_time) jobs and accounts each into
    exactly one outcome."""

    def __init__(self, gen: "LoadGen", idx: int) -> None:
        super().__init__(name=f"loadgen-send-{idx}", daemon=True)
        self.gen = gen
        self._conns: dict[tuple[str, int], socket.socket] = {}

    def _conn(self, ep: tuple[str, int],
              timeout: float) -> socket.socket:
        sock = self._conns.get(ep)
        if sock is None:
            sock = socket.create_connection(ep, timeout=timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns[ep] = sock
        sock.settimeout(timeout)
        return sock

    def _drop(self, ep: tuple[str, int]) -> None:
        sock = self._conns.pop(ep, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def run(self) -> None:
        gen = self.gen
        while True:
            job = gen.jobs.get()
            if job is None:
                return
            seq, sched_t = job
            gen.note_result(seq, sched_t,
                            *self._one(seq, sched_t))

    def _chaos_fired_reset(self, site: str, ep: tuple[str, int]) -> bool:
        """Consult one serving-wire chaos site.  A stall is served
        inside the plan and detected here by its elapsed time (it has
        no other observable); a reset returns True after dropping the
        connection — the caller's reconnect/retry IS the detection
        path the pairing gate counts."""
        plan = self.gen.chaos
        if plan is None:
            return False
        t0 = time.monotonic()
        kind = plan.link(site)
        if kind is None:
            if time.monotonic() - t0 >= plan.stall_ms / 2000.0:
                self.gen.note_chaos_detected(site, "stall")
            return False
        self.gen.note_chaos_detected(site, "reset")
        self._drop(ep)
        return True

    def _one(self, seq: int, sched_t: float
             ) -> tuple[str, float, float, int, int, int]:
        """Send one request (hedging if armed); returns (outcome,
        service_sec, sojourn_sec, wire_status, retry_after_ms, qos).
        ``service`` is send→reply (the server's behavior); ``sojourn``
        is scheduled arrival→reply (adds client-side sender delay —
        the open-loop honesty number)."""
        gen = self.gen
        qos = gen.qos_for(seq)
        features = gen.features_for(seq)
        req = SP.PredictRequest(seq & 0xFFFFFFFF, gen.deadline_ms,
                                features, qos=qos,
                                idem_key=gen.idem_for(seq))
        timeout = gen.client_timeout
        sent_t = time.monotonic()
        reply = None
        for _attempt in (0, 1):
            ep = gen.pick_endpoint()
            if ep is None:
                return "error", 0.0, 0.0, -1, 0, qos
            try:
                reply, rep_ep = self._exchange(req, ep, timeout)
                break
            except _ChaosReplyLost:
                continue  # retry on a fresh conn; the idem key dedups
            except (OSError, SP.ServeProtocolError, ConnectionError):
                self._drop(ep)
                now = time.monotonic()
                return ("error", now - sent_t, now - sched_t, -1, 0,
                        qos)
        if reply is None:  # both attempts lost to injected resets
            now = time.monotonic()
            return "error", now - sent_t, now - sched_t, -1, 0, qos
        now = time.monotonic()
        outcome = _status_outcome(reply.status)
        if reply.status == SP.STATUS_OK:
            gen.note_ok_serve(seq, ep=rep_ep, service=now - sent_t)
        if reply.predictions is not None and gen.verifier is not None \
                and reply.status in (SP.STATUS_OK, SP.STATUS_DUPLICATE):
            # A Duplicate carrying the idempotency cache is verified
            # exactly like an OK: the cached answer must be the
            # committed version's bits too.
            verdict = gen.verifier.check(reply, features)
            if verdict is False:
                gen.count_wrong()
                if outcome == "ok":
                    outcome = "error"
            elif verdict is None:
                gen.count_unverifiable()
        return (outcome, now - sent_t, now - sched_t, reply.status,
                reply.retry_after_ms, qos)

    def _exchange(self, req: SP.PredictRequest, ep: tuple[str, int],
                  timeout: float
                  ) -> tuple[SP.PredictReply, tuple[str, int]]:
        """Send ``req`` to ``ep`` and wait for ITS reply, arming a
        hedge to a second endpoint at the rolling-percentile delay.
        Stray frames read along the way (a previous request's
        abandoned hedge loser parked on this connection) are accounted
        and skipped — replies match by req_id, never by position."""
        gen = self.gen
        if self._chaos_fired_reset(chaos_mod.SITE_SERVE_REQ, ep):
            pass  # reconnect below: the retry path is the detection
        sock = self._conn(ep, timeout)
        req.send(sock)
        gen.note_send(ep)
        if self._chaos_fired_reset(chaos_mod.SITE_SERVE_REPLY, ep):
            raise _ChaosReplyLost()
        want = req.req_id
        socks: dict[socket.socket, tuple[str, int]] = {sock: ep}
        deadline = time.monotonic() + timeout
        hedge_delay = gen.hedge_delay()
        hedge_at = (time.monotonic() + hedge_delay
                    if hedge_delay is not None else None)
        while True:
            now = time.monotonic()
            remaining = deadline - now
            if remaining <= 0:
                for s_ep in list(socks.values()):
                    self._drop(s_ep)
                raise socket.timeout("client timeout waiting for reply")
            wait = remaining
            if hedge_at is not None:
                wait = min(wait, max(hedge_at - now, 0.0))
            ready, _, _ = select.select(list(socks), [], [], wait)
            if not ready:
                if hedge_at is not None and time.monotonic() >= hedge_at:
                    hedge_at = None
                    hedged = self._send_hedge(req, ep, timeout)
                    if hedged is not None:
                        hsock, hep = hedged
                        socks[hsock] = hep
                continue
            for s in ready:
                s.settimeout(max(deadline - time.monotonic(), 0.1))
                reply = SP.PredictReply.recv(s)
                if reply.req_id == want:
                    if len(socks) > 1:
                        gen.note_hedge_win(socks[s] != ep)
                        # The loser's reply stays parked on its
                        # connection; a later job's read loop consumes
                        # and accounts it (note_stray).
                    return reply, socks[s]
                gen.note_stray(reply, socks[s])

    def _send_hedge(self, req: SP.PredictRequest,
                    primary: tuple[str, int], timeout: float
                    ) -> tuple[socket.socket, tuple[str, int]] | None:
        """Fire the hedge copy (same req_id, same idem key) at a
        different endpoint; best-effort — a failed hedge leaves the
        primary wait untouched."""
        gen = self.gen
        hep = gen.pick_endpoint(exclude=primary)
        if hep is None or hep == primary:
            return None
        try:
            hsock = self._conn(hep, timeout)
            req.send(hsock)
        except (OSError, ConnectionError):
            self._drop(hep)
            return None
        gen.note_send(hep)
        gen.note_hedge_fired()
        return hsock, hep


class LoadGen:
    """One open-loop run (library face; ``main`` is the CLI)."""

    def __init__(self, endpoints: EndpointSet, rate: float,
                 duration: float, *, deadline_ms: int = 0,
                 dim: int = 16, seed: int = 0, poisson: bool = False,
                 outstanding: int = 64,
                 verifier: Verifier | None = None,
                 qos_mix: str | None = None,
                 hedge_after_pct: float | None = None,
                 idem: bool = False,
                 router: Router | None = None,
                 chaos_spec: str | None = None) -> None:
        self.endpoints = endpoints
        self.router = router
        self.rate = max(float(rate), 0.001)
        self.duration = float(duration)
        self.deadline_ms = int(deadline_ms)
        self.dim = int(dim)
        self.seed = int(seed)
        self.poisson = bool(poisson)
        self.verifier = verifier
        self.qos_bins = parse_qos_mix(qos_mix) if qos_mix else None
        self.hedge_after_pct = (float(hedge_after_pct)
                                if hedge_after_pct is not None else None)
        # Hedging without idempotency keys would double-serve by
        # design: arming the hedge arms the keys.
        self.idem = bool(idem) or self.hedge_after_pct is not None
        self.chaos = None
        self.chaos_injected: dict[str, int] = {}
        self.chaos_detected: dict[str, int] = {}
        if chaos_spec:
            self.chaos = chaos_mod.parse_plan(
                chaos_spec, "loadgen", on_inject=self._on_inject)
        self.client_timeout = max((deadline_ms or 1000) / 1000.0 * 4,
                                  2.0)
        self.jobs: queue.Queue = queue.Queue()
        self._lock = threading.Lock()
        self.offered = 0
        self.counts = {k: 0 for k in OUTCOMES}
        self.statuses: dict[int, int] = {}
        # Per-QoS-class sub-books: offered at schedule time, outcomes
        # at settle time, the identity checked per class at close.
        self.per_class = {name: {"offered": 0, "statuses": {},
                                 **{k: 0 for k in OUTCOMES}}
                          for name in SP.QOS_NAMES.values()}
        self.per_endpoint: dict[str, dict[str, int]] = {}
        self.wrong = 0
        self.unverifiable = 0
        self.retry_after_seen = 0
        self.hedges_fired = 0
        self.hedge_wins = 0
        self.hedge_strays = 0
        # idem key -> {endpoint: OK-serve count}.  Dedup's guarantee
        # is per rank: one endpoint serving a key twice is a double
        # serve (gate failure); two DIFFERENT endpoints each serving
        # one hedged key is the known, counted cost of a cross-rank
        # hedge (doc/serving.md "Hedged retries").
        self._ok_serves: dict[int, dict[str, int]] = {}
        self.latencies_ok: list[float] = []   # send→reply (service)
        self.sojourns_ok: list[float] = []    # scheduled→reply
        self._senders = [_Sender(self, i) for i in range(outstanding)]
        self._done = 0
        self._closed = False  # books finalized: late replies ignored
        # Deterministic feature pool: row ``seq % pool`` — cheap per
        # request (no per-request rng) and reproducible from (seed,
        # seq) alone, which is all the verifier needs.
        self._pool = np.random.default_rng(self.seed).standard_normal(
            (512, self.dim)).astype(np.float32)
        # Deterministic per-seq class draws, same discipline as the
        # feature pool: reproducible from (seed, seq) alone.
        self._qos_pool = np.random.default_rng(
            self.seed + 1).random(512)

    def _on_inject(self, kind: str, site: str, _ordinal: int,
                   _detail: str) -> None:
        with self._lock:
            key = f"{kind}@{site}"
            self.chaos_injected[key] = self.chaos_injected.get(key, 0) + 1

    def note_chaos_detected(self, site: str, kind: str) -> None:
        with self._lock:
            key = f"{kind}@{site}"
            self.chaos_detected[key] = self.chaos_detected.get(key, 0) + 1

    def features_for(self, seq: int) -> np.ndarray:
        return self._pool[seq % len(self._pool)]

    def qos_for(self, seq: int) -> int:
        if self.qos_bins is None:
            return SP.QOS_SILVER
        draw = self._qos_pool[seq % len(self._qos_pool)]
        for threshold, qos in self.qos_bins:
            if draw <= threshold:
                return qos
        return self.qos_bins[-1][1]

    def idem_for(self, seq: int) -> int:
        """Unique non-zero u64 idempotency key per logical request:
        every copy of seq (primary, hedge, chaos retry) carries the
        same key, no two seqs ever share one."""
        if not self.idem:
            return 0
        return (((self.seed & 0x7FFFFF) + 1) << 40 | (seq + 1)) \
            & 0xFFFFFFFFFFFFFFFF

    def pick_endpoint(self, exclude: tuple[str, int] | None = None
                      ) -> tuple[str, int] | None:
        if self.router is not None:
            return self.router.pick(exclude=exclude)
        ep = None
        for _ in range(4):
            ep = self.endpoints.pick()
            if ep is None or ep != exclude:
                return ep
        return ep

    def hedge_delay(self) -> float | None:
        """Seconds to wait before hedging: the rolling P-percentile of
        recent ok service latencies (None = hedging off).  Before
        enough samples exist a conservative default keeps early
        requests from storming the fleet."""
        if self.hedge_after_pct is None:
            return None
        with self._lock:
            recent = self.latencies_ok[-200:]
        if len(recent) < 20:
            return 0.05
        lat = sorted(recent)
        idx = min(int(len(lat) * self.hedge_after_pct / 100.0),
                  len(lat) - 1)
        return max(lat[idx], 0.005)

    def count_wrong(self) -> None:
        with self._lock:
            self.wrong += 1

    def count_unverifiable(self) -> None:
        with self._lock:
            self.unverifiable += 1

    def note_send(self, ep: tuple[str, int]) -> None:
        key = f"{ep[0]}:{ep[1]}"
        with self._lock:
            row = self.per_endpoint.setdefault(key, {"sent": 0, "ok": 0})
            row["sent"] += 1

    def note_hedge_fired(self) -> None:
        with self._lock:
            self.hedges_fired += 1

    def note_hedge_win(self, hedge_won: bool) -> None:
        if hedge_won:
            with self._lock:
                self.hedge_wins += 1

    def note_ok_serve(self, seq: int, ep: tuple[str, int] | None = None,
                      service: float | None = None) -> None:
        """Register one STATUS_OK serve — settled or stray — keyed by
        (idempotency key, endpoint): a second OK for one key FROM THE
        SAME ENDPOINT is a double serve, the exact thing the server's
        dedup window exists to prevent."""
        key = self.idem_for(seq)
        ep_key = f"{ep[0]}:{ep[1]}" if ep is not None else "?"
        with self._lock:
            if key:
                by_ep = self._ok_serves.setdefault(key, {})
                by_ep[ep_key] = by_ep.get(ep_key, 0) + 1
            if ep is not None:
                row = self.per_endpoint.setdefault(
                    ep_key, {"sent": 0, "ok": 0})
                row["ok"] += 1
        if ep is not None and service is not None \
                and self.router is not None:
            self.router.note_latency(ep, service)

    def note_stray(self, reply: SP.PredictReply,
                   ep: tuple[str, int]) -> None:
        """Account a hedge loser's late reply consumed off a
        persistent connection: it settles nothing (its logical request
        already did), but an OK here is a serve and MUST feed the
        double-serve registry, and its bits still get verified."""
        with self._lock:
            self.hedge_strays += 1
        if reply.status == SP.STATUS_OK:
            self.note_ok_serve(reply.req_id, ep=ep)
            if self.verifier is not None:
                verdict = self.verifier.check(
                    reply, self.features_for(reply.req_id))
                if verdict is False:
                    self.count_wrong()

    def note_result(self, seq: int, _sched_t: float, outcome: str,
                    service: float, sojourn: float, status: int,
                    retry_after_ms: int, qos: int) -> None:
        qname = SP.QOS_NAMES.get(qos, "bronze")
        sname = SP.STATUS_NAMES.get(status, str(status))
        with self._lock:
            if self._closed:
                return  # already accounted as a client timeout
            self.counts[outcome] += 1
            self.statuses[status] = self.statuses.get(status, 0) + 1
            cls = self.per_class[qname]
            cls[outcome] += 1
            cls["statuses"][sname] = cls["statuses"].get(sname, 0) + 1
            if retry_after_ms:
                self.retry_after_seen += 1
            if outcome == "ok":
                self.latencies_ok.append(service)
                self.sojourns_ok.append(sojourn)
            self._done += 1

    def run(self) -> dict:
        for s in self._senders:
            s.start()
        rescan_stop = threading.Event()

        def _rescan():
            while not rescan_stop.wait(0.5):
                self.endpoints.rescan()
                if self.router is not None:
                    self.router.update()
        threading.Thread(target=_rescan, daemon=True).start()

        rng = np.random.default_rng(self.seed)
        t0 = time.monotonic()
        next_t = 0.0
        seq = 0
        while next_t < self.duration:
            now = time.monotonic() - t0
            if now < next_t:
                time.sleep(min(next_t - now, 0.05))
                continue
            with self._lock:
                self.per_class[SP.QOS_NAMES[self.qos_for(seq)]][
                    "offered"] += 1
            self.jobs.put((seq, t0 + next_t))
            seq += 1
            gap = (rng.exponential(1.0 / self.rate) if self.poisson
                   else 1.0 / self.rate)
            next_t += gap
        self.offered = seq
        # Drain: wait for in-flight work, bounded; anything never
        # answered is a client-side timeout — the books still close.
        deadline = time.monotonic() + self.client_timeout + 2.0
        while time.monotonic() < deadline:
            with self._lock:
                if self._done >= self.offered:
                    break
            time.sleep(0.05)
        with self._lock:
            self._closed = True  # freeze the books: a reply landing
            # after this instant was already counted as a timeout
            unanswered = self.offered - self._done
            if unanswered > 0:
                self.counts["timeout"] += unanswered
                # Per-class close: each class's unanswered remainder
                # is its own client-side timeout — the sub-identity
                # must balance exactly like the aggregate one.
                for cls in self.per_class.values():
                    gap = cls["offered"] - sum(cls[k] for k in OUTCOMES)
                    if gap > 0:
                        cls["timeout"] += gap
        for _ in self._senders:
            self.jobs.put(None)
        rescan_stop.set()
        return self.report()

    def report(self) -> dict:
        with self._lock:
            lat = sorted(self.latencies_ok)
            soj = sorted(self.sojourns_ok)
            counts = dict(self.counts)
            wrong = self.wrong
            unverifiable = self.unverifiable
            per_class = {name: {k: (dict(v) if isinstance(v, dict)
                                    else v)
                                for k, v in cls.items()}
                         for name, cls in self.per_class.items()}
            per_endpoint = {k: dict(v)
                            for k, v in self.per_endpoint.items()}
            double_served = sum(
                1 for by_ep in self._ok_serves.values()
                for n in by_ep.values() if n > 1)
            cross_rank_serves = sum(
                max(sum(by_ep.values()) - 1, 0)
                for by_ep in self._ok_serves.values()
                if len(by_ep) > 1)
            hedges = {"fired": self.hedges_fired,
                      "wins": self.hedge_wins,
                      "stray_replies": self.hedge_strays,
                      "cross_rank_serves": cross_rank_serves}
            chaos_books = None
            if self.chaos is not None:
                chaos_books = {"injected": dict(self.chaos_injected),
                               "detected": dict(self.chaos_detected)}

        def pctl(xs: list[float], q: float) -> float:
            if not xs:
                return 0.0
            return xs[min(int(len(xs) * q / 100.0), len(xs) - 1)]

        def pct(q: float) -> float:
            return pctl(lat, q)
        for cls in per_class.values():
            cls["accounted"] = sum(cls[k] for k in OUTCOMES)
            cls["accounting_ok"] = cls["accounted"] == cls["offered"]
        accounted = sum(counts.values())
        return {
            "offered": self.offered,
            "rate_req_s": self.rate,
            "duration_sec": self.duration,
            "deadline_ms": self.deadline_ms,
            **counts,
            "wrong": wrong,
            "unverifiable": unverifiable,
            "accounted": accounted,
            "accounting_ok": accounted == self.offered,
            "retry_after_seen": self.retry_after_seen,
            "per_class": per_class,
            "per_endpoint": per_endpoint,
            "hedges": hedges,
            "double_served": double_served,
            "idem_keys": len(self._ok_serves),
            "router": (self.router.snapshot()
                       if self.router is not None else None),
            "chaos": chaos_books,
            "statuses": {SP.STATUS_NAMES.get(k, str(k)): v
                         for k, v in sorted(self.statuses.items())},
            "achieved_req_s": (counts["ok"] / self.duration
                               if self.duration else 0.0),
            "latency_ok_sec": {
                "p50": round(pct(50), 6), "p90": round(pct(90), 6),
                "p99": round(pct(99), 6),
                "mean": round(sum(lat) / len(lat), 6) if lat else 0.0,
                "max": round(lat[-1], 6) if lat else 0.0,
            },
            # scheduled-arrival→reply (includes client sender delay):
            # the coordinated-omission-honest number, reported next to
            # the service latency rather than instead of it.
            "sojourn_ok_sec": {
                "p50": round(pctl(soj, 50), 6),
                "p99": round(pctl(soj, 99), 6),
                "max": round(soj[-1], 6) if soj else 0.0,
            },
        }


def run_load(endpoints_dir: str | None = None,
             endpoints: list[str] | None = None, *,
             rate: float, duration: float, deadline_ms: int = 0,
             dim: int = 16, seed: int = 0, poisson: bool = False,
             outstanding: int = 64,
             verify_dir: str | None = None,
             qos_mix: str | None = None,
             hedge_after_pct: float | None = None,
             idem: bool = False,
             route: bool = False,
             metrics_url: str | None = None,
             chaos_spec: str | None = None) -> dict:
    """Library entry (bench.py / soak.py): one open-loop pass."""
    static = []
    for ep in endpoints or []:
        host, port = ep.rsplit(":", 1)
        static.append((host, int(port)))
    eps = EndpointSet(static, endpoints_dir)
    verifier = Verifier(verify_dir) if verify_dir else None
    router = (Router(eps, metrics_url=metrics_url)
              if route or metrics_url else None)
    gen = LoadGen(eps, rate, duration, deadline_ms=deadline_ms,
                  dim=dim, seed=seed, poisson=poisson,
                  outstanding=outstanding, verifier=verifier,
                  qos_mix=qos_mix, hedge_after_pct=hedge_after_pct,
                  idem=idem, router=router, chaos_spec=chaos_spec)
    return gen.run()


def run_storm(endpoint: str, *, keys: int = 32, copies: int = 4,
              dim: int = 16, seed: int = 0, deadline_ms: int = 0,
              qos: int = SP.QOS_SILVER,
              verify_dir: str | None = None) -> dict:
    """Forced hedge storm against ONE endpoint: ``copies`` copies of
    each idempotency key fired back-to-back on one connection (the
    worst interleaving a hedge retry can produce rank-locally).  The
    gate material: at most one STATUS_OK serve per key ever
    (``double_served == 0``), every suppressed copy a typed Duplicate,
    and both OK and cached-Duplicate predictions bitwise-verified."""
    host, port = endpoint.rsplit(":", 1)
    verifier = Verifier(verify_dir) if verify_dir else None
    pool = np.random.default_rng(seed).standard_normal(
        (512, dim)).astype(np.float32)
    base = ((seed & 0x7FFFFF) + 1) << 40
    ok_per_key: dict[int, int] = {k: 0 for k in range(keys)}
    duplicates = 0
    dup_cached = 0
    verified = 0
    wrong = 0
    other = 0
    sock = socket.create_connection((host, int(port)), timeout=10.0)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    try:
        for k in range(keys):
            features = pool[k % len(pool)]
            burst = b"".join(
                SP.PredictRequest(k * copies + c, deadline_ms,
                                  features, qos=qos,
                                  idem_key=base | (k + 1)).encode()
                for c in range(copies))
            sock.sendall(burst)
            for _ in range(copies):
                reply = SP.PredictReply.recv(sock)
                rk = reply.req_id // copies
                if reply.status == SP.STATUS_OK:
                    ok_per_key[rk] += 1
                elif reply.status == SP.STATUS_DUPLICATE:
                    duplicates += 1
                    if reply.predictions is not None:
                        dup_cached += 1
                else:
                    other += 1
                    continue
                if verifier is not None \
                        and reply.predictions is not None:
                    verdict = verifier.check(reply, pool[rk % len(pool)])
                    if verdict is True:
                        verified += 1
                    elif verdict is False:
                        wrong += 1
    finally:
        try:
            sock.close()
        except OSError:
            pass
    return {
        "keys": keys, "copies": copies,
        "ok_serves": sum(ok_per_key.values()),
        "double_served": sum(1 for n in ok_per_key.values() if n > 1),
        "unserved_keys": sum(1 for n in ok_per_key.values() if n == 0),
        "duplicates": duplicates, "duplicates_cached": dup_cached,
        "other": other, "verified": verified, "wrong": wrong,
    }


def run_once(endpoints_dir: str | None, endpoints: list[str] | None,
             dim: int, verify_dir: str | None, seed: int = 0,
             deadline_ms: int = 2000) -> int:
    """The ``--once`` smoke: one request, one verified reply."""
    static = []
    for ep in endpoints or []:
        host, port = ep.rsplit(":", 1)
        static.append((host, int(port)))
    eps = EndpointSet(static, endpoints_dir)
    ep = eps.pick()
    if ep is None:
        print("loadgen: no endpoints found", file=sys.stderr)
        return 2
    rng = np.random.default_rng(seed)
    features = rng.standard_normal(dim).astype(np.float32)
    try:
        sock = socket.create_connection(ep, timeout=5)
        SP.PredictRequest(1, deadline_ms, features).send(sock)
        reply = SP.PredictReply.recv(sock)
        sock.close()
    except (OSError, SP.ServeProtocolError) as e:
        print(f"loadgen: request to {ep} failed: {e}", file=sys.stderr)
        return 2
    print(f"loadgen: {ep} -> status={reply.status_name} "
          f"version={reply.model_version} "
          f"pred={reply.predictions[0] if reply.predictions is not None else None} "
          f"reason={reply.reason!r}")
    if reply.status != SP.STATUS_OK:
        return 1
    if verify_dir:
        verdict = Verifier(verify_dir).check(reply, features)
        label = {True: "PASSED", False: "FAILED"}.get(
            verdict, "UNVERIFIABLE (blob not readable)")
        print(f"loadgen: bitwise verification {label} against "
              f"committed version {reply.model_version}")
        if verdict is not True:
            return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop load generator for the rabit_tpu "
                    "serving plane (doc/serving.md)")
    ap.add_argument("--endpoint", action="append", default=[],
                    metavar="HOST:PORT")
    ap.add_argument("--endpoints-dir", default=None,
                    help="the serve ranks' published endpoint files "
                         "(re-scanned live)")
    ap.add_argument("--rate", type=float, default=100.0,
                    help="offered arrival rate, req/s (open loop)")
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--deadline-ms", type=int, default=0,
                    help="per-request latency budget sent to the "
                         "server (0 = none)")
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--poisson", action="store_true",
                    help="exponential inter-arrival gaps instead of "
                         "uniform")
    ap.add_argument("--outstanding", type=int, default=64,
                    help="sender pool size (max in-flight requests)")
    ap.add_argument("--verify-dir", default=None,
                    help="model checkpoint store: verify every OK "
                         "reply BITWISE against the committed blob of "
                         "the version it names")
    ap.add_argument("--qos-mix", default=None,
                    help="traffic class mix, e.g. "
                         "'gold:0.2,silver:0.5,bronze:0.3' "
                         "(default: all silver)")
    ap.add_argument("--hedge-after-pct", type=float, default=None,
                    help="hedge a request to a second endpoint once "
                         "its reply is later than this rolling ok-"
                         "latency percentile (arms idempotency keys)")
    ap.add_argument("--idem", action="store_true",
                    help="attach a unique idempotency key per request "
                         "even without hedging")
    ap.add_argument("--route", action="store_true",
                    help="straggler-aware weighted routing instead of "
                         "round-robin")
    ap.add_argument("--metrics-url", default=None,
                    help="tracker /metrics URL: feed "
                         "rabit_straggler_score into the router "
                         "(implies --route)")
    ap.add_argument("--chaos",
                    default=os.environ.get("RABIT_CHAOS"),
                    help="chaos spec for the serve_req/serve_reply "
                         "wire sites (see rabit_tpu.chaos)")
    ap.add_argument("--json", default=None,
                    help="write the full result JSON here")
    ap.add_argument("--once", action="store_true",
                    help="send one request, verify, exit (smoke test)")
    args = ap.parse_args(argv)
    if not args.endpoint and not args.endpoints_dir:
        ap.error("need --endpoint or --endpoints-dir")
    if args.once:
        return run_once(args.endpoints_dir, args.endpoint, args.dim,
                        args.verify_dir, seed=args.seed)
    rep = run_load(args.endpoints_dir, args.endpoint, rate=args.rate,
                   duration=args.duration, deadline_ms=args.deadline_ms,
                   dim=args.dim, seed=args.seed, poisson=args.poisson,
                   outstanding=args.outstanding,
                   verify_dir=args.verify_dir, qos_mix=args.qos_mix,
                   hedge_after_pct=args.hedge_after_pct,
                   idem=args.idem, route=args.route,
                   metrics_url=args.metrics_url,
                   chaos_spec=args.chaos)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rep, f, indent=2, sort_keys=True)
    lat = rep["latency_ok_sec"]
    print(f"loadgen: offered={rep['offered']} ok={rep['ok']} "
          f"shed={rep['shed']} timeout={rep['timeout']} "
          f"error={rep['error']} duplicate={rep['duplicate']} "
          f"wrong={rep['wrong']} double_served={rep['double_served']} "
          f"hedges={rep['hedges']['fired']} "
          f"p50={lat['p50'] * 1e3:.1f}ms p99={lat['p99'] * 1e3:.1f}ms "
          f"achieved={rep['achieved_req_s']:.1f} req/s "
          f"accounting={'OK' if rep['accounting_ok'] else 'MISMATCH'}")
    if not rep["accounting_ok"] or rep["wrong"] or rep["double_served"]:
        return 1
    return 0


def cli() -> int:
    return main()


if __name__ == "__main__":
    sys.exit(main())
