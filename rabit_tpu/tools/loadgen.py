"""Open-loop load generator for the serving plane (doc/serving.md).

**Open loop means arrival-rate, not closed-loop**: requests arrive on a
fixed schedule (``--rate`` req/s, optionally Poisson gaps) regardless
of how fast the service answers — the generator never waits for a
reply before issuing the next request, so an overloaded service sees
the true offered load instead of a politely self-throttling client.
Latency is measured from each request's *scheduled arrival* (client-
side sender delay counts against the service, coordinated-omission
style), and every reply is accounted into exactly one outcome bucket:

    offered == ok + shed + timeout + error        (the books must close)

With ``--verify-dir`` pointed at the model's durable checkpoint store,
every OK reply is recomputed client-side from the committed blob of
the version the reply names and compared **bitwise**
(serve/model.py ``predict_row`` is the oracle) — a single wrong bit is
a counted ``wrong`` answer and a non-zero exit.

Endpoints come from ``--endpoint host:port`` (repeatable) or
``--endpoints-dir`` (the serve ranks' published files, re-scanned live
so a draining rank rotates out and a fresh joiner rotates in).

Usage:
    python -m rabit_tpu.tools.loadgen --endpoints-dir D --rate 200
        --duration 10 [--deadline-ms 250] [--verify-dir CKPT]
        [--json OUT.json] [--poisson] [--seed 0] [--dim 16]
    python -m rabit_tpu.tools.loadgen --endpoints-dir D --once
        [--verify-dir CKPT]       # one request, verified: smoke test
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import queue
import socket
import sys
import threading
import time

import numpy as np

from rabit_tpu import ckpt as ckpt_mod
from rabit_tpu.serve import model as serve_model
from rabit_tpu.serve import protocol as SP

#: outcome buckets the accounting identity closes over.
OUTCOMES = ("ok", "shed", "timeout", "error")


def _status_outcome(status: int) -> str:
    """Collapse wire statuses into the accounting buckets: DRAINING is
    a shed (typed not-served-retry-elsewhere, like Overloaded)."""
    return {SP.STATUS_OK: "ok", SP.STATUS_SHED: "shed",
            SP.STATUS_DRAINING: "shed",
            SP.STATUS_TIMEOUT: "timeout"}.get(status, "error")


class EndpointSet:
    """Round-robin endpoint picker over static addrs and/or a live
    re-scanned endpoints directory."""

    def __init__(self, static: list[tuple[str, int]],
                 endpoints_dir: str | None) -> None:
        self._static = list(static)
        self._dir = endpoints_dir
        self._lock = threading.Lock()
        self._dynamic: list[tuple[str, int]] = []
        self._i = 0
        self.rescan()

    def rescan(self) -> None:
        if not self._dir:
            return
        found = []
        for path in sorted(glob.glob(os.path.join(self._dir, "*.json"))):
            try:
                with open(path) as f:
                    doc = json.load(f)
                found.append((str(doc["host"]), int(doc["port"])))
            except (OSError, ValueError, KeyError, TypeError):
                continue  # torn write / vanished file: next scan
        with self._lock:
            self._dynamic = found

    def all(self) -> list[tuple[str, int]]:
        with self._lock:
            return self._static + self._dynamic

    def pick(self) -> tuple[str, int] | None:
        with self._lock:
            eps = self._static + self._dynamic
            if not eps:
                return None
            ep = eps[self._i % len(eps)]
            self._i += 1
            return ep


class Verifier:
    """Bitwise reply verification against the committed blobs."""

    def __init__(self, ckpt_dir: str) -> None:
        self._store = ckpt_mod.CheckpointStore(ckpt_dir, rank=0)
        self._lock = threading.Lock()
        self._weights: dict[int, np.ndarray | None] = {}

    def weights_for(self, version: int) -> np.ndarray | None:
        with self._lock:
            if version in self._weights:
                return self._weights[version]
        dc = self._store.load_version(version)
        w = None
        if dc is not None:
            try:
                w = serve_model.ServedModel.from_disk_checkpoint(
                    dc).weights
            except serve_model.ModelError:
                w = None
        with self._lock:
            if w is not None:
                # Only POSITIVE results are cached: a version whose
                # blob is currently unreadable (pruned by retention, a
                # transient CRC failure) may become readable — a
                # negative cache would turn every later reply naming
                # it into a permanent verdict.
                self._weights[version] = w
        return w

    def check(self, reply: SP.PredictReply,
              features: np.ndarray) -> bool | None:
        """True/False: the reply's prediction is/is not BITWISE what
        the named committed version produces for these features.
        ``None``: UNVERIFIABLE — the version's blob is not readable
        from the store right now (pruned, torn) — which is not
        evidence of a wrong answer and is counted separately."""
        if reply.predictions is None or len(reply.predictions) != 1:
            return False
        w = self.weights_for(reply.model_version)
        if w is None:
            return None
        if w.shape[0] != features.shape[0]:
            return False
        want = serve_model.predict_row(w, features)
        got = float(reply.predictions[0])
        return got == want


class _Sender(threading.Thread):
    """One sender: a persistent connection per endpoint, re-dialed on
    failure.  Pulls (seq, scheduled_time) jobs and accounts each into
    exactly one outcome."""

    def __init__(self, gen: "LoadGen", idx: int) -> None:
        super().__init__(name=f"loadgen-send-{idx}", daemon=True)
        self.gen = gen
        self._conns: dict[tuple[str, int], socket.socket] = {}

    def _conn(self, ep: tuple[str, int],
              timeout: float) -> socket.socket:
        sock = self._conns.get(ep)
        if sock is None:
            sock = socket.create_connection(ep, timeout=timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns[ep] = sock
        sock.settimeout(timeout)
        return sock

    def _drop(self, ep: tuple[str, int]) -> None:
        sock = self._conns.pop(ep, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def run(self) -> None:
        gen = self.gen
        while True:
            job = gen.jobs.get()
            if job is None:
                return
            seq, sched_t = job
            gen.note_result(seq, sched_t,
                            *self._one(seq, sched_t))

    def _one(self, seq: int, sched_t: float
             ) -> tuple[str, float, float, int, int]:
        """Send one request; returns (outcome, service_sec,
        sojourn_sec, wire_status, retry_after_ms).  ``service`` is
        send→reply (the server's behavior); ``sojourn`` is scheduled
        arrival→reply (adds client-side sender delay — the open-loop
        honesty number)."""
        gen = self.gen
        ep = gen.endpoints.pick()
        if ep is None:
            return "error", 0.0, 0.0, -1, 0
        features = gen.features_for(seq)
        timeout = gen.client_timeout
        sent_t = time.monotonic()
        try:
            sock = self._conn(ep, timeout)
            SP.PredictRequest(seq & 0xFFFFFFFF, gen.deadline_ms,
                              features).send(sock)
            reply = SP.PredictReply.recv(sock)
        except (OSError, SP.ServeProtocolError, ConnectionError):
            self._drop(ep)
            now = time.monotonic()
            return "error", now - sent_t, now - sched_t, -1, 0
        now = time.monotonic()
        outcome = _status_outcome(reply.status)
        if outcome == "ok" and gen.verifier is not None:
            verdict = gen.verifier.check(reply, features)
            if verdict is False:
                gen.count_wrong()
                outcome = "error"
            elif verdict is None:
                gen.count_unverifiable()
        return (outcome, now - sent_t, now - sched_t, reply.status,
                reply.retry_after_ms)


class LoadGen:
    """One open-loop run (library face; ``main`` is the CLI)."""

    def __init__(self, endpoints: EndpointSet, rate: float,
                 duration: float, *, deadline_ms: int = 0,
                 dim: int = 16, seed: int = 0, poisson: bool = False,
                 outstanding: int = 64,
                 verifier: Verifier | None = None) -> None:
        self.endpoints = endpoints
        self.rate = max(float(rate), 0.001)
        self.duration = float(duration)
        self.deadline_ms = int(deadline_ms)
        self.dim = int(dim)
        self.seed = int(seed)
        self.poisson = bool(poisson)
        self.verifier = verifier
        self.client_timeout = max((deadline_ms or 1000) / 1000.0 * 4,
                                  2.0)
        self.jobs: queue.Queue = queue.Queue()
        self._lock = threading.Lock()
        self.offered = 0
        self.counts = {k: 0 for k in OUTCOMES}
        self.statuses: dict[int, int] = {}
        self.wrong = 0
        self.unverifiable = 0
        self.retry_after_seen = 0
        self.latencies_ok: list[float] = []   # send→reply (service)
        self.sojourns_ok: list[float] = []    # scheduled→reply
        self._senders = [_Sender(self, i) for i in range(outstanding)]
        self._done = 0
        self._closed = False  # books finalized: late replies ignored
        # Deterministic feature pool: row ``seq % pool`` — cheap per
        # request (no per-request rng) and reproducible from (seed,
        # seq) alone, which is all the verifier needs.
        self._pool = np.random.default_rng(self.seed).standard_normal(
            (512, self.dim)).astype(np.float32)

    def features_for(self, seq: int) -> np.ndarray:
        return self._pool[seq % len(self._pool)]

    def count_wrong(self) -> None:
        with self._lock:
            self.wrong += 1

    def count_unverifiable(self) -> None:
        with self._lock:
            self.unverifiable += 1

    def note_result(self, _seq: int, _sched_t: float, outcome: str,
                    service: float, sojourn: float, status: int,
                    retry_after_ms: int) -> None:
        with self._lock:
            if self._closed:
                return  # already accounted as a client timeout
            self.counts[outcome] += 1
            self.statuses[status] = self.statuses.get(status, 0) + 1
            if retry_after_ms:
                self.retry_after_seen += 1
            if outcome == "ok":
                self.latencies_ok.append(service)
                self.sojourns_ok.append(sojourn)
            self._done += 1

    def run(self) -> dict:
        for s in self._senders:
            s.start()
        rescan_stop = threading.Event()

        def _rescan():
            while not rescan_stop.wait(0.5):
                self.endpoints.rescan()
        threading.Thread(target=_rescan, daemon=True).start()

        rng = np.random.default_rng(self.seed)
        t0 = time.monotonic()
        next_t = 0.0
        seq = 0
        while next_t < self.duration:
            now = time.monotonic() - t0
            if now < next_t:
                time.sleep(min(next_t - now, 0.05))
                continue
            self.jobs.put((seq, t0 + next_t))
            seq += 1
            gap = (rng.exponential(1.0 / self.rate) if self.poisson
                   else 1.0 / self.rate)
            next_t += gap
        self.offered = seq
        # Drain: wait for in-flight work, bounded; anything never
        # answered is a client-side timeout — the books still close.
        deadline = time.monotonic() + self.client_timeout + 2.0
        while time.monotonic() < deadline:
            with self._lock:
                if self._done >= self.offered:
                    break
            time.sleep(0.05)
        with self._lock:
            self._closed = True  # freeze the books: a reply landing
            # after this instant was already counted as a timeout
            unanswered = self.offered - self._done
            if unanswered > 0:
                self.counts["timeout"] += unanswered
        for _ in self._senders:
            self.jobs.put(None)
        rescan_stop.set()
        return self.report()

    def report(self) -> dict:
        with self._lock:
            lat = sorted(self.latencies_ok)
            soj = sorted(self.sojourns_ok)
            counts = dict(self.counts)
            wrong = self.wrong
            unverifiable = self.unverifiable

        def pctl(xs: list[float], q: float) -> float:
            if not xs:
                return 0.0
            return xs[min(int(len(xs) * q / 100.0), len(xs) - 1)]

        def pct(q: float) -> float:
            return pctl(lat, q)
        accounted = sum(counts.values())
        return {
            "offered": self.offered,
            "rate_req_s": self.rate,
            "duration_sec": self.duration,
            "deadline_ms": self.deadline_ms,
            **counts,
            "wrong": wrong,
            "unverifiable": unverifiable,
            "accounted": accounted,
            "accounting_ok": accounted == self.offered,
            "retry_after_seen": self.retry_after_seen,
            "statuses": {SP.STATUS_NAMES.get(k, str(k)): v
                         for k, v in sorted(self.statuses.items())},
            "achieved_req_s": (counts["ok"] / self.duration
                               if self.duration else 0.0),
            "latency_ok_sec": {
                "p50": round(pct(50), 6), "p90": round(pct(90), 6),
                "p99": round(pct(99), 6),
                "mean": round(sum(lat) / len(lat), 6) if lat else 0.0,
                "max": round(lat[-1], 6) if lat else 0.0,
            },
            # scheduled-arrival→reply (includes client sender delay):
            # the coordinated-omission-honest number, reported next to
            # the service latency rather than instead of it.
            "sojourn_ok_sec": {
                "p50": round(pctl(soj, 50), 6),
                "p99": round(pctl(soj, 99), 6),
                "max": round(soj[-1], 6) if soj else 0.0,
            },
        }


def run_load(endpoints_dir: str | None = None,
             endpoints: list[str] | None = None, *,
             rate: float, duration: float, deadline_ms: int = 0,
             dim: int = 16, seed: int = 0, poisson: bool = False,
             outstanding: int = 64,
             verify_dir: str | None = None) -> dict:
    """Library entry (bench.py / soak.py): one open-loop pass."""
    static = []
    for ep in endpoints or []:
        host, port = ep.rsplit(":", 1)
        static.append((host, int(port)))
    eps = EndpointSet(static, endpoints_dir)
    verifier = Verifier(verify_dir) if verify_dir else None
    gen = LoadGen(eps, rate, duration, deadline_ms=deadline_ms,
                  dim=dim, seed=seed, poisson=poisson,
                  outstanding=outstanding, verifier=verifier)
    return gen.run()


def run_once(endpoints_dir: str | None, endpoints: list[str] | None,
             dim: int, verify_dir: str | None, seed: int = 0,
             deadline_ms: int = 2000) -> int:
    """The ``--once`` smoke: one request, one verified reply."""
    static = []
    for ep in endpoints or []:
        host, port = ep.rsplit(":", 1)
        static.append((host, int(port)))
    eps = EndpointSet(static, endpoints_dir)
    ep = eps.pick()
    if ep is None:
        print("loadgen: no endpoints found", file=sys.stderr)
        return 2
    rng = np.random.default_rng(seed)
    features = rng.standard_normal(dim).astype(np.float32)
    try:
        sock = socket.create_connection(ep, timeout=5)
        SP.PredictRequest(1, deadline_ms, features).send(sock)
        reply = SP.PredictReply.recv(sock)
        sock.close()
    except (OSError, SP.ServeProtocolError) as e:
        print(f"loadgen: request to {ep} failed: {e}", file=sys.stderr)
        return 2
    print(f"loadgen: {ep} -> status={reply.status_name} "
          f"version={reply.model_version} "
          f"pred={reply.predictions[0] if reply.predictions is not None else None} "
          f"reason={reply.reason!r}")
    if reply.status != SP.STATUS_OK:
        return 1
    if verify_dir:
        verdict = Verifier(verify_dir).check(reply, features)
        label = {True: "PASSED", False: "FAILED"}.get(
            verdict, "UNVERIFIABLE (blob not readable)")
        print(f"loadgen: bitwise verification {label} against "
              f"committed version {reply.model_version}")
        if verdict is not True:
            return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop load generator for the rabit_tpu "
                    "serving plane (doc/serving.md)")
    ap.add_argument("--endpoint", action="append", default=[],
                    metavar="HOST:PORT")
    ap.add_argument("--endpoints-dir", default=None,
                    help="the serve ranks' published endpoint files "
                         "(re-scanned live)")
    ap.add_argument("--rate", type=float, default=100.0,
                    help="offered arrival rate, req/s (open loop)")
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--deadline-ms", type=int, default=0,
                    help="per-request latency budget sent to the "
                         "server (0 = none)")
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--poisson", action="store_true",
                    help="exponential inter-arrival gaps instead of "
                         "uniform")
    ap.add_argument("--outstanding", type=int, default=64,
                    help="sender pool size (max in-flight requests)")
    ap.add_argument("--verify-dir", default=None,
                    help="model checkpoint store: verify every OK "
                         "reply BITWISE against the committed blob of "
                         "the version it names")
    ap.add_argument("--json", default=None,
                    help="write the full result JSON here")
    ap.add_argument("--once", action="store_true",
                    help="send one request, verify, exit (smoke test)")
    args = ap.parse_args(argv)
    if not args.endpoint and not args.endpoints_dir:
        ap.error("need --endpoint or --endpoints-dir")
    if args.once:
        return run_once(args.endpoints_dir, args.endpoint, args.dim,
                        args.verify_dir, seed=args.seed)
    rep = run_load(args.endpoints_dir, args.endpoint, rate=args.rate,
                   duration=args.duration, deadline_ms=args.deadline_ms,
                   dim=args.dim, seed=args.seed, poisson=args.poisson,
                   outstanding=args.outstanding,
                   verify_dir=args.verify_dir)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rep, f, indent=2, sort_keys=True)
    lat = rep["latency_ok_sec"]
    print(f"loadgen: offered={rep['offered']} ok={rep['ok']} "
          f"shed={rep['shed']} timeout={rep['timeout']} "
          f"error={rep['error']} wrong={rep['wrong']} "
          f"p50={lat['p50'] * 1e3:.1f}ms p99={lat['p99'] * 1e3:.1f}ms "
          f"achieved={rep['achieved_req_s']:.1f} req/s "
          f"accounting={'OK' if rep['accounting_ok'] else 'MISMATCH'}")
    if not rep["accounting_ok"] or rep["wrong"]:
        return 1
    return 0


def cli() -> int:
    return main()


if __name__ == "__main__":
    sys.exit(main())
