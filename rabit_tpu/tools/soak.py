"""Randomized fault-injection soak for the recovery protocol.

Generates a seeded random kill-point matrix (ranks × versions × seqnos,
including die-hard second-life kills) and runs the self-verifying
recovery workers under the keepalive launcher — the randomized big
brother of the fixed scenario matrix in tests/test_recovery.py
(reference analogue: the die-same/die-hard cases of test/test.mk:7-24).

Usage:
    python -m rabit_tpu.tools.soak [--world 8] [--rounds 3] [--seed 0]
        [--worker model_recover] [--ndata 5000] [--niter 8]
        [--engine mock|pyrobust]   # native C++ or pure-Python recovery
    python -m rabit_tpu.tools.soak --worker xla_restart [--world 4]
        # randomized die-plans through the XLA engine's device-plane
        # re-formation (--ndata/--niter/--kills do not apply)
    python -m rabit_tpu.tools.soak --chaos [--engine pyrobust|pysocket]
        # wire-level chaos: each round additionally drives a seeded
        # RABIT_CHAOS plan (resets, refused dials, partial writes,
        # stalls) through the pure-Python engines; pyrobust rounds mix
        # kills + resets (full recovery), pysocket rounds restrict the
        # mix to faults the non-fault-tolerant base engine must absorb
        # (connect retries, splits, sub-timeout stalls)
    python -m rabit_tpu.tools.soak --cold-restart --engine pyrobust
        # the durable-tier headline gate: each round kills EVERY rank
        # right after a seeded checkpoint commit (no in-memory replica
        # survives), the supervisor relaunches the world under the
        # restart budget, the relaunched lives cold-resume from
        # RABIT_CKPT_DIR, and the final model is compared bit-for-bit
        # against an uninterrupted reference run; mix in --chaos for
        # wire faults on top
Exits non-zero on the first failed run, printing the kill matrix (and
chaos plan) so the failure is reproducible.
"""
from __future__ import annotations

import argparse
import os
import pathlib
import random
import sys

# repo root (tools/ -> rabit_tpu/ -> repo); the workers live in
# tests/workers/, so resolve against the source checkout instead of the
# cwd.  tests/ is not packaged — installed environments must pass
# --worker-path explicitly.
_REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


def gen_matrix(rng: random.Random, world: int, niter: int,
               nkills: int) -> str:
    """';'-joined mock=rank,version,seqno,ndeath kill-points."""
    points = set()
    while len(points) < nkills:
        rank = rng.randrange(world)
        version = rng.randrange(niter)
        seqno = rng.randrange(4)
        # occasionally kill the same point on the restarted life too
        ndeath = 1 if rng.random() < 0.2 and any(
            p[:3] == (rank, version, seqno) for p in points) else 0
        points.add((rank, version, seqno, ndeath))
    return ";".join(",".join(map(str, p)) for p in sorted(points))


def gen_chaos(rng: random.Random, engine: str) -> str:
    """One seeded RABIT_CHAOS plan (doc/fault_tolerance.md "Chaos
    testing").  pyrobust gets the full mix — recovery must absorb
    mid-stream resets on top of kill-points; pysocket (no recovery)
    gets only the faults the hardened base transport must survive:
    refused/slow dials (retry+backoff), partial splits, EINTR, and
    stalls well under the link timeout."""
    seed = rng.randrange(1 << 30)
    if engine == "pyrobust":
        return (f"{seed}:reset@io=0.002*2;refuse@connect=0.25*6;"
                f"partial@io=0.05*400;eintr@io=0.02*50;stall@io=0.02*40;"
                f"stallms=25;budget=512")
    return (f"{seed}:refuse@connect=0.25*6;partial@io=0.08*400;"
            f"eintr@io=0.02*50;stall@io=0.02*40;stallms=20;budget=512")


def run_cold_restart(args, rng: random.Random,
                     round_obs_dir) -> int:
    """Seeded kill-ALL-ranks rounds against the durable checkpoint tier
    (--cold-restart): every rank SIGKILLs itself right after committing
    a seeded version, the supervisor relaunches the world, and the
    resumed run's final model must be bit-identical to an uninterrupted
    reference."""
    import shutil
    import tempfile

    from rabit_tpu.tracker.launch_local import launch

    worker_path = args.worker_path or str(
        _REPO_ROOT / "tests" / "workers" / "cold_restart.py")
    base = pathlib.Path(tempfile.mkdtemp(prefix="rabit_cold_soak_"))
    try:
        ref_dir = base / "ref"
        code = launch(
            args.world, [sys.executable, worker_path,
                         str(args.ndata), str(args.niter)],
            extra_env={"RABIT_ENGINE": "pyrobust",
                       "RABIT_OUT_DIR": str(ref_dir)})
        if code != 0:
            print(f"[soak] FAILED: uninterrupted reference run exited "
                  f"{code}", flush=True)
            return 1
        for r in range(args.rounds):
            kill_iter = 1 + rng.randrange(max(args.niter - 1, 1))
            rdir = base / f"round{r}"
            cold_dir = rdir / "cold"
            cold_dir.mkdir(parents=True)
            env = {"RABIT_ENGINE": "pyrobust",
                   "RABIT_OUT_DIR": str(rdir / "out"),
                   "RABIT_COLD_DIR": str(cold_dir),
                   "RABIT_COLD_KILL_ITER": str(kill_iter)}
            if args.chaos:
                env["RABIT_CHAOS"] = gen_chaos(rng, "pyrobust")
                if "RABIT_TIMEOUT_SEC" not in os.environ:
                    env["RABIT_TIMEOUT_SEC"] = "20"
                if "RABIT_BACKOFF_BASE_MS" not in os.environ:
                    env["RABIT_BACKOFF_BASE_MS"] = "20"
            print(f"[soak] round {r}: cold-restart kill_iter={kill_iter} "
                  f"chaos={env.get('RABIT_CHAOS', '')}", flush=True)
            code = launch(
                args.world, [sys.executable, worker_path,
                             str(args.ndata), str(args.niter)],
                extra_env=env, ckpt_dir=str(rdir / "ckpt"),
                heartbeat_sec=args.heartbeat,
                max_restarts=args.max_restarts, restart_backoff_ms=100,
                obs_dir=round_obs_dir(r))
            if code != 0:
                print(f"[soak] FAILED (exit {code}) — reproduce with "
                      f"RABIT_COLD_KILL_ITER='{kill_iter}' "
                      f"RABIT_CHAOS='{env.get('RABIT_CHAOS', '')}'",
                      flush=True)
                return 1
            for rank in range(args.world):
                ref = (ref_dir / f"final.{rank}").read_bytes()
                got = (rdir / "out" / f"final.{rank}").read_bytes()
                if ref != got:
                    print(f"[soak] FAILED: rank {rank} final model is "
                          f"NOT bit-identical after the cold restart "
                          f"(kill_iter={kill_iter})", flush=True)
                    return 1
            print(f"[soak] round {r}: resumed at v{kill_iter}, final "
                  "model bit-identical", flush=True)
        print(f"[soak] {args.rounds} cold-restart rounds passed",
              flush=True)
        return 0
    finally:
        shutil.rmtree(base, ignore_errors=True)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=8)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--worker", default="model_recover",
                    choices=["model_recover", "local_recover",
                             "lazy_recover", "xla_restart"])
    ap.add_argument("--engine", default="mock",
                    choices=["mock", "pyrobust", "pysocket"],
                    help="robust engine the kill matrix drives: the "
                         "native C++ mock (default) or the pure-Python "
                         "pyrobust engine (no .so needed; same "
                         "RABIT_MOCK kill-point format); pysocket is "
                         "valid only with --chaos (no recovery — the "
                         "chaos mix is restricted to survivable faults)")
    ap.add_argument("--chaos", action="store_true",
                    help="layer a seeded RABIT_CHAOS wire-fault plan "
                         "(resets/refusals/partial writes/stalls) onto "
                         "each round; python engines only")
    ap.add_argument("--cold-restart", action="store_true",
                    help="kill ALL ranks after a seeded checkpoint "
                         "commit each round, relaunch the world under "
                         "the supervisor, cold-resume from the durable "
                         "tier and verify the final model bit-for-bit "
                         "against an uninterrupted run (pyrobust only)")
    ap.add_argument("--max-restarts", type=int, default=4,
                    help="supervisor relaunch budget per worker for "
                         "--cold-restart rounds")
    ap.add_argument("--heartbeat", type=float, default=0.5,
                    help="worker heartbeat period for --cold-restart "
                         "rounds (proactive tracker-side liveness)")
    ap.add_argument("--ndata", type=int, default=5000)
    ap.add_argument("--niter", type=int, default=8)
    ap.add_argument("--kills", type=int, default=6)
    ap.add_argument("--worker-path", default=None,
                    help="explicit path to the worker script (defaults "
                         "to tests/workers/<worker>.py in the repo)")
    ap.add_argument("--obs-dir", default=None,
                    help="enable telemetry: each round writes per-rank "
                         "event traces plus the tracker-aggregated "
                         "obs_report.json under <obs-dir>/round<N> "
                         "(render with python -m "
                         "rabit_tpu.tools.obs_report)")
    args = ap.parse_args(argv)
    if args.chaos and args.engine == "mock" and not args.cold_restart:
        ap.error("--chaos drives the Python engines only; pass "
                 "--engine pyrobust (recovery mix) or pysocket "
                 "(survivable mix)")
    if args.engine == "pysocket" and not args.chaos:
        ap.error("--engine pysocket is only meaningful with --chaos "
                 "(it has no recovery protocol for a kill matrix)")
    if args.chaos and args.worker == "xla_restart":
        ap.error("--chaos does not apply to the xla_restart worker")
    if args.cold_restart and args.engine != "pyrobust":
        ap.error("--cold-restart drives the durable tier through the "
                 "pure-Python robust engine; pass --engine pyrobust")

    from rabit_tpu.tracker.launch_local import launch

    worker_path = args.worker_path or str(
        _REPO_ROOT / "tests" / "workers" / f"{args.worker}.py")
    rng = random.Random(args.seed)

    def round_obs_dir(r: int) -> str | None:
        if not args.obs_dir:
            return None
        return str(pathlib.Path(args.obs_dir) / f"round{r}")

    if args.cold_restart:
        return run_cold_restart(args, rng, round_obs_dir)

    for r in range(args.rounds):
        if args.worker == "xla_restart":
            # Randomized deaths through the XLA engine's device-plane
            # re-formation: distinct victims at random iterations (the
            # worker's fixed NITER is 4; iters 1-3 leave room to resume,
            # re-form, and verify the post-reform device path).
            # --ndata/--niter/--kills are mock-matrix knobs, inert here.
            if r == 0 and (args.ndata != 5000 or args.niter != 8
                           or args.kills != 6):
                print("[soak] note: --ndata/--niter/--kills do not apply "
                      "to the xla_restart worker (fixed NITER=4, 1-2 "
                      "victims)", flush=True)
            nvictims = min(1 + rng.randrange(2), args.world - 1)
            victims = rng.sample(range(args.world), nvictims)
            plan = ";".join(f"{v}:{1 + rng.randrange(3)}" for v in victims)
            print(f"[soak] round {r}: xla die-plan={plan}", flush=True)
            # --engine maps onto the XLA engine's host control plane:
            # mock -> the native robust inner, pyrobust -> the pure-
            # Python one.  A caller-exported RABIT_INNER still wins.
            inner = "native" if args.engine == "mock" else args.engine
            code = launch(
                args.world, [sys.executable, worker_path],
                extra_env={"RABIT_INNER": os.environ.get("RABIT_INNER",
                                                         inner),
                           "RABIT_XLA_DIE": plan},
                # worlds share one core on the CI box: scale the grace
                # period so jax import/startup isn't mistaken for a hang
                watchdog_sec=max(20, 4 * args.world),
                obs_dir=round_obs_dir(r))
            if code != 0:
                print(f"[soak] FAILED (exit {code}) — reproduce with "
                      f"RABIT_XLA_DIE='{plan}'", flush=True)
                return 1
            continue
        # pysocket has no recovery: chaos rounds on it run kill-free.
        matrix = ("" if args.engine == "pysocket"
                  else gen_matrix(rng, args.world, args.niter, args.kills))
        env = {"RABIT_ENGINE": args.engine}
        if matrix:
            env["RABIT_MOCK"] = matrix
        if args.chaos:
            env["RABIT_CHAOS"] = gen_chaos(rng, args.engine)
            # Fast hung-peer detection so injected stalls/resets turn
            # into recovery rounds in seconds, not the 600 s default;
            # quick backoff keeps the chaos rounds snappy.  A caller's
            # exported value wins (launch() overlays this dict onto
            # os.environ, so defaulting here would clobber it).
            if "RABIT_TIMEOUT_SEC" not in os.environ:
                env["RABIT_TIMEOUT_SEC"] = "20"
            if "RABIT_BACKOFF_BASE_MS" not in os.environ:
                env["RABIT_BACKOFF_BASE_MS"] = "20"
        print(f"[soak] round {r}: engine={args.engine} mock={matrix} "
              f"chaos={env.get('RABIT_CHAOS', '')}", flush=True)
        code = launch(
            args.world,
            [sys.executable, worker_path,
             str(args.ndata), str(args.niter)],
            extra_env=env, obs_dir=round_obs_dir(r))
        if code != 0:
            print(f"[soak] FAILED (exit {code}) — reproduce with "
                  f"RABIT_ENGINE='{args.engine}' RABIT_MOCK='{matrix}' "
                  f"RABIT_CHAOS='{env.get('RABIT_CHAOS', '')}'",
                  flush=True)
            return 1
    print(f"[soak] {args.rounds} rounds passed", flush=True)
    return 0


def cli() -> int:
    """Console-script entry point."""
    return main()


if __name__ == "__main__":
    sys.exit(main())
