"""Randomized fault-injection soak for the recovery protocol.

Generates a seeded random kill-point matrix (ranks × versions × seqnos,
including die-hard second-life kills) and runs the self-verifying
recovery workers under the keepalive launcher — the randomized big
brother of the fixed scenario matrix in tests/test_recovery.py
(reference analogue: the die-same/die-hard cases of test/test.mk:7-24).

Usage:
    python -m rabit_tpu.tools.soak [--world 8] [--rounds 3] [--seed 0]
        [--worker model_recover] [--ndata 5000] [--niter 8]
        [--engine mock|pyrobust]   # native C++ or pure-Python recovery
    python -m rabit_tpu.tools.soak --worker xla_restart [--world 4]
        # randomized die-plans through the XLA engine's device-plane
        # re-formation (--ndata/--niter/--kills do not apply)
    python -m rabit_tpu.tools.soak --chaos [--engine pyrobust|pysocket]
        # wire-level chaos: each round additionally drives a seeded
        # RABIT_CHAOS plan (resets, refused dials, partial writes,
        # stalls) through the pure-Python engines; pyrobust rounds mix
        # kills + resets (full recovery), pysocket rounds restrict the
        # mix to faults the non-fault-tolerant base engine must absorb
        # (connect retries, splits, sub-timeout stalls)
    python -m rabit_tpu.tools.soak --cold-restart --engine pyrobust
        # the durable-tier headline gate: each round kills EVERY rank
        # right after a seeded checkpoint commit (no in-memory replica
        # survives), the supervisor relaunches the world under the
        # restart budget, the relaunched lives cold-resume from
        # RABIT_CKPT_DIR, and the final model is compared bit-for-bit
        # against an uninterrupted reference run; mix in --chaos for
        # wire faults on top
    python -m rabit_tpu.tools.soak --elastic [--rounds 1]
        # the elastic-membership headline gate: the world grows 4->6
        # (late joiners admitted at a checkpoint-commit boundary) and
        # shrinks 6->3 (three seeded SIGKILLs -> heartbeat scale-down)
        # mid-training, with the TRACKER killed and restarted once at a
        # seeded point (journal replayed from --state-dir; the workers'
        # registration retry bridges the outage).  Each rescale segment
        # is then re-run as a FRESH job at that world size from the
        # same committed blob and the models compared bit-for-bit at
        # the next boundary; mix in --chaos for wire faults on top
    python -m rabit_tpu.tools.soak --transport shm [--chaos]
        # the shm-transport gate: shared-memory rings + integrity
        # framing under seeded corruption (one guaranteed torn ring
        # write per rank -> detect -> live shm->tcp failover), final
        # model bit-exact vs an uninterrupted tcp reference; mix in
        # --chaos for the full wire fault mix on top
    python -m rabit_tpu.tools.soak --adapt [--chaos]
        # the closed-loop gate: a world-4 pyrobust job with rank 0
        # deliberately slowed runs under a tracker with the adaptive
        # controller armed (--adapt --tune-dir); the controller must
        # (a) converge to a measurably faster schedule than the static
        # pick (switch decision whose challenger cost beats the
        # incumbent, asserted from the merged span data), (b) demote
        # the slowed rank out of hierarchical leader roles, (c) keep
        # the final model bit-exact vs an uninterrupted run, and (d)
        # persist what it learned into the TuningCache so a FRESH
        # rabit_sched=auto job starts on the learned schedule; mix in
        # --chaos for wire faults on top
    python -m rabit_tpu.tools.soak --serve [--rounds 1]
        # the serving-plane gate (doc/serving.md): a 2-rank fleet with
        # pinned capacity (the slow-ms seam) serves bitwise-verified
        # traffic through steady load, a live model-version rollover,
        # a 2x-capacity open-loop overload spike (typed Overloaded
        # sheds with retry-after, served p99 within 5x steady — no
        # queue collapse), a mid-traffic rank SIGKILL absorbed by an
        # elastic epoch with bounded availability dip, and a
        # train-while-serving co-tenant job that must stay bit-exact
        # vs a solo run
    python -m rabit_tpu.tools.soak --postmortem [--rounds 1]
        # the crash-forensics gate (doc/observability.md "Causal
        # tracing & postmortem"): a world-4 pysocket job has a seeded
        # rank SIGKILLed immediately before a seeded allreduce; the
        # survivors' LinkError fault paths persist their always-on
        # flight recorders under --trace-dir and tools/postmortem.py
        # must name the first-dead rank and the in-flight op
        # (kind/seq) from the persisted artifacts alone
    python -m rabit_tpu.tools.soak --tenants 2 [--chaos] [--elastic]
        [--adapt]
        # the multi-tenant isolation gate: N jobs train concurrently
        # against ONE shared tracker (--max-jobs admission armed);
        # mid-training EVERY worker of tenant A is SIGKILLed — the
        # tracker must survive, orphan-GC tenant A's job, and tenant
        # B's final model must be BIT-EXACT against a solo run of the
        # same workload on a dedicated tracker (no cross-tenant
        # interference); mix in --chaos for wire faults on both
        # tenants, --elastic to arm elastic membership on the shared
        # tracker
Exits non-zero on the first failed run, printing the kill matrix (and
chaos plan) so the failure is reproducible.
"""
from __future__ import annotations

import argparse
import os
import pathlib
import random
import sys

# repo root (tools/ -> rabit_tpu/ -> repo); the workers live in
# tests/workers/, so resolve against the source checkout instead of the
# cwd.  tests/ is not packaged — installed environments must pass
# --worker-path explicitly.
_REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]


def gen_matrix(rng: random.Random, world: int, niter: int,
               nkills: int) -> str:
    """';'-joined mock=rank,version,seqno,ndeath kill-points."""
    points = set()
    while len(points) < nkills:
        rank = rng.randrange(world)
        version = rng.randrange(niter)
        seqno = rng.randrange(4)
        # occasionally kill the same point on the restarted life too
        ndeath = 1 if rng.random() < 0.2 and any(
            p[:3] == (rank, version, seqno) for p in points) else 0
        points.add((rank, version, seqno, ndeath))
    return ";".join(",".join(map(str, p)) for p in sorted(points))


def gen_chaos(rng: random.Random, engine: str,
              link: bool = False) -> str:
    """One seeded RABIT_CHAOS plan (doc/fault_tolerance.md "Chaos
    testing").  pyrobust gets the full mix — recovery must absorb
    mid-stream resets on top of kill-points; pysocket (no recovery)
    gets only the faults the hardened base transport must survive:
    refused/slow dials (retry+backoff), partial splits, EINTR, and
    stalls well under the link timeout.  ``link`` (the --shards gate)
    additionally arms the tracker-link sites — seeded resets/stalls at
    the hello and heartbeat exchanges, the faults a dying shard
    produces — which the worker must turn into counted retries and
    re-dials, never a hang."""
    seed = rng.randrange(1 << 30)
    tracker_link = ("reset@hello=0.2*2;stall@hb=0.25*4;reset@hb=0.1*2;"
                    if link else "")
    if engine == "pyrobust":
        return (f"{seed}:{tracker_link}"
                f"reset@io=0.002*2;refuse@connect=0.25*6;"
                f"partial@io=0.05*400;eintr@io=0.02*50;stall@io=0.02*40;"
                f"stallms=25;budget=512")
    return (f"{seed}:{tracker_link}refuse@connect=0.25*6;"
            f"partial@io=0.08*400;"
            f"eintr@io=0.02*50;stall@io=0.02*40;stallms=20;budget=512")


def run_cold_restart(args, rng: random.Random,
                     round_obs_dir) -> int:
    """Seeded kill-ALL-ranks rounds against the durable checkpoint tier
    (--cold-restart): every rank SIGKILLs itself right after committing
    a seeded version, the supervisor relaunches the world, and the
    resumed run's final model must be bit-identical to an uninterrupted
    reference."""
    import shutil
    import tempfile

    from rabit_tpu.tracker.launch_local import launch

    worker_path = args.worker_path or str(
        _REPO_ROOT / "tests" / "workers" / "cold_restart.py")
    base = pathlib.Path(tempfile.mkdtemp(prefix="rabit_cold_soak_"))
    try:
        ref_dir = base / "ref"
        code = launch(
            args.world, [sys.executable, worker_path,
                         str(args.ndata), str(args.niter)],
            extra_env={"RABIT_ENGINE": "pyrobust",
                       "RABIT_OUT_DIR": str(ref_dir)})
        if code != 0:
            print(f"[soak] FAILED: uninterrupted reference run exited "
                  f"{code}", flush=True)
            return 1
        for r in range(args.rounds):
            kill_iter = 1 + rng.randrange(max(args.niter - 1, 1))
            rdir = base / f"round{r}"
            cold_dir = rdir / "cold"
            cold_dir.mkdir(parents=True)
            env = {"RABIT_ENGINE": "pyrobust",
                   "RABIT_OUT_DIR": str(rdir / "out"),
                   "RABIT_COLD_DIR": str(cold_dir),
                   "RABIT_COLD_KILL_ITER": str(kill_iter)}
            if args.chaos:
                env["RABIT_CHAOS"] = gen_chaos(rng, "pyrobust")
                if "RABIT_TIMEOUT_SEC" not in os.environ:
                    env["RABIT_TIMEOUT_SEC"] = "20"
                if "RABIT_BACKOFF_BASE_MS" not in os.environ:
                    env["RABIT_BACKOFF_BASE_MS"] = "20"
            print(f"[soak] round {r}: cold-restart kill_iter={kill_iter} "
                  f"chaos={env.get('RABIT_CHAOS', '')}", flush=True)
            code = launch(
                args.world, [sys.executable, worker_path,
                             str(args.ndata), str(args.niter)],
                extra_env=env, ckpt_dir=str(rdir / "ckpt"),
                heartbeat_sec=args.heartbeat,
                max_restarts=args.max_restarts, restart_backoff_ms=100,
                obs_dir=round_obs_dir(r))
            if code != 0:
                print(f"[soak] FAILED (exit {code}) — reproduce with "
                      f"RABIT_COLD_KILL_ITER='{kill_iter}' "
                      f"RABIT_CHAOS='{env.get('RABIT_CHAOS', '')}'",
                      flush=True)
                return 1
            for rank in range(args.world):
                ref = (ref_dir / f"final.{rank}").read_bytes()
                got = (rdir / "out" / f"final.{rank}").read_bytes()
                if ref != got:
                    print(f"[soak] FAILED: rank {rank} final model is "
                          f"NOT bit-identical after the cold restart "
                          f"(kill_iter={kill_iter})", flush=True)
                    return 1
            print(f"[soak] round {r}: resumed at v{kill_iter}, final "
                  "model bit-identical", flush=True)
        print(f"[soak] {args.rounds} cold-restart rounds passed",
              flush=True)
        return 0
    finally:
        shutil.rmtree(base, ignore_errors=True)


def _free_port() -> int:
    """A locally-bindable port for the restartable tracker (the restart
    must land on the SAME port, so the ephemeral-bind trick of the
    in-process tracker does not apply)."""
    from rabit_tpu.utils.net import free_port

    return free_port("127.0.0.1")


def _wait_port(port: int, deadline_sec: float = 20.0) -> bool:
    import socket
    import time

    end = time.monotonic() + deadline_sec
    while time.monotonic() < end:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=1.0).close()
            return True
        except OSError:
            time.sleep(0.05)
    return False


def _scrape(port: int, path: str, timeout: float = 3.0) -> str | None:
    """One GET against the tracker's live telemetry plane (--obs-port);
    None while the endpoint is unreachable."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
            return r.read().decode()
    except (urllib.error.URLError, OSError, ValueError):
        return None


def _live_scrape_ok(port: int, tenants: int) -> str | None:
    """The mid-run live-plane check of the --tenants gate: GET /metrics
    and /status must return correctly job-labeled data for EVERY
    tenant, with no op series missing its job label.  Returns None when
    satisfied, else a description of what is (still) wrong — the
    caller polls until the deadline."""
    import json

    metrics = _scrape(port, "/metrics")
    raw = _scrape(port, "/status")
    if metrics is None or raw is None:
        return "GET /metrics or /status unreachable"
    try:
        status = json.loads(raw)
    except ValueError:
        return "/status is not valid JSON"
    for j in range(tenants):
        name = f"tenant{j}"
        if name not in (status.get("jobs") or {}):
            return f"/status has no job {name!r} yet"
        if f'job="{name}"' not in metrics:
            return f"/metrics has no series labeled job={name!r} yet"
    ops = [ln for ln in metrics.splitlines()
           if ln.startswith("rabit_op_") and not ln.startswith("#")]
    if not ops:
        return "no rabit_op_* series streamed yet"
    for ln in ops:
        if 'job="' not in ln:
            return f"op series without a job label: {ln!r}"
    return None


def _committed_version(ckpt_dir) -> int:
    """Newest version any writer's manifest records (driver-side poll:
    how the gate times joins/kills to checkpoint-commit progress)."""
    import glob
    import json

    best = 0
    for m in glob.glob(str(ckpt_dir / "manifest*.json")):
        try:
            with open(m) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue  # mid-rename read: the next poll sees it
        for e in doc.get("entries", []):
            if isinstance(e.get("version"), int):
                best = max(best, e["version"])
    return best


def _journal_state(state_dir) -> dict | None:
    """The tracker's newest journaled control-plane state, read WITHOUT
    CheckpointStore (whose stale-tmp sweep could race the live
    tracker's in-flight persist)."""
    import glob
    import json

    from rabit_tpu.ckpt.store import unpack_blob

    best = None
    for m in glob.glob(str(state_dir / "manifest*.json")):
        try:
            with open(m) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        for e in doc.get("entries", []):
            if isinstance(e.get("version"), int) and (
                    best is None or e["version"] > best["version"]):
                best = e
    if best is None:
        return None
    try:
        dc = unpack_blob((state_dir / best["file"]).read_bytes())
        return json.loads(dc.global_blob.decode())
    except (OSError, ValueError):
        return None


def _read_rescales(out_dir) -> dict[int, tuple[int, int, int]]:
    """epoch -> (version, old_world, new_world) from the workers'
    rescale markers; inconsistent reports for one epoch return -1
    versions so the caller fails loudly."""
    import glob
    import json

    got: dict[int, tuple[int, int, int]] = {}
    for path in glob.glob(str(out_dir / "rescale.*.jsonl")):
        try:
            lines = open(path).read().splitlines()
        except OSError:
            continue
        for line in lines:
            if not line.strip():
                continue
            ev = json.loads(line)
            key = int(ev["epoch"])
            val = (int(ev["version"]), int(ev["old_world"]),
                   int(ev["new_world"]))
            if key in got and got[key] != val:
                got[key] = (-1, -1, -1)
            else:
                got.setdefault(key, val)
    return got


def run_elastic(args, rng: random.Random, round_obs_dir) -> int:
    """The elastic-membership headline gate (--elastic): grow 4->6 via
    late joiners, shrink 6->3 via seeded SIGKILLs (heartbeat
    scale-down), a seeded tracker kill+restart mixed in — then each
    rescale segment re-run as a fresh job at that world size from the
    same committed blob, bit-identical at the next boundary."""
    import json
    import shutil
    import subprocess
    import tempfile
    import time

    from rabit_tpu import ckpt as ckpt_mod
    from rabit_tpu.tracker.launch_local import launch

    worker_path = args.worker_path or str(
        _REPO_ROOT / "tests" / "workers" / "elastic_worker.py")
    base = pathlib.Path(tempfile.mkdtemp(prefix="rabit_elastic_soak_"))

    def fail(r: int, why: str, procs, tracker) -> int:
        print(f"[soak] FAILED (round {r}): {why}", flush=True)
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        if tracker is not None and tracker.poll() is None:
            tracker.kill()
        return 1

    try:
        for r in range(args.rounds):
            rdir = base / f"round{r}"
            ckpt_dir = rdir / "ckpt"
            out = rdir / "out"
            state = rdir / "state"
            for d in (ckpt_dir, out, state):
                d.mkdir(parents=True)
            obs = round_obs_dir(r)
            grow_at = 2 + rng.randrange(3)
            shrink_gap = 4 + rng.randrange(3)
            kill_tracker_after_grow = bool(rng.randrange(2))
            # The commit hold pins the grow boundary near grow_at, so
            # this leaves the 6-world segment shrink_gap commits and
            # the 3-world tail a healthy remainder.
            niter = max(args.niter, grow_at + shrink_gap + 16)
            chaos = gen_chaos(rng, "pyrobust") if args.chaos else ""
            port = _free_port()
            print(f"[soak] round {r}: elastic 4->6->3, grow@v{grow_at}, "
                  f"shrink {shrink_gap} commits later, tracker restart "
                  f"{'after' if kill_tracker_after_grow else 'before'} "
                  f"the grow, niter={niter} chaos={chaos}", flush=True)

            tracker_cmd = [sys.executable, "-m",
                           "rabit_tpu.tracker.tracker", "-n", "4",
                           "--host", "127.0.0.1", "--port", str(port),
                           "--min-workers", "2", "--max-workers", "6",
                           "--state-dir", str(state)]
            if obs:
                tracker_cmd += ["--obs-dir", obs]
            tracker = subprocess.Popen(tracker_cmd)
            procs: dict[str, subprocess.Popen] = {}
            if not _wait_port(port):
                return fail(r, "tracker never came up", procs, tracker)

            env_base = dict(os.environ)
            env_base.update({
                "RABIT_TRACKER_URI": "127.0.0.1",
                "RABIT_TRACKER_PORT": str(port),
                "RABIT_HOLD_FILE": str(out / "hold"),
                "RABIT_ENGINE": "pyrobust",
                "RABIT_ELASTIC": "1",
                # EOF on the heartbeat channel (a SIGKILL) is the
                # scale-down signal; a generous miss budget keeps a
                # CPU-contended beat thread from false verdicts.
                "RABIT_HEARTBEAT_SEC": "0.5",
                "RABIT_HEARTBEAT_MISS": "10",
                "RABIT_CKPT_DIR": str(ckpt_dir),
                "RABIT_CKPT_KEEP": "512",  # every boundary blob kept
                "RABIT_OUT_DIR": str(out),
                "RABIT_ITER_SLEEP": "0.15",
                "RABIT_TIMEOUT_SEC": "20",
                "RABIT_BACKOFF_BASE_MS": "20",
            })
            if obs:
                env_base["RABIT_OBS_DIR"] = obs
            if chaos:
                env_base["RABIT_CHAOS"] = chaos

            def spawn(tid: str) -> subprocess.Popen:
                env = dict(env_base)
                env["RABIT_TASK_ID"] = tid
                env["RABIT_WORLD_SIZE"] = "4"
                return subprocess.Popen(
                    [sys.executable, worker_path, str(args.ndata),
                     str(niter)], env=env)

            for i in range(4):
                procs[str(i)] = spawn(str(i))

            def wait_for(pred, what: str, deadline_sec: float) -> bool:
                end = time.monotonic() + deadline_sec
                while time.monotonic() < end:
                    if pred():
                        return True
                    if any(p.poll() not in (None, 0)
                           for p in procs.values()):
                        return False  # a worker failed; caller reports
                    time.sleep(0.1)
                return False

            def restart_tracker(t):
                t.kill()
                t.wait()
                print(f"[soak] round {r}: tracker killed; restarting on "
                      f"port {port} from {state}", flush=True)
                time.sleep(0.5)
                t2 = subprocess.Popen(tracker_cmd)
                if not _wait_port(port):
                    return None
                return t2

            if not wait_for(
                    lambda: _committed_version(ckpt_dir) >= grow_at,
                    "grow point", 120):
                return fail(r, f"never committed v{grow_at} "
                            "(pre-grow)", procs, tracker)
            if not kill_tracker_after_grow:
                tracker = restart_tracker(tracker)
                if tracker is None:
                    return fail(r, "tracker restart never came up",
                                procs, tracker)
            # Hold the commit boundary while BOTH joiners park, so the
            # grow lands as one 4->6 epoch instead of 4->5->6 (the
            # tracker batches every parked joiner into one pending
            # target; the journal tells us when it reached 6).
            hold = out / "hold"
            hold.touch()
            for tid in ("4", "5"):
                procs[tid] = spawn(tid)
            both_parked = wait_for(
                lambda: (_journal_state(state) or {}).get(
                    "target_world") == 6, "joiners parked", 60)
            hold.unlink()
            if not both_parked:
                return fail(r, "the tracker never saw both joiners "
                            "(target_world != 6)", procs, tracker)
            if not wait_for(
                    lambda: any(v[2] == 6
                                for v in _read_rescales(out).values()),
                    "grow rescale", 120):
                return fail(r, "the 4->6 rescale never landed",
                            procs, tracker)
            if kill_tracker_after_grow:
                tracker = restart_tracker(tracker)
                if tracker is None:
                    return fail(r, "tracker restart never came up",
                                procs, tracker)
            v_grow = next(v[0] for v in _read_rescales(out).values()
                          if v[2] == 6)
            shrink_at = max(grow_at, v_grow) + shrink_gap
            if not wait_for(
                    lambda: _committed_version(ckpt_dir) >= shrink_at,
                    "shrink point", 120):
                return fail(r, f"never committed v{shrink_at} "
                            "(post-grow)", procs, tracker)
            victims = rng.sample(sorted(procs), 3)
            print(f"[soak] round {r}: grow landed at v{v_grow}; killing "
                  f"tasks {victims} at >=v{shrink_at} for the 6->3 "
                  "scale-down", flush=True)
            for tid in victims:
                procs[tid].kill()
            survivors = {t: p for t, p in procs.items()
                         if t not in victims}

            deadline = time.monotonic() + 300
            for tid, p in survivors.items():
                left = max(deadline - time.monotonic(), 1)
                try:
                    code = p.wait(timeout=left)
                except subprocess.TimeoutExpired:
                    return fail(r, f"worker {tid} hung past the deadline",
                                procs, tracker)
                if code != 0:
                    return fail(r, f"worker {tid} exited {code}",
                                procs, tracker)
            try:
                code = tracker.wait(timeout=60)
            except subprocess.TimeoutExpired:
                return fail(r, "tracker never saw the job finish",
                            procs, tracker)
            if code != 0:
                return fail(r, f"tracker exited {code}", procs, tracker)

            # -- verification: world history + segmented bit-identity --
            rescales = sorted(_read_rescales(out).items())
            history = [(v, ow, nw) for _e, (v, ow, nw) in rescales]
            worlds = [(ow, nw) for _v, ow, nw in history]
            if worlds != [(4, 6), (6, 3)] or any(
                    v < 0 for v, _o, _n in history):
                return fail(r, f"unexpected rescale history {history}",
                            procs, tracker)
            v1, v2 = history[0][0], history[1][0]
            finals = sorted(out.glob("final.*"))
            blobs = {f.name: f.read_bytes() for f in finals}
            if len(finals) != 3 or len(set(blobs.values())) != 1:
                return fail(r, f"expected 3 identical finals, got "
                            f"{sorted(blobs)}", procs, tracker)
            elastic_final = finals[0].read_bytes()
            est = ckpt_mod.CheckpointStore(str(ckpt_dir), rank=0)

            print(f"[soak] round {r}: elastic run done (4->6 at v{v1}, "
                  f"6->3 at v{v2}); running fixed-world reference "
                  "segments", flush=True)
            for v0, world, vend in ((0, 4, v1), (v1, 6, v2),
                                    (v2, 3, None)):
                ref = rdir / f"ref_w{world}"
                ref_ckpt = ref / "ckpt"
                ref_out = ref / "out"
                ref_ckpt.mkdir(parents=True)
                if v0:
                    dc = est.load_version(v0)
                    if dc is None:
                        return fail(r, f"boundary blob v{v0} missing "
                                    "from the elastic durable tier",
                                    procs, tracker)
                    ckpt_mod.CheckpointStore(
                        str(ref_ckpt), rank=0, keep=512).persist(
                            v0, world, dc.global_blob)
                env = {"RABIT_ENGINE": "pyrobust",
                       "RABIT_OUT_DIR": str(ref_out),
                       "RABIT_CKPT_DIR": str(ref_ckpt),
                       "RABIT_CKPT_KEEP": "512"}
                if v0:
                    env["RABIT_EXPECT_START_VERSION"] = str(v0)
                if vend:
                    env["RABIT_STOP_ITER"] = str(vend)
                code = launch(world, [sys.executable, worker_path,
                                      str(args.ndata), str(niter)],
                              extra_env=env)
                if code != 0:
                    return fail(r, f"reference segment (world {world}, "
                                f"v{v0}->{vend or niter}) exited {code}",
                                procs, tracker)
                if vend:
                    a = est.load_version(vend)
                    b = ckpt_mod.CheckpointStore(
                        str(ref_ckpt), rank=0).load_version(vend)
                    if a is None or b is None \
                            or a.global_blob != b.global_blob:
                        return fail(
                            r, f"model at v{vend} differs from a fresh "
                            f"world-{world} job resumed at v{v0}",
                            procs, tracker)
                else:
                    ref_final = sorted(ref_out.glob("final.*"))
                    if not ref_final or ref_final[0].read_bytes() \
                            != elastic_final:
                        return fail(
                            r, f"final model differs from a fresh "
                            f"world-{world} job resumed at v{v0}",
                            procs, tracker)
            print(f"[soak] round {r}: rescales bit-identical to fixed-"
                  f"world references at v{v1}/v{v2}/final", flush=True)
        print(f"[soak] {args.rounds} elastic rounds passed", flush=True)
        return 0
    finally:
        shutil.rmtree(base, ignore_errors=True)


def run_transport(args, rng: random.Random, round_obs_dir) -> int:
    """The shm-transport gate (``--transport shm``): a same-host world
    runs the bit-exactness worker over shared-memory rings with
    integrity framing armed and a seeded corruption schedule — a
    guaranteed ``torn`` ring write per rank (permanent damage: must be
    DETECTED and then survived by a live shm→tcp failover mid-job),
    transient ``flip``s on both transports (absorbed by the bounded
    re-read / the robust op retry), and with ``--chaos`` the full wire
    fault mix on top.  The final model of every rank must be
    bit-identical to an uninterrupted loopback-TCP reference run —
    zero silent corruption — and the failover must be visible in the
    ``transport.failover.*`` counters and the merged tracker timeline.
    """
    import json
    import shutil
    import tempfile

    from rabit_tpu.tracker.launch_local import launch

    worker_path = args.worker_path or str(
        _REPO_ROOT / "tests" / "workers" / "cold_restart.py")
    base = pathlib.Path(tempfile.mkdtemp(prefix="rabit_shm_soak_"))
    try:
        ref_dir = base / "ref"
        code = launch(
            args.world, [sys.executable, worker_path,
                         str(args.ndata), str(args.niter)],
            extra_env={"RABIT_ENGINE": "pyrobust",
                       "RABIT_OUT_DIR": str(ref_dir)})
        if code != 0:
            print(f"[soak] FAILED: uninterrupted tcp reference run "
                  f"exited {code}", flush=True)
            return 1
        for r in range(args.rounds):
            rdir = base / f"round{r}"
            obs_dir = round_obs_dir(r) or str(rdir / "obs")
            if args.chaos:
                plan = gen_chaos(rng, "pyrobust")
            else:
                plan = (f"{rng.randrange(1 << 30)}:"
                        f"refuse@connect=0.1*4")
            # The transport-specific teeth: one guaranteed permanent
            # torn ring write per rank (the failover trigger), plus
            # transient read-side flips on shm and framed-TCP links.
            plan += (";torn@shm=1.0*1;flip@shm=0.05*20;"
                     "flip@io=0.01*20;corrupt@io=0.01*10")
            env = {"RABIT_ENGINE": "pyrobust",
                   "RABIT_TRANSPORT": "shm",
                   "RABIT_WIRE_INTEGRITY": "crc32c",
                   "RABIT_OUT_DIR": str(rdir / "out"),
                   "RABIT_CHAOS": plan}
            if "RABIT_TIMEOUT_SEC" not in os.environ:
                env["RABIT_TIMEOUT_SEC"] = "20"
            if "RABIT_BACKOFF_BASE_MS" not in os.environ:
                env["RABIT_BACKOFF_BASE_MS"] = "20"
            print(f"[soak] round {r}: transport=shm world={args.world} "
                  f"chaos={plan}", flush=True)
            code = launch(
                args.world, [sys.executable, worker_path,
                             str(args.ndata), str(args.niter)],
                extra_env=env, obs_dir=obs_dir)
            if code != 0:
                print(f"[soak] FAILED (exit {code}) — reproduce with "
                      f"RABIT_TRANSPORT=shm RABIT_WIRE_INTEGRITY=crc32c "
                      f"RABIT_CHAOS='{plan}'", flush=True)
                return 1
            for rank in range(args.world):
                ref = (ref_dir / f"final.{rank}").read_bytes()
                got = (rdir / "out" / f"final.{rank}").read_bytes()
                if ref != got:
                    print(f"[soak] FAILED: rank {rank} final model is "
                          f"NOT bit-identical to the tcp reference "
                          f"(silent corruption?)", flush=True)
                    return 1
            rep = json.loads(
                (pathlib.Path(obs_dir) / "obs_report.json").read_text())
            agg = rep["aggregate"]
            tl = rep["recovery_timeline"]

            def metric(name: str) -> float:
                return agg.get(name, {}).get("max", 0)

            if metric("transport.links.shm") < 1:
                print("[soak] FAILED: no shm link was ever negotiated "
                      "— the gate ran vacuously on tcp", flush=True)
                return 1
            if metric("chaos.injected.torn") < 1 \
                    or metric("integrity.detected") < 1:
                print("[soak] FAILED: seeded corruption was injected "
                      "but never detected (silent corruption window)",
                      flush=True)
                return 1
            if metric("transport.failover.shm_to_tcp") < 1:
                print("[soak] FAILED: the torn shm link never failed "
                      "over to tcp", flush=True)
                return 1
            if not any(e["name"] == "transport"
                       and e.get("phase") == "failover" for e in tl):
                print("[soak] FAILED: failover happened but is not on "
                      "the tracker timeline", flush=True)
                return 1
            print(f"[soak] round {r}: detected={metric('integrity.detected'):.0f} "
                  f"failovers={metric('transport.failover'):.0f} "
                  f"final model bit-identical to the tcp reference",
                  flush=True)
        print(f"[soak] {args.rounds} shm-transport rounds passed",
              flush=True)
        return 0
    finally:
        shutil.rmtree(base, ignore_errors=True)


def run_adapt(args, rng: random.Random, round_obs_dir) -> int:
    """The closed-loop adaptive gate (--adapt): a world-4 pyrobust job
    with rank 0 deliberately slowed (RABIT_SLOW_RANK) runs under a
    tracker whose AdaptiveController is armed.  The gate fails unless
    the controller (a) converges to a measurably FASTER schedule than
    the static pick — a switch decision whose challenger cost beats
    the pre-switch incumbent, asserted from the merged span data — (b)
    demotes the slowed rank out of hierarchical leader roles, (c)
    leaves the final model bit-exact vs an uninterrupted reference
    run, and (d) round-trips the learned TuningCache: a FRESH
    rabit_sched=auto job must start on the learned schedule.

    With --chaos the wire timing is deliberately poisoned, so (a)
    relaxes to "the controller keeps deciding" (a switch, when it does
    happen, is still evidence-checked and round-tripped); demotion,
    bit-exactness and tracker survival stay mandatory."""
    import json as _json
    import shutil
    import subprocess
    import tempfile
    import time

    from rabit_tpu.sched import TuningCache
    from rabit_tpu.tracker.launch_local import launch

    world = 4
    # Room for the exploration probes; chaos rounds burn iterations on
    # forced recovery, so they get a longer run.
    niter = max(args.niter, 72 if args.chaos else 48)
    # 256KB f32 / 512KB f64 payloads: the regime where BENCH_sched.json
    # measured multi-x schedule gains, so a faster-than-static winner
    # exists for the controller to find.  An explicit --ndata wins.
    ndata = args.ndata if args.ndata_explicit else 65536
    worker_path = args.worker_path or str(
        _REPO_ROOT / "tests" / "workers" / "cold_restart.py")
    base = pathlib.Path(tempfile.mkdtemp(prefix="rabit_adapt_soak_"))
    groups = "0,0,1,1"                 # two host groups: hier applies

    def fail(r, why, procs=(), tracker=None) -> int:
        print(f"[soak] FAILED (round {r}): {why}", flush=True)
        for p in procs:
            if p.poll() is None:
                p.kill()
        if tracker is not None and tracker.poll() is None:
            tracker.kill()
        return 1

    # launch_local's tracker runs IN-PROCESS and reads the group
    # override from its own environment (extra_env only reaches the
    # workers) — the warm-start auto job below needs the same two-group
    # handout or hier could never apply.
    saved_groups = os.environ.get("RABIT_TRACKER_GROUPS")
    os.environ["RABIT_TRACKER_GROUPS"] = groups
    try:
        # Uninterrupted reference (dedicated tracker, no controller, no
        # slow rank): the bits the adaptive run must reproduce — the
        # worker's ops are exact-arithmetic, so schedule switches and
        # pacing sleeps must not change a single bit.
        ref_out = base / "ref"
        code = launch(world, [sys.executable, worker_path, str(ndata),
                              str(niter)],
                      extra_env={"RABIT_ENGINE": "pyrobust",
                                 "RABIT_OUT_DIR": str(ref_out)})
        if code != 0:
            print(f"[soak] FAILED: reference run exited {code}",
                  flush=True)
            return 1
        ref = {i: (ref_out / f"final.{i}").read_bytes()
               for i in range(world)}

        for r in range(args.rounds):
            rdir = base / f"round{r}"
            tune = rdir / "tune"
            obs = round_obs_dir(r)
            chaos = gen_chaos(rng, "pyrobust") if args.chaos else ""
            port = _free_port()
            obs_port = _free_port()
            print(f"[soak] round {r}: adaptive controller armed, world "
                  f"{world}, {niter} iters x {ndata} floats; rank 0 "
                  f"deliberately slowed; live plane on :{obs_port}"
                  + (f" chaos={chaos}" if chaos else ""), flush=True)
            tenv = dict(os.environ)
            tenv.update({
                "RABIT_TRACKER_GROUPS": groups,
                # Fast convergence knobs for the gate: small per-
                # (schedule, bucket) windows, a short demotion streak,
                # and a tight switch margin — the pessimized tree
                # incumbent usually loses by 1.5-2x here, but a noisy
                # shared box occasionally compresses the gap under the
                # production 15% margin and the controller (correctly)
                # settles; 5% keeps the gate about the LOOP, not about
                # one box's run-to-run variance.  Production defaults
                # are deliberately slower/wider (doc/performance.md
                # "Online adaptation").
                "RABIT_ADAPT_MIN_SAMPLES": "4",
                "RABIT_ADAPT_MARGIN": "0.05",
                "RABIT_DEMOTE_CHECKS": "2",
            })
            tracker_cmd = [sys.executable, "-m",
                           "rabit_tpu.tracker.tracker", "-n", str(world),
                           "--host", "127.0.0.1", "--port", str(port),
                           "--obs-port", str(obs_port),
                           "--adapt", "--tune-dir", str(tune)]
            if obs:
                tracker_cmd += ["--obs-dir", obs]
            tracker = subprocess.Popen(tracker_cmd, env=tenv)
            procs: list[subprocess.Popen] = []
            if not _wait_port(port):
                return fail(r, "tracker never came up", procs, tracker)

            out_dir = rdir / "out"
            out_dir.mkdir(parents=True)
            env = dict(os.environ)
            env.update({
                "RABIT_TRACKER_URI": "127.0.0.1",
                "RABIT_TRACKER_PORT": str(port),
                "RABIT_WORLD_SIZE": str(world),
                "RABIT_ENGINE": "pyrobust",
                "RABIT_ADAPT": "1",
                "RABIT_OUT_DIR": str(out_dir),
                "RABIT_CKPT_DIR": str(rdir / "ckpt"),
                "RABIT_OBS": "1",
                "RABIT_OBS_FLUSH_SEC": "0.2",
                "RABIT_HEARTBEAT_SEC": "0.3",
                "RABIT_HEARTBEAT_MISS": "10",
                "RABIT_ITER_SLEEP": "0.05",
                # The injected straggler: rank 0 — a hier GROUP LEADER
                # by default, so its demotion observably moves the
                # leadership (groups 0,0,1,1: leaders [0,2] -> [1,2]).
                "RABIT_SLOW_RANK": "0",
                "RABIT_SLOW_EXTRA": "0.3",
                # Pessimize the static pick DETERMINISTICALLY: with the
                # crossover pushed past the payload sizes, static rides
                # the latency-bound tree at these bandwidth-bound
                # 256-512KB payloads — the regime where BENCH_sched.json
                # measured the ring-family schedules 2-3x faster, so a
                # measurably-better challenger exists for the
                # controller to find regardless of box noise.  (The
                # bit-exact reference runs the DEFAULT static config:
                # the worker's ops are exact arithmetic, so schedule
                # choice never changes the model bits.)
                "RABIT_RING_THRESHOLD_BYTES": "8MB",
            })
            if chaos:
                env["RABIT_CHAOS"] = chaos
                env.setdefault("RABIT_TIMEOUT_SEC", "20")
                env.setdefault("RABIT_BACKOFF_BASE_MS", "20")
            if obs:
                env["RABIT_OBS_DIR"] = obs
            for i in range(world):
                env_i = dict(env)
                env_i["RABIT_TASK_ID"] = str(i)
                procs.append(subprocess.Popen(
                    [sys.executable, worker_path, str(ndata),
                     str(niter)], env=env_i))

            # Watch /status while the job runs: the gate's evidence is
            # the controller's own decision records.
            switch = None          # the final switch decision record
            decided = 0            # ANY controller decisions recorded
            last_ctl: dict = {}    # last controller snapshot (diagnosis)
            demoted_seen = False
            deadline = time.monotonic() + 420
            while any(p.poll() is None for p in procs):
                if time.monotonic() > deadline:
                    return fail(r, "job never finished (controller "
                                "wedged the commit boundaries?)",
                                procs, tracker)
                if tracker.poll() is not None:
                    return fail(r, "tracker died mid-run", procs,
                                tracker)
                raw = _scrape(obs_port, "/status")
                if raw:
                    try:
                        jobs = _json.loads(raw).get("jobs") or {}
                    except ValueError:
                        jobs = {}
                    ctl = (jobs.get("default") or {}).get(
                        "controller") or {}
                    if ctl:
                        last_ctl = ctl
                    if "0" in [str(x) for x in ctl.get("demoted") or []]:
                        demoted_seen = True
                    counters = ctl.get("counters") or {}
                    decided = max(decided,
                                  sum(counters.values()) if counters
                                  else len(ctl.get("decisions") or []))
                    for d in ctl.get("decisions") or []:
                        if d.get("kind") == "switch":
                            switch = d
                time.sleep(0.3)
            for i, p in enumerate(procs):
                if p.wait() != 0:
                    return fail(r, f"rank {i} exited {p.returncode}",
                                procs, tracker)
            try:
                code = tracker.wait(timeout=90)
            except subprocess.TimeoutExpired:
                return fail(r, "tracker never exited after the job",
                            procs, tracker)
            if code != 0:
                return fail(r, f"tracker exited {code}", procs, tracker)

            # (a) converged to a measurably faster schedule: the switch
            # decision's challenger cost (rolling mean over merged
            # spans AFTER convergence) beats the pre-switch incumbent.
            # Under --chaos the wire timing is deliberately poisoned
            # (stalls, resets, recovery rounds), so demanding a
            # specific switch would assert on injected noise: the
            # chaos composition instead requires the control plane to
            # keep DECIDING (probes/settles recorded, nothing wedged)
            # while every structural check below still holds.
            if switch is None and args.chaos:
                if not decided:
                    return fail(r, "under chaos the controller never "
                                "recorded a single decision", procs,
                                tracker)
                print(f"[soak] round {r}: chaos round — controller "
                      f"made {decided} decision(s), no switch verdict "
                      "demanded under injected wire noise", flush=True)
            elif switch is None:
                return fail(r, "the controller never switched the "
                            "schedule (no switch decision on /status); "
                            f"last controller state: {last_ctl}",
                            procs, tracker)
            winner = bucket = None
            if switch is not None:
                evd = switch.get("evidence") or {}
                inc, cha = (evd.get("incumbent_sec"),
                            evd.get("challenger_sec"))
                if not (isinstance(inc, (int, float))
                        and isinstance(cha, (int, float)) and cha < inc):
                    return fail(r, f"switch evidence does not show the "
                                f"challenger beating the incumbent: "
                                f"{evd}", procs, tracker)
                winner, bucket = switch.get("sched"), switch.get("bucket")
                print(f"[soak] round {r}: switch {bucket}B -> {winner} "
                      f"({evd.get('incumbent')} {inc * 1e3:.2f}ms -> "
                      f"{cha * 1e3:.2f}ms over {evd.get('samples')})",
                      flush=True)
            # (b) the slowed rank lost its hier leader role.
            if not demoted_seen:
                return fail(r, "the slowed rank 0 was never demoted "
                            "out of leader roles", procs, tracker)
            from rabit_tpu.sched import topo as _topo
            leaders = _topo.group_leaders([0, 0, 1, 1], {0})
            if 0 in leaders or leaders != [1, 2]:
                return fail(r, f"demoted rank 0 still leads: {leaders}",
                            procs, tracker)
            print(f"[soak] round {r}: rank 0 demoted — hier leaders "
                  f"moved to {leaders}", flush=True)
            # (c) bit-exact vs the uninterrupted reference.
            for i in range(world):
                got = out_dir / f"final.{i}"
                if not got.exists() or got.read_bytes() != ref[i]:
                    return fail(r, f"rank {i} final model is NOT "
                                "bit-exact vs the uninterrupted "
                                "reference", procs, tracker)
            # (d) the TuningCache round-trips: the learned winner is on
            # disk and a FRESH auto job starts on it.  (Chaos rounds
            # without a switch verdict have nothing to round-trip.)
            if winner is None:
                print(f"[soak] round {r}: chaos round survived — "
                      "controller live, model bit-exact", flush=True)
                continue
            cache = TuningCache.load(str(tune))
            if cache is None:
                return fail(r, "no usable TuningCache persisted under "
                            "--tune-dir", procs, tracker)
            if cache.pick("allreduce", int(bucket), world) != winner:
                return fail(r, f"TuningCache does not serve the "
                            f"learned winner {winner} for "
                            f"{bucket}B/world {world}", procs, tracker)
            warm_obs = rdir / "warm_obs"
            code = launch(world, [sys.executable, worker_path,
                                  str(ndata), "3"],
                          extra_env={"RABIT_ENGINE": "pyrobust",
                                     "RABIT_SCHED": "auto",
                                     "RABIT_TUNE_DIR": str(tune),
                                     "RABIT_OUT_DIR": str(rdir / "wout")},
                          obs_dir=str(warm_obs))
            if code != 0:
                return fail(r, f"fresh warm-start job exited {code}",
                            procs, tracker)
            try:
                rep = _json.loads(
                    (warm_obs / "obs_report.json").read_text())
            except (OSError, ValueError) as e:
                return fail(r, f"warm-start obs report unreadable: {e}",
                            procs, tracker)
            picks = (rep.get("aggregate") or {}).get(
                f"sched.pick.{winner}") or {}
            if not picks.get("max", 0) > 0:
                return fail(r, f"the fresh auto job never dispatched "
                            f"the learned schedule {winner} "
                            f"(sched.pick counters: "
                            f"{sorted(k for k in rep.get('aggregate', {}) if k.startswith('sched.pick.'))})",
                            procs, tracker)
            print(f"[soak] round {r}: TuningCache round-trip OK — a "
                  f"fresh rabit_sched=auto job started on {winner}",
                  flush=True)
        print(f"[soak] {args.rounds} adaptive rounds passed", flush=True)
        return 0
    finally:
        if saved_groups is None:
            os.environ.pop("RABIT_TRACKER_GROUPS", None)
        else:
            os.environ["RABIT_TRACKER_GROUPS"] = saved_groups
        shutil.rmtree(base, ignore_errors=True)


def run_serve(args, rng: random.Random, round_obs_dir) -> int:
    """The serving-plane gate (--serve; doc/serving.md).  Each round
    drives one fleet through the four production failure shapes:

    1. **Steady load** at half the fleet's (pinned, via the slow-ms
       capacity seam) capacity: everything served, every reply
       bit-consistent with the committed model version it names —
       including a mid-phase **version rollover** (a new version is
       committed to the store; every rank must atomically swap to it
       via the control loop's agreement broadcast).
    2. **2x-capacity open-loop spike**: the service must SHED with
       typed Overloaded replies (retry-after set) instead of queue-
       collapsing — served-request p99 stays within 5x the steady p99
       (structurally enforced by the deadline budget + shed-before-
       compute), the accounting identity holds exactly, zero wrong
       answers.
    3. **SIGKILL a serving rank mid-traffic**: the availability dip is
       bounded (most requests still served), the fleet recovers via an
       elastic epoch (asserted from the supervisor's event log), and
       every served answer remains bit-consistent.
    4. **Train-while-serving**: a co-tenant training job runs on the
       SAME tracker under live traffic and must finish bit-exact vs a
       solo run on a dedicated tracker (the PR 8 isolation contract,
       now with a serving workload as the neighbor).
    """
    import json as _json
    import shutil
    import signal as _signal
    import subprocess
    import tempfile
    import threading
    import time

    import numpy as np

    from rabit_tpu import ckpt as ckpt_mod
    from rabit_tpu.tools.loadgen import run_load
    from rabit_tpu.tracker.launch_local import launch
    from rabit_tpu.utils.serial import serialize_model

    base = pathlib.Path(tempfile.mkdtemp(prefix="rabit_serve_soak_"))
    worker_path = args.worker_path or str(
        _REPO_ROOT / "tests" / "workers" / "cold_restart.py")
    fleet = 2
    # Low ABSOLUTE rates on purpose: the open-loop generator runs
    # in-process on the same (often 2-core) box as the fleet, and the
    # gate's claims are about RATIOS (0.5x vs 2x capacity, p99 vs
    # steady p99) — rates the client cannot honestly offer would turn
    # "the server sheds" into "the client throttled" and prove
    # nothing.  25 ms/request × batch 4 = 40 req/s per rank.
    slow_ms = 25.0
    batch_max = 4
    max_workers = 3
    capacity = fleet * 1000.0 / slow_ms
    # The spike overloads the fleet's MAXIMUM capacity (autoscale may
    # legitimately grow the world to max_workers before or during the
    # spike — the overload factor must survive that, or the gate would
    # race its own autoscaler).
    capacity_max = max_workers * 1000.0 / slow_ms
    # Small per-rank queue bound: the queue-full shed engages within
    # ~queue_max/excess-rate seconds of sustained overload, and caps a
    # served request's queue wait at queue_max/capacity regardless of
    # how generous its deadline is.
    queue_max = 16
    # One FULL batch's compute time: the irreducible service quantum a
    # served request can pay on top of its deadline (it enters a batch
    # just before its budget dies, then the batch computes).  The p99
    # baseline is floored at TWO quanta: a served spike request costs
    # up to deadline + one batch + scheduling slack, all of which
    # quantize against the batch time — a baseline below two quanta
    # reads a quiet box's idle-path luck, and 5x of luck is not a
    # bound the service's own granularity can honor.
    batch_service = batch_max * slow_ms / 1000.0
    dim = 16

    def _teardown(procs) -> None:
        """SIGTERM first (the supervisor's handler drains its serving
        ranks — a bare kill would orphan them holding the log pipe),
        then kill whatever is left."""
        for p in procs:
            if p is not None and p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + 15
        for p in procs:
            if p is None:
                continue
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()

    def fail(r: int, why: str, procs=(), extra: dict | None = None
             ) -> int:
        print(f"[soak] FAILED (round {r}): {why}", flush=True)
        if extra:
            print(f"[soak]   detail: {_json.dumps(extra, default=str)}",
                  flush=True)
        _teardown(procs)
        return 1

    procs: list = []
    try:
        for r in range(args.rounds):
            rdir = base / f"round{r}"
            model_dir = rdir / "model"
            eps_dir = rdir / "eps"
            state_json = rdir / "supervisor.json"
            rdir.mkdir(parents=True)
            rng_w = np.random.default_rng(args.seed * 7919 + r)
            store = ckpt_mod.CheckpointStore(str(model_dir), rank=0)
            w1 = rng_w.standard_normal(dim)
            store.persist(1, fleet, serialize_model({"w": w1}))

            port = _free_port()
            obs_port = _free_port()
            tracker_cmd = [sys.executable, "-m",
                           "rabit_tpu.tracker.tracker", "-n", str(fleet),
                           "--host", "127.0.0.1", "--port", str(port),
                           "--min-workers", "1",
                           "--max-workers", str(max_workers),
                           "--max-jobs", "4", "--obs-port",
                           str(obs_port)]
            obs = round_obs_dir(r)
            if obs:
                tracker_cmd += ["--obs-dir", obs]
            tracker = subprocess.Popen(tracker_cmd)
            procs = [tracker]
            if not _wait_port(port):
                return fail(r, "tracker never came up", procs)

            sup_cmd = [sys.executable, "-m", "rabit_tpu.tools.serve",
                       "--tracker", f"127.0.0.1:{port}",
                       "--model-dir", str(model_dir),
                       "--endpoints-dir", str(eps_dir),
                       "--workers", str(fleet),
                       "--min-workers", "1",
                       "--max-workers", str(max_workers),
                       "--slow-ms", str(slow_ms),
                       "--sync-sec", "0.5", "--tick-sec", "0.5",
                       "--batch-max", str(batch_max),
                       "--queue-max", str(queue_max),
                       "--state-json", str(state_json),
                       "--max-restarts", "2",
                       "--stop-file", str(rdir / "STOP")]
            sup_env = dict(os.environ)
            if obs:
                sup_env["RABIT_OBS_DIR"] = obs
            sup = subprocess.Popen(sup_cmd, env=sup_env)
            procs.append(sup)
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                try:
                    if len([p for p in eps_dir.iterdir()
                            if p.suffix == ".json"]) >= fleet:
                        break
                except OSError:
                    pass
                if sup.poll() is not None:
                    return fail(r, f"supervisor exited "
                                f"{sup.returncode} during startup",
                                procs)
                time.sleep(0.3)
            else:
                return fail(r, "serving fleet never published its "
                            "endpoints", procs)
            print(f"[soak] round {r}: fleet of {fleet} up "
                  f"(capacity {capacity:.0f} req/s; live plane on "
                  f":{obs_port})", flush=True)

            # -- phase 1: steady load at 0.5x capacity ----------------
            steady = run_load(str(eps_dir), None,
                              rate=capacity * 0.5, duration=6,
                              deadline_ms=2000, dim=dim,
                              seed=args.seed, verify_dir=str(model_dir))
            if not steady["accounting_ok"]:
                return fail(r, "steady-phase accounting mismatch",
                            procs, steady)
            if steady["wrong"]:
                return fail(r, f"{steady['wrong']} bitwise-WRONG "
                            "answers under steady load", procs, steady)
            if steady["ok"] < 0.9 * steady["offered"]:
                return fail(r, "steady load not served "
                            f"({steady['ok']}/{steady['offered']} ok)",
                            procs, steady)
            p99_steady = max(steady["latency_ok_sec"]["p99"],
                             2 * batch_service)
            print(f"[soak] round {r}: steady OK "
                  f"{steady['ok']}/{steady['offered']} served, "
                  f"p99 {p99_steady * 1e3:.1f}ms", flush=True)

            # -- version rollover under live reading ------------------
            w2 = rng_w.standard_normal(dim)
            store.persist(2, fleet, serialize_model({"w": w2}))
            roll = run_load(str(eps_dir), None, rate=40, duration=4,
                            deadline_ms=2000, dim=dim,
                            seed=args.seed + 1,
                            verify_dir=str(model_dir))
            if roll["wrong"]:
                return fail(r, "wrong answers during the version "
                            "rollover (old/new weights crossed a "
                            "version tag)", procs, roll)
            v2 = run_load(str(eps_dir), None, rate=20, duration=2,
                          deadline_ms=2000, dim=dim,
                          seed=args.seed + 2,
                          verify_dir=str(model_dir))
            if v2["wrong"] or not v2["statuses"].get("ok"):
                return fail(r, "post-rollover traffic not served "
                            "cleanly", procs, v2)
            # The spike's p99 baseline must be CONTEMPORANEOUS: phases
            # run minutes apart and a shared box's background load
            # drifts — fold the rollover-check loads (the closest in
            # time to the spike) into the steady baseline.
            p99_steady = max(p99_steady,
                             roll["latency_ok_sec"]["p99"],
                             v2["latency_ok_sec"]["p99"])
            print(f"[soak] round {r}: version rollover v1 -> v2 served "
                  "bit-consistently (every reply verified against the "
                  "version it named)", flush=True)

            # -- phase 2: 2x-capacity overload spike ------------------
            # The deadline budget is what BOUNDS served latency under
            # overload (shed-before-compute): a served request pays at
            # most its deadline in queue plus one full batch of
            # compute plus scheduling slack.  With the baseline
            # floored at 2*batch_service, a 2x-baseline deadline
            # leaves the 5x acceptance bound structural headroom of
            # 3*p99_base - batch_service (>= 5 batch quanta of slack).
            spike_deadline_ms = max(int(2.0 * p99_steady * 1000), 80)
            # outstanding=64: enough in-flight slots to OFFER 2x the
            # max fleet capacity (240/s x ~0.25s roundtrips), small
            # enough that the client's sender threads don't starve the
            # co-located servers of the 2-core box's GIL time — a
            # starved server's latency lives in the kernel socket
            # buffers where no admission gate can see it, which is box
            # contention, not the queue collapse this phase tests for.
            spike = run_load(str(eps_dir), None,
                             rate=capacity_max * 2, duration=6,
                             deadline_ms=spike_deadline_ms, dim=dim,
                             seed=args.seed + 3, outstanding=64,
                             verify_dir=str(model_dir))
            if not spike["accounting_ok"]:
                return fail(r, "spike accounting mismatch: served + "
                            "shed + timeout + error != offered",
                            procs, spike)
            if spike["wrong"]:
                return fail(r, f"{spike['wrong']} bitwise-WRONG "
                            "answers under overload", procs, spike)
            if not spike["shed"]:
                return fail(r, "a 2x-capacity spike produced ZERO "
                            "typed shed replies — where did the "
                            "excess load go?", procs, spike)
            if not spike["retry_after_seen"]:
                return fail(r, "shed replies carried no retry-after "
                            "hint", procs, spike)
            p99_spike = spike["latency_ok_sec"]["p99"]
            if p99_spike > 5 * p99_steady:
                return fail(r, f"served-request p99 under the spike "
                            f"({p99_spike * 1e3:.1f}ms) exceeds 5x "
                            f"the steady p99 ({p99_steady * 1e3:.1f}"
                            "ms) — queue collapse", procs, spike)
            print(f"[soak] round {r}: spike OK — offered "
                  f"{spike['offered']}, served {spike['ok']}, shed "
                  f"{spike['shed']} (typed, retry-after set), served "
                  f"p99 {p99_spike * 1e3:.1f}ms <= 5x steady",
                  flush=True)

            # -- autoscale: the spike's queue depth must GROW the
            # fleet — a supervisor scale_up spawn whose joiner then
            # PUBLISHES its endpoint (publication happens after
            # rabit init, i.e. after the elastic epoch admitted it
            # into the serving world, so this asserts the whole
            # scale-up choreography end to end).
            deadline = time.monotonic() + 60
            scaled_up = False
            while time.monotonic() < deadline and not scaled_up:
                try:
                    evs = _json.loads(
                        state_json.read_text()).get("events", [])
                except (OSError, ValueError):
                    evs = []
                spawned = {e.get("task") for e in evs
                           if e.get("kind") == "spawn"
                           and str(e.get("why", "")).startswith(
                               "queue depth")}
                published = {e.get("task") for e in evs
                             if e.get("kind") == "published"}
                scaled_up = bool(spawned & published)
                if not scaled_up:
                    time.sleep(0.5)
            if not scaled_up:
                return fail(r, "the 2x spike never produced a "
                            "COMPLETED scale-up (no queue-depth-"
                            "spawned joiner published an endpoint — "
                            "did the elastic epoch admit it?)", procs)
            print(f"[soak] round {r}: autoscale landed — a queue-"
                  f"depth joiner joined the serving world "
                  f"({sorted(spawned & published)})", flush=True)

            # -- phase 3: SIGKILL a serving rank mid-traffic ----------
            def _serve_epoch() -> int | None:
                raw = _scrape(obs_port, "/status", timeout=5)
                if not raw:
                    return None
                try:
                    jobs = _json.loads(raw).get("jobs") or {}
                    return int((jobs.get("serve") or {}).get("epoch"))
                except (ValueError, TypeError):
                    return None

            # Snapshot BEFORE the kill: the autoscale phase already
            # moved the epoch, so "epoch is truthy afterwards" would
            # be vacuous — the assertion is that the kill itself
            # moves it again (the scale-down rescale).
            epoch_before = _serve_epoch() or 0
            victims = sorted(eps_dir.glob("*.json"))
            if not victims:
                return fail(r, "no endpoint left to kill", procs)
            victim_doc = _json.loads(victims[0].read_text())
            kill_result: dict = {}

            def _kill_later():
                time.sleep(2.0)
                try:
                    os.kill(int(victim_doc["pid"]), _signal.SIGKILL)
                    kill_result["killed"] = victim_doc["task_id"]
                except OSError as e:
                    kill_result["error"] = str(e)
            killer = threading.Thread(target=_kill_later, daemon=True)
            killer.start()
            under_kill = run_load(str(eps_dir), None,
                                  rate=capacity * 0.4, duration=8,
                                  deadline_ms=2000, dim=dim,
                                  seed=args.seed + 4,
                                  verify_dir=str(model_dir))
            killer.join()
            if "error" in kill_result:
                return fail(r, f"could not SIGKILL the victim: "
                            f"{kill_result['error']}", procs)
            if under_kill["wrong"]:
                return fail(r, "wrong answers while a rank was "
                            "SIGKILLed — replies must stay bit-"
                            "consistent with their version", procs,
                            under_kill)
            if not under_kill["accounting_ok"]:
                return fail(r, "kill-phase accounting mismatch",
                            procs, under_kill)
            if under_kill["ok"] < 0.6 * under_kill["offered"]:
                return fail(r, "availability dip unbounded: only "
                            f"{under_kill['ok']}/"
                            f"{under_kill['offered']} served through "
                            "the rank kill", procs, under_kill)
            # The fleet must have absorbed the death via an elastic
            # epoch: the supervisor logged the death, and the serve
            # job's world moved (or a replacement joined) on /status.
            deadline = time.monotonic() + 20
            died_seen = False
            while time.monotonic() < deadline and not died_seen:
                try:
                    sup_state = _json.loads(state_json.read_text())
                    died_seen = any(e["kind"] in ("died", "left")
                                    and e.get("task")
                                    == kill_result.get("killed")
                                    for e in sup_state.get("events", []))
                except (OSError, ValueError):
                    pass
                time.sleep(0.3)
            if not died_seen:
                return fail(r, "the supervisor never noticed the "
                            "SIGKILLed rank", procs)
            # The kill must move the membership epoch PAST its
            # pre-kill value (heartbeat EOF → scale-down rescale);
            # poll briefly — the boundary lands within ~sync_sec.
            deadline = time.monotonic() + 30
            epoch_after = epoch_before
            while time.monotonic() < deadline:
                e = _serve_epoch()
                if e is not None:
                    epoch_after = e
                    if e > epoch_before:
                        break
                time.sleep(0.5)
            if epoch_after <= epoch_before:
                return fail(r, f"the serve job's membership epoch "
                            f"never moved after the rank kill "
                            f"({epoch_before} -> {epoch_after}; no "
                            "elastic recovery)", procs)
            post = run_load(str(eps_dir), None, rate=30, duration=3,
                            deadline_ms=2000, dim=dim,
                            seed=args.seed + 5,
                            verify_dir=str(model_dir))
            if post["wrong"] or post["ok"] < 0.8 * post["offered"]:
                return fail(r, "service did not recover cleanly after "
                            "the rank kill", procs, post)
            print(f"[soak] round {r}: rank "
                  f"{kill_result.get('killed')} SIGKILLed mid-traffic "
                  f"— {under_kill['ok']}/{under_kill['offered']} "
                  f"served through the dip, elastic epoch "
                  f"{epoch_after} absorbed it, recovery clean",
                  flush=True)

            # -- phase 4: train-while-serving (co-tenant) -------------
            ndata, niter = 4000, 6
            solo_out = rdir / "solo"
            code = launch(2, [sys.executable, worker_path, str(ndata),
                              str(niter)],
                          extra_env={"RABIT_ENGINE": "pyrobust",
                                     "RABIT_OUT_DIR": str(solo_out)})
            if code != 0:
                return fail(r, f"solo trainer reference exited {code}",
                            procs)
            train_out = rdir / "train"
            tenv = dict(os.environ)
            tenv.update({
                "RABIT_TRACKER_URI": "127.0.0.1",
                "RABIT_TRACKER_PORT": str(port),
                "RABIT_WORLD_SIZE": "2",
                "RABIT_ENGINE": "pyrobust",
                "RABIT_JOB_ID": "train",
                "RABIT_OUT_DIR": str(train_out),
            })
            trainers = []
            for i in range(2):
                env_i = dict(tenv)
                env_i["RABIT_TASK_ID"] = f"t{i}"
                trainers.append(subprocess.Popen(
                    [sys.executable, worker_path, str(ndata),
                     str(niter)], env=env_i))
            procs += trainers
            co_load = run_load(str(eps_dir), None, rate=40,
                               duration=6, deadline_ms=2000, dim=dim,
                               seed=args.seed + 6,
                               verify_dir=str(model_dir))
            for i, t in enumerate(trainers):
                try:
                    if t.wait(timeout=120) != 0:
                        return fail(r, f"co-tenant trainer {i} exited "
                                    f"{t.returncode}", procs)
                except subprocess.TimeoutExpired:
                    return fail(r, f"co-tenant trainer {i} hung",
                                procs)
            if co_load["wrong"] or not co_load["statuses"].get("ok"):
                return fail(r, "serving degraded wrongly under the "
                            "co-tenant trainer", procs, co_load)
            for i in range(2):
                ref = (solo_out / f"final.{i}").read_bytes()
                got_p = train_out / f"final.{i}"
                if not got_p.exists() or got_p.read_bytes() != ref:
                    return fail(r, f"train-while-serving rank {i} "
                                "final model NOT bit-exact vs the "
                                "solo reference", procs)
            print(f"[soak] round {r}: train-while-serving co-tenant "
                  "bit-exact vs solo; serving stayed healthy "
                  f"({co_load['ok']}/{co_load['offered']} ok)",
                  flush=True)

            # -- teardown ---------------------------------------------
            (rdir / "STOP").touch()
            try:
                if sup.wait(timeout=30) != 0:
                    return fail(r, f"supervisor exited "
                                f"{sup.returncode}", procs)
            except subprocess.TimeoutExpired:
                return fail(r, "supervisor never exited on the stop "
                            "file", procs)
            tracker.kill()
            tracker.wait()
        print(f"[soak] {args.rounds} serving rounds passed", flush=True)
        return 0
    finally:
        _teardown(procs)  # exception paths must not orphan the fleet
        shutil.rmtree(base, ignore_errors=True)


def run_qos(args, rng: random.Random, round_obs_dir) -> int:
    """The tail-tolerance gate (--qos; doc/serving.md "QoS classes",
    "Hedged retries", "Straggler-aware routing").  Each round drives
    one 3-rank fleet — one rank a deliberate 4x straggler via the
    supervisor's per-task slow seam — through five phases:

    1. **Straggler-aware routing**: under routed load (client EWMA +
       the tracker's serve-fold ``rabit_straggler_score``), the slow
       rank's traffic share must fall to <= 70% of its fair share.
    2. **QoS overload**: a 2x-capacity mixed-class spike against
       per-class budgets — gold keeps being served while bronze sheds,
       and the accounting identity closes exactly PER CLASS.
    3. **Hedge storm** (``run_storm``): every idempotency key fired 4x
       back-to-back at one rank — exactly one OK serve per key, every
       suppressed copy a typed Duplicate, cached answers bit-exact.
    4. **Hedged tail run**: aggressive hedging (p50 trigger) across the
       fleet — hedges fire, zero per-endpoint double serves, books
       balanced, zero wrong answers.
    5. **Chaos on the serving wire**: seeded resets/stalls at the
       ``serve_req``/``serve_reply`` sites — every injection paired
       with a client-side detection, books still exact under retries
       (idempotency keys make the retry safe).

    Every phase uses a DISTINCT seed: idempotency keys derive from the
    seed, so reusing one against the same fleet would re-answer phase
    N+1 from phase N's dedup window (correct server behavior, wrong
    test)."""
    import json as _json
    import shutil
    import subprocess
    import tempfile
    import time

    import numpy as np

    from rabit_tpu import ckpt as ckpt_mod
    from rabit_tpu.tools.loadgen import run_load, run_storm
    from rabit_tpu.utils.serial import serialize_model

    base = pathlib.Path(tempfile.mkdtemp(prefix="rabit_qos_soak_"))
    fleet = 3
    # Pinned capacity (the --serve gate's reasoning): 25 ms/request x
    # batch 4 = 40 req/s per healthy rank; the straggler runs 4x
    # slower (100 ms/request = 10 req/s).
    slow_ms = 25.0
    straggler_ms = 100.0
    batch_max = 4
    queue_max = 16
    capacity = (fleet - 1) * 1000.0 / slow_ms + 1000.0 / straggler_ms
    dim = 16

    def _teardown(procs) -> None:
        for p in procs:
            if p is not None and p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + 15
        for p in procs:
            if p is None:
                continue
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()

    def fail(r: int, why: str, procs=(), extra: dict | None = None
             ) -> int:
        print(f"[soak] FAILED (round {r}): {why}", flush=True)
        if extra:
            print(f"[soak]   detail: {_json.dumps(extra, default=str)}",
                  flush=True)
        _teardown(procs)
        return 1

    procs: list = []
    try:
        for r in range(args.rounds):
            rdir = base / f"round{r}"
            model_dir = rdir / "model"
            eps_dir = rdir / "eps"
            state_json = rdir / "supervisor.json"
            rdir.mkdir(parents=True)
            rng_w = np.random.default_rng(args.seed * 6007 + r)
            store = ckpt_mod.CheckpointStore(str(model_dir), rank=0)
            store.persist(1, fleet,
                          serialize_model({"w":
                                           rng_w.standard_normal(dim)}))
            # Distinct per-phase seeds (idempotency keys derive from
            # them; see the docstring).
            sbase = args.seed * 1000 + r * 100

            port = _free_port()
            obs_port = _free_port()
            tracker_cmd = [sys.executable, "-m",
                           "rabit_tpu.tracker.tracker", "-n", str(fleet),
                           "--host", "127.0.0.1", "--port", str(port),
                           "--min-workers", "2",
                           "--max-workers", str(fleet),
                           "--max-jobs", "2",
                           "--obs-port", str(obs_port)]
            obs = round_obs_dir(r)
            if obs:
                tracker_cmd += ["--obs-dir", obs]
            tracker = subprocess.Popen(tracker_cmd)
            procs = [tracker]
            if not _wait_port(port):
                return fail(r, "tracker never came up", procs)

            # s001 is the straggler: spawned first, slowed via the
            # per-task seam.  Tight bronze budget so the overload
            # phase has a class to shed first; gold+silver together
            # still fit the queue.
            sup_cmd = [sys.executable, "-m", "rabit_tpu.tools.serve",
                       "--tracker", f"127.0.0.1:{port}",
                       "--model-dir", str(model_dir),
                       "--endpoints-dir", str(eps_dir),
                       "--workers", str(fleet),
                       "--min-workers", "2",
                       "--max-workers", str(fleet),
                       "--slow-ms", str(slow_ms),
                       "--slow-task-ms", f"s001:{straggler_ms:g}",
                       "--qos-budgets", "gold:10,silver:8,bronze:2",
                       "--sync-sec", "0.5", "--tick-sec", "0.5",
                       "--batch-max", str(batch_max),
                       "--queue-max", str(queue_max),
                       "--state-json", str(state_json),
                       "--max-restarts", "2",
                       "--stop-file", str(rdir / "STOP")]
            sup_env = dict(os.environ)
            if obs:
                sup_env["RABIT_OBS_DIR"] = obs
            sup = subprocess.Popen(sup_cmd, env=sup_env)
            procs.append(sup)
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                try:
                    if len([p for p in eps_dir.iterdir()
                            if p.suffix == ".json"]) >= fleet:
                        break
                except OSError:
                    pass
                if sup.poll() is not None:
                    return fail(r, f"supervisor exited "
                                f"{sup.returncode} during startup",
                                procs)
                time.sleep(0.3)
            else:
                return fail(r, "serving fleet never published its "
                            "endpoints", procs)
            slow_doc = _json.loads(
                (eps_dir / "s001.json").read_text())
            slow_ep = f"{slow_doc['host']}:{slow_doc['port']}"
            fast_doc = _json.loads(
                (eps_dir / "s002.json").read_text())
            fast_ep = f"{fast_doc['host']}:{fast_doc['port']}"
            metrics_url = f"http://127.0.0.1:{obs_port}/metrics"
            print(f"[soak] round {r}: fleet of {fleet} up, straggler "
                  f"s001 at {straggler_ms:g}ms ({slow_ep}); capacity "
                  f"~{capacity:.0f} req/s", flush=True)

            # -- phase 1: straggler-aware routing ---------------------
            routed = run_load(str(eps_dir), None, rate=40, duration=10,
                              deadline_ms=2000, dim=dim,
                              seed=sbase + 1,
                              verify_dir=str(model_dir),
                              route=True, metrics_url=metrics_url)
            if not routed["accounting_ok"] or routed["wrong"]:
                return fail(r, "routing-phase books broken",
                            procs, routed)
            fair = routed["offered"] / fleet
            slow_sent = routed["per_endpoint"].get(
                slow_ep, {}).get("sent", 0)
            if slow_sent > 0.7 * fair:
                return fail(r, f"router left {slow_sent} requests on "
                            f"the straggler (fair share {fair:.0f}; "
                            "wanted <= 70% of fair)", procs, routed)
            if not routed["router"] or not routed["router"]["convicted"]:
                return fail(r, "the straggler was never convicted by "
                            "the router hysteresis", procs, routed)
            print(f"[soak] round {r}: routing OK — straggler got "
                  f"{slow_sent}/{routed['offered']} "
                  f"(fair {fair:.0f}), convicted="
                  f"{routed['router']['convicted']}", flush=True)

            # -- phase 2: QoS-classed overload ------------------------
            spike = run_load(str(eps_dir), None, rate=capacity * 2,
                             duration=6, deadline_ms=1000, dim=dim,
                             seed=sbase + 2, outstanding=64,
                             verify_dir=str(model_dir),
                             qos_mix="gold:0.25,silver:0.35,bronze:0.4",
                             route=True, metrics_url=metrics_url)
            if spike["wrong"]:
                return fail(r, f"{spike['wrong']} bitwise-WRONG "
                            "answers under the QoS spike", procs, spike)
            if not spike["accounting_ok"]:
                return fail(r, "QoS-spike aggregate accounting "
                            "mismatch", procs, spike)
            pc = spike["per_class"]
            for name, cls in pc.items():
                if cls["offered"] and not cls["accounting_ok"]:
                    return fail(r, f"per-class accounting identity "
                                f"broken for {name}", procs, spike)
            gold, bronze = pc["gold"], pc["bronze"]
            gold_frac = gold["ok"] / max(gold["offered"], 1)
            bronze_frac = bronze["ok"] / max(bronze["offered"], 1)
            if bronze["shed"] == 0:
                return fail(r, "a 2x mixed-class spike shed ZERO "
                            "bronze — budgets not engaging",
                            procs, spike)
            if gold_frac < 0.6:
                return fail(r, f"gold served fraction {gold_frac:.2f} "
                            "under the spike — the gold SLO did not "
                            "hold", procs, spike)
            if gold_frac < bronze_frac + 0.15:
                return fail(r, f"gold ({gold_frac:.2f}) not "
                            f"meaningfully better than bronze "
                            f"({bronze_frac:.2f}) under overload — "
                            "classes are not classes", procs, spike)
            page = _scrape(obs_port, "/metrics", timeout=5) or ""
            if "rabit_serve_qos_requests_total{" not in page:
                return fail(r, "tracker exposition never rendered the "
                            "per-class serving series", procs)
            print(f"[soak] round {r}: QoS spike OK — gold "
                  f"{gold_frac:.0%} served, bronze {bronze_frac:.0%} "
                  f"served / {bronze['shed']} shed, per-class books "
                  "exact", flush=True)

            # -- phase 3: forced hedge storm (one rank) ---------------
            storm = run_storm(fast_ep, keys=24, copies=4, dim=dim,
                              seed=sbase + 3,
                              verify_dir=str(model_dir))
            if storm["double_served"]:
                return fail(r, f"{storm['double_served']} keys served "
                            "twice by ONE rank under the hedge storm "
                            "— dedup broken", procs, storm)
            if storm["unserved_keys"]:
                return fail(r, "hedge storm lost keys entirely",
                            procs, storm)
            if not storm["duplicates"]:
                return fail(r, "hedge storm produced zero typed "
                            "Duplicate replies", procs, storm)
            if storm["wrong"]:
                return fail(r, "cached duplicate answers not bit-exact",
                            procs, storm)
            print(f"[soak] round {r}: hedge storm OK — "
                  f"{storm['ok_serves']}/{storm['keys']} keys served "
                  f"exactly once, {storm['duplicates']} duplicates "
                  "typed, cached answers bit-exact", flush=True)

            # -- phase 4: hedged tail run across the fleet ------------
            hedged = run_load(str(eps_dir), None, rate=40, duration=6,
                              deadline_ms=2000, dim=dim,
                              seed=sbase + 4,
                              verify_dir=str(model_dir),
                              hedge_after_pct=50.0, idem=True,
                              route=True, metrics_url=metrics_url)
            if not hedged["hedges"]["fired"]:
                return fail(r, "aggressive hedging fired zero hedges",
                            procs, hedged)
            if hedged["double_served"]:
                return fail(r, f"{hedged['double_served']} per-"
                            "endpoint double serves under hedging",
                            procs, hedged)
            if not hedged["accounting_ok"] or hedged["wrong"]:
                return fail(r, "hedged-phase books broken",
                            procs, hedged)
            print(f"[soak] round {r}: hedged run OK — "
                  f"{hedged['hedges']['fired']} hedges, "
                  f"{hedged['hedges']['wins']} wins, "
                  f"{hedged['hedges']['cross_rank_serves']} cross-rank "
                  "serves, zero double serves", flush=True)

            # -- phase 5: chaos on the serving wire -------------------
            chaos_spec = (f"{args.seed + 7 + r}:"
                          "reset@serve_req=0.04;reset@serve_reply=0.03;"
                          "stall@serve_reply=0.04;stallms=60")
            chaotic = run_load(str(eps_dir), None, rate=40, duration=6,
                               deadline_ms=2000, dim=dim,
                               seed=sbase + 5,
                               verify_dir=str(model_dir),
                               idem=True, chaos_spec=chaos_spec)
            books = chaotic["chaos"] or {}
            injected = books.get("injected") or {}
            detected = books.get("detected") or {}
            if not injected:
                return fail(r, "the seeded serving-wire chaos plan "
                            "never fired", procs, chaotic)
            if injected != detected:
                return fail(r, "chaos injected/detected books diverge "
                            f"({injected} vs {detected})",
                            procs, chaotic)
            if not chaotic["accounting_ok"] or chaotic["wrong"]:
                return fail(r, "chaos-phase books broken",
                            procs, chaotic)
            print(f"[soak] round {r}: serving-wire chaos OK — "
                  f"{sum(injected.values())} injections, every one "
                  "detected, books exact, zero wrong", flush=True)

            # -- teardown ---------------------------------------------
            (rdir / "STOP").touch()
            try:
                if sup.wait(timeout=30) != 0:
                    return fail(r, f"supervisor exited "
                                f"{sup.returncode}", procs)
            except subprocess.TimeoutExpired:
                return fail(r, "supervisor never exited on the stop "
                            "file", procs)
            tracker.kill()
            tracker.wait()
        print(f"[soak] {args.rounds} QoS rounds passed", flush=True)
        return 0
    finally:
        _teardown(procs)
        shutil.rmtree(base, ignore_errors=True)


def run_tenants(args, rng: random.Random, round_obs_dir) -> int:
    """The multi-tenant isolation gate (--tenants N): N jobs share one
    tracker process; tenant A's whole worker set is SIGKILLed
    mid-training and the gate fails on ANY cross-tenant interference —
    tenant B erroring/hanging, a final model that is not bit-exact
    against a solo run on a dedicated tracker, or the shared tracker
    process dying."""
    import shutil
    import subprocess
    import tempfile
    import time

    from rabit_tpu.tracker.launch_local import launch

    world = 2                     # per-tenant world (N*world workers)
    worker_path = args.worker_path or str(
        _REPO_ROOT / "tests" / "workers" / "cold_restart.py")
    base = pathlib.Path(tempfile.mkdtemp(prefix="rabit_tenant_soak_"))

    def fail(r: int, why: str, procs, tracker) -> int:
        print(f"[soak] FAILED (round {r}): {why}", flush=True)
        for p in procs:
            if p.poll() is None:
                p.kill()
        if tracker is not None and tracker.poll() is None:
            tracker.kill()
        return 1

    try:
        # Solo reference: tenant B's exact workload on a dedicated
        # tracker — the bits tenant B must reproduce next to a dying
        # co-tenant.
        ref_out = base / "ref"
        code = launch(world, [sys.executable, worker_path,
                              str(args.ndata), str(args.niter)],
                      extra_env={"RABIT_ENGINE": "pyrobust",
                                 "RABIT_OUT_DIR": str(ref_out)})
        if code != 0:
            print(f"[soak] FAILED: solo reference run exited {code}",
                  flush=True)
            return 1
        ref = {i: (ref_out / f"final.{i}").read_bytes()
               for i in range(world)}

        for r in range(args.rounds):
            rdir = base / f"round{r}"
            state = rdir / "state"
            state.mkdir(parents=True)
            obs = round_obs_dir(r)
            kill_at = 1 + rng.randrange(max(args.niter - 2, 1))
            chaos = {f"tenant{j}": gen_chaos(rng, "pyrobust")
                     for j in range(args.tenants)} if args.chaos else {}
            port = _free_port()
            obs_port = _free_port()
            print(f"[soak] round {r}: {args.tenants} tenants x world "
                  f"{world} on one tracker; massacre tenant0 at "
                  f">=v{kill_at}; live plane on :{obs_port} "
                  "(tenant1 rank 1 deliberately slowed)"
                  + (f" chaos={sorted(chaos.values())}" if chaos else "")
                  + (" elastic" if args.elastic else ""), flush=True)

            tracker_cmd = [sys.executable, "-m",
                           "rabit_tpu.tracker.tracker", "-n", str(world),
                           "--host", "127.0.0.1", "--port", str(port),
                           "--state-dir", str(state),
                           "--max-jobs", str(args.tenants),
                           "--job-gc-sec", "4",
                           "--obs-port", str(obs_port)]
            if args.elastic:
                tracker_cmd += ["--min-workers", "1",
                                "--max-workers", str(world + 2)]
            if args.adapt:
                # Composition: the adaptive controller runs on the
                # SHARED tracker — adaptation on one tenant must never
                # leak into a co-tenant (the bit-exact check below is
                # the judge).
                tracker_cmd += ["--adapt", "--tune-dir",
                                str(rdir / "tune")]
            if obs:
                tracker_cmd += ["--obs-dir", obs]
            tracker = subprocess.Popen(tracker_cmd)
            procs: list[subprocess.Popen] = []
            by_tenant: dict[str, list[subprocess.Popen]] = {}
            if not _wait_port(port):
                return fail(r, "tracker never came up", procs, tracker)

            for j in range(args.tenants):
                name = f"tenant{j}"
                tdir = rdir / name
                (tdir / "out").mkdir(parents=True)
                env = dict(os.environ)
                env.update({
                    "RABIT_TRACKER_URI": "127.0.0.1",
                    "RABIT_TRACKER_PORT": str(port),
                    "RABIT_JOB_ID": name,
                    "RABIT_WORLD_SIZE": str(world),
                    "RABIT_ENGINE": "pyrobust",
                    "RABIT_OUT_DIR": str(tdir / "out"),
                    "RABIT_CKPT_DIR": str(tdir / "ckpt"),
                    # A SIGKILL'd tenant must EOF its channel for the
                    # orphan GC's evidence; the generous miss budget
                    # avoids false verdicts on a loaded CI box.
                    "RABIT_HEARTBEAT_SEC": "0.3",
                    "RABIT_HEARTBEAT_MISS": "10",
                    # Pacing so the massacre lands mid-training.
                    "RABIT_ITER_SLEEP": "0.2",
                    # Live telemetry plane: every tenant streams delta
                    # frames + collective spans so the mid-run scrape
                    # has per-job labeled data to verify.
                    "RABIT_OBS": "1",
                    "RABIT_OBS_FLUSH_SEC": "0.3",
                })
                if name == "tenant1":
                    # The deliberate straggler: tenant1's rank 1 pads
                    # every iteration — the tracker's span merge must
                    # attribute the slowness to exactly that rank.
                    env["RABIT_SLOW_RANK"] = "1"
                    env["RABIT_SLOW_EXTRA"] = "0.4"
                if args.elastic:
                    env["RABIT_ELASTIC"] = "1"
                if args.adapt:
                    env["RABIT_ADAPT"] = "1"
                if name in chaos:
                    env["RABIT_CHAOS"] = chaos[name]
                    env.setdefault("RABIT_TIMEOUT_SEC", "20")
                    env.setdefault("RABIT_BACKOFF_BASE_MS", "20")
                if obs:
                    env["RABIT_OBS_DIR"] = os.path.join(obs, name)
                by_tenant[name] = []
                for i in range(world):
                    env_i = dict(env)
                    env_i["RABIT_TASK_ID"] = str(i)
                    p = subprocess.Popen(
                        [sys.executable, worker_path, str(args.ndata),
                         str(args.niter)], env=env_i)
                    procs.append(p)
                    by_tenant[name].append(p)

            # Massacre tenant0 once its commits reach the seeded point —
            # and, concurrently, prove the LIVE plane: mid-run, GET
            # /metrics and /status must return correctly job-labeled
            # data for both tenants (the acceptance gate of the
            # streaming-telemetry plane, doc/observability.md).
            victim_ckpt = rdir / "tenant0" / "ckpt"
            deadline = time.monotonic() + 120
            live_why: str | None = "never scraped"
            while True:
                committed = _committed_version(victim_ckpt) >= kill_at
                if live_why is not None:
                    live_why = _live_scrape_ok(obs_port, args.tenants)
                if committed and live_why is None:
                    break
                if time.monotonic() > deadline:
                    if not committed:
                        return fail(r, f"tenant0 never committed "
                                    f"v{kill_at}", procs, tracker)
                    return fail(r, "live scrape never became healthy: "
                                + str(live_why), procs, tracker)
                if tracker.poll() is not None:
                    return fail(r, "tracker died before the massacre",
                                procs, tracker)
                if all(p.poll() is not None for p in by_tenant["tenant0"]):
                    break  # tenant0 already finished: still a valid round
                time.sleep(0.05)
            # tenant0 finishing early must not skip the live-plane
            # verdict: keep polling the scrape against the deadline.
            while live_why is not None and time.monotonic() <= deadline:
                live_why = _live_scrape_ok(obs_port, args.tenants)
                time.sleep(0.2)
            if live_why is not None:
                return fail(r, "live scrape never became healthy: "
                            + str(live_why), procs, tracker)
            print(f"[soak] round {r}: mid-run scrape OK — /metrics and "
                  "/status carry correctly job-labeled live data for "
                  f"all {args.tenants} tenants", flush=True)
            for p in by_tenant["tenant0"]:
                if p.poll() is None:
                    p.kill()
            print(f"[soak] round {r}: tenant0 massacred at "
                  f">=v{_committed_version(victim_ckpt)}", flush=True)
            time.sleep(1.0)
            if tracker.poll() is not None:
                return fail(r, "tracker died with tenant0 (isolation "
                            "breach)", procs, tracker)

            # Every OTHER tenant must finish cleanly — and while they
            # run, the tracker's span merge must flag tenant1's
            # deliberately slowed rank 1 with a straggler verdict
            # (polled via /status; the verdict also lands as a
            # straggler event on the job timeline).  Generous deadline:
            # chaos-forced recovery rounds on a loaded CI box stack up;
            # a genuine cross-tenant wedge still fails loudly well
            # under the outer test timeout.
            import json as _json

            straggler_seen = False
            waiting = {(j, i): p for j in range(1, args.tenants)
                       for i, p in enumerate(by_tenant[f"tenant{j}"])}
            # Same worst-case envelope as the sequential per-worker
            # p.wait(300) this loop replaced: chaos-forced recovery
            # rounds stack PER worker on a loaded box.
            wait_deadline = time.monotonic() + 300 * max(len(waiting), 1)
            while waiting:
                if time.monotonic() > wait_deadline:
                    j, i = next(iter(waiting))
                    return fail(r, f"tenant{j} rank {i} hung after "
                                "the tenant0 massacre", procs, tracker)
                for (j, i), p in list(waiting.items()):
                    code = p.poll()
                    if code is None:
                        continue
                    del waiting[(j, i)]
                    if code != 0:
                        return fail(r, f"tenant{j} rank {i} exited "
                                    f"{code} after the tenant0 "
                                    "massacre", procs, tracker)
                if not straggler_seen:
                    raw = _scrape(obs_port, "/status")
                    if raw:
                        try:
                            jobs = _json.loads(raw).get("jobs") or {}
                        except ValueError:
                            jobs = {}
                        t1 = jobs.get("tenant1") or {}
                        if "1" in (t1.get("stragglers") or {}):
                            straggler_seen = True
                            print(f"[soak] round {r}: straggler verdict "
                                  "fired for tenant1 rank 1 (score "
                                  f"{t1['stragglers']['1']})", flush=True)
                time.sleep(0.2)
            # Grace window: the verdict may land with the final flush
            # frames of tenant1's shutdown, just after the last exit.
            grace = time.monotonic() + 10
            while not straggler_seen and time.monotonic() < grace:
                raw = _scrape(obs_port, "/status")
                if raw:
                    try:
                        t1 = (_json.loads(raw).get("jobs")
                              or {}).get("tenant1") or {}
                    except ValueError:
                        t1 = {}
                    if "1" in (t1.get("stragglers") or {}):
                        straggler_seen = True
                        break
                time.sleep(0.2)
            if not straggler_seen:
                return fail(r, "the deliberately slowed tenant1 rank 1 "
                            "never earned a straggler verdict on "
                            "/status", procs, tracker)
            # ... the tracker must orphan-GC tenant0 and exit cleanly...
            try:
                code = tracker.wait(timeout=90)
            except subprocess.TimeoutExpired:
                return fail(r, "tracker never GC'd the orphaned tenant0 "
                            "job", procs, tracker)
            if code != 0:
                return fail(r, f"tracker exited {code}", procs, tracker)
            # ... and tenant1's model must be bit-exact vs the solo run.
            for i in range(world):
                got = (rdir / "tenant1" / "out" / f"final.{i}")
                if not got.exists():
                    return fail(r, f"tenant1 rank {i} wrote no final "
                                "model", procs, tracker)
                if got.read_bytes() != ref[i]:
                    return fail(r, f"tenant1 rank {i} final model is "
                                "NOT bit-exact vs the solo reference "
                                "(cross-tenant interference)", procs,
                                tracker)
            if obs:
                # The written report must carry the straggler table
                # (rank 1 flagged, per-schedule lateness split) and the
                # per-schedule span latency breakdown, and obs_report
                # must render it.
                from rabit_tpu.tools import obs_report as obs_report_mod

                rp = pathlib.Path(obs) / "tenant1" / "obs_report.json"
                try:
                    rep = _json.loads(rp.read_text())
                except (OSError, ValueError) as e:
                    return fail(r, f"tenant1 obs report unreadable: {e}",
                                procs, tracker)
                stragg = rep.get("straggler") or {}
                if 1 not in (stragg.get("straggling") or []):
                    return fail(r, "tenant1 obs report does not flag "
                                f"rank 1 as straggling: {stragg}",
                                procs, tracker)
                if not rep.get("sched_latency"):
                    return fail(r, "tenant1 obs report has no "
                                "per-schedule span latency", procs,
                                tracker)
                if obs_report_mod.main([str(rp.parent)]) != 0:
                    return fail(r, "obs_report failed to render the "
                                "tenant1 report", procs, tracker)
            print(f"[soak] round {r}: tenant1 bit-exact vs solo run "
                  "(straggler attributed to its slowed rank 1); "
                  "tracker survived and GC'd tenant0", flush=True)
        print(f"[soak] {args.rounds} tenant rounds passed", flush=True)
        return 0
    finally:
        shutil.rmtree(base, ignore_errors=True)


def run_shards(args, rng: random.Random, round_obs_dir) -> int:
    """The sharded-control-plane failover gate (--shards N with
    --tenants M): M co-tenant jobs hash across N tracker shards behind
    the job directory; one job-owning shard is SIGKILLed mid-training
    and its jobs must journal-replay onto a survivor within the
    workers' retry budget — finishing bit-exact vs a solo reference —
    while co-tenants on other shards never stall, the fleet books
    balance hierarchically (admitted == finished + orphan-GC'd summed
    across shards), and mid-run the directory's hierarchical /status
    and /metrics folds attribute every job to its shard (rendered
    through rabit_top).

    Self-healing extensions (doc/fault_tolerance.md "Replicated
    directory & job migration"): --dir-replicas N runs the directory
    as N lease-elected replicas; --dir-kill SIGKILLs the leader
    mid-training (a successor must take the lease and the postmortem
    must name the dead replica from the membership journal);
    --migrate holds one shard back and adds it mid-training — the
    armed shards must live-migrate >=1 RUNNING job to its new ring
    owner (migrated_out == migrated_in, bit-exact finals, balanced
    books)."""
    import io
    import json as _json
    import shutil
    import subprocess
    import tempfile
    import time

    from rabit_tpu.tools import rabit_top
    from rabit_tpu.tracker.directory import DirectoryClient
    from rabit_tpu.tracker.launch_local import launch

    world = 2                     # per-job world (M*world workers)
    worker_path = args.worker_path or str(
        _REPO_ROOT / "tests" / "workers" / "cold_restart.py")
    base = pathlib.Path(tempfile.mkdtemp(prefix="rabit_shard_soak_"))
    all_procs: list[subprocess.Popen] = []

    def down(procs) -> None:
        for p in procs:
            if p is not None and p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + 15
        for p in procs:
            if p is None:
                continue
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()

    def fail(r: int, why: str) -> int:
        print(f"[soak] FAILED (round {r}): {why}", flush=True)
        return 1

    try:
        # Solo reference: each job runs the same deterministic
        # workload, so ONE uninterrupted run on a dedicated tracker is
        # the bits every tenant must reproduce across the shard kill.
        ref_out = base / "ref"
        code = launch(world, [sys.executable, worker_path,
                              str(args.ndata), str(args.niter)],
                      extra_env={"RABIT_ENGINE": "pyrobust",
                                 "RABIT_OUT_DIR": str(ref_out)})
        if code != 0:
            print(f"[soak] FAILED: solo reference run exited {code}",
                  flush=True)
            return 1
        ref = {i: (ref_out / f"final.{i}").read_bytes()
               for i in range(world)}

        names = [f"tenant{j}" for j in range(args.tenants)]
        for r in range(args.rounds):
            rdir = base / f"round{r}"
            state = rdir / "state"
            state.mkdir(parents=True)
            obs = round_obs_dir(r)
            kill_at = 1 + rng.randrange(2)
            chaos = {name: gen_chaos(rng, "pyrobust", link=True)
                     for name in names} if args.chaos else {}

            # -- control plane: directory replica(s) + N shards --------
            n_rep = max(1, args.dir_replicas)
            dports = [_free_port() for _ in range(n_rep)]
            dir_url = ",".join(f"http://127.0.0.1:{p}" for p in dports)
            dir_procs: list[subprocess.Popen] = []
            for di, dp in enumerate(dports):
                cmd = [sys.executable, "-m",
                       "rabit_tpu.tracker.directory",
                       "--host", "127.0.0.1", "--port", str(dp),
                       "--max-jobs", str(args.tenants),
                       "--health-sec", "0.5", "--health-miss", "4"]
                if n_rep > 1:
                    # Replicated: deterministic lease (lowest healthy
                    # id leads); each replica journals membership into
                    # the shared state dir — the postmortem coordinate
                    # for --dir-kill.
                    cmd += ["--replica-index", str(di),
                            "--peers", dir_url,
                            "--lease-sec", "0.3", "--lease-miss", "3",
                            "--state-dir", str(state)]
                p = subprocess.Popen(cmd)
                all_procs.append(p)
                dir_procs.append(p)
            for dp in dports:
                if not _wait_port(dp):
                    return fail(r, "directory replica never came up")
            dead_dirs: set[int] = set()   # SIGKILLed by design

            def dir_down_why() -> str | None:
                for di, p in enumerate(dir_procs):
                    if di not in dead_dirs and p.poll() is not None:
                        return (f"directory replica {di} died "
                                "unexpectedly")
                return None

            def scrape_dir(path: str) -> str | None:
                for di, dp in enumerate(dports):
                    if di in dead_dirs:
                        continue
                    raw = _scrape(dp, path)
                    if raw is not None:
                        return raw
                return None

            shard_procs: dict[int, subprocess.Popen] = {}
            killed_shards: set[int] = set()
            # The directory link sites (dir_register/dir_poll/
            # dir_resolve) fire in the SHARD's DirectoryClient — the
            # detectors (counted register retries, poll-outage
            # episodes, ride-the-cache) live there, so their chaos
            # plan rides the shard env, not the workers'.
            shard_chaos = None
            if args.chaos:
                shard_chaos = (f"{rng.randrange(1 << 30)}:"
                               "reset@dir_register=0.5*2;"
                               "reset@dir_poll=0.15*3;"
                               "stall@dir_resolve=0.25*3;stallms=40")

            def start_shard(i: int) -> bool:
                port, oport = _free_port(), _free_port()
                cmd = [sys.executable, "-m", "rabit_tpu.tracker.tracker",
                       "-n", str(world), "--host", "127.0.0.1",
                       "--port", str(port), "--shard-index", str(i),
                       "--directory", dir_url,
                       "--state-dir", str(state),
                       "--job-gc-sec", "4", "--obs-port", str(oport)]
                if args.migrate:
                    cmd += ["--migrate-after-sec", "0.5",
                            "--migrate-max", "2"]
                if obs:
                    cmd += ["--obs-dir", os.path.join(obs, f"shard{i}")]
                senv = dict(os.environ)
                if shard_chaos:
                    senv["RABIT_CHAOS"] = shard_chaos
                p = subprocess.Popen(cmd, env=senv)
                all_procs.append(p)
                shard_procs[i] = p
                return _wait_port(port)

            # --migrate holds the last shard back: it joins mid-training
            # as the scale-up that makes running jobs misowned.
            n_start = args.shards - 1 if args.migrate else args.shards
            for i in range(n_start):
                if not start_shard(i):
                    return fail(r, f"shard {i} never came up")
            dc = DirectoryClient(dir_url)
            deadline = time.monotonic() + 20
            while True:
                try:
                    snap = dc.refresh()
                except (OSError, ValueError):
                    snap = {"shards": []}
                if len(snap.get("shards", ())) >= n_start:
                    break
                if time.monotonic() > deadline:
                    return fail(r, "shards never all registered with "
                                "the directory")
                time.sleep(0.1)

            owner_of = {}
            by_shard: dict[int, list[str]] = {}
            for name in names:
                own = dc.owner(name)
                if own is None:
                    return fail(r, f"directory has no owner for {name!r}")
                owner_of[name] = own
                by_shard.setdefault(own[0], []).append(name)
            if n_start > 1 and len(by_shard) < 2:
                return fail(r, "degenerate hash spread (every job on "
                            f"one shard): {by_shard}")
            # With --migrate the victim shard is only the commit-point
            # trigger (nothing is killed); otherwise it is SIGKILLed.
            victim = rng.choice(sorted(by_shard))
            action = ("scale-up + live migration"
                      if args.migrate else f"SIGKILL shard {victim}")
            print(f"[soak] round {r}: {args.tenants} jobs x world "
                  f"{world} over {n_start} shards "
                  + " ".join(f"shard{i}={by_shard.get(i, [])}"
                             for i in range(n_start))
                  + f"; {action} at >=v{kill_at}"
                  + (f"; {n_rep} directory replicas" if n_rep > 1
                     else "")
                  + ("; leader SIGKILL" if args.dir_kill else "")
                  + (" chaos(+tracker-link)" if chaos else ""),
                  flush=True)

            # -- workers ------------------------------------------------
            workers: list[subprocess.Popen] = []
            by_job: dict[str, list[subprocess.Popen]] = {}
            for name in names:
                idx, shost, sport = owner_of[name]
                tdir = rdir / name
                (tdir / "out").mkdir(parents=True)
                env = dict(os.environ)
                env.update({
                    "RABIT_TRACKER_URI": shost,
                    "RABIT_TRACKER_PORT": str(sport),
                    # The failover coordinate: a dead shard turns into
                    # a directory re-resolve, not a lost job.
                    "RABIT_DIRECTORY": dir_url,
                    "RABIT_JOB_ID": name,
                    "RABIT_WORLD_SIZE": str(world),
                    "RABIT_ENGINE": "pyrobust",
                    "RABIT_OUT_DIR": str(tdir / "out"),
                    "RABIT_CKPT_DIR": str(tdir / "ckpt"),
                    "RABIT_HEARTBEAT_SEC": "0.3",
                    "RABIT_HEARTBEAT_MISS": "10",
                    # Pacing so the shard kill (or the scale-up's
                    # migration window) lands mid-training.
                    "RABIT_ITER_SLEEP": "1.0" if args.migrate
                                        else "0.3",
                    # Redial budget across the failover window:
                    # health-removal (~2 s) + the survivor's adoption
                    # tick must fit inside the backoff walk.
                    "RABIT_CONNECT_RETRIES": "16",
                    "RABIT_OBS": "1",
                    "RABIT_OBS_FLUSH_SEC": "0.3",
                })
                if args.migrate:
                    # Elastic epoch polls are the steering wheel: the
                    # source's tombstone answers them with a forced
                    # epoch bump, driving workers through the rescale
                    # re-registration that redirects to the new owner.
                    env["RABIT_ELASTIC"] = "1"
                if name in chaos:
                    env["RABIT_CHAOS"] = chaos[name]
                    env.setdefault("RABIT_TIMEOUT_SEC", "20")
                    env.setdefault("RABIT_BACKOFF_BASE_MS", "20")
                if obs:
                    env["RABIT_OBS_DIR"] = os.path.join(obs, name)
                by_job[name] = []
                for i in range(world):
                    env_i = dict(env)
                    env_i["RABIT_TASK_ID"] = str(i)
                    p = subprocess.Popen(
                        [sys.executable, worker_path, str(args.ndata),
                         str(args.niter)], env=env_i)
                    all_procs.append(p)
                    workers.append(p)
                    by_job[name].append(p)

            # -- mid-run: hierarchical fold + the kill trigger ----------
            def fold_ok() -> str | None:
                raw = scrape_dir("/status")
                met = scrape_dir("/metrics")
                if raw is None or met is None:
                    return "directory /status or /metrics unreachable"
                try:
                    doc = _json.loads(raw)
                except ValueError:
                    return "/status fold is not valid JSON"
                jobs = doc.get("jobs") or {}
                for name in names:
                    row = jobs.get(name)
                    if row is None:
                        return f"/status fold has no job {name!r} yet"
                    if row.get("shard") != owner_of[name][0]:
                        return (f"job {name!r} attributed to shard "
                                f"{row.get('shard')!r}; owner is "
                                f"{owner_of[name][0]}")
                    if f'job="{name}"' not in met:
                        return (f"/metrics fold has no series labeled "
                                f"job={name!r} yet")
                buf = io.StringIO()
                try:
                    rabit_top.render(doc, None, out=buf)
                except Exception as e:  # noqa: BLE001 — verdict, not crash
                    return f"rabit_top failed on the fold: {e}"
                if "shard=" not in buf.getvalue():
                    return "rabit_top render shows no shard attribution"
                return None

            victim_job = by_shard[victim][0]
            victim_ckpt = rdir / victim_job / "ckpt"
            deadline = time.monotonic() + 120
            fold_why: str | None = "never scraped"
            while True:
                committed = _committed_version(victim_ckpt) >= kill_at
                if fold_why is not None:
                    fold_why = fold_ok()
                if committed and fold_why is None:
                    break
                if time.monotonic() > deadline:
                    if fold_why is not None:
                        return fail(r, "hierarchical fold never became "
                                    "healthy: " + str(fold_why))
                    return fail(r, f"{victim_job} never committed "
                                f"v{kill_at}")
                why = dir_down_why()
                if why:
                    return fail(r, why)
                for i, p in shard_procs.items():
                    if p.poll() is not None:
                        return fail(r, f"shard {i} died before the "
                                    "seeded kill")
                if all(p.poll() is not None for p in by_job[victim_job]):
                    return fail(r, f"{victim_job} finished before the "
                                "kill point — nothing to hand off")
                time.sleep(0.05)
            print(f"[soak] round {r}: mid-run fold OK — directory "
                  "/status + /metrics attribute all "
                  f"{args.tenants} jobs to their shards (rabit_top "
                  "renders shard columns)", flush=True)
            leader_killed: int | None = None
            if args.dir_kill:
                # SIGKILL the leader replica (lowest live id): the
                # successor must claim the lease within the window and
                # keep serving at a strictly HIGHER generation.
                leader_killed = min(di for di in range(n_rep)
                                    if di not in dead_dirs)
                dir_procs[leader_killed].kill()
                dead_dirs.add(leader_killed)
                print(f"[soak] round {r}: directory leader replica "
                      f"{leader_killed} SIGKILLed mid-training "
                      "(successor must take the lease)", flush=True)
                fo_deadline = time.monotonic() + 30
                new_leader = None
                while new_leader is None:
                    for di, dp in enumerate(dports):
                        if di in dead_dirs:
                            continue
                        raw = _scrape(dp, "/replica")
                        if raw is None:
                            continue
                        try:
                            doc = _json.loads(raw)
                        except ValueError:
                            continue
                        if doc.get("leader"):
                            new_leader = di
                            break
                    if new_leader is not None:
                        break
                    if time.monotonic() > fo_deadline:
                        return fail(r, "no surviving replica took the "
                                    "lease after SIGKILLing replica "
                                    f"{leader_killed}")
                    why = dir_down_why()
                    if why:
                        return fail(r, why)
                    time.sleep(0.1)
                print(f"[soak] round {r}: replica {new_leader} leads "
                      "after the kill (fenced takeover journaled)",
                      flush=True)

            if args.migrate:
                # Scale-up: the held-back shard joins, remapping part
                # of the ring — armed shards must hand >=1 RUNNING job
                # to its new owner at a commit boundary.
                grow = args.shards - 1
                print(f"[soak] round {r}: scale-up — starting shard "
                      f"{grow} (live migration must follow)",
                      flush=True)
                if not start_shard(grow):
                    return fail(r, f"shard {grow} (the scale-up) "
                                "never came up")
                mig_deadline = time.monotonic() + 90
                mig_why = "never scraped"
                while True:
                    raw = scrape_dir("/status")
                    c: dict = {}
                    if raw:
                        try:
                            c = (_json.loads(raw).get("service")
                                 or {}).get("counters") or {}
                        except ValueError:
                            c = {}
                    out_n = c.get("job.migrated_out", 0)
                    in_n = c.get("job.migrated_in", 0)
                    if out_n >= 1 and out_n == in_n:
                        print(f"[soak] round {r}: {out_n} live "
                              "migration(s) committed (migrated_out "
                              "== migrated_in)", flush=True)
                        break
                    mig_why = (f"migrated_out={out_n} "
                               f"migrated_in={in_n}")
                    if time.monotonic() > mig_deadline:
                        return fail(r, "no live migration committed "
                                    "after the scale-up: " + mig_why)
                    why = dir_down_why()
                    if why:
                        return fail(r, why)
                    for i, p in shard_procs.items():
                        if p.poll() is not None:
                            return fail(r, f"shard {i} died during "
                                        "the migration window")
                    time.sleep(0.2)
            else:
                shard_procs[victim].kill()
                killed_shards.add(victim)
                print(f"[soak] round {r}: shard {victim} SIGKILLed at "
                      f">=v{_committed_version(victim_ckpt)} "
                      f"(jobs {by_shard[victim]} must replay onto a "
                      "survivor)", flush=True)

            # -- every worker must finish (handoff + co-tenants) --------
            waiting = {(name, i): p for name in names
                       for i, p in enumerate(by_job[name])}
            wait_deadline = time.monotonic() + 300 * max(len(waiting), 1)
            while waiting:
                if time.monotonic() > wait_deadline:
                    name, i = next(iter(waiting))
                    return fail(r, f"{name} rank {i} hung after the "
                                f"{action}")
                why = dir_down_why()
                if why:
                    return fail(r, why + " after the " + action)
                for i, p in shard_procs.items():
                    if i not in killed_shards and p.poll() is not None:
                        return fail(r, f"surviving shard {i} died "
                                    "(handoff overload?)")
                for (name, i), p in list(waiting.items()):
                    code = p.poll()
                    if code is None:
                        continue
                    del waiting[(name, i)]
                    if code != 0:
                        return fail(r, f"{name} rank {i} exited {code} "
                                    f"after the {action}")
                time.sleep(0.1)

            # -- fleet books: admitted == finished + orphan-GC'd --------
            # job.created counted on survivors + job.restored counted by
            # the adopting shard must equal job.finished + job.orphan_gc
            # across the fold — each job accounted exactly once
            # fleet-wide, none lost, none doubled.
            deadline = time.monotonic() + 30
            books_why: str | None = "never scraped"
            while time.monotonic() < deadline:
                raw = scrape_dir("/status")
                counters: dict = {}
                if raw:
                    try:
                        counters = (_json.loads(raw).get("service")
                                    or {}).get("counters") or {}
                    except ValueError:
                        counters = {}
                admitted = (counters.get("job.created", 0)
                            + counters.get("job.restored", 0))
                closed = (counters.get("job.finished", 0)
                          + counters.get("job.orphan_gc", 0))
                if admitted == closed == args.tenants:
                    books_why = None
                    break
                books_why = (f"admitted={admitted} "
                             f"finished+orphan_gc={closed} "
                             f"(want {args.tenants} == {args.tenants}); "
                             f"counters={counters}")
                time.sleep(0.2)
            if books_why is not None:
                return fail(r, "fleet books never balanced: " + books_why)
            if args.migrate:
                # Migration is a transfer, not an admission: the pair
                # of counters must mirror exactly or a job was double-
                # entered / lost in flight.
                out_n = counters.get("job.migrated_out", 0)
                in_n = counters.get("job.migrated_in", 0)
                if not (out_n >= 1 and out_n == in_n):
                    return fail(r, "migration books skewed at the end: "
                                f"migrated_out={out_n} "
                                f"migrated_in={in_n}")

            # -- postmortem: the membership journal names the corpse ----
            if leader_killed is not None:
                from rabit_tpu.tools import postmortem as _pm
                dj = _pm.load_directory_journals(str(state))
                verdict = _pm.reconstruct([], [], dir_journals=dj)
                named = verdict.get("dead_replicas") or []
                if leader_killed not in named:
                    return fail(r, "postmortem does not name dead "
                                f"replica {leader_killed}: takeovers="
                                f"{verdict.get('directory_takeovers')}")
                print(f"[soak] round {r}: postmortem names dead "
                      f"replica(s) {named} from the membership "
                      "journal", flush=True)

            # -- finals: every job bit-exact vs the solo reference ------
            for name in names:
                for i in range(world):
                    got = rdir / name / "out" / f"final.{i}"
                    if not got.exists():
                        return fail(r, f"{name} rank {i} wrote no final "
                                    "model")
                    if got.read_bytes() != ref[i]:
                        return fail(r, f"{name} rank {i} final model is "
                                    "NOT bit-exact vs the solo "
                                    f"reference across the {action}")
            print(f"[soak] round {r}: all {args.tenants} jobs bit-exact "
                  f"vs solo across the {action}; books balanced "
                  "fleet-wide", flush=True)
            down([p for i, p in shard_procs.items()] + dir_procs)
        print(f"[soak] {args.rounds} shard rounds passed", flush=True)
        return 0
    finally:
        down(all_procs)  # exception paths must not orphan the fleet
        shutil.rmtree(base, ignore_errors=True)


def run_postmortem(args, rng: random.Random, round_obs_dir) -> int:
    """The crash-forensics gate (--postmortem): a world-4 pysocket job
    has one seeded rank SIGKILLed immediately before entering a seeded
    allreduce (an uncatchable death — the victim leaves NO flight
    record).  The survivors' link timeouts escalate to LinkErrors whose
    fault paths persist their always-on flight recorders under
    --trace-dir, the in-process tracker dumps its control-plane journal
    at teardown, and ``tools/postmortem.py`` must then reconstruct the
    incident FROM THE PERSISTED ARTIFACTS ALONE: the first-dead rank
    (the blamed peer that never wrote a record) and the op that was in
    flight (kind/seq matching the seeded kill point)."""
    import shutil
    import tempfile

    from rabit_tpu.obs import load_flight_records
    from rabit_tpu.tools.postmortem import (load_tracker_journals,
                                            reconstruct)
    from rabit_tpu.tracker.launch_local import launch

    world = 4
    niter = max(args.niter, 6)
    worker_path = args.worker_path or str(
        _REPO_ROOT / "tests" / "workers" / "postmortem_victim.py")
    base = pathlib.Path(tempfile.mkdtemp(prefix="rabit_pm_soak_"))
    try:
        for r in range(args.rounds):
            rdir = base / f"round{r}"
            trace_dir = rdir / "trace"
            trace_dir.mkdir(parents=True)
            victim = rng.randrange(world)
            kill_iter = 2 + rng.randrange(max(niter - 3, 1))
            env = {"RABIT_ENGINE": "pysocket",
                   "RABIT_OBS": "1",
                   "RABIT_OBS_FLUSH_SEC": "0.2",
                   # Trace EVERY op: the gate also proves the hop
                   # records kept streaming right up to the death.
                   "RABIT_TRACE_SAMPLE": "1",
                   "RABIT_PM_KILL_RANK": str(victim),
                   "RABIT_PM_KILL_ITER": str(kill_iter),
                   "RABIT_ITER_SLEEP": "0.05"}
            # Fast wedge->LinkError escalation so survivors persist and
            # exit in seconds; a caller's exported value wins.
            if "RABIT_TIMEOUT_SEC" not in os.environ:
                env["RABIT_TIMEOUT_SEC"] = "5"
            print(f"[soak] round {r}: postmortem — SIGKILL rank "
                  f"{victim} before allreduce #{kill_iter} "
                  f"(world {world}, {niter} iters)", flush=True)
            code = launch(
                world, [sys.executable, worker_path,
                        str(args.ndata), str(niter)],
                extra_env=env, trace_dir=str(trace_dir),
                obs_dir=round_obs_dir(r))
            if code == 0:
                print("[soak] FAILED: the job survived the SIGKILL — "
                      "the gate ran vacuously", flush=True)
                return 1
            records = load_flight_records(str(trace_dir))
            journals = load_tracker_journals(str(trace_dir))
            if not records:
                print("[soak] FAILED: no survivor persisted a flight "
                      f"record under {trace_dir}", flush=True)
                return 1
            verdict = reconstruct(records, journals)
            if verdict.get("first_dead") != victim:
                print(f"[soak] FAILED: postmortem blamed rank "
                      f"{verdict.get('first_dead')}, the corpse is rank "
                      f"{victim} (votes={verdict.get('blame_votes')})",
                      flush=True)
                return 1
            op = verdict.get("op_in_flight") or {}
            if op.get("kind") != "allreduce" or op.get("seq") != kill_iter:
                print(f"[soak] FAILED: postmortem named op {op}, the "
                      f"seeded kill point is allreduce #{kill_iter}",
                      flush=True)
                return 1
            print(f"[soak] round {r}: postmortem verdict correct — "
                  f"first dead rank {victim} "
                  f"({len(verdict.get('survivors') or [])} survivor "
                  f"records, votes={verdict.get('blame_votes')}), op in "
                  f"flight allreduce seq={op.get('seq')} "
                  f"epoch={op.get('epoch')} version={op.get('version')}",
                  flush=True)
        print(f"[soak] {args.rounds} postmortem rounds passed",
              flush=True)
        return 0
    finally:
        shutil.rmtree(base, ignore_errors=True)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=8)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--worker", default="model_recover",
                    choices=["model_recover", "local_recover",
                             "lazy_recover", "xla_restart"])
    ap.add_argument("--engine", default="mock",
                    choices=["mock", "pyrobust", "pysocket"],
                    help="robust engine the kill matrix drives: the "
                         "native C++ mock (default) or the pure-Python "
                         "pyrobust engine (no .so needed; same "
                         "RABIT_MOCK kill-point format); pysocket is "
                         "valid only with --chaos (no recovery — the "
                         "chaos mix is restricted to survivable faults)")
    ap.add_argument("--chaos", action="store_true",
                    help="layer a seeded RABIT_CHAOS wire-fault plan "
                         "(resets/refusals/partial writes/stalls) onto "
                         "each round; python engines only")
    ap.add_argument("--cold-restart", action="store_true",
                    help="kill ALL ranks after a seeded checkpoint "
                         "commit each round, relaunch the world under "
                         "the supervisor, cold-resume from the durable "
                         "tier and verify the final model bit-for-bit "
                         "against an uninterrupted run (pyrobust only)")
    ap.add_argument("--elastic", action="store_true",
                    help="elastic membership gate: grow the world 4->6 "
                         "(late joiners), shrink 6->3 (seeded SIGKILLs "
                         "-> heartbeat scale-down) mid-training with a "
                         "seeded tracker kill+restart (journal replay "
                         "from --state-dir); each rescale segment is "
                         "verified bit-identical against a fresh fixed-"
                         "world job resumed from the same committed "
                         "blob (pyrobust only; mixable with --chaos)")
    ap.add_argument("--tenants", type=int, default=0, metavar="N",
                    help="multi-tenant isolation gate: N concurrent "
                         "jobs against ONE shared tracker (admission "
                         "armed); tenant0's workers are all SIGKILLed "
                         "mid-training and the gate fails on any "
                         "cross-tenant interference — tenant1 must "
                         "finish bit-exact vs a solo run on a "
                         "dedicated tracker and the tracker must "
                         "survive + orphan-GC the dead job (pyrobust; "
                         "mixable with --chaos and --elastic)")
    ap.add_argument("--shards", type=int, default=0, metavar="N",
                    help="sharded-control-plane gate (requires "
                         "--tenants M): the M jobs hash across N "
                         "tracker shards behind the job directory; one "
                         "job-owning shard is SIGKILLed mid-training "
                         "and its jobs must journal-replay onto a "
                         "survivor — bit-exact finals, co-tenants on "
                         "other shards unstalled, fleet books balanced "
                         "through the hierarchical fold (pyrobust; "
                         "mixable with --chaos, which arms the "
                         "tracker-link fault kinds)")
    ap.add_argument("--dir-replicas", type=int, default=1, metavar="N",
                    help="with --shards: run the job directory as N "
                         "lease-elected replicas (lowest healthy id "
                         "leads; followers sync the membership journal "
                         "and redirect writes) — doc/fault_tolerance.md "
                         "'Replicated directory & job migration'")
    ap.add_argument("--dir-kill", action="store_true",
                    help="with --dir-replicas >= 2: SIGKILL the leader "
                         "replica mid-training; a successor must take "
                         "the lease within the window, registrations "
                         "keep flowing at a fenced higher generation, "
                         "and the postmortem must name the dead "
                         "replica from the membership journal")
    ap.add_argument("--migrate", action="store_true",
                    help="with --shards: hold the last shard back and "
                         "add it mid-training (scale-up); shards armed "
                         "with --migrate-after-sec must live-migrate "
                         ">=1 RUNNING job to its new ring owner at a "
                         "commit boundary — migrated_out == "
                         "migrated_in, bit-exact finals, balanced "
                         "fleet books")
    ap.add_argument("--transport", default="tcp",
                    choices=["tcp", "shm"],
                    help="shm: the transport gate — a same-host world "
                         "over shared-memory rings with integrity "
                         "framing and seeded corruption (guaranteed "
                         "torn ring write per rank -> detection -> "
                         "live shm->tcp failover), final model "
                         "bit-exact vs an uninterrupted tcp reference; "
                         "mixable with --chaos for the full wire fault "
                         "mix on top (doc/fault_tolerance.md "
                         "'Transports, integrity & failover')")
    ap.add_argument("--adapt", action="store_true",
                    help="closed-loop adaptive gate: a world-4 job "
                         "with a deliberately slowed rank under a "
                         "tracker with the adaptive controller armed "
                         "must converge to a measurably faster "
                         "schedule than the static pick, demote the "
                         "slow rank from hier leadership, stay "
                         "bit-exact vs an uninterrupted run, and "
                         "round-trip the learned TuningCache "
                         "(pyrobust; mixable with --chaos; with "
                         "--tenants it arms the controller on the "
                         "shared tracker instead)")
    ap.add_argument("--postmortem", action="store_true",
                    help="crash-forensics gate: a world-4 pysocket job "
                         "has a seeded rank SIGKILLed immediately "
                         "before a seeded allreduce; the survivors' "
                         "fault paths persist their flight recorders "
                         "and tools/postmortem.py must name the first-"
                         "dead rank and the in-flight op from the "
                         "persisted artifacts alone "
                         "(doc/observability.md)")
    ap.add_argument("--serve", action="store_true",
                    help="serving-plane gate (doc/serving.md): a "
                         "2-rank fleet with pinned capacity serves "
                         "verified traffic through steady load, a "
                         "mid-read version rollover, a 2x-capacity "
                         "open-loop spike (typed sheds, served p99 "
                         "bounded at 5x steady), a mid-traffic rank "
                         "SIGKILL absorbed by an elastic epoch, and a "
                         "train-while-serving co-tenant run that must "
                         "stay bit-exact vs solo training")
    ap.add_argument("--qos", action="store_true",
                    help="tail-tolerance gate (doc/serving.md): a "
                         "3-rank fleet with one 4x straggler must "
                         "route >= 30% of its traffic share away "
                         "(conviction hysteresis), hold the gold SLO "
                         "through a 2x mixed-class spike while bronze "
                         "sheds (per-class books exact), survive a "
                         "forced hedge storm with zero double serves "
                         "(typed Duplicates, cached answers bit-"
                         "exact), and keep exact books under seeded "
                         "serving-wire chaos")
    ap.add_argument("--max-restarts", type=int, default=4,
                    help="supervisor relaunch budget per worker for "
                         "--cold-restart rounds")
    ap.add_argument("--heartbeat", type=float, default=0.5,
                    help="worker heartbeat period for --cold-restart "
                         "rounds (proactive tracker-side liveness)")
    # None = unset (the shared default 5000 is applied after parsing),
    # so scenarios with their own payload default — --adapt wants the
    # bandwidth-bound 256KB regime — can tell an EXPLICIT --ndata 5000
    # apart from the default.
    ap.add_argument("--ndata", type=int, default=None)
    ap.add_argument("--niter", type=int, default=8)
    ap.add_argument("--kills", type=int, default=6)
    ap.add_argument("--worker-path", default=None,
                    help="explicit path to the worker script (defaults "
                         "to tests/workers/<worker>.py in the repo)")
    ap.add_argument("--obs-dir", default=None,
                    help="enable telemetry: each round writes per-rank "
                         "event traces plus the tracker-aggregated "
                         "obs_report.json under <obs-dir>/round<N> "
                         "(render with python -m "
                         "rabit_tpu.tools.obs_report)")
    args = ap.parse_args(argv)
    args.ndata_explicit = args.ndata is not None
    if args.ndata is None:
        args.ndata = 5000
    if (args.chaos and args.engine == "mock" and not args.cold_restart
            and not args.elastic and not args.tenants
            and not args.adapt and args.transport != "shm"):
        ap.error("--chaos drives the Python engines only; pass "
                 "--engine pyrobust (recovery mix) or pysocket "
                 "(survivable mix)")
    if args.engine == "pysocket" and not args.chaos:
        ap.error("--engine pysocket is only meaningful with --chaos "
                 "(it has no recovery protocol for a kill matrix)")
    if args.chaos and args.worker == "xla_restart":
        ap.error("--chaos does not apply to the xla_restart worker")
    if args.cold_restart and args.engine != "pyrobust":
        ap.error("--cold-restart drives the durable tier through the "
                 "pure-Python robust engine; pass --engine pyrobust")
    if args.elastic and not args.tenants:
        if args.engine not in ("mock", "pyrobust"):
            ap.error("--elastic drives the pure-Python robust engine; "
                     "pass --engine pyrobust (or leave the default)")
        if args.cold_restart or args.worker != "model_recover":
            ap.error("--elastic is its own scenario (elastic_worker); "
                     "it does not combine with --cold-restart or "
                     "--worker")
    if args.adapt and not args.tenants:
        if args.engine not in ("mock", "pyrobust"):
            ap.error("--adapt drives the pure-Python robust engine; "
                     "pass --engine pyrobust (or leave the default)")
        if args.cold_restart or args.elastic \
                or args.worker != "model_recover":
            ap.error("--adapt is its own scenario (cold_restart worker "
                     "with a slowed rank); it only combines with "
                     "--chaos (or rides --tenants)")
    if args.transport == "shm":
        if args.engine not in ("mock", "pyrobust"):
            ap.error("--transport shm drives the pure-Python robust "
                     "engine; pass --engine pyrobust (or leave the "
                     "default)")
        if args.cold_restart or args.elastic or args.adapt \
                or args.tenants or args.worker != "model_recover":
            ap.error("--transport shm is its own scenario "
                     "(cold_restart worker, bit-exact vs a tcp "
                     "reference); it only combines with --chaos")
    if args.postmortem:
        if (args.cold_restart or args.elastic or args.adapt
                or args.tenants or args.transport == "shm"
                or args.serve or args.chaos
                or args.worker != "model_recover"):
            ap.error("--postmortem is its own scenario (a seeded "
                     "SIGKILL mid-collective through the pysocket "
                     "engine); it does not combine with the other "
                     "gates")
    if args.serve:
        if args.engine not in ("mock", "pyrobust"):
            ap.error("--serve drives the pure-Python robust engine; "
                     "pass --engine pyrobust (or leave the default)")
        if (args.cold_restart or args.elastic or args.adapt
                or args.tenants or args.transport == "shm"
                or args.chaos or args.qos
                or args.worker != "model_recover"):
            ap.error("--serve is its own scenario (serving fleet + "
                     "co-tenant trainer); it does not combine with "
                     "the other gates")
    if args.qos:
        if args.engine not in ("mock", "pyrobust"):
            ap.error("--qos drives the pure-Python robust engine; "
                     "pass --engine pyrobust (or leave the default)")
        if (args.cold_restart or args.elastic or args.adapt
                or args.tenants or args.transport == "shm"
                or args.chaos or args.postmortem
                or args.worker != "model_recover"):
            ap.error("--qos is its own scenario (serving fleet with a "
                     "pinned straggler; it seeds its OWN serving-wire "
                     "chaos phase); it does not combine with the "
                     "other gates")
    if args.tenants:
        if args.tenants < 2:
            ap.error("--tenants needs at least 2 jobs to prove "
                     "isolation")
        if args.engine not in ("mock", "pyrobust"):
            ap.error("--tenants drives the pure-Python robust engine; "
                     "pass --engine pyrobust (or leave the default)")
        if args.cold_restart or args.worker != "model_recover":
            ap.error("--tenants is its own scenario (cold_restart "
                     "worker per tenant); it does not combine with "
                     "--cold-restart or --worker")
    if args.shards:
        if args.shards < 2:
            ap.error("--shards needs at least 2 shards for a handoff "
                     "to have a survivor")
        if not args.tenants:
            ap.error("--shards needs --tenants N (the jobs to spread "
                     "across the shard fleet)")
        if args.elastic or args.adapt:
            ap.error("--shards is its own scenario (sharded control "
                     "plane with a shard kill); it only combines with "
                     "--tenants and --chaos")
    if args.dir_replicas < 1:
        ap.error("--dir-replicas needs at least 1 replica")
    if (args.dir_replicas > 1 or args.dir_kill or args.migrate) \
            and not args.shards:
        ap.error("--dir-replicas/--dir-kill/--migrate ride the "
                 "--shards scenario; pass --shards N --tenants M")
    if args.dir_kill and args.dir_replicas < 2:
        ap.error("--dir-kill needs --dir-replicas >= 2 (a failover "
                 "needs a successor)")

    from rabit_tpu.tracker.launch_local import launch

    worker_path = args.worker_path or str(
        _REPO_ROOT / "tests" / "workers" / f"{args.worker}.py")
    rng = random.Random(args.seed)

    def round_obs_dir(r: int) -> str | None:
        if not args.obs_dir:
            return None
        return str(pathlib.Path(args.obs_dir) / f"round{r}")

    if args.postmortem:
        return run_postmortem(args, rng, round_obs_dir)
    if args.qos:
        return run_qos(args, rng, round_obs_dir)
    if args.serve:
        return run_serve(args, rng, round_obs_dir)
    if args.shards:
        return run_shards(args, rng, round_obs_dir)
    if args.tenants:
        return run_tenants(args, rng, round_obs_dir)
    if args.transport == "shm":
        return run_transport(args, rng, round_obs_dir)
    if args.adapt:
        return run_adapt(args, rng, round_obs_dir)
    if args.elastic:
        return run_elastic(args, rng, round_obs_dir)
    if args.cold_restart:
        return run_cold_restart(args, rng, round_obs_dir)

    for r in range(args.rounds):
        if args.worker == "xla_restart":
            # Randomized deaths through the XLA engine's device-plane
            # re-formation: distinct victims at random iterations (the
            # worker's fixed NITER is 4; iters 1-3 leave room to resume,
            # re-form, and verify the post-reform device path).
            # --ndata/--niter/--kills are mock-matrix knobs, inert here.
            if r == 0 and (args.ndata != 5000 or args.niter != 8
                           or args.kills != 6):
                print("[soak] note: --ndata/--niter/--kills do not apply "
                      "to the xla_restart worker (fixed NITER=4, 1-2 "
                      "victims)", flush=True)
            nvictims = min(1 + rng.randrange(2), args.world - 1)
            victims = rng.sample(range(args.world), nvictims)
            plan = ";".join(f"{v}:{1 + rng.randrange(3)}" for v in victims)
            print(f"[soak] round {r}: xla die-plan={plan}", flush=True)
            # --engine maps onto the XLA engine's host control plane:
            # mock -> the native robust inner, pyrobust -> the pure-
            # Python one.  A caller-exported RABIT_INNER still wins.
            inner = "native" if args.engine == "mock" else args.engine
            code = launch(
                args.world, [sys.executable, worker_path],
                extra_env={"RABIT_INNER": os.environ.get("RABIT_INNER",
                                                         inner),
                           "RABIT_XLA_DIE": plan},
                # worlds share one core on the CI box: scale the grace
                # period so jax import/startup isn't mistaken for a hang
                watchdog_sec=max(20, 4 * args.world),
                obs_dir=round_obs_dir(r))
            if code != 0:
                print(f"[soak] FAILED (exit {code}) — reproduce with "
                      f"RABIT_XLA_DIE='{plan}'", flush=True)
                return 1
            continue
        # pysocket has no recovery: chaos rounds on it run kill-free.
        matrix = ("" if args.engine == "pysocket"
                  else gen_matrix(rng, args.world, args.niter, args.kills))
        env = {"RABIT_ENGINE": args.engine}
        if matrix:
            env["RABIT_MOCK"] = matrix
        if args.chaos:
            env["RABIT_CHAOS"] = gen_chaos(rng, args.engine)
            # Fast hung-peer detection so injected stalls/resets turn
            # into recovery rounds in seconds, not the 600 s default;
            # quick backoff keeps the chaos rounds snappy.  A caller's
            # exported value wins (launch() overlays this dict onto
            # os.environ, so defaulting here would clobber it).
            if "RABIT_TIMEOUT_SEC" not in os.environ:
                env["RABIT_TIMEOUT_SEC"] = "20"
            if "RABIT_BACKOFF_BASE_MS" not in os.environ:
                env["RABIT_BACKOFF_BASE_MS"] = "20"
        print(f"[soak] round {r}: engine={args.engine} mock={matrix} "
              f"chaos={env.get('RABIT_CHAOS', '')}", flush=True)
        code = launch(
            args.world,
            [sys.executable, worker_path,
             str(args.ndata), str(args.niter)],
            extra_env=env, obs_dir=round_obs_dir(r))
        if code != 0:
            print(f"[soak] FAILED (exit {code}) — reproduce with "
                  f"RABIT_ENGINE='{args.engine}' RABIT_MOCK='{matrix}' "
                  f"RABIT_CHAOS='{env.get('RABIT_CHAOS', '')}'",
                  flush=True)
            return 1
    print(f"[soak] {args.rounds} rounds passed", flush=True)
    return 0


def cli() -> int:
    """Console-script entry point."""
    return main()


if __name__ == "__main__":
    sys.exit(main())
