"""``top`` for a rabit_tpu tracker — polls the live telemetry plane.

Points at a tracker started with ``--obs-port`` and renders its
``GET /status`` JSON as a refreshing terminal dashboard: one block per
job (world, epoch, committed version, membership) and one row per rank
(streamed op totals and rates, heartbeat freshness, straggler score).
Rates come from successive polls of the cumulative live fold, so the
dashboard needs no tracker-side state beyond what ``/status`` already
serves (doc/observability.md "Live telemetry").

Usage:
    python -m rabit_tpu.tools.rabit_top --port 9100 [--host H]
        [--interval 2] [--once] [--json] [--trace]

``--once`` prints a single snapshot and exits (scripting / tests);
``--once --json`` emits the raw ``/status`` document instead of the
rendered dashboard, so scripts get the per-job ``trace`` / ``serve_slo``
sections verbatim.  ``--trace`` appends the last assembled op's
skew-corrected cross-rank timeline under each job (doc/observability.md
"Causal tracing & postmortem").
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

CLEAR = "\x1b[2J\x1b[H"


def fetch_status(url: str, timeout: float = 3.0) -> dict:
    with urllib.request.urlopen(url + "/status", timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _age(sec: float | None) -> str:
    if sec is None:
        return "?"
    return f"{sec:.1f}s"


def _render_trace_block(job: dict, show_timeline: bool, out) -> None:
    """The per-job causal-trace lines: the bound-by verdict (which
    link the collectives' completion most often waited on) and, with
    ``--trace``, the last assembled op's corrected timeline."""
    tr = job.get("trace") or {}
    if not tr:
        return
    last = tr.get("last_op") or {}
    crit = last.get("critical") or {}
    bound = tr.get("bound_by") or "?"
    crit_s = ""
    if crit:
        crit_s = (f"  last op {last.get('key')}: {crit.get('kind')} "
                  f"hop{crit.get('hop')} {crit.get('link')} "
                  f"{crit.get('sec', 0.0) * 1e3:.2f}ms")
    print(f"  bound by: {bound}  "
          f"(ops={tr.get('ops_assembled', 0)} "
          f"records={tr.get('records', 0)}){crit_s}", file=out)
    if not show_timeline:
        return
    for r in last.get("records") or []:
        print(f"    t={r.get('t0')} rank{r.get('rank')} "
              f"{r.get('phase'):<7} hop{r.get('hop')} "
              f"peer={r.get('peer')} "
              f"{(r.get('t1', 0.0) - r.get('t0', 0.0)) * 1e3:.3f}ms "
              f"{r.get('nbytes', 0)}B", file=out)


def render(status: dict, prev: dict | None, out=None,
           show_trace: bool = False) -> None:
    # Resolve the stream at call time: a def-time ``sys.stdout`` default
    # would freeze whatever stdout object was installed at first import
    # (a test harness's capture buffer, long closed by the next caller).
    out = sys.stdout if out is None else out
    svc = status.get("service") or {}
    counters = svc.get("counters") or {}
    jobs = status.get("jobs") or {}
    # A sharded-tracker directory (or a single shard) annotates the
    # doc with fleet membership — surface it so an operator can see at
    # a glance which generation the dashboard reflects.
    fleet = status.get("directory") or {}
    fleet_s = (f"shards={fleet.get('shards')} "
               f"gen={fleet.get('generation')}  " if fleet else "")
    print(f"rabit_top — {time.strftime('%H:%M:%S')}  {fleet_s}"
          f"jobs_active={svc.get('jobs_active', [])}  "
          + " ".join(f"{k}={v}" for k, v in sorted(counters.items())
                     if k.startswith("job.")), file=out)
    prev_jobs = (prev or {}).get("jobs") or {}
    dt = max(status.get("ts", 0.0) - (prev or {}).get("ts", 0.0), 1e-6)
    for name in sorted(jobs):
        job = jobs[name] or {}
        if "error" in job:
            print(f"\njob {name}: (render raced a mutation: "
                  f"{job['error']})", file=out)
            continue
        flagged = job.get("stragglers") or {}
        shard_s = (f"shard={job['shard']} " if "shard" in job else "")
        print(f"\njob {name}: {shard_s}world={job.get('world')} "
              f"epoch={job.get('epoch')} "
              f"v={job.get('committed_version')} "
              f"members={len(job.get('members') or [])} "
              f"done={job.get('done')}"
              + (f"  STRAGGLERS={sorted(flagged)}" if flagged else ""),
              file=out)
        # Adaptive controller: the active schedule directive and the
        # last decision with its evidence (doc/performance.md "Online
        # adaptation").
        ctl = job.get("controller") or {}
        demoted = ctl.get("demoted") or []
        if ctl:
            active = ctl.get("active_sched") or {}
            sched_s = (" ".join(f"{b}B->{s}" for b, s in sorted(
                active.items(), key=lambda kv: int(kv[0])))
                or "(engine default)")
            last = (ctl.get("decisions") or [])[-1:]
            last_s = ""
            if last:
                d = last[0]
                evd = d.get("evidence") or {}
                last_s = f"  last={d.get('kind')}"
                if d.get("sched"):
                    last_s += f" {d['sched']}"
                if d.get("rank") is not None:
                    last_s += f" rank{d['rank']}"
                if "incumbent_sec" in evd and "challenger_sec" in evd:
                    last_s += (f" ({evd.get('incumbent')} "
                               f"{evd['incumbent_sec'] * 1e3:.2f}ms -> "
                               f"{evd['challenger_sec'] * 1e3:.2f}ms)")
            print(f"  active sched: {sched_s}"
                  + (f"  demoted={demoted}" if demoted else "")
                  + last_s, file=out)
        _render_trace_block(job, show_trace, out)
        def unwrap(live):
            # /status serves the live fold flat ({rank: row}); the
            # written obs report wraps it as {"ranks": ...} — accept
            # both so the dashboard also renders saved reports.
            live = live or {}
            ranks = live.get("ranks") if "ranks" in live else live
            return ranks if isinstance(ranks, dict) else {}

        ranks = unwrap(job.get("live"))
        # Serving row (doc/serving.md "SLOs"): jobs whose ranks file
        # serve.* instruments get one fleet-aggregated line — request
        # totals per status, served-request rate, queue depth and the
        # worst per-rank latency percentiles.
        serve_rows = [row["serve"] for row in ranks.values()
                      if isinstance(row, dict) and row.get("serve")]
        if serve_rows:
            agg: dict[str, float] = {}
            for s in serve_rows:
                for k, v in (s.get("requests") or {}).items():
                    agg[k] = agg.get(k, 0) + v
            ok_total = agg.get("ok", 0)
            prev_rows = [row.get("serve") for row in
                         unwrap((prev_jobs.get(name) or {})
                                .get("live")).values()
                         if isinstance(row, dict) and row.get("serve")]
            prev_ok = sum((p.get("requests") or {}).get("ok", 0)
                          for p in prev_rows)
            rate = max(ok_total - prev_ok, 0) / dt if prev else 0.0
            depth = sum(s.get("queue_depth", 0) for s in serve_rows)
            p99 = max((s.get("latency_p99_sec", 0.0)
                       for s in serve_rows), default=0.0)
            version = max((s.get("model_version", 0)
                           for s in serve_rows), default=0)
            slo = job.get("serve_slo") or {}
            slo_s = (f" slo_budget={slo['budget_remaining']:.3f}"
                     f" burn={slo['burn_rate']:.2f}"
                     if "budget_remaining" in slo else "")
            print(f"  serving: v={int(version)} "
                  f"ok={int(ok_total)} "
                  f"shed={int(agg.get('shed', 0))} "
                  f"timeout={int(agg.get('timeout', 0))} "
                  f"err={int(agg.get('error', 0))} "
                  f"q={int(depth)} req/s={rate:.1f} "
                  f"p99={p99 * 1e3:.1f}ms{slo_s}", file=out)
        liveness = job.get("liveness") or {}
        by_rank_seen = {str(v.get("rank")): v.get("last_seen_sec")
                        for v in liveness.values() if isinstance(v, dict)}
        scores = job.get("straggler_scores") or {}
        prev_ranks = unwrap((prev_jobs.get(name) or {}).get("live"))
        # Any rank that resolved a codec impl gets a codec column:
        # backend label plus mean per-op codec kernel time, so a rank
        # that silently fell back to numpy stands out in one glance.
        show_codec = any(isinstance(r, dict) and r.get("codec_impl")
                         for r in ranks.values())
        if ranks:
            print(f"  {'rank':<6}{'ops':>10}{'ops/s':>9}{'MB':>10}"
                  f"{'frames':>8}{'hb age':>8}{'score':>8}"
                  + (f"{'codec':>22}" if show_codec else ""), file=out)
            for rank in sorted(ranks, key=lambda r: int(r)
                               if str(r).isdigit() else 1 << 30):
                row = ranks[rank] or {}
                ops = row.get("ops", 0)
                prev_ops = (prev_ranks.get(rank) or {}).get("ops", ops)
                rate = max(ops - prev_ops, 0) / dt if prev else 0.0
                score = scores.get(str(rank), 0.0)
                mark = " <-- straggler" if str(rank) in {
                    str(s) for s in flagged} else ""
                if str(rank) in {str(r) for r in demoted}:
                    mark += " [demoted]"
                codec_s = ""
                if show_codec:
                    impl = row.get("codec_impl") or "-"
                    ck = row.get("codec_kernel_ms")
                    codec_s = (impl if ck is None
                               else f"{impl} {ck:.2f}ms")
                print(f"  {rank:<6}{ops:>10}{rate:>9.1f}"
                      f"{row.get('bytes', 0) / 1e6:>10.1f}"
                      f"{row.get('frames', 0):>8}"
                      f"{_age(by_rank_seen.get(str(rank))):>8}"
                      f"{score:>8.2f}"
                      + (f"{codec_s:>22}" if show_codec else "")
                      + mark, file=out)
        else:
            print("  (no streamed frames yet — workers need rabit_obs=1 "
                  "and rabit_obs_flush_sec > 0)", file=out)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="terminal dashboard over a tracker's --obs-port")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True,
                    help="the tracker's --obs-port")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    ap.add_argument("--json", action="store_true",
                    help="with --once: emit the raw /status JSON "
                         "(includes the per-job trace and serve_slo "
                         "sections) instead of the dashboard")
    ap.add_argument("--trace", action="store_true",
                    help="append the last assembled op's skew-corrected "
                         "cross-rank timeline under each job")
    args = ap.parse_args(argv)
    url = f"http://{args.host}:{args.port}"
    prev: dict | None = None
    while True:
        try:
            status = fetch_status(url)
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"rabit_top: cannot reach {url}/status: {e}",
                  file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        if args.once and args.json:
            json.dump(status, sys.stdout, sort_keys=True, indent=1)
            sys.stdout.write("\n")
            sys.stdout.flush()
            return 0
        if not args.once:
            sys.stdout.write(CLEAR)
        render(status, prev, show_trace=args.trace)
        sys.stdout.flush()
        if args.once:
            return 0
        prev = status
        time.sleep(args.interval)


def cli() -> int:
    """Console-script entry point."""
    return main()


if __name__ == "__main__":
    sys.exit(main())
