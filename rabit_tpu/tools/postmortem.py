"""Reconstruct a dead job's last seconds from its crash artifacts.

A job launched with ``--trace-dir`` leaves three kinds of evidence
behind when it dies (doc/observability.md "Causal tracing &
postmortem"):

- ``flight.rank<N>.json`` — each surviving rank's always-on flight
  recorder, persisted atomically on its fault path (LinkError
  escalation, recovery budget exhaustion, SIGTERM, serve drain); it
  carries the op that was in flight and the last ring of wire/engine
  events.  A SIGKILLed rank writes nothing — its absence IS evidence.
- ``tracker.<job>.json`` — the tracker's control-plane journal
  (membership, lost ranks, recent timeline events, the assembled trace
  report), dumped at teardown.
- the streamed trace/obs state, if a ``/status`` snapshot was saved.
- ``directory.r<i>.journal.jsonl`` — the replicated directory's
  per-replica membership journals, when the fleet's ``--state-dir``
  doubles as the trace dir; a ``takeover`` event names the dead
  replica(s) the new leader fenced out.
- ``loadgen.*.json`` — the load generator's serving-books reports
  (``--json``): per-QoS-class accounting identities plus the
  hedge/duplicate-suppression counts, folded into one balanced-or-not
  verdict per class (doc/serving.md "QoS classes").

This tool merges them and answers the three postmortem questions:
which rank died first, what op was in flight (epoch/version/seqno),
and which links stalled.  The first-dead verdict is a majority vote:
every survivor's flight record blames the peer its wire error surfaced
on, and a blamed rank that never persisted a record of its own is the
corpse.

Usage:
    python -m rabit_tpu.tools.postmortem TRACE_DIR [--json] [--out F]
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import sys

from rabit_tpu.obs import load_flight_records


def load_tracker_journals(trace_dir: str) -> list[dict]:
    """Read every ``tracker.*.json`` control-plane journal under
    ``trace_dir`` (malformed files skipped, like flight records)."""
    out = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("tracker.") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(trace_dir, name), encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict):
            out.append(doc)
    return out


def load_directory_journals(trace_dir: str) -> dict[int, list[dict]]:
    """Read every ``directory.r<i>.journal.jsonl`` membership journal
    under ``trace_dir`` — the replicated directory's per-replica event
    log (doc/fault_tolerance.md "Replicated directory & job
    migration").  Returns {replica_index: events}; malformed lines and
    files are skipped like flight records."""
    out: dict[int, list[dict]] = {}
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("directory.r")
                and name.endswith(".journal.jsonl")):
            continue
        idx_s = name[len("directory.r"):-len(".journal.jsonl")]
        if not idx_s.isdigit():
            continue
        events: list[dict] = []
        try:
            with open(os.path.join(trace_dir, name),
                      encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(ev, dict):
                        events.append(ev)
        except OSError:
            continue
        out[int(idx_s)] = events
    return out


def load_serving_reports(trace_dir: str) -> list[dict]:
    """Read every ``loadgen.*.json`` serving-books report under
    ``trace_dir`` — the client-side half of the serving evidence (a
    driver passing ``--json <trace_dir>/loadgen.<phase>.json`` to the
    load generator leaves one per traffic phase).  Malformed files are
    skipped like flight records."""
    out = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("loadgen.") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(trace_dir, name),
                      encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict):
            out.append(doc)
    return out


#: The serving accounting identity's outcome buckets (doc/serving.md):
#: offered == sum of these, aggregate AND per QoS class.
_SERVE_OUTCOMES = ("ok", "shed", "timeout", "error", "duplicate")


def fold_serving_books(reports: list[dict]) -> dict | None:
    """Fold loadgen reports into one set of serving books: aggregate
    and per-QoS-class outcome totals with their balance verdicts, plus
    the hedge/duplicate-suppression counts.  Counter sums, so the fold
    is associative across phases and clients.  Pure — unit-testable on
    synthetic reports; None when there is nothing to fold."""
    if not reports:
        return None
    totals = {"offered": 0, "wrong": 0, "double_served": 0,
              **{k: 0 for k in _SERVE_OUTCOMES}}
    hedges = {"fired": 0, "wins": 0, "stray_replies": 0,
              "cross_rank_serves": 0}
    per_class: dict[str, dict] = {}
    folded = 0
    for rep in reports:
        if not isinstance(rep, dict) or "offered" not in rep:
            continue
        folded += 1
        for k in ("offered", "wrong", "double_served",
                  *_SERVE_OUTCOMES):
            try:
                totals[k] += int(rep.get(k) or 0)
            except (TypeError, ValueError):
                continue
        for k in hedges:
            try:
                hedges[k] += int((rep.get("hedges") or {}).get(k) or 0)
            except (TypeError, ValueError):
                continue
        for name, cls in (rep.get("per_class") or {}).items():
            if not isinstance(cls, dict):
                continue
            row = per_class.setdefault(
                str(name), {"offered": 0,
                            **{k: 0 for k in _SERVE_OUTCOMES}})
            for k in ("offered", *_SERVE_OUTCOMES):
                try:
                    row[k] += int(cls.get(k) or 0)
                except (TypeError, ValueError):
                    continue
    if not folded:
        return None
    totals["balanced"] = totals["offered"] == sum(
        totals[k] for k in _SERVE_OUTCOMES)
    for row in per_class.values():
        row["balanced"] = row["offered"] == sum(
            row[k] for k in _SERVE_OUTCOMES)
    return {"reports": folded, "totals": totals,
            "per_class": per_class, "hedges": hedges}


def _blame_votes(records: list[dict], writers: set[int]) -> collections.Counter:
    """One vote per surviving rank for the peer its wire error blamed,
    counting only peers that never persisted a record themselves (a
    writer survived by definition)."""
    votes: collections.Counter = collections.Counter()
    for rec in records:
        blamed: set[int] = set()
        peer = rec.get("peer")
        if isinstance(peer, int) and peer >= 0 and peer not in writers:
            blamed.add(peer)
        for ev in rec.get("events") or []:
            if ev.get("name") != "link_error":
                continue
            p = ev.get("peer")
            if isinstance(p, int) and p >= 0 and p not in writers:
                blamed.add(p)
        for p in blamed:
            votes[p] += 1
    return votes


def reconstruct(records: list[dict],
                journals: list[dict] | None = None,
                last_events: int = 80,
                dir_journals: dict[int, list[dict]] | None = None,
                serving_reports: list[dict] | None = None) -> dict:
    """Fold flight records + tracker journals (and, when present, the
    replicated directory's membership journals and the load
    generator's serving-books reports) into the postmortem verdict.
    Pure — unit-testable on synthetic records."""
    journals = journals or []
    writers = {int(r["rank"]) for r in records
               if isinstance(r.get("rank"), int)}
    verdict: dict = {
        "survivors": sorted(writers),
        "reasons": {str(r.get("rank")): r.get("reason")
                    for r in sorted(records,
                                    key=lambda r: r.get("rank", -1))},
    }
    world = max([r.get("world") or 0 for r in records]
                + [j.get("world") or 0 for j in journals] + [0])
    if world:
        verdict["world"] = world

    # -- who died first -------------------------------------------------
    votes = _blame_votes(records, writers)
    lost = sorted({int(m) for j in journals
                   for m in (j.get("lost") or [])
                   if str(m).lstrip("-").isdigit()})
    if votes:
        # Majority of survivors' wire errors point at the corpse; ties
        # broken by the tracker's lost list, then by rank.
        top = max(votes.values())
        leaders = sorted(p for p, n in votes.items() if n == top)
        in_lost = [p for p in leaders if p in lost]
        verdict["first_dead"] = (in_lost or leaders)[0]
        verdict["blame_votes"] = {str(p): n for p, n in sorted(votes.items())}
    elif lost:
        verdict["first_dead"] = lost[0]
    elif world and writers:
        missing = sorted(set(range(world)) - writers)
        if missing:
            verdict["first_dead"] = missing[0]
    if lost:
        verdict["tracker_lost"] = lost

    # -- what op was in flight -------------------------------------------
    ops: collections.Counter = collections.Counter()
    by_key: dict = {}
    for rec in records:
        op = rec.get("inflight")
        if not isinstance(op, dict):
            continue
        key = (op.get("kind"), op.get("epoch"), op.get("version"),
               op.get("seq"))
        ops[key] += 1
        by_key[key] = op
    if ops:
        key, n = ops.most_common(1)[0]
        verdict["op_in_flight"] = dict(by_key[key])
        verdict["op_in_flight"]["votes"] = n

    # -- which links stalled ----------------------------------------------
    links = sorted({f"{rec.get('rank')}->{ev.get('peer')}"
                    for rec in records
                    for ev in (rec.get("events") or [])
                    if ev.get("name") == "link_error"
                    and ev.get("peer") is not None})
    if links:
        verdict["stalled_links"] = links

    # -- the merged last seconds -------------------------------------------
    merged = []
    for rec in records:
        for ev in rec.get("events") or []:
            if isinstance(ev, dict) and "ts" in ev:
                merged.append({**ev, "rank": ev.get("rank",
                                                    rec.get("rank"))})
    for j in journals:
        for ev in j.get("events") or []:
            if isinstance(ev, dict) and "ts" in ev:
                merged.append({**ev, "source": "tracker"})
    merged.sort(key=lambda e: e["ts"])
    verdict["last_events"] = merged[-last_events:]
    if journals:
        verdict["journal"] = [{k: j.get(k) for k in
                               ("job", "world", "epoch",
                                "committed_version", "lost")}
                              for j in journals]

    # -- the directory control plane ---------------------------------------
    # A takeover event in any replica's membership journal NAMES the
    # dead replica(s) it fenced out — the control-plane half of the
    # "who died" question.
    takeovers = []
    seen = set()
    for idx in sorted(dir_journals or {}):
        for ev in dir_journals[idx]:
            if ev.get("ev") != "takeover":
                continue
            key = (ev.get("gen"), ev.get("replica"),
                   tuple(ev.get("dead") or ()))
            if key in seen:
                continue  # follower-synced copies duplicate the leader's
            seen.add(key)
            takeovers.append({"gen": ev.get("gen"),
                              "by_replica": ev.get("replica"),
                              "dead_replicas": sorted(ev.get("dead")
                                                      or [])})
    if takeovers:
        takeovers.sort(key=lambda t: (t["gen"] if
                                      isinstance(t["gen"], int) else -1))
        verdict["directory_takeovers"] = takeovers
        verdict["dead_replicas"] = sorted(
            {d for t in takeovers for d in t["dead_replicas"]})

    # -- the serving books ---------------------------------------------------
    serving = fold_serving_books(serving_reports or [])
    if serving is not None:
        verdict["serving"] = serving
    return verdict


def render(verdict: dict, out=sys.stdout) -> None:
    print(f"postmortem: survivors={verdict.get('survivors')} "
          f"world={verdict.get('world', '?')}", file=out)
    if "first_dead" in verdict:
        votes = verdict.get("blame_votes") or {}
        vote_s = (f" (blame votes {votes})" if votes else
                  " (from tracker journal)" if verdict.get("tracker_lost")
                  else " (absent from flight records)")
        print(f"  first dead: rank {verdict['first_dead']}{vote_s}",
              file=out)
    else:
        print("  first dead: unknown (no blame evidence)", file=out)
    for t in verdict.get("directory_takeovers") or []:
        print(f"  directory: replica {t.get('by_replica')} took over at "
              f"generation {t.get('gen')} — dead replica(s): "
              f"{t.get('dead_replicas')}", file=out)
    op = verdict.get("op_in_flight")
    if op:
        print(f"  op in flight: {op.get('kind')} seq={op.get('seq')} "
              f"epoch={op.get('epoch')} version={op.get('version')} "
              f"({op.get('votes')} survivor(s) agree)", file=out)
    else:
        print("  op in flight: none recorded", file=out)
    for link in verdict.get("stalled_links") or []:
        print(f"  stalled link: {link}", file=out)
    for rank, reason in (verdict.get("reasons") or {}).items():
        print(f"  rank {rank} persisted on: {reason}", file=out)
    sv = verdict.get("serving")
    if sv:
        t = sv["totals"]
        print(f"  serving books ({sv['reports']} report(s)): "
              f"offered={t['offered']} ok={t['ok']} shed={t['shed']} "
              f"timeout={t['timeout']} error={t['error']} "
              f"duplicate={t['duplicate']} "
              f"double_served={t['double_served']} wrong={t['wrong']} "
              f"{'balanced' if t['balanced'] else 'IMBALANCED'}",
              file=out)
        for name, row in sorted((sv.get("per_class") or {}).items()):
            print(f"    class {name}: offered={row['offered']} "
                  f"ok={row['ok']} shed={row['shed']} "
                  f"timeout={row['timeout']} error={row['error']} "
                  f"duplicate={row['duplicate']} "
                  f"{'balanced' if row['balanced'] else 'IMBALANCED'}",
                  file=out)
        h = sv["hedges"]
        print(f"    hedges: fired={h['fired']} wins={h['wins']} "
              f"stray_replies={h['stray_replies']} "
              f"cross_rank_serves={h['cross_rank_serves']}", file=out)
    tail = verdict.get("last_events") or []
    if tail:
        print(f"  last {len(tail)} events:", file=out)
        for ev in tail[-12:]:
            who = (f"rank{ev['rank']}" if ev.get("rank") is not None
                   else ev.get("source", "?"))
            extra = " ".join(f"{k}={v}" for k, v in sorted(ev.items())
                             if k not in ("ts", "name", "rank", "source"))
            print(f"    {ev['ts']:.3f} {who:<8} {ev['name']} {extra}",
                  file=out)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="reconstruct a dead job's last seconds from the "
                    "flight records + tracker journal in a --trace-dir")
    ap.add_argument("trace_dir", help="the job's --trace-dir")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as JSON instead of text")
    ap.add_argument("--out", default=None,
                    help="also write the verdict as JSON here")
    args = ap.parse_args(argv)
    records = load_flight_records(args.trace_dir)
    journals = load_tracker_journals(args.trace_dir)
    dir_journals = load_directory_journals(args.trace_dir)
    serving_reports = load_serving_reports(args.trace_dir)
    if not records and not journals and not dir_journals \
            and not serving_reports:
        print(f"postmortem: no flight records, tracker journals, "
              f"directory journals or serving reports under "
              f"{args.trace_dir}", file=sys.stderr)
        return 1
    verdict = reconstruct(records, journals, dir_journals=dir_journals,
                          serving_reports=serving_reports)
    if args.json:
        json.dump(verdict, sys.stdout, sort_keys=True, indent=1)
        sys.stdout.write("\n")
    else:
        render(verdict)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(verdict, fh, sort_keys=True, indent=1)
    return 0


def cli() -> int:
    """Console-script entry point."""
    return main()


if __name__ == "__main__":
    sys.exit(main())
