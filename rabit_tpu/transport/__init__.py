"""rabit_tpu.transport — pluggable worker-worker link transports.

Factors every byte the engines move to a peer behind the
:class:`~rabit_tpu.transport.base.Link` interface: the classic TCP path
(``tcp.py``, byte-identical wire), same-host shared-memory rings
(``shm.py``), link-level integrity framing (``framing.py``), the
transport-generic progress pumps (``pump.py``) and the negotiating
link factory with shm→tcp failover bookkeeping (``factory.py``).

Engine knobs (doc/parameters.md "Transports"): ``rabit_transport``
(tcp/shm/auto), ``rabit_wire_integrity`` (off/crc32/crc32c),
``rabit_shm_ring_bytes``, ``rabit_transport_failover``,
``rabit_shm_retries``, ``RABIT_SHM_DIR``.  All off by default: the
default-config wire is byte-identical to pre-transport releases, and
every feature is negotiated per link so mixed-config worlds degrade to
the common subset instead of diverging.

This layer is also the plug point for what comes next: an RDMA/ICI
link is one more Link subclass, and a quantized wire codec (EQuARX-
style, ROADMAP item 1) slots between the engine and the frame layer.
"""
from __future__ import annotations

from rabit_tpu.transport.base import (FRAME_MAX, INTEGRITY_MODES,
                                      TRANSPORT_MODES, Events,
                                      IntegrityError, Link, LinkError,
                                      NULL_EVENTS, TransportConfig,
                                      setup_stream_socket)
from rabit_tpu.transport.factory import XMAGIC, LinkFactory
from rabit_tpu.transport.framing import FrameDecoder, encode_frames
from rabit_tpu.transport.pump import HopPipeline, exchange, recv_all
from rabit_tpu.transport.shm import ShmLink, ShmRing, default_shm_dir
from rabit_tpu.transport.tcp import TcpLink

__all__ = [
    "Link", "LinkError", "IntegrityError", "TransportConfig", "Events",
    "NULL_EVENTS", "LinkFactory", "TcpLink", "ShmLink", "ShmRing",
    "FrameDecoder", "encode_frames", "exchange", "recv_all",
    "HopPipeline",
    "setup_stream_socket", "default_shm_dir", "XMAGIC", "FRAME_MAX",
    "INTEGRITY_MODES", "TRANSPORT_MODES",
]
