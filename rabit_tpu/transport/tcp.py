"""``TcpTransport``: the classic TCP link behind the Link interface.

This is the existing engine wire, byte-identical: the same syscalls
(``sendall``/``sendmsg``/``recv_into``) in the same patterns the
engine's IO helpers used inline, so the chaos wrapper
(:class:`rabit_tpu.chaos.sock.ChaosSocket`) interposes at exactly the
same seam — the socket handed in here may already be chaos-wrapped —
and the per-link byte stream of a default-config job is unchanged.

With negotiated integrity framing the same socket carries
``len|payload|crc`` frames (framing.py); corruption surfaces as
:class:`~rabit_tpu.transport.base.IntegrityError` before any poisoned
byte reaches the engine.
"""
from __future__ import annotations

import socket
from typing import Optional

from rabit_tpu.transport.base import (SENDMSG_MAX_PARTS, Events, IntegrityError,
                                      Link, NULL_EVENTS, advance_iov,
                                      flatten_parts)
from rabit_tpu.transport.framing import FrameDecoder, encode_frames

_RAW_READ = 65536


class TcpLink(Link):
    kind = "tcp"

    def __init__(self, sock, peer: int, timeout: Optional[float],
                 events: Events = NULL_EVENTS,
                 frames: bool = False, pacer=None) -> None:
        self._sock = sock            # possibly a ChaosSocket
        self.peer = peer
        self._timeout = timeout
        self._ev = events
        self._frames = frames
        self._dec = (FrameDecoder(peer, events, kind=self.kind)
                     if frames else None)
        self._pend: list = []        # pump-mode framed tx backlog
        self._tmp = bytearray(_RAW_READ)
        self._dead = False
        # Egress pacing (rabit_link_mbps, a LinkPacer or None): charges
        # every byte this link sends — blocking paths sleep off their
        # deficit, the pump path gates below — so the link emulates a
        # constrained cross-host budget for bandwidth-regime benches.
        self._pacer = pacer

    # ------------------------------------------------------------------
    # blocking
    # ------------------------------------------------------------------
    def sendall(self, data) -> None:
        if self._frames:
            self._sendmsg_all(encode_frames(flatten_parts([data])))
            return
        while True:
            try:
                self._sock.sendall(data)
                if self._pacer is not None:
                    self._pacer.pay(len(memoryview(data).cast("B")))
                return
            except InterruptedError:
                # EINTR only ever surfaces with zero bytes moved
                # (sendall retries internally once transfer starts,
                # PEP 475), so reissuing the whole buffer is safe.
                continue
            except OSError as e:
                self._dead = True
                self._fail(f"send to rank {self.peer} failed: {e}", e)

    def sendv(self, parts) -> None:
        bufs = flatten_parts(parts)
        if self._frames:
            bufs = encode_frames(bufs)
        self._sendmsg_all(bufs)

    def _sendmsg_all(self, bufs: list) -> None:
        """Vectored blocking send: coalesce buffers into as few
        syscalls as ``sendmsg`` allows — the byte stream is identical
        to sequential ``sendall`` calls."""
        try:
            while bufs:
                try:
                    n = self._sock.sendmsg(bufs[:SENDMSG_MAX_PARTS])
                except InterruptedError:
                    continue  # EINTR: nothing consumed, reissue
                if self._pacer is not None:
                    self._pacer.pay(n)
                advance_iov(bufs, n)
        except OSError as e:
            self._dead = True
            self._fail(f"send to rank {self.peer} failed: {e}", e)

    def recv_exact(self, nbytes: int, into=None):
        buf = into if into is not None else memoryview(bytearray(nbytes))
        if self._frames:
            return self._recv_exact_framed(buf, nbytes)
        got = 0
        try:
            while got < nbytes:
                try:
                    n = self._sock.recv_into(buf[got:nbytes], nbytes - got)
                except InterruptedError:
                    continue  # EINTR: not a peer failure, just retry
                if n == 0:
                    self._dead = True
                    self._fail(f"rank {self.peer} closed the link")
                got += n
        except OSError as e:
            self._dead = True
            self._fail(f"recv from rank {self.peer} failed: {e}", e)
        return buf

    def _recv_exact_framed(self, buf, nbytes: int):
        got = self._dec.take(buf[:nbytes])
        while got < nbytes:
            try:
                try:
                    n = self._sock.recv_into(self._tmp, _RAW_READ)
                except InterruptedError:
                    continue
            except OSError as e:
                self._dead = True
                self._fail(f"recv from rank {self.peer} failed: {e}", e)
            if n == 0:
                self._dead = True
                self._fail(f"rank {self.peer} closed the link")
            self._feed(memoryview(self._tmp)[:n])
            got += self._dec.take(buf[got:nbytes])
        return buf

    def _feed(self, raw) -> None:
        try:
            self._dec.feed(raw)
        except IntegrityError as e:
            e.link = self  # attribution for the engine's failover hook
            raise

    # ------------------------------------------------------------------
    # pump
    # ------------------------------------------------------------------
    def pump_begin(self) -> None:
        try:
            self._sock.setblocking(False)
        except OSError as e:
            # A link already reset by a previous phase of the same op
            # must surface as LinkError (-> recovery), never EBADF.
            self._dead = True
            self._fail(f"link to rank {self.peer} is dead: {e}", e)

    def pump_end(self) -> None:
        # settimeout (not setblocking) — setblocking(True) would clear
        # the link IO timeout set at wiring.  Tolerant of a dead fd:
        # restoring state on a reset link must not mask the LinkError
        # in flight with EBADF.
        try:
            self._sock.settimeout(self._timeout)
        except OSError:
            pass
        if self._pend:
            self._sendmsg_all(self._pend)
            self._pend = []

    def pump_abort(self) -> None:
        self._pend = []
        try:
            self._sock.settimeout(self._timeout)
        except OSError:
            pass

    def poll_sendv(self, bufs: list) -> bool:
        if self._pacer is not None and not self._pacer.ready():
            return False  # paced out: the pump waits a bounded slice
        if self._frames:
            if not self._pend and bufs:
                # Claim payload one frame batch at a time; the frame
                # references the caller's buffers, so claim == consume.
                self._pend = encode_frames(bufs)
                del bufs[:]
            if not self._pend:
                return False
            send_bufs = self._pend
        else:
            if not bufs:
                return False
            send_bufs = bufs
        try:
            n = self._sock.sendmsg(send_bufs[:SENDMSG_MAX_PARTS])
        except (BlockingIOError, InterruptedError):
            return False
        except OSError as e:
            self._dead = True
            self._fail(f"send to rank {self.peer} failed: {e}", e)
        if self._pacer is not None:
            self._pacer.debit(n)  # overdraft <= one send window
        advance_iov(send_bufs, n)
        return n > 0

    def poll_recv(self, mv) -> int:
        self.wire_progress = False
        if self._frames:
            n = self._dec.take(mv)
            if n:
                self.wire_progress = True
                return n
        try:
            if self._frames:
                m = self._sock.recv_into(self._tmp, _RAW_READ)
            else:
                m = self._sock.recv_into(mv, len(mv))
        except (BlockingIOError, InterruptedError):
            return 0
        except OSError as e:
            self._dead = True
            self._fail(f"recv from rank {self.peer} failed: {e}", e)
        if m == 0:
            self._dead = True
            self._fail(f"rank {self.peer} closed the link")
        self.wire_progress = True
        if self._frames:
            self._feed(memoryview(self._tmp)[:m])
            return self._dec.take(mv)
        return m

    def rx_pending(self) -> bool:
        return self._dec.pending() if self._dec is not None else False

    def tx_pending(self) -> bool:
        return bool(self._pend)

    def needs_poll(self) -> bool:
        # A paced-out link is write-ready to select (the kernel buffer
        # has room) but must not be re-polled hot: bound the pump's
        # wait to the shm-style slice until the bucket refills.
        return self._pacer is not None and not self._pacer.ready()

    def fileno(self) -> int:
        return self._sock.fileno()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def settimeout(self, t) -> None:
        self._timeout = t
        try:
            self._sock.settimeout(t)
        except OSError:
            pass

    def healthy(self) -> bool:
        if self._dead:
            return False
        try:
            return self._sock.fileno() >= 0
        except OSError:
            return False

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
