"""Link-level integrity framing: ``u32 length | payload | u32 crc``.

When ``rabit_wire_integrity`` is negotiated on a link, every write call
is wrapped in one or more frames (payload capped at
:data:`~rabit_tpu.transport.base.FRAME_MAX` per frame) and the receiver
verifies each frame's CRC trailer before a single payload byte reaches
the engine.  The framing is a pure stream transform — frame boundaries
follow the sender's write calls, the receiver reassembles a plain byte
stream — so every schedule, pump and chunk budget composes unchanged.

Detection, not correction: a mismatched trailer increments the
``integrity.detected`` counter and raises
:class:`~rabit_tpu.transport.base.IntegrityError` (TCP consumes the
stream, so there is nothing left to re-read; the pyrobust layer retries
the whole op from pristine buffers).  The shm transport can do better —
its ring supports re-reading an unconsumed frame — and implements the
bounded re-read retry in :mod:`rabit_tpu.transport.shm`.

The checksum is the stdlib's C-accelerated CRC-32 (``zlib.crc32``) for
both negotiated mode names (see ``INTEGRITY_MODES`` in base.py).
"""
from __future__ import annotations

import struct
import zlib

from rabit_tpu.transport.base import (FRAME_MAX, Events, IntegrityError,
                                      NULL_EVENTS)

HDR_FMT = "<I"
HDR_BYTES = 4
CRC_BYTES = 4


def frame_crc(*views) -> int:
    crc = 0
    for v in views:
        crc = zlib.crc32(v, crc)
    return crc & 0xFFFFFFFF


def encode_frames(bufs: list, frame_max: int = FRAME_MAX) -> list:
    """Wrap a flat list of payload memoryviews into wire parts:
    ``[hdr, payload..., crc] * nframes``.  Payload views are referenced,
    never copied — only the 8 header/trailer bytes per frame are new.
    """
    out: list = []
    pend: list = []
    pend_bytes = 0

    def flush() -> None:
        nonlocal pend, pend_bytes
        if not pend_bytes:
            return
        out.append(memoryview(struct.pack(HDR_FMT, pend_bytes)))
        out.extend(pend)
        out.append(memoryview(struct.pack(HDR_FMT, frame_crc(*pend))))
        pend = []
        pend_bytes = 0

    for mv in bufs:
        off = 0
        while off < len(mv):
            take = min(len(mv) - off, frame_max - pend_bytes)
            pend.append(mv[off:off + take])
            pend_bytes += take
            off += take
            if pend_bytes == frame_max:
                flush()
    flush()
    return out


class PlainBuffer:
    """Verified-plaintext staging shared by the framed receive paths
    (the TCP deframer below and the shm ring's verify-then-consume
    reader): ``push()`` verified payload in, ``take()`` serves the
    engine's reads in whatever sizes it asks."""

    def __init__(self) -> None:
        self._buf = bytearray()
        self._off = 0

    def push(self, data) -> None:
        self._buf += data

    def take(self, mv) -> int:
        """Copy up to ``len(mv)`` plaintext bytes out."""
        avail = len(self._buf) - self._off
        n = min(avail, len(mv))
        if n:
            mv[:n] = memoryview(self._buf)[self._off:self._off + n]
            self._off += n
            if self._off == len(self._buf):
                self._buf = bytearray()
                self._off = 0
        return n

    def pending(self) -> bool:
        return len(self._buf) > self._off


class FrameDecoder:
    """Incremental deframer for stream transports: ``feed()`` raw wire
    bytes in whatever chunks arrive, ``take()`` verified plaintext.

    A frame is verified the moment its last byte lands; corruption
    (CRC mismatch, or a length no honest sender can produce) raises
    :class:`IntegrityError` from ``feed`` after counting
    ``integrity.detected`` — the engine never sees the poisoned bytes.
    """

    def __init__(self, peer: int, events: Events = NULL_EVENTS,
                 frame_max: int = FRAME_MAX, kind: str = "tcp") -> None:
        self._peer = peer
        self._ev = events
        self._max = frame_max
        self._kind = kind
        self._raw = bytearray()      # undecoded wire bytes
        self._plain = PlainBuffer()  # verified payload, not yet taken

    def feed(self, data) -> None:
        self._raw += data
        while True:
            if len(self._raw) < HDR_BYTES:
                return
            (ln,) = struct.unpack_from(HDR_FMT, self._raw)
            if not 0 < ln <= self._max:
                self._detect(f"impossible frame length {ln}")
            need = HDR_BYTES + ln + CRC_BYTES
            if len(self._raw) < need:
                return
            payload = memoryview(self._raw)[HDR_BYTES:HDR_BYTES + ln]
            (want,) = struct.unpack_from(HDR_FMT, self._raw,
                                         HDR_BYTES + ln)
            if frame_crc(payload) != want:
                payload.release()
                self._detect(f"frame crc mismatch (len {ln})")
            self._plain.push(payload)
            payload.release()
            del self._raw[:need]

    def _detect(self, what: str) -> None:
        self._ev.counter("integrity.detected")
        self._ev.event("integrity", phase="detected", peer=self._peer,
                       transport=self._kind, detail=what)
        raise IntegrityError(
            f"wire corruption from rank {self._peer} detected: {what}")

    def take(self, mv) -> int:
        """Copy up to ``len(mv)`` verified plaintext bytes out."""
        return self._plain.take(mv)

    def pending(self) -> bool:
        return self._plain.pending()
