"""The Transport/Link interface — how engine bytes reach a peer.

Every worker-worker byte the pure-Python engines move now flows through
a :class:`Link`: the engine wires one per peer at rendezvous (via
:class:`rabit_tpu.transport.factory.LinkFactory`), the collective
schedules keep calling the engine's IO helpers (``_send``/``_recv``/
``_exchange``/``_recv_all``), and those helpers delegate here.  A link
owns exactly the byte-moving concerns — blocking and non-blocking
send/recv, vectored writes, timeouts, health — while the engine keeps
everything above the byte stream (op framing, reduction math, seqno/
replay, recovery).

Two implementations ship: :class:`rabit_tpu.transport.tcp.TcpLink`
(the existing TCP path, byte-identical on the wire, chaos interposition
at the same syscall seam) and :class:`rabit_tpu.transport.shm.ShmLink`
(same-host shared-memory ring buffers with the TCP connection retained
as doorbell + liveness channel).  Both optionally speak **integrity
framing** (``rabit_wire_integrity``): every write is wrapped in a
``u32 length | payload | u32 crc`` frame so a flipped wire bit is
*detected* — surfacing as a typed :class:`IntegrityError` (a
:class:`LinkError`, so the pyrobust recovery path treats it like any
dead link) instead of silently corrupting the model.  Framing is
negotiated per link in the handshake (factory.py) and off by default,
which keeps the default-config wire byte-identical to older peers.

No engine imports here — engine → transport only, never back.
"""
from __future__ import annotations

import math
import select
import socket
import time
from typing import Optional

from rabit_tpu.utils.checks import check

#: integrity frame payload cap: bounds the deframer's staging memory and
#: the blast radius of one corrupted frame (matches the engines' stream
#: chunk so large payloads frame per chunk, not per byte).
FRAME_MAX = 256 << 10

#: scatter-gather segments per sendmsg (mirrors the engine's historical
#: cap; IOV_MAX is >= 1024 everywhere we run).
SENDMSG_MAX_PARTS = 64

#: accepted ``rabit_wire_integrity`` modes.  Both currently compute the
#: trailer with the C-accelerated stdlib CRC-32 (zlib); ``crc32c`` is
#: the negotiated NAME reserved for a Castagnoli implementation — the
#: frame layout and detection strength are identical, and peers agree on
#: the mode through the link handshake either way.
INTEGRITY_MODES = ("off", "crc32", "crc32c")
TRANSPORT_MODES = ("tcp", "shm", "auto")

#: smallest usable shm ring: enforced on the local config AND on the
#: NEGOTIATED size (a skewed or garbled peer offer below this takes the
#: clean tcp-fallback path — a degenerate ring whose every write
#: returns 0 would stall to the link timeout instead).
SHM_RING_MIN = 4096


class LinkError(ConnectionError):
    """A worker-worker or tracker link failed (peer death or reset).

    Raised by every transport on IO failure; the robust engine's
    recovery path catches exactly this.  Instances raised inside a
    :class:`Link` carry the link as ``err.link`` so the engine can
    attribute the failure (e.g. shm→tcp failover bookkeeping)."""

    link: Optional["Link"] = None


class IntegrityError(LinkError):
    """Integrity framing detected wire corruption on a link.

    A frame's CRC trailer (or a structurally impossible frame length)
    did not match its payload after the transport's bounded re-read
    budget.  This IS a :class:`LinkError` on purpose: the pyrobust
    recovery path escalates it exactly like a peer death — the op
    retries from pristine buffers — and the engine's failover hook
    additionally tears a corrupted shm link down and re-dials it as
    TCP.  Without a robust layer it reaches the caller typed, never as
    silently wrong numbers."""


class Events:
    """Telemetry hooks the engine hands the transport layer (counters +
    trace events ride the engine's obs subsystem; the default sink
    drops everything, so transports never gate on obs config)."""

    def counter(self, name: str, n: int = 1) -> None:
        pass

    def event(self, name: str, **fields) -> None:
        pass


NULL_EVENTS = Events()


class TransportConfig:
    """Resolved transport knobs (doc/parameters.md "Transports").

    ``transport``: ``tcp`` (default — byte-identical classic wire),
    ``shm``/``auto`` (offer shared-memory rings to same-host-group
    peers, TCP cross-host; ``shm`` logs when it has to fall back).
    ``integrity``: ``off`` | ``crc32`` | ``crc32c`` frame trailers.
    ``shm_ring_bytes``: per-direction ring capacity.  ``failover``:
    tear a failing shm link down and re-dial as TCP at the next
    rendezvous.  ``shm_retries``: bounded re-reads of a CRC-failed shm
    frame before escalating (catches a torn-but-completing write).
    ``link_mbps``: egress pacing per TCP link (:class:`LinkPacer`;
    0 = unpaced — the default and the only production setting).
    """

    def __init__(self, transport: str = "tcp", integrity: str = "off",
                 shm_ring_bytes: int = 1 << 20, failover: bool = True,
                 shm_retries: int = 3,
                 shm_dir: Optional[str] = None,
                 link_mbps: float = 0.0) -> None:
        check(transport in TRANSPORT_MODES,
              "rabit_transport must be one of %s, got %r",
              "/".join(TRANSPORT_MODES), transport)
        check(integrity in INTEGRITY_MODES,
              "rabit_wire_integrity must be one of %s, got %r",
              "/".join(INTEGRITY_MODES), integrity)
        check(shm_ring_bytes >= SHM_RING_MIN,
              "rabit_shm_ring_bytes must be >= %d, got %r",
              SHM_RING_MIN, shm_ring_bytes)
        check(shm_retries >= 0, "rabit_shm_retries must be >= 0")
        check(link_mbps >= 0, "rabit_link_mbps must be >= 0")
        self.link_mbps = float(link_mbps)
        self.transport = transport
        self.integrity = integrity
        self.shm_ring_bytes = int(shm_ring_bytes)
        self.failover = bool(failover)
        self.shm_retries = int(shm_retries)
        self.shm_dir = shm_dir

    @property
    def wants_integrity(self) -> bool:
        return self.integrity != "off"

    @property
    def wants_shm(self) -> bool:
        return self.transport in ("shm", "auto")

    def mode_label(self, groups: list[int]) -> str:
        """The transport label for tuning-cache keys: ``shm`` when shm
        is configured AND the topology has same-group peers to use it
        on, else ``tcp``.  Replicated inputs only (config + handout),
        so every rank computes the same label — schedule choice stays a
        collective decision."""
        if self.wants_shm and len(groups) != len(set(groups)):
            return "shm"
        return "tcp"


class LinkPacer:
    """Deterministic egress pacing for one link (``rabit_link_mbps``).

    A measurement/testing knob, not a production QoS feature: it
    emulates a constrained cross-host link budget (a 10-25 Gbps DCN
    hop) on hardware whose loopback runs at memory speed, so
    bandwidth-regime comparisons — the quantized wire codecs, schedule
    crossovers — measure the regime they actually target (TACCL's
    argument: match the algorithm to the link budget).  Token bucket
    per link direction: blocking sends sleep off their deficit
    (:meth:`pay`), pump sends gate on :meth:`ready` and overdraft by at
    most one send window (:meth:`debit`) — the average rate converges
    either way, and the receive side needs no pacing because every
    byte it sees was paced by its sender."""

    def __init__(self, mbps: float) -> None:
        self._rate = float(mbps) * 1e6          # bytes per second
        # ~5 ms of line rate of burst: big enough to amortize sleep
        # granularity, small enough that a 256KB chunk still paces.
        self._burst = max(self._rate * 0.005, 65536.0)
        self._tokens = self._burst
        self._last = time.monotonic()

    def _refill(self) -> None:
        now = time.monotonic()
        self._tokens = min(self._tokens
                           + (now - self._last) * self._rate, self._burst)
        self._last = now

    def ready(self) -> bool:
        """True when the bucket allows more egress; pump-mode sends
        gate on this and report no progress otherwise."""
        self._refill()
        return self._tokens > 0.0

    def debit(self, n: int) -> None:
        """Charge ``n`` sent bytes without blocking (pump paths; the
        bucket may overdraft by one send window)."""
        self._refill()
        self._tokens -= n

    def pay(self, n: int) -> None:
        """Charge ``n`` sent bytes and sleep off any deficit (blocking
        send paths)."""
        self.debit(n)
        if self._tokens < 0.0:
            time.sleep(-self._tokens / self._rate)


#: poll masks: errors/hangups surface as "readable" so the caller's
#: next read turns them into a typed LinkError (POLLNVAL covers a fd
#: closed out from under a racing pump).
_POLL_RD = select.POLLIN | select.POLLERR | select.POLLHUP | select.POLLNVAL


def wait_readable_writable(rlist, wlist, timeout: Optional[float]
                           ) -> tuple[list, list]:
    """One bounded readiness wait over objects with ``fileno()`` —
    ``select.poll``, NOT ``select.select``: link fds in an fd-heavy
    host process routinely exceed FD_SETSIZE, and the transport layer
    must degrade to a LinkError, never a ValueError crash (same
    rationale as the tracker's serve loop).  Not an epoll selector
    either: shm waits call this once per 2 ms slice, and a poll object
    costs no kernel fd and no per-call register/close syscalls.
    Returns (readable, writable)."""
    poller = select.poll()
    by_fd: dict = {}
    for obj in rlist:
        fd = obj.fileno()
        if fd < 0:
            raise ValueError(f"wait on closed fd ({fd})")
        by_fd[fd] = obj
        ev = _POLL_RD
        if obj in wlist:
            ev |= select.POLLOUT
        poller.register(fd, ev)
    for obj in wlist:
        fd = obj.fileno()
        if fd < 0:
            raise ValueError(f"wait on closed fd ({fd})")
        if fd not in by_fd:
            by_fd[fd] = obj
            poller.register(fd, select.POLLOUT | select.POLLERR
                            | select.POLLHUP | select.POLLNVAL)
    # Ceil to whole ms: a sub-ms slice must sleep, not busy-poll.
    ms = None if timeout is None else max(0, math.ceil(timeout * 1000))
    readable, writable = [], []
    for fd, ev in poller.poll(ms):
        obj = by_fd[fd]
        err = ev & (select.POLLERR | select.POLLHUP | select.POLLNVAL)
        if obj in rlist and (ev & select.POLLIN or err):
            readable.append(obj)
        if obj in wlist and (ev & select.POLLOUT or err):
            writable.append(obj)
    return readable, writable



def setup_stream_socket(sock: socket.socket, timeout: Optional[float],
                        sock_buf: int) -> socket.socket:
    """The ONE socket-setup helper every TCP link creation path runs —
    first wiring, recovery re-dials after a chaos reset, and shm→tcp
    failover re-dials alike — so ``rabit_sock_buf`` and the latency
    options can never silently miss a re-created link.  TCP_NODELAY
    (small consensus words must not wait on Nagle), the engine's link
    IO timeout, and SO_SNDBUF/SO_RCVBUF when ``rabit_sock_buf`` asks
    (both directions; the kernel doubles the value for bookkeeping).
    """
    sock.settimeout(timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    if sock_buf > 0:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, sock_buf)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, sock_buf)
    return sock


def advance_iov(bufs: list, n: int) -> None:
    """Consume ``n`` sent bytes from the head of a scatter-gather
    buffer list in place (the partial-write bookkeeping shared by every
    vectored send path)."""
    while bufs and n >= len(bufs[0]):
        n -= len(bufs[0])
        bufs.pop(0)
    if bufs and n:
        bufs[0] = bufs[0][n:]


def flatten_parts(parts) -> list:
    """Normalize a part list to non-empty byte memoryviews."""
    return [m for m in (memoryview(p).cast("B") for p in parts) if len(m)]


class Link:
    """One established engine↔peer byte channel.

    Byte-STREAM semantics on both sides (like a TCP socket): send
    boundaries are invisible to the receiver, so every engine pump and
    every schedule's chunking composes with any transport.  All methods
    raise :class:`LinkError` (with ``err.link = self``) on peer
    failure; blocking calls honor the engine's link IO timeout.

    Two operating modes:

    * **blocking** — ``sendall``/``sendv``/``recv_exact`` for the tree
      and sequential paths;
    * **pump** — bracketed by ``pump_begin``/``pump_end``, the
      non-blocking ``poll_sendv``/``poll_recv`` primitives plus
      ``rx_pending``/``tx_pending``/``fileno`` that the generic
      multi-link pumps (:mod:`rabit_tpu.transport.pump`) multiplex
      over.  ``rx_pending()`` must be True only when ``poll_recv``
      WILL make progress without new wire bytes, or the pump would
      busy-spin; ``needs_poll()`` marks transports whose readiness a
      plain ``select`` cannot fully see (shm rings), bounding the
      pump's wait slices.
    """

    kind = "?"
    peer = -1

    # -- blocking ------------------------------------------------------
    def sendall(self, data) -> None:
        raise NotImplementedError

    def sendv(self, parts) -> None:
        raise NotImplementedError

    def recv_exact(self, nbytes: int, into=None):
        raise NotImplementedError

    # -- pump ----------------------------------------------------------
    def pump_begin(self) -> None:
        pass

    def pump_end(self) -> None:
        pass

    def pump_abort(self) -> None:
        """Exception-path pump exit: restore the blocking state but
        DROP any claimed-but-unsent framed tx backlog instead of
        flushing it.  The op is aborted and recovery rewires every link
        from scratch (engine ``_close_links`` + ``_reconnect_links``),
        so a flush here could only block — up to the full link timeout
        — on a peer that is itself aborting, delaying the LinkError the
        recovery path is waiting on.  Must never raise."""

    def poll_sendv(self, bufs: list) -> bool:
        """Non-blocking send attempt from ``bufs`` (mutated in place as
        payload is claimed).  True iff any progress was made."""
        raise NotImplementedError

    #: set by ``poll_recv``: True when the call moved RAW wire bytes
    #: even if it produced no plaintext yet (an integrity frame
    #: arriving in pieces) — the pumps re-arm their idle timeout on it,
    #: so a slowly-but-continuously delivering link never times out
    #: mid-frame.
    wire_progress = False

    def poll_recv(self, mv) -> int:
        """Non-blocking receive into ``mv``; bytes produced (0 = would
        block).  Must update ``wire_progress``."""
        raise NotImplementedError

    def rx_pending(self) -> bool:
        return False

    def tx_pending(self) -> bool:
        return False

    def needs_poll(self) -> bool:
        return False

    def drain_wakeups(self) -> None:
        """Consume queued doorbell bytes (shm); no-op elsewhere."""

    def arm_wait(self, rx: bool) -> None:
        """Advertise an imminent blocking wait for data (``rx``) or
        space (``not rx``) so the peer knows a wakeup is wanted (shm
        waiter flags); no-op elsewhere.  Callers must re-check
        readiness after arming and ``disarm_wait`` afterwards."""

    def disarm_wait(self, rx: bool) -> None:
        pass

    def fileno(self) -> int:
        raise NotImplementedError

    # -- lifecycle -----------------------------------------------------
    def healthy(self) -> bool:
        """Cheap liveness probe: False once the peer is known dead or
        the channel is structurally broken (closed fd, bad ring magic).
        Never blocks."""
        return True

    def close(self) -> None:
        raise NotImplementedError

    # -- shared raise helper -------------------------------------------
    def _fail(self, msg: str, cause: Optional[BaseException] = None,
              cls=LinkError):
        err = cls(msg)
        err.link = self
        if cause is not None:
            raise err from cause
        raise err
