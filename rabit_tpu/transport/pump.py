"""Transport-generic multi-link progress pumps.

The engine's deadlock-sensitive concurrent IO patterns — full-duplex
ring exchange and the tree's multi-child drain — used to be select
loops hardwired to sockets.  They live here now, written against the
:class:`~rabit_tpu.transport.base.Link` pump interface so a ring step
between two shm peers, two TCP peers, or one of each runs the same
loop: poll every involved link, and only when NOTHING progressed wait
on the links' fds.  Shm links bound the wait to a short slice
(``needs_poll``): their ring state is not fully visible to ``select``,
so the pump re-polls at millisecond granularity as the lost-wakeup
safety net while the doorbell fd provides the common-case wakeup.

Byte streams are unchanged from the inline loops: payload is consumed
in arrival order per link, send windows are whatever the kernel (or
ring) accepts, and the timeout is an IDLE bound — it re-arms on every
byte of progress, exactly like the per-select timeout it replaces.
"""
from __future__ import annotations

import time
from typing import Optional

from rabit_tpu.transport.base import (Link, LinkError, flatten_parts,
                                      wait_readable_writable)
from rabit_tpu.transport.shm import WAIT_SLICE_SEC


def _timeout_error(links: list[Link], msg: str) -> LinkError:
    """Build the idle-timeout error, health-probing the stalled links
    first: a link that is structurally dead (ctrl EOF, lost ring
    magic) gets the blame — with ``err.link`` attribution, so the
    engine's failover hook fires — instead of an anonymous timeout."""
    for link in links:
        try:
            ok = link.healthy()
        except (OSError, ValueError):
            ok = False
        if not ok:
            err = LinkError(f"{msg} (link to rank {link.peer} failed "
                            f"its health probe)")
            err.link = link
            return err
    return LinkError(msg)


def _wait(rlinks: list[Link], wlinks: list[Link],
          deadline: Optional[float], timeout_msg: str) -> None:
    """Block until some link is plausibly ready (or a slice passes).
    Raises LinkError once the idle deadline expires."""
    now = time.monotonic()
    if deadline is not None and now >= deadline:
        raise _timeout_error(rlinks + wlinks, timeout_msg)
    bounded = any(link.needs_poll() for link in rlinks) \
        or any(link.needs_poll() for link in wlinks)
    # Shm write-waits watch the doorbell fd for READABLE wakeup bytes
    # (the reader signals freed space on the same channel).
    rlist = list(rlinks) + [lk for lk in wlinks
                            if lk.needs_poll() and lk not in rlinks]
    wlist = [lk for lk in wlinks if not lk.needs_poll()]
    wait_sec = None if deadline is None else max(deadline - now, 0.0)
    if bounded:
        wait_sec = WAIT_SLICE_SEC if wait_sec is None \
            else min(wait_sec, WAIT_SLICE_SEC)
    if not rlist and not wlist:
        return
    # Waiter flags first, readiness re-check second (the shm sleep
    # protocol: the peer rings only for an advertised sleeper, and it
    # may have acted between our poll and the arm).
    for link in rlinks:
        link.arm_wait(rx=True)
    for link in wlinks:
        link.arm_wait(rx=False)
    try:
        for link in rlinks:
            if link.rx_pending():
                return
        try:
            readable, writable = wait_readable_writable(rlist, wlist,
                                                        wait_sec)
        except (OSError, ValueError) as e:
            raise LinkError(f"{timeout_msg.split(':')[0]}: wait "
                            f"failed: {e}") from e
        if deadline is not None and not bounded \
                and not readable and not writable:
            # select blocked the full remaining idle budget, no event
            raise _timeout_error(rlinks + wlinks, timeout_msg)
        for link in rlist:
            link.drain_wakeups()
    finally:
        for link in rlinks:
            link.disarm_wait(rx=True)
        for link in wlinks:
            link.disarm_wait(rx=False)


def _end_all(begun: list[Link], suppress: bool) -> None:
    """Restore EVERY entered link's blocking state.  ``suppress`` means
    a real error already aborted the pump: the links ABORT — framed tx
    backlog dropped, never flushed — because recovery rewires every
    link from scratch and a blocking flush to a peer that is itself
    stuck in the failed collective would delay the in-flight LinkError
    by up to the full link timeout.  On the success path pump_end
    flushes, and the first flush failure propagates (after every link
    was still restored)."""
    if suppress:
        for link in begun:
            link.pump_abort()
        return
    flush_err: Optional[LinkError] = None
    for link in begun:
        try:
            link.pump_end()
        except LinkError as e:
            flush_err = flush_err if flush_err is not None else e
    if flush_err is not None:
        raise flush_err


def exchange(slink: Link, send_parts: list, rlink: Link,
             recv_parts: list, timeout: Optional[float],
             what: str = "exchange") -> None:
    """Full-duplex: stream ``send_parts`` to one link while filling
    ``recv_parts`` from another (possibly the same link — the halving
    schedule pairs both directions on one peer).  Vectored on the send
    side; receive buffers fill strictly in order."""
    sbufs = flatten_parts(send_parts)
    rbufs = flatten_parts(recv_parts)
    links = [slink] if slink is rlink else [slink, rlink]
    deadline = None if timeout is None else time.monotonic() + timeout
    begun: list[Link] = []
    try:
        for link in links:
            link.pump_begin()  # raises LinkError on a dead fd
            begun.append(link)
        while sbufs or rbufs or slink.tx_pending():
            progress = False
            if rbufs:
                n = rlink.poll_recv(rbufs[0])
                if n:
                    progress = True
                    rbufs[0] = rbufs[0][n:]
                    if not len(rbufs[0]):
                        rbufs.pop(0)
                elif rlink.wire_progress:
                    # Raw bytes of an incomplete integrity frame moved:
                    # the link is alive and delivering — re-arm the
                    # idle timeout even though no plaintext surfaced.
                    progress = True
            if sbufs or slink.tx_pending():
                progress |= slink.poll_sendv(sbufs)
            if progress:
                if timeout is not None:
                    deadline = time.monotonic() + timeout  # idle re-arm
            else:
                _wait([rlink] if rbufs else [],
                      [slink] if sbufs or slink.tx_pending() else [],
                      deadline, f"{what}: timed out")
    except BaseException:
        _end_all(begun, suppress=True)
        raise
    _end_all(begun, suppress=False)


def recv_all(links: list[Link], nbytes: int, bufs: list,
             timeout: Optional[float],
             timeout_msg: str = "tree recv: timed out on children"
             ) -> None:
    """Fill ``bufs[i][:nbytes]`` from ``links[i]``, draining every link
    concurrently (bytes are consumed in arrival order across links, so
    one slow peer no longer serializes its siblings).  Callers merge in
    deterministic rank order afterwards — reduction order unchanged."""
    got = [0] * len(links)
    pending = set(range(len(links)))
    deadline = None if timeout is None else time.monotonic() + timeout
    begun: list[Link] = []
    try:
        for link in links:
            link.pump_begin()  # raises LinkError on a dead fd
            begun.append(link)
        while pending:
            progress = False
            for i in list(pending):
                n = links[i].poll_recv(bufs[i][got[i]:nbytes])
                if n:
                    got[i] += n
                    progress = True
                    if got[i] == nbytes:
                        pending.discard(i)
                elif links[i].wire_progress:
                    progress = True  # mid-frame raw bytes: link alive
            if pending and not progress:
                _wait([links[i] for i in pending], [], deadline,
                      timeout_msg)
            elif progress and timeout is not None:
                deadline = time.monotonic() + timeout  # idle re-arm
    except BaseException:
        _end_all(begun, suppress=True)
        raise
    _end_all(begun, suppress=False)
