"""Transport-generic multi-link progress pumps.

The engine's deadlock-sensitive concurrent IO patterns — full-duplex
ring exchange and the tree's multi-child drain — used to be select
loops hardwired to sockets.  They live here now, written against the
:class:`~rabit_tpu.transport.base.Link` pump interface so a ring step
between two shm peers, two TCP peers, or one of each runs the same
loop: poll every involved link, and only when NOTHING progressed wait
on the links' fds.  Shm links bound the wait to a short slice
(``needs_poll``): their ring state is not fully visible to ``select``,
so the pump re-polls at millisecond granularity as the lost-wakeup
safety net while the doorbell fd provides the common-case wakeup.

Byte streams are unchanged from the inline loops: payload is consumed
in arrival order per link, send windows are whatever the kernel (or
ring) accepts, and the timeout is an IDLE bound — it re-arms on every
byte of progress, exactly like the per-select timeout it replaces.
"""
from __future__ import annotations

import time
from typing import Optional

from rabit_tpu.transport.base import (Link, LinkError, flatten_parts,
                                      wait_readable_writable)
from rabit_tpu.transport.shm import WAIT_SLICE_SEC


def _timeout_error(links: list[Link], msg: str) -> LinkError:
    """Build the idle-timeout error, health-probing the stalled links
    first: a link that is structurally dead (ctrl EOF, lost ring
    magic) gets the blame — with ``err.link`` attribution, so the
    engine's failover hook fires — instead of an anonymous timeout."""
    for link in links:
        try:
            ok = link.healthy()
        except (OSError, ValueError):
            ok = False
        if not ok:
            err = LinkError(f"{msg} (link to rank {link.peer} failed "
                            f"its health probe)")
            err.link = link
            return err
    return LinkError(msg)


def _wait(rlinks: list[Link], wlinks: list[Link],
          deadline: Optional[float], timeout_msg: str) -> None:
    """Block until some link is plausibly ready (or a slice passes).
    Raises LinkError once the idle deadline expires."""
    now = time.monotonic()
    if deadline is not None and now >= deadline:
        raise _timeout_error(rlinks + wlinks, timeout_msg)
    bounded = any(link.needs_poll() for link in rlinks) \
        or any(link.needs_poll() for link in wlinks)
    # Shm write-waits watch the doorbell fd for READABLE wakeup bytes
    # (the reader signals freed space on the same channel).
    rlist = list(rlinks) + [lk for lk in wlinks
                            if lk.needs_poll() and lk not in rlinks]
    wlist = [lk for lk in wlinks if not lk.needs_poll()]
    wait_sec = None if deadline is None else max(deadline - now, 0.0)
    if bounded:
        wait_sec = WAIT_SLICE_SEC if wait_sec is None \
            else min(wait_sec, WAIT_SLICE_SEC)
    if not rlist and not wlist:
        return
    # Waiter flags first, readiness re-check second (the shm sleep
    # protocol: the peer rings only for an advertised sleeper, and it
    # may have acted between our poll and the arm).
    for link in rlinks:
        link.arm_wait(rx=True)
    for link in wlinks:
        link.arm_wait(rx=False)
    try:
        for link in rlinks:
            if link.rx_pending():
                return
        try:
            readable, writable = wait_readable_writable(rlist, wlist,
                                                        wait_sec)
        except (OSError, ValueError) as e:
            raise LinkError(f"{timeout_msg.split(':')[0]}: wait "
                            f"failed: {e}") from e
        if deadline is not None and not bounded \
                and not readable and not writable:
            # select blocked the full remaining idle budget, no event
            raise _timeout_error(rlinks + wlinks, timeout_msg)
        for link in rlist:
            link.drain_wakeups()
    finally:
        for link in rlinks:
            link.disarm_wait(rx=True)
        for link in wlinks:
            link.disarm_wait(rx=False)


def _end_all(begun: list[Link], suppress: bool) -> None:
    """Restore EVERY entered link's blocking state.  ``suppress`` means
    a real error already aborted the pump: the links ABORT — framed tx
    backlog dropped, never flushed — because recovery rewires every
    link from scratch and a blocking flush to a peer that is itself
    stuck in the failed collective would delay the in-flight LinkError
    by up to the full link timeout.  On the success path pump_end
    flushes, and the first flush failure propagates (after every link
    was still restored)."""
    if suppress:
        for link in begun:
            link.pump_abort()
        return
    flush_err: Optional[LinkError] = None
    for link in begun:
        try:
            link.pump_end()
        except LinkError as e:
            flush_err = flush_err if flush_err is not None else e
    if flush_err is not None:
        raise flush_err


class HopPipeline:
    """Depth-N window of in-flight chunk exchanges over one (send, recv)
    link pair — the transport half of the engine's hop pipeline
    (doc/performance.md "Hop pipelining").

    Where :func:`exchange` runs ONE full-duplex transfer to completion,
    a HopPipeline keeps several consecutive chunk exchanges of the same
    hop in flight at once: ``push()`` enqueues a chunk's send/recv
    buffers (starting its IO opportunistically), ``pop()`` blocks until
    the OLDEST pushed chunk has fully completed and returns its ``meta``
    — so the caller can fold chunk k's bytes (``_wire_merge``, codec
    dequant/requant) while chunk k+1's wire IO progresses underneath.
    The per-link byte stream is IDENTICAL to the serial loop (same
    bytes, same order; only the compute/IO interleaving changes), so
    peers running different depths — including depth-1 serial peers —
    interoperate on the same collective.

    Completion of a chunk means: all its recv bytes arrived AND all its
    send bytes are actually on the wire — for framed links that claim
    payload by reference (``encode_frames`` never copies), the claimed
    backlog must also have drained (``tx_pending``), or a caller
    mutating the just-"sent" region (swing merges in place) could
    corrupt frames still pointing at it.

    Pump mode is held for the pipeline's lifetime; ``close()`` flushes
    and restores blocking state (success path), ``abort()`` drops any
    framed backlog and restores state (exception path, never raises).
    The idle timeout re-arms on every byte of progress, exactly like
    the one-shot pumps.
    """

    def __init__(self, slink: Link, rlink: Link,
                 timeout: Optional[float],
                 what: str = "hop pipeline") -> None:
        self._slink = slink
        self._rlink = rlink
        self._timeout = timeout
        self._what = what
        self._sq: list = []      # flattened pending send views (in order)
        self._rq: list = []      # flattened pending recv views (in order)
        self._bounds: list = []  # (send_end, recv_end, meta) per chunk
        self._senq = 0           # send bytes enqueued so far
        self._renq = 0           # recv bytes enqueued so far
        self._sent = 0           # send bytes claimed by the link
        self._recvd = 0          # recv bytes landed in caller buffers
        self._deadline = (None if timeout is None
                          else time.monotonic() + timeout)
        self._begun: list[Link] = []
        links = [slink] if slink is rlink else [slink, rlink]
        try:
            for link in links:
                link.pump_begin()  # raises LinkError on a dead fd
                self._begun.append(link)
        except BaseException:
            self.abort()
            raise

    @property
    def inflight(self) -> int:
        """Chunks pushed but not yet popped."""
        return len(self._bounds)

    def push(self, send_parts: list, recv_parts: list, meta=None) -> None:
        """Enqueue one chunk exchange (either side may be empty) and
        make opportunistic non-blocking progress."""
        sb = flatten_parts(send_parts)
        rb = flatten_parts(recv_parts)
        self._senq += sum(len(m) for m in sb)
        self._renq += sum(len(m) for m in rb)
        self._sq.extend(sb)
        self._rq.extend(rb)
        self._bounds.append((self._senq, self._renq, meta))
        self._advance(block=False)

    def pop(self):
        """Block until the OLDEST chunk completes; return its meta."""
        send_end, recv_end, meta = self._bounds[0]
        while not self._done(send_end, recv_end):
            self._advance(block=True)
        self._bounds.pop(0)
        return meta

    def _done(self, send_end: int, recv_end: int) -> bool:
        if self._recvd < recv_end or self._sent < send_end:
            return False
        # Framed links claim payload by REFERENCE (claim != on-wire),
        # and they claim the whole queue at once — so a chunk with send
        # bytes completes only once the claimed backlog drained, or a
        # caller mutating the region it just "sent" (swing merges in
        # place) could corrupt frames still pointing at it.
        return send_end == 0 or not self._slink.tx_pending()

    def _advance(self, block: bool) -> None:
        progress = False
        if self._rq:
            n = self._rlink.poll_recv(self._rq[0])
            if n:
                progress = True
                self._recvd += n
                self._rq[0] = self._rq[0][n:]
                if not len(self._rq[0]):
                    self._rq.pop(0)
            elif self._rlink.wire_progress:
                # Raw bytes of an incomplete integrity frame moved: the
                # link is delivering — re-arm the idle timeout.
                progress = True
        if self._sq or self._slink.tx_pending():
            left = sum(len(m) for m in self._sq)
            if self._slink.poll_sendv(self._sq):
                progress = True
            self._sent += left - sum(len(m) for m in self._sq)
        if progress:
            if self._timeout is not None:
                self._deadline = time.monotonic() + self._timeout
        elif block:
            _wait([self._rlink] if self._rq else [],
                  [self._slink]
                  if self._sq or self._slink.tx_pending() else [],
                  self._deadline, f"{self._what}: timed out")

    def close(self) -> None:
        """Success-path exit: flush framed backlog, restore blocking
        state on every entered link (first flush error propagates)."""
        begun, self._begun = self._begun, []
        _end_all(begun, suppress=False)

    def abort(self) -> None:
        """Exception-path exit: drop framed tx backlog, restore state.
        Never raises (recovery rewires the links from scratch)."""
        begun, self._begun = self._begun, []
        _end_all(begun, suppress=True)


def exchange(slink: Link, send_parts: list, rlink: Link,
             recv_parts: list, timeout: Optional[float],
             what: str = "exchange") -> None:
    """Full-duplex: stream ``send_parts`` to one link while filling
    ``recv_parts`` from another (possibly the same link — the halving
    schedule pairs both directions on one peer).  Vectored on the send
    side; receive buffers fill strictly in order."""
    sbufs = flatten_parts(send_parts)
    rbufs = flatten_parts(recv_parts)
    links = [slink] if slink is rlink else [slink, rlink]
    deadline = None if timeout is None else time.monotonic() + timeout
    begun: list[Link] = []
    try:
        for link in links:
            link.pump_begin()  # raises LinkError on a dead fd
            begun.append(link)
        while sbufs or rbufs or slink.tx_pending():
            progress = False
            if rbufs:
                n = rlink.poll_recv(rbufs[0])
                if n:
                    progress = True
                    rbufs[0] = rbufs[0][n:]
                    if not len(rbufs[0]):
                        rbufs.pop(0)
                elif rlink.wire_progress:
                    # Raw bytes of an incomplete integrity frame moved:
                    # the link is alive and delivering — re-arm the
                    # idle timeout even though no plaintext surfaced.
                    progress = True
            if sbufs or slink.tx_pending():
                progress |= slink.poll_sendv(sbufs)
            if progress:
                if timeout is not None:
                    deadline = time.monotonic() + timeout  # idle re-arm
            else:
                _wait([rlink] if rbufs else [],
                      [slink] if sbufs or slink.tx_pending() else [],
                      deadline, f"{what}: timed out")
    except BaseException:
        _end_all(begun, suppress=True)
        raise
    _end_all(begun, suppress=False)


def recv_all(links: list[Link], nbytes: int, bufs: list,
             timeout: Optional[float],
             timeout_msg: str = "tree recv: timed out on children"
             ) -> None:
    """Fill ``bufs[i][:nbytes]`` from ``links[i]``, draining every link
    concurrently (bytes are consumed in arrival order across links, so
    one slow peer no longer serializes its siblings).  Callers merge in
    deterministic rank order afterwards — reduction order unchanged."""
    got = [0] * len(links)
    pending = set(range(len(links)))
    deadline = None if timeout is None else time.monotonic() + timeout
    begun: list[Link] = []
    try:
        for link in links:
            link.pump_begin()  # raises LinkError on a dead fd
            begun.append(link)
        while pending:
            progress = False
            for i in list(pending):
                n = links[i].poll_recv(bufs[i][got[i]:nbytes])
                if n:
                    got[i] += n
                    progress = True
                    if got[i] == nbytes:
                        pending.discard(i)
                elif links[i].wire_progress:
                    progress = True  # mid-frame raw bytes: link alive
            if pending and not progress:
                _wait([links[i] for i in pending], [], deadline,
                      timeout_msg)
            elif progress and timeout is not None:
                deadline = time.monotonic() + timeout  # idle re-arm
    except BaseException:
        _end_all(begun, suppress=True)
        raise
    _end_all(begun, suppress=False)
