"""``ShmTransport``: same-host shared-memory ring-buffer links.

``doc/benchmarks.md`` records OpenMPI's shm BTL matching tuned TCP on
loopback — and small payloads are exactly the regime a serving workload
produces.  This transport moves the payload bytes through a pair of
mmap'd single-producer/single-consumer ring buffers (one per
direction) created in ``RABIT_SHM_DIR`` (default ``/dev/shm``), while
the already-established TCP connection is RETAINED as the **doorbell +
liveness channel**: wakeup bytes ride it when a side transitions the
ring from empty (or frees a full ring), and a peer death surfaces as
EOF on it — so a dead shm peer is detected exactly like a dead TCP
peer, never by spinning forever on a frozen ring.

Ring layout (offsets in the mmap): ``u32 magic | u32 pad | u64 size |
u64 head | u64 tail``, data at byte 64.  ``head``/``tail`` are
free-running u64 cursors (writer owns head, reader owns tail — one
8-byte aligned word each, so the two sides never write the same word).
The writer copies payload THEN publishes ``head``; the reader loads
``head`` THEN copies — on x86's total-store-order that is exactly the
SPSC publication contract.  Weaker orderings are additionally covered
by integrity framing's bounded re-read: a torn read re-checks the CRC
after a short pause before escalating.

Readiness: a reader first spins briefly on the ring (sub-µs wakeup on
the hot path — this is where the ≤64KB latency win over loopback TCP
comes from), then blocks on the doorbell fd in bounded slices, re-
polling the ring each slice so a lost wakeup costs milliseconds, not a
hang.  The engine's link IO timeout bounds the whole wait.

Chaos (``rabit_chaos``) tortures this transport with the same seeded
schedules as TCP at the dedicated ``shm`` site: write-side ``torn``
(a half-completed-looking ring write — permanent corruption, which
framing detects and failover survives), ``flip``/``corrupt`` read-side
bit damage (transient: the bounded re-read recovers it), ``doorbell``
(a swallowed wakeup — the bounded poll slices absorb it), ``stall``.
"""
from __future__ import annotations

import os
import mmap
import socket
import struct
import tempfile
import time
import zlib
from typing import Optional

from rabit_tpu.transport.base import (Events, IntegrityError, Link,
                                      NULL_EVENTS, advance_iov,
                                      flatten_parts,
                                      wait_readable_writable)
from rabit_tpu.transport.framing import (CRC_BYTES, FRAME_MAX, HDR_BYTES,
                                         PlainBuffer, encode_frames,
                                         frame_crc)

RING_MAGIC = 0x7AB175B1
RING_HDR_BYTES = 64
_OFF_MAGIC = 0
_OFF_SIZE = 8
_OFF_HEAD = 16
_OFF_TAIL = 24
# Sleep-advertisement flags (the SPSC waiter protocol): a side sets its
# flag before blocking on the doorbell fd, and the OTHER side rings
# only when the flag is up (then clears it).  Doorbell bytes are
# therefore bounded by actual sleeps — sending one per publish would
# slowly fill the ctrl socket buffer until every wakeup dropped and
# each hand-off degraded to a full poll slice.
_OFF_RWAIT = 32   # ring's reader is asleep waiting for data
_OFF_WWAIT = 36   # ring's writer is asleep waiting for space

#: ring polls before falling back to the doorbell fd (each poll is one
#: 8-byte read of the peer's cursor — cheap enough that the spin covers
#: the common same-host turnaround without burning a timeslice).  The
#: busy phase is short and the bulk of the budget is sched_yield polls:
#: on an oversubscribed box (more ranks than cores — every CI box) the
#: peer needs OUR timeslice to produce the bytes we are waiting for.
SPIN_POLLS = 64
YIELD_POLLS = 256
#: doorbell wait slice: the lost-wakeup safety net — every blocked side
#: re-polls the ring at least this often, so a swallowed doorbell byte
#: (chaos, or the benign publish/consume race) degrades latency by
#: milliseconds instead of hanging.
WAIT_SLICE_SEC = 0.002
#: pause between bounded re-reads of a CRC-failed frame (a torn-but-
#: completing write needs the writer's memcpy to finish, not long).
RETRY_PAUSE_SEC = 0.001

_DOORBELL = b"\x01"


def default_shm_dir() -> str:
    d = "/dev/shm"
    if os.path.isdir(d) and os.access(d, os.W_OK):
        return d
    return tempfile.gettempdir()


class ShmRing:
    """One single-writer single-reader mmap ring (byte stream)."""

    def __init__(self, mm: mmap.mmap, size: int, fileobj) -> None:
        self._mm = mm
        self._size = size
        self._file = fileobj          # kept open: the mapping's anchor
        self._buf = memoryview(mm)
        self._data = self._buf[RING_HDR_BYTES:RING_HDR_BYTES + size]

    @property
    def size(self) -> int:
        return self._size

    # -- construction --------------------------------------------------
    @classmethod
    def create(cls, dir_path: str, size: int) -> tuple["ShmRing", str]:
        fd, path = tempfile.mkstemp(prefix="rabit-shm-", dir=dir_path)
        f = os.fdopen(fd, "r+b")
        f.truncate(RING_HDR_BYTES + size)
        mm = mmap.mmap(f.fileno(), RING_HDR_BYTES + size)
        struct.pack_into("<IIQQQ", mm, 0, RING_MAGIC, 0, size, 0, 0)
        return cls(mm, size, f), path

    @classmethod
    def attach(cls, path: str) -> "ShmRing":
        f = open(path, "r+b")
        try:
            mm = mmap.mmap(f.fileno(), 0)
        except (OSError, ValueError):
            f.close()
            raise
        magic, _pad, size = struct.unpack_from("<IIQ", mm, 0)
        if magic != RING_MAGIC or len(mm) != RING_HDR_BYTES + size:
            mm.close()
            f.close()
            raise OSError(f"not a rabit shm ring: {path}")
        return cls(mm, size, f)

    # -- cursors -------------------------------------------------------
    @property
    def head(self) -> int:
        return struct.unpack_from("<Q", self._buf, _OFF_HEAD)[0]

    @property
    def tail(self) -> int:
        return struct.unpack_from("<Q", self._buf, _OFF_TAIL)[0]

    def avail(self) -> int:
        return self.head - self.tail

    def space(self) -> int:
        return self._size - (self.head - self.tail)

    def magic_ok(self) -> bool:
        return struct.unpack_from("<I", self._buf, _OFF_MAGIC)[0] \
            == RING_MAGIC

    # -- waiter flags ---------------------------------------------------
    def set_reader_waiting(self, v: int) -> None:
        struct.pack_into("<I", self._mm, _OFF_RWAIT, v)

    @property
    def reader_waiting(self) -> int:
        return struct.unpack_from("<I", self._buf, _OFF_RWAIT)[0]

    def set_writer_waiting(self, v: int) -> None:
        struct.pack_into("<I", self._mm, _OFF_WWAIT, v)

    @property
    def writer_waiting(self) -> int:
        return struct.unpack_from("<I", self._buf, _OFF_WWAIT)[0]

    # -- writer side ---------------------------------------------------
    def write(self, mv, corrupt=None) -> int:
        """Copy what fits, publish head AFTER the copy, return bytes
        taken (0 when full).  ``corrupt(tail_pos, nbytes)`` (the chaos
        torn-write hook) runs BETWEEN the copy and the publish: the
        damage is in place before the reader can possibly see the
        bytes, so an injected torn write provably lands — damaging
        after publish would race a spinning reader and silently vanish.
        """
        head = self.head
        n = min(self._size - (head - self.tail), len(mv))
        if n <= 0:
            return 0
        pos = head % self._size
        self._copy_in(pos, mv[:n])
        if corrupt is not None:
            corrupt(pos, n)
        struct.pack_into("<Q", self._mm, _OFF_HEAD, head + n)
        return n

    def _copy_in(self, pos: int, src) -> None:
        first = min(len(src), self._size - pos)
        self._data[pos:pos + first] = src[:first]
        if first < len(src):
            self._data[:len(src) - first] = src[first:]

    def damage_tail(self, pos: int, n: int, nback: int, mutate) -> None:
        """Damage the last ``nback`` of the ``n`` bytes at ring
        position ``pos`` (unpublished — see :meth:`write`): the torn
        write the chaos layer models.  The writer's own payload buffer
        stays pristine."""
        nback = max(1, min(nback, n))
        start = (pos + n - nback) % self._size
        tmp = bytearray(nback)
        self._peek_abs(start, tmp)
        mutate(tmp)
        self._copy_in(start, tmp)

    # -- reader side ---------------------------------------------------
    def read(self, mv) -> int:
        n = min(self.avail(), len(mv))
        if n <= 0:
            return 0
        self.peek(0, mv[:n])
        self.advance(n)
        return n

    def peek(self, off: int, mv) -> None:
        """Copy ``len(mv)`` bytes at ``tail + off`` WITHOUT consuming
        (the framed reader verifies before it advances, which is what
        makes the bounded corrupted-frame re-read possible at all)."""
        self._peek_abs((self.tail + off) % self._size, mv)

    def _peek_abs(self, pos: int, mv) -> None:
        first = min(len(mv), self._size - pos)
        mv[:first] = self._data[pos:pos + first]
        if first < len(mv):
            mv[first:] = self._data[:len(mv) - first]

    def advance(self, n: int) -> None:
        struct.pack_into("<Q", self._mm, _OFF_TAIL, self.tail + n)

    # -- lifecycle -----------------------------------------------------
    def close(self) -> None:
        if self._mm is None:
            return  # idempotent: teardown paths may overlap
        self._data.release()
        self._buf.release()
        try:
            self._mm.close()
            self._file.close()
        except (OSError, ValueError):
            pass  # fd already invalid: the mapping dies with us anyway
        self._mm = None


class ShmLink(Link):
    kind = "shm"

    def __init__(self, ctrl: socket.socket, peer: int,
                 tx: ShmRing, rx: ShmRing, timeout: Optional[float],
                 events: Events = NULL_EVENTS, frames: bool = False,
                 plan=None, retries: int = 3) -> None:
        self._ctrl = ctrl
        self._ctrl.setblocking(False)
        self.peer = peer
        self._tx = tx
        self._rx = rx
        self._timeout = timeout
        self._ev = events
        self._frames = frames
        self._plan = plan
        self._retries = retries
        # A frame is verified WHOLE from the ring, so it must always be
        # able to become fully resident: cap frames well under the ring
        # capacity (both ends negotiated the same ring size, so sender
        # cap and receiver expectation agree).
        self._frame_max = min(FRAME_MAX, max(tx.size // 4, 1024))
        # Acceptance bound: generous (an honest peer caps at its ring/4
        # by the same formula) but strictly ring-fitting, so a corrupt
        # length can never name a frame that could not become resident
        # — that would stall to the timeout instead of detecting.
        self._rx_frame_cap = min(FRAME_MAX, rx.size - 8)
        self._plain = PlainBuffer()   # verified framed payload
        self._pend: list = []         # pump-mode tx backlog
        self._rx_seen_head = 0        # wire_progress watermark
        self._dead = False
        self._suppress_doorbell = False  # chaos 'doorbell' fault armed

    # ------------------------------------------------------------------
    # doorbell channel
    # ------------------------------------------------------------------
    def _doorbell(self) -> None:
        if self._suppress_doorbell:
            # chaos: swallow exactly one wakeup — the peer's bounded
            # poll slices must absorb it (never a hang).
            self._suppress_doorbell = False
            return
        try:
            self._ctrl.send(_DOORBELL)
        except (BlockingIOError, InterruptedError):
            pass  # ctrl buffer full = wakeups already queued at the peer
        except OSError:
            # Peer teardown races a wakeup; the reader path will turn
            # the dead channel into a typed LinkError.
            self._dead = True

    def drain_wakeups(self) -> None:
        while True:
            try:
                got = self._ctrl.recv(4096)
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                self._dead = True
                self._fail(f"shm doorbell to rank {self.peer} failed: {e}",
                           e)
            if got == b"":
                self._dead = True
                self._fail(f"rank {self.peer} closed the link")

    def arm_wait(self, rx: bool) -> None:
        """Advertise an imminent sleep (waiter-flag protocol): the peer
        rings the doorbell on its next publish/consume iff the flag is
        up, so wakeup bytes are bounded by actual sleeps.  Callers MUST
        re-check readiness AFTER arming (the peer may have acted in
        between — the residual store/load race costs one bounded
        slice, nothing more)."""
        if rx:
            self._rx.set_reader_waiting(1)
        else:
            self._tx.set_writer_waiting(1)

    def disarm_wait(self, rx: bool) -> None:
        if rx:
            self._rx.set_reader_waiting(0)
        else:
            self._tx.set_writer_waiting(0)

    def _wait(self, deadline: Optional[float], what: str,
              ready=None, rx: bool = True) -> None:
        """One bounded wait for ring progress: drain wakeups, arm the
        waiter flag, RE-CHECK the ring, then sleep on the doorbell fd
        for at most a slice.  The re-check after arm+drain is
        load-bearing: the peer may have published between our last ring
        poll and the arm — sleeping then would turn its (unsent or
        already-drained) wakeup into a dead slice on every hot
        hand-off."""
        self.drain_wakeups()
        self.arm_wait(rx)
        try:
            if ready is not None and ready():
                return
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    self._fail(f"{what} rank {self.peer} timed out (shm)")
                slice_sec = min(WAIT_SLICE_SEC, left)
            else:
                slice_sec = WAIT_SLICE_SEC
            try:
                # poll, not select.select: the ctrl fd may exceed
                # FD_SETSIZE in an fd-heavy host process (base.py
                # wait_readable_writable has the full rationale).
                wait_readable_writable([self._ctrl], [], slice_sec)
            except (OSError, ValueError) as e:
                self._dead = True
                self._fail(f"shm doorbell to rank {self.peer} failed: {e}",
                           e)
        finally:
            self.disarm_wait(rx)

    def _deadline(self) -> Optional[float]:
        return (None if self._timeout is None
                else time.monotonic() + self._timeout)

    # ------------------------------------------------------------------
    # chaos
    # ------------------------------------------------------------------
    #: fault kinds the two shm touchpoint directions may draw — a
    #: write fault is PERMANENT ring damage (detection must escalate,
    #: ultimately to shm→tcp failover), a read fault is TRANSIENT
    #: (the bounded re-read of the pristine ring bytes recovers it)
    _TX_KINDS = ("torn", "doorbell", "stall")
    _RX_KINDS = ("flip", "corrupt")

    def _chaos_tx(self):
        """Write-side fault consult, taken right before a ring write
        that WILL land bytes: returns the pre-publish ``corrupt`` hook
        for a fired ``torn`` (ShmRing.write applies it before the
        reader can see the bytes), arms a swallowed wakeup for
        ``doorbell``; stalls were served inside the plan."""
        if self._plan is None:
            return None
        kind = self._plan.shm(self._TX_KINDS)
        if kind == "doorbell":
            self._suppress_doorbell = True
        elif kind == "torn":
            return lambda pos, n: self._tx.damage_tail(
                pos, n, 8, lambda mv: self._plan.mutate(mv, "torn"))
        return None

    def _chaos_rx(self, view) -> None:
        """Read-side fault consult on a PEEKED (unconsumed) copy:
        flip/corrupt damage the copy only, so the bounded re-read of
        the pristine ring bytes recovers — the transparent-retry path
        under test."""
        if self._plan is None or len(view) == 0:
            return
        kind = self._plan.shm(self._RX_KINDS)
        if kind in ("flip", "corrupt"):
            self._plan.mutate(view, kind)

    # ------------------------------------------------------------------
    # blocking send
    # ------------------------------------------------------------------
    def sendall(self, data) -> None:
        self.sendv([data])

    def sendv(self, parts) -> None:
        bufs = flatten_parts(parts)
        if self._frames:
            bufs = encode_frames(bufs, self._frame_max)
        deadline = self._deadline()
        for mv in bufs:
            off = 0
            while off < len(mv):
                n = self._ring_write(mv[off:])
                if n:
                    off += n
                    deadline = self._deadline()  # idle re-arm
                elif not self._spin(lambda: self._tx.space() > 0):
                    self._wait(deadline, "send to",
                               ready=lambda: self._tx.space() > 0,
                               rx=False)

    def _ring_write(self, mv) -> int:
        # Consult only when bytes will actually move (same consult
        # sequence as the post-write consult it replaces: one per
        # successful ring write), so seeded schedules stay comparable.
        corrupt = None
        if len(mv) and self._tx.space() > 0:
            corrupt = self._chaos_tx()
        n = self._tx.write(mv, corrupt)
        if n:
            if self._tx.reader_waiting:
                self._tx.set_reader_waiting(0)
                self._doorbell()
        return n

    def _note_consumed(self) -> None:
        """Ring bytes were just consumed: wake a space-starved writer
        that advertised a sleep."""
        if self._rx.writer_waiting:
            self._rx.set_writer_waiting(0)
            self._doorbell()

    # ------------------------------------------------------------------
    # blocking recv
    # ------------------------------------------------------------------
    def recv_exact(self, nbytes: int, into=None):
        buf = into if into is not None else memoryview(bytearray(nbytes))
        deadline = self._deadline()
        got = 0
        while got < nbytes:
            n = self._recv_some(buf[got:nbytes])
            if n:
                got += n
                deadline = self._deadline()  # idle re-arm
            elif not self._spin_rx():
                self._wait(deadline, "recv from", ready=self.rx_pending)
        return buf

    @staticmethod
    def _spin(ready) -> bool:
        """Brief busy poll, then yield-polls, before blocking on the
        doorbell: the same-host hot path wakes in well under a
        microsecond, and the yield phase hands the timeslice to the
        peer instead of burning it on an oversubscribed box."""
        for _ in range(SPIN_POLLS):
            if ready():
                return True
        for _ in range(YIELD_POLLS):
            os.sched_yield()
            if ready():
                return True
        return False

    def _spin_rx(self) -> bool:
        # rx_pending, not bare avail: a PARTIALLY resident frame must
        # not satisfy the spin (a hot loop would burn the timeslice the
        # writer needs to finish publishing it).
        return self._spin(self.rx_pending)

    def _recv_some(self, mv) -> int:
        """One non-waiting receive attempt into ``mv``."""
        if not self._frames:
            n = self._rx.read(mv)
            if n:
                self._note_consumed()
            return n
        n = self._plain.take(mv)
        if n:
            return n
        if not self._decode_frame():
            return 0
        return self._plain.take(mv)

    def _frame_ready(self):
        """Length of the next frame when it is FULLY resident in the
        ring, else None (the one header peek serves both the readiness
        check and the decode)."""
        avail = self._rx.avail()
        if avail < HDR_BYTES:
            return None
        hdr = bytearray(HDR_BYTES)
        self._rx.peek(0, hdr)
        (ln,) = struct.unpack("<I", hdr)
        if not 0 < ln <= self._rx_frame_cap:
            self._ev.counter("integrity.detected")
            self._ev.event("integrity", phase="detected", peer=self.peer,
                           transport=self.kind,
                           detail=f"impossible frame length {ln}")
            self._detect(f"impossible frame length {ln}")
        if avail < HDR_BYTES + ln + CRC_BYTES:
            return None
        return ln

    def _decode_frame(self) -> bool:
        """Verify-then-consume one frame from the ring; False when no
        complete frame is resident yet.  The CRC is checked on a PEEKED
        copy, so a mismatch can be re-read (bounded) before the typed
        escalation — a torn-but-completing write or a transiently
        damaged read recovers transparently."""
        ln = self._frame_ready()
        if ln is None:
            return False
        body = bytearray(ln + CRC_BYTES)
        detected = False
        for attempt in range(self._retries + 1):
            self._rx.peek(HDR_BYTES, body)
            payload = memoryview(body)[:ln]
            if attempt == 0:
                self._chaos_rx(payload)
            (want,) = struct.unpack_from("<I", body, ln)
            if frame_crc(payload) == want:
                if detected:
                    self._ev.counter("integrity.recovered")
                    self._ev.event("integrity", phase="recovered",
                                   peer=self.peer, transport=self.kind,
                                   retries=attempt)
                self._rx.advance(HDR_BYTES + ln + CRC_BYTES)
                self._note_consumed()
                self._plain.push(payload)
                payload.release()
                return True
            payload.release()
            if not detected:
                detected = True
                self._ev.counter("integrity.detected")
                self._ev.event("integrity", phase="detected",
                               peer=self.peer, transport=self.kind,
                               detail=f"frame crc mismatch (len {ln})")
            if attempt < self._retries:
                self._ev.counter("integrity.retry")
                time.sleep(RETRY_PAUSE_SEC)
        self._detect(f"frame crc mismatch persisted across "
                     f"{self._retries} re-read(s) (len {ln})")

    def _detect(self, what: str):
        self._fail(f"wire corruption from rank {self.peer} detected "
                   f"(shm): {what}", cls=IntegrityError)

    # ------------------------------------------------------------------
    # pump
    # ------------------------------------------------------------------
    def pump_begin(self) -> None:
        pass

    def pump_end(self) -> None:
        if self._pend:
            deadline = self._deadline()
            for mv in self._pend:
                off = 0
                while off < len(mv):
                    n = self._ring_write(mv[off:])
                    if n:
                        off += n
                    else:
                        self._wait(deadline, "send to",
                                   ready=lambda: self._tx.space() > 0,
                                   rx=False)
            self._pend = []

    def pump_abort(self) -> None:
        self._pend = []

    def poll_sendv(self, bufs: list) -> bool:
        if self._frames:
            if not self._pend and bufs:
                self._pend = encode_frames(bufs, self._frame_max)
                del bufs[:]
            send_bufs = self._pend
        else:
            send_bufs = bufs
        if not send_bufs:
            return False
        n = self._ring_write(send_bufs[0])
        if n:
            advance_iov(send_bufs, n)
            return True
        return False

    def poll_recv(self, mv) -> int:
        # wire_progress: did the peer PUBLISH since our last poll?  A
        # large integrity frame arrives in several ring writes; the
        # pumps re-arm their idle timeout on this even while no
        # complete frame (hence no plaintext) is ready yet.
        head = self._rx.head
        self.wire_progress = head != self._rx_seen_head
        self._rx_seen_head = head
        n = self._recv_some(mv)
        if n == 0:
            self.drain_wakeups()  # surfaces peer death as LinkError
        return n

    def rx_pending(self) -> bool:
        if self._frames:
            return (self._plain.pending()
                    or self._frame_ready() is not None)
        return self._rx.avail() > 0

    def tx_pending(self) -> bool:
        return bool(self._pend)

    def needs_poll(self) -> bool:
        return True

    def fileno(self) -> int:
        return self._ctrl.fileno()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def settimeout(self, t) -> None:
        self._timeout = t

    def healthy(self) -> bool:
        if self._dead:
            return False
        if not (self._tx.magic_ok() and self._rx.magic_ok()):
            return False
        try:
            self.drain_wakeups()
        except OSError:
            return False
        return not self._dead

    def close(self) -> None:
        try:
            self._ctrl.close()
        except OSError:
            pass
        self._tx.close()
        self._rx.close()
