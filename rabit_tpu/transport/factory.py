"""Link construction: handshake, feature negotiation, transport pick.

The engine dials/accepts raw TCP sockets exactly as before (retry and
backoff stay engine-side); the factory turns each established socket
into a :class:`~rabit_tpu.transport.base.Link`:

* **Default config** sends the CLASSIC handshake — ``u32 MAGIC, u32
  rank`` each way — so the wire is byte-identical to every previous
  release and to old peers.
* A worker with transport features configured (``rabit_transport``
  shm/auto toward a same-host-group peer, or any
  ``rabit_wire_integrity``) opens with ``XMAGIC`` instead and appends
  one feature string ("crc32c,shm:1048576").  A this-release acceptor
  MIRRORS whichever magic it received and answers with its OWN offer
  (possibly empty), and each feature activates only in the
  INTERSECTION of the two offers — so a featured worker and a
  default-config worker interoperate in both directions, each link
  degrading to the common subset.  (A featured worker dialing a
  pre-feature BINARY fails the peer's magic check — enabling the
  opt-in knobs requires the world upgraded, which is the documented
  contract.)
* An agreed shm link keeps the TCP connection as doorbell + liveness
  channel: the dialer creates the two ring files (one per direction),
  sends their paths, and the acceptor maps them — a cross-host peer
  that cannot open the paths answers 0 and BOTH sides fall back to
  TCP, which is also the self-verifying same-host check (the
  host-group handout nominates candidates; the filesystem proves it).
  Ring files are unlinked as soon as both sides hold the mapping, so a
  crashed worker leaks nothing.

**Failover** bookkeeping also lives here: the engine records a peer
whose shm link failed (health probe, ring fault, integrity escalation)
in :attr:`LinkFactory.denied`, and every later negotiation with that
peer simply never offers shm again — the recover rendezvous that the
LinkError already triggered re-dials the link as plain TCP, mid-job.
"""
from __future__ import annotations

import os
import socket
from typing import Optional

from rabit_tpu.tracker import protocol as P
from rabit_tpu.transport import shm as shm_mod
from rabit_tpu.transport.base import (Events, Link, LinkPacer, NULL_EVENTS,
                                      SHM_RING_MIN, TransportConfig,
                                      setup_stream_socket)
from rabit_tpu.transport.tcp import TcpLink
from rabit_tpu.utils.checks import check

#: feature-negotiating link hello (the classic hello is protocol.MAGIC)
XMAGIC = 0x7AB17912
#: feature-string length cap (a handshake read, so bounded like all of
#: them — see protocol.MAX_HELLO_STR for the rationale)
MAX_FEATURES = 256


def _parse_offer(raw: str) -> dict:
    """``"crc32c,shm:1048576"`` → ``{"crc": "crc32c", "shm": 1048576}``.
    Unknown tokens are IGNORED (forward compatibility: a newer peer may
    offer features we cannot parse — the intersection simply excludes
    them)."""
    out: dict = {}
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok in ("crc32", "crc32c"):
            out["crc"] = tok
        elif tok.startswith("shm:"):
            try:
                out["shm"] = int(tok[4:])
            except ValueError:
                continue
    return out


class LinkFactory:
    """Per-engine link builder; topology and denial state mutate across
    rendezvous rounds, the config never does."""

    def __init__(self, cfg: TransportConfig, *,
                 timeout: Optional[float], sock_buf: int = 0,
                 chaos=None, wrap=None, events: Events = NULL_EVENTS,
                 log=None) -> None:
        self.cfg = cfg
        self.timeout = timeout
        self.sock_buf = sock_buf
        self.chaos = chaos           # ChaosPlan for shm-site faults
        self.wrap = wrap             # chaos socket wrapper (tcp data path)
        self.events = events
        self.log = log
        self.rank = 0
        self.groups: list[int] = []
        #: peers whose shm link failed — permanently TCP for this
        #: process life (transport.failover.* counters mark each entry)
        self.denied: set[int] = set()
        self._shm_dir: Optional[str] = None

    # ------------------------------------------------------------------
    # topology / feature state
    # ------------------------------------------------------------------
    def set_topology(self, rank: int, groups: list[int]) -> None:
        self.rank = int(rank)
        self.groups = list(groups)

    def shm_dir(self) -> str:
        if self._shm_dir is None:
            self._shm_dir = self.cfg.shm_dir or shm_mod.default_shm_dir()
        return self._shm_dir

    def same_group(self, peer: int) -> bool:
        g = self.groups
        return (0 <= self.rank < len(g) and 0 <= peer < len(g)
                and g[self.rank] == g[peer])

    def _offer(self, peer: int) -> dict:
        feats: dict = {}
        if self.cfg.wants_integrity:
            feats["crc"] = self.cfg.integrity
        if (self.cfg.wants_shm and self.same_group(peer)
                and peer not in self.denied):
            feats["shm"] = self.cfg.shm_ring_bytes
        return feats

    @staticmethod
    def _offer_str(feats: dict) -> str:
        toks = []
        if "crc" in feats:
            toks.append(feats["crc"])
        if "shm" in feats:
            toks.append(f"shm:{feats['shm']}")
        return ",".join(toks)

    # ------------------------------------------------------------------
    # handshake
    # ------------------------------------------------------------------
    def dial(self, sock: socket.socket, peer: int) -> Link:
        """Upgrade an engine-dialed socket into a Link (dialer side of
        the link handshake)."""
        setup_stream_socket(sock, self.timeout, self.sock_buf)
        feats = self._offer(peer)
        if not feats:
            # Classic bytes: identical to every pre-transport release.
            P.send_u32(sock, P.MAGIC)
            P.send_u32(sock, self.rank)
            check(P.recv_u32(sock) == P.MAGIC, "link handshake: bad magic")
            check(P.recv_u32(sock) == peer, "link handshake: rank mismatch")
            return self._tcp_link(sock, peer, frames=False)
        P.send_u32(sock, XMAGIC)
        P.send_u32(sock, self.rank)
        P.send_str(sock, self._offer_str(feats))
        check(P.recv_u32(sock) == XMAGIC, "link handshake: bad magic "
              "(peer does not speak transport negotiation — upgrade it "
              "or clear rabit_transport/rabit_wire_integrity)")
        check(P.recv_u32(sock) == peer, "link handshake: rank mismatch")
        theirs = _parse_offer(P.recv_str(sock, max_len=MAX_FEATURES))
        frames = self._crc_agreed(peer, feats, theirs)
        if "shm" in feats and "shm" in theirs:
            link = self._dial_shm(sock, peer, theirs, frames)
            if link is not None:
                return link
        return self._tcp_link(sock, peer, frames=frames)

    def accept(self, sock: socket.socket) -> tuple[Link, int]:
        """Acceptor side; returns ``(link, peer_rank)``."""
        setup_stream_socket(sock, self.timeout, self.sock_buf)
        magic = P.recv_u32(sock)
        if magic == P.MAGIC:
            peer = P.recv_u32(sock)
            P.send_u32(sock, P.MAGIC)
            P.send_u32(sock, self.rank)
            return self._tcp_link(sock, peer, frames=False), peer
        check(magic == XMAGIC, "link handshake: bad magic")
        peer = P.recv_u32(sock)
        theirs = _parse_offer(P.recv_str(sock, max_len=MAX_FEATURES))
        feats = self._offer(peer)
        P.send_u32(sock, XMAGIC)
        P.send_u32(sock, self.rank)
        P.send_str(sock, self._offer_str(feats))
        frames = self._crc_agreed(peer, feats, theirs)
        if "shm" in feats and "shm" in theirs:
            link = self._accept_shm(sock, peer, frames)
            if link is not None:
                return link, peer
        return self._tcp_link(sock, peer, frames=frames), peer

    def _crc_agreed(self, peer: int, mine: dict, theirs: dict) -> bool:
        """Integrity activates only when both ends offered the SAME
        mode name: the two names are interchangeable today (both the
        stdlib CRC-32), but the moment ``crc32c`` becomes a real
        Castagnoli a mixed-mode link would reject every frame as
        corruption — so a mismatch deactivates framing (loudly) rather
        than arming a time bomb."""
        if "crc" not in mine or "crc" not in theirs:
            return False
        if mine["crc"] == theirs["crc"]:
            return True
        if self.log is not None:
            self.log.warn(
                "integrity mode mismatch with rank %d (%s vs %s): "
                "framing DISABLED on this link — align "
                "rabit_wire_integrity across the world", peer,
                mine["crc"], theirs["crc"])
        return False

    # ------------------------------------------------------------------
    # shm upgrade (ctrl socket = the handshake socket, kept open)
    # ------------------------------------------------------------------
    def _dial_shm(self, sock: socket.socket, peer: int, theirs: dict,
                  frames: bool) -> Optional[Link]:
        size = min(self.cfg.shm_ring_bytes, int(theirs["shm"]))
        if size < SHM_RING_MIN:
            # A skewed or garbled peer offer (tiny/zero/negative ring)
            # must take the clean tcp-fallback path: a degenerate ring
            # whose every write returns 0 would stall each send to the
            # link timeout instead of ever moving a byte.
            P.send_str(sock, "")   # protocol: empty path = dialer abort
            self._fallback(peer, "bad_ring_offer")
            return None
        try:
            tx, path_tx = shm_mod.ShmRing.create(self.shm_dir(), size)
            rx, path_rx = shm_mod.ShmRing.create(self.shm_dir(), size)
        except OSError as e:
            if self.log is not None:
                self.log.warn("shm ring creation failed (%s); link to "
                              "rank %d stays tcp", e, peer)
            P.send_str(sock, "")   # protocol: empty path = dialer abort
            self._fallback(peer, "create_failed")
            return None
        try:
            P.send_str(sock, path_tx)
            P.send_str(sock, path_rx)
            ok = P.recv_u32(sock)
        except BaseException:
            # The peer died mid-exchange: tmpfs ring files surviving a
            # failed handshake would leak RAM on every chaos/failure
            # re-dial, so unlink + unmap before the error propagates.
            self._unlink_rings(path_tx, path_rx)
            tx.close()
            rx.close()
            raise
        # Both sides hold (or refused) the mapping now: the names are
        # no longer needed either way — a crash leaks nothing.
        self._unlink_rings(path_tx, path_rx)
        if not ok:
            tx.close()
            rx.close()
            self._fallback(peer, "peer_attach_failed")
            return None
        return self._shm_link(sock, peer, tx, rx, frames)

    @staticmethod
    def _unlink_rings(*paths: str) -> None:
        for path in paths:
            try:
                os.unlink(path)
            except OSError:
                pass

    def _accept_shm(self, sock: socket.socket, peer: int,
                    frames: bool) -> Optional[Link]:
        path_tx_of_dialer = P.recv_str(sock, max_len=4096)
        if not path_tx_of_dialer:
            self._fallback(peer, "peer_create_failed")
            return None
        path_rx_of_dialer = P.recv_str(sock, max_len=4096)
        try:
            # Dialer's tx ring is our rx, and vice versa.
            rx = shm_mod.ShmRing.attach(path_tx_of_dialer)
        except OSError:
            P.send_u32(sock, 0)
            self._fallback(peer, "attach_failed")
            return None
        try:
            tx = shm_mod.ShmRing.attach(path_rx_of_dialer)
        except OSError:
            rx.close()
            P.send_u32(sock, 0)
            self._fallback(peer, "attach_failed")
            return None
        if rx.size < SHM_RING_MIN or tx.size < SHM_RING_MIN:
            # The dialer (version skew, corrupt offer) built rings our
            # release considers degenerate: refuse the attach so BOTH
            # sides land on tcp instead of a ring that can stall.
            tx.close()
            rx.close()
            P.send_u32(sock, 0)
            self._fallback(peer, "bad_ring_size")
            return None
        try:
            P.send_u32(sock, 1)
        except OSError:
            # Dialer died before our ack: drop the mappings (it owns
            # the unlink; our fds were the only thing pinning the RAM).
            tx.close()
            rx.close()
            raise
        return self._shm_link(sock, peer, tx, rx, frames)

    # ------------------------------------------------------------------
    # link construction + bookkeeping
    # ------------------------------------------------------------------
    def _tcp_link(self, sock: socket.socket, peer: int,
                  frames: bool) -> Link:
        data_sock = self.wrap(sock, peer) if self.wrap is not None \
            else sock
        self.events.counter("transport.links.tcp")
        # One pacer per link (rabit_link_mbps, bench/test knob): each
        # direction of each peer pair paces independently, like per-NIC
        # egress queues on a real constrained hop.
        pacer = (LinkPacer(self.cfg.link_mbps)
                 if self.cfg.link_mbps > 0 else None)
        return TcpLink(data_sock, peer, self.timeout, self.events,
                       frames=frames, pacer=pacer)

    def _shm_link(self, sock: socket.socket, peer: int, tx, rx,
                  frames: bool) -> Link:
        self.events.counter("transport.links.shm")
        self.events.event("transport", phase="shm_link", peer=peer,
                          frames=frames)
        return shm_mod.ShmLink(sock, peer, tx, rx, self.timeout,
                               self.events, frames=frames,
                               plan=self.chaos,
                               retries=self.cfg.shm_retries)

    def _fallback(self, peer: int, why: str) -> None:
        self.events.counter("transport.shm.fallback")
        self.events.event("transport", phase="shm_fallback", peer=peer,
                          reason=why)
        if self.cfg.transport == "shm" and self.log is not None:
            self.log.info("rabit_transport=shm: link to rank %d fell "
                          "back to tcp (%s)", peer, why)

    def deny(self, peer: int) -> bool:
        """Mark a peer's shm link failed; True when newly denied (the
        caller emits the failover telemetry exactly once)."""
        if not self.cfg.failover or peer in self.denied:
            return False
        self.denied.add(peer)
        return True
