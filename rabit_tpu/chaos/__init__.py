"""rabit_tpu.chaos — deterministic network fault injection.

The paper's reliability claim is only as strong as the failure modes the
test harness can produce.  ``RABIT_MOCK`` kill-points exit cleanly at op
boundaries; this subsystem injects the faults real networks produce —
refused and timed-out connects, mid-stream connection resets, short
read/write splits, EINTR, and bounded latency stalls — at every socket
touchpoint of the pure-Python engines (tracker connects, peer link
dials/accepts, established-link IO in the exchange paths and the async
progress pump).  The schedule is **seeded and deterministic**: the same
seed driven through the same call sequence reproduces the same
injection log bit for bit, so a chaos failure found in CI replays
locally from one string.

Enable with the ``rabit_chaos`` parameter / ``RABIT_CHAOS`` env (same
spirit as the ``RABIT_MOCK`` tuple format):

    RABIT_CHAOS = "<seed>:<rule>[;<rule>...]"
    rule        = <kind>[@<site>]=<rate>[*<limit>]
                | stallms=<ms> | budget=<n> | partialmax=<bytes>
                | ranks=<r0|r1|...>

Kinds: ``refuse`` (ECONNREFUSED), ``cto`` (connect timeout), ``reset``
(mid-stream RST), ``partial`` (short read/write split), ``stall``
(bounded sleep), ``eintr`` (interrupted syscall), ``flip`` (one wire
bit XOR'd in a transferred byte) and ``corrupt`` (one transferred byte
overwritten) — the corruption kinds integrity framing
(``rabit_wire_integrity``) exists to catch — plus the shm-transport
kinds ``torn`` (a half-completed-looking ring write: permanent
corruption that must escalate to shm→tcp failover) and ``doorbell``
(one swallowed ring wakeup: the reader's bounded poll must absorb it).
Sites: ``tracker`` and ``connect`` (connect-stage kinds), ``accept``,
``io`` (established TCP links; the default for
reset/partial/stall/eintr/flip/corrupt) and ``shm`` (ring
touchpoints: torn/doorbell/flip/corrupt/stall — both transports are
tortured by the same seeds).  The ``accept`` site admits only
``stall`` — an accept has no retry path to absorb a refusal (the
dialing peer owns the retry).  Control-plane link sites (sharded
tracker, doc/fault_tolerance.md "Sharded tracker"): ``hello`` (the
worker→tracker registration exchange), ``hb`` (the heartbeat channel)
and ``scrape`` (the shard→aggregator obs scrape) admit only
``reset``/``stall``, must be named explicitly (no kind defaults to
them), and are direction-filtered like the shm kinds — each fires on
the side whose detector the pairing gates read.  The replicated
directory adds ``dir_register`` / ``dir_poll`` / ``dir_resolve``
(same reset/stall vocabulary, consulted in ``DirectoryClient`` where
the bounded-retry / ride-the-cache detectors live).  The serving wire
adds ``serve_req`` / ``serve_reply`` (same vocabulary, consulted in
the loadgen sender where the reconnect-retry / deadline detectors
live — doc/serving.md "Chaos on the serving wire").
``rate`` is a per-touchpoint probability in [0, 1]; ``*limit`` caps a
rule's total fires; ``budget`` (default 256) caps the whole plan;
``ranks`` scopes the plan to specific worker identities (task ids —
equal to ranks under ``RABIT_TRACKER_PIN_RANKS=1``).  Example — one
mid-stream reset and flaky rendezvous dials, reproducible under seed 7:

    RABIT_CHAOS="7:reset@io=0.01*1;refuse@connect=0.3*4;partial@io=0.05"

See doc/fault_tolerance.md "Chaos testing" for the fault/recovery
pairing the obs timeline records, and ``tools/soak.py --chaos`` for the
randomized soak gate.  The chaos layer lives entirely in the Python
engines (pysocket/pyrobust and the XLA engine's host control plane);
the native C++ engine does not see it.
"""
from __future__ import annotations

import os
from typing import Callable, Optional

from rabit_tpu.chaos.plan import (CONNECT_KINDS, CONNECT_SITES,
                                  DEFAULT_BUDGET, DEFAULT_PARTIAL_MAX,
                                  DEFAULT_STALL_MS, DIRECTORY_SITES,
                                  IO_KINDS, KIND_CORRUPT,
                                  KIND_CTO, KIND_DOORBELL, KIND_EINTR,
                                  KIND_FLIP, KIND_PARTIAL, KIND_REFUSE,
                                  KIND_RESET, KIND_STALL, KIND_TORN, KINDS,
                                  SERVE_SITES, SHM_KINDS, SITE_ACCEPT,
                                  SITE_CONNECT,
                                  SITE_DIR_POLL, SITE_DIR_REGISTER,
                                  SITE_DIR_RESOLVE,
                                  SITE_HB, SITE_HELLO, SITE_IO, SITE_SCRAPE,
                                  SITE_SERVE_REPLY, SITE_SERVE_REQ,
                                  SITE_SHM, SITE_TRACKER, SITES,
                                  TRACKER_LINK_KINDS, TRACKER_LINK_SITES,
                                  ChaosPlan, ChaosRule, parse_plan)
from rabit_tpu.chaos.sock import ChaosSocket


def configure(params: dict, identity: str,
              on_inject: Optional[Callable[[str, str, int, str],
                                           None]] = None
              ) -> Optional[ChaosPlan]:
    """Resolve ``rabit_chaos`` / ``RABIT_CHAOS`` into a compiled
    :class:`ChaosPlan`, or None when chaos is off (the common case —
    the engines then skip every touchpoint with one ``is None`` check).
    Called from the Python engines' ``init()``."""
    spec = params.get("rabit_chaos")
    if spec is None:
        spec = os.environ.get("RABIT_CHAOS", "")
    spec = str(spec).strip()
    if not spec:
        return None
    return parse_plan(spec, identity, on_inject=on_inject)


__all__ = [
    "ChaosPlan", "ChaosRule", "ChaosSocket", "configure", "parse_plan",
    "KINDS", "SITES", "CONNECT_KINDS", "IO_KINDS", "SHM_KINDS",
    "CONNECT_SITES",
    "KIND_REFUSE", "KIND_CTO", "KIND_RESET", "KIND_PARTIAL", "KIND_STALL",
    "KIND_EINTR", "KIND_FLIP", "KIND_CORRUPT", "KIND_TORN",
    "KIND_DOORBELL", "SITE_TRACKER", "SITE_CONNECT", "SITE_ACCEPT",
    "SITE_IO", "SITE_SHM", "SITE_HELLO", "SITE_HB", "SITE_SCRAPE",
    "SITE_DIR_REGISTER", "SITE_DIR_POLL", "SITE_DIR_RESOLVE",
    "SITE_SERVE_REQ", "SITE_SERVE_REPLY", "SERVE_SITES",
    "TRACKER_LINK_KINDS", "TRACKER_LINK_SITES", "DIRECTORY_SITES",
    "DEFAULT_BUDGET", "DEFAULT_STALL_MS", "DEFAULT_PARTIAL_MAX",
]
