"""The seeded fault plan: deterministic Bernoulli schedules per rule.

A :class:`ChaosPlan` is compiled from a ``seed:SPEC`` string (grammar in
:mod:`rabit_tpu.chaos`) and consulted at every socket touchpoint.  Each
rule keeps its own consult counter, and the fire/skip decision for
consult ``n`` is a pure function of ``(seed, identity, kind, site, n)``
— a CRC32 hash mapped to [0, 1) and compared against the rule's rate.
Nothing in the schedule depends on wall-clock time, thread interleaving
or the behaviour of other rules, so the same seed driven through the
same call sequence reproduces the same injection log bit for bit.

Every fired injection is appended to :attr:`ChaosPlan.log` (the
determinism contract pinned by ``tests/test_chaos.py``) and reported
through the plan's ``on_inject`` callback, which the engines route into
the telemetry subsystem (``chaos.injected.*`` counters + ``chaos``
trace events).
"""
from __future__ import annotations

import socket
import time
import zlib
from typing import Callable, Optional

from rabit_tpu.utils.checks import check, error

# Fault kinds (the wire failure modes real networks produce).
KIND_REFUSE = "refuse"    # connect: ECONNREFUSED (nobody listening yet)
KIND_CTO = "cto"          # connect: SYN timeout (host unreachable / dropped)
KIND_RESET = "reset"      # established link: mid-stream RST
KIND_PARTIAL = "partial"  # established link: short read/write split
KIND_STALL = "stall"      # bounded latency stall (silent slow peer)
KIND_EINTR = "eintr"      # signal-interrupted syscall (EINTR)
# Wire CORRUPTION kinds (the faults integrity framing exists to catch —
# doc/fault_tolerance.md "Transports, integrity & failover").  Applied
# at the receive boundary of a transfer, so an injection always lands
# in the byte stream the peer actually produced (a send-side flip could
# fall in the unsent remainder of a partial write and never reach the
# wire, breaking the injected↔detected pairing the gates assert).
KIND_FLIP = "flip"        # one bit XOR'd in one transferred byte
KIND_CORRUPT = "corrupt"  # one transferred byte overwritten
# Shm-transport-specific kinds (the failure modes a ring buffer adds):
KIND_TORN = "torn"        # write-side: a half-completed-looking ring
#                           write (several trailing bytes damaged) —
#                           PERMANENT corruption: detection must
#                           escalate to failover, never a silent pass
KIND_DOORBELL = "doorbell"  # write-side: one swallowed wakeup byte —
#                             the reader's bounded poll slices must
#                             absorb it (latency, never a hang)

CONNECT_KINDS = (KIND_REFUSE, KIND_CTO, KIND_STALL)
IO_KINDS = (KIND_RESET, KIND_PARTIAL, KIND_STALL, KIND_EINTR,
            KIND_FLIP, KIND_CORRUPT)
SHM_KINDS = (KIND_TORN, KIND_DOORBELL, KIND_FLIP, KIND_CORRUPT,
             KIND_STALL)
KINDS = (KIND_REFUSE, KIND_CTO, KIND_RESET, KIND_PARTIAL, KIND_STALL,
         KIND_EINTR, KIND_FLIP, KIND_CORRUPT, KIND_TORN, KIND_DOORBELL)

# Injection sites.  Connect-stage sites see only CONNECT_KINDS; the
# "io" site (established worker-worker TCP links) sees IO_KINDS; the
# "shm" site (shared-memory ring touchpoints) sees SHM_KINDS — both
# transports are tortured by the same seeded schedules.
SITE_TRACKER = "tracker"       # tracker command connects
SITE_CONNECT = "connect"       # peer link dials during rendezvous
SITE_ACCEPT = "accept"         # peer link accepts during rendezvous
SITE_IO = "io"                 # established link send/recv
SITE_SHM = "shm"               # shm ring writes/reads + doorbells
# Control-plane link sites (the sharded tracker's fault surface —
# doc/fault_tolerance.md "Sharded tracker").  Direction-filtered like
# the shm kinds: each site is consulted only on the side named here, so
# an injection always lands where its detector lives.
SITE_HELLO = "hello"           # worker→tracker registration exchange
SITE_HB = "hb"                 # worker→tracker heartbeat channel
SITE_SCRAPE = "scrape"         # shard→aggregator obs scrape
# Directory link sites (ISSUE 19: the replicated directory's fault
# surface).  Consulted client-side in DirectoryClient — a reset lands
# in the caller's existing retry/ride path (a shard's bounded register
# retry, a poll tick's failure count, a resolve riding the cached
# snapshot), so every injection pairs with a counted detection.
SITE_DIR_REGISTER = "dir_register"  # shard→directory registration
SITE_DIR_POLL = "dir_poll"          # shard→directory load report
SITE_DIR_RESOLVE = "dir_resolve"    # client→directory snapshot refresh
DIRECTORY_SITES = (SITE_DIR_REGISTER, SITE_DIR_POLL, SITE_DIR_RESOLVE)
# Serving wire sites (ISSUE 20: the prediction service's fault
# surface).  Consulted client-side in the loadgen sender — a reset
# lands in the sender's reconnect-and-retry path and a stall in its
# deadline budget, so every injection pairs with a counted detection
# in the same process that injected it.
SITE_SERVE_REQ = "serve_req"        # client→rank predict request send
SITE_SERVE_REPLY = "serve_reply"    # rank→client reply read
SERVE_SITES = (SITE_SERVE_REQ, SITE_SERVE_REPLY)
CONNECT_SITES = (SITE_TRACKER, SITE_CONNECT, SITE_ACCEPT)
TRACKER_LINK_SITES = (SITE_HELLO, SITE_HB, SITE_SCRAPE)
# Established control-plane links survive only bounded faults: a reset
# (the retry/failover paths must absorb it) or a stall (the deadline
# budgets must absorb it).  Connect-stage kinds already have their own
# site (tracker), and corruption is the data plane's problem.
TRACKER_LINK_KINDS = (KIND_RESET, KIND_STALL)
SITES = (CONNECT_SITES + (SITE_IO, SITE_SHM) + TRACKER_LINK_SITES
         + DIRECTORY_SITES + SERVE_SITES)

# Kinds without an explicit @site apply here.
_DEFAULT_SITES = {
    KIND_REFUSE: (SITE_CONNECT, SITE_TRACKER),
    KIND_CTO: (SITE_CONNECT, SITE_TRACKER),
    KIND_RESET: (SITE_IO,),
    KIND_PARTIAL: (SITE_IO,),
    KIND_STALL: (SITE_IO,),
    KIND_EINTR: (SITE_IO,),
    KIND_FLIP: (SITE_IO, SITE_SHM),
    KIND_CORRUPT: (SITE_IO, SITE_SHM),
    KIND_TORN: (SITE_SHM,),
    KIND_DOORBELL: (SITE_SHM,),
}

DEFAULT_BUDGET = 256      # total injections per process life
DEFAULT_STALL_MS = 50.0   # bounded stall duration
DEFAULT_PARTIAL_MAX = 7   # byte cap of a split read/write (odd on purpose)


class ChaosRule:
    """One ``kind@site=rate*limit`` rule with its own consult counter."""

    __slots__ = ("kind", "sites", "rate", "limit", "consults", "fired")

    def __init__(self, kind: str, sites: tuple[str, ...], rate: float,
                 limit: Optional[int]) -> None:
        self.kind = kind
        self.sites = sites
        self.rate = rate
        self.limit = limit      # None = bounded only by the global budget
        self.consults = 0
        self.fired = 0


class ChaosPlan:
    """Compiled fault plan for one worker process.

    ``identity`` is the worker's stable task id (known before the first
    rendezvous assigns a rank, and stable across restarts — under the
    local launcher it is the worker index, and with
    ``RABIT_TRACKER_PIN_RANKS=1`` it equals the rank).  ``on_inject``
    receives ``(kind, site, ordinal, detail)`` for every fired fault.
    """

    def __init__(self, seed: int, rules: list[ChaosRule], identity: str,
                 stall_ms: float = DEFAULT_STALL_MS,
                 budget: int = DEFAULT_BUDGET,
                 partial_max: int = DEFAULT_PARTIAL_MAX,
                 ranks: Optional[set[int]] = None,
                 on_inject: Optional[Callable[[str, str, int, str],
                                              None]] = None) -> None:
        self.seed = int(seed)
        self.identity = str(identity)
        self.stall_ms = float(stall_ms)
        self.budget = int(budget)
        self.partial_max = int(partial_max)
        self.on_inject = on_inject
        self.log: list[tuple[int, str, str, int]] = []  # (ord, kind, site, n)
        self.injected = 0
        self._rules = rules
        self._mutations = 0   # mutate() draw counter (see mutate)
        # Rank scoping: a plan whose ranks filter excludes this identity
        # is inert (parses, logs nothing, injects nothing).
        self.active = True
        if ranks is not None:
            try:
                me = int(self.identity)
            except ValueError:
                me = zlib.crc32(self.identity.encode())
            self.active = me in ranks

    # -- schedule ------------------------------------------------------
    def _draw(self, rule: ChaosRule, site: str) -> bool:
        """Deterministic Bernoulli: consult ``n`` of a rule fires iff
        H(seed, identity, kind, site, n) / 2^32 < rate."""
        rule.consults += 1
        key = (f"{self.seed}:{self.identity}:{rule.kind}:{site}:"
               f"{rule.consults}").encode()
        return (zlib.crc32(key) & 0xFFFFFFFF) / 4294967296.0 < rule.rate

    def _consult(self, site: str,
                 kinds: Optional[tuple[str, ...]] = None) -> Optional[str]:
        """One injection decision at ``site``; returns the fired kind or
        None.  Rules are evaluated in spec order; the first that fires
        wins (at most one fault per touchpoint).  ``kinds`` restricts
        which rules this touchpoint can draw (the shm transport's
        write and read touchpoints serve disjoint fault kinds — a
        write-side ``torn`` must never fire at a read, where it would
        degrade to a transient); per-rule consult counters keep the
        schedule deterministic either way."""
        if not self.active or self.injected >= self.budget:
            return None
        for rule in self._rules:
            if site not in rule.sites:
                continue
            if kinds is not None and rule.kind not in kinds:
                continue
            if rule.limit is not None and rule.fired >= rule.limit:
                continue
            if self._draw(rule, site):
                rule.fired += 1
                self.injected += 1
                self.log.append((len(self.log), rule.kind, site,
                                 rule.consults))
                if self.on_inject is not None:
                    self.on_inject(rule.kind, site, len(self.log) - 1,
                                   f"consult={rule.consults}")
                return rule.kind
        return None

    # -- touchpoints ---------------------------------------------------
    def connect(self, site: str) -> None:
        """Consult before a connect/accept syscall; raises the injected
        connect failure (or sleeps through an injected stall)."""
        kind = self._consult(site)
        if kind is None:
            return
        if kind == KIND_STALL:
            time.sleep(self.stall_ms / 1000.0)
            return
        if kind == KIND_REFUSE:
            raise ConnectionRefusedError(
                f"[chaos] injected connection refusal at {site}")
        if kind == KIND_CTO:
            raise socket.timeout(
                f"[chaos] injected connect timeout at {site}")

    def io(self, kinds: Optional[tuple[str, ...]] = None
           ) -> Optional[str]:
        """Consult before one established-link send/recv syscall.
        Returns the fired kind (the socket wrapper applies it) or None.
        Stalls are served here — the wrapper then proceeds with the
        real, now-delayed syscall.  ``kinds`` restricts what this
        touchpoint can draw: send-side consults exclude flip/corrupt
        (corruption manifests in RECEIVED bytes, so firing it at a
        send could vanish into an unsent remainder and break the
        injected↔detected pairing the integrity gates assert)."""
        kind = self._consult(SITE_IO, kinds)
        if kind == KIND_STALL:
            time.sleep(self.stall_ms / 1000.0)
            return None
        return kind

    def shm(self, kinds: Optional[tuple[str, ...]] = None
            ) -> Optional[str]:
        """Consult at one shm ring touchpoint (a completed ring write
        on the producer side, a frame decode on the consumer side —
        each passes the kinds it can apply, so write faults stay
        permanent and read faults stay transient).  Same contract as
        :meth:`io`: stalls served here, other kinds returned for the
        ShmLink to apply."""
        kind = self._consult(SITE_SHM, kinds)
        if kind == KIND_STALL:
            time.sleep(self.stall_ms / 1000.0)
            return None
        return kind

    def link(self, site: str,
             kinds: Optional[tuple[str, ...]] = None) -> Optional[str]:
        """Consult at one control-plane link touchpoint (the hello
        exchange, a heartbeat send, an aggregator scrape — each names
        its site, so rules stay direction-filtered).  Same contract as
        :meth:`io`: stalls are served here and return None; a reset is
        returned for the caller to apply as its link failure (the
        worker raises ``ConnectionResetError`` into its existing
        retry path, the aggregator counts a failed scrape).  Only
        ``TRACKER_LINK_KINDS`` can fire, and only for rules that named
        this site explicitly — control-plane rules never perturb the
        data-plane schedules (per-rule consult counters)."""
        kind = self._consult(site, kinds if kinds is not None
                             else TRACKER_LINK_KINDS)
        if kind == KIND_STALL:
            time.sleep(self.stall_ms / 1000.0)
            return None
        return kind

    def mutate(self, mv, kind: str) -> None:
        """Deterministically damage ``mv`` in place for a fired
        flip/corrupt/torn injection.  Position and bit ride the same
        hash family as the schedule itself (keyed by a dedicated
        mutation counter), so a replayed seed reproduces the identical
        damage whenever the transfer sizes line up.  XOR damage is
        never a no-op, so every fired corruption is a REAL corruption —
        the injected↔detected pairing gate depends on it."""
        n = len(mv)
        if n == 0:
            return
        self._mutations += 1
        h = zlib.crc32(f"{self.seed}:{self.identity}:mut:"
                       f"{self._mutations}".encode()) & 0xFFFFFFFF
        pos = h % n
        if kind == KIND_FLIP:
            mv[pos] ^= 1 << ((h >> 8) & 7)
        elif kind == KIND_CORRUPT:
            mv[pos] ^= ((h >> 8) & 0xFF) or 0xA5
        else:  # torn: damage from pos to the end (a memcpy cut short)
            for i in range(pos, n):
                mv[i] ^= 0xFF

    def summary(self) -> dict:
        """Per-rule fire counts (for logs and reproduce lines)."""
        return {f"{r.kind}@{'|'.join(r.sites)}": r.fired
                for r in self._rules}


def parse_plan(spec: str, identity: str,
               on_inject: Optional[Callable[[str, str, int, str],
                                            None]] = None) -> ChaosPlan:
    """Compile a ``seed:SPEC`` string (see the package docstring for the
    grammar) into a :class:`ChaosPlan`.  Malformed specs fail loudly —
    a chaos run with a silently-dropped rule would report vacuous green.
    """
    check(":" in spec, "rabit_chaos must be 'seed:SPEC', got %r", spec)
    seed_s, _, body = spec.partition(":")
    try:
        seed = int(seed_s)
    except ValueError:
        error("rabit_chaos seed must be an integer, got %r", seed_s)
    rules: list[ChaosRule] = []
    stall_ms = DEFAULT_STALL_MS
    budget = DEFAULT_BUDGET
    partial_max = DEFAULT_PARTIAL_MAX
    ranks: Optional[set[int]] = None
    for part in body.split(";"):
        part = part.strip()
        if not part:
            continue
        check("=" in part, "rabit_chaos rule %r: expected key=value", part)
        key, _, val = part.partition("=")
        key = key.strip()
        val = val.strip()
        if key == "stallms":
            stall_ms = float(val)
            check(stall_ms >= 0, "rabit_chaos: stallms must be >= 0")
            continue
        if key == "budget":
            budget = int(val)
            check(budget >= 0, "rabit_chaos: budget must be >= 0")
            continue
        if key == "partialmax":
            partial_max = int(val)
            check(partial_max >= 1, "rabit_chaos: partialmax must be >= 1")
            continue
        if key == "ranks":
            ranks = {int(r) for r in val.split("|") if r.strip() != ""}
            continue
        kind, _, site = key.partition("@")
        check(kind in KINDS, "rabit_chaos: unknown fault kind %r (one of "
              "%s)", kind, "/".join(KINDS))
        if site:
            check(site in SITES, "rabit_chaos: unknown site %r (one of "
                  "%s)", site, "/".join(SITES))
            if site == SITE_IO:
                allowed: tuple[str, ...] = IO_KINDS
            elif site == SITE_SHM:
                allowed = SHM_KINDS
            elif site == SITE_ACCEPT:
                # An accept has no retry path to absorb a refusal (the
                # dialing PEER owns the retry), so only stalls make a
                # survivable injection here.
                allowed = (KIND_STALL,)
            elif site in (TRACKER_LINK_SITES + DIRECTORY_SITES
                          + SERVE_SITES):
                allowed = TRACKER_LINK_KINDS
            else:
                allowed = CONNECT_KINDS
            check(kind in allowed, "rabit_chaos: kind %r cannot fire at "
                  "site %r", kind, site)
            sites: tuple[str, ...] = (site,)
        else:
            sites = _DEFAULT_SITES[kind]
        rate_s, _, limit_s = val.partition("*")
        try:
            rate = float(rate_s)
        except ValueError:
            error("rabit_chaos rule %r: rate %r is not a number",
                  part, rate_s)
        check(0.0 <= rate <= 1.0,
              "rabit_chaos rule %r: rate must be in [0, 1]", part)
        limit = None
        if limit_s:
            limit = int(limit_s)
            check(limit >= 0, "rabit_chaos rule %r: limit must be >= 0",
                  part)
        rules.append(ChaosRule(kind, sites, rate, limit))
    check(bool(rules), "rabit_chaos %r names no fault rules", spec)
    return ChaosPlan(seed, rules, identity, stall_ms=stall_ms,
                     budget=budget, partial_max=partial_max, ranks=ranks,
                     on_inject=on_inject)
