"""Fault-injecting socket wrapper for established worker-worker links.

:class:`ChaosSocket` interposes on exactly the syscalls the engine's IO
helpers use (``send``/``sendall``/``sendmsg``/``recv_into``), consults
the plan once per call, and applies the fired fault *at the syscall
boundary* — so the engine code above exercises its real partial-write
loops, EINTR classification and reset handling, not a simulation of
them:

* ``reset`` — the real socket is closed (the peer sees an actual
  EOF/RST on the wire) and ``ConnectionResetError`` is raised;
* ``partial`` — the transfer is capped to ``partial_max`` bytes, which
  splits TCP segments for the peer too (short reads on the far side);
* ``eintr`` — ``InterruptedError`` is raised *before any byte moves*
  (matching PEP 475 semantics: a syscall that transferred data never
  surfaces EINTR), so retry-from-the-top is always correct;
* ``stall`` — a bounded sleep inside the plan, then the real syscall.

Everything else (``fileno``, ``settimeout``, ``setblocking``,
``setsockopt``, ``close``, …) delegates to the wrapped socket, so
``select``/``selectors`` registration and link teardown work unchanged.
"""
from __future__ import annotations

import socket

from rabit_tpu.chaos.plan import (KIND_CORRUPT, KIND_EINTR, KIND_FLIP,
                                  KIND_PARTIAL, KIND_RESET, KIND_STALL,
                                  ChaosPlan)


class ChaosSocket:
    """A worker-worker link socket with the fault plan in its data path."""

    __slots__ = ("_sock", "_plan", "_peer", "_rx_damage")

    def __init__(self, sock: socket.socket, plan: ChaosPlan,
                 peer: int) -> None:
        self._sock = sock
        self._plan = plan
        self._peer = peer
        self._rx_damage: str | None = None  # fired flip/corrupt pending

    #: what each touchpoint direction may draw: corruption kinds fire
    #: ONLY at receives, where the damage provably lands in transferred
    #: bytes (a send-side flip could fall in the unsent tail of a
    #: partial write and vanish — breaking the injected↔detected
    #: pairing the integrity gates assert)
    _TX_KINDS = (KIND_RESET, KIND_PARTIAL, KIND_STALL, KIND_EINTR)

    def _io(self, kinds=None) -> int | None:
        """One plan consult; returns the byte cap of an injected partial
        transfer, None for a clean (or merely stalled) call, and raises
        for reset/EINTR injections.  A fired flip/corrupt is PARKED in
        ``_rx_damage`` until a receive lands bytes to damage (a
        non-blocking receive may fire the consult and then would-block;
        the damage stays armed for the next real bytes on this link)."""
        kind = self._plan.io(kinds)
        if kind is None:
            return None
        if kind == KIND_RESET:
            try:
                self._sock.close()
            except OSError:
                pass
            raise ConnectionResetError(
                f"[chaos] injected connection reset on link to rank "
                f"{self._peer}")
        if kind == KIND_EINTR:
            raise InterruptedError(
                f"[chaos] injected EINTR on link to rank {self._peer}")
        if kind == KIND_PARTIAL:
            return self._plan.partial_max
        if kind in (KIND_FLIP, KIND_CORRUPT):
            self._rx_damage = kind
        return None

    # -- intercepted syscalls ------------------------------------------
    def send(self, data, *flags) -> int:
        cap = self._io(self._TX_KINDS)
        if cap is not None:
            data = memoryview(data).cast("B")[:cap]
        return self._sock.send(data, *flags)

    def sendall(self, data, *flags) -> None:
        cap = self._io(self._TX_KINDS)
        if cap is None:
            return self._sock.sendall(data, *flags)
        mv = memoryview(data).cast("B")
        # A short first write, then the remainder: the caller's byte
        # stream is intact but the wire sees the split.
        sent = self._sock.send(mv[:cap], *flags)
        return self._sock.sendall(mv[sent:], *flags)

    def sendmsg(self, buffers, *rest) -> int:
        cap = self._io(self._TX_KINDS)
        if cap is None:
            return self._sock.sendmsg(buffers, *rest)
        bufs = list(buffers)
        if not bufs:
            return self._sock.sendmsg(bufs, *rest)
        return self._sock.send(memoryview(bufs[0]).cast("B")[:cap])

    def recv_into(self, buffer, nbytes: int = 0, *flags) -> int:
        cap = self._io()
        n = nbytes or len(buffer)
        if cap is not None:
            n = min(n, cap)
        got = self._sock.recv_into(buffer, n, *flags)
        if self._rx_damage is not None and got > 0:
            self._plan.mutate(memoryview(buffer).cast("B")[:got],
                              self._rx_damage)
            self._rx_damage = None
        return got

    # -- passthrough ---------------------------------------------------
    def __getattr__(self, name):
        return getattr(self._sock, name)

    def __repr__(self) -> str:  # aids debugging link dumps
        return f"<ChaosSocket peer={self._peer} {self._sock!r}>"
