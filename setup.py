"""Install hook: best-effort build of the compiled codec kernels.

``pip install .`` tries ``make -C rabit_tpu/native codec`` so a box
with a C toolchain gets librabit_codec.so (the fused block-scale hop
kernels, codec/kernel.py) baked into the wheel for free.  Any failure
— no make, no cc, a hermetic build sandbox — degrades to a stderr
warning and the pure-numpy reference, NEVER a failed install: the
runtime seam treats a missing library exactly the same way
(rabit_codec_impl=auto falls back with one obs-visible warning), so
the two layers agree that native is an opportunistic upgrade and
numpy is the contract.  ``rabit_codec_impl=native`` remains the loud
opt-in for deployments that must not silently run the slow path.
"""
import os
import subprocess
import sys

from setuptools import setup
from setuptools.command.build_py import build_py


class build_py_with_codec_kernels(build_py):
    def run(self):
        native = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "rabit_tpu", "native")
        try:
            subprocess.run(["make", "-C", native, "codec"], check=True)
        except Exception as exc:  # noqa: BLE001 — degrade, never fail
            print("setup.py: codec kernel build skipped "
                  f"({type(exc).__name__}: {exc}); the numpy reference "
                  "path will serve (rabit_codec_impl=auto falls back)",
                  file=sys.stderr)
        super().run()


setup(cmdclass={"build_py": build_py_with_codec_kernels})
