"""Microbench: k-means stats-pass formulations for ELL (padded sparse) data.

The round-3 probe established that the *densify-by-one-hot* algorithm is
VPU-bound: vectorised scatter costs ~2·nnz·d lane-ops per row however it
is written (doc/benchmarks.md "ELL densify bound").  This harness
measures algorithm changes, per VERDICT r3 item 2:

  scan        the shipped `_stats_fn` ELL scan pass (baseline)
  batched:H   two-level densify — split f = lo_idx·H + hi_idx, build the
              (nnz, hi) and weighted (nnz, lo) one-hots (VPU cost
              nnz·(hi+lo) per row instead of 2·nnz·d), then contract
              them on the MXU as a per-row batched matmul
  band:G:H    same two-level split, but G rows share one matmul: the
              weighted lo one-hot is laid out block-diagonally as
              (G·nnz, G·lo) so Lᵀ@H is a single well-tiled MXU matmul
              per group whose (G·lo, hi) output reshapes directly to
              (G, d) — G-fold FLOP inflation traded for MXU tiling
  gather:G:H  gather-based similarity (sim[r,:] = Σ_s val·cnorm[idx,:],
              nnz·k MACs per row, no densify for the assignment pass)
              + band densify for the stats accumulation only
  pallas:G:H  fully fused Pallas kernel: band densify + similarity +
              stats in ONE kernel — the dense block lives only in VMEM,
              so the per-block HBM round trip of the dense intermediate
              (the dominant cost of band:* at these shapes) disappears

All modes run the FULL k-means iteration (assignment + stats + centroid
update) as a data-dependent device chain (centroids feed back), and are
difference-timed so the axon-tunnel round trip cancels — the same
discipline as bench.py.  Each variant is checked against the f32 scan
oracle before timing.

Usage: python tools/ell_experiments.py [mode ...]
"""
from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

N, D, K, NNZ = 1 << 19, 512, 64, 32     # the 50M-run's row shape
BLOCK = 4096
CHAINS = {"scan": (3, 30)}
DEFAULT_CHAIN = (20, 200)
GUARD_TOL = 2e-2


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from rabit_tpu.learn import kmeans

    specs = sys.argv[1:] or [
        "scan", "batched:128", "batched:32",
        "band:8:64", "band:8:128", "band:4:128", "band:16:32",
        "gather:8:64",
    ]
    rng = np.random.default_rng(0)
    idx = rng.integers(0, D, (N, NNZ)).astype(np.int32)
    val = rng.standard_normal((N, NNZ)).astype(np.float32)
    cent0 = rng.standard_normal((K, D)).astype(np.float32)
    valid = np.ones(N, np.float32)
    c0 = jax.device_put(jnp.asarray(cent0))
    print("backend:", jax.default_backend(), flush=True)

    nb = N // BLOCK
    di = jax.device_put(jnp.asarray(idx.reshape(nb, BLOCK, NNZ)))
    dv = jax.device_put(jnp.asarray(val.reshape(nb, BLOCK, NNZ)))
    dvl = jax.device_put(jnp.asarray(valid.reshape(nb, BLOCK)))

    def stats_scan(cent):
        fn = kmeans._stats_fn(K, D, BLOCK, NNZ)
        return fn(cent, di, dv, dvl)

    def two_level_onehots(bi, bv, hi, lo, G=None):
        """Per-block (B, nnz) idx/val → hi one-hot and weighted lo
        one-hot.  ``f = lo_idx*hi + hi_idx``; pad entries carry val=0 so
        their one-hot rows contribute nothing wherever they land."""
        hi_idx = bi % hi
        lo_idx = bi // hi
        hio = (hi_idx[..., None] ==
               lax.broadcasted_iota(jnp.int32, (1, 1, hi), 2))
        if G is None:
            loo = (lo_idx[..., None] ==
                   lax.broadcasted_iota(jnp.int32, (1, 1, lo), 2))
            return (hio.astype(jnp.bfloat16),
                    (loo * bv[..., None]).astype(jnp.bfloat16))
        # band layout: row g of each G-group owns columns [g*lo, (g+1)*lo)
        B = bi.shape[0]
        g = (jnp.arange(B, dtype=jnp.int32) % G)[:, None]
        col = g * lo + lo_idx                                # (B, nnz)
        loo = (col[..., None] ==
               lax.broadcasted_iota(jnp.int32, (1, 1, G * lo), 2))
        return (hio.astype(jnp.bfloat16),
                (loo * bv[..., None]).astype(jnp.bfloat16))

    def densify_batched(bi, bv, hi):
        lo = D // hi
        hio, loo = two_level_onehots(bi, bv, hi, lo)
        # per-row (lo, hi) = looᵀ @ hio, batched over rows
        dense = lax.dot_general(
            loo, hio, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)              # (B, lo, hi)
        return dense.reshape(bi.shape[0], D)

    def densify_band(bi, bv, G, hi):
        lo = D // hi
        B = bi.shape[0]
        hio, loo = two_level_onehots(bi, bv, hi, lo, G=G)
        hio = hio.reshape(B // G, G * NNZ, hi)
        loo = loo.reshape(B // G, G * NNZ, G * lo)
        dense = lax.dot_general(
            loo, hio, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)     # (B/G, G*lo, hi)
        return dense.reshape(B, D)

    def iter_with_densify(densify):
        def one(cent):
            cn = kmeans._normalize_rows(cent).astype(jnp.bfloat16)

            def body(acc, blk):
                bi, bv, bvl = blk
                dense = densify(bi, bv)
                onehot = kmeans._dense_assign(cn, dense.astype(jnp.bfloat16),
                                              bvl)
                sums = lax.dot_general(
                    onehot.astype(jnp.bfloat16), dense.astype(jnp.bfloat16),
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                counts = jnp.sum(onehot, axis=0)
                return acc + jnp.concatenate(
                    [sums, counts[:, None]], axis=1), None

            acc0 = jnp.zeros((K, D + 1), jnp.float32)
            stats, _ = lax.scan(body, acc0, (di, dv, dvl))
            return kmeans.centroid_update(cent, stats)
        return one

    def iter_gather(G, hi):
        def one(cent):
            cn = kmeans._normalize_rows(cent).astype(jnp.bfloat16)
            cn_ext = jnp.concatenate(
                [cn, jnp.zeros((1, D), jnp.bfloat16)], axis=0)  # pad row

            def body(acc, blk):
                bi, bv, bvl = blk
                safe = jnp.minimum(bi, D)      # pad index D → zero row
                gath = jnp.take(cn_ext.T, safe, axis=1)   # (k, B, nnz)
                sim = jnp.einsum("kbs,bs->bk", gath.astype(jnp.float32),
                                 bv)
                assign = jnp.argmax(sim, axis=1)
                onehot = (jax.nn.one_hot(assign, K, dtype=jnp.float32)
                          * bvl[:, None])
                dense = densify_band(bi, bv, G, hi)
                sums = lax.dot_general(
                    onehot.astype(jnp.bfloat16), dense.astype(jnp.bfloat16),
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                counts = jnp.sum(onehot, axis=0)
                return acc + jnp.concatenate(
                    [sums, counts[:, None]], axis=1), None

            acc0 = jnp.zeros((K, D + 1), jnp.float32)
            stats, _ = lax.scan(body, acc0, (di, dv, dvl))
            return kmeans.centroid_update(cent, stats)
        return one

    def iter_pallas(G, hi):
        from rabit_tpu.ops.kmeans_kernel import kmeans_ell_stats_fused

        idx_flat = di.reshape(N, NNZ)
        val_flat = dv.reshape(N, NNZ)
        valid_flat = dvl.reshape(N)

        def one(cent):
            stats = kmeans_ell_stats_fused(
                cent, idx_flat, val_flat, valid_flat, D,
                group=G, hi=hi)
            return kmeans.centroid_update(cent, stats)
        return one

    def one_iter_scan(cent):
        return kmeans.centroid_update(cent, stats_scan(cent))

    def chained(one_iter, iters):
        @jax.jit
        def run(cent):
            return lax.fori_loop(0, iters, lambda _, c: one_iter(c), cent)
        return run

    oracle = None

    for spec in specs:
        mode, _, arg = spec.partition(":")
        if mode == "scan":
            one = one_iter_scan
        elif mode == "batched":
            hi = int(arg)
            one = iter_with_densify(
                lambda bi, bv, hi=hi: densify_batched(bi, bv, hi))
        elif mode == "band":
            gs, hs = arg.split(":")
            G, hi = int(gs), int(hs)
            one = iter_with_densify(
                lambda bi, bv, G=G, hi=hi: densify_band(bi, bv, G, hi))
        elif mode == "gather":
            gs, hs = arg.split(":")
            one = iter_gather(int(gs), int(hs))
        elif mode == "pallas":
            gs, hs = arg.split(":")
            one = iter_pallas(int(gs), int(hs))
        else:
            print(f"{spec}: unknown mode", flush=True)
            continue

        try:
            got = np.asarray(chained(one, 5)(c0), np.float32)
            if oracle is None:
                oracle = got  # scan runs first by default
                rel = 0.0
            else:
                rel = float(np.linalg.norm(got - oracle)
                            / np.linalg.norm(oracle))
            tag = "OK" if rel < GUARD_TOL else "NUMERICS-FAIL"
            short, long_ = CHAINS.get(mode, DEFAULT_CHAIN)
            fs, fl = chained(one, short), chained(one, long_)
            np.asarray(fs(c0)); np.asarray(fl(c0))
            ts = []
            for _ in range(3):
                t0 = time.perf_counter(); np.asarray(fs(c0))
                t_s = time.perf_counter() - t0
                t0 = time.perf_counter(); np.asarray(fl(c0))
                t_l = time.perf_counter() - t0
                ts.append((t_l - t_s) / (long_ - short))
            ts.sort()
            dt = ts[len(ts) // 2]
            print(f"{spec:14} {dt * 1e3:8.3f} ms/iter  "
                  f"{N / dt / 1e6:7.1f} Mpoints/s  rel_err={rel:.2e} {tag}",
                  flush=True)
        except Exception as exc:  # noqa: BLE001 — survey harness
            print(f"{spec:14} FAILED: {type(exc).__name__}: {exc}",
                  flush=True)


if __name__ == "__main__":
    main()
