"""Biggest-that-fits single-chip kmeans: the measured anchor behind
doc/scaling.md's pod arithmetic (BASELINE.md "kmeans on 1B points").

Runs `learn/kmeans.py run()` END-TO-END — staging, per-iteration stats
pass, allreduce (world 1), per-iteration in-memory checkpoint — on the
largest synthetic dataset one chip's HBM holds, and reports measured
points/s and effective bytes/s against the HBM roofline.

Two shapes, mirroring the reference's workloads:

  sparse   50M rows x 32 nnz ELL (the libsvm shape the reference's
           kmeans consumes; reference: rabit-learn/utils/data.h) —
           ~13 GB on device (int32 idx + f32 val) of a v5e's 16 GB
  dense    ~24M rows x 256 features, bf16, device-chained iterations
           (the bench.py path) — 12.3 GB resident, ~77% of HBM

Timing: sparse mode takes the median gap between the per-iteration
checkpoint calls inside ONE run (in-run timestamps are immune to the
multi-GB staging variance); dense mode difference-times two chained
fori_loop programs, syncing by FETCH (through the axon tunnel,
block_until_ready returns before the remote execution finishes).

Usage: python tools/big_kmeans.py [sparse|dense] [--points N] [--iters N]
"""
from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")

GEN_BLOCK = 1 << 20


def gen_sparse(n: int, nnz: int, dim: int, k_true: int, seed: int = 0):
    """Clustered ELL data, generated block-wise to bound peak RAM.

    Cluster signal: row r of cluster c gets its first few slots on
    c's signature features with positive values, the rest uniform
    noise — enough structure that centroids separate, cheap to make.
    """
    from rabit_tpu.learn.data import SparseMat

    if nnz < 9 or dim <= 9:
        raise ValueError(
            f"gen_sparse needs nnz >= 9 and dim > 9 (got nnz={nnz}, "
            f"dim={dim}): 8 slots carry the shared cluster signal and "
            "the rest must draw from features above it")
    rng = np.random.default_rng(seed)
    findex = np.empty((n, nnz), np.int32)
    fvalue = np.empty((n, nnz), np.float32)
    # 8 features common to every row with continuous positive weights
    # (cluster centers + per-row noise): similarities vary continuously,
    # so no argmax ties -> no empty Voronoi cells at init
    centers = np.abs(rng.standard_normal((k_true, 8))) + 0.5
    for lo in range(0, n, GEN_BLOCK):
        hi = min(n, lo + GEN_BLOCK)
        m = hi - lo
        cluster = (np.arange(lo, hi) % k_true)
        findex[lo:hi] = rng.integers(8, dim, (m, nnz), dtype=np.int32)
        findex[lo:hi, :8] = np.arange(8, dtype=np.int32)
        fvalue[lo:hi] = (rng.standard_normal((m, nnz))
                         .astype(np.float32) * 0.2)
        fvalue[lo:hi, :8] = centers[cluster] + rng.standard_normal(
            (m, 8)).astype(np.float32) * 0.3
    return SparseMat(
        indptr=np.arange(n + 1, dtype=np.int64) * nnz,
        findex=findex.reshape(-1), fvalue=fvalue.reshape(-1),
        labels=np.zeros(n, np.float32), feat_dim=dim)


def gen_dense_bf16(n: int, dim: int, k_true: int, seed: int = 0):
    """Clustered dense rows, bf16 on host (half the HBM footprint —
    the TPU idiom the fused stats kernel is built for)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    x = np.empty((n, dim), dtype=jnp.bfloat16)
    centers = rng.standard_normal((k_true, dim), dtype=np.float32) * 3
    for lo in range(0, n, GEN_BLOCK):
        hi = min(n, lo + GEN_BLOCK)
        cluster = (np.arange(lo, hi) % k_true)
        blk = centers[cluster] + rng.standard_normal(
            (hi - lo, dim), dtype=np.float32)
        x[lo:hi] = blk.astype(jnp.bfloat16)
    return x


def timed_run(data, k: int, iters: int, **kw):
    """One end-to-end run(); per-iteration time = gaps between the
    per-iteration checkpoint calls (median, first gap dropped — it
    carries the XLA compile).  In-run gaps are immune to the multi-GB
    staging variance that breaks whole-run difference timing on the
    tunneled chip."""
    import rabit_tpu
    from rabit_tpu.learn import kmeans

    rabit_tpu.finalize()
    rabit_tpu.init(rabit_engine="empty")
    stamps: list[float] = [time.perf_counter()]
    orig = rabit_tpu.checkpoint

    def stamping_checkpoint(model):
        stamps.append(time.perf_counter())
        orig(model)

    rabit_tpu.checkpoint = stamping_checkpoint
    try:
        model = kmeans.run(data, num_cluster=k, max_iter=iters, **kw)
    finally:
        rabit_tpu.checkpoint = orig
    gaps = np.diff(np.asarray(stamps))[1:]  # drop the compile gap
    # iterations per checkpoint gap, derived from what run() actually
    # did (device_chain only engages on the dense/ell_fused single-
    # worker path — never guess from the requested chain)
    iters_per_gap = iters / max(len(gaps) + 1, 1)
    return float(np.median(gaps) / iters_per_gap), model


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", nargs="?", default="sparse",
                    choices=["sparse", "dense", "hashed"])
    ap.add_argument("--points", type=int, default=None)
    ap.add_argument("--nnz", type=int, default=32)
    ap.add_argument("--dim", type=int, default=None)
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--chain", type=int, default=1,
                    help="sparse mode: device-chain this many iterations per checkpoint (amortizes the per-iteration host fetch; checkpoint granularity coarsens to match)")
    args = ap.parse_args()

    import rabit_tpu

    rabit_tpu.init(rabit_engine="empty")
    if args.mode == "hashed":
        # the SAME 50M-row sparse dataset as sparse mode, clustered via
        # run(hash_dim=128, compute_dtype="bfloat16"): signed hashing +
        # half-width dense staging put the whole run on the
        # HBM-roofline dense kernel (doc/benchmarks.md "Feature-hashed
        # sparse k-means"); approximate where sparse mode is exact
        n = args.points or 50_000_000
        dim = args.dim or 512
        hash_dim = 128
        print(f"generating {n} x {args.nnz}-nnz rows (dim {dim}), "
              f"hash_dim {hash_dim}...", flush=True)
        t0 = time.perf_counter()
        data = gen_sparse(n, args.nnz, dim, args.k)
        print(f"  generated in {time.perf_counter() - t0:.1f}s", flush=True)
        per_iter, model = timed_run(data, args.k, args.iters,
                                    device_chain=args.chain,
                                    hash_dim=hash_dim,
                                    compute_dtype="bfloat16")
        bytes_per_iter = n * hash_dim * 2   # one bf16 read of the rows
    elif args.mode == "sparse":
        n = args.points or 50_000_000
        # moderate width: the ELL stats pass densifies per row block, so
        # width trades against block size; 512 ~ a dense-ish ads/ctr shape
        dim = args.dim or 512
        print(f"generating {n} x {args.nnz}-nnz rows (dim {dim})...",
              flush=True)
        t0 = time.perf_counter()
        data = gen_sparse(n, args.nnz, dim, args.k)
        print(f"  generated in {time.perf_counter() - t0:.1f}s", flush=True)
        per_iter, model = timed_run(data, args.k, args.iters,
                                    device_chain=args.chain)
        bytes_per_iter = n * args.nnz * 8  # idx int32 + val f32, read once
    else:
        # biggest dense shape: device-chained iterations (the bench.py
        # path) on a bf16 shard filling most of a v5e's 16 GB
        import jax
        import jax.numpy as jnp
        from rabit_tpu.learn import kmeans

        # exact multiple of the fused kernel's 16384 row block: the
        # kernel's row padding is then a no-op instead of a second
        # 12 GB copy that overflows HBM
        n = args.points or 16384 * 1464   # 23,986,176
        dim = args.dim or 256
        print(f"generating {n} dense bf16 rows (dim {dim})...", flush=True)
        t0 = time.perf_counter()
        x_host = gen_dense_bf16(n, dim, args.k)
        print(f"  generated in {time.perf_counter() - t0:.1f}s", flush=True)
        x = jax.device_put(jnp.asarray(x_host))
        del x_host
        valid = jnp.ones((n,), jnp.float32)
        rng = np.random.default_rng(1)
        cent = jnp.asarray(rng.standard_normal((args.k, dim)),
                           dtype=jnp.float32)

        def chain(iters):
            # sync by FETCHING the (k, dim) result: through the axon
            # tunnel block_until_ready returns before the remote
            # execution finishes — only a fetch truly synchronizes
            t0 = time.perf_counter()
            out = kmeans.device_iterations(cent, x, valid, iters,
                                           compute_dtype="bfloat16")
            np.asarray(out)
            return time.perf_counter() - t0, out

        iters = max(args.iters, 50)  # enough work to beat tunnel jitter
        chain(2)            # compile short chain
        chain(2 + iters)    # compile long chain
        t_s, _ = chain(2)
        t_l, out = chain(2 + iters)
        per_iter = (t_l - t_s) / iters

        class _M:  # minimal shim for the shared report below
            centroids = np.asarray(out)
        model = _M()
        bytes_per_iter = n * dim * 2
    assert np.isfinite(model.centroids).all()
    note = ("device-chained, no checkpoint" if args.mode == "dense"
            else "per-iteration checkpoint included" if args.chain <= 1
            else f"checkpoint every {args.chain} device-chained iters")
    print(f"mode={args.mode} n={n} k={args.k}: {per_iter * 1e3:.1f} ms/iter, "
          f"{n / per_iter / 1e6:.0f} Mpoints/s, "
          f"{bytes_per_iter / per_iter / 1e9:.0f} GB/s effective "
          f"({note})", flush=True)


if __name__ == "__main__":
    main()
