"""Measure the full-barrier rendezvous at large worlds — for real.

The repo's recovery re-registers EVERY rank with the tracker (full
barrier) where the reference repairs only broken links
(reference: src/allreduce_base.cc:207-261 good-link protocol).  The
round-3/4 measurements showed recovery cost is flat and dominated by
process restart at world <= 64; the residual concern was extrapolation:
"is the tracker's serial accept loop still cheap at W ~ 1024?".  This
tool measures exactly that component at storm scale.

Each worker is a THREAD speaking the raw wire protocol
(tracker/protocol.py): bind a listener, register, receive the topology,
make the real tree+ring TCP links (magic/rank handshake), then — the
recovery-relevant number — run a second full round with cmd=recover,
which is precisely the path every rank takes after a failure.  Threads
in one process overstate the cost (GIL + one accept queue timeshared),
so the numbers are an upper bound on the distributed reality.

Usage: python tools/rendezvous_storm.py [--worlds 64,128,256,512]
"""
from __future__ import annotations

import argparse
import socket
import sys
import threading
import time

sys.path.insert(0, ".")

from rabit_tpu.tracker import protocol as P  # noqa: E402
from rabit_tpu.tracker.tracker import Tracker  # noqa: E402


def tracker_round(tracker_addr, task_id: str, cmd: str,
                  listener: socket.socket, links: dict,
                  job: str = P.DEFAULT_JOB, world: int = 0) -> None:
    """One worker's rendezvous: register, get topology, make links.
    The default job sends the classic hello layout byte-for-byte; a
    named ``job`` (sharded mode) rides the MAGIC_JOB extension against
    whichever shard the directory hashed the job onto."""
    host, port = listener.getsockname()
    for attempt in range(50):
        try:
            sock = socket.create_connection(tracker_addr, timeout=120)
            break
        except OSError:
            # accept-backlog overflow under the storm: retry
            time.sleep(0.02 * (attempt + 1))
    else:
        raise RuntimeError("cannot reach tracker")
    try:
        P.send_hello(sock, cmd, task_id, world, job=job)
        P.send_str(sock, "127.0.0.1")
        P.send_u32(sock, port)
        topo = P.TopologyReply.recv_or_reject(sock)
        if isinstance(topo, P.RejectReply):
            raise RuntimeError(
                f"tracker rejected {task_id} (job {job}): "
                f"code {topo.code} {topo.reason!r}")
    finally:
        sock.close()
    # recovery closes every link first (full teardown, the design under
    # test); remake them all
    for s in links.values():
        s.close()
    links.clear()
    lock = threading.Lock()
    accept_err: list = []

    def do_accept():
        # bounded accept: a peer that exhausted ITS connect retries must
        # surface here as a timeout, not hang the whole storm barrier
        listener.settimeout(120)
        try:
            for _ in range(topo.naccept):
                s, _ = listener.accept()
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                if P.recv_u32(s) != P.MAGIC:
                    raise RuntimeError("bad magic")
                peer = P.recv_u32(s)
                P.send_u32(s, P.MAGIC)
                P.send_u32(s, topo.rank)
                with lock:
                    links[peer] = s
        except Exception as e:  # noqa: BLE001 — re-raised after join
            accept_err.append(e)
        finally:
            listener.settimeout(None)

    acceptor = threading.Thread(target=do_accept)
    acceptor.start()
    for r, h, p in topo.connect:
        for attempt in range(50):
            try:
                s = socket.create_connection((h, p), timeout=120)
                break
            except OSError:
                time.sleep(0.02 * (attempt + 1))
        else:
            raise RuntimeError(f"cannot reach peer {r}")
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        P.send_u32(s, P.MAGIC)
        P.send_u32(s, topo.rank)
        if P.recv_u32(s) != P.MAGIC or P.recv_u32(s) != r:
            raise RuntimeError("link handshake mismatch")
        with lock:
            links[r] = s
    acceptor.join()
    if accept_err:
        raise accept_err[0]


def storm(world: int) -> tuple[float, float]:
    """Returns (start_round_s, recover_round_s) wall time across all
    workers (slowest worker defines the barrier)."""
    tracker = Tracker(world)
    tracker.start()
    addr = (tracker.host, tracker.port)
    listeners = []
    for _ in range(world):
        ln = socket.socket()
        ln.bind(("127.0.0.1", 0))
        ln.listen(64)
        listeners.append(ln)
    all_links: list[dict] = [{} for _ in range(world)]
    errors: list = []
    times = {}

    def phase(cmd: str) -> float:
        done = threading.Barrier(world + 1)

        def work(i: int) -> None:
            try:
                tracker_round(addr, str(i), cmd, listeners[i],
                              all_links[i])
            except Exception as e:  # noqa: BLE001
                errors.append((i, e))
            finally:
                done.wait()

        t0 = time.monotonic()
        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(world)]
        for t in threads:
            t.start()
        done.wait()
        dt = time.monotonic() - t0
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError(f"storm failed: {errors[:3]}")
        return dt

    try:
        times["start"] = phase(P.CMD_START)
        times["recover"] = phase(P.CMD_RECOVER)
    finally:
        for i in range(world):
            for s in all_links[i].values():
                s.close()
            listeners[i].close()
        # raw clients never send cmd=shutdown; stop the tracker directly
        tracker.stop()
    return times["start"], times["recover"]


def shard_storm(world: int, n_shards: int) -> tuple[float, float]:
    """The storm against a SHARDED control plane: one in-process
    directory authority, ``n_shards`` :class:`ShardServer`s registered
    with it, and the world split into ``n_shards`` named jobs.  Each
    job's workers resolve their ring owner through the directory and
    speak the job-aware hello to that shard — so the measured barrier
    is the per-shard serial accept loop at ~1/N the flat pressure,
    the scaling claim the directory tier exists to buy."""
    from rabit_tpu.tracker.directory import Directory
    from rabit_tpu.tracker.shard import ShardServer

    if world % n_shards:
        raise SystemExit(
            f"--shards {n_shards} must divide world {world}")
    per = world // n_shards
    directory = Directory()
    shards = [ShardServer(per, shard_index=i, directory=directory)
              for i in range(n_shards)]
    for tr in shards:
        tr.start()
    jobs = [f"storm{j}" for j in range(n_shards)]
    addr_of = {}
    for name in jobs:
        owner = directory.owner(name)
        assert owner is not None, "empty fleet after registration"
        addr_of[name] = (owner[1], owner[2])
    listeners = []
    for _ in range(world):
        ln = socket.socket()
        ln.bind(("127.0.0.1", 0))
        ln.listen(64)
        listeners.append(ln)
    all_links: list[dict] = [{} for _ in range(world)]
    errors: list = []
    times = {}

    def phase(cmd: str) -> float:
        done = threading.Barrier(world + 1)

        def work(i: int) -> None:
            name = jobs[i // per]
            try:
                tracker_round(addr_of[name], str(i % per), cmd,
                              listeners[i], all_links[i],
                              job=name, world=per)
            except Exception as e:  # noqa: BLE001
                errors.append((i, e))
            finally:
                done.wait()

        t0 = time.monotonic()
        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(world)]
        for t in threads:
            t.start()
        done.wait()
        dt = time.monotonic() - t0
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError(f"shard storm failed: {errors[:3]}")
        return dt

    try:
        times["start"] = phase(P.CMD_START)
        times["recover"] = phase(P.CMD_RECOVER)
    finally:
        for i in range(world):
            for s in all_links[i].values():
                s.close()
            listeners[i].close()
        for tr in shards:
            tr.stop()
    return times["start"], times["recover"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--worlds", default="128,256,512,1024")
    ap.add_argument("--shards", type=int, default=0,
                    help="run the storm against a sharded control "
                         "plane: N in-process tracker shards behind a "
                         "directory, the world split into N jobs")
    args = ap.parse_args()
    for w in map(int, args.worlds.split(",")):
        if args.shards > 0:
            t_start, t_recover = shard_storm(w, args.shards)
            tag = f" ({args.shards} shards)"
        else:
            t_start, t_recover = storm(w)
            tag = ""
        print(f"world {w:4d}{tag}: start round {t_start * 1e3:7.1f} ms"
              f"   recover round (full-barrier re-rendezvous) "
              f"{t_recover * 1e3:7.1f} ms", flush=True)


if __name__ == "__main__":
    main()
