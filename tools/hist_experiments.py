"""Microbench: gradient-histogram formulations on the real chip.

Difference timing (long - short run of dispatch chains, one fetch)
cancels the ~100 ms axon tunnel round trip that made round-2's "129 ms"
recording meaningless.  Modes:

  xla1        XLA per-feature one-hot contraction, one (f, nbin, 2) hist
  pallas1     fused kernel, single grad/hess pair, resident (f, n) bins
  pallasM     fused kernel, m-node level build: (2m, n) weight channels
              sharing ONE bins pass
  xlaM        m XLA passes (the per-node pattern pallasM replaces)

Usage: python tools/hist_experiments.py [mode:m ...]
"""
from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

N, F, NBIN = 262144, 64, 256


def main():
    import jax
    import jax.numpy as jnp

    from rabit_tpu.learn import histogram
    from rabit_tpu.ops.histogram_kernel import hist_fused_multi

    specs = sys.argv[1:] or [
        "xla1", "pallas1", "pallasM:2", "pallasM:4", "pallasM:8",
        "pallasM:16", "xlaM:8",
    ]
    rng = np.random.default_rng(0)
    bins = rng.integers(0, NBIN, (N, F)).astype(np.int32)
    db = jax.device_put(jnp.asarray(bins))
    dbt = jax.device_put(jnp.asarray(bins.T))
    dg = jax.device_put(jnp.asarray(
        rng.standard_normal(N).astype(np.float32)))
    dh = jax.device_put(jnp.asarray(rng.random(N).astype(np.float32)))
    node = jnp.asarray(rng.integers(0, 16, N).astype(np.int32))
    print("backend:", jax.default_backend())

    def weights(m):
        nid = jnp.arange(m, dtype=jnp.int32)
        mask = (node[None, :] % m == nid[:, None]).astype(jnp.float32)
        return jnp.concatenate([mask * dg[None, :], mask * dh[None, :]])

    def per_iter(fn, iters=40, short=4):
        for _ in range(3):
            fn().block_until_ready()
        def run(k):
            t = time.perf_counter()
            for _ in range(k):
                r = fn()
            r.block_until_ready()
            return time.perf_counter() - t
        best = float("inf")
        for _ in range(3):
            best = min(best, (run(iters) - run(short)) / (iters - short))
        return best

    for spec in specs:
        mode, _, arg = spec.partition(":")
        m = int(arg) if arg else 1
        if mode == "xla1":
            fn = lambda: histogram.build_local(db, dg, dh, NBIN,
                                               use_pallas=False)
        elif mode == "pallas1":
            w2 = jnp.stack([dg, dh])
            fn = lambda: hist_fused_multi(dbt, w2, NBIN)
        elif mode == "pallasM":
            w = weights(m)
            fn = lambda: hist_fused_multi(dbt, w, NBIN)
        elif mode == "xlaM":
            w = weights(m)
            def fn(w=w, m=m):
                outs = [histogram.build_local(db, w[v], w[m + v], NBIN,
                                              use_pallas=False)
                        for v in range(m)]
                return outs[-1]
        else:
            print(f"{spec}: unknown mode")
            continue
        iters = 40 if mode in ("xla1", "pallas1") else 16
        t = per_iter(fn, iters=iters)
        print(f"{spec:12s} {t*1e3:8.3f} ms   "
              f"({N * F * 4 / t / 1e9:6.1f} GB/s bins-read rate)")


if __name__ == "__main__":
    main()
