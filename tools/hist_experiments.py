"""Microbench: gradient-histogram formulations on the real chip.

Measurement discipline (hard-won, see doc/benchmarks.md): through the
axon tunnel `block_until_ready` returns before the remote execution
finishes, and independent dispatches need not serialize — the ONLY
trustworthy timing is a DATA-DEPENDENT chain inside one jitted program
(each iteration's weights perturbed by the previous histogram so
nothing can be hoisted or overlapped), difference-timed between a long
and a short chain with one fetch each so the fixed tunnel round trip
cancels.  This is the same recipe `kernel_experiments.py` uses for the
kmeans kernel.

Modes:

  xla1        XLA per-feature one-hot contraction, one histogram/iter
  pallas1     fused two-level kernel, single grad/hess pair
  pallasM:m   fused kernel, m-node level build: (2m, n) weight channels
              sharing ONE bins pass
  xlaM:m      m XLA passes per iteration (the per-node pattern pallasM
              replaces)
  plan:hi:lo:fpg  pallas1 with an overridden two-level plan — measures
              the inflation/occupancy frontier (default 16:16:8 is
              8x-inflated at full M=128 tiles; 32:8:4 and 64:4:2 shrink
              the inflation at shrinking M = 2*fpg^2 tiles)

Usage: python tools/hist_experiments.py [mode[:m] ...]
"""
from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

N, F, NBIN = 262144, 64, 256
# slow modes get a short chain (enough signal at ~30 ms/iter); fast
# ones need hundreds of iterations to rise above tunnel jitter
CHAINS = {"xla1": (5, 50), "xlaM": (2, 12)}
DEFAULT_CHAIN = (50, 500)


def main():
    import jax
    import jax.numpy as jnp

    from rabit_tpu.learn import histogram
    from rabit_tpu.ops.histogram_kernel import hist_fused_multi

    specs = sys.argv[1:] or [
        "xla1", "pallas1", "pallasM:2", "pallasM:4", "pallasM:8",
        "pallasM:16", "xlaM:8",
    ]
    rng = np.random.default_rng(0)
    bins = rng.integers(0, NBIN, (N, F)).astype(np.int32)
    db = jax.device_put(jnp.asarray(bins))
    dbt = jax.device_put(jnp.asarray(bins.T))
    dg = jax.device_put(jnp.asarray(
        rng.standard_normal(N).astype(np.float32)))
    dh = jax.device_put(jnp.asarray(rng.random(N).astype(np.float32)))
    node = jnp.asarray(rng.integers(0, 16, N).astype(np.int32))
    print("backend:", jax.default_backend(), flush=True)

    def weights(m):
        nid = jnp.arange(m, dtype=jnp.int32)
        mask = (node[None, :] % m == nid[:, None]).astype(jnp.float32)
        return jnp.concatenate([mask * dg[None, :], mask * dh[None, :]])

    def chained(one_hist, w0, iters):
        """iters histogram passes, each perturbing the next weights so
        the chain is a true data dependency."""

        @jax.jit
        def run(w):
            def body(_, w):
                h = one_hist(w)
                return w * (1.0 + 1e-30 * h.sum())
            return jax.lax.fori_loop(0, iters, body, w)

        return run, w0

    def time_chain(one_hist, w0, mode):
        short, long_ = CHAINS.get(mode, DEFAULT_CHAIN)
        fs, w = chained(one_hist, w0, short)
        fl, _ = chained(one_hist, w0, long_)
        np.asarray(fs(w))
        np.asarray(fl(w))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(fs(w))
            ts = time.perf_counter() - t0
            t0 = time.perf_counter()
            np.asarray(fl(w))
            tl = time.perf_counter() - t0
            best = min(best, (tl - ts) / (long_ - short))
        return best

    for spec in specs:
        mode, _, arg = spec.partition(":")
        m = int(arg) if arg and mode != "plan" else 1
        if mode == "xla1":
            w0 = jnp.stack([dg, dh])

            def one(w):
                return histogram.build_local(
                    db, w[0], w[1], NBIN, use_pallas=False)
        elif mode == "pallas1":
            w0 = jnp.stack([dg, dh])

            def one(w):
                return hist_fused_multi(dbt, w, NBIN)
        elif mode == "plan":
            hi, lo, fpg = (int(x) for x in arg.split(":"))
            w0 = jnp.stack([dg, dh])

            def one(w, plan=(hi, lo, fpg)):
                return hist_fused_multi(dbt, w, NBIN, plan_override=plan)
        elif mode == "pallasM":
            w0 = weights(m)

            def one(w):
                return hist_fused_multi(dbt, w, NBIN)
        elif mode == "xlaM":
            w0 = weights(m)

            def one(w, m=m):
                outs = [histogram.build_local(
                    db, w[v], w[m + v], NBIN, use_pallas=False)
                    for v in range(m)]
                return jnp.stack(outs)
        else:
            print(f"{spec}: unknown mode")
            continue
        try:
            t = time_chain(one, w0, mode)
        except Exception as e:  # noqa: BLE001
            print(f"{spec:12s} FAILED: {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:100]}")
            continue
        print(f"{spec:12s} {t*1e3:8.3f} ms   "
              f"({N * F * 4 / t / 1e9:6.1f} GB/s bins-read rate)",
              flush=True)


if __name__ == "__main__":
    main()
