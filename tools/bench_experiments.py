"""Scratch experiment harness for the flagship kmeans bench (round 2).

Times one-iteration variants chained device-side (ITERS iterations in a
single fori_loop program, one host sync), per the axon timing rules:
sync with np.asarray, time the second run of the exact jitted program.

Usage: python tools/bench_experiments.py [variant ...]
"""
from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

N, D, K, ITERS = 1 << 19, 256, 64, 50


def make_data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((N, D)).astype(np.float32)
    c = rng.standard_normal((K, D)).astype(np.float32)
    v = np.ones(N, np.float32)
    return x, c, v


def run_variant(name: str, x, c, v) -> float:
    import jax
    from rabit_tpu.learn import kmeans

    if name.startswith("xla-"):
        dtype = name.split("-")[1]
        fn = lambda: kmeans.device_iterations(
            c, x, v, ITERS, use_pallas=False, compute_dtype=dtype)
    elif name.startswith("pallas-"):
        parts = name.split("-")
        dtype = parts[1]
        block = int(parts[2]) if len(parts) > 2 else 2048
        fn = lambda: kmeans.device_iterations(
            c, x, v, ITERS, use_pallas=True, block=block,
            compute_dtype=dtype)
    else:
        raise ValueError(name)

    np.asarray(fn())          # compile + warm
    np.asarray(fn())          # drain any pending work
    t0 = time.perf_counter()
    out = fn()
    np.asarray(out)
    dt = (time.perf_counter() - t0) / ITERS
    return dt


def main():
    variants = sys.argv[1:] or [
        "xla-float32", "xla-bfloat16",
        "pallas-float32-2048", "pallas-bfloat16-2048",
    ]
    x, c, v = make_data()
    import jax
    import jax.numpy as jnp
    x = jax.device_put(jnp.asarray(x))
    c = jax.device_put(jnp.asarray(c))
    v = jax.device_put(jnp.asarray(v))
    print("backend:", jax.default_backend())
    for name in variants:
        try:
            dt = run_variant(name, x, c, v)
            print(f"{name:28s} {dt*1e3:8.3f} ms/iter  "
                  f"{N/dt/1e6:8.1f} Mpoints/s")
        except Exception as e:
            print(f"{name:28s} FAILED: {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
