"""Feature hashing vs exact sparse k-means: quality + throughput.

The fused ELL kernel's VPU floor is ``nnz x 128`` lane-ops/row
(doc/benchmarks.md "ELL kernel plan sweep" closed form); dense rows at a
small width instead ride the HBM-roofline stats kernel.  This harness
measures the remaining algorithmic out from the round-3 verdict: hash
the sparse features to ``d_out`` (signed hashing,
``learn/data.py hash_features``), densify, and run the DENSE kernel —
trading collision noise for bandwidth.

Data: synthetic clustered sparse rows (64 ground-truth clusters in
d=512, each with a ~48-feature support; rows draw nnz=32 support
features + noise), so quality is measurable as purity of the final
assignment against the generating labels plus the mean cosine to the
assigned centroid (the objective k-means optimizes here).

Each path runs the same ITERS iterations from the same init and is
difference-timed as a device chain (bench.py discipline).

Usage: python tools/hash_experiments.py [--n 262144] [--douts 256,128]
"""
from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")

D, K, NNZ, SUPPORT = 512, 64, 32, 48
ITERS = 15
CHAIN = (5, 50)


def make_clustered(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    support = np.stack([rng.choice(D, SUPPORT, replace=False)
                        for _ in range(K)])              # (K, SUPPORT)
    weight = rng.standard_normal((K, SUPPORT)).astype(np.float32) + 2.0
    labels = rng.integers(0, K, n)
    slot = rng.integers(0, SUPPORT, (n, NNZ))
    idx = support[labels[:, None], slot].astype(np.int32)
    val = (weight[labels[:, None], slot]
           + 0.3 * rng.standard_normal((n, NNZ))).astype(np.float32)
    return idx, val, labels


def densify(idx: np.ndarray, val: np.ndarray, d: int) -> np.ndarray:
    n = idx.shape[0]
    out = np.zeros((n, d), np.float32)
    np.add.at(out, (np.arange(n)[:, None], idx), val)
    return out


def purity(assign: np.ndarray, labels: np.ndarray) -> float:
    """Mean over found clusters of the majority generating label share."""
    total = 0
    for c in np.unique(assign):
        lab = labels[assign == c]
        total += np.bincount(lab).max()
    return total / len(labels)


def _time_chain(chain) -> float:
    """Median-of-5 interleaved difference timing (bench.py discipline —
    this repo has twice measured physically impossible numbers from
    single-pair difference estimates).  ``chain(it)`` must run ``it``
    iterations with data passed as ARGUMENTS (captured constants turn
    the whole chain into XLA constant folding and time compilation
    instead of execution)."""
    import statistics

    s, l = CHAIN
    np.asarray(chain(s)); np.asarray(chain(l))  # compile both lengths
    xs = []
    for _ in range(5):
        t0 = time.perf_counter(); np.asarray(chain(s))
        ts = time.perf_counter() - t0
        t0 = time.perf_counter(); np.asarray(chain(l))
        tl = time.perf_counter() - t0
        dt = (tl - ts) / (l - s)
        if dt > 0:
            xs.append(dt)
    return statistics.median(xs) if xs else float("nan")


def run_dense(x_host: np.ndarray, cent0: np.ndarray, iters: int):
    import jax
    import jax.numpy as jnp

    from rabit_tpu.learn import kmeans

    x = jax.device_put(jnp.asarray(x_host))
    v = jnp.ones(x.shape[0], jnp.float32)
    c = jax.device_put(jnp.asarray(cent0))

    def chain(it):
        return kmeans.device_iterations(c, x, v, it,
                                        compute_dtype="bfloat16")

    final = np.asarray(chain(iters), np.float32)
    dt = _time_chain(chain)
    cn = final / (np.linalg.norm(final, axis=1, keepdims=True) + 1e-12)
    xn = x_host / (np.linalg.norm(x_host, axis=1, keepdims=True) + 1e-12)
    sim = xn @ cn.T
    assign = sim.argmax(axis=1)
    return final, assign, sim.max(axis=1).mean(), dt


def run_ell(idx: np.ndarray, val: np.ndarray, cent0: np.ndarray,
            iters: int, x_host: np.ndarray, block: int = 4096):
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    from rabit_tpu.learn import kmeans
    from rabit_tpu.ops.kmeans_kernel import kmeans_ell_stats_fused

    n = idx.shape[0]
    bi = jax.device_put(jnp.asarray(idx))
    bv = jax.device_put(jnp.asarray(val))
    v = jnp.ones(n, jnp.float32)
    c0 = jax.device_put(jnp.asarray(cent0))

    @functools.partial(jax.jit, static_argnames=("it",))
    def run(c, bi, bv, v, it):
        def one(_, cc):
            stats = kmeans_ell_stats_fused(
                cc, bi, bv, v, D, group=4, hi=128, block=block)
            return kmeans.centroid_update(cc, stats)
        return lax.fori_loop(0, it, one, c)

    def chain(it):
        return run(c0, bi, bv, v, it)

    final = np.asarray(chain(iters), np.float32)
    dt = _time_chain(chain)
    cn = final / (np.linalg.norm(final, axis=1, keepdims=True) + 1e-12)
    xn = x_host / (np.linalg.norm(x_host, axis=1, keepdims=True) + 1e-12)
    sim = xn @ cn.T
    assign = sim.argmax(axis=1)
    return final, assign, sim.max(axis=1).mean(), dt


def main():
    from rabit_tpu.learn.data import hash_features

    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1 << 18,
                    help="rounded up to a multiple of 16384 (the dense "
                         "kernel's row block; the ELL block divides it)")
    ap.add_argument("--douts", default="256,128")
    args = ap.parse_args()
    if args.n % 16384:
        args.n = -(-args.n // 16384) * 16384
        print(f"[n rounded up to {args.n}]", flush=True)

    idx, val, labels = make_clustered(args.n)
    rng = np.random.default_rng(1)
    pick = rng.choice(args.n, K, replace=False)
    cent0 = densify(idx[pick], val[pick], D)   # init from random rows

    print(f"n={args.n} d={D} nnz={NNZ} k={K} iters={ITERS}", flush=True)

    # quality judged in the ORIGINAL space: purity of the hashed
    # assignment against the generating labels, and the mean cosine of
    # original rows to their hashed-assigned cluster's ORIGINAL mean
    # (what a user of the recipe actually gets)
    x0 = densify(idx, val, D)
    x0n = x0 / (np.linalg.norm(x0, axis=1, keepdims=True) + 1e-12)

    _, assign, cos, dt = run_ell(idx, val, cent0, ITERS, x0)
    print(f"exact ELL d={D}:        purity={purity(assign, labels):.3f}  "
          f"mean-cos={cos:.4f}  {dt * 1e3:7.3f} ms/iter  "
          f"{args.n / dt / 1e6:7.1f} Mpoints/s", flush=True)
    for d_out in map(int, args.douts.split(",")):
        hidx, hval = hash_features(idx, val, d_out)
        xh = densify(hidx, hval, d_out)
        ch0 = xh[pick]
        _, assign, _, dt = run_dense(xh, ch0, ITERS)
        cos0 = 0.0
        for c in np.unique(assign):
            rows = assign == c
            mu = x0[rows].mean(axis=0)
            mu /= (np.linalg.norm(mu) + 1e-12)
            cos0 += float((x0n[rows] @ mu).sum())
        cos0 /= args.n
        print(f"hashed dense d={d_out:4d}: "
              f"purity={purity(assign, labels):.3f}  "
              f"mean-cos={cos0:.4f}  {dt * 1e3:7.3f} ms/iter  "
              f"{args.n / dt / 1e6:7.1f} Mpoints/s", flush=True)


if __name__ == "__main__":
    main()
