"""Distributed hashed k-means at data scale, with a death in the middle.

The round-4 verdict asked for the one run that exercises everything at
once: N real worker processes through `rabit_engine=xla` (1 virtual CPU
device each), >=10M total rows of synthetic sparse data staged onto the
device plane, per-iteration stats over the device collectives,
per-iteration in-memory checkpoints, ONE injected worker death mid-run,
keepalive relaunch, device-plane re-formation at the checkpoint
boundary, and shard re-upload — then full numeric agreement at the end.
This turns doc/scaling.md's pod arithmetic into executed evidence
(reference analogue: rabit-learn/kmeans run as a real N-worker job,
kmeans_hadoop.sh + test/test.mk).

Parent mode generates nothing: each worker synthesises its own seeded
shard in memory (LibSVM files at this scale would dominate the run).
Rank 0 wraps `rabit_tpu.checkpoint` to timestamp every iteration and
prints the gaps at the end; the parent parses them and reports iter/s
before and after the recovery.

Usage:
  python tools/dist_kmeans_soak.py [--world 4] [--rows 10000000]
      [--iters 6] [--die-rank 2] [--die-version 3] [--k 8]
      [--hash-dim 64]
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def worker() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 1)

    import numpy as np

    import rabit_tpu
    from rabit_tpu.learn.data import SparseMat
    from rabit_tpu.learn import kmeans

    rows = int(os.environ["SOAK_ROWS_PER_RANK"])
    nnz = int(os.environ.get("SOAK_NNZ", "4"))
    raw_dim = int(os.environ.get("SOAK_RAW_DIM", "100000"))
    k = int(os.environ["SOAK_K"])
    iters = int(os.environ["SOAK_ITERS"])
    hash_dim = int(os.environ["SOAK_HASH_DIM"])
    trial = int(os.environ.get("RABIT_NUM_TRIAL", "0") or 0)

    # the native robust engine is the fault-tolerant control plane the
    # death scenario needs (pysocket is the non-fault-tolerant twin)
    rabit_tpu.init(rabit_engine="xla", rabit_inner_engine="native",
                   rabit_timeout_sec="30")
    rank = rabit_tpu.get_rank()

    # Seeded per-rank shard: k_true latent clusters, each row gets its
    # cluster's signature feature plus noise features (block-generated).
    rng = np.random.default_rng(1000 + rank)
    k_true = k
    findex = np.empty((rows, nnz), np.int32)
    fvalue = np.empty((rows, nnz), np.float32)
    block = 1 << 18
    for lo in range(0, rows, block):
        hi = min(rows, lo + block)
        n = hi - lo
        cluster = rng.integers(0, k_true, n)
        findex[lo:hi, 0] = cluster.astype(np.int32)  # signature feature
        fvalue[lo:hi, 0] = 2.0 + rng.random(n, np.float32)
        findex[lo:hi, 1:] = rng.integers(k_true, raw_dim, (n, nnz - 1))
        # strong positive per-row noise: rows of one latent cluster must
        # still DIFFER enough in hashed space that random-row init
        # centroids define non-empty Voronoi cells (cosine argmax ties
        # between near-duplicate centroids starve one of them)
        fvalue[lo:hi, 1:] = rng.uniform(
            0.5, 2.0, (n, nnz - 1)).astype(np.float32)
    indptr = np.arange(0, (rows + 1) * nnz, nnz, dtype=np.int64)
    data = SparseMat(indptr=indptr, findex=findex.reshape(-1),
                     fvalue=fvalue.reshape(-1),
                     labels=np.zeros(rows, np.float32), feat_dim=raw_dim)

    # Death injection (first life only): die just before committing the
    # chosen checkpoint version, exit 254 -> keepalive relaunch.
    die = os.environ.get("SOAK_DIE")  # "rank:version"
    stamps: list[tuple[int, float]] = []
    orig_checkpoint = rabit_tpu.checkpoint

    def instrumented_checkpoint(model):
        if die and trial == 0:
            die_rank, die_version = map(int, die.split(":"))
            if (rank == die_rank
                    and rabit_tpu.version_number() + 1 >= die_version):
                os._exit(254)
        orig_checkpoint(model)
        stamps.append((rabit_tpu.version_number(), time.perf_counter()))

    rabit_tpu.checkpoint = instrumented_checkpoint
    try:
        model = kmeans.run(data, num_cluster=k, max_iter=iters,
                           hash_dim=hash_dim)
    finally:
        rabit_tpu.checkpoint = orig_checkpoint

    # every rank must hold the same model
    gathered = rabit_tpu.allgather(model.centroids.reshape(-1))
    for r in range(rabit_tpu.get_world_size()):
        np.testing.assert_allclose(gathered[r],
                                   model.centroids.reshape(-1), rtol=1e-5)
    if rank == 0:
        for (v0, t0), (v1, t1) in zip(stamps, stamps[1:]):
            rabit_tpu.tracker_print(
                f"SOAK iter v{v0}->v{v1} gap={t1 - t0:.3f}s")
        rabit_tpu.tracker_print("SOAK final-agreement OK")
    rabit_tpu.finalize()
    return 0


def main() -> int:
    if os.environ.get("SOAK_WORKER"):
        return worker()
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=4)
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--iters", type=int, default=6)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--hash-dim", type=int, default=64)
    ap.add_argument("--die-rank", type=int, default=2)
    ap.add_argument("--die-version", type=int, default=3,
                    help="0 disables the death")
    args = ap.parse_args()

    env = dict(os.environ)
    env.update({
        "SOAK_WORKER": "1",
        "SOAK_ROWS_PER_RANK": str(args.rows // args.world),
        "SOAK_K": str(args.k),
        "SOAK_ITERS": str(args.iters),
        "SOAK_HASH_DIM": str(args.hash_dim),
    })
    if args.die_version > 0:
        env["SOAK_DIE"] = f"{args.die_rank}:{args.die_version}"

    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "rabit_tpu.tracker.launch_local",
         "-n", str(args.world), "--",
         sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True)
    wall = time.perf_counter() - t0
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr[-4000:])
    if proc.returncode != 0:
        print(f"FAILED rc={proc.returncode}")
        return proc.returncode

    out = proc.stdout + proc.stderr
    gaps = [(int(m.group(1)), float(m.group(2))) for m in re.finditer(
        r"SOAK iter v(\d+)->v\d+ gap=([0-9.]+)s", out)]
    assert "SOAK final-agreement OK" in out, "final agreement missing"
    # gap v->v+1 containing the death (degraded iteration), then the
    # reform iteration (device plane rebuilt + shard re-upload), then
    # steady state again
    pre = [g for v, g in gaps if v + 1 < args.die_version]
    death = [g for v, g in gaps if v + 1 == args.die_version]
    reform = [g for v, g in gaps if v == args.die_version]
    post = [g for v, g in gaps if v > args.die_version]
    summary = {
        "world": args.world, "rows": args.rows, "iters": args.iters,
        "hash_dim": args.hash_dim, "wall_s": round(wall, 1),
        "iter_s_pre_death": round(
            1 / (sum(pre) / len(pre)), 3) if pre else None,
        "death_iter_gap_s": round(death[0], 3) if death else None,
        "reform_iter_gap_s": round(reform[0], 3) if reform else None,
        "iter_s_post_recovery": round(
            1 / (sum(post) / len(post)), 3) if post else None,
    }
    print("SOAK_SUMMARY " + json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
