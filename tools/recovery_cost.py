"""Measure full-barrier recovery cost vs world size.

The repo's recovery rendezvous is a full-world barrier (every rank
re-registers with the tracker after a failure) where the reference
repairs only broken links (reference: src/allreduce_base.cc:207-261).
doc/scaling.md argues detection latency, not the barrier, dominates at
the reference's design point — this tool turns that argument into a
measurement: run a small-payload iteration loop at world W, once
clean and once with a mid-run death (kill-point restart), and report
the wall-time difference = death + relaunch + full-barrier rendezvous
+ replay catch-up.

Usage: python tools/recovery_cost.py [--worlds 4,8,16,32] [--iters 30]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, ".")

WORKER = r"""
import os, sys
sys.path.insert(0, os.getcwd())
import numpy as np
import rabit_tpu

niter = int(sys.argv[1])
rabit_tpu.init(rabit_engine="mock")
rank = rabit_tpu.get_rank()
world = rabit_tpu.get_world_size()
version, _ = rabit_tpu.load_checkpoint()
for it in range(version, niter):
    a = np.ones(1024, np.float32) * (rank + it)
    rabit_tpu.allreduce(a, rabit_tpu.SUM)
    expect = sum(r + it for r in range(world))
    np.testing.assert_allclose(a, expect)
    rabit_tpu.checkpoint(float(it + 1))
rabit_tpu.finalize()
"""


def run_once(world: int, iters: int, die: bool) -> float:
    from rabit_tpu.tracker.launch_local import launch

    path = "/tmp/recovery_cost_worker.py"
    with open(path, "w") as f:
        f.write(WORKER)
    env = {"RABIT_TIMEOUT_SEC": "20"}
    if die:
        # rank 1 dies at version 1, seq 0, first life (mock kill-point)
        env["RABIT_MOCK"] = "1,1,0,0"
    t0 = time.monotonic()
    code = launch(world, [sys.executable, path, str(iters)],
                  extra_env=env, watchdog_sec=15)
    took = time.monotonic() - t0
    assert code == 0, f"world {world} die={die}: exit {code}"
    return took


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--worlds", default="4,8,16,32")
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()
    for w in map(int, args.worlds.split(",")):
        clean = min(run_once(w, args.iters, False) for _ in range(2))
        faulty = min(run_once(w, args.iters, True) for _ in range(2))
        print(f"world {w:3d}: clean {clean:6.2f}s  one-death {faulty:6.2f}s"
              f"  recovery cost ~{faulty - clean:5.2f}s", flush=True)


if __name__ == "__main__":
    main()
