"""Measure full-barrier recovery cost vs world size.

The repo's recovery rendezvous is a full-world barrier (every rank
re-registers with the tracker after a failure) where the reference
repairs only broken links (reference: src/allreduce_base.cc:207-261).
doc/scaling.md argues detection latency, not the barrier, dominates at
the reference's design point — this tool turns that argument into a
measurement.

Measurement (round 4): IN-RUN iteration gaps, not whole-run wall time.
Whole-run difference timing is noise-dominated on a 1-core box once
world reaches ~32 (two ~100 s runs of W timeshared interpreters swing
by ±20 s — round-4 runs measured NEGATIVE "recovery cost" that way).
Instead every rank stamps each iteration; rank 0 reports the median
gap and the global MAX single gap (allreduce-MAX).  In a clean run
max ≈ median; in a faulty run the death iteration's gap contains
detection + relaunch + full-barrier rendezvous + replay catch-up, so

    recovery cost ≈ max_gap(faulty) − median_gap(faulty)

immune to load outside the death window.

Usage: python tools/recovery_cost.py [--worlds 4,8,16,32] [--iters 30]
                                     [--die rank,ver,seq,life[;...]]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")

WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.getcwd())
import numpy as np
import rabit_tpu

niter = int(sys.argv[1])
rabit_tpu.init(rabit_engine="mock")
rank = rabit_tpu.get_rank()
world = rabit_tpu.get_world_size()
version, _ = rabit_tpu.load_checkpoint()
stamps = [time.monotonic()]
for it in range(version, niter):
    a = np.ones(1024, np.float32) * (rank + it)
    rabit_tpu.allreduce(a, rabit_tpu.SUM)
    expect = sum(r + it for r in range(world))
    np.testing.assert_allclose(a, expect)
    rabit_tpu.checkpoint(float(it + 1))
    stamps.append(time.monotonic())
gaps = np.diff(np.asarray(stamps))
# global max single-iteration gap: the death window shows up here on
# every survivor (they block on the dead rank's relaunch)
mx = np.array([gaps.max() if gaps.size else 0.0])
rabit_tpu.allreduce(mx, rabit_tpu.MAX)
if rank == 0 and "RABIT_COST_FILE" in os.environ:
    with open(os.environ["RABIT_COST_FILE"], "w") as f:
        json.dump({"median_gap": float(np.median(gaps)),
                   "max_gap": float(mx[0])}, f)
rabit_tpu.finalize()
"""


def run_once(world: int, iters: int, die: str | None) -> dict:
    from rabit_tpu.tracker.launch_local import launch

    path = "/tmp/recovery_cost_worker.py"
    with open(path, "w") as f:
        f.write(WORKER)
    cost_file = f"/tmp/recovery_cost_{os.getpid()}_{world}.json"
    env = {"RABIT_TIMEOUT_SEC": "20", "RABIT_COST_FILE": cost_file}
    if die:
        env["RABIT_MOCK"] = die
    code = launch(world, [sys.executable, path, str(iters)],
                  extra_env=env, watchdog_sec=15)
    assert code == 0, f"world {world} die={die}: exit {code}"
    with open(cost_file) as f:
        out = json.load(f)
    os.unlink(cost_file)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--worlds", default="4,8,16,32")
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--die", default="1,1,0,0",
                    help="mock kill-point plan for the faulty runs "
                         "(rank,version,seq,life[;...] — multiple entries "
                         "= multiple deaths)")
    args = ap.parse_args()
    ndeaths = len(args.die.split(";"))
    for w in map(int, args.worlds.split(",")):
        clean = run_once(w, args.iters, None)
        faulty = run_once(w, args.iters, args.die)
        cost = faulty["max_gap"] - faulty["median_gap"]
        print(f"world {w:3d}: clean med/max "
              f"{clean['median_gap'] * 1e3:7.1f}/"
              f"{clean['max_gap'] * 1e3:7.1f} ms   "
              f"{ndeaths}-death med/max "
              f"{faulty['median_gap'] * 1e3:7.1f}/"
              f"{faulty['max_gap'] * 1e3:7.1f} ms   "
              f"recovery cost ~{cost:5.2f}s", flush=True)


if __name__ == "__main__":
    main()
