"""Microbench: isolate per-step cost of the fused kmeans stats kernel.

Each variant runs ITERS chained stats passes (fori_loop; centroids fed
back so nothing is DCE'd), one host sync.  Measurement only — variant
"maxcmp" allows argmax ties (not for production).
"""
from __future__ import annotations

import functools
import sys
import time

import numpy as np

sys.path.insert(0, ".")

N, D, K, ITERS = 1 << 19, 256, 64, 50


def build_kernel(mode: str):
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    def kernel_t(x_ref, cn_ref, valid_ref, sums_ref, counts_ref):
        # transposed one-hot: both matmuls natural layout, no relayout
        i = pl.program_id(0)
        x = x_ref[:]
        block, _ = x.shape
        k = cn_ref.shape[0]
        sim = jnp.dot(x, cn_ref[:].T, preferred_element_type=jnp.float32)
        assign = jnp.argmax(sim, axis=1)                     # (block,)
        rows = lax.broadcasted_iota(jnp.int32, (k, block), 0)
        onehot_t = (rows == assign[None, :]).astype(jnp.float32)
        onehot_t = onehot_t * valid_ref[:]                   # (1, block)
        part_sums = jnp.dot(onehot_t.astype(x.dtype), x,
                            preferred_element_type=jnp.float32)
        part_counts = jnp.sum(onehot_t, axis=1)[:, None]     # (k, 1)

        @pl.when(i == 0)
        def _():
            sums_ref[:] = part_sums
            counts_ref[:] = part_counts

        @pl.when(i != 0)
        def _():
            sums_ref[:] = sums_ref[:] + part_sums
            counts_ref[:] = counts_ref[:] + part_counts

    if mode == "argmaxT":
        return kernel_t

    def kernel(x_ref, cn_ref, valid_ref, sums_ref, counts_ref):
        i = pl.program_id(0)
        x = x_ref[:]
        block, _ = x.shape
        k = cn_ref.shape[0]
        sim = jnp.dot(x, cn_ref[:].T, preferred_element_type=jnp.float32)
        if mode == "maxcmp":
            rowmax = jnp.max(sim, axis=1, keepdims=True)
            onehot = (sim >= rowmax).astype(jnp.float32)
        elif mode == "simonly":
            onehot = jnp.clip(sim, 0.0, 1.0)
        else:
            assign = jnp.argmax(sim, axis=1)
            cols = lax.broadcasted_iota(jnp.int32, (block, k), 1)
            onehot = (cols == assign[:, None]).astype(jnp.float32)
        if mode != "novalid":
            onehot = onehot * valid_ref[:]
        part_sums = lax.dot_general(
            onehot.astype(x.dtype), x, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        part_counts = jnp.sum(onehot, axis=0)[None, :]

        @pl.when(i == 0)
        def _():
            sums_ref[:] = part_sums
            counts_ref[:] = part_counts

        @pl.when(i != 0)
        def _():
            sums_ref[:] = sums_ref[:] + part_sums
            counts_ref[:] = counts_ref[:] + part_counts

    return kernel


def build_loop(mode: str, block: int, dtype: str, vmem_mb: int,
               iters: int = ITERS):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    cdt = jnp.dtype(dtype)
    kernel = build_kernel(mode)

    def stats(cnorm, x, valid):
        nb = N // block
        params = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=vmem_mb << 20)
        if mode == "argmaxT":
            sums, counts = pl.pallas_call(
                kernel,
                grid=(nb,),
                in_specs=[
                    pl.BlockSpec((block, D), lambda i: (i, 0)),
                    pl.BlockSpec((K, D), lambda i: (0, 0)),
                    pl.BlockSpec((1, block), lambda i: (0, i)),
                ],
                out_specs=(
                    pl.BlockSpec((K, D), lambda i: (0, 0)),
                    pl.BlockSpec((K, 1), lambda i: (0, 0)),
                ),
                out_shape=(
                    jax.ShapeDtypeStruct((K, D), jnp.float32),
                    jax.ShapeDtypeStruct((K, 1), jnp.float32),
                ),
                compiler_params=params,
            )(x, cnorm, valid.reshape(1, N))
            return sums, counts.T
        sums, counts = pl.pallas_call(
            kernel,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((block, D), lambda i: (i, 0)),
                pl.BlockSpec((K, D), lambda i: (0, 0)),
                pl.BlockSpec((block, 1), lambda i: (i, 0)),
            ],
            out_specs=(
                pl.BlockSpec((K, D), lambda i: (0, 0)),
                pl.BlockSpec((1, K), lambda i: (0, 0)),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((K, D), jnp.float32),
                jax.ShapeDtypeStruct((1, K), jnp.float32),
            ),
            compiler_params=params,
        )(x, cnorm, valid.reshape(N, 1))
        return sums, counts

    @jax.jit
    def run(cent, x, valid):
        x = x.astype(cdt)

        def one(_, c):
            cn = c / (jnp.linalg.norm(c, axis=1, keepdims=True) + 1e-12)
            sums, counts = stats(cn.astype(cdt), x, valid)
            new = jnp.where(counts.T > 0, sums / jnp.maximum(counts.T, 1.0),
                            c)
            return new

        return jax.lax.fori_loop(0, iters, one, cent)

    return run


def main():
    import jax
    import jax.numpy as jnp

    specs = sys.argv[1:] or [
        "argmax:2048:bfloat16:16", "maxcmp:2048:bfloat16:16",
        "simonly:2048:bfloat16:16", "argmax:4096:bfloat16:64",
        "argmax:8192:bfloat16:64", "maxcmp:8192:bfloat16:64",
        "argmax:8192:float32:100", "simonly:8192:bfloat16:64",
    ]
    rng = np.random.default_rng(0)
    x = jax.device_put(jnp.asarray(
        rng.standard_normal((N, D)).astype(np.float32)))
    c = jax.device_put(jnp.asarray(
        rng.standard_normal((K, D)).astype(np.float32)))
    v = jax.device_put(jnp.ones(N, dtype=jnp.float32))
    print("backend:", jax.default_backend())
    # difference timing: the axon tunnel adds a ~95 ms fixed round-trip
    # per fetched execution, and loop-invariant bodies get hoisted — so
    # time (long - short) chained runs of the REAL recurrent loop and
    # divide by the iteration difference to cancel the fixed cost.
    short, long_ = 50, 500
    for spec in specs:
        mode, block, dtype, vmem = spec.split(":")
        try:
            fns = build_loop(mode, int(block), dtype, int(vmem), short)
            fnl = build_loop(mode, int(block), dtype, int(vmem), long_)
            np.asarray(fns(c, x, v)); np.asarray(fnl(c, x, v))
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter(); np.asarray(fns(c, x, v))
                ts = time.perf_counter() - t0
                t0 = time.perf_counter(); np.asarray(fnl(c, x, v))
                tl = time.perf_counter() - t0
                best = min(best, (tl - ts) / (long_ - short))
            print(f"{spec:28s} {best*1e3:8.3f} ms/iter  "
                  f"{N/best/1e6:8.1f} Mpoints/s")
        except Exception as e:
            msg = str(e).split("\n")[0][:120]
            print(f"{spec:28s} FAILED: {type(e).__name__}: {msg}")


if __name__ == "__main__":
    main()
