"""Microbench: isolate per-step cost of the fused kmeans stats kernel.

Each variant runs ITERS chained stats passes (fori_loop; centroids fed
back so nothing is DCE'd), one host sync.  Measurement only — variant
"maxcmp" allows argmax ties (not for production).
"""
from __future__ import annotations

import functools
import sys
import time

import numpy as np

sys.path.insert(0, ".")

N, D, K, ITERS = 1 << 19, 256, 64, 50


def build_kernel(mode: str):
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    def make_kernel_t(classify):
        """Transposed-one-hot kernel (both matmuls natural layout, no
        relayout) with a pluggable classify stage, so the decomposition
        probes share EVERY line with the production formulation except
        the stage under test.  ``classify(sim, valid_ref, block, k)``
        returns ``(onehot_t, keep)``; ``keep`` (folded into counts) is
        the probe's device-side anchor that stops the sim matmul from
        being DCE'd when onehot_t does not depend on it."""

        def kernel_t(x_ref, cn_ref, valid_ref, sums_ref, counts_ref):
            i = pl.program_id(0)
            x = x_ref[:]
            block, _ = x.shape
            k = cn_ref.shape[0]
            sim = jnp.dot(x, cn_ref[:].T,
                          preferred_element_type=jnp.float32)
            onehot_t, keep = classify(sim, valid_ref, block, k)
            part_sums = jnp.dot(onehot_t.astype(x.dtype), x,
                                preferred_element_type=jnp.float32)
            part_counts = jnp.sum(onehot_t, axis=1)[:, None] + keep

            @pl.when(i == 0)
            def _():
                sums_ref[:] = part_sums
                counts_ref[:] = part_counts

            @pl.when(i != 0)
            def _():
                sums_ref[:] = sums_ref[:] + part_sums
                counts_ref[:] = counts_ref[:] + part_counts

        return kernel_t

    def classify_argmax(sim, valid_ref, block, k):
        # the production stage (ops/kmeans_kernel.py _stats_kernel)
        assign = jnp.argmax(sim, axis=1)                     # (block,)
        rows = lax.broadcasted_iota(jnp.int32, (k, block), 0)
        onehot_t = (rows == assign[None, :]).astype(jnp.float32)
        return onehot_t * valid_ref[:], jnp.float32(0)       # (1, block)

    def classify_none(sim, valid_ref, block, k):
        # simonlyT: both matmuls, NO classify — isolates matmuls + DMA;
        # the thin-slice reduce keeps the sim matmul alive
        onehot_t = jnp.broadcast_to(valid_ref[:], (k, block)
                                    ).astype(jnp.float32)
        return onehot_t, jnp.sum(sim[:, :1])

    def classify_cheap(sim, valid_ref, block, k):
        # cheapassignT: one-hot build kept, argmax replaced by a free
        # iota%k assignment — isolates the argmax reduce (same
        # thin-slice keep-alive as classify_none: an integer *0 would
        # be constant-folded and let the sim matmul be DCE'd)
        assign = lax.broadcasted_iota(jnp.int32, (block,), 0) % k
        rows = lax.broadcasted_iota(jnp.int32, (k, block), 0)
        onehot_t = (rows == assign[None, :]).astype(jnp.float32)
        return onehot_t * valid_ref[:], jnp.sum(sim[:, :1])

    if mode in ("argmaxT", "simonlyT", "cheapassignT"):
        return make_kernel_t({"argmaxT": classify_argmax,
                              "simonlyT": classify_none,
                              "cheapassignT": classify_cheap}[mode])

    def kernel(x_ref, cn_ref, valid_ref, sums_ref, counts_ref):
        i = pl.program_id(0)
        x = x_ref[:]
        block, _ = x.shape
        k = cn_ref.shape[0]
        sim = jnp.dot(x, cn_ref[:].T, preferred_element_type=jnp.float32)
        if mode == "maxcmp":
            rowmax = jnp.max(sim, axis=1, keepdims=True)
            onehot = (sim >= rowmax).astype(jnp.float32)
        elif mode == "simonly":
            onehot = jnp.clip(sim, 0.0, 1.0)
        else:
            assign = jnp.argmax(sim, axis=1)
            cols = lax.broadcasted_iota(jnp.int32, (block, k), 1)
            onehot = (cols == assign[:, None]).astype(jnp.float32)
        if mode != "novalid":
            onehot = onehot * valid_ref[:]
        part_sums = lax.dot_general(
            onehot.astype(x.dtype), x, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        part_counts = jnp.sum(onehot, axis=0)[None, :]

        @pl.when(i == 0)
        def _():
            sums_ref[:] = part_sums
            counts_ref[:] = part_counts

        @pl.when(i != 0)
        def _():
            sums_ref[:] = sums_ref[:] + part_sums
            counts_ref[:] = counts_ref[:] + part_counts

    return kernel


def build_loop(mode: str, block: int, dtype: str, vmem_mb: int,
               iters: int = ITERS):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    cdt = jnp.dtype(dtype)
    kernel = build_kernel(mode)

    def stats(cnorm, x, valid):
        nb = N // block
        params = pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=vmem_mb << 20)
        if mode in ("argmaxT", "simonlyT", "cheapassignT"):
            sums, counts = pl.pallas_call(
                kernel,
                grid=(nb,),
                in_specs=[
                    pl.BlockSpec((block, D), lambda i: (i, 0)),
                    pl.BlockSpec((K, D), lambda i: (0, 0)),
                    pl.BlockSpec((1, block), lambda i: (0, i)),
                ],
                out_specs=(
                    pl.BlockSpec((K, D), lambda i: (0, 0)),
                    pl.BlockSpec((K, 1), lambda i: (0, 0)),
                ),
                out_shape=(
                    jax.ShapeDtypeStruct((K, D), jnp.float32),
                    jax.ShapeDtypeStruct((K, 1), jnp.float32),
                ),
                compiler_params=params,
            )(x, cnorm, valid.reshape(1, N))
            return sums, counts.T
        sums, counts = pl.pallas_call(
            kernel,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((block, D), lambda i: (i, 0)),
                pl.BlockSpec((K, D), lambda i: (0, 0)),
                pl.BlockSpec((block, 1), lambda i: (i, 0)),
            ],
            out_specs=(
                pl.BlockSpec((K, D), lambda i: (0, 0)),
                pl.BlockSpec((1, K), lambda i: (0, 0)),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((K, D), jnp.float32),
                jax.ShapeDtypeStruct((1, K), jnp.float32),
            ),
            compiler_params=params,
        )(x, cnorm, valid.reshape(N, 1))
        return sums, counts

    @jax.jit
    def run(cent, x, valid):
        x = x.astype(cdt)

        def one(_, c):
            cn = c / (jnp.linalg.norm(c, axis=1, keepdims=True) + 1e-12)
            sums, counts = stats(cn.astype(cdt), x, valid)
            new = jnp.where(counts.T > 0, sums / jnp.maximum(counts.T, 1.0),
                            c)
            return new

        return jax.lax.fori_loop(0, iters, one, cent)

    return run


def main():
    import jax
    import jax.numpy as jnp

    specs = sys.argv[1:] or [
        "argmax:2048:bfloat16:16", "maxcmp:2048:bfloat16:16",
        "simonly:2048:bfloat16:16", "argmax:4096:bfloat16:64",
        "argmax:8192:bfloat16:64", "maxcmp:8192:bfloat16:64",
        "argmax:8192:float32:100", "simonly:8192:bfloat16:64",
    ]
    rng = np.random.default_rng(0)
    x = jax.device_put(jnp.asarray(
        rng.standard_normal((N, D)).astype(np.float32)))
    c = jax.device_put(jnp.asarray(
        rng.standard_normal((K, D)).astype(np.float32)))
    v = jax.device_put(jnp.ones(N, dtype=jnp.float32))
    print("backend:", jax.default_backend())
    # difference timing: the axon tunnel adds a ~95 ms fixed round-trip
    # per fetched execution, and loop-invariant bodies get hoisted — so
    # time (long - short) chained runs of the REAL recurrent loop and
    # divide by the iteration difference to cancel the fixed cost.
    # bench.py's measurement discipline: candidates interleaved across
    # trials (a load burst hits every spec, not one), MEDIAN of the
    # per-trial difference timings, non-positive/absurd diffs dropped —
    # a min over differences of noisy pairs is biased low and once
    # measured an impossible 4.8 TB/s here.
    short, long_, trials = 50, 500, 5
    import statistics

    fns = {}
    for spec in specs:
        mode, block, dtype, vmem = spec.split(":")
        try:
            fs = build_loop(mode, int(block), dtype, int(vmem), short)
            fl = build_loop(mode, int(block), dtype, int(vmem), long_)
            np.asarray(fs(c, x, v)); np.asarray(fl(c, x, v))
            fns[spec] = (fs, fl)
        except Exception as e:
            msg = str(e).split("\n")[0][:120]
            print(f"{spec:28s} FAILED: {type(e).__name__}: {msg}")
    samples: dict = {s: [] for s in fns}
    for _ in range(trials):
        for spec, (fs, fl) in fns.items():
            try:
                t0 = time.perf_counter(); np.asarray(fs(c, x, v))
                ts = time.perf_counter() - t0
                t0 = time.perf_counter(); np.asarray(fl(c, x, v))
                tl = time.perf_counter() - t0
            except Exception as e:  # noqa: BLE001 — keep other specs' data
                msg = str(e).split("\n")[0][:120]
                print(f"{spec:28s} trial FAILED: {type(e).__name__}: {msg}")
                continue
            dt = (tl - ts) / (long_ - short)
            if dt > 0:
                samples[spec].append(dt)
    for spec, xs in samples.items():
        if not xs:
            print(f"{spec:28s} no valid trials")
            continue
        med = statistics.median(xs)
        spread = 100.0 * (max(xs) - min(xs)) / med
        print(f"{spec:28s} {med*1e3:8.3f} ms/iter  "
              f"{N/med/1e6:8.1f} Mpoints/s  "
              f"(n={len(xs)} spread {spread:.0f}%)")


if __name__ == "__main__":
    main()
