// C++ public-API smoke test: the reference's bring-up path
// (reference: guide/basic.cc + src/engine_empty.cc) against the
// world-of-1 empty engine — exercises templates, streams, checkpoints.
// Compiled and run by tests/test_native_api.py.
#include <cassert>
#include <cstdio>
#include <vector>

#include "rabit_tpu/rabit_tpu.h"
#include "rabit_tpu/timer.h"

namespace rt = rabit_tpu;

struct Model : public rt::ISerializable {
  std::vector<float> weights;
  void Load(rt::IStream& fi) override { fi.ReadVector(&weights); }
  void Save(rt::IStream& fo) const override { fo.WriteVector(weights); }
};

int main(int argc, char* argv[]) {
  const char* args[] = {"rabit_engine=empty"};
  (void)argc;
  (void)argv;
  rt::InitEngine({args[0]});

  assert(rt::GetRank() == 0);
  assert(rt::GetWorldSize() == 1);
  assert(!rt::IsDistributed());

  double t0 = rt::GetTime();

  // allreduce templates: identity at world=1, but exercises dispatch
  float fbuf[4] = {1.f, 2.f, 3.f, 4.f};
  rt::Allreduce<rt::op::Sum>(fbuf, 4);
  assert(fbuf[2] == 3.f);
  int32_t ibuf[3] = {5, -1, 7};
  rt::Allreduce<rt::op::Max>(ibuf, 3);
  assert(ibuf[1] == -1);
  bool prepared = false;
  rt::Allreduce<rt::op::Sum>(fbuf, 4, [&] { prepared = true; });
  assert(prepared);

  // broadcast overloads
  std::string s = "hello";
  rt::Broadcast(&s, 0);
  assert(s == "hello");
  std::vector<int32_t> v = {1, 2, 3};
  rt::Broadcast(&v, 0);
  assert(v.size() == 3);

  // allgather (world=1: identity block)
  int64_t mine[2] = {41, 42};
  std::vector<int64_t> gathered;
  rt::Allgather(mine, 2, &gathered);
  assert(gathered.size() == 2 && gathered[1] == 42);

  // checkpoint round-trip through the serialization streams
  Model m;
  int version = rt::LoadCheckPoint(&m);
  assert(version == 0);
  m.weights = {0.5f, 1.5f};
  rt::CheckPoint(&m);
  assert(rt::VersionNumber() == 1);
  Model m2;
  version = rt::LoadCheckPoint(&m2);
  assert(version == 1);
  assert(m2.weights.size() == 2 && m2.weights[1] == 1.5f);

  // lazy checkpoint (empty engine: eager default path)
  m.weights = {7.0f};
  rt::LazyCheckPoint(&m);
  assert(rt::VersionNumber() == 2);
  Model m3;
  assert(rt::LoadCheckPoint(&m3) == 2);
  assert(m3.weights.size() == 1 && m3.weights[0] == 7.0f);

  // memory streams standalone
  char raw[64];
  rt::MemoryFixSizeBuffer fix(raw, sizeof(raw));
  fix.WritePod<double>(2.75);
  fix.Seek(0);
  double d = 0;
  assert(fix.ReadPod(&d) && d == 2.75);

  assert(rt::GetTime() >= t0);
  rt::Finalize();
  std::printf("api_smoke OK\n");
  return 0;
}
