// Custom-reducer integration test: Reducer<> (flat struct argmax) and
// SerializeReducer<> (variable-content set union in a fixed slot),
// reduced across a multi-worker job — the reference's ReduceHandle
// surface (reference: include/rabit.h:236-326) on the native engine.
// Run under the launcher by tests/test_native_api.py.
#include <cassert>
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "rabit_tpu/rabit_tpu.h"

namespace rt = rabit_tpu;

struct ArgMax {
  float value;
  int32_t index;
};

static void ArgMaxReduce(ArgMax& dst, const ArgMax& src) {
  if (src.value > dst.value) dst = src;
}

// A small sorted-set that unions under reduction.
struct SmallSet : public rt::ISerializable {
  std::vector<int32_t> items;
  void Load(rt::IStream& fi) override { fi.ReadVector(&items); }
  void Save(rt::IStream& fo) const override { fo.WriteVector(items); }
  void Reduce(const SmallSet& src, size_t /*max_nbyte*/) {
    std::vector<int32_t> merged;
    merged.reserve(items.size() + src.items.size());
    size_t a = 0, b = 0;
    while (a < items.size() || b < src.items.size()) {
      int32_t next;
      if (b >= src.items.size() ||
          (a < items.size() && items[a] <= src.items[b])) {
        next = items[a++];
      } else {
        next = src.items[b++];
      }
      if (merged.empty() || merged.back() != next) merged.push_back(next);
    }
    items = std::move(merged);
  }
};

int main(int argc, char* argv[]) {
  rt::Init(argc - 1, argv + 1);
  int rank = rt::GetRank();
  int world = rt::GetWorldSize();

  // Reducer: per-lane argmax; lane i peaks at rank (i % world)
  const int kLanes = 5;
  ArgMax lanes[kLanes];
  bool prepared = false;
  rt::Reducer<ArgMax, ArgMaxReduce> red;
  red.Allreduce(lanes, kLanes, [&] {
    prepared = true;
    for (int i = 0; i < kLanes; ++i) {
      lanes[i].value = (rank == i % world) ? 100.0f + i : float(rank);
      lanes[i].index = rank;
    }
  });
  // On a fresh run prepare must fire; on a restarted life the result is
  // replayed from the robust cache and prepare is (correctly) skipped.
  const char* trial_env = std::getenv("RABIT_NUM_TRIAL");
  int trial = trial_env != nullptr ? std::atoi(trial_env) : 0;
  assert(prepared == (trial == 0));
  for (int i = 0; i < kLanes; ++i) {
    assert(lanes[i].value == 100.0f + i);
    assert(lanes[i].index == i % world);
  }

  // SerializeReducer: union of {rank, rank + world, 7}
  SmallSet sets[2];
  sets[0].items = {rank, rank + world};
  sets[1].items = {7};
  rt::SerializeReducer<SmallSet> sred;
  sred.Allreduce(sets, 256, 2);
  assert(static_cast<int>(sets[0].items.size()) == 2 * world);
  for (int r = 0; r < world; ++r) {
    assert(sets[0].items[r] == r);
    assert(sets[0].items[world + r] == world + r);
  }
  assert(sets[1].items.size() == 1 && sets[1].items[0] == 7);

  rt::TrackerPrint("custom_reduce rank " + std::to_string(rank) + " OK\n");
  rt::Finalize();
  std::printf("custom_reduce OK\n");
  return 0;
}
