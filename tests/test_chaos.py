"""Chaos subsystem: deterministic wire-fault injection + the hardening
it forces.

Four layers under test (doc/fault_tolerance.md "Chaos testing"):

* the plan itself — seeded schedules replay bit for bit, malformed
  specs fail loudly, rank scoping works;
* the per-fault-kind recovery matrix on pysocket+pyrobust — mid-stream
  reset, refused/timed-out reconnect (retry + backoff), partial-write
  splits, EINTR, and a stall past ``rabit_timeout_sec`` — all
  self-verified bit-exact by the workers;
* bounded graceful failure — ``RecoveryError`` with the attempt history
  when the recover budget is exhausted, and async-pump death poisoning
  pending handles so ``wait()`` raises instead of hanging;
* the tracker — a registrant lost mid-barrier re-opens the round; plus
  the engine-hygiene lint (no silent exception swallows) and the
  slow-marked randomized chaos soak gate with its obs-timeline pairing.
"""
import ast
import json
import pathlib
import socket
import sys
import time

import pytest

pytestmark = pytest.mark.chaos

REPO = pathlib.Path(__file__).resolve().parent.parent


def _launch(worker, world, env, args=("1000", "3"), obs_dir=None):
    from rabit_tpu.tracker.launch_local import launch

    env = {"RABIT_BACKOFF_BASE_MS": "10", **env}
    return launch(world, [sys.executable, f"tests/workers/{worker}.py",
                          *args], extra_env=env, obs_dir=obs_dir)


# ------------------------------------------------------------- the plan
def _drive(plan, n=400):
    """A fixed consult sequence: alternating connect-site and io-site
    touchpoints, injected faults swallowed (the schedule, not the
    effect, is under test)."""
    for _ in range(n):
        for site in ("connect", "tracker"):
            try:
                plan.connect(site)
            except OSError:
                pass
        plan.io()
    return plan.log


def test_seeded_schedule_determinism():
    """Same seed ⇒ bit-identical injection log; different seed ⇒ a
    different schedule (the reproducibility contract chaos CI rests
    on)."""
    from rabit_tpu.chaos import parse_plan

    spec = ("17:refuse@connect=0.2;cto@tracker=0.1;reset@io=0.05*3;"
            "partial@io=0.2;stall@io=0.05;stallms=0;budget=100")
    log_a = _drive(parse_plan(spec, identity="2"))
    log_b = _drive(parse_plan(spec, identity="2"))
    assert log_a and log_a == log_b
    log_c = _drive(parse_plan(spec.replace("17:", "18:", 1), identity="2"))
    assert log_c != log_a
    # identity is part of the key: another rank draws another schedule
    log_d = _drive(parse_plan(spec, identity="3"))
    assert log_d != log_a


def test_plan_budget_and_limits():
    from rabit_tpu.chaos import parse_plan

    plan = parse_plan("5:partial@io=1.0*4;stall@io=1.0;stallms=0;budget=7",
                      identity="0")
    for _ in range(50):
        plan.io()
    assert plan.injected == 7  # global budget is a hard cap
    kinds = [k for _, k, _, _ in plan.log]
    assert kinds.count("partial") == 4  # per-rule *limit respected


def test_plan_rank_scoping():
    from rabit_tpu.chaos import parse_plan

    spec = "9:partial@io=1.0;ranks=1|3"
    active = parse_plan(spec, identity="3")
    inert = parse_plan(spec, identity="0")
    active.io()
    inert.io()
    assert active.log and not inert.log


def test_malformed_specs_fail_loudly():
    from rabit_tpu.chaos import parse_plan
    from rabit_tpu.utils.checks import RabitError

    for bad in ("no-seed-here", "x:reset@io=0.1", "1:frobnicate=0.1",
                "1:reset@tracker=0.1", "1:refuse@io=0.1", "1:reset@io=2.0",
                "1:reset@io=abc", "1:", "1:stallms=5",
                # accept admits only stall: a refused accept has no
                # retry path (the dialing peer owns the retry)
                "1:refuse@accept=0.1", "1:cto@accept=0.1"):
        with pytest.raises((RabitError, ValueError)):
            parse_plan(bad, identity="0")


# ---------------------------------------- per-fault-kind recovery matrix
def test_reset_mid_allreduce_recovers():
    """A mid-stream RST on an established link cascades every rank into
    a recover rendezvous and the job completes bit-exact (the worker
    asserts every collective's numeric result)."""
    assert _launch("model_recover", 4,
                   {"RABIT_ENGINE": "pyrobust",
                    "RABIT_CHAOS": "5:reset@io=1.0*1;ranks=1",
                    "RABIT_TIMEOUT_SEC": "10"}) == 0


@pytest.mark.parametrize("engine", ["pysocket", "pyrobust"])
@pytest.mark.parametrize("kind", ["refuse", "cto"])
def test_refused_reconnect_retries(engine, kind):
    """Every worker's first two peer dials fail (refused or timed out —
    a peer merely slow to reach listen()): the capped-backoff retry
    absorbs them on BOTH python engines; before the retry existed one
    refused SYN during rendezvous killed the worker."""
    assert _launch("check_basic", 4,
                   {"RABIT_ENGINE": engine,
                    "RABIT_CHAOS": f"11:{kind}@connect=1.0*2"},
                   args=("2000",)) == 0


@pytest.mark.parametrize("engine", ["pysocket", "pyrobust"])
def test_partial_write_splits(engine):
    """Short read/write splits at a high rate: the partial-transfer
    loops in _send/_recv/_exchange/_exchange_v must reassemble the
    streams bit-exact (check_basic covers tree, ring, fused and
    broadcast paths), with injected EINTR mixed in."""
    assert _launch("check_basic", 4,
                   {"RABIT_ENGINE": engine,
                    "RABIT_CHAOS": ("13:partial@io=0.2*300;"
                                    "eintr@io=0.05*50")},
                   args=("4000",)) == 0


def test_stall_past_timeout_recovers():
    """A silent stall longer than rabit_timeout_sec: peers classify the
    wedged link as dead (LinkError), cascade into recovery, and the
    stalled rank rejoins when it wakes — completion, not a hang."""
    t0 = time.monotonic()
    assert _launch("model_recover", 4,
                   {"RABIT_ENGINE": "pyrobust",
                    "RABIT_CHAOS": "3:stall@io=1.0*1;stallms=4000;ranks=2",
                    "RABIT_TIMEOUT_SEC": "2"},
                   args=("500", "2")) == 0
    assert time.monotonic() - t0 < 90


def test_chaos_under_kill_points():
    """Wire faults and RABIT_MOCK kill-points compose: a reset, flaky
    dials and splits layered over the flagship two-deaths scenario."""
    assert _launch("model_recover", 4,
                   {"RABIT_ENGINE": "pyrobust",
                    "RABIT_MOCK": "0,0,1,0;1,1,1,0",
                    "RABIT_CHAOS": ("21:reset@io=0.01*1;"
                                    "refuse@connect=0.3*4;"
                                    "partial@io=0.1*100"),
                    "RABIT_TIMEOUT_SEC": "10"}) == 0


# ------------------------------------------------ bounded graceful failure
def test_recovery_error_when_budget_exhausted():
    """A recover rendezvous that cannot reach the tracker fails FAST
    with the typed RecoveryError carrying the full per-attempt failure
    history — never a spin past rabit_timeout_sec semantics."""
    from rabit_tpu.engine.robust import PyRobustEngine, RecoveryError

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()  # nothing listens here: instant ECONNREFUSED

    eng = PyRobustEngine()
    eng._tracker_addr = ("127.0.0.1", port)
    eng._timeout = 0.5
    eng._connect_retries = 1
    eng._backoff_base_ms = 1.0
    eng._recover_attempts = 3
    t0 = time.monotonic()
    with pytest.raises(RecoveryError) as ei:
        eng._rendezvous_recover()
    eng._close_links()
    assert time.monotonic() - t0 < 30  # fail-fast, not the 600 s floor
    assert len(ei.value.history) == 3
    assert all("Connection refused" in err for _, _, err in ei.value.history)
    # the narrative survives into the message for logs/postmortems
    assert "3 time(s)" in str(ei.value)
    from rabit_tpu.utils.checks import RabitError

    assert isinstance(ei.value, RabitError)  # old catch sites still work


def test_pump_death_poisons_pending_handles():
    """A BaseException killing the async progress pump must fail every
    pending (and future) handle so wait() raises — never hangs — and
    _fence() must wake instead of waiting on ops nobody will run."""
    from rabit_tpu.engine.interface import CollectiveHandle
    from rabit_tpu.engine.pysocket import AsyncPumpError, PySocketEngine

    eng = PySocketEngine()
    h1, h2 = CollectiveHandle(), CollectiveHandle()

    def boom():
        raise KeyboardInterrupt("injected pump death")

    eng._submit(boom, (h1,))
    eng._submit(lambda: None, (h2,))
    with pytest.raises((KeyboardInterrupt, AsyncPumpError)):
        h1.wait(timeout=30)
    with pytest.raises(AsyncPumpError):
        h2.wait(timeout=30)
    eng._fence()  # returns (poison zeroed the in-flight count)
    h3 = CollectiveHandle()
    eng._submit(lambda: None, (h3,))  # post-death issue fails at once
    with pytest.raises(AsyncPumpError):
        h3.wait(timeout=30)


# ----------------------------------------------------------- the tracker
def test_registrant_loss_reopens_round():
    """A worker that registers and then dies while parked in the
    rendezvous barrier must be swept out: without the sweep its corpse
    'fills' the round and the reply hands survivors a topology naming a
    dead worker; with it, the two live workers complete a clean world-2
    round."""
    from rabit_tpu.tracker import protocol as P
    from rabit_tpu.tracker.tracker import Tracker

    tr = Tracker(2, "127.0.0.1", 0)
    tr.start()

    def register(task_id):
        s = socket.create_connection((tr.host, tr.port), timeout=10)
        P.send_u32(s, P.MAGIC)
        P.send_str(s, P.CMD_START)
        P.send_str(s, task_id)
        P.send_u32(s, 2)
        P.send_str(s, "127.0.0.1")
        P.send_u32(s, 1)  # bogus data port; nobody will dial it
        return s

    try:
        corpse = register("corpse")
        time.sleep(0.2)  # let the tracker park it in the barrier
        corpse.close()   # dies mid-round
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with tr._pending_lock:
                if not tr._pending:
                    break  # swept
            time.sleep(0.1)
        else:
            pytest.fail("dead registrant never swept from the barrier")
        a, b = register("0"), register("1")
        topos = [P.TopologyReply.recv(x) for x in (a, b)]
        assert {t.rank for t in topos} == {0, 1}
        assert all(t.world == 2 for t in topos)
        a.close()
        b.close()
    finally:
        tr.stop()


# --------------------------------------------------- telemetry integration
def test_chaos_faults_visible_in_obs_report(tmp_path):
    """Injected faults are first-class telemetry: counters per kind,
    chaos/net events in each rank's trace, and the tracker's merged
    timeline pairs the faults with the retries/recoveries they forced."""
    assert _launch("model_recover", 3,
                   {"RABIT_ENGINE": "pyrobust",
                    "RABIT_CHAOS": ("29:reset@io=1.0*1;ranks=1;"
                                    "refuse@connect=0.5*3"),
                    "RABIT_TIMEOUT_SEC": "10"},
                   args=("500", "2"), obs_dir=str(tmp_path)) == 0
    rep = json.loads((tmp_path / "obs_report.json").read_text())
    agg = rep["aggregate"]
    assert agg["chaos.injected"]["max"] >= 1
    assert agg["chaos.injected.reset"]["max"] >= 1
    tl = rep["recovery_timeline"]
    names = [e["name"] for e in tl]
    assert "chaos" in names
    # the reset forced a recovery on some rank; a refusal (if any fired
    # before the budget) forced a backoff retry
    assert any(e["name"] == "recovery" and e.get("phase") == "link_error"
               for e in tl)
    if agg.get("chaos.injected.refuse", {}).get("max", 0) >= 1:
        assert agg["net.connect.retries"]["max"] >= 1
        assert any(e["name"] == "net" and e.get("phase") == "backoff"
                   for e in tl)


# ------------------------------------------------------- engine hygiene
def test_no_silent_exception_swallows_in_engine():
    """Structured-logger routing (PR 2) stays enforced: no handler in
    rabit_tpu/engine/ may catch a broad exception class and silently
    ``pass`` — a swallowed wire error is exactly how chaos bugs hide."""
    broad = {"Exception", "BaseException"}
    offenders = []
    # The live-telemetry modules (PR 10) process network-originated
    # frames — exactly where a silent swallow would hide a wire bug —
    # so they ride the same lint as the engines.
    obs_live = [REPO / "rabit_tpu" / "obs" / "export.py",
                REPO / "rabit_tpu" / "obs" / "span.py",
                REPO / "rabit_tpu" / "obs" / "adapt.py",
                # The causal-trace plane (ISSUE 17): hop records ride
                # the same network frames and the flight recorder runs
                # on fault paths — a swallow there erases the evidence.
                REPO / "rabit_tpu" / "obs" / "trace.py"]
    # The forensics CLIs (ISSUE 17) parse whatever a crash left behind
    # — they may skip malformed artifacts, but never silently.  The
    # serving-plane clients (ISSUE 20) own the hedge/retry/chaos-
    # detection paths: a swallow there un-pairs the chaos books or
    # hides a lost reply behind a retry.
    tools = [REPO / "rabit_tpu" / "tools" / "trace_report.py",
             REPO / "rabit_tpu" / "tools" / "postmortem.py",
             REPO / "rabit_tpu" / "tools" / "loadgen.py",
             REPO / "rabit_tpu" / "tools" / "serve.py"]
    # Every worker-worker byte now moves through rabit_tpu/transport/
    # (PR 12) — it IS the wire, so it rides the engine lint wholesale.
    # The wire codecs (PR 13) transform those bytes in the reduction
    # hot path — a swallowed encode error would surface as silently
    # wrong sums, so they ride the same lint.  The schedules (PR 14)
    # own the pipelined hop loops' error paths — a swallowed abort
    # there wedges a pumped link — so they ride it too.
    # The serving plane (ISSUE 15) answers network clients and runs a
    # collective control loop — a swallowed error there is a silently
    # wrong or wedged reply, so it rides the same lint.
    # The tracker control plane (ISSUE 16: sharded directory, shard
    # servers, launchers; ISSUE 19: directory replication + live
    # migration — tracker/*.py globs pick the new modules up) and the
    # chaos layer itself (a swallow in the injector hides the injected
    # fault from its own pairing gates) arbitrate every job's
    # membership and fault schedule — they ride it too.
    for path in sorted((REPO / "rabit_tpu" / "engine").glob("*.py")) \
            + sorted((REPO / "rabit_tpu" / "transport").glob("*.py")) \
            + sorted((REPO / "rabit_tpu" / "codec").glob("*.py")) \
            + sorted((REPO / "rabit_tpu" / "sched").glob("*.py")) \
            + sorted((REPO / "rabit_tpu" / "serve").glob("*.py")) \
            + sorted((REPO / "rabit_tpu" / "tracker").glob("*.py")) \
            + sorted((REPO / "rabit_tpu" / "chaos").glob("*.py")) \
            + obs_live + tools:
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            names = []
            t = node.type
            if t is None:
                names = [None]  # bare except:
            else:
                for e in (t.elts if isinstance(t, ast.Tuple) else [t]):
                    if isinstance(e, ast.Name):
                        names.append(e.id)
            is_broad = any(n is None or n in broad for n in names)
            only_pass = all(isinstance(s, ast.Pass) for s in node.body)
            if is_broad and only_pass:
                offenders.append(f"{path.name}:{node.lineno}")
    assert not offenders, (
        f"silent broad-exception swallows in engine/: {offenders} — "
        "route through the structured logger (rabit_tpu.obs.log)")


def test_obs_live_modules_hygiene():
    """The live-plane modules (obs/export.py, obs/span.py and the
    adaptive controller obs/adapt.py) must use no bare ``except:`` and
    no raw ``print`` — diagnostics route through the structured logger
    / tracker log like the engines'.  The tracker control plane
    (ISSUE 16) rides the same lint: a shard's stdout/stderr is service
    telemetry, not a print dumping ground."""
    offenders = []
    paths = [REPO / "rabit_tpu" / "obs" / name
             for name in ("export.py", "span.py", "adapt.py",
                          "trace.py")]
    paths += sorted((REPO / "rabit_tpu" / "tracker").glob("*.py"))
    # ISSUE 19: the replication/migration modules land via the
    # tracker/*.py glob above; the chaos layer (directory link sites)
    # rides the same hygiene bar.
    paths += sorted((REPO / "rabit_tpu" / "chaos").glob("*.py"))
    for path in paths:
        name = path.name
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                offenders.append(f"{name}:{node.lineno} bare except")
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                offenders.append(f"{name}:{node.lineno} raw print")
    assert not offenders, offenders


# ------------------------------------------------------- the soak gate
@pytest.mark.slow
def test_chaos_soak_gate(tmp_path):
    """Randomized seeded chaos soak (kills + resets + stalls + splits,
    world 4, both python engines): bit-exact results (the workers
    assert them), zero hangs (bounded by the runner's timeout), and an
    obs timeline in which every recovery-forcing fault pairs with a
    recovery/retry event."""
    from rabit_tpu.tools.soak import main as soak_main

    pyr = tmp_path / "pyrobust"
    assert soak_main(["--chaos", "--engine", "pyrobust", "--world", "4",
                      "--rounds", "2", "--ndata", "4000", "--niter", "5",
                      "--kills", "4", "--obs-dir", str(pyr)]) == 0
    pys = tmp_path / "pysocket"
    assert soak_main(["--chaos", "--engine", "pysocket", "--world", "4",
                      "--rounds", "1", "--ndata", "4000",
                      "--obs-dir", str(pys)]) == 0
    saw_chaos = False
    for report in sorted(pyr.glob("round*/obs_report.json")) + sorted(
            pys.glob("round*/obs_report.json")):
        rep = json.loads(report.read_text())
        agg = rep["aggregate"]
        tl = rep["recovery_timeline"]
        injected = agg.get("chaos.injected", {}).get("max", 0)
        if injected:
            saw_chaos = True
            assert any(e["name"] == "chaos" for e in tl), report
        # every mid-stream reset must pair with a link_error->recovery
        if agg.get("chaos.injected.reset", {}).get("max", 0) >= 1:
            assert any(e["name"] == "recovery"
                       and e.get("phase") == "link_error" for e in tl)
            assert any(e["name"] == "recovery"
                       and e.get("phase") == "resume" for e in tl)
        # every refused/timed-out dial must pair with a backoff retry
        if agg.get("chaos.injected.refuse", {}).get("max", 0) >= 1:
            assert agg["net.connect.retries"]["max"] >= 1
    assert saw_chaos, "soak rounds injected nothing — vacuous gate"
