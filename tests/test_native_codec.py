"""Compiled codec kernels (rabit_tpu/native/src/codec_kernels.c) —
the native<->numpy bit-identity contract behind ``rabit_codec_impl``.

The contracts pinned here:

* the ctypes seam (codec/kernel.py) degrades gracefully: ``numpy``
  forces the reference, ``native`` is LOUD when the library is
  missing, ``auto`` falls back with exactly one obs-visible warning
  and never an ImportError — a toolchain-free box stays green;
* hop math is BIT-identical across the seam for every block format
  (int8 / int4 / fp8e4m3 / fp8e5m2), block size and merge depth —
  wire bytes, hop-residual ledgers, decoded outputs and committed
  feedback residuals all compare bitwise, including the unrecorded
  (swing-style) merge side and adversarial payloads (all-zero and
  mixed-sign-zero blocks, 1e30 / 1e-38 magnitudes);
* the native bf16 elementwise merge matches the ml_dtypes reference
  bit for bit (subnormals, overflow-to-inf, rounding ties, NaN);
* fp8 formats decode exhaustively (all 256 codes) to the ml_dtypes
  ground truth and round-trip within the half-ulp + subnormal-quantum
  error envelope, with honest wire-byte accounting;
* end to end, per-rank result digests are identical for native vs
  numpy vs MIXED worlds across pipeline depths (the impl is not a
  collective decision), and pyrobust kill-point replay with the
  native kernels armed still serves bit-exact cached payloads;
* the resolved backend label reaches the live plane (/status rows,
  rabit_top's codec column) so a silent fallback is visible.

``make -C rabit_tpu/native smoke`` builds the library and runs this
file under ``-m "not slow"``.
"""
import io
import os
import pathlib
import sys

import numpy as np
import pytest

pytestmark = [pytest.mark.codec, pytest.mark.native_codec]

REPO = pathlib.Path(__file__).resolve().parent.parent

FMTS = ["int8", "int4", "fp8e4m3", "fp8e5m2"]


def _kernel():
    from rabit_tpu import codec

    return codec.load()


requires_native = pytest.mark.skipif(
    _kernel() is None,
    reason="librabit_codec.so not built (make -C rabit_tpu/native codec)")


def _launch(worker, world, extra_env=None, args=()):
    from rabit_tpu.tracker.launch_local import launch

    saved = os.environ.pop("RABIT_TRACKER_GROUPS", None)
    try:
        return launch(world, [sys.executable,
                              f"tests/workers/{worker}.py",
                              *map(str, args)], extra_env=extra_env or {})
    finally:
        if saved is not None:
            os.environ["RABIT_TRACKER_GROUPS"] = saved


def _payload(rng, n: int) -> np.ndarray:
    """Adversarial f32 payload: normals salted with exact zeros, signed
    zeros and extreme magnitudes — the cases where C-vs-numpy semantic
    drift (fmaxf vs np.maximum on ±0/NaN, rounding mode) would show."""
    v = rng.standard_normal(n).astype(np.float32)
    v[rng.random(n) < 0.10] = 0.0
    v[rng.random(n) < 0.05] = -0.0
    big = rng.random(n) < 0.05
    v[big] *= np.float32(1e30)
    v[rng.random(n) < 0.05] *= np.float32(1e-38)
    return v


# ----------------------------------------------------------- the seam
def test_resolve_impl_vocabulary():
    from rabit_tpu import codec
    from rabit_tpu.utils import RabitError

    assert codec.resolve_impl("numpy") == (None, "numpy")
    with pytest.raises(RabitError, match="rabit_codec_impl"):
        codec.resolve_impl("fortran")


def test_native_request_is_loud_or_loads():
    from rabit_tpu import codec
    from rabit_tpu.utils import RabitError

    if _kernel() is None:
        # explicit native on a toolchain-free box: a config error that
        # names the build command, never a silent numpy downgrade
        with pytest.raises(RabitError, match="make -C rabit_tpu/native"):
            codec.resolve_impl("native")
        assert codec.load_error()
    else:
        k, label = codec.resolve_impl("native")
        assert k is not None and label == "native"
        k, label = codec.resolve_impl("auto")
        assert k is not None and label == "native"


def test_auto_fallback_warns_exactly_once(monkeypatch):
    from rabit_tpu.codec import kernel as kernel_mod

    # Simulate the toolchain-free box regardless of the real build.
    monkeypatch.setattr(kernel_mod, "_loaded", True)
    monkeypatch.setattr(kernel_mod, "_kernel", None)
    monkeypatch.setattr(kernel_mod, "_load_error", "no lib (simulated)")
    monkeypatch.setattr(kernel_mod, "_warned", False)
    warnings = []

    class Log:
        def warning(self, msg, *a):
            warnings.append(msg % a if a else msg)

    for _ in range(3):
        k, label = kernel_mod.resolve_impl("auto", log=Log())
        assert k is None and label == "numpy-fallback"
    assert len(warnings) == 1, warnings
    assert "numpy" in warnings[0]


def test_bogus_lib_path_never_imports_error(monkeypatch):
    from rabit_tpu.codec import kernel as kernel_mod

    monkeypatch.setenv("RABIT_CODEC_LIB", "/nonexistent/librabit.so")
    monkeypatch.setattr(kernel_mod, "_loaded", False)
    monkeypatch.setattr(kernel_mod, "_kernel", None)
    monkeypatch.setattr(kernel_mod, "_load_error", None)
    assert kernel_mod.load() is None
    assert "/nonexistent/librabit.so" in kernel_mod.load_error()


# --------------------------------------- bit-identity: the hop math
@requires_native
@pytest.mark.parametrize("block", [2, 8, 64])
@pytest.mark.parametrize("fmt", FMTS)
def test_hop_math_bit_identical(fmt, block):
    """Native and numpy run the same op stream — encode, a chain of
    recorded AND unrecorded merges at ragged chunk offsets, decode,
    residual commit — over a 3-op feedback stream.  Every artifact
    compares bitwise at every step: this is the contract that makes
    ``rabit_codec_impl`` a non-collective knob."""
    from rabit_tpu import codec as codec_mod

    k = _kernel()
    cn = codec_mod.make(fmt, block=block, min_bytes=0, kernel=k)
    cp = codec_mod.make(fmt, block=block, min_bytes=0)
    assert cn.wire_nbytes(4 * 10 * block) == cp.wire_nbytes(4 * 10 * block)
    fbn, fbp = codec_mod.FeedbackBuffer(), codec_mod.FeedbackBuffer()
    rng = np.random.default_rng(5)
    n = 5 * block + block // 2 + 1  # ragged: zero-padded tail block
    base = _payload(rng, n)
    for rnd in range(3):  # the feedback stream advances across ops
        v = base * np.float32(rnd + 1)
        with np.errstate(over="ignore"):
            sn = cn.begin(v.copy(), fbn)
            sp = cp.begin(v.copy(), fbp)
        assert sn.wire.tobytes() == sp.wire.tobytes(), (fmt, block, rnd)
        nblocks = sn.wire.size
        for hop in range(4):  # merge depth: chained peer contributions
            u = _payload(rng, n) * np.float32(hop + 1)
            with np.errstate(over="ignore"):
                pn = cn.begin(u.copy(), codec_mod.FeedbackBuffer())
                pp = cp.begin(u.copy(), codec_mod.FeedbackBuffer())
            assert pn.wire.tobytes() == pp.wire.tobytes()
            e0 = hop % nblocks
            ne = max(1, (nblocks - e0) // (1 + hop % 2))
            record = hop % 2 == 0  # the swing-style unrecorded side too
            with np.errstate(over="ignore"):
                cn.merge(sn, sn.wire, e0, ne, pn.wire[e0:e0 + ne], record)
                cp.merge(sp, sp.wire, e0, ne, pp.wire[e0:e0 + ne], record)
            assert sn.wire.tobytes() == sp.wire.tobytes(), \
                (fmt, block, rnd, hop, record)
            assert np.array_equal(sn.hop, sp.hop), (fmt, block, rnd, hop)
        outn = np.empty(n, np.float32)
        outp = np.empty(n, np.float32)
        rn = cn.finish(sn, outn, fbn)
        rp = cp.finish(sp, outp, fbp)
        assert outn.tobytes() == outp.tobytes(), (fmt, block, rnd)
        assert rn.tobytes() == rp.tobytes(), (fmt, block, rnd)


@requires_native
def test_bf16_elementwise_merge_bit_identical():
    """The native bf16 merge vs the ml_dtypes reference the engine's
    numpy path uses: add in bf16, bit for bit — subnormals, ties,
    overflow-to-inf and NaN quieting included."""
    import ml_dtypes

    from rabit_tpu.codec import kernel as kernel_mod

    bf = np.dtype(ml_dtypes.bfloat16)
    rng = np.random.default_rng(9)
    with np.errstate(over="ignore"):  # 1e38-scale: bf16 overflow cases
        vals = np.concatenate([
            rng.standard_normal(4096).astype(np.float32),
            (rng.standard_normal(4096) * 1e38).astype(np.float32),
            (rng.standard_normal(4096) * 1e-40).astype(np.float32),
            np.array([0.0, -0.0, np.inf, -np.inf, np.nan, 1.0, -1.0],
                     np.float32),
        ])
    a = vals.astype(bf)
    b = vals[::-1].copy().astype(bf)
    with np.errstate(over="ignore"):  # overflow-to-inf is a test case
        want = (a + b).view(np.uint16)
    dst = a.view(np.uint16).copy()
    src = b.view(np.uint16).copy()
    _kernel().bf16_merge(kernel_mod.pu16(dst), kernel_mod.pu16(src),
                         dst.size)
    assert np.array_equal(dst, want)


# ------------------------------------------------------- fp8 formats
@pytest.mark.parametrize("fmt", ["fp8e4m3", "fp8e5m2"])
def test_fp8_decode_exhaustive_all_codes(fmt):
    """Every one of the 256 fp8 codes, at two scales: the numpy path
    IS the ml_dtypes view, and the native path must match it bitwise
    (finite codes) / NaN-for-NaN."""
    from rabit_tpu import codec as codec_mod

    cp = codec_mod.make(fmt, block=256, min_bytes=0)
    wire = np.zeros(2, dtype=cp.block_dtype)
    wire["s"] = [1.0, 0.5]
    wire["q"] = np.arange(256, dtype=np.uint8)
    ref = cp._deq(wire)
    # ground truth straight from ml_dtypes
    truth = wire["q"].view(np.dtype(getattr(
        __import__("ml_dtypes"), codec_mod.FP8_FORMATS[fmt][0]))).astype(
        np.float32) * wire["s"][..., None]
    nan_ref = np.isnan(ref)
    assert np.array_equal(nan_ref, np.isnan(truth))
    assert np.array_equal(ref[~nan_ref], truth[~np.isnan(truth)])
    if _kernel() is not None:
        cn = codec_mod.make(fmt, block=256, min_bytes=0, kernel=_kernel())
        got = cn._deq(wire)
        nan_got = np.isnan(got)
        assert np.array_equal(nan_got, nan_ref)
        assert np.array_equal(
            got.reshape(-1).view(np.uint32)[~nan_got.reshape(-1)],
            ref.reshape(-1).view(np.uint32)[~nan_ref.reshape(-1)])


@pytest.mark.parametrize("fmt,man", [("fp8e4m3", 3), ("fp8e5m2", 2)])
def test_fp8_roundtrip_error_bounds(fmt, man):
    """One encode/decode round trip per magnitude decade: per-element
    error within the half-ulp envelope (2^-(man+1) relative) plus the
    block's subnormal quantum, and the committed residual is exactly
    ``v - decoded`` — the error-feedback invariant."""
    import ml_dtypes

    from rabit_tpu import codec as codec_mod

    mld = np.dtype(getattr(ml_dtypes, codec_mod.FP8_FORMATS[fmt][0]))
    sub = float(ml_dtypes.finfo(mld).smallest_subnormal)
    block = 64
    c = codec_mod.make(fmt, block=block, min_bytes=0)
    rng = np.random.default_rng(11)
    for decade in (1e-3, 1.0, 1e4):
        n = 10 * block + 7
        v = (rng.standard_normal(n) * decade).astype(np.float32)
        st = c.begin(v.copy(), codec_mod.FeedbackBuffer())
        out = np.empty(n, np.float32)
        res = c.finish(st, out, codec_mod.FeedbackBuffer())
        assert np.array_equal(res, v - out)
        scale = np.repeat(st.wire["s"], block)[:n].astype(np.float64)
        err = np.abs(out.astype(np.float64) - v.astype(np.float64))
        bound = np.maximum(np.abs(v) * 2.0 ** -(man + 1) * 1.001,
                           scale * sub)
        assert (err <= bound).all(), (
            fmt, decade, float(err.max()), float(bound[err.argmax()]))


def test_fp8_wire_bytes_honest():
    """fp8's claimed wire size is the structured layout's true size:
    4-byte scale + block bytes per block, ragged tail rounded up — and
    it matches the array the encode actually produces."""
    from rabit_tpu import codec as codec_mod

    c = codec_mod.make("fp8e4m3", block=64, min_bytes=0)
    for n in (1, 63, 64, 65, 1000):
        want = -(-n // 64) * (4 + 64)
        assert c.wire_nbytes(4 * n) == want
        st = c.begin(np.ones(n, np.float32), codec_mod.FeedbackBuffer())
        assert st.wire.nbytes == want


# ---------------------------------------------- end-to-end digest A/B
@requires_native
@pytest.mark.parametrize("codec", [
    "int8", "fp8e4m3",
    pytest.param("int4", marks=pytest.mark.slow),
    pytest.param("fp8e5m2", marks=pytest.mark.slow)])
def test_e2e_digest_parity_native_numpy_mixed(tmp_path, codec):
    """The whole stack, three ways — all-numpy (serial hops), all-native
    (pipelined hops), and a MIXED world (even ranks native, odd numpy)
    — must produce identical per-rank result digests: implementation
    and pipeline depth both leave the byte stream invariant."""
    runs = {"numpy": {"RABIT_CODEC_IMPL": "numpy",
                      "RABIT_PIPELINE_DEPTH": "1"},
            "native": {"RABIT_CODEC_IMPL": "native",
                       "RABIT_PIPELINE_DEPTH": "4"},
            "mixed": {"RABIT_CODEC_IMPL": "numpy",
                      "RABIT_CODEC_IMPL_MIXED": "1",
                      "RABIT_PIPELINE_DEPTH": "4"}}
    world, digests = 2, {}
    for tag, env in runs.items():
        out = tmp_path / f"d-{tag}"
        assert _launch("pipeline_parity", world,
                       {"RABIT_ENGINE": "pysocket", "RABIT_SCHED": "ring",
                        "RABIT_WIRE_CODEC": codec,
                        "RABIT_PIPELINE_CHUNK": "16KB",
                        "RABIT_REDUCE_BUFFER": "64KB", **env},
                       args=[str(out)]) == 0
        digests[tag] = [(tmp_path / f"d-{tag}.r{r}").read_text()
                        for r in range(world)]
    assert digests["native"] == digests["numpy"], "native != numpy"
    assert digests["mixed"] == digests["numpy"], "mixed != numpy"


@requires_native
@pytest.mark.recovery
@pytest.mark.parametrize("codec", [
    "int8", pytest.param("fp8e4m3", marks=pytest.mark.slow)])
def test_replay_after_crash_native_bit_identical(codec):
    """Kill-point replay with the native kernels armed: the relaunched
    rank must be served the EXACT cached wire bytes — encode
    determinism (feedback read-not-mutate + bit-identical requant)
    holds across the seam."""
    assert _launch("codec_replay", 3,
                   {"RABIT_ENGINE": "pyrobust",
                    "RABIT_WIRE_CODEC": codec,
                    "RABIT_CODEC_IMPL": "native",
                    "RABIT_MOCK": "1,0,1,0"}) == 0


# --------------------------------------------------- live-plane label
def test_status_and_rabit_top_surface_backend():
    """The resolved impl label flows frame -> LiveTable -> /status row
    -> rabit_top's codec column, with the mean per-op kernel time."""
    from rabit_tpu.obs.export import LiveTable
    from rabit_tpu.tools.rabit_top import render

    lt = LiveTable()
    lt.ingest(0, 1.0, {"engine": "pysocket", "codec_impl": "native",
                       "counters": {"op.allreduce.count": 3},
                       "gauges": {"codec.kernel.seconds.mean": 4.2e-4}})
    lt.ingest(1, 1.0, {"engine": "pysocket",
                       "codec_impl": "numpy-fallback", "counters": {}})
    rep = lt.report()
    assert rep["0"]["codec_impl"] == "native"
    assert rep["0"]["codec_kernel_ms"] == pytest.approx(0.42)
    assert rep["1"]["codec_impl"] == "numpy-fallback"
    assert dict(lt.rows())[0]["codec_impl"] == "native"
    buf = io.StringIO()
    render({"ts": 2.0, "jobs": {"j": {"world": 2, "live": rep}}},
           None, out=buf)
    text = buf.getvalue()
    assert "native 0.42ms" in text
    assert "numpy-fallback" in text
